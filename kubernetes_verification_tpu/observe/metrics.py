"""Every shared metric family, registered at import time.

Keeping the declarations in one module (rather than scattered next to each
increment site) gives three things: the registry dump names the full
instrument set even on runs that exercise one backend, the
``scripts/check_metrics_names.py`` lint has a single import to validate,
and grep-for-a-metric lands here with the help string.

Naming: ``kvtpu_`` prefix, ``_total`` suffix on counters, base units in the
name (``_seconds``, ``_per_second``) — Prometheus conventions.
"""
from __future__ import annotations

from .registry import Counter, Gauge, Histogram, set_exemplar_counter

__all__ = [
    "SPAN_SECONDS",
    "VERIFY_TOTAL",
    "PAIRS_PER_SECOND",
    "BYTES_TRANSFERRED",
    "CLOSURE_ITERATIONS",
    "CLOSURE_SHARDED_ITERATIONS",
    "CLOSURE_STRIPE_ROWS",
    "CLOSURE_BOUNDED_LEVELS",
    "HBM_GUARD_REFUSALS",
    "DELTA_CLOSURE_ROUNDS",
    "INCREMENTAL_OPS",
    "STRIPE_WIDTH",
    "STRIPES_SOLVED",
    "JIT_RECOMPILES",
    "KERNEL_INVOCATIONS",
    "KERNEL_TILES",
    "RETRIES_TOTAL",
    "FALLBACKS_TOTAL",
    "FAULTS_INJECTED_TOTAL",
    "DEGRADATIONS_TOTAL",
    "HBM_BYTES_IN_USE",
    "HBM_PEAK_BYTES",
    "KERNEL_FLOPS",
    "KERNEL_BYTES_ACCESSED",
    "KERNEL_PEAK_BYTES",
    "COST_REPORTS_TOTAL",
    "SERVE_EVENTS_TOTAL",
    "SERVE_COALESCED_TOTAL",
    "SERVE_BATCHES_TOTAL",
    "SERVE_SOLVES_TOTAL",
    "SERVE_QUERIES_TOTAL",
    "SERVE_ASSERTION_FAILURES_TOTAL",
    "SERVE_QUEUE_DEPTH",
    "SERVE_STALENESS_SECONDS",
    "QUERY_CACHE_HITS_TOTAL",
    "QUERY_CACHE_MISSES_TOTAL",
    "QUERY_BATCH_SIZE",
    "QUERY_H2D_BYTES_TOTAL",
    "QUERY_PACKED_DISPATCHES_TOTAL",
    "DEVICE_STATE_FLIPS_TOTAL",
    "CHECKPOINTS_TOTAL",
    "RECOVERIES_TOTAL",
    "WAL_TRUNCATIONS_TOTAL",
    "BREAKER_TRANSITIONS_TOTAL",
    "REPLICA_LAG_SECONDS",
    "REPLICA_LAG_SEQ",
    "PROMOTIONS_TOTAL",
    "STALE_READS_TOTAL",
    "NET_REQUESTS_TOTAL",
    "NET_REQUEST_FAILURES_TOTAL",
    "NET_BYTES_TOTAL",
    "NET_FAULTS_INJECTED_TOTAL",
    "LB_REQUESTS_TOTAL",
    "LB_STALE_RETRIES_TOTAL",
    "LB_EJECTIONS_TOTAL",
    "LINT_FINDINGS_TOTAL",
    "AOT_CACHE_HITS_TOTAL",
    "AOT_CACHE_MISSES_TOTAL",
    "AOT_PACK_BYTES",
    "SENTINEL_KERNEL_SECONDS",
    "SENTINEL_SPREAD_PCT",
    "SENTINEL_DISPATCH_SECONDS",
    "SENTINEL_CALIBRATION_FAILURES_TOTAL",
    "ROOFLINE_ACHIEVED_MACS_PER_SECOND",
    "ROOFLINE_PCT_OF_PEAK",
    "QUERY_LATENCY_SECONDS",
    "SLO_BURN_RATE",
    "LB_RETRIES_TOTAL",
    "FLIGHT_DUMPS_TOTAL",
    "SCRAPE_REQUESTS_TOTAL",
    "PROGRESS_PASSES_TOTAL",
    "PROGRESS_FRACTION",
    "PROGRESS_ETA_SECONDS",
    "PROGRESS_ACTIVE_JOBS",
    "PROFILE_CAPTURES_TOTAL",
    "TRACE_EXEMPLARS_TOTAL",
    "INGRESS_REQUESTS_TOTAL",
    "INGRESS_QUEUE_DEPTH",
    "INGRESS_BATCH_FILL",
    "INGRESS_WAIT_SECONDS",
    "INGRESS_BATCHES_TOTAL",
    "INGRESS_FAULTS_INJECTED_TOTAL",
    "ADMISSION_REJECTIONS_TOTAL",
    "ADMISSION_QUOTA_UTILIZATION",
    "ADMISSION_BROWNOUT_LEVEL",
    "ADMISSION_BROWNOUT_TRANSITIONS_TOTAL",
    "AUTOSCALE_DECISIONS_TOTAL",
    "AUTOSCALE_FLEET_SIZE",
    "POSTURE_REACHABLE_PAIRS",
    "POSTURE_WIDENED_TOTAL",
    "POSTURE_NARROWED_TOTAL",
    "POSTURE_DELTA_SECONDS",
    "POSTURE_ALERT_VIOLATIONS_TOTAL",
    "STRIPE_FANOUT_TOTAL",
    "STRIPE_QUERIES_TOTAL",
    "STRIPE_COVERAGE_GAPS_TOTAL",
    "STRIPE_OWNED_ROWS",
    "REQUIRED_FAMILIES",
]

SPAN_SECONDS = Histogram(
    "kvtpu_span_seconds",
    "Wall-clock seconds per span/phase, labeled by span name. The registry "
    "dump derives its `spans` section (count/total/last) from this family.",
    ("name",),
)

VERIFY_TOTAL = Counter(
    "kvtpu_verify_total",
    "Verification runs dispatched through the backend registry.",
    ("backend", "mode"),
)

PAIRS_PER_SECOND = Gauge(
    "kvtpu_pairs_per_second",
    "Pod pairs decided per second of solve time in the most recent run "
    "(n_pods^2 / solve seconds) — the roofline-style throughput number.",
    ("backend",),
)

BYTES_TRANSFERRED = Gauge(
    "kvtpu_bytes_transferred",
    "Host<->device bytes moved by the most recent run (encoded operands in "
    "plus fetched results out; 0 for pure-host backends).",
    ("backend",),
)

CLOSURE_ITERATIONS = Counter(
    "kvtpu_closure_iterations_total",
    "Boolean matrix squarings executed by host-driven transitive-closure "
    "loops (packed fixpoint + NumPy oracle). Unlabeled so it appears in "
    "every dump.",
)

CLOSURE_SHARDED_ITERATIONS = Counter(
    "kvtpu_closure_sharded_iterations_total",
    "Mesh-sharded squaring passes executed by sharded_packed_closure — each "
    "one is a full all-gather + per-stripe retile sweep over the (pods, "
    "grants) mesh, converged on the globally-reduced change flag.",
)

CLOSURE_STRIPE_ROWS = Gauge(
    "kvtpu_closure_stripe_rows",
    "Row-stripe height (source rows per device) of the most recent "
    "sharded closure dispatch — N padded to the mesh geometry over the pod "
    "axis; how wide the closure was sharded.",
)

CLOSURE_BOUNDED_LEVELS = Counter(
    "kvtpu_closure_bounded_levels_total",
    "Frontier levels (one-hop [K, N] extensions) executed by the bounded "
    "multi-source closure instead of full N x N squarings — the path-query "
    "work metric at matrix-free scale.",
)

HBM_GUARD_REFUSALS = Counter(
    "kvtpu_hbm_guard_refusals_total",
    "Closure dispatches refused by the pre-flight HBM guard because the "
    "estimated working set exceeded the device budget — each refusal "
    "replaced a device OOM with actionable guidance (shard wider / bounded "
    "mode / lower tile cap).",
)

DELTA_CLOSURE_ROUNDS = Counter(
    "kvtpu_delta_closure_rounds_total",
    "Frontier/suspect-row propagation rounds run by packed_closure_delta "
    "instead of full re-closures.",
)

INCREMENTAL_OPS = Counter(
    "kvtpu_incremental_ops_total",
    "Mutations applied to an incremental verifier, by engine and operation "
    "(pod_add, policy_remove, namespace_relabel, ...).",
    ("engine", "op"),
)

STRIPE_WIDTH = Gauge(
    "kvtpu_stripe_width",
    "Destination-stripe width (pods) used by the most recent solve_stripe "
    "call, per engine.",
    ("engine",),
)

STRIPES_SOLVED = Counter(
    "kvtpu_stripes_solved_total",
    "Dirty destination stripes re-solved by the incremental engines.",
    ("engine",),
)

JIT_RECOMPILES = Counter(
    "kvtpu_jit_recompiles_total",
    "Novel abstract-shape signatures seen at jit dispatch sites — each one "
    "is an XLA trace+compile, the usual silent latency cliff.",
    ("engine", "fn"),
)

KERNEL_INVOCATIONS = Counter(
    "kvtpu_kernel_invocations_total",
    "tiled_k8s_reach launches, by selected kernel (xla, pallas, ...).",
    ("kernel",),
)

KERNEL_TILES = Counter(
    "kvtpu_kernel_tiles_total",
    "Destination tiles/stripes processed by tiled_k8s_reach, by kernel.",
    ("kernel",),
)

RETRIES_TOTAL = Counter(
    "kvtpu_retries_total",
    "Solve attempts retried on a transient BackendError, by backend/engine "
    "and failure kind (oom, timeout, flaky, ...).",
    ("backend", "kind"),
)

FALLBACKS_TOTAL = Counter(
    "kvtpu_fallbacks_total",
    "Fallback-chain hops: a backend was abandoned and the next one tried.",
    ("from_backend", "to_backend"),
)

FAULTS_INJECTED_TOTAL = Counter(
    "kvtpu_faults_injected_total",
    "Faults injected by the resilience.faults harness (faulty:* backends), "
    "by wrapped backend and fault kind.",
    ("backend", "kind"),
)

DEGRADATIONS_TOTAL = Counter(
    "kvtpu_degradations_total",
    "Adaptive tile-size halvings applied after RESOURCE_EXHAUSTED before "
    "falling back to the next backend.",
    ("backend",),
)

HBM_BYTES_IN_USE = Gauge(
    "kvtpu_hbm_bytes_in_use",
    "Device memory in use at the most recent telemetry sample, per device "
    "(host RSS under device=host when the platform exposes no "
    "memory_stats(), e.g. the CPU backend).",
    ("device",),
)

HBM_PEAK_BYTES = Gauge(
    "kvtpu_hbm_peak_bytes",
    "Peak device memory since process start, per device (peak host RSS "
    "under device=host on platforms without memory_stats()).",
    ("device",),
)

KERNEL_FLOPS = Gauge(
    "kvtpu_kernel_flops",
    "XLA cost_analysis() FLOP estimate for the most recent compile of a "
    "jitted dispatch site (host-side analytic estimate for pure-NumPy "
    "backends), by engine and function.",
    ("engine", "fn"),
)

KERNEL_BYTES_ACCESSED = Gauge(
    "kvtpu_kernel_bytes_accessed",
    "XLA cost_analysis() bytes-accessed estimate for the most recent "
    "compile of a jitted dispatch site — the memory-traffic side of the "
    "roofline.",
    ("engine", "fn"),
)

KERNEL_PEAK_BYTES = Gauge(
    "kvtpu_kernel_peak_bytes",
    "Peak live bytes (arguments + outputs + temporaries) from "
    "memory_analysis() for the most recent compile of a jitted dispatch "
    "site — the HBM high-water mark the executable needs.",
    ("engine", "fn"),
)

COST_REPORTS_TOTAL = Counter(
    "kvtpu_cost_reports_total",
    "KernelCostReports published by the introspection layer, by engine/"
    "function and source (xla AOT lowering vs. host analytic estimate).",
    ("engine", "fn", "source"),
)

SERVE_EVENTS_TOTAL = Counter(
    "kvtpu_serve_events_total",
    "Mutation events APPLIED to the serving engine after coalescing, by "
    "event kind (add_policy, update_pod_labels, full_resync, ...).",
    ("kind",),
)

SERVE_COALESCED_TOTAL = Counter(
    "kvtpu_serve_coalesced_total",
    "Events absorbed by write-coalescing before reaching the engine "
    "(duplicate relabels folded, add+remove pairs cancelled, deltas "
    "discarded by a full_resync), by event kind.",
    ("kind",),
)

SERVE_BATCHES_TOTAL = Counter(
    "kvtpu_serve_batches_total",
    "Event batches applied by the verification service (one span and at "
    "most one solve per batch).",
)

SERVE_SOLVES_TOTAL = Counter(
    "kvtpu_serve_solves_total",
    "Reachability re-derivations run by the serving loop, by trigger "
    "(query arrived, staleness bound expired, assertions checked after a "
    "batch, incremental-solve fallback to a from-scratch verify).",
    ("trigger",),
)

SERVE_QUERIES_TOTAL = Counter(
    "kvtpu_serve_queries_total",
    "Queries answered by the serving query engine, by query kind "
    "(can_reach, who_can_reach, blast_radius, what_if).",
    ("kind",),
)

SERVE_ASSERTION_FAILURES_TOTAL = Counter(
    "kvtpu_serve_assertion_failures_total",
    "Declarative allow/deny assertions found violated after an applied "
    "batch, by assertion name.",
    ("assertion",),
)

SERVE_QUEUE_DEPTH = Gauge(
    "kvtpu_serve_queue_depth",
    "Events buffered in the serving queue but not yet applied to the "
    "engine, sampled when the worker drains a batch.",
)

QUERY_CACHE_HITS_TOTAL = Counter(
    "kvtpu_query_cache_hits_total",
    "Generation-keyed query-cache hits on the batched query path, by entry "
    "kind: 'rows' (memoized packed reach rows, one per distinct source) or "
    "'ports' (memoized per-pair port-atom tables).",
    ("kind",),
)

QUERY_CACHE_MISSES_TOTAL = Counter(
    "kvtpu_query_cache_misses_total",
    "Generation-keyed query-cache misses on the batched query path, by "
    "entry kind ('rows' / 'ports') — each rows miss is one gathered row in "
    "the batch's single device dispatch, each ports miss one refined pair "
    "in its group's oracle solve.",
    ("kind",),
)

QUERY_BATCH_SIZE = Histogram(
    "kvtpu_query_batch_size",
    "Probes per can_reach_batch call — how much batching amortizes the "
    "per-dispatch overhead the scalar path pays per query.",
    buckets=(1.0, 8.0, 64.0, 512.0, 4096.0, 32768.0),
)

QUERY_H2D_BYTES_TOTAL = Counter(
    "kvtpu_query_h2d_bytes_total",
    "Host→device bytes uploaded to build query-plane device state, by "
    "engine kind ('dense' uploads its isolation vectors once per "
    "generation; 'packed' aliases already-resident state and charges "
    "nothing) — flat across warm batches means steady-state queries "
    "moved zero engine bytes over the tunnel.",
    ("kind",),
)

QUERY_PACKED_DISPATCHES_TOTAL = Counter(
    "kvtpu_query_packed_dispatches_total",
    "Batched query dispatches answered from the packed uint32 bitmap "
    "state (no dense [N, N] operand in the program), by kernel kind: "
    "'rows' (word-row gather), 'cols' (who-can-reach columns) or 'probe' "
    "(fused rows + verdict-bit extraction).",
    ("kind",),
)

DEVICE_STATE_FLIPS_TOTAL = Counter(
    "kvtpu_device_state_flips_total",
    "Generation flips published by the query plane's double-buffered "
    "device-state cache, by engine kind — each one is a shadow state "
    "built off to the side and swapped in atomically, never a stall of "
    "in-flight query reads.",
    ("kind",),
)

SERVE_STALENESS_SECONDS = Gauge(
    "kvtpu_serve_staleness_seconds",
    "Age of the oldest applied-but-unsolved mutation at the most recent "
    "solve — how stale answers were allowed to get before re-deriving.",
)

CHECKPOINTS_TOTAL = Counter(
    "kvtpu_checkpoints_total",
    "Atomic serving checkpoints committed (engine snapshot + manifest "
    "binding snapshot digest, event-log offset and last-applied sequence "
    "number, promoted via tmp-file + fsync + os.replace).",
)

RECOVERIES_TOTAL = Counter(
    "kvtpu_recoveries_total",
    "Serving-state recoveries, by outcome: 'newest' (latest checkpoint "
    "generation loaded clean), 'fallback' (a newer generation was corrupt "
    "and an older one was used), 'rebuild' (every checkpoint was unusable "
    "— replayed the whole event log from scratch).",
    ("outcome",),
)

WAL_TRUNCATIONS_TOTAL = Counter(
    "kvtpu_wal_truncations_total",
    "Torn event-log tails truncated on WAL open — a crash mid-append left "
    "a partial or checksum-failing final record, which was dropped so the "
    "surviving prefix stays replayable (strict mode raises instead).",
)

BREAKER_TRANSITIONS_TOTAL = Counter(
    "kvtpu_breaker_transitions_total",
    "Circuit-breaker state transitions, by backend and destination state "
    "(closed / open / half_open) — a flapping backend shows up as "
    "open/half_open churn instead of burning the fallback chain and "
    "watchdog budget on every solve.",
    ("backend", "to"),
)

REPLICA_LAG_SECONDS = Gauge(
    "kvtpu_replica_lag_seconds",
    "Seconds since this follower last caught up to the leader's WAL tip, "
    "per replica — 0 while fully caught up; the measured half of every "
    "staleness-bounded read.",
    ("replica",),
)

REPLICA_LAG_SEQ = Gauge(
    "kvtpu_replica_lag_seq",
    "WAL records the leader has committed that this follower has not yet "
    "applied, per replica — the sequence-space twin of "
    "kvtpu_replica_lag_seconds.",
    ("replica",),
)

PROMOTIONS_TOTAL = Counter(
    "kvtpu_promotions_total",
    "Follower-to-leader promotions: the lease expired, the leader-probe "
    "breaker opened, and this replica won the epoch claim — each one bumps "
    "the fencing epoch stamped into every subsequent WAL record.",
    ("replica",),
)

STALE_READS_TOTAL = Counter(
    "kvtpu_stale_reads_total",
    "Follower reads that arrived past their staleness bound, by outcome: "
    "'rejected' (typed StaleReadError returned to the caller) or 'proxied' "
    "(answered with leader-fresh state under --proxy-stale).",
    ("outcome",),
)

NET_REQUESTS_TOTAL = Counter(
    "kvtpu_net_requests_total",
    "Replication-transport requests issued by followers and the query "
    "load balancer, by wire operation (tip / wal / manifest / file) — "
    "the denominator for the failure ratio on the networked read plane.",
    ("op",),
)

NET_REQUEST_FAILURES_TOTAL = Counter(
    "kvtpu_net_request_failures_total",
    "Replication-transport requests that failed after exhausting their "
    "bounded retry budget (connection refused/reset, timeout, checksum "
    "mismatch, injected network fault), by wire operation — each one "
    "feeds the caller's leader-probe or per-replica breaker.",
    ("op",),
)

NET_BYTES_TOTAL = Counter(
    "kvtpu_net_bytes_total",
    "Payload bytes shipped over the replication transport, by wire "
    "operation — WAL range bytes under 'wal', checkpoint chunk bytes "
    "under 'file'; snapshot-shipping bootstrap cost is visible here.",
    ("op",),
)

NET_FAULTS_INJECTED_TOTAL = Counter(
    "kvtpu_net_faults_injected_total",
    "Network faults fired at the transport seam by the injection harness "
    "(net-drop / net-delay / net-partition), by kind and wire operation — "
    "the chaos suite's ground truth for what each run actually injected.",
    ("kind", "op"),
)

LB_REQUESTS_TOTAL = Counter(
    "kvtpu_lb_requests_total",
    "Query batches the load balancer routed, by destination replica "
    "(the leader counts under its own name when a stale read was "
    "retried against it) — staleness-weighted routing skew is read "
    "straight off this family.",
    ("replica",),
)

LB_STALE_RETRIES_TOTAL = Counter(
    "kvtpu_lb_stale_retries_total",
    "Batches a replica rejected with StaleReadError that the load "
    "balancer retried against the leader — sustained growth means the "
    "staleness bound is tighter than the followers can tail.",
)

LB_EJECTIONS_TOTAL = Counter(
    "kvtpu_lb_ejections_total",
    "Replicas the load balancer ejected from rotation (their per-replica "
    "breaker opened after consecutive transport failures), by replica — "
    "they re-enter through the breaker's half-open probe.",
    ("replica",),
)

LINT_FINDINGS_TOTAL = Counter(
    "kvtpu_lint_findings_total",
    "Non-grandfathered findings reported by `kv-tpu lint` runs in this "
    "process, by rule id — lint health rides the same dashboards as every "
    "other kvtpu_* family.",
    ("rule",),
)

LINT_CALLGRAPH_NODES = Gauge(
    "kvtpu_lint_callgraph_nodes",
    "Functions indexed by the interprocedural lint call graph on the last "
    "`kv-tpu lint` run in this process — a sudden drop means the "
    "module/import resolver stopped seeing part of the package and the "
    "cross-function rules silently lost coverage.",
)

LINT_CALLGRAPH_EDGES = Gauge(
    "kvtpu_lint_callgraph_edges",
    "Resolved call edges in the interprocedural lint call graph on the "
    "last `kv-tpu lint` run — the denominator for how much of the package "
    "the summary propagation (taint, raises, donation) can traverse.",
)

LINT_CACHE_HITS_TOTAL = Counter(
    "kvtpu_lint_cache_hits_total",
    "Files whose per-function lint summaries were served from the "
    "content-hash cache (.kvtpu_lint_cache.json) instead of re-running "
    "the label dataflow — the warm-run speedup `kv-tpu lint` budgets "
    "against.",
)

SENTINEL_KERNEL_SECONDS = Gauge(
    "kvtpu_sentinel_kernel_seconds",
    "Median wall-clock of one fixed-shape calibration-kernel run "
    "(observe/sentinel.py), by kernel — the compute-bound reference every "
    "bench round records so headline drift can be attributed to code vs "
    "the host↔device path.",
    ("kernel",),
)

SENTINEL_SPREAD_PCT = Gauge(
    "kvtpu_sentinel_spread_pct",
    "Measured run-to-run spread ((max-min)/median, percent) of each "
    "calibration kernel on its last measurement — the round's noise "
    "figure; a calibrated sentinel repeats within its registration bound "
    "(<1% on a real chip).",
    ("kernel",),
)

SENTINEL_DISPATCH_SECONDS = Gauge(
    "kvtpu_sentinel_dispatch_seconds",
    "Median round-trip of the near-empty dispatch probe (dispatch + "
    "scalar read-back) — the per-dispatch overhead the tunnel adds to "
    "every timed solve, and the quantity dispatch-deflation removes from "
    "bench headlines.",
)

SENTINEL_CALIBRATION_FAILURES_TOTAL = Counter(
    "kvtpu_sentinel_calibration_failures_total",
    "Sentinel kernels whose measured spread exceeded the registration "
    "bound, by kernel — the instrument itself was too noisy to calibrate "
    "with (the bench record carries calibrated=false instead of a "
    "verdict).",
    ("kernel",),
)

AOT_CACHE_HITS_TOTAL = Counter(
    "kvtpu_aot_cache_hits_total",
    "Kernel dispatches first served by a pack-loaded AOT executable "
    "(observe/aot.py), by engine and function — one per cache key, so a "
    "fully warm start counts every manifest kernel here and nothing under "
    "the miss family.",
    ("engine", "fn"),
)

AOT_CACHE_MISSES_TOTAL = Counter(
    "kvtpu_aot_cache_misses_total",
    "AOT warm-start cache misses, by engine, function and reason: 'cold' "
    "(signature never packed — a fresh trace+compile), 'key-mismatch' "
    "(pack entry built under a different platform/device/jax version/XLA "
    "flags, never loaded), 'corrupt' (truncated or digest-failing pack "
    "entry, degraded to recompile with a warning), 'exec-error' (a loaded "
    "executable failed at dispatch and was poisoned back to the jit "
    "path). Zero on the warm path is the failover SLO bench asserts.",
    ("engine", "fn", "reason"),
)

AOT_PACK_BYTES = Gauge(
    "kvtpu_aot_pack_bytes",
    "Serialized bytes of the warm executable pack most recently saved or "
    "loaded by this process — the on-disk cost of second-scale warm "
    "starts, shipped by CheckpointManager next to its gen-N/ snapshots.",
)

ROOFLINE_ACHIEVED_MACS_PER_SECOND = Gauge(
    "kvtpu_roofline_achieved_macs_per_second",
    "Achieved multiply-accumulates per steady-state second for the newest "
    "bench record of each mode that carries MAC accounting "
    "(observe/introspect.py roofline report), by mode.",
    ("mode",),
)

ROOFLINE_PCT_OF_PEAK = Gauge(
    "kvtpu_roofline_pct_of_peak",
    "Achieved MACs/s as percent of the device peak (published v5e-class "
    "table, else the sentinel-calibrated or analytic host fallback), by "
    "mode — the number that calibrates every 'practical XLA optimum' "
    "claim and locates remaining headroom.",
    ("mode",),
)

QUERY_LATENCY_SECONDS = Histogram(
    "kvtpu_query_latency_seconds",
    "Batched-query latency decomposed by pipeline stage — 'queue' (waiting "
    "for the coalescing flush), 'dispatch' (cache sync + reference-index "
    "gather), 'solve' (the device answer), 'd2h' (device→host readback and "
    "answer assembly) — the per-stage attribution `kv-tpu trace` renders "
    "per query and this family aggregates per process.",
    ("stage",),
    buckets=(0.0001, 0.001, 0.01, 0.1, 1.0, 10.0),
)

SLO_BURN_RATE = Gauge(
    "kvtpu_slo_burn_rate",
    "Error-budget burn rate per SLO objective and evaluation window "
    "(bad-event fraction over the window divided by the objective's "
    "budget; 1.0 = burning exactly the budget, >1 = on track to violate) "
    "— the multi-window signal `kv-tpu fleet` alerts on.",
    ("objective", "window"),
)

LB_RETRIES_TOTAL = Counter(
    "kvtpu_lb_retries_total",
    "Query batches the load balancer re-routed after the first replica "
    "failed to answer, by reason: 'stale' (StaleReadError, retried at the "
    "leader), 'transport' (ejectable transport error, next replica in the "
    "weighted order), 'exhausted' (every replica failed; the error "
    "propagated to the caller).",
    ("reason",),
)

FLIGHT_DUMPS_TOTAL = Counter(
    "kvtpu_flight_dumps_total",
    "Flight-recorder ring dumps written, by trigger: 'error' (a KvTpuError "
    "escalated out of a CLI command), 'breaker-open' (a circuit breaker "
    "opened), 'kill-point' (a fault-injection kill fired; the dump lands "
    "before os._exit), 'sigusr2' (operator-requested via signal).",
    ("trigger",),
)

SCRAPE_REQUESTS_TOTAL = Counter(
    "kvtpu_scrape_requests_total",
    "Observability scrapes served by this replica's HTTP surface, by "
    "endpoint ('metrics' for Prometheus text, 'healthz' for the JSON "
    "health document) — the scrape-path load the <2 percent overhead "
    "budget in bench replicate --net is measured against.",
    ("endpoint",),
)

PROGRESS_PASSES_TOTAL = Counter(
    "kvtpu_progress_passes_total",
    "Pass boundaries a long-running multi-pass host loop crossed (closure "
    "squaring passes, bounded-BFS levels, bootstrap files shipped, WAL "
    "replay batches, checkpoint phases), by job name — the raw tick count "
    "behind the ProgressTicker's rate/ETA estimates.",
    ("job",),
)

PROGRESS_FRACTION = Gauge(
    "kvtpu_progress_fraction",
    "Completed fraction (0..1) of each in-flight long-running job, by job "
    "name; -1 when the job's total is unknown (pure fixpoint loops with no "
    "usable bound). `kv-tpu jobs` / `kv-tpu top` render this as the ETA "
    "bar.",
    ("job",),
)

PROGRESS_ETA_SECONDS = Gauge(
    "kvtpu_progress_eta_seconds",
    "Smoothed remaining-seconds estimate per in-flight long-running job "
    "(exponential moving average of the per-pass rate, so one slow stripe "
    "does not whipsaw the estimate); -1 while no rate is established.",
    ("job",),
)

PROGRESS_ACTIVE_JOBS = Gauge(
    "kvtpu_progress_active_jobs",
    "Long-running jobs currently registered with the progress plane in "
    "this process — nonzero means `kv-tpu jobs` has something to show.",
)

PROFILE_CAPTURES_TOTAL = Counter(
    "kvtpu_profile_captures_total",
    "Bounded on-demand jax.profiler captures completed, by trigger: "
    "'sigusr1' (operator signal), 'http' (the /profile?seconds=N route), "
    "'cli' (kv-tpu profile), 'api' (programmatic). Rate-limited attempts "
    "and degraded (profiler-unavailable) attempts do not count.",
    ("trigger",),
)

TRACE_EXEMPLARS_TOTAL = Counter(
    "kvtpu_trace_exemplars_total",
    "Histogram bucket exemplars recorded (a slowest-in-window observation "
    "replaced the bucket's retained trace_id) — the write-side volume of "
    "the metric-to-trace join `kv-tpu trace --slowest` reads.",
)

INGRESS_REQUESTS_TOTAL = Counter(
    "kvtpu_ingress_requests_total",
    "Client probe requests at the front-door ingress tier, by tenant and "
    "outcome: 'answered' (batched, dispatched, result returned within the "
    "deadline), 'rejected' (typed AdmissionRejectedError with a finite "
    "retry-after), 'failed' (the backend dispatch itself errored after "
    "admission).",
    ("tenant", "outcome"),
)

INGRESS_QUEUE_DEPTH = Gauge(
    "kvtpu_ingress_queue_depth",
    "Probes admitted but not yet dispatched by the continuous-batching "
    "queue, sampled at every enqueue and flush — bounded by construction "
    "(the bounded-queue lint enforces it); sustained sits near the bound "
    "mean the brown-out ladder is about to climb.",
)

INGRESS_BATCH_FILL = Histogram(
    "kvtpu_ingress_batch_fill",
    "Fill fraction (probes dispatched / device batch shape) of each "
    "continuous-batching flush — the TPU-KNN peak-FLOP/s shape only pays "
    "off when this stays near 1.0 under load; a time-triggered flush on a "
    "quiet door legitimately dispatches low-fill batches.",
    buckets=(0.0625, 0.125, 0.25, 0.5, 0.75, 1.0),
)

INGRESS_WAIT_SECONDS = Histogram(
    "kvtpu_ingress_wait_seconds",
    "Seconds each admitted request waited in the batching queue between "
    "enqueue and dispatch — the coalescing tax every probe pays for "
    "riding a full device-shaped batch, bounded by the dual trigger's "
    "max-wait.",
    buckets=(0.0005, 0.002, 0.01, 0.05, 0.2, 1.0),
)

INGRESS_BATCHES_TOTAL = Counter(
    "kvtpu_ingress_batches_total",
    "Device-shaped batches the ingress tier dispatched, by flush trigger: "
    "'size' (the batch filled), 'time' (the oldest request hit max-wait), "
    "'deadline' (a request's budget demanded dispatch now), 'drain' "
    "(shutdown flushed the residue).",
    ("trigger",),
)

INGRESS_FAULTS_INJECTED_TOTAL = Counter(
    "kvtpu_ingress_faults_injected_total",
    "Ingress-seam faults fired by the injection harness, by kind: "
    "'client-burst' (one submission amplified into an N-times arrival "
    "spike) or 'slow-client' (a stalled request body delaying the "
    "submission) — the chaos suite's ground truth for front-door runs.",
    ("kind",),
)

ADMISSION_REJECTIONS_TOTAL = Counter(
    "kvtpu_admission_rejections_total",
    "Requests the admission controller refused with a typed "
    "AdmissionRejectedError, by tenant and reason ('over-quota', "
    "'concurrency', 'queue-full', 'brownout', 'deadline') — every one "
    "carried a finite computed retry-after; kv-tpu fleet/top render the "
    "per-tenant shed columns from this family.",
    ("tenant", "reason"),
)

ADMISSION_QUOTA_UTILIZATION = Gauge(
    "kvtpu_admission_quota_utilization",
    "Fraction of each tenant's token-bucket burst currently spent "
    "(0 = idle, 1 = the next request is over quota), sampled at every "
    "admission decision — the quota-pressure column in kv-tpu fleet/top.",
    ("tenant",),
)

ADMISSION_BROWNOUT_LEVEL = Gauge(
    "kvtpu_admission_brownout_level",
    "Current rung of the graceful-degradation ladder: 0 = normal, 1 = "
    "what-if overlays disabled, 2 = lowest-priority tenants shed, 3 = "
    "rejecting at the door — each transition is traced and "
    "flight-recorded.",
)

ADMISSION_BROWNOUT_TRANSITIONS_TOTAL = Counter(
    "kvtpu_admission_brownout_transitions_total",
    "Brown-out ladder transitions, by destination level ('0'..'3') — "
    "escalations and recoveries both count, so a flapping door shows up "
    "as volume here even when the level gauge looks calm.",
    ("to",),
)

AUTOSCALE_DECISIONS_TOTAL = Counter(
    "kvtpu_autoscale_decisions_total",
    "Fleet autoscaler decisions, by action: 'scale-up' / 'scale-down' "
    "(a follower was spawned/retired), 'hold' (signals inside the "
    "hysteresis band or cooling down), 'clamped' (the controller wanted "
    "to move but the fenced min/max fleet bound refused).",
    ("action",),
)

AUTOSCALE_FLEET_SIZE = Gauge(
    "kvtpu_autoscale_fleet_size",
    "Followers currently managed by the fleet autoscaler — always within "
    "the fenced [min_fleet, max_fleet] bound; reconcile this against "
    "kvtpu_autoscale_decisions_total to audit every spawn/retire.",
)

POSTURE_REACHABLE_PAIRS = Gauge(
    "kvtpu_posture_reachable_pairs",
    "Total reachable (src, dst) pod pairs in the current verifier "
    "generation, recomputed from the packed word state after every applied "
    "mutation batch — the level whose per-generation first difference is "
    "exactly widened minus narrowed.",
)

POSTURE_WIDENED_TOTAL = Counter(
    "kvtpu_posture_widened_total",
    "Pod pairs that became reachable across all applied mutation batches "
    "— each batch contributes the popcount of `cur & ~prev` over the "
    "packed word states, bit-identical to a dense recompute-and-diff; "
    "monotone drift here against a flat narrowed counter is a posture "
    "regression even when every batch stays under the alert bound.",
)

POSTURE_NARROWED_TOTAL = Counter(
    "kvtpu_posture_narrowed_total",
    "Pod pairs that became unreachable across all applied mutation "
    "batches (`prev & ~cur` popcount per batch) — the lockdown half of "
    "the posture ledger; reconcile widened - narrowed against the "
    "reachable-pair gauge's movement to audit the journal.",
)

POSTURE_DELTA_SECONDS = Histogram(
    "kvtpu_posture_delta_seconds",
    "Wall-clock seconds the posture tracker spent deriving one "
    "generation's delta record (packed XOR/popcount kernels + namespace "
    "aggregation + witness decode + journal append) — the overhead "
    "`bench.py --mode posture` gates at < 5% of the apply path.",
    buckets=(0.0005, 0.002, 0.01, 0.05, 0.2, 1.0),
)

POSTURE_ALERT_VIOLATIONS_TOTAL = Counter(
    "kvtpu_posture_alert_violations_total",
    "Posture alert rules violated by an applied generation, by rule kind "
    "('deny', 'max-widening', 'max-narrowing') — every increment rides "
    "with a typed PostureAlertError on the service, a traced event, and "
    "a flight-recorder dump of the offending delta.",
    ("rule",),
)

STRIPE_FANOUT_TOTAL = Counter(
    "kvtpu_stripe_fanout_total",
    "WAL mutations a stripe owner applied that did NOT originate in its "
    "own pod range (label/policy events whose selector membership crosses "
    "stripes fan out as full applies — correctness first), by event kind; "
    "the ratio to kvtpu_serve_events_total is the fan-out tax of striping.",
    ("kind",),
)

STRIPE_QUERIES_TOTAL = Counter(
    "kvtpu_stripe_queries_total",
    "Queries the stripe coordinator routed, by route shape: 'local' "
    "(answered by one source-pod stripe owner), 'scatter' (fanned out to "
    "every stripe and merged), 'retry' (a fragment re-dispatched to a "
    "backup owner after the primary failed mid-query).",
    ("route",),
)

STRIPE_COVERAGE_GAPS_TOTAL = Counter(
    "kvtpu_stripe_coverage_gaps_total",
    "Scatter-gather queries refused with StripeCoverageError because a "
    "stripe had no live owner — every increment is an outage surfaced as "
    "a typed failure instead of a silently truncated answer.",
)

STRIPE_OWNED_ROWS = Gauge(
    "kvtpu_stripe_owned_rows",
    "Pod rows [lo, hi) this stripe owner holds of the packed reachability "
    "maps — the numerator of the (1/N + eps) per-process state bound the "
    "stripe fleet exists to enforce.",
)

#: The frozen dashboard contract: families that must exist in every build.
#: New families are appended here by the PR that introduces them; the
#: `metrics-names` lint rule and `scripts/check_metrics_names.py` both fail
#: when one goes missing or a literal registration drifts off the list.
REQUIRED_FAMILIES = frozenset(
    {
        "kvtpu_span_seconds",
        "kvtpu_verify_total",
        "kvtpu_pairs_per_second",
        "kvtpu_bytes_transferred",
        "kvtpu_closure_iterations_total",
        # distributed/bounded closure engine (parallel/sharded_closure.py)
        "kvtpu_closure_sharded_iterations_total",
        "kvtpu_closure_stripe_rows",
        "kvtpu_closure_bounded_levels_total",
        "kvtpu_hbm_guard_refusals_total",
        "kvtpu_delta_closure_rounds_total",
        "kvtpu_incremental_ops_total",
        "kvtpu_stripe_width",
        "kvtpu_stripes_solved_total",
        "kvtpu_jit_recompiles_total",
        "kvtpu_kernel_invocations_total",
        "kvtpu_kernel_tiles_total",
        "kvtpu_retries_total",
        "kvtpu_fallbacks_total",
        "kvtpu_faults_injected_total",
        "kvtpu_degradations_total",
        # introspection layer
        "kvtpu_hbm_bytes_in_use",
        "kvtpu_hbm_peak_bytes",
        "kvtpu_kernel_flops",
        "kvtpu_kernel_bytes_accessed",
        "kvtpu_kernel_peak_bytes",
        "kvtpu_cost_reports_total",
        # serving layer (serve/)
        "kvtpu_serve_events_total",
        "kvtpu_serve_coalesced_total",
        "kvtpu_serve_batches_total",
        "kvtpu_serve_solves_total",
        "kvtpu_serve_queries_total",
        "kvtpu_serve_assertion_failures_total",
        "kvtpu_serve_queue_depth",
        "kvtpu_serve_staleness_seconds",
        # batched query engine (ops/batched.py + serve/queries.py)
        "kvtpu_query_cache_hits_total",
        "kvtpu_query_cache_misses_total",
        "kvtpu_query_batch_size",
        # device-resident query plane (ops/device_state.py + packed twins)
        "kvtpu_query_h2d_bytes_total",
        "kvtpu_query_packed_dispatches_total",
        "kvtpu_device_state_flips_total",
        # durability layer (WAL / checkpoints / recovery / breaker)
        "kvtpu_checkpoints_total",
        "kvtpu_recoveries_total",
        "kvtpu_wal_truncations_total",
        "kvtpu_breaker_transitions_total",
        # replicated serving (serve/replication.py)
        "kvtpu_replica_lag_seconds",
        "kvtpu_replica_lag_seq",
        "kvtpu_promotions_total",
        "kvtpu_stale_reads_total",
        # networked replication (serve/transport.py + serve/lb.py)
        "kvtpu_net_requests_total",
        "kvtpu_net_request_failures_total",
        "kvtpu_net_bytes_total",
        "kvtpu_net_faults_injected_total",
        "kvtpu_lb_requests_total",
        "kvtpu_lb_stale_retries_total",
        "kvtpu_lb_ejections_total",
        # perf sentinel + roofline accounting (observe/sentinel.py +
        # observe/introspect.py)
        "kvtpu_sentinel_kernel_seconds",
        "kvtpu_sentinel_spread_pct",
        "kvtpu_sentinel_dispatch_seconds",
        "kvtpu_sentinel_calibration_failures_total",
        "kvtpu_roofline_achieved_macs_per_second",
        "kvtpu_roofline_pct_of_peak",
        # static analysis (analysis/)
        "kvtpu_lint_findings_total",
        # interprocedural engine (analysis/callgraph.py + summaries.py)
        "kvtpu_lint_callgraph_nodes",
        "kvtpu_lint_callgraph_edges",
        "kvtpu_lint_cache_hits_total",
        # AOT warm-start subsystem (observe/aot.py)
        "kvtpu_aot_cache_hits_total",
        "kvtpu_aot_cache_misses_total",
        "kvtpu_aot_pack_bytes",
        # fleet observability plane (observe/flight.py + observe/fleet.py +
        # serve/transport.py scrape surface)
        "kvtpu_query_latency_seconds",
        "kvtpu_slo_burn_rate",
        "kvtpu_lb_retries_total",
        "kvtpu_flight_dumps_total",
        "kvtpu_scrape_requests_total",
        # deep observability plane (observe/progress.py + on-demand
        # profiler captures + histogram trace exemplars)
        "kvtpu_progress_passes_total",
        "kvtpu_progress_fraction",
        "kvtpu_progress_eta_seconds",
        "kvtpu_progress_active_jobs",
        "kvtpu_profile_captures_total",
        "kvtpu_trace_exemplars_total",
        # front-door ingress tier (serve/ingress.py + serve/admission.py +
        # serve/autoscale.py)
        "kvtpu_ingress_requests_total",
        "kvtpu_ingress_queue_depth",
        "kvtpu_ingress_batch_fill",
        "kvtpu_ingress_wait_seconds",
        "kvtpu_ingress_batches_total",
        "kvtpu_ingress_faults_injected_total",
        "kvtpu_admission_rejections_total",
        "kvtpu_admission_quota_utilization",
        "kvtpu_admission_brownout_level",
        "kvtpu_admission_brownout_transitions_total",
        "kvtpu_autoscale_decisions_total",
        "kvtpu_autoscale_fleet_size",
        # posture observability plane (serve/posture.py + ops/posture.py)
        "kvtpu_posture_reachable_pairs",
        "kvtpu_posture_widened_total",
        "kvtpu_posture_narrowed_total",
        "kvtpu_posture_delta_seconds",
        "kvtpu_posture_alert_violations_total",
        # stripe-sharded serving fleet (serve/stripes.py)
        "kvtpu_stripe_fanout_total",
        "kvtpu_stripe_queries_total",
        "kvtpu_stripe_coverage_gaps_total",
        "kvtpu_stripe_owned_rows",
    }
)

# the registry cannot import this module (it is our import parent), so the
# exemplar-volume counter is injected instead
set_exemplar_counter(TRACE_EXEMPLARS_TOTAL)
