"""Spans: nested wall-clock regions that feed the registry, the JSON event
stream, and — when jax is already loaded — the device profiler.

``trace("solve", backend="tpu")`` is the one instrumentation primitive the
rest of the codebase uses. Each span:

* times the region and observes ``kvtpu_span_seconds{name=...}``;
* emits one JSON event line (with ``ok: false`` added when the body raised,
  instead of pretending the phase completed);
* nests via a thread-local stack, so events carry ``parent`` and depth;
* carries distributed-trace identity: a ``trace_id`` shared by every span
  of one logical operation (across processes, via the ``X-Kvtpu-Trace``
  header), its own ``span_id``, and ``parent_id`` linking it to its caller
  — the caller may live in another process (``trace_context`` adopts the
  parsed wire context so server-side spans parent under the client span);
* wraps ``jax.profiler.TraceAnnotation`` when jax is importable, so the
  same names line up in a TensorBoard TPU trace captured via
  ``profile_to``. jax is looked up in ``sys.modules`` — tracing never
  forces the heavyweight import on pure-host paths.

Timestamps come from the one injectable clock in ``observe.events``: event
lines carry wall ``ts`` (cross-process orderable) and monotonic ``perf``
(duration-stable within a process), so ``kv-tpu trace`` reassembles
timelines without guessing which clock a line was stamped from.

``Phases`` keeps the seed's accumulate-into-a-dict API (backends still hand
``VerifyResult.timings`` to callers) but is now a thin layer over spans.
"""
from __future__ import annotations

import contextlib
import os
import sys
import threading
from dataclasses import dataclass, field
from typing import Dict, Iterator, Optional, Tuple

from .events import get_clock, log_event, set_context_provider
from .metrics import SPAN_SECONDS

__all__ = [
    "Span",
    "trace",
    "current_span",
    "current_trace_id",
    "trace_context",
    "TRACE_HEADER",
    "trace_headers",
    "format_trace_header",
    "parse_trace_header",
    "add_span_sink",
    "remove_span_sink",
    "Phases",
    "profile_to",
    "trace_to_dir",
    "set_memory_hook",
]

#: HTTP header carrying trace context over the wire: ``<trace_id>-<span_id>``
#: (two lowercase-hex tokens). The receiver's spans adopt the trace id and
#: parent under the sender's span id.
TRACE_HEADER = "X-Kvtpu-Trace"

_state = threading.local()

#: optional () -> int callable returning live memory bytes; when installed
#: (``telemetry.install_span_memory_hook``) every span records
#: ``mem_enter_bytes``/``mem_exit_bytes`` in its event line
_memory_hook = None

#: callables handed every closed Span — the flight recorder's ring and the
#: bench stage collector subscribe here instead of parsing event lines
_span_sinks: list = []


def set_memory_hook(hook) -> None:
    """Install (or clear, with None) the span memory snapshot hook."""
    global _memory_hook
    _memory_hook = hook  # kvtpu: ignore[concurrency-hygiene] single atomic reference rebind; span readers tolerate either value


def add_span_sink(sink) -> None:
    """Subscribe ``sink(span)`` to every span close (append-only list —
    registration is rare; iteration tolerates concurrent appends)."""
    _span_sinks.append(sink)


def remove_span_sink(sink) -> None:
    """Unsubscribe a sink previously added; missing sinks are ignored."""
    try:
        _span_sinks.remove(sink)
    except ValueError:
        pass


def _memory_bytes():
    if _memory_hook is None:
        return None
    try:
        return int(_memory_hook())
    except Exception:  # telemetry must never fail a traced region
        return None


def _stack() -> list:
    st = getattr(_state, "spans", None)
    if st is None:
        st = _state.spans = []
    return st


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


@dataclass
class Span:
    """One timed region. ``seconds``/``ok`` are filled when it closes."""

    name: str
    attrs: Dict[str, object] = field(default_factory=dict)
    parent: Optional["Span"] = None
    seconds: Optional[float] = None
    ok: bool = True
    trace_id: str = ""
    span_id: str = ""
    parent_id: Optional[str] = None
    start_wall: Optional[float] = None

    @property
    def depth(self) -> int:
        return 0 if self.parent is None else self.parent.depth + 1


def current_span() -> Optional[Span]:
    st = _stack()
    return st[-1] if st else None


def current_trace_id() -> Optional[str]:
    """The trace id spans opened *now* would join: the active span's, else
    an adopted remote context's, else None (a fresh root would mint one)."""
    span = current_span()
    if span is not None:
        return span.trace_id
    remote = getattr(_state, "remote", None)
    return remote[0] if remote else None


@contextlib.contextmanager
def trace_context(
    trace_id: Optional[str], parent_span_id: Optional[str] = None
) -> Iterator[None]:
    """Adopt a remote trace context for the duration of the block: root
    spans opened inside join ``trace_id`` and parent under
    ``parent_span_id`` instead of minting a fresh trace. A None
    ``trace_id`` is a no-op block, so callers can pass the (possibly
    absent) parsed header straight through."""
    if not trace_id:
        yield
        return
    prev = getattr(_state, "remote", None)
    _state.remote = (trace_id, parent_span_id)
    try:
        yield
    finally:
        _state.remote = prev


def format_trace_header(trace_id: str, span_id: str) -> str:
    return f"{trace_id}-{span_id}"


def parse_trace_header(value: Optional[str]) -> Tuple[Optional[str], Optional[str]]:
    """``(trace_id, parent_span_id)`` from an ``X-Kvtpu-Trace`` value;
    ``(None, None)`` for absent or malformed headers (never raises — a bad
    header must not fail the request it rode in on)."""
    if not value:
        return None, None
    head, sep, tail = value.strip().partition("-")
    if not sep or not head or not tail:
        return None, None
    try:
        int(head, 16), int(tail, 16)
    except ValueError:
        return None, None
    return head, tail


def trace_headers() -> Dict[str, str]:
    """Headers to stamp on an outgoing request: ``{TRACE_HEADER: ...}``
    when a trace is active on this thread, ``{}`` otherwise. Always pass
    this to ``conn.request(..., headers=trace_headers())`` — the
    trace-context lint counts un-headered requests as findings."""
    span = current_span()
    if span is not None:
        return {TRACE_HEADER: format_trace_header(span.trace_id, span.span_id)}
    remote = getattr(_state, "remote", None)
    if remote and remote[0]:
        return {TRACE_HEADER: format_trace_header(remote[0], remote[1] or "0")}
    return {}


def _trace_fields() -> Dict[str, object]:
    """Context-provider body for ``log_event``: every event line emitted
    inside a traced region carries the trace/span ids, even when the
    emitting module has never heard of spans."""
    span = current_span()
    if span is not None:
        return {"trace_id": span.trace_id, "span_id": span.span_id}
    remote = getattr(_state, "remote", None)
    if remote and remote[0]:
        return {"trace_id": remote[0]}
    return {}


set_context_provider(_trace_fields)


def _device_annotation(name: str):
    # only annotate if jax is already imported — never pull it in ourselves
    jax = sys.modules.get("jax")
    if jax is None:
        return contextlib.nullcontext()
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()


@contextlib.contextmanager
def trace(name: str, _event: str = "span", **attrs) -> Iterator[Span]:
    """Open a nested span; yields the live ``Span`` so callers can attach
    attrs mid-flight (``span.attrs["rounds"] = r``)."""
    parent = current_span()
    if parent is not None:
        trace_id, parent_id = parent.trace_id, parent.span_id
    else:
        remote = getattr(_state, "remote", None)
        if remote and remote[0]:
            trace_id, parent_id = remote
        else:
            trace_id, parent_id = _new_id(8), None
    clock = get_clock()
    span = Span(
        name=name,
        attrs=dict(attrs),
        parent=parent,
        trace_id=trace_id,
        span_id=_new_id(4),
        parent_id=parent_id,
        start_wall=clock.wall(),
    )
    mem0 = _memory_bytes()
    if mem0 is not None:
        span.attrs["mem_enter_bytes"] = mem0
    _stack().append(span)
    t0 = clock.perf()
    try:
        with _device_annotation(name):
            yield span
    except BaseException:
        span.ok = False
        raise
    finally:
        span.seconds = clock.perf() - t0
        _stack().pop()
        SPAN_SECONDS.labels(name=name).observe(span.seconds)
        mem1 = _memory_bytes()
        if mem1 is not None:
            span.attrs["mem_exit_bytes"] = mem1
        fields = dict(span.attrs)
        fields.update(
            name=name,
            seconds=span.seconds,
            trace_id=span.trace_id,
            span_id=span.span_id,
            start_ts=span.start_wall,
        )
        if span.parent_id is not None:
            fields["parent_id"] = span.parent_id
        if span.parent is not None:
            fields["parent"] = span.parent.name
            fields["depth"] = span.depth
        if not span.ok:
            fields["ok"] = False
        log_event(_event, **fields)
        for sink in list(_span_sinks):
            try:
                sink(span)
            except Exception:  # a broken sink must not fail traced work
                pass


class Phases:
    """Accumulate named phase timings (``encode``/``compile``/``solve``)
    into a dict — the shape ``VerifyResult.timings`` has always carried —
    while each phase also runs as a full span (registry + events + device
    annotation). Timings accumulate even when the body raises, and the
    emitted ``phase`` event then carries ``ok: false``. Uses the same
    injectable clock the spans themselves stamp from.
    """

    def __init__(self) -> None:
        self.timings: Dict[str, float] = {}

    @contextlib.contextmanager
    def __call__(self, name: str, **attrs) -> Iterator[Span]:
        clock = get_clock()
        t0 = clock.perf()
        try:
            with trace(name, _event="phase", **attrs) as span:
                yield span
        finally:
            self.timings[name] = self.timings.get(name, 0.0) + (
                clock.perf() - t0
            )


@contextlib.contextmanager
def profile_to(log_dir: str) -> Iterator[None]:
    """Capture a jax profiler trace into ``log_dir`` (TensorBoard format).

    Degrades to a no-op — one hint line on stderr plus a
    ``profile_skipped`` event — when jax is unavailable or the platform has
    no profiler support, instead of failing the whole command. Creates
    ``log_dir`` (the jax profiler assumes it exists)."""
    try:
        import jax
    except Exception:  # pragma: no cover - exercised only without jax
        log_event("profile_skipped", reason="jax unavailable", log_dir=log_dir)
        yield
        return
    import os

    os.makedirs(log_dir, exist_ok=True)
    try:
        ctx = jax.profiler.trace(log_dir)
        ctx.__enter__()
    except Exception as e:
        print(
            f"kv-tpu: --profile unsupported on this platform "
            f"({type(e).__name__}: {e}); continuing without a device trace",
            file=sys.stderr,
        )
        log_event(
            "profile_skipped",
            reason=f"{type(e).__name__}: {e}",
            log_dir=log_dir,
        )
        yield
        return
    log_event("profile_start", log_dir=log_dir)
    ok = True
    try:
        yield
    finally:
        try:
            ctx.__exit__(None, None, None)
        except Exception as e:
            ok = False
            print(
                f"kv-tpu: --profile capture failed "
                f"({type(e).__name__}: {e}); no trace written to {log_dir}",
                file=sys.stderr,
            )
            log_event(
                "profile_skipped",
                reason=f"{type(e).__name__}: {e}",
                log_dir=log_dir,
            )
        if ok:
            log_event("profile_done", log_dir=log_dir)


#: the name ISSUE/older docs use for the same facility
trace_to_dir = profile_to
