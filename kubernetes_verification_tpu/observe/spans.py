"""Spans: nested wall-clock regions that feed the registry, the JSON event
stream, and — when jax is already loaded — the device profiler.

``trace("solve", backend="tpu")`` is the one instrumentation primitive the
rest of the codebase uses. Each span:

* times the region and observes ``kvtpu_span_seconds{name=...}``;
* emits one JSON event line (with ``ok: false`` added when the body raised,
  instead of pretending the phase completed);
* nests via a thread-local stack, so events carry ``parent`` and depth;
* wraps ``jax.profiler.TraceAnnotation`` when jax is importable, so the
  same names line up in a TensorBoard TPU trace captured via
  ``profile_to``. jax is looked up in ``sys.modules`` — tracing never
  forces the heavyweight import on pure-host paths.

``Phases`` keeps the seed's accumulate-into-a-dict API (backends still hand
``VerifyResult.timings`` to callers) but is now a thin layer over spans.
"""
from __future__ import annotations

import contextlib
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, Optional

from .events import log_event
from .metrics import SPAN_SECONDS

__all__ = ["Span", "trace", "current_span", "Phases", "profile_to"]

_state = threading.local()


def _stack() -> list:
    st = getattr(_state, "spans", None)
    if st is None:
        st = _state.spans = []
    return st


@dataclass
class Span:
    """One timed region. ``seconds``/``ok`` are filled when it closes."""

    name: str
    attrs: Dict[str, object] = field(default_factory=dict)
    parent: Optional["Span"] = None
    seconds: Optional[float] = None
    ok: bool = True

    @property
    def depth(self) -> int:
        return 0 if self.parent is None else self.parent.depth + 1


def current_span() -> Optional[Span]:
    st = _stack()
    return st[-1] if st else None


def _device_annotation(name: str):
    # only annotate if jax is already imported — never pull it in ourselves
    jax = sys.modules.get("jax")
    if jax is None:
        return contextlib.nullcontext()
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()


@contextlib.contextmanager
def trace(name: str, _event: str = "span", **attrs) -> Iterator[Span]:
    """Open a nested span; yields the live ``Span`` so callers can attach
    attrs mid-flight (``span.attrs["rounds"] = r``)."""
    span = Span(name=name, attrs=dict(attrs), parent=current_span())
    _stack().append(span)
    t0 = time.perf_counter()
    try:
        with _device_annotation(name):
            yield span
    except BaseException:
        span.ok = False
        raise
    finally:
        span.seconds = time.perf_counter() - t0
        _stack().pop()
        SPAN_SECONDS.labels(name=name).observe(span.seconds)
        fields = dict(span.attrs)
        fields.update(name=name, seconds=span.seconds)
        if span.parent is not None:
            fields["parent"] = span.parent.name
            fields["depth"] = span.depth
        if not span.ok:
            fields["ok"] = False
        log_event(_event, **fields)


class Phases:
    """Accumulate named phase timings (``encode``/``compile``/``solve``)
    into a dict — the shape ``VerifyResult.timings`` has always carried —
    while each phase also runs as a full span (registry + events + device
    annotation). Timings accumulate even when the body raises, and the
    emitted ``phase`` event then carries ``ok: false``.
    """

    def __init__(self) -> None:
        self.timings: Dict[str, float] = {}

    @contextlib.contextmanager
    def __call__(self, name: str, **attrs) -> Iterator[Span]:
        t0 = time.perf_counter()
        try:
            with trace(name, _event="phase", **attrs) as span:
                yield span
        finally:
            self.timings[name] = self.timings.get(name, 0.0) + (
                time.perf_counter() - t0
            )


@contextlib.contextmanager
def profile_to(log_dir: str) -> Iterator[None]:
    """Capture a jax profiler trace into ``log_dir`` (TensorBoard format).
    No-op (with a warning event) when jax is unavailable."""
    try:
        import jax
    except Exception:  # pragma: no cover - exercised only without jax
        log_event("profile_skipped", reason="jax unavailable", log_dir=log_dir)
        yield
        return
    with jax.profiler.trace(log_dir):
        log_event("profile_start", log_dir=log_dir)
        yield
    log_event("profile_done", log_dir=log_dir)
