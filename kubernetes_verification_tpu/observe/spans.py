"""Spans: nested wall-clock regions that feed the registry, the JSON event
stream, and — when jax is already loaded — the device profiler.

``trace("solve", backend="tpu")`` is the one instrumentation primitive the
rest of the codebase uses. Each span:

* times the region and observes ``kvtpu_span_seconds{name=...}``;
* emits one JSON event line (with ``ok: false`` added when the body raised,
  instead of pretending the phase completed);
* nests via a thread-local stack, so events carry ``parent`` and depth;
* carries distributed-trace identity: a ``trace_id`` shared by every span
  of one logical operation (across processes, via the ``X-Kvtpu-Trace``
  header), its own ``span_id``, and ``parent_id`` linking it to its caller
  — the caller may live in another process (``trace_context`` adopts the
  parsed wire context so server-side spans parent under the client span);
* wraps ``jax.profiler.TraceAnnotation`` when jax is importable, so the
  same names line up in a TensorBoard TPU trace captured via
  ``profile_to``. jax is looked up in ``sys.modules`` — tracing never
  forces the heavyweight import on pure-host paths.

Timestamps come from the one injectable clock in ``observe.events``: event
lines carry wall ``ts`` (cross-process orderable) and monotonic ``perf``
(duration-stable within a process), so ``kv-tpu trace`` reassembles
timelines without guessing which clock a line was stamped from.

``Phases`` keeps the seed's accumulate-into-a-dict API (backends still hand
``VerifyResult.timings`` to callers) but is now a thin layer over spans.
"""
from __future__ import annotations

import contextlib
import json
import os
import signal
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, Optional, Tuple

from .events import get_clock, log_event, set_context_provider
from .metrics import PROFILE_CAPTURES_TOTAL, SPAN_SECONDS
from .registry import set_exemplar_provider

__all__ = [
    "Span",
    "trace",
    "current_span",
    "current_trace_id",
    "trace_context",
    "TRACE_HEADER",
    "trace_headers",
    "format_trace_header",
    "parse_trace_header",
    "add_span_sink",
    "remove_span_sink",
    "Phases",
    "profile_to",
    "trace_to_dir",
    "set_memory_hook",
    "PROFILE_DIR_ENV",
    "capture_profile",
    "load_capture_manifest",
    "install_profile_signal",
    "uninstall_profile_signal",
    "install_profile_from_env",
    "reset_profile_rate_limit",
]

#: HTTP header carrying trace context over the wire: ``<trace_id>-<span_id>``
#: (two lowercase-hex tokens). The receiver's spans adopt the trace id and
#: parent under the sender's span id.
TRACE_HEADER = "X-Kvtpu-Trace"

_state = threading.local()

#: optional () -> int callable returning live memory bytes; when installed
#: (``telemetry.install_span_memory_hook``) every span records
#: ``mem_enter_bytes``/``mem_exit_bytes`` in its event line
_memory_hook = None

#: callables handed every closed Span — the flight recorder's ring and the
#: bench stage collector subscribe here instead of parsing event lines
_span_sinks: list = []


def set_memory_hook(hook) -> None:
    """Install (or clear, with None) the span memory snapshot hook."""
    global _memory_hook
    _memory_hook = hook  # kvtpu: ignore[concurrency-hygiene] single atomic reference rebind; span readers tolerate either value


def add_span_sink(sink) -> None:
    """Subscribe ``sink(span)`` to every span close (append-only list —
    registration is rare; iteration tolerates concurrent appends)."""
    _span_sinks.append(sink)


def remove_span_sink(sink) -> None:
    """Unsubscribe a sink previously added; missing sinks are ignored."""
    try:
        _span_sinks.remove(sink)
    except ValueError:
        pass


def _memory_bytes():
    if _memory_hook is None:
        return None
    try:
        return int(_memory_hook())
    except Exception:  # telemetry must never fail a traced region
        return None


def _stack() -> list:
    st = getattr(_state, "spans", None)
    if st is None:
        st = _state.spans = []
    return st


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


@dataclass
class Span:
    """One timed region. ``seconds``/``ok`` are filled when it closes."""

    name: str
    attrs: Dict[str, object] = field(default_factory=dict)
    parent: Optional["Span"] = None
    seconds: Optional[float] = None
    ok: bool = True
    trace_id: str = ""
    span_id: str = ""
    parent_id: Optional[str] = None
    start_wall: Optional[float] = None

    @property
    def depth(self) -> int:
        return 0 if self.parent is None else self.parent.depth + 1


def current_span() -> Optional[Span]:
    st = _stack()
    return st[-1] if st else None


def current_trace_id() -> Optional[str]:
    """The trace id spans opened *now* would join: the active span's, else
    an adopted remote context's, else None (a fresh root would mint one)."""
    span = current_span()
    if span is not None:
        return span.trace_id
    remote = getattr(_state, "remote", None)
    return remote[0] if remote else None


@contextlib.contextmanager
def trace_context(
    trace_id: Optional[str], parent_span_id: Optional[str] = None
) -> Iterator[None]:
    """Adopt a remote trace context for the duration of the block: root
    spans opened inside join ``trace_id`` and parent under
    ``parent_span_id`` instead of minting a fresh trace. A None
    ``trace_id`` is a no-op block, so callers can pass the (possibly
    absent) parsed header straight through."""
    if not trace_id:
        yield
        return
    prev = getattr(_state, "remote", None)
    _state.remote = (trace_id, parent_span_id)
    try:
        yield
    finally:
        _state.remote = prev


def format_trace_header(trace_id: str, span_id: str) -> str:
    return f"{trace_id}-{span_id}"


def parse_trace_header(value: Optional[str]) -> Tuple[Optional[str], Optional[str]]:
    """``(trace_id, parent_span_id)`` from an ``X-Kvtpu-Trace`` value;
    ``(None, None)`` for absent or malformed headers (never raises — a bad
    header must not fail the request it rode in on)."""
    if not value:
        return None, None
    head, sep, tail = value.strip().partition("-")
    if not sep or not head or not tail:
        return None, None
    try:
        int(head, 16), int(tail, 16)
    except ValueError:
        return None, None
    return head, tail


def trace_headers() -> Dict[str, str]:
    """Headers to stamp on an outgoing request: ``{TRACE_HEADER: ...}``
    when a trace is active on this thread, ``{}`` otherwise. Always pass
    this to ``conn.request(..., headers=trace_headers())`` — the
    trace-context lint counts un-headered requests as findings."""
    span = current_span()
    if span is not None:
        return {TRACE_HEADER: format_trace_header(span.trace_id, span.span_id)}
    remote = getattr(_state, "remote", None)
    if remote and remote[0]:
        return {TRACE_HEADER: format_trace_header(remote[0], remote[1] or "0")}
    return {}


def _trace_fields() -> Dict[str, object]:
    """Context-provider body for ``log_event``: every event line emitted
    inside a traced region carries the trace/span ids, even when the
    emitting module has never heard of spans."""
    span = current_span()
    if span is not None:
        return {"trace_id": span.trace_id, "span_id": span.span_id}
    remote = getattr(_state, "remote", None)
    if remote and remote[0]:
        return {"trace_id": remote[0]}
    return {}


set_context_provider(_trace_fields)
# histograms retain the slowest-in-window trace id per bucket; the registry
# cannot import us (cycle), so it receives the trace-id source here
set_exemplar_provider(current_trace_id)


def _device_annotation(name: str):
    # only annotate if jax is already imported — never pull it in ourselves
    jax = sys.modules.get("jax")
    if jax is None:
        return contextlib.nullcontext()
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()


@contextlib.contextmanager
def trace(name: str, _event: str = "span", **attrs) -> Iterator[Span]:
    """Open a nested span; yields the live ``Span`` so callers can attach
    attrs mid-flight (``span.attrs["rounds"] = r``)."""
    parent = current_span()
    if parent is not None:
        trace_id, parent_id = parent.trace_id, parent.span_id
    else:
        remote = getattr(_state, "remote", None)
        if remote and remote[0]:
            trace_id, parent_id = remote
        else:
            trace_id, parent_id = _new_id(8), None
    clock = get_clock()
    span = Span(
        name=name,
        attrs=dict(attrs),
        parent=parent,
        trace_id=trace_id,
        span_id=_new_id(4),
        parent_id=parent_id,
        start_wall=clock.wall(),
    )
    mem0 = _memory_bytes()
    if mem0 is not None:
        span.attrs["mem_enter_bytes"] = mem0
    _stack().append(span)
    t0 = clock.perf()
    try:
        with _device_annotation(name):
            yield span
    except BaseException:
        span.ok = False
        raise
    finally:
        span.seconds = clock.perf() - t0
        _stack().pop()
        SPAN_SECONDS.labels(name=name).observe(span.seconds)
        mem1 = _memory_bytes()
        if mem1 is not None:
            span.attrs["mem_exit_bytes"] = mem1
        fields = dict(span.attrs)
        fields.update(
            name=name,
            seconds=span.seconds,
            trace_id=span.trace_id,
            span_id=span.span_id,
            start_ts=span.start_wall,
        )
        if span.parent_id is not None:
            fields["parent_id"] = span.parent_id
        if span.parent is not None:
            fields["parent"] = span.parent.name
            fields["depth"] = span.depth
        if not span.ok:
            fields["ok"] = False
        log_event(_event, **fields)
        for sink in list(_span_sinks):
            try:
                sink(span)
            except Exception:  # a broken sink must not fail traced work
                pass


class Phases:
    """Accumulate named phase timings (``encode``/``compile``/``solve``)
    into a dict — the shape ``VerifyResult.timings`` has always carried —
    while each phase also runs as a full span (registry + events + device
    annotation). Timings accumulate even when the body raises, and the
    emitted ``phase`` event then carries ``ok: false``. Uses the same
    injectable clock the spans themselves stamp from.
    """

    def __init__(self) -> None:
        self.timings: Dict[str, float] = {}

    @contextlib.contextmanager
    def __call__(self, name: str, **attrs) -> Iterator[Span]:
        clock = get_clock()
        t0 = clock.perf()
        try:
            with trace(name, _event="phase", **attrs) as span:
                yield span
        finally:
            self.timings[name] = self.timings.get(name, 0.0) + (
                clock.perf() - t0
            )


@contextlib.contextmanager
def profile_to(log_dir: str) -> Iterator[None]:
    """Capture a jax profiler trace into ``log_dir`` (TensorBoard format).

    Degrades to a no-op — one hint line on stderr plus a
    ``profile_skipped`` event — when jax is unavailable or the platform has
    no profiler support, instead of failing the whole command. Creates
    ``log_dir`` (the jax profiler assumes it exists)."""
    try:
        import jax
    except Exception:  # pragma: no cover - exercised only without jax
        log_event("profile_skipped", reason="jax unavailable", log_dir=log_dir)
        yield
        return
    import os

    os.makedirs(log_dir, exist_ok=True)
    try:
        ctx = jax.profiler.trace(log_dir)
        ctx.__enter__()
    except Exception as e:
        print(
            f"kv-tpu: --profile unsupported on this platform "
            f"({type(e).__name__}: {e}); continuing without a device trace",
            file=sys.stderr,
        )
        log_event(
            "profile_skipped",
            reason=f"{type(e).__name__}: {e}",
            log_dir=log_dir,
        )
        yield
        return
    log_event("profile_start", log_dir=log_dir)
    ok = True
    try:
        yield
    finally:
        try:
            ctx.__exit__(None, None, None)
        except Exception as e:
            ok = False
            print(
                f"kv-tpu: --profile capture failed "
                f"({type(e).__name__}: {e}); no trace written to {log_dir}",
                file=sys.stderr,
            )
            log_event(
                "profile_skipped",
                reason=f"{type(e).__name__}: {e}",
                log_dir=log_dir,
            )
        if ok:
            log_event("profile_done", log_dir=log_dir)


#: the name ISSUE/older docs use for the same facility
trace_to_dir = profile_to


# ---------------------------------------------------- on-demand deep capture
#: environment variable arming the SIGUSR1 capture handler in subprocess
#: harnesses (same regime as the flight recorder's KVTPU_FLIGHT_DIR)
PROFILE_DIR_ENV = "KVTPU_PROFILE_DIR"

#: minimum seconds between completed captures (override with
#: KVTPU_PROFILE_MIN_INTERVAL or a ``min_interval`` argument): a scrape
#: loop hammering /profile must not keep the device profiler permanently
#: on
DEFAULT_CAPTURE_MIN_INTERVAL = 30.0

#: bound on one capture window — /profile?seconds=N is operator-facing and
#: a typo must not profile for an hour
MAX_CAPTURE_SECONDS = 60.0

CAPTURE_MANIFEST = "manifest.json"

_capture_lock = threading.Lock()
_last_capture_perf: Optional[float] = None


def reset_profile_rate_limit() -> None:
    """Forget the last capture time (tests; also after reconfiguring the
    interval)."""
    global _last_capture_perf
    with _capture_lock:
        _last_capture_perf = None


def _capture_min_interval(min_interval: Optional[float]) -> float:
    if min_interval is not None:
        return float(min_interval)
    raw = os.environ.get("KVTPU_PROFILE_MIN_INTERVAL")
    try:
        return float(raw) if raw else DEFAULT_CAPTURE_MIN_INTERVAL
    except ValueError:
        return DEFAULT_CAPTURE_MIN_INTERVAL


def _capture_file_count(path: str) -> int:
    total = 0
    for _dir, _sub, files in os.walk(path):
        total += len(files)
    return total


def load_capture_manifest(capture_dir: str) -> list:
    """The capture dir's manifest entries (newest last); [] when no capture
    has completed there."""
    try:
        with open(os.path.join(capture_dir, CAPTURE_MANIFEST)) as fh:
            entries = json.load(fh)
    except (OSError, ValueError):
        return []
    return entries if isinstance(entries, list) else []


def _append_manifest(capture_dir: str, entry: dict) -> None:
    # caller holds _capture_lock; atomic replace so a reader mid-capture
    # never sees a torn manifest
    entries = load_capture_manifest(capture_dir)
    entries.append(entry)
    path = os.path.join(capture_dir, CAPTURE_MANIFEST)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(entries, fh, indent=2, sort_keys=True)
        fh.write("\n")
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def capture_profile(
    seconds: float,
    *,
    trigger: str = "api",
    capture_dir: Optional[str] = None,
    min_interval: Optional[float] = None,
) -> dict:
    """One bounded ``jax.profiler`` capture: ``start_trace``, wait
    ``seconds`` (clamped to :data:`MAX_CAPTURE_SECONDS`), ``stop_trace``,
    record the capture in ``<capture_dir>/manifest.json``.

    Returns a JSON-safe outcome dict and never raises: ``ok`` (path,
    seconds, file count), ``rate-limited`` (a capture completed less than
    ``min_interval`` ago — the device is not re-profiled), or ``skipped``
    (jax or its profiler unavailable; the triggering surface stays up).
    Completed captures count into
    ``kvtpu_profile_captures_total{trigger}``."""
    global _last_capture_perf
    seconds = min(max(float(seconds), 0.01), MAX_CAPTURE_SECONDS)
    capture_dir = (
        capture_dir
        or os.environ.get(PROFILE_DIR_ENV)
        or os.path.join(os.getcwd(), "kvtpu-profiles")
    )
    interval = _capture_min_interval(min_interval)
    with _capture_lock:
        now = get_clock().perf()
        if (
            _last_capture_perf is not None
            and now - _last_capture_perf < interval
        ):
            retry = interval - (now - _last_capture_perf)
            log_event(
                "profile_rate_limited",
                trigger=trigger,
                retry_after_s=round(retry, 3),
            )
            return {
                "outcome": "rate-limited",
                "trigger": trigger,
                "retry_after_s": round(retry, 3),
            }
        try:
            import jax
        except Exception as e:  # pragma: no cover - exercised without jax
            log_event(
                "profile_skipped", trigger=trigger,
                reason=f"{type(e).__name__}: {e}",
            )
            return {"outcome": "skipped", "trigger": trigger,
                    "reason": "jax unavailable"}
        wall = get_clock().wall()
        path = os.path.join(
            capture_dir, f"capture-{int(wall * 1000)}-{trigger}"
        )
        os.makedirs(path, exist_ok=True)
        try:
            jax.profiler.start_trace(path)
        except Exception as e:
            log_event(
                "profile_skipped", trigger=trigger,
                reason=f"{type(e).__name__}: {e}", path=path,
            )
            return {"outcome": "skipped", "trigger": trigger,
                    "reason": f"{type(e).__name__}: {e}"}
        time.sleep(seconds)
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            log_event(
                "profile_skipped", trigger=trigger,
                reason=f"{type(e).__name__}: {e}", path=path,
            )
            return {"outcome": "skipped", "trigger": trigger,
                    "reason": f"{type(e).__name__}: {e}"}
        _last_capture_perf = get_clock().perf()
        entry = {
            "path": path,
            "trigger": trigger,
            "seconds": seconds,
            "ts": wall,
            "files": _capture_file_count(path),
        }
        _append_manifest(capture_dir, entry)
    PROFILE_CAPTURES_TOTAL.labels(trigger=trigger).inc()
    log_event("profile_capture", **entry)
    return {"outcome": "ok", **entry}


_prev_sigusr1 = None
_sigusr1_config: Optional[tuple] = None
_last_sigusr1_thread: Optional[threading.Thread] = None


def install_profile_signal(
    capture_dir: Optional[str] = None,
    seconds: float = 2.0,
    min_interval: Optional[float] = None,
) -> bool:
    """Bind SIGUSR1 to a bounded profiler capture (in a worker thread — a
    signal handler must not block the main thread for the whole window).

    Chains any pre-existing Python handler: the profile capture fires AND
    the previous handler still runs, so arming deep profiling never
    disables another subsystem's signal (the flight recorder does the same
    on SIGUSR2). Returns False where signals cannot be bound (no SIGUSR1 on
    the platform, or not the main thread)."""
    global _prev_sigusr1, _sigusr1_config
    uninstall_profile_signal()
    _sigusr1_config = (capture_dir, float(seconds), min_interval)  # kvtpu: ignore[concurrency-hygiene] install/uninstall run on the main thread only
    if not hasattr(signal, "SIGUSR1"):
        return False

    def _handler(signum, frame):
        global _last_sigusr1_thread
        cfg = _sigusr1_config
        if cfg is not None:
            t = threading.Thread(
                target=capture_profile,
                args=(cfg[1],),
                kwargs={
                    "trigger": "sigusr1",
                    "capture_dir": cfg[0],
                    "min_interval": cfg[2],
                },
                daemon=True,
                name="kvtpu-profile-capture",
            )
            _last_sigusr1_thread = t  # kvtpu: ignore[concurrency-hygiene] signal handlers run on the main thread only
            t.start()
        prev = _prev_sigusr1
        if callable(prev):
            prev(signum, frame)

    try:
        _prev_sigusr1 = signal.signal(signal.SIGUSR1, _handler)  # kvtpu: ignore[concurrency-hygiene] install/uninstall run on the main thread only
    except ValueError:  # not the main thread — HTTP/CLI triggers still work
        _prev_sigusr1 = None  # kvtpu: ignore[concurrency-hygiene] install/uninstall run on the main thread only
        _sigusr1_config = None  # kvtpu: ignore[concurrency-hygiene] install/uninstall run on the main thread only
        return False
    return True


def uninstall_profile_signal() -> None:
    """Restore the previous SIGUSR1 disposition (tests; also the first half
    of re-install)."""
    global _prev_sigusr1, _sigusr1_config
    _sigusr1_config = None  # kvtpu: ignore[concurrency-hygiene] install/uninstall run on the main thread only
    if _prev_sigusr1 is not None and hasattr(signal, "SIGUSR1"):
        try:
            signal.signal(signal.SIGUSR1, _prev_sigusr1)
        except ValueError:
            pass
        _prev_sigusr1 = None  # kvtpu: ignore[concurrency-hygiene] install/uninstall run on the main thread only


def install_profile_from_env() -> bool:
    """Arm the SIGUSR1 capture handler from ``KVTPU_PROFILE_DIR`` — the
    zero-flag hook subprocess harnesses call at startup."""
    directory = os.environ.get(PROFILE_DIR_ENV)
    if not directory:
        return False
    return install_profile_signal(directory)
