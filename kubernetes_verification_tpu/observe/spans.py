"""Spans: nested wall-clock regions that feed the registry, the JSON event
stream, and — when jax is already loaded — the device profiler.

``trace("solve", backend="tpu")`` is the one instrumentation primitive the
rest of the codebase uses. Each span:

* times the region and observes ``kvtpu_span_seconds{name=...}``;
* emits one JSON event line (with ``ok: false`` added when the body raised,
  instead of pretending the phase completed);
* nests via a thread-local stack, so events carry ``parent`` and depth;
* wraps ``jax.profiler.TraceAnnotation`` when jax is importable, so the
  same names line up in a TensorBoard TPU trace captured via
  ``profile_to``. jax is looked up in ``sys.modules`` — tracing never
  forces the heavyweight import on pure-host paths.

``Phases`` keeps the seed's accumulate-into-a-dict API (backends still hand
``VerifyResult.timings`` to callers) but is now a thin layer over spans.
"""
from __future__ import annotations

import contextlib
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, Optional

from .events import log_event
from .metrics import SPAN_SECONDS

__all__ = [
    "Span",
    "trace",
    "current_span",
    "Phases",
    "profile_to",
    "trace_to_dir",
    "set_memory_hook",
]

_state = threading.local()

#: optional () -> int callable returning live memory bytes; when installed
#: (``telemetry.install_span_memory_hook``) every span records
#: ``mem_enter_bytes``/``mem_exit_bytes`` in its event line
_memory_hook = None


def set_memory_hook(hook) -> None:
    """Install (or clear, with None) the span memory snapshot hook."""
    global _memory_hook
    _memory_hook = hook  # kvtpu: ignore[concurrency-hygiene] single atomic reference rebind; span readers tolerate either value


def _memory_bytes():
    if _memory_hook is None:
        return None
    try:
        return int(_memory_hook())
    except Exception:  # telemetry must never fail a traced region
        return None


def _stack() -> list:
    st = getattr(_state, "spans", None)
    if st is None:
        st = _state.spans = []
    return st


@dataclass
class Span:
    """One timed region. ``seconds``/``ok`` are filled when it closes."""

    name: str
    attrs: Dict[str, object] = field(default_factory=dict)
    parent: Optional["Span"] = None
    seconds: Optional[float] = None
    ok: bool = True

    @property
    def depth(self) -> int:
        return 0 if self.parent is None else self.parent.depth + 1


def current_span() -> Optional[Span]:
    st = _stack()
    return st[-1] if st else None


def _device_annotation(name: str):
    # only annotate if jax is already imported — never pull it in ourselves
    jax = sys.modules.get("jax")
    if jax is None:
        return contextlib.nullcontext()
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()


@contextlib.contextmanager
def trace(name: str, _event: str = "span", **attrs) -> Iterator[Span]:
    """Open a nested span; yields the live ``Span`` so callers can attach
    attrs mid-flight (``span.attrs["rounds"] = r``)."""
    span = Span(name=name, attrs=dict(attrs), parent=current_span())
    mem0 = _memory_bytes()
    if mem0 is not None:
        span.attrs["mem_enter_bytes"] = mem0
    _stack().append(span)
    t0 = time.perf_counter()
    try:
        with _device_annotation(name):
            yield span
    except BaseException:
        span.ok = False
        raise
    finally:
        span.seconds = time.perf_counter() - t0
        _stack().pop()
        SPAN_SECONDS.labels(name=name).observe(span.seconds)
        mem1 = _memory_bytes()
        if mem1 is not None:
            span.attrs["mem_exit_bytes"] = mem1
        fields = dict(span.attrs)
        fields.update(name=name, seconds=span.seconds)
        if span.parent is not None:
            fields["parent"] = span.parent.name
            fields["depth"] = span.depth
        if not span.ok:
            fields["ok"] = False
        log_event(_event, **fields)


class Phases:
    """Accumulate named phase timings (``encode``/``compile``/``solve``)
    into a dict — the shape ``VerifyResult.timings`` has always carried —
    while each phase also runs as a full span (registry + events + device
    annotation). Timings accumulate even when the body raises, and the
    emitted ``phase`` event then carries ``ok: false``.
    """

    def __init__(self) -> None:
        self.timings: Dict[str, float] = {}

    @contextlib.contextmanager
    def __call__(self, name: str, **attrs) -> Iterator[Span]:
        t0 = time.perf_counter()
        try:
            with trace(name, _event="phase", **attrs) as span:
                yield span
        finally:
            self.timings[name] = self.timings.get(name, 0.0) + (
                time.perf_counter() - t0
            )


@contextlib.contextmanager
def profile_to(log_dir: str) -> Iterator[None]:
    """Capture a jax profiler trace into ``log_dir`` (TensorBoard format).

    Degrades to a no-op — one hint line on stderr plus a
    ``profile_skipped`` event — when jax is unavailable or the platform has
    no profiler support, instead of failing the whole command. Creates
    ``log_dir`` (the jax profiler assumes it exists)."""
    try:
        import jax
    except Exception:  # pragma: no cover - exercised only without jax
        log_event("profile_skipped", reason="jax unavailable", log_dir=log_dir)
        yield
        return
    import os

    os.makedirs(log_dir, exist_ok=True)
    try:
        ctx = jax.profiler.trace(log_dir)
        ctx.__enter__()
    except Exception as e:
        print(
            f"kv-tpu: --profile unsupported on this platform "
            f"({type(e).__name__}: {e}); continuing without a device trace",
            file=sys.stderr,
        )
        log_event(
            "profile_skipped",
            reason=f"{type(e).__name__}: {e}",
            log_dir=log_dir,
        )
        yield
        return
    log_event("profile_start", log_dir=log_dir)
    ok = True
    try:
        yield
    finally:
        try:
            ctx.__exit__(None, None, None)
        except Exception as e:
            ok = False
            print(
                f"kv-tpu: --profile capture failed "
                f"({type(e).__name__}: {e}); no trace written to {log_dir}",
                file=sys.stderr,
            )
            log_event(
                "profile_skipped",
                reason=f"{type(e).__name__}: {e}",
                log_dir=log_dir,
            )
        if ok:
            log_event("profile_done", log_dir=log_dir)


#: the name ISSUE/older docs use for the same facility
trace_to_dir = profile_to
