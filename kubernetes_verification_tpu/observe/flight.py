"""Flight recorder: a bounded in-memory ring of recent spans and event
lines plus a metric-delta baseline, dumped to ``flight-<ts>.json`` when the
process hits trouble — a ``KvTpuError`` escalating out of a CLI command, a
circuit breaker opening, a fault-injection kill-point firing (the dump
lands before ``os._exit``), or an operator ``SIGUSR2``.

The point is post-mortem without prearranged logging: a SIGKILLed leader's
last ~512 observability records survive on disk even when nobody pointed
``--log-json`` anywhere. The recorder is passive until :func:`install` is
called (``kv-tpu --flight DIR``, or ``KVTPU_FLIGHT_DIR`` in subprocess
harnesses); every trigger seam in the codebase calls
:func:`trigger_dump`, which is a no-op while nothing is installed.

Capture taps:

* a span sink (``observe.spans.add_span_sink``) records every closed span
  with its trace identity, so a dump is also a partial trace;
* a ``logging.Handler`` on the ``kvtpu`` logger records every JSON event
  line (the recorder parses them back so the dump holds structured data);
* the registry is snapshotted at install and diffed at dump time — the
  ``metric_deltas`` section shows what this process *did*, not its
  lifetime totals.
"""
from __future__ import annotations

import collections
import json
import logging
import os
import signal
import threading
from typing import Dict, List, Optional

from .events import get_clock, log_event, logger
from .metrics import FLIGHT_DUMPS_TOTAL
from .registry import REGISTRY
from .spans import Span, add_span_sink, remove_span_sink

__all__ = [
    "FlightRecorder",
    "install",
    "uninstall",
    "installed",
    "trigger_dump",
    "recent_dumps",
    "load_dump",
    "render_dump",
    "FLIGHT_SCHEMA",
    "FLIGHT_DIR_ENV",
]

FLIGHT_SCHEMA = "kvtpu-flight-v1"

#: environment variable subprocess harnesses (bench workers, chaos
#: children) use to arm the recorder without plumbing a CLI flag through
FLIGHT_DIR_ENV = "KVTPU_FLIGHT_DIR"

DEFAULT_CAPACITY = 512


def _json_safe(value):
    try:
        json.dumps(value)
        return value
    except (TypeError, ValueError):
        return repr(value)


class _RingHandler(logging.Handler):
    """Captures every ``kvtpu`` event line into the recorder's ring."""

    def __init__(self, recorder: "FlightRecorder") -> None:
        super().__init__(level=logging.INFO)
        self._recorder = recorder

    def emit(self, record) -> None:  # pragma: no cover - trivial dispatch
        try:
            self._recorder._record_event(record.getMessage())
        except Exception:
            pass  # the recorder must never fail the code it observes


class FlightRecorder:
    """Bounded ring of recent observability records for one process."""

    def __init__(
        self, directory: str, capacity: int = DEFAULT_CAPACITY
    ) -> None:
        self.directory = directory
        self.capacity = int(capacity)
        self._ring: collections.deque = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._handler: Optional[_RingHandler] = None
        self._baseline = self._scalar_snapshot()
        self._dumps = 0

    # -- capture taps ----------------------------------------------------

    def _record_span(self, span: Span) -> None:
        entry = {
            "kind": "span",
            "name": span.name,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_id": span.parent_id,
            "start_ts": span.start_wall,
            "seconds": span.seconds,
            "ok": span.ok,
            "attrs": {k: _json_safe(v) for k, v in span.attrs.items()},
        }
        with self._lock:
            self._ring.append(entry)

    def _record_event(self, line: str) -> None:
        try:
            payload = json.loads(line)
        except (ValueError, TypeError):
            payload = {"raw": line}
        # span/phase closes already arrive via the span sink with richer
        # identity; recording their event line too would halve capacity
        if payload.get("event") in ("span", "phase"):
            return
        with self._lock:
            self._ring.append({"kind": "event", "data": payload})

    @staticmethod
    def _scalar_snapshot() -> Dict[str, Dict[str, float]]:
        d = REGISTRY.dump(include_buckets=False)
        return {
            "counters": {
                name: dict(children)
                for name, children in d.get("counters", {}).items()
            },
            "gauges": {
                name: dict(children)
                for name, children in d.get("gauges", {}).items()
            },
        }

    def _metric_deltas(self) -> Dict[str, Dict[str, Dict[str, float]]]:
        now = self._scalar_snapshot()
        out: Dict[str, Dict[str, Dict[str, float]]] = {}
        for section in ("counters", "gauges"):
            deltas: Dict[str, Dict[str, float]] = {}
            base = self._baseline.get(section, {})
            for name, children in now[section].items():
                fam_base = base.get(name, {})
                changed = {
                    key: round(value - fam_base.get(key, 0.0), 9)
                    for key, value in children.items()
                    if value != fam_base.get(key, 0.0)
                }
                if changed:
                    deltas[name] = changed
            out[section] = deltas
        return out

    # -- lifecycle -------------------------------------------------------

    def attach(self) -> None:
        add_span_sink(self._record_span)
        self._handler = _RingHandler(self)
        logger.addHandler(self._handler)
        # log_event() gates on isEnabledFor(INFO); an unconfigured process
        # would record nothing — exactly the process the recorder exists
        # for. Opening the logger level is safe: Python's last-resort
        # handler only prints WARNING+, so nothing leaks to stderr.
        self._prev_level = logger.level
        if not logger.isEnabledFor(logging.INFO):
            logger.setLevel(logging.INFO)

    def detach(self) -> None:
        remove_span_sink(self._record_span)
        if self._handler is not None:
            logger.removeHandler(self._handler)
            self._handler = None
        prev = getattr(self, "_prev_level", None)
        if prev is not None:
            logger.setLevel(prev)
            self._prev_level = None

    # -- dumping ---------------------------------------------------------

    def dump(self, trigger: str, info: Optional[dict] = None) -> str:
        """Write the ring to ``flight-<ts>.json`` in the recorder's
        directory (atomically — a reaper reading mid-crash never sees a
        torn file) and return the path."""
        clock = get_clock()
        ts = clock.wall()
        with self._lock:
            entries = list(self._ring)
            self._dumps += 1
            seq = self._dumps
        payload = {
            "schema": FLIGHT_SCHEMA,
            "trigger": trigger,
            "info": {k: _json_safe(v) for k, v in (info or {}).items()},
            "ts": ts,
            "pid": os.getpid(),
            "capacity": self.capacity,
            "entries": entries,
            "metric_deltas": self._metric_deltas(),
        }
        os.makedirs(self.directory, exist_ok=True)
        name = f"flight-{int(ts * 1000)}-{os.getpid()}-{seq}.json"
        path = os.path.join(self.directory, name)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(payload, fh, indent=2, sort_keys=True)
            fh.write("\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        FLIGHT_DUMPS_TOTAL.labels(trigger=trigger).inc()
        log_event("flight_dump", trigger=trigger, path=path, entries=len(entries))
        return path


_RECORDER: Optional[FlightRecorder] = None
_prev_sigusr2 = None


def installed() -> Optional[FlightRecorder]:
    return _RECORDER


def install(
    directory: str,
    capacity: int = DEFAULT_CAPACITY,
    with_signal: bool = True,
) -> FlightRecorder:
    """Arm the process-global flight recorder writing into ``directory``.

    Idempotent per directory: re-installing replaces the previous
    recorder (its taps are detached first). ``SIGUSR2`` is bound to an
    on-demand dump when possible (main thread, platform with the signal);
    elsewhere the recorder still dumps on the programmatic triggers."""
    global _RECORDER, _prev_sigusr2
    uninstall()
    rec = FlightRecorder(directory, capacity=capacity)
    rec.attach()
    _RECORDER = rec  # kvtpu: ignore[concurrency-hygiene] single atomic reference rebind; trigger_dump tolerates either value
    if with_signal and hasattr(signal, "SIGUSR2"):

        def _handler(signum, frame):
            trigger_dump("sigusr2")
            # chain any pre-existing Python handler: arming the recorder
            # must not silently disable another subsystem's SIGUSR2
            prev = _prev_sigusr2
            if callable(prev):
                prev(signum, frame)

        try:
            _prev_sigusr2 = signal.signal(signal.SIGUSR2, _handler)  # kvtpu: ignore[concurrency-hygiene] install/uninstall run on the main thread only
        except ValueError:  # not the main thread — programmatic triggers only
            _prev_sigusr2 = None  # kvtpu: ignore[concurrency-hygiene] install/uninstall run on the main thread only
    return rec


def uninstall() -> None:
    """Disarm the recorder (tests; also the first half of re-install)."""
    global _RECORDER, _prev_sigusr2
    if _RECORDER is not None:
        _RECORDER.detach()
        _RECORDER = None  # kvtpu: ignore[concurrency-hygiene] single atomic reference rebind; trigger_dump tolerates either value
    if _prev_sigusr2 is not None and hasattr(signal, "SIGUSR2"):
        try:
            signal.signal(signal.SIGUSR2, _prev_sigusr2)
        except ValueError:
            pass
        _prev_sigusr2 = None  # kvtpu: ignore[concurrency-hygiene] install/uninstall run on the main thread only


def install_from_env() -> Optional[FlightRecorder]:
    """Arm the recorder from ``KVTPU_FLIGHT_DIR`` when set — the hook
    subprocess harnesses (bench workers, chaos children) call at startup."""
    directory = os.environ.get(FLIGHT_DIR_ENV)
    if not directory:
        return None
    return install(directory)


def trigger_dump(trigger: str, **info) -> Optional[str]:
    """Dump the ring if a recorder is installed; returns the dump path or
    None. Never raises — every caller sits on an error path already."""
    rec = _RECORDER
    if rec is None:
        return None
    try:
        return rec.dump(trigger, info)
    except Exception:  # pragma: no cover - disk-full etc. on a crash path
        return None


# -- reading dumps back (kv-tpu recover / tests) -------------------------


def recent_dumps(
    directory: Optional[str] = None, limit: int = 5
) -> List[str]:
    """Newest-first ``flight-*.json`` paths under ``directory`` (default:
    the installed recorder's directory, else ``KVTPU_FLIGHT_DIR``); [] when
    nothing is armed or nothing was dumped — the list ``/healthz`` and
    ``kv-tpu top`` surface."""
    if directory is None:
        rec = _RECORDER
        directory = rec.directory if rec is not None else os.environ.get(
            FLIGHT_DIR_ENV
        )
    if not directory:
        return []
    try:
        names = [
            n
            for n in os.listdir(directory)
            if n.startswith("flight-") and n.endswith(".json")
        ]
    except OSError:
        return []
    names.sort(reverse=True)
    return [os.path.join(directory, n) for n in names[: max(limit, 0)]]


def load_dump(path: str) -> dict:
    """Parse a flight dump; raises ValueError on schema mismatch."""
    with open(path) as fh:
        payload = json.load(fh)
    if payload.get("schema") != FLIGHT_SCHEMA:
        # kvtpu: ignore[error-taxonomy] documented parse contract: callers (kv-tpu recover) map it to a per-file error entry
        raise ValueError(
            f"{path}: not a flight dump (schema={payload.get('schema')!r})"
        )
    return payload


def render_dump(payload: dict) -> List[str]:
    """Human-readable lines for one dump — trigger header, the recent
    entries oldest-first, then the metric deltas."""
    lines = [
        f"flight dump: trigger={payload.get('trigger')} "
        f"pid={payload.get('pid')} ts={payload.get('ts'):.3f} "
        f"entries={len(payload.get('entries', []))}"
    ]
    info = payload.get("info") or {}
    if info:
        detail = " ".join(f"{k}={v}" for k, v in sorted(info.items()))
        lines.append(f"  {detail}")
    for entry in payload.get("entries", []):
        if entry.get("kind") == "span":
            ok = "" if entry.get("ok", True) else " FAILED"
            lines.append(
                f"  span  {entry.get('name')} "
                f"{(entry.get('seconds') or 0.0) * 1000:.3f}ms "
                f"trace={entry.get('trace_id')}{ok}"
            )
        else:
            data = entry.get("data", {})
            rest = {
                k: v for k, v in data.items() if k not in ("event", "ts", "perf")
            }
            detail = " ".join(f"{k}={v}" for k, v in sorted(rest.items()))
            lines.append(f"  event {data.get('event')} {detail}".rstrip())
    deltas = payload.get("metric_deltas", {})
    for section in ("counters", "gauges"):
        for name, children in sorted(deltas.get(section, {}).items()):
            for key, value in sorted(children.items()):
                label = f"{{{key}}}" if key else ""
                lines.append(f"  delta {name}{label} {value:+g}")
    return lines
