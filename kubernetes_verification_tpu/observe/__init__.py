"""Observability for kubernetes-verification-tpu.

One import surface for the whole stack:

* ``REGISTRY`` / ``MetricsRegistry`` — process-global counters, gauges,
  fixed-bucket histograms (``registry``); the shared families live in
  ``metrics``.
* ``trace`` / ``Span`` / ``Phases`` — nested wall-clock spans that feed the
  registry, emit JSON event lines, and annotate device profiler traces
  (``spans``).
* ``log_event`` / ``configure_logging`` — the JSON event stream
  (``events``).
* ``DispatchTracker`` — jit-recompile detection by abstract-shape hashing
  (``jit``).
* ``dump_registry`` / ``write_metrics`` / ``to_prometheus`` — exporters
  (``export``).
* ``KernelCostReport`` / ``set_introspection`` / ``maybe_publish`` — AOT
  cost/memory analysis of compiled dispatch sites (``introspect``).
* ``memory_snapshot`` / ``start_sampler`` — live device-memory telemetry
  with host-RSS fallback (``telemetry``).
* ``append_run`` / ``check_regression`` / ``expand_derived`` — the
  bench-history store and (dispatch-deflation-aware) regression gate
  (``history``).
* ``run_calibration`` / ``SentinelSuite`` — fixed-shape compute-bound
  calibration kernels + the dispatch-latency probe; the noise context
  every bench record carries (``sentinel``).
* ``TRACE_HEADER`` / ``trace_headers`` / ``parse_trace_header`` /
  ``trace_context`` — distributed-trace context over the wire (``spans``).
* ``FlightRecorder`` / ``trigger_dump`` — the crash flight recorder
  (``flight``).
* ``SloMonitor`` / ``scrape_replica`` — fleet scraping and SLO burn-rate
  evaluation (``fleet``).

``utils.observe`` re-exports the seed-era names from here for backward
compatibility.
"""
from __future__ import annotations

from . import (
    fleet,
    flight,
    history,
    introspect,
    metrics,
    progress,
    sentinel,
    telemetry,
)
from .events import (
    Clock,
    configure_logging,
    get_clock,
    log_event,
    logger,
    set_clock,
)
from .export import (
    dump_registry,
    parse_exemplars,
    parse_prometheus,
    to_prometheus,
    write_metrics,
)
from .fleet import (
    ReplicaScrape,
    SloMonitor,
    SloObjective,
    parse_slo_spec,
    render_fleet,
    scrape_replica,
)
from .flight import (
    FlightRecorder,
    load_dump,
    render_dump,
    trigger_dump,
)
from .flight import install_from_env as install_flight_recorder_from_env
from .history import (
    append_run,
    check_regression,
    deflate_record,
    expand_derived,
    load_runs,
)
from .introspect import (
    KernelCostReport,
    device_peak_macs_per_s,
    format_cost_table,
    format_roofline_table,
    maybe_publish,
    publish_host_estimate,
    roofline_rows,
    set_introspection,
)
from .sentinel import (
    SentinelCalibrationError,
    SentinelSuite,
    run_calibration,
    slim_context,
)
from .jit import DispatchTracker, abstract_signature, tree_nbytes
from .registry import (
    DEFAULT_BUCKETS,
    METRIC_NAME_RE,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from .progress import ProgressTicker, active_jobs, eta_bar, render_jobs
from .spans import (
    PROFILE_DIR_ENV,
    TRACE_HEADER,
    Phases,
    Span,
    add_span_sink,
    capture_profile,
    current_span,
    current_trace_id,
    install_profile_from_env,
    install_profile_signal,
    load_capture_manifest,
    parse_trace_header,
    profile_to,
    remove_span_sink,
    set_memory_hook,
    trace,
    trace_context,
    trace_headers,
    trace_to_dir,
    uninstall_profile_signal,
)
from .telemetry import (
    TelemetrySampler,
    format_memory_table,
    install_span_memory_hook,
    memory_snapshot,
    sample_once,
    start_sampler,
    stop_sampler,
)

__all__ = [
    "metrics",
    "introspect",
    "telemetry",
    "history",
    "KernelCostReport",
    "format_cost_table",
    "maybe_publish",
    "publish_host_estimate",
    "set_introspection",
    "TelemetrySampler",
    "format_memory_table",
    "install_span_memory_hook",
    "memory_snapshot",
    "sample_once",
    "start_sampler",
    "stop_sampler",
    "append_run",
    "check_regression",
    "deflate_record",
    "expand_derived",
    "load_runs",
    "sentinel",
    "SentinelCalibrationError",
    "SentinelSuite",
    "run_calibration",
    "slim_context",
    "device_peak_macs_per_s",
    "format_roofline_table",
    "roofline_rows",
    "set_memory_hook",
    "trace_to_dir",
    "configure_logging",
    "log_event",
    "logger",
    "dump_registry",
    "to_prometheus",
    "write_metrics",
    "DispatchTracker",
    "abstract_signature",
    "tree_nbytes",
    "DEFAULT_BUCKETS",
    "METRIC_NAME_RE",
    "REGISTRY",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Phases",
    "Span",
    "current_span",
    "profile_to",
    "trace",
    # fleet observability plane
    "fleet",
    "flight",
    "Clock",
    "get_clock",
    "set_clock",
    "TRACE_HEADER",
    "trace_headers",
    "trace_context",
    "current_trace_id",
    "parse_trace_header",
    "add_span_sink",
    "remove_span_sink",
    "parse_prometheus",
    "FlightRecorder",
    "install_flight_recorder_from_env",
    "trigger_dump",
    "load_dump",
    "render_dump",
    "ReplicaScrape",
    "scrape_replica",
    "render_fleet",
    "SloObjective",
    "SloMonitor",
    "parse_slo_spec",
    # deep observability plane (progress + on-demand capture + exemplars)
    "progress",
    "ProgressTicker",
    "active_jobs",
    "render_jobs",
    "eta_bar",
    "PROFILE_DIR_ENV",
    "capture_profile",
    "load_capture_manifest",
    "install_profile_signal",
    "uninstall_profile_signal",
    "install_profile_from_env",
    "parse_exemplars",
]
