"""Observability for kubernetes-verification-tpu.

One import surface for the whole stack:

* ``REGISTRY`` / ``MetricsRegistry`` — process-global counters, gauges,
  fixed-bucket histograms (``registry``); the shared families live in
  ``metrics``.
* ``trace`` / ``Span`` / ``Phases`` — nested wall-clock spans that feed the
  registry, emit JSON event lines, and annotate device profiler traces
  (``spans``).
* ``log_event`` / ``configure_logging`` — the JSON event stream
  (``events``).
* ``DispatchTracker`` — jit-recompile detection by abstract-shape hashing
  (``jit``).
* ``dump_registry`` / ``write_metrics`` / ``to_prometheus`` — exporters
  (``export``).

``utils.observe`` re-exports the seed-era names from here for backward
compatibility.
"""
from __future__ import annotations

from . import metrics
from .events import configure_logging, log_event, logger
from .export import dump_registry, to_prometheus, write_metrics
from .jit import DispatchTracker, abstract_signature, tree_nbytes
from .registry import (
    DEFAULT_BUCKETS,
    METRIC_NAME_RE,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from .spans import Phases, Span, current_span, profile_to, trace

__all__ = [
    "metrics",
    "configure_logging",
    "log_event",
    "logger",
    "dump_registry",
    "to_prometheus",
    "write_metrics",
    "DispatchTracker",
    "abstract_signature",
    "tree_nbytes",
    "DEFAULT_BUCKETS",
    "METRIC_NAME_RE",
    "REGISTRY",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Phases",
    "Span",
    "current_span",
    "profile_to",
    "trace",
]
