"""Fleet scraping and SLO burn-rate monitoring.

``kv-tpu fleet`` points this module at every replica the load balancer
knows: each one is scraped over its replication port (``GET /healthz`` for
the JSON health document, ``GET /metrics`` for the Prometheus text the
exporter already renders), the results are rendered as one fleet table,
and an :class:`SloMonitor` turns the per-replica observations into
multi-window error-budget burn rates (``kvtpu_slo_burn_rate{objective,
window}``) — the Google-SRE-shaped signal that replaces "lag looked fine
in the bench footnote".

Objectives come from a tiny spec grammar (CLI ``--slo`` flags):

* ``availability=0.999`` — target fraction of scrapes/queries that must
  succeed; the error budget is ``1 - target``.
* ``staleness=0.995@2.0`` — target fraction of observations whose replica
  lag is within the ``@``-bound (seconds).

Burn rate over a window is ``bad_fraction / (1 - target)``: 1.0 means the
fleet is burning budget exactly at the sustainable rate, above 1 it
exhausts the budget early (the classic 5m/1h multi-window pair tells fast
burns from slow leaks).

This module deliberately does NOT import ``serve`` — the serving layer
imports ``observe`` everywhere, and the scraper only needs a URL and
stdlib HTTP.
"""
from __future__ import annotations

import collections
import http.client
import json
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple
from urllib.parse import urlsplit

from .events import get_clock
from .export import parse_prometheus
from .metrics import SLO_BURN_RATE
from .spans import trace, trace_headers

__all__ = [
    "ReplicaScrape",
    "scrape_replica",
    "render_fleet",
    "fleet_row",
    "stripe_coverage",
    "SloObjective",
    "parse_slo_spec",
    "SloMonitor",
    "DEFAULT_WINDOWS",
]

#: the classic multi-window pair: a fast window that catches sharp burns
#: and a slow one that catches leaks (seconds)
DEFAULT_WINDOWS: Tuple[float, ...] = (300.0, 3600.0)


@dataclass
class ReplicaScrape:
    """One replica's scrape result: health JSON + parsed metric samples
    (both None when the scrape failed; ``error`` says why)."""

    url: str
    ok: bool = False
    error: Optional[str] = None
    health: Optional[dict] = None
    metrics: Optional[dict] = None

    @property
    def lag_seconds(self) -> Optional[float]:
        if not self.health:
            return None
        lag = self.health.get("lag") or {}
        return lag.get("seconds")


def _get(url: str, path: str, timeout: float) -> Tuple[int, bytes]:
    parts = urlsplit(url)
    conn = http.client.HTTPConnection(
        parts.hostname, parts.port, timeout=timeout
    )
    try:
        conn.request("GET", path, headers=trace_headers())
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def scrape_replica(url: str, timeout: float = 5.0) -> ReplicaScrape:
    """Scrape one replica's ``/healthz`` + ``/metrics``; never raises —
    an unreachable replica is itself an availability observation."""
    out = ReplicaScrape(url=url)
    with trace("fleet_scrape", url=url) as span:
        try:
            status, body = _get(url, "/healthz", timeout)
            if status != 200:
                # kvtpu: ignore[error-taxonomy] raised-and-caught two lines down: a failed scrape is an availability datum, not an error path
                raise OSError(f"/healthz -> HTTP {status}")
            out.health = json.loads(body.decode("utf-8"))
            status, body = _get(url, "/metrics", timeout)
            if status != 200:
                # kvtpu: ignore[error-taxonomy] raised-and-caught below: a failed scrape is an availability datum, not an error path
                raise OSError(f"/metrics -> HTTP {status}")
            out.metrics = parse_prometheus(body.decode("utf-8"))
            out.ok = True
        except Exception as e:  # noqa: BLE001 - scrape failure is data
            out.error = f"{type(e).__name__}: {e}"
            span.attrs["error"] = out.error
    return out


def _fmt(value, digits: int = 3) -> str:
    if value is None:
        return "-"
    if isinstance(value, bool):
        return "yes" if value else "no"
    if isinstance(value, float):
        return f"{value:.{digits}f}"
    return str(value)


def _tenant_totals(samples) -> Dict[str, float]:
    """Per-tenant sums from parsed metric samples ``[(labels, value), ...]``
    (labels without a ``tenant`` key are skipped)."""
    per: Dict[str, float] = {}
    for labels, value in samples or []:
        tenant = labels.get("tenant")
        if tenant is None:
            continue
        per[tenant] = per.get(tenant, 0.0) + value
    return per


def _tenant_summary(samples, *, digits: Optional[int] = None, top: int = 2) -> str:
    """Compact per-tenant column text from parsed metric samples
    (``[(labels, value), ...]``): the ``top`` largest as ``tenant=value``,
    a ``+N`` tail for the rest, ``-`` when the family is absent."""
    per = _tenant_totals(samples)
    if not per:
        return "-"
    items = sorted(per.items(), key=lambda kv: (-kv[1], kv[0]))
    cells = [
        f"{t}={v:.{digits}f}" if digits is not None else f"{t}={int(v)}"
        for t, v in items[:top]
    ]
    if len(items) > top:
        cells.append(f"+{len(items) - top}")
    return ",".join(cells)


def _posture_summary(health: Optional[dict]) -> str:
    """Compact posture column text from a ``/healthz`` document: current
    reachable-pair count, last generation's movement (``+widened/-narrowed``)
    and a ``!N`` suffix for accumulated alert violations; ``-`` when the
    replica has no posture plane enabled."""
    p = (health or {}).get("service") or {}
    p = p.get("posture")
    if not p or p.get("reachable_pairs") is None:
        return "-"
    txt = (
        f"{p['reachable_pairs']}p "
        f"+{p.get('widened_last', 0)}/-{p.get('narrowed_last', 0)}"
    )
    violations = p.get("violations") or 0
    if violations:
        txt += f" !{violations}"
    return txt


def _human_pods(n) -> str:
    """``1250000 -> "1.25M"`` — the stripe column's pod-count rendering."""
    n = float(n)
    for div, suffix in ((1e9, "G"), (1e6, "M"), (1e3, "k")):
        if n >= div:
            txt = f"{n / div:.2f}".rstrip("0").rstrip(".")
            return f"{txt}{suffix}"
    return str(int(n))


def _stripe_summary(health: Optional[dict]) -> str:
    """Compact stripe-ownership column text from a ``/healthz`` document:
    ``3/8 · 1.25M pods`` (1-based stripe index / stripe count, owned pod
    rows); ``-`` when the replica serves whole state."""
    frag = (health or {}).get("stripe")
    if not frag or frag.get("count") is None:
        return "-"
    return (
        f"{int(frag.get('index', 0)) + 1}/{int(frag['count'])} · "
        f"{_human_pods(frag.get('pods', 0))} pods"
    )


def stripe_coverage(scrapes: Sequence[ReplicaScrape]) -> Optional[dict]:
    """Fleet-wide stripe coverage from the scrapes' ``/healthz`` stripe
    fragments: which stripe indices have at least one LIVE owner. Returns
    None when no replica reports a stripe (a whole-state fleet has no
    coverage concept). A stripe with no live owner is an outage for every
    query touching its rows — the coordinator fails it typed
    (:class:`~..resilience.errors.StripeCoverageError`), never silently
    answers from the surviving stripes — so the fleet view must shout the
    gap, not average it away. Disagreeing stripe counts across replicas
    (a mid-resharding scrape) report ``consistent: False``."""
    by_count: Dict[int, set] = {}
    for s in scrapes:
        frag = (s.health or {}).get("stripe") if s.ok else None
        if frag and frag.get("count"):
            by_count.setdefault(int(frag["count"]), set()).add(
                int(frag.get("index", 0))
            )
    if not by_count:
        return None
    if len(by_count) > 1:
        return {"consistent": False, "counts": sorted(by_count)}
    count, owned = next(iter(by_count.items()))
    missing = sorted(set(range(count)) - owned)
    return {
        "consistent": True,
        "count": count,
        "owned": sorted(owned),
        "missing": missing,
    }


def render_fleet(scrapes: Sequence[ReplicaScrape]) -> List[str]:
    """The fleet table: one aligned row per replica, down replicas
    included (their row says why). ``shed`` / ``quota`` summarise the
    front-door admission metrics per tenant (total typed rejections and
    token-bucket utilisation) so an operator sees who is being refused
    where without correlating counters by hand; ``posture`` is the
    reach-drift plane (reachable pairs, last movement, alert count)."""
    header = (
        "replica", "role", "epoch", "last_seq", "lag_s", "breaker", "aot",
        "shed", "quota", "posture", "stripe",
    )
    rows = [header]
    for s in scrapes:
        if not s.ok:
            rows.append(
                (
                    s.url, "DOWN", "-", "-", "-", s.error or "-", "-",
                    "-", "-", "-", "-",
                )
            )
            continue
        h = s.health or {}
        breakers = h.get("breakers") or {}
        btxt = (
            ",".join(f"{k}={v}" for k, v in sorted(breakers.items()))
            if breakers
            else "-"
        )
        aot = h.get("aot") or {}
        if not aot.get("present"):
            atxt = "-"
        elif aot.get("env_match") and not aot.get("corrupt"):
            atxt = f"ok/{aot.get('matching', 0)}"
        else:
            atxt = "stale"
        metrics = s.metrics or {}
        rows.append(
            (
                s.url,
                str(h.get("role", "-")),
                _fmt(h.get("epoch")),
                _fmt(h.get("last_seq")),
                _fmt(s.lag_seconds),
                btxt,
                atxt,
                _tenant_summary(
                    metrics.get("kvtpu_admission_rejections_total")
                ),
                _tenant_summary(
                    metrics.get("kvtpu_admission_quota_utilization"),
                    digits=2,
                ),
                _posture_summary(h),
                _stripe_summary(h),
            )
        )
    widths = [max(len(r[i]) for r in rows) for i in range(len(header))]
    out = [
        "  ".join(cell.ljust(widths[i]) for i, cell in enumerate(row)).rstrip()
        for row in rows
    ]
    cov = stripe_coverage(scrapes)
    if cov is not None:
        if not cov["consistent"]:
            out.append(
                "stripe coverage: INCONSISTENT stripe counts "
                f"{cov['counts']} across the fleet"
            )
        elif cov["missing"]:
            gaps = ", ".join(
                f"{i + 1}/{cov['count']}" for i in cov["missing"]
            )
            out.append(
                f"stripe coverage: GAP — stripe(s) {gaps} have no live "
                "owner (queries touching those rows fail typed, not "
                "truncated)"
            )
        else:
            out.append(
                f"stripe coverage: {cov['count']}/{cov['count']} stripes "
                "owned"
            )
    return out


def fleet_row(s: ReplicaScrape) -> dict:
    """The machine-readable mirror of one :func:`render_fleet` row —
    ``kv-tpu fleet --json`` emits these so CI consumes fleet state without
    screen-scraping the aligned table. Raw values, not column text: lag is
    a float, shed/quota are per-tenant maps, ``posture`` is the replica's
    posture health fragment (None when the plane is disabled)."""
    h = s.health or {}
    metrics = s.metrics or {}
    svc = h.get("service") or {}
    return {
        "url": s.url,
        "ok": s.ok,
        "error": s.error,
        "role": h.get("role"),
        "epoch": h.get("epoch"),
        "last_seq": h.get("last_seq"),
        "lag_s": s.lag_seconds,
        "breakers": h.get("breakers") or {},
        "aot": h.get("aot"),
        "shed": _tenant_totals(
            metrics.get("kvtpu_admission_rejections_total")
        ),
        "quota": _tenant_totals(
            metrics.get("kvtpu_admission_quota_utilization")
        ),
        "posture": svc.get("posture"),
        "stripe": h.get("stripe"),
    }


@dataclass(frozen=True)
class SloObjective:
    """One objective: ``target`` fraction of good events; ``bound`` is the
    staleness threshold (seconds) for lag-shaped objectives, None for
    plain availability."""

    name: str
    target: float
    bound: Optional[float] = None

    @property
    def budget(self) -> float:
        return 1.0 - self.target


def parse_slo_spec(spec: str) -> SloObjective:
    """``availability=0.999`` / ``staleness=0.995@2.0`` -> SloObjective.

    Raises ValueError with the offending spec on malformed input (the CLI
    surfaces it as an input error, exit code 2)."""
    name, sep, rest = spec.partition("=")
    name = name.strip()
    if not sep or not name:
        # kvtpu: ignore[error-taxonomy] documented parse contract: the CLI maps ValueError to an input error (exit 2)
        raise ValueError(f"bad SLO spec {spec!r}: want name=target[@bound]")
    target_text, at, bound_text = rest.partition("@")
    try:
        target = float(target_text)
        bound = float(bound_text) if at else None
    except ValueError:
        # kvtpu: ignore[error-taxonomy] documented parse contract: the CLI maps ValueError to an input error (exit 2)
        raise ValueError(
            f"bad SLO spec {spec!r}: target/bound must be numbers"
        ) from None
    if not 0.0 < target < 1.0:
        # kvtpu: ignore[error-taxonomy] documented parse contract: the CLI maps ValueError to an input error (exit 2)
        raise ValueError(
            f"bad SLO spec {spec!r}: target must be in (0, 1), got {target}"
        )
    return SloObjective(name=name, target=target, bound=bound)


def _window_label(seconds: float) -> str:
    if seconds % 3600 == 0:
        return f"{int(seconds // 3600)}h"
    if seconds % 60 == 0:
        return f"{int(seconds // 60)}m"
    return f"{int(seconds)}s"


@dataclass
class SloMonitor:
    """Rolling good/bad observations per objective with multi-window
    burn-rate evaluation. Timestamps come from the shared injectable clock
    (``observe.events.set_clock``) so tests drive the windows."""

    objectives: Sequence[SloObjective]
    max_observations: int = 4096
    #: seconds a known source (replica URL) stays on the books after its
    #: last observation; a source silent for longer is treated as
    #: decommissioned rather than unscrapeable
    source_ttl: float = 7200.0
    _events: Dict[str, collections.deque] = field(default_factory=dict)
    _sources: Dict[str, Dict[str, float]] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def __post_init__(self) -> None:
        for o in self.objectives:
            self._events[o.name] = collections.deque(
                maxlen=self.max_observations
            )
            self._sources[o.name] = {}

    def objective(self, name: str) -> SloObjective:
        for o in self.objectives:
            if o.name == name:
                return o
        raise KeyError(name)  # kvtpu: ignore[error-taxonomy] mapping-lookup contract on a programmer-facing accessor

    def record(
        self,
        name: str,
        ok: bool,
        ts: Optional[float] = None,
        source: Optional[str] = None,
    ) -> None:
        """One observation for ``name``: ``ok`` consumed no budget.
        ``source`` (a replica URL) registers where it came from, so a
        source that later falls silent is charged against the objective
        instead of vanishing from it; sourceless observations keep the
        pre-source semantics (no data, no violation)."""
        if ts is None:
            ts = get_clock().wall()
        with self._lock:
            self._events[name].append((ts, bool(ok)))
            if source is not None:
                self._sources[name][source] = ts

    def observe_scrape(self, scrape: ReplicaScrape) -> None:
        """Fold one replica scrape into every objective: availability-
        shaped objectives count scrape success, staleness-shaped ones
        count the reported lag against their bound (a down replica is
        bad for those too — its staleness is unbounded)."""
        for o in self.objectives:
            if o.bound is None:
                self.record(o.name, scrape.ok, source=scrape.url)
            else:
                lag = scrape.lag_seconds
                self.record(
                    o.name,
                    scrape.ok and lag is not None and lag <= o.bound,
                    source=scrape.url,
                )

    def _silent_sources(
        self, name: str, cutoff: float, now: float
    ) -> List[str]:
        """Known sources (seen within ``source_ttl``) with zero
        observations inside the window — each is one synthetic bad
        availability event: a replica nobody managed to scrape is not
        healthy, it is invisible (lock held)."""
        horizon = now - self.source_ttl
        sources = self._sources[name]
        for src in [s for s, ts in sources.items() if ts < horizon]:
            del sources[src]  # decommissioned, not unscrapeable
        return [s for s, ts in sources.items() if ts < cutoff]

    def burn_rate(
        self, name: str, window_seconds: float, now: Optional[float] = None
    ) -> float:
        """``bad_fraction / budget`` over the trailing window; 0.0 with no
        observations (no data is not a violation), ``inf`` when a
        zero-budget objective saw a bad event.

        A *known* source with zero in-window observations counts as one
        bad event (availability-shaped objectives only): before this, a
        replica that stopped answering scrapes entirely aged out of the
        window and silently contributed zero burn — the least available
        replica was the one the monitor ignored."""
        if now is None:
            now = get_clock().wall()
        o = self.objective(name)
        cutoff = now - window_seconds
        with self._lock:
            events = [e for e in self._events[name] if e[0] >= cutoff]
            silent = (
                self._silent_sources(name, cutoff, now)
                if o.bound is None
                else []
            )
        total = len(events) + len(silent)
        if not total:
            return 0.0
        bad = sum(1 for _, ok in events if not ok) + len(silent)
        bad_fraction = bad / total
        if o.budget <= 0.0:
            return float("inf") if bad else 0.0
        return bad_fraction / o.budget

    def evaluate(
        self,
        windows: Sequence[float] = DEFAULT_WINDOWS,
        now: Optional[float] = None,
    ) -> Dict[str, Dict[str, float]]:
        """Burn rates for every objective × window, published to
        ``kvtpu_slo_burn_rate{objective,window}`` and returned as
        ``{objective: {window_label: burn}}``."""
        out: Dict[str, Dict[str, float]] = {}
        for o in self.objectives:
            per: Dict[str, float] = {}
            for w in windows:
                label = _window_label(w)
                burn = self.burn_rate(o.name, w, now=now)
                SLO_BURN_RATE.labels(objective=o.name, window=label).set(burn)
                per[label] = burn
            out[o.name] = per
        return out
