"""Benchmark history: an append-only JSONL store plus a regression gate.

``bench.py`` appends every result line (headline metric + compile/steady
split + cost reports) to ``bench_history.jsonl``; the gate compares the
newest value per metric series against the trailing median of the previous
runs and flags a configurable relative slip. Two on-disk shapes are
understood, so the gate also runs directly over the repo's recorded
``BENCH_r0*.json`` trajectory:

* one JSON object per line with ``metric``/``value``/``unit`` keys (what
  ``append_run`` writes);
* a whole-file JSON wrapper with a ``parsed`` sub-object carrying those
  keys (the driver snapshots in ``BENCH_r0*.json``).

Regression direction comes from the unit: throughput units are
higher-is-better, latency units lower-is-better, anything unrecognised is
reported but never gated (a delta-percent series has no universal "worse"
direction). A few metric NAMES carry an explicit direction regardless of
unit string (``closure_pairs_per_second`` and
``aggregate_queries_per_second`` gate higher-is-better — the ``bench.py
--mode closure`` / ``--mode replicate`` throughput series;
``replica_lag_seconds`` gates lower-is-better). Rate-shaped series are
recognised
structurally as a fallback — a ``*_per_second`` metric name or a
``.../s`` unit gates higher-is-better (so the ``queries_per_second``
series from BENCH rounds is gated even where its unit string predates the
list above). Further structural suffix rules (the perf-sentinel layer,
``observe/sentinel.py``):

* ``*_deflated`` inherits the direction of the base series it was derived
  from (strip the suffix, infer again) — the dispatch-deflated twin of a
  throughput gates higher, of a latency lower;
* ``compile_s`` (bare or as a ``... compile_s`` derived-series suffix)
  gates lower-is-better — the 14.3s→59.8s compile-time walk slipped
  through precisely because no series watched it;
* ``pct_of_peak`` / ``*_pct_of_peak`` gates higher-is-better (roofline
  utilisation);
* the sentinel *context* series (``sentinel_dispatch_s``,
  ``sentinel_spread_pct``) are explicitly UNGATED: they measure the
  environment's noise, and gating them would re-admit exactly the noise
  the deflated series exist to remove. The per-kernel ``sentinel_<k>_s``
  series DO gate (lower-is-better by unit): a calibrated compute-bound
  kernel slowing down is a real toolchain/code signal, not tunnel noise.

Dispatch-deflated twins: every record whose calibration block
(``sentinel.dispatch_s``, attached by ``bench.py``) and timing shape allow
it grows a ``<metric>_deflated`` sibling series via :func:`deflate_record`
— the measured per-dispatch overhead is removed from the steady figure, so
the twin tracks device compute while the raw series keeps tracking what a
user experiences. ``expand_derived`` materialises those twins (plus the
``... compile_s`` series) and ``check_regression(prefer_deflated=True)``
gates the twin INSTEAD of the raw series wherever the twin has enough
history — raw stays visible as an ungated context row.
"""
from __future__ import annotations

import glob
import json
import os
import time
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "DEFAULT_HISTORY",
    "DEFLATED_SUFFIX",
    "append_run",
    "load_runs",
    "deflate_record",
    "expand_derived",
    "check_regression",
    "format_findings",
]

DEFAULT_HISTORY = "bench_history.jsonl"

#: unit -> gate direction; anything else is "unknown" and not gated
_HIGHER_IS_BETTER = frozenset(
    {
        "pairs/s",
        "pairs_per_second",
        "ops/s",
        "qps",
        "queries/s",
        "queries_per_second",
        "events/s",
        "events_per_second",
    }
)
_LOWER_IS_BETTER = frozenset({"s", "ms", "us", "seconds", "bytes"})

#: metric name -> explicit direction, consulted before the unit sets; the
#: closure and replicate throughput series must gate higher-is-better even
#: if a future emitter changes its unit string
_HIGHER_IS_BETTER_METRICS = frozenset(
    {"closure_pairs_per_second", "aggregate_queries_per_second"}
)
#: and the replica-lag series gates lower-is-better by NAME — a follower
#: falling further behind the leader is a regression whatever the unit;
#: the failover SLO series (promotion/resume to first answered batch)
#: gate the same way: the whole point of the warm pack is keeping them low
_LOWER_IS_BETTER_METRICS = frozenset(
    {
        "replica_lag_seconds",
        "replica_lag_spread_seconds",
        "promote_to_first_answer_s",
        "resume_to_first_answer_s",
        # the observability tax: aggregate QPS lost to a 1 Hz /metrics
        # poller during the networked replicate window — the scrape
        # surface must stay effectively free (<2%), and growth here is a
        # regression in the serving path, not the environment
        "net_scrape_overhead_pct",
        # the posture plane's tax on the serving apply path: the exact
        # per-batch reach delta must stay under 5% of apply (bench.py
        # --mode posture asserts the budget inline as well)
        "posture_overhead_pct",
    }
)
#: sentinel context series: the round's NOISE measurements. Never gated —
#: a slower tunnel or a noisier host is environment, not regression; the
#: deflated series exist so these numbers stop leaking into verdicts.
_UNGATED_METRICS = frozenset(
    {"sentinel_dispatch_s", "sentinel_spread_pct"}
)

#: suffix of the dispatch-deflated twin series ``deflate_record`` derives
DEFLATED_SUFFIX = "_deflated"
#: suffixes of the derived compile-time series (``"<metric> compile_s"``;
#: the AOT warm-start split emits cold/warm twins of the same shape —
#: ``compile_warm_s`` is the one the pack must keep near zero)
_COMPILE_SUFFIX = "compile_s"
_COMPILE_FIELDS = ("compile_s", "compile_cold_s", "compile_warm_s")

#: latency units deflation understands, as seconds-per-unit
_SECONDS_PER_UNIT = {"s": 1.0, "seconds": 1.0, "ms": 1e-3, "us": 1e-6}


def append_run(record: dict, path: str = DEFAULT_HISTORY) -> dict:
    """Append one result record (must carry ``metric`` and ``value``) to the
    history file, stamping ``ts`` when absent. Returns the stored record."""
    rec = dict(record)
    rec.setdefault("ts", round(time.time(), 3))
    with open(path, "a") as fh:  # kvtpu: ignore[atomic-write] JSONL append; the gate reader skips undecodable torn lines
        fh.write(json.dumps(rec, sort_keys=True) + "\n")
    return rec


def _entry(obj, origin: str) -> Optional[dict]:
    """Normalise one decoded JSON object to a gate entry, unwrapping the
    driver's ``{"n": .., "parsed": {...}}`` snapshot shape."""
    if not isinstance(obj, dict):
        return None
    if "metric" not in obj and isinstance(obj.get("parsed"), dict):
        inner = dict(obj["parsed"])
        inner.setdefault("round", obj.get("n"))
        obj = inner
    if "metric" not in obj or "value" not in obj:
        return None
    try:
        value = float(obj["value"])
    except (TypeError, ValueError):
        return None
    out = dict(obj)
    out["value"] = value
    out["origin"] = origin
    return out


def load_runs(paths: Iterable[str]) -> List[dict]:
    """Parse history entries from JSONL and/or whole-file JSON paths, in
    the given order (order defines "newest" within a series). Unreadable
    files and unparseable lines are skipped — the gate reports on whatever
    survives."""
    runs: List[dict] = []
    for path in paths:
        try:
            with open(path) as fh:
                text = fh.read().strip()
        except OSError:
            continue
        if not text:
            continue
        objs = []
        try:
            objs = [json.loads(text)]  # whole-file JSON (BENCH_r0*.json)
        except ValueError:
            for line in text.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    objs.append(json.loads(line))
                except ValueError:
                    continue
        for obj in objs:
            e = _entry(obj, path)
            if e is not None:
                runs.append(e)
    return runs


def default_paths(root: str = ".") -> List[str]:
    """The history file when present, else the committed BENCH_r*.json
    trajectory snapshots."""
    hist = os.path.join(root, DEFAULT_HISTORY)
    if os.path.exists(hist):
        return [hist]
    return sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))


def _direction(unit: Optional[str], metric: Optional[str] = None) -> str:
    # the sentinel context series are never gated: they ARE the noise
    # measurement the deflated series subtract out
    if metric in _UNGATED_METRICS:
        return "unknown"
    if metric in _HIGHER_IS_BETTER_METRICS:
        return "higher"
    if metric in _LOWER_IS_BETTER_METRICS:
        return "lower"
    if unit in _HIGHER_IS_BETTER:
        return "higher"
    if unit in _LOWER_IS_BETTER:
        return "lower"
    # rate-shaped series gate higher-is-better even under a novel unit
    # string: a ``*_per_second`` metric name or a ``.../s`` unit is a
    # throughput by construction (the queries_per_second series from BENCH
    # rounds predates its unit being listed above)
    if metric is not None and metric.endswith("_per_second"):
        return "higher"
    if unit is not None and unit.endswith("/s"):
        return "higher"
    # structural suffix rules (perf-sentinel layer):
    if metric is not None:
        # the dispatch-deflated twin inherits its base series' direction
        if metric.endswith(DEFLATED_SUFFIX):
            return _direction(unit, metric[: -len(DEFLATED_SUFFIX)])
        # compile time gates lower-is-better whether emitted bare or as
        # a derived "<metric> compile[_cold|_warm]_s" series
        if metric in _COMPILE_FIELDS or any(
            metric.endswith(" " + f) for f in _COMPILE_FIELDS
        ):
            return "lower"
        # roofline utilisation gates higher-is-better
        if metric == "pct_of_peak" or metric.endswith("_pct_of_peak"):
            return "higher"
        # byte counters (h2d traffic, transfer volumes) gate
        # lower-is-better: growth means a residency or caching regression
        if metric.endswith("_bytes"):
            return "lower"
    return "unknown"


def _sentinel_dispatch_s(rec: dict) -> Optional[float]:
    """The per-dispatch overhead from a record's calibration block, when
    present and usable."""
    sentinel = rec.get("sentinel")
    if not isinstance(sentinel, dict):
        return None
    try:
        dispatch_s = float(sentinel["dispatch_s"])
    except (KeyError, TypeError, ValueError):
        return None
    if dispatch_s <= 0.0:
        return None
    return dispatch_s


def deflate_record(rec: dict) -> Optional[dict]:
    """Derive the dispatch-deflated twin of one history record, or ``None``
    when the record carries no usable calibration block or its shape does
    not support deflation.

    Throughput records (direction "higher") additionally need a numeric
    ``steady_s``: the model is wall = compute + dispatch, so the deflated
    throughput is ``value * steady_s / (steady_s - dispatch_s)``. Latency
    records in a seconds-family unit subtract the dispatch overhead
    directly. Both clamp the compute term to 10% of the measured figure
    (flagged ``deflation_clamped``) so a probe misread can never produce a
    negative or absurd twin.
    """
    dispatch_s = _sentinel_dispatch_s(rec)
    if dispatch_s is None:
        return None
    metric = rec.get("metric")
    if not isinstance(metric, str) or metric.endswith(DEFLATED_SUFFIX):
        return None
    unit = rec.get("unit")
    direction = _direction(unit, metric)
    try:
        value = float(rec["value"])
    except (KeyError, TypeError, ValueError):
        return None
    twin = {
        "metric": metric + DEFLATED_SUFFIX,
        "unit": unit,
        "derived_from": metric,
        "dispatch_s": dispatch_s,
        "deflation_clamped": False,
    }
    for key in ("ts", "round", "mode", "origin"):
        if key in rec:
            twin[key] = rec[key]
    if direction == "higher":
        try:
            steady_s = float(rec["steady_s"])
        except (KeyError, TypeError, ValueError):
            return None
        if steady_s <= 0.0:
            return None
        compute_s = steady_s - dispatch_s
        floor = 0.1 * steady_s
        if compute_s < floor:
            compute_s = floor
            twin["deflation_clamped"] = True
        twin["value"] = value * steady_s / compute_s
        return twin
    if direction == "lower" and unit in _SECONDS_PER_UNIT:
        scale = _SECONDS_PER_UNIT[unit]
        value_s = value * scale
        compute_s = value_s - dispatch_s
        floor = 0.1 * value_s
        if compute_s < floor:
            compute_s = floor
            twin["deflation_clamped"] = True
        twin["value"] = compute_s / scale
        return twin
    return None


def expand_derived(runs: List[dict], deflate: bool = True) -> List[dict]:
    """Materialise the derived series alongside their sources, preserving
    within-series order:

    * a ``"<metric> compile_s"`` series (unit "s") from every record with
      a numeric ``compile_s`` field — so compile-time walks gate
      lower-is-better per headline series — and the same for the AOT
      split's ``compile_cold_s`` / ``compile_warm_s`` fields (the warm
      series is how a silent cold-start walk would resurface);
    * the ``<metric>_deflated`` twin (:func:`deflate_record`) from every
      record carrying a usable sentinel calibration block.
    """
    out: List[dict] = []
    for rec in runs:
        out.append(rec)
        metric = rec.get("metric")
        for field in _COMPILE_FIELDS:
            v = rec.get(field)
            if isinstance(metric, str) and isinstance(v, (int, float)):
                derived = {
                    "metric": f"{metric} {field}",
                    "unit": "s",
                    "value": float(v),
                    "derived_from": metric,
                }
                for key in ("ts", "round", "mode", "origin"):
                    if key in rec:
                        derived[key] = rec[key]
                out.append(derived)
        if deflate:
            twin = deflate_record(rec)
            if twin is not None:
                out.append(twin)
    return out


def check_regression(
    runs: List[dict],
    tolerance: float = 0.25,
    window: int = 5,
    prefer_deflated: bool = False,
) -> Tuple[bool, List[dict]]:
    """Group runs by (metric, unit) series; within each series with ≥ 2
    entries, compare the newest value against the median of up to
    ``window`` preceding runs. A drop (throughput) or rise (latency) beyond
    ``tolerance`` (relative) regresses. Returns (ok, findings).

    With ``prefer_deflated=True``, any raw series whose
    ``<metric>_deflated`` twin also has ≥ 2 entries is demoted to an
    ungated context row (``gated_via`` names the twin): the twin carries
    the verdict, the raw headline stays visible."""
    series: Dict[Tuple[str, Optional[str]], List[dict]] = {}
    for r in runs:
        series.setdefault((r["metric"], r.get("unit")), []).append(r)
    findings: List[dict] = []
    for (metric, unit), rs in sorted(series.items()):
        if len(rs) < 2:
            continue
        newest = rs[-1]
        prev = rs[:-1][-window:]
        vals = sorted(r["value"] for r in prev)
        median = vals[len(vals) // 2]
        direction = _direction(unit, metric)
        finding = {
            "metric": metric,
            "unit": unit,
            "direction": direction,
            "newest": newest["value"],
            "trailing_median": median,
            "n_previous": len(prev),
            "regressed": False,
        }
        gated_via = None
        if prefer_deflated and not metric.endswith(DEFLATED_SUFFIX):
            twin = metric + DEFLATED_SUFFIX
            if len(series.get((twin, unit), [])) >= 2:
                gated_via = twin
                finding["gated_via"] = twin
        if median > 0 and direction != "unknown":
            ratio = newest["value"] / median
            finding["ratio"] = round(ratio, 4)
            if gated_via is None:
                if direction == "higher":
                    finding["regressed"] = ratio < 1.0 - tolerance
                else:
                    finding["regressed"] = ratio > 1.0 + tolerance
        findings.append(finding)
    ok = not any(f["regressed"] for f in findings)
    return ok, findings


def format_findings(findings: List[dict]) -> str:
    if not findings:
        return "no metric series with >= 2 runs; nothing to gate"
    lines = []
    for f in findings:
        ratio = f.get("ratio")
        if f["regressed"]:
            verdict = "REGRESSED"
        elif f.get("gated_via"):
            verdict = "context"  # verdict carried by the deflated twin
        elif f["direction"] != "unknown":
            verdict = "ok"
        else:
            verdict = "ungated"
        lines.append(
            f"[{verdict:>9}] {f['metric']} ({f['unit']}, {f['direction']}"
            f"-is-better): newest={f['newest']:.6g} vs median({f['n_previous']}"
            f" prev)={f['trailing_median']:.6g}"
            + (f" ratio={ratio:.3f}" if ratio is not None else "")
        )
    return "\n".join(lines)
