"""Benchmark history: an append-only JSONL store plus a regression gate.

``bench.py`` appends every result line (headline metric + compile/steady
split + cost reports) to ``bench_history.jsonl``; the gate compares the
newest value per metric series against the trailing median of the previous
runs and flags a configurable relative slip. Two on-disk shapes are
understood, so the gate also runs directly over the repo's recorded
``BENCH_r0*.json`` trajectory:

* one JSON object per line with ``metric``/``value``/``unit`` keys (what
  ``append_run`` writes);
* a whole-file JSON wrapper with a ``parsed`` sub-object carrying those
  keys (the driver snapshots in ``BENCH_r0*.json``).

Regression direction comes from the unit: throughput units are
higher-is-better, latency units lower-is-better, anything unrecognised is
reported but never gated (a delta-percent series has no universal "worse"
direction). A few metric NAMES carry an explicit direction regardless of
unit string (``closure_pairs_per_second`` and
``aggregate_queries_per_second`` gate higher-is-better — the ``bench.py
--mode closure`` / ``--mode replicate`` throughput series;
``replica_lag_seconds`` gates lower-is-better). Rate-shaped series are
recognised
structurally as a fallback — a ``*_per_second`` metric name or a
``.../s`` unit gates higher-is-better (so the ``queries_per_second``
series from BENCH rounds is gated even where its unit string predates the
list above).
"""
from __future__ import annotations

import glob
import json
import os
import time
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "DEFAULT_HISTORY",
    "append_run",
    "load_runs",
    "check_regression",
    "format_findings",
]

DEFAULT_HISTORY = "bench_history.jsonl"

#: unit -> gate direction; anything else is "unknown" and not gated
_HIGHER_IS_BETTER = frozenset(
    {
        "pairs/s",
        "pairs_per_second",
        "ops/s",
        "qps",
        "queries/s",
        "queries_per_second",
        "events/s",
        "events_per_second",
    }
)
_LOWER_IS_BETTER = frozenset({"s", "ms", "us", "seconds", "bytes"})

#: metric name -> explicit direction, consulted before the unit sets; the
#: closure and replicate throughput series must gate higher-is-better even
#: if a future emitter changes its unit string
_HIGHER_IS_BETTER_METRICS = frozenset(
    {"closure_pairs_per_second", "aggregate_queries_per_second"}
)
#: and the replica-lag series gates lower-is-better by NAME — a follower
#: falling further behind the leader is a regression whatever the unit
_LOWER_IS_BETTER_METRICS = frozenset(
    {"replica_lag_seconds", "replica_lag_spread_seconds"}
)


def append_run(record: dict, path: str = DEFAULT_HISTORY) -> dict:
    """Append one result record (must carry ``metric`` and ``value``) to the
    history file, stamping ``ts`` when absent. Returns the stored record."""
    rec = dict(record)
    rec.setdefault("ts", round(time.time(), 3))
    with open(path, "a") as fh:  # kvtpu: ignore[atomic-write] JSONL append; the gate reader skips undecodable torn lines
        fh.write(json.dumps(rec, sort_keys=True) + "\n")
    return rec


def _entry(obj, origin: str) -> Optional[dict]:
    """Normalise one decoded JSON object to a gate entry, unwrapping the
    driver's ``{"n": .., "parsed": {...}}`` snapshot shape."""
    if not isinstance(obj, dict):
        return None
    if "metric" not in obj and isinstance(obj.get("parsed"), dict):
        inner = dict(obj["parsed"])
        inner.setdefault("round", obj.get("n"))
        obj = inner
    if "metric" not in obj or "value" not in obj:
        return None
    try:
        value = float(obj["value"])
    except (TypeError, ValueError):
        return None
    out = dict(obj)
    out["value"] = value
    out["origin"] = origin
    return out


def load_runs(paths: Iterable[str]) -> List[dict]:
    """Parse history entries from JSONL and/or whole-file JSON paths, in
    the given order (order defines "newest" within a series). Unreadable
    files and unparseable lines are skipped — the gate reports on whatever
    survives."""
    runs: List[dict] = []
    for path in paths:
        try:
            with open(path) as fh:
                text = fh.read().strip()
        except OSError:
            continue
        if not text:
            continue
        objs = []
        try:
            objs = [json.loads(text)]  # whole-file JSON (BENCH_r0*.json)
        except ValueError:
            for line in text.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    objs.append(json.loads(line))
                except ValueError:
                    continue
        for obj in objs:
            e = _entry(obj, path)
            if e is not None:
                runs.append(e)
    return runs


def default_paths(root: str = ".") -> List[str]:
    """The history file when present, else the committed BENCH_r*.json
    trajectory snapshots."""
    hist = os.path.join(root, DEFAULT_HISTORY)
    if os.path.exists(hist):
        return [hist]
    return sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))


def _direction(unit: Optional[str], metric: Optional[str] = None) -> str:
    if metric in _HIGHER_IS_BETTER_METRICS:
        return "higher"
    if metric in _LOWER_IS_BETTER_METRICS:
        return "lower"
    if unit in _HIGHER_IS_BETTER:
        return "higher"
    if unit in _LOWER_IS_BETTER:
        return "lower"
    # rate-shaped series gate higher-is-better even under a novel unit
    # string: a ``*_per_second`` metric name or a ``.../s`` unit is a
    # throughput by construction (the queries_per_second series from BENCH
    # rounds predates its unit being listed above)
    if metric is not None and metric.endswith("_per_second"):
        return "higher"
    if unit is not None and unit.endswith("/s"):
        return "higher"
    return "unknown"


def check_regression(
    runs: List[dict], tolerance: float = 0.25, window: int = 5
) -> Tuple[bool, List[dict]]:
    """Group runs by (metric, unit) series; within each series with ≥ 2
    entries, compare the newest value against the median of up to
    ``window`` preceding runs. A drop (throughput) or rise (latency) beyond
    ``tolerance`` (relative) regresses. Returns (ok, findings)."""
    series: Dict[Tuple[str, Optional[str]], List[dict]] = {}
    for r in runs:
        series.setdefault((r["metric"], r.get("unit")), []).append(r)
    findings: List[dict] = []
    for (metric, unit), rs in sorted(series.items()):
        if len(rs) < 2:
            continue
        newest = rs[-1]
        prev = rs[:-1][-window:]
        vals = sorted(r["value"] for r in prev)
        median = vals[len(vals) // 2]
        direction = _direction(unit, metric)
        finding = {
            "metric": metric,
            "unit": unit,
            "direction": direction,
            "newest": newest["value"],
            "trailing_median": median,
            "n_previous": len(prev),
            "regressed": False,
        }
        if median > 0 and direction != "unknown":
            ratio = newest["value"] / median
            finding["ratio"] = round(ratio, 4)
            if direction == "higher":
                finding["regressed"] = ratio < 1.0 - tolerance
            else:
                finding["regressed"] = ratio > 1.0 + tolerance
        findings.append(finding)
    ok = not any(f["regressed"] for f in findings)
    return ok, findings


def format_findings(findings: List[dict]) -> str:
    if not findings:
        return "no metric series with >= 2 runs; nothing to gate"
    lines = []
    for f in findings:
        ratio = f.get("ratio")
        verdict = "REGRESSED" if f["regressed"] else (
            "ok" if f["direction"] != "unknown" else "ungated"
        )
        lines.append(
            f"[{verdict:>9}] {f['metric']} ({f['unit']}, {f['direction']}"
            f"-is-better): newest={f['newest']:.6g} vs median({f['n_previous']}"
            f" prev)={f['trailing_median']:.6g}"
            + (f" ratio={ratio:.3f}" if ratio is not None else "")
        )
    return "\n".join(lines)
