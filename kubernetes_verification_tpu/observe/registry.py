"""The metrics core: a process-global registry of counters, gauges and
fixed-bucket histograms.

Design rules (enforced here, linted by ``scripts/check_metrics_names.py``):

* every metric name matches ``^kvtpu_[a-z0-9_]+$`` so the Prometheus text
  exposition stays stable across exporters;
* metric *families* are registered at module import time (one line at the
  top of the owning module), children (label combinations) materialise on
  first use — so a registry dump always names every instrument the build
  carries, even ones a particular run never touched;
* everything is plain stdlib (no jax, no numpy): the registry must be
  importable from CPU-only contexts (docs builds, the pure-NumPy oracle).

All mutation goes through a per-registry lock — the packed engines dispatch
from worker threads in serving setups, and a torn histogram bucket is the
kind of bug no differential test catches.
"""
from __future__ import annotations

import re
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "METRIC_NAME_RE",
    "DEFAULT_BUCKETS",
    "EXEMPLAR_WINDOW_SECONDS",
    "MetricsRegistry",
    "REGISTRY",
    "Counter",
    "Gauge",
    "Histogram",
    "set_exemplar_provider",
    "set_exemplar_counter",
]

METRIC_NAME_RE = re.compile(r"^kvtpu_[a-z0-9_]+$")
_LABEL_NAME_RE = re.compile(r"^[a-z_][a-z0-9_]*$")

#: Latency-shaped buckets (seconds): sub-ms dispatches through the ~5-minute
#: flagship full sweeps. Fixed at family construction — exporters rely on
#: bucket stability across a process's lifetime.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)


#: Exemplar retention window (seconds): within it a bucket keeps the
#: trace_id of its *slowest* observation; once the retained exemplar ages
#: past the window any newer observation replaces it, so a scrape always
#: joins to a recent trace instead of an hours-old outlier.
EXEMPLAR_WINDOW_SECONDS = 300.0

#: () -> Optional[str] returning the active trace_id, installed by
#: ``observe.spans`` — the registry stays stdlib-only and import-cycle-free
#: (spans imports metrics imports this module) by receiving the provider
#: instead of importing it.
_exemplar_provider = None

#: .inc()-able counter (``kvtpu_trace_exemplars_total``), installed by
#: ``observe.metrics`` for the same cycle reason.
_exemplar_counter = None


def set_exemplar_provider(provider) -> None:
    """Install (or clear, with None) the trace-id source histograms consult
    when retaining bucket exemplars."""
    global _exemplar_provider
    _exemplar_provider = provider  # kvtpu: ignore[concurrency-hygiene] single atomic reference rebind; observers tolerate either value


def set_exemplar_counter(counter) -> None:
    """Install the counter bumped whenever a bucket exemplar is retained."""
    global _exemplar_counter
    _exemplar_counter = counter  # kvtpu: ignore[concurrency-hygiene] single atomic reference rebind; observers tolerate either value


def _label_key(labelnames: Sequence[str], labels: Dict[str, str]) -> str:
    """Canonical child key: ``k1=v1,k2=v2`` in declared label order (the
    JSON-dump form; the Prometheus exporter quotes/escapes on top)."""
    return ",".join(f"{k}={labels[k]}" for k in labelnames)


class _Child:
    """One (metric family, label combination) instrument."""

    __slots__ = ("_lock",)

    def __init__(self, lock: threading.RLock) -> None:
        self._lock = lock


class _CounterChild(_Child):
    __slots__ = ("_value",)

    def __init__(self, lock) -> None:
        super().__init__(lock)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class _GaugeChild(_Child):
    __slots__ = ("_value",)

    def __init__(self, lock) -> None:
        super().__init__(lock)
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value


class _HistogramChild(_Child):
    __slots__ = ("_uppers", "_counts", "_sum", "_count", "_last", "_exemplars")

    def __init__(self, lock, uppers: Tuple[float, ...]) -> None:
        super().__init__(lock)
        self._uppers = uppers  # ascending, +inf last
        self._counts = [0] * len(uppers)
        self._sum = 0.0
        self._count = 0
        self._last: Optional[float] = None
        # per-bucket (value, trace_id, wall_ts) of the slowest observation
        # inside the retention window, None where no traced observation
        # landed yet — aligned with _uppers
        self._exemplars: List[Optional[Tuple[float, str, float]]] = (
            [None] * len(uppers)
        )

    def observe(self, value: float) -> None:
        value = float(value)
        trace_id = None
        provider = _exemplar_provider
        if provider is not None:
            try:
                trace_id = provider()
            except Exception:  # the exemplar tap must never fail an observe
                trace_id = None
        retained = False
        with self._lock:
            idx = None
            for i, ub in enumerate(self._uppers):
                if value <= ub:
                    self._counts[i] += 1
                    idx = i
                    break
            self._sum += value
            self._count += 1
            self._last = value
            if trace_id is not None and idx is not None:
                ex = self._exemplars[idx]
                now = time.time()
                if (
                    ex is None
                    or value >= ex[0]
                    or now - ex[2] > EXEMPLAR_WINDOW_SECONDS
                ):
                    self._exemplars[idx] = (value, trace_id, now)
                    retained = True
        if retained and _exemplar_counter is not None:
            _exemplar_counter.inc()

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def last(self) -> Optional[float]:
        """Most recent observation — the "what did the last run measure"
        view the registry dump's ``spans`` section surfaces."""
        return self._last

    def cumulative_buckets(self) -> List[Tuple[float, int]]:
        out = []
        acc = 0
        for ub, c in zip(self._uppers, self._counts):
            acc += c
            out.append((ub, acc))
        return out

    def exemplars(self) -> List[Optional[Tuple[float, str, float]]]:
        """Per-bucket retained (value, trace_id, wall_ts), aligned with the
        bucket upper bounds; None where no traced observation landed."""
        with self._lock:
            return list(self._exemplars)


class _Metric:
    """A metric family: name + help + label schema; children per label set."""

    kind = "untyped"
    _child_cls = _CounterChild

    def __init__(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        *,
        registry: Optional["MetricsRegistry"] = None,
    ) -> None:
        if not METRIC_NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} must match {METRIC_NAME_RE.pattern}"
            )
        for ln in labelnames:
            if not _LABEL_NAME_RE.match(ln):
                raise ValueError(f"bad label name {ln!r} on {name}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.RLock()
        self._children: Dict[str, _Child] = {}
        if not self.labelnames:
            # unlabeled: the default child exists from birth so the family
            # shows a value (0) in every dump, used or not
            self._children[""] = self._new_child()
        reg = REGISTRY if registry is None else registry
        reg.register(self)

    def _new_child(self) -> _Child:
        return self._child_cls(self._lock)

    def labels(self, **labels: str):
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name} takes labels {self.labelnames}, got "
                f"{tuple(sorted(labels))}"
            )
        key = _label_key(self.labelnames, {k: str(v) for k, v in labels.items()})
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._new_child()
                self._children[key] = child
        return child

    def _default(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}; use .labels()"
            )
        return self._children[""]

    def children(self) -> Dict[str, _Child]:
        with self._lock:
            return dict(self._children)

    def reset(self) -> None:
        """Drop all children (recreating the default one when unlabeled)."""
        with self._lock:
            self._children.clear()
            if not self.labelnames:
                self._children[""] = self._new_child()


class Counter(_Metric):
    kind = "counter"
    _child_cls = _CounterChild

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    @property
    def value(self) -> float:
        return self._default().value


class Gauge(_Metric):
    kind = "gauge"
    _child_cls = _GaugeChild

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    @property
    def value(self) -> float:
        return self._default().value


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        *,
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        registry: Optional["MetricsRegistry"] = None,
    ) -> None:
        ubs = tuple(sorted(float(b) for b in buckets))
        if not ubs:
            raise ValueError("histogram needs at least one bucket bound")
        if ubs[-1] != float("inf"):
            ubs = ubs + (float("inf"),)
        self.buckets = ubs
        super().__init__(name, help, labelnames, registry=registry)

    def _new_child(self) -> _HistogramChild:
        return _HistogramChild(self._lock, self.buckets)

    def observe(self, value: float) -> None:
        self._default().observe(value)


class MetricsRegistry:
    """Holds metric families; one process-global instance (``REGISTRY``)
    plus throwaway instances for tests."""

    #: the span histogram the dump's ``spans`` convenience section reads
    SPAN_METRIC = "kvtpu_span_seconds"

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}

    def register(self, metric: _Metric) -> None:
        with self._lock:
            if metric.name in self._metrics:
                raise ValueError(f"metric {metric.name!r} already registered")
            self._metrics[metric.name] = metric

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def get(self, name: str) -> _Metric:
        return self._metrics[name]

    def collect(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def reset(self) -> None:
        """Zero every family (drop all labeled children). Families stay
        registered — only observations are discarded."""
        for m in self.collect():
            m.reset()

    # ------------------------------------------------------------ dumping
    def dump(self, include_buckets: bool = True) -> dict:
        """JSON-ready snapshot: every registered family, grouped by kind,
        plus a ``spans`` section derived from the span histogram (per-name
        count / total / last seconds — the "where did the solve go" view).
        """
        counters: Dict[str, dict] = {}
        gauges: Dict[str, dict] = {}
        histograms: Dict[str, dict] = {}
        for m in self.collect():
            if m.kind == "counter":
                counters[m.name] = {
                    k: c.value for k, c in m.children().items()
                }
            elif m.kind == "gauge":
                gauges[m.name] = {k: c.value for k, c in m.children().items()}
            elif m.kind == "histogram":
                fam = {}
                for k, c in m.children().items():
                    entry = {
                        "count": c.count,
                        "sum": round(c.sum, 9),
                        "last": None if c.last is None else round(c.last, 9),
                    }
                    if include_buckets:
                        entry["buckets"] = {
                            _format_le(ub): n
                            for ub, n in c.cumulative_buckets()
                        }
                    fam[k] = entry
                histograms[m.name] = fam
        out = {
            "counters": counters,
            "gauges": gauges,
            "histograms": histograms,
        }
        spans = {}
        span_fam = histograms.get(self.SPAN_METRIC, {})
        for key, entry in span_fam.items():
            # key is `name=<span name>` (single label)
            name = key.partition("=")[2] or key
            spans[name] = {
                "count": entry["count"],
                "total_seconds": entry["sum"],
                "last_seconds": entry["last"],
            }
        out["spans"] = spans
        return out


def _format_le(ub: float) -> str:
    if ub == float("inf"):
        return "+Inf"
    return repr(ub) if ub != int(ub) else str(int(ub)) + ".0"


#: The process-global registry every module-level metric family joins.
REGISTRY = MetricsRegistry()
