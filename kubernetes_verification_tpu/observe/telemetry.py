"""Live device-memory telemetry.

Samples ``device.memory_stats()`` for every visible accelerator into the
``kvtpu_hbm_bytes_in_use`` / ``kvtpu_hbm_peak_bytes`` gauges. Platforms
that expose no allocator stats (the CPU backend of jax, or a process that
never imported jax at all) degrade to one ``device=host`` sample backed by
process RSS — current from ``/proc/self/statm``, peak from
``getrusage(RUSAGE_SELF)`` — so the memory column of ``kv-tpu explain``
never comes back empty.

Like ``spans``, this module never *imports* jax itself: it looks the module
up in ``sys.modules`` so pure-host paths stay jax-free. Two consumers:

* ``TelemetrySampler`` — a daemon thread sampling at a fixed interval for
  long solves (start with ``start_sampler()``);
* ``install_span_memory_hook()`` — after this, every span records
  ``mem_enter_bytes`` / ``mem_exit_bytes`` in its event line, turning the
  span stream into a coarse per-phase memory profile.
"""
from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional

from .metrics import HBM_BYTES_IN_USE, HBM_PEAK_BYTES

__all__ = [
    "memory_snapshot",
    "sample_once",
    "total_bytes_in_use",
    "TelemetrySampler",
    "start_sampler",
    "stop_sampler",
    "install_span_memory_hook",
    "format_memory_table",
]


def _host_memory() -> Dict[str, int]:
    """(current, peak) RSS of this process, best effort."""
    cur = peak = 0
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        peak = int(ru) * (1 if sys.platform == "darwin" else 1024)
    except Exception:  # pragma: no cover - resource is POSIX-only
        pass
    try:
        with open("/proc/self/statm") as fh:
            cur = int(fh.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except Exception:  # pragma: no cover - non-Linux
        cur = peak
    return {"bytes_in_use": cur, "peak_bytes_in_use": max(peak, cur)}


def memory_snapshot() -> List[dict]:
    """One entry per device with allocator stats; falls back to a single
    ``device=host`` RSS entry when no device reports any (CPU platform) or
    jax was never imported."""
    out: List[dict] = []
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            devices = list(jax.devices())
        except Exception:
            devices = []
        for d in devices:
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            in_use = int(stats.get("bytes_in_use", 0))
            out.append(
                {
                    "device": str(d),
                    "platform": getattr(d, "platform", "unknown"),
                    "bytes_in_use": in_use,
                    "peak_bytes_in_use": int(
                        stats.get("peak_bytes_in_use", in_use)
                    ),
                    "limit_bytes": int(stats.get("bytes_limit", 0)),
                    "source": "device",
                }
            )
    if not out:
        host = _host_memory()
        out.append(
            {
                "device": "host",
                "platform": "host",
                "bytes_in_use": host["bytes_in_use"],
                "peak_bytes_in_use": host["peak_bytes_in_use"],
                "limit_bytes": 0,
                "source": "host-rss",
            }
        )
    return out


def sample_once() -> List[dict]:
    """Take a snapshot and publish it to the HBM gauges."""
    snap = memory_snapshot()
    for entry in snap:
        HBM_BYTES_IN_USE.labels(device=entry["device"]).set(
            entry["bytes_in_use"]
        )
        HBM_PEAK_BYTES.labels(device=entry["device"]).set(
            entry["peak_bytes_in_use"]
        )
    return snap


def total_bytes_in_use() -> int:
    return sum(e["bytes_in_use"] for e in memory_snapshot())


class TelemetrySampler(threading.Thread):
    """Background gauge refresher for long solves. Daemonized so a hung
    solve (or an exiting process) never blocks on it."""

    def __init__(self, interval_s: float = 0.5) -> None:
        super().__init__(name="kvtpu-telemetry", daemon=True)
        self.interval_s = float(interval_s)
        # NOT named _stop: threading.Thread owns a private _stop() method
        # that join() calls on exit — shadowing it with an Event breaks join
        self._halt = threading.Event()
        self.samples = 0

    def run(self) -> None:
        while not self._halt.is_set():
            sample_once()
            self.samples += 1
            self._halt.wait(self.interval_s)

    def stop(self, join_timeout: float = 2.0) -> None:
        self._halt.set()
        self.join(timeout=join_timeout)


_sampler: Optional[TelemetrySampler] = None
_sampler_lock = threading.Lock()


def start_sampler(interval_s: float = 0.5) -> TelemetrySampler:
    """Start (or return) the process-global background sampler."""
    global _sampler
    with _sampler_lock:
        if _sampler is None or not _sampler.is_alive():
            _sampler = TelemetrySampler(interval_s)
            _sampler.start()
        return _sampler


def stop_sampler() -> None:
    global _sampler
    with _sampler_lock:
        if _sampler is not None:
            _sampler.stop()
            _sampler = None


def install_span_memory_hook() -> None:
    """Make every span snapshot memory at enter/exit (adds
    ``mem_enter_bytes`` / ``mem_exit_bytes`` to span event lines and keeps
    the HBM gauges fresh as a side effect)."""
    from .spans import set_memory_hook

    set_memory_hook(lambda: sum(e["bytes_in_use"] for e in sample_once()))


def _fmt_bytes(v: float) -> str:
    v = float(v)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024 or unit == "TiB":
            return f"{v:.0f}{unit}" if unit == "B" else f"{v:.1f}{unit}"
        v /= 1024
    return f"{v:.1f}TiB"  # pragma: no cover - unreachable


def format_memory_table(snapshot: Optional[List[dict]] = None) -> str:
    """Fixed-width device-memory table (the second half of ``kv-tpu
    explain``'s output)."""
    snap = memory_snapshot() if snapshot is None else snapshot
    header = ("device", "platform", "in_use", "peak", "limit", "source")
    rows = [header]
    for e in snap:
        rows.append(
            (
                e["device"],
                e["platform"],
                _fmt_bytes(e["bytes_in_use"]),
                _fmt_bytes(e["peak_bytes_in_use"]),
                _fmt_bytes(e["limit_bytes"]) if e.get("limit_bytes") else "-",
                e["source"],
            )
        )
    widths = [max(len(r[i]) for r in rows) for i in range(len(header))]
    lines = []
    for ri, row in enumerate(rows):
        lines.append(
            "  ".join(c.ljust(widths[i]) for i, c in enumerate(row)).rstrip()
        )
        if ri == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)
