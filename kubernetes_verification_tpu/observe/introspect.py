"""Per-kernel cost/memory introspection via the JAX AOT API.

The observe layer so far records *wall-clock* facts (spans, counters); this
module records what the compiled XLA programs actually *cost*: FLOPs and
bytes accessed from ``compiled.cost_analysis()``, and argument/output/temp
bytes from ``compiled.memory_analysis()``, folded into a structured
``KernelCostReport`` with an arithmetic-intensity figure positioned against
a per-platform roofline ridge (TPU-KNN, arXiv:2206.14286, argues per-kernel
cost models are what make peak-FLOP/s reasoning possible at all).

Publishing is **off by default** and explicitly enabled (``kv-tpu
explain``, ``bench.py --introspect``, or ``KVTPU_INTROSPECT=1``): the AOT
path re-lowers and re-compiles the dispatch — ``jitted.lower(*args)
.compile()`` does not share jit's executable cache — so an always-on pass
would double every compile cliff. Dispatch sites therefore hand the tracker
a zero-arg ``lower=`` closure that is only evaluated when introspection is
on AND the abstract signature is new (``DispatchTracker.track``), mirroring
the recompile cache in ``observe/jit.py``.

Pure-host backends (cpu, datalog, native) have no XLA program to analyse;
they publish analytic order-of-magnitude estimates through
``publish_host_estimate`` so ``kv-tpu explain --backend cpu`` still renders
a cost/memory table (``source=host-estimate`` marks those rows).
"""
from __future__ import annotations

import dataclasses
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

from .events import log_event
from .metrics import (
    COST_REPORTS_TOTAL,
    KERNEL_BYTES_ACCESSED,
    KERNEL_FLOPS,
    KERNEL_PEAK_BYTES,
    ROOFLINE_ACHIEVED_MACS_PER_SECOND,
    ROOFLINE_PCT_OF_PEAK,
)

__all__ = [
    "KernelCostReport",
    "introspection_enabled",
    "set_introspection",
    "publish_compiled",
    "publish_host_estimate",
    "maybe_publish",
    "reports",
    "reports_dict",
    "clear_reports",
    "format_cost_table",
    "roofline_ridge",
    "device_peak_macs_per_s",
    "roofline_rows",
    "format_roofline_table",
]

#: Machine-balance ridge points (FLOP/byte at which a kernel flips from
#: memory- to compute-bound), per platform. TPU: v5e-class bf16 peak
#: (~197 TFLOP/s) over HBM bandwidth (~819 GB/s) ≈ 240. CPU: order of a
#: server core's FMA throughput over DRAM bandwidth. Coarse by design —
#: the table labels a kernel "memory"- or "compute"-bound, not a percent.
_RIDGE_FLOPS_PER_BYTE = {"tpu": 240.0, "gpu": 80.0, "cpu": 10.0, "host": 10.0}

_ENV_FLAG = "KVTPU_INTROSPECT"

_lock = threading.RLock()
_enabled: Optional[bool] = None  # None = defer to the env var
_reports: Dict[Tuple[str, str, object], "KernelCostReport"] = {}


@dataclasses.dataclass(frozen=True)
class KernelCostReport:
    """Structured cost/memory summary of one compiled dispatch site."""

    engine: str
    fn: str
    platform: str
    source: str  # "xla" (AOT cost/memory analysis) | "host-estimate"
    flops: int
    bytes_accessed: int
    argument_bytes: int
    output_bytes: int
    temp_bytes: int
    peak_bytes: int
    generated_code_bytes: int = 0

    @property
    def arithmetic_intensity(self) -> float:
        """FLOPs per byte of memory traffic — the roofline x-axis."""
        return self.flops / self.bytes_accessed if self.bytes_accessed else 0.0

    @property
    def ridge_flops_per_byte(self) -> float:
        return roofline_ridge(self.platform)

    @property
    def roofline_bound(self) -> str:
        """Which roofline the kernel sits under on its platform."""
        ridge = self.ridge_flops_per_byte
        if not self.flops or not self.bytes_accessed:
            return "n/a"
        return "compute" if self.arithmetic_intensity >= ridge else "memory"

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["arithmetic_intensity"] = round(self.arithmetic_intensity, 4)
        d["ridge_flops_per_byte"] = self.ridge_flops_per_byte
        d["roofline_bound"] = self.roofline_bound
        return d


def roofline_ridge(platform: str) -> float:
    return _RIDGE_FLOPS_PER_BYTE.get(platform, _RIDGE_FLOPS_PER_BYTE["host"])


#: Published MXU peak, in MACs/s (= published TOPS / 2: one MAC is a
#: multiply + an add), keyed by ``device_kind`` prefix (longest prefix
#: wins; a v5e reports "TPU v5 lite"). Sources: the public TPU spec
#: sheets — v5e 394.2 int8 TOPS / 197.1 bf16 TFLOP/s; v5p 918 int8 TOPS;
#: v4 has no int8 MXU mode (275 bf16 TFLOP/s for both rows); v6e (Trillium)
#: 1836.7 int8 TOPS.
_PEAK_MACS_PER_S = {
    "TPU v5 lite": {"int8": 197.1e12, "bf16": 98.55e12},
    "TPU v5e": {"int8": 197.1e12, "bf16": 98.55e12},
    "TPU v5p": {"int8": 459.0e12, "bf16": 229.5e12},
    "TPU v5": {"int8": 459.0e12, "bf16": 229.5e12},
    "TPU v4": {"int8": 137.5e12, "bf16": 137.5e12},
    "TPU v6 lite": {"int8": 918.35e12, "bf16": 459.2e12},
    "TPU v6e": {"int8": 918.35e12, "bf16": 459.2e12},
}


def device_peak_macs_per_s(
    device_kind: Optional[str], dtype: str = "int8"
) -> Optional[float]:
    """Published MXU peak for a device model string (longest-prefix match
    over the table above), or ``None`` for unknown devices — callers fall
    back to the sentinel-calibrated or analytic host peak."""
    if not device_kind:
        return None
    best = None
    for prefix, peaks in _PEAK_MACS_PER_S.items():
        if device_kind.startswith(prefix) and dtype in peaks:
            if best is None or len(prefix) > len(best[0]):
                best = (prefix, peaks[dtype])
    return best[1] if best else None


def _analytic_host_peak() -> float:
    """Order-of-magnitude host MAC peak: cores × ~2.5 GHz × 16 int8
    MACs/cycle (one 128-bit FMA pipe's worth). Deliberately coarse — it
    exists so a ``pct_of_peak`` on an unknown host is a bounded estimate
    instead of a division by zero."""
    cores = os.cpu_count() or 1
    return float(cores) * 2.5e9 * 16.0


def _roofline_peak(rec: dict) -> Tuple[float, str]:
    """(peak MACs/s, source) for one history record: the published device
    table when the model is known, else the record's own
    sentinel-calibrated matmul peak, else the analytic host estimate."""
    peak = device_peak_macs_per_s(rec.get("device"))
    if peak:
        return peak, f"peak-table[{rec.get('device')}]"
    sentinel = rec.get("sentinel")
    if isinstance(sentinel, dict):
        try:
            cal = float(sentinel.get("calibrated_peak_macs_per_s", 0.0))
        except (TypeError, ValueError):
            cal = 0.0
        if cal > 0.0:
            return cal, "sentinel-calibrated"
    return _analytic_host_peak(), "analytic-host"


def roofline_rows(runs: List[dict]) -> List[dict]:
    """Achieved-vs-peak accounting over a bench history: for the newest
    record of every mode that carries a MAC count (``macs``, stamped by
    ``bench.py``) and a steady-state seconds figure, convert measured
    throughput into achieved MACs/s and position it against the device
    peak (published table → sentinel-calibrated → analytic host). Updates
    the ``kvtpu_roofline_*`` gauges as a side effect."""
    newest: Dict[str, dict] = {}
    for rec in runs:
        try:
            macs = float(rec["macs"])
            steady = float(rec["steady_s"])
        except (KeyError, TypeError, ValueError):
            continue
        if macs <= 0.0 or steady <= 0.0:
            continue
        mode = rec.get("mode") or str(rec.get("metric", "?"))
        newest[mode] = rec  # later records win: history order is oldest-first
    rows = []
    for mode, rec in sorted(newest.items()):
        macs = float(rec["macs"])
        steady = float(rec["steady_s"])
        achieved = macs / steady
        peak, source = _roofline_peak(rec)
        pct = 100.0 * achieved / peak if peak else 0.0
        ROOFLINE_ACHIEVED_MACS_PER_SECOND.labels(mode=mode).set(achieved)
        ROOFLINE_PCT_OF_PEAK.labels(mode=mode).set(pct)
        rows.append(
            {
                "mode": mode,
                "metric": rec.get("metric"),
                "device": rec.get("device"),
                "platform": rec.get("platform"),
                "macs": macs,
                "steady_s": steady,
                "achieved_macs_per_s": achieved,
                "peak_macs_per_s": peak,
                "peak_source": source,
                "pct_of_peak": round(pct, 2),
                "macs_basis": rec.get("macs_basis"),
            }
        )
    return rows


def format_roofline_table(rows: List[dict]) -> str:
    """Fixed-width roofline table (the ``kv-tpu explain --roofline``
    body). Empty string when no record carries MAC accounting."""
    if not rows:
        return ""
    header = (
        "mode", "device", "achieved MACs/s", "peak MACs/s", "% peak",
        "peak source", "basis",
    )
    out = [header]
    for r in rows:
        out.append(
            (
                str(r["mode"]),
                str(r.get("device") or "?"),
                _fmt_count(r["achieved_macs_per_s"]),
                _fmt_count(r["peak_macs_per_s"]),
                f"{r['pct_of_peak']:.1f}%",
                str(r["peak_source"]),
                str(r.get("macs_basis") or ""),
            )
        )
    widths = [max(len(row[i]) for row in out) for i in range(len(header))]
    lines = []
    for ri, row in enumerate(out):
        lines.append(
            "  ".join(cell.ljust(widths[i]) for i, cell in enumerate(row)).rstrip()
        )
        if ri == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


# ------------------------------------------------------------------ gating
def introspection_enabled() -> bool:
    if _enabled is not None:
        return _enabled
    return os.environ.get(_ENV_FLAG, "").lower() not in ("", "0", "false")


def set_introspection(on: bool) -> None:
    """Force introspection on/off for this process (overrides the
    KVTPU_INTROSPECT env var)."""
    global _enabled
    with _lock:
        _enabled = bool(on)


# ------------------------------------------------------------- publishing
def _store(key: Tuple[str, str, object], rep: KernelCostReport) -> None:
    with _lock:
        _reports[key] = rep
    KERNEL_FLOPS.labels(engine=rep.engine, fn=rep.fn).set(rep.flops)
    KERNEL_BYTES_ACCESSED.labels(engine=rep.engine, fn=rep.fn).set(
        rep.bytes_accessed
    )
    KERNEL_PEAK_BYTES.labels(engine=rep.engine, fn=rep.fn).set(rep.peak_bytes)
    COST_REPORTS_TOTAL.labels(
        engine=rep.engine, fn=rep.fn, source=rep.source
    ).inc()
    log_event(
        "kernel_cost_report",
        engine=rep.engine,
        fn=rep.fn,
        source=rep.source,
        flops=rep.flops,
        bytes_accessed=rep.bytes_accessed,
        peak_bytes=rep.peak_bytes,
        bound=rep.roofline_bound,
    )


def _first_cost_dict(cost) -> dict:
    """``compiled.cost_analysis()`` is a dict on new jax, a list of dicts on
    older versions, or None when the backend doesn't implement it."""
    if isinstance(cost, dict):
        return cost
    if isinstance(cost, (list, tuple)) and cost and isinstance(cost[0], dict):
        return cost[0]
    return {}


def publish_compiled(
    engine: str,
    fn: str,
    lower: Callable[[], object],
    signature: object = None,
) -> Optional[KernelCostReport]:
    """Evaluate a zero-arg ``lower`` closure (returning ``jitted.lower(...)``
    or an already-``.compile()``d executable), extract cost/memory analysis,
    and cache the report per (engine, fn, signature). No-op when
    introspection is disabled; never raises — an unanalysable kernel logs
    an event and returns None."""
    if not introspection_enabled():
        return None
    key = (engine, fn, signature)
    with _lock:
        if key in _reports:
            return _reports[key]
    try:
        obj = lower()
        compiled = obj.compile() if hasattr(obj, "compile") else obj
        cost = _first_cost_dict(compiled.cost_analysis())
        mem = None
        try:
            mem = compiled.memory_analysis()
        except Exception:  # some backends lower but don't expose memory
            mem = None
        platform = "cpu"
        try:
            platform = compiled.devices()[0].platform
        except Exception:
            pass
    except Exception as e:  # AOT analysis must never break the solve path
        log_event(
            "introspect_error", engine=engine, fn=fn, error=f"{type(e).__name__}: {e}"
        )
        return None
    arg_b = int(getattr(mem, "argument_size_in_bytes", 0) or 0)
    out_b = int(getattr(mem, "output_size_in_bytes", 0) or 0)
    tmp_b = int(getattr(mem, "temp_size_in_bytes", 0) or 0)
    alias_b = int(getattr(mem, "alias_size_in_bytes", 0) or 0)
    rep = KernelCostReport(
        engine=engine,
        fn=fn,
        platform=platform,
        source="xla",
        flops=int(cost.get("flops", 0) or 0),
        bytes_accessed=int(cost.get("bytes accessed", 0) or 0),
        argument_bytes=arg_b,
        output_bytes=out_b,
        temp_bytes=tmp_b,
        # live high-water mark: everything resident at once, minus buffers
        # the executable aliases input->output
        peak_bytes=max(0, arg_b + out_b + tmp_b - alias_b),
        generated_code_bytes=int(
            getattr(mem, "generated_code_size_in_bytes", 0) or 0
        ),
    )
    _store(key, rep)
    return rep


def maybe_publish(
    engine: str, fn: str, jitted, args: Tuple = (), kwargs: Optional[dict] = None
) -> Optional[KernelCostReport]:
    """Publish a cost report for ``jitted(*args, **kwargs)`` keyed by the
    operands' abstract signature. For dispatch sites without a
    ``DispatchTracker`` (the sharded ops build their shard_map jits
    per-call); cheap no-op when introspection is off."""
    if not introspection_enabled():
        return None
    from .jit import abstract_signature

    kwargs = kwargs or {}
    sig = (
        abstract_signature(args),
        tuple(sorted((k, abstract_signature(v)) for k, v in kwargs.items())),
    )
    return publish_compiled(
        engine, fn, lambda: jitted.lower(*args, **kwargs), signature=sig
    )


def _host_peak_bytes() -> int:
    """Peak RSS of this process — the host analogue of peak HBM."""
    try:
        import resource
        import sys

        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(ru) * (1 if sys.platform == "darwin" else 1024)
    except Exception:  # pragma: no cover - resource is POSIX-only
        return 0


def publish_host_estimate(
    engine: str,
    fn: str,
    *,
    flops: int,
    bytes_accessed: int,
    argument_bytes: int = 0,
    output_bytes: int = 0,
    temp_bytes: int = 0,
    signature: object = None,
) -> Optional[KernelCostReport]:
    """Analytic cost report for a pure-host kernel (no XLA program to
    lower): the caller supplies order-of-magnitude FLOP/byte counts from
    its problem shape; peak memory falls back to process peak RSS."""
    if not introspection_enabled():
        return None
    key = (engine, fn, signature)
    with _lock:
        if key in _reports:
            return _reports[key]
    rep = KernelCostReport(
        engine=engine,
        fn=fn,
        platform="host",
        source="host-estimate",
        flops=int(flops),
        bytes_accessed=int(bytes_accessed),
        argument_bytes=int(argument_bytes),
        output_bytes=int(output_bytes),
        temp_bytes=int(temp_bytes),
        peak_bytes=_host_peak_bytes(),
    )
    _store(key, rep)
    return rep


# -------------------------------------------------------------- reporting
def reports() -> List[KernelCostReport]:
    """All published reports, in publication order."""
    with _lock:
        return list(_reports.values())


def reports_dict() -> List[dict]:
    """JSON-ready report list (what bench.py attaches to its result line)."""
    return [r.to_dict() for r in reports()]


def clear_reports() -> None:
    with _lock:
        _reports.clear()


def _fmt_count(v: float) -> str:
    """Engineering-style count: 0, 999, 1.2e6."""
    v = float(v)
    if v == 0:
        return "0"
    if abs(v) < 1e4:
        return str(int(v)) if v == int(v) else f"{v:.1f}"
    return f"{v:.2e}"


def _fmt_bytes(v: float) -> str:
    v = float(v)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024 or unit == "TiB":
            return f"{v:.0f}{unit}" if unit == "B" else f"{v:.1f}{unit}"
        v /= 1024
    return f"{v:.1f}TiB"  # pragma: no cover - unreachable


def format_cost_table(reps: Optional[List[KernelCostReport]] = None) -> str:
    """Fixed-width per-kernel cost/memory table (the ``kv-tpu explain``
    body). Empty string when nothing was published."""
    reps = reports() if reps is None else list(reps)
    if not reps:
        return ""
    header = (
        "engine", "kernel", "src", "flops", "bytes", "flops/B",
        "bound", "peak", "args", "out", "temp",
    )
    rows = [header]
    for r in reps:
        rows.append(
            (
                r.engine,
                r.fn,
                r.source if r.source == "xla" else "host",
                _fmt_count(r.flops),
                _fmt_bytes(r.bytes_accessed),
                _fmt_count(round(r.arithmetic_intensity, 2)),
                r.roofline_bound,
                _fmt_bytes(r.peak_bytes),
                _fmt_bytes(r.argument_bytes),
                _fmt_bytes(r.output_bytes),
                _fmt_bytes(r.temp_bytes),
            )
        )
    widths = [max(len(row[i]) for row in rows) for i in range(len(header))]
    lines = []
    for ri, row in enumerate(rows):
        lines.append(
            "  ".join(cell.ljust(widths[i]) for i, cell in enumerate(row)).rstrip()
        )
        if ri == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)
