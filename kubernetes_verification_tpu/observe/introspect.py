"""Per-kernel cost/memory introspection via the JAX AOT API.

The observe layer so far records *wall-clock* facts (spans, counters); this
module records what the compiled XLA programs actually *cost*: FLOPs and
bytes accessed from ``compiled.cost_analysis()``, and argument/output/temp
bytes from ``compiled.memory_analysis()``, folded into a structured
``KernelCostReport`` with an arithmetic-intensity figure positioned against
a per-platform roofline ridge (TPU-KNN, arXiv:2206.14286, argues per-kernel
cost models are what make peak-FLOP/s reasoning possible at all).

Publishing is **off by default** and explicitly enabled (``kv-tpu
explain``, ``bench.py --introspect``, or ``KVTPU_INTROSPECT=1``): the AOT
path re-lowers and re-compiles the dispatch — ``jitted.lower(*args)
.compile()`` does not share jit's executable cache — so an always-on pass
would double every compile cliff. Dispatch sites therefore hand the tracker
a zero-arg ``lower=`` closure that is only evaluated when introspection is
on AND the abstract signature is new (``DispatchTracker.track``), mirroring
the recompile cache in ``observe/jit.py``.

Pure-host backends (cpu, datalog, native) have no XLA program to analyse;
they publish analytic order-of-magnitude estimates through
``publish_host_estimate`` so ``kv-tpu explain --backend cpu`` still renders
a cost/memory table (``source=host-estimate`` marks those rows).
"""
from __future__ import annotations

import dataclasses
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

from .events import log_event
from .metrics import (
    COST_REPORTS_TOTAL,
    KERNEL_BYTES_ACCESSED,
    KERNEL_FLOPS,
    KERNEL_PEAK_BYTES,
)

__all__ = [
    "KernelCostReport",
    "introspection_enabled",
    "set_introspection",
    "publish_compiled",
    "publish_host_estimate",
    "maybe_publish",
    "reports",
    "reports_dict",
    "clear_reports",
    "format_cost_table",
    "roofline_ridge",
]

#: Machine-balance ridge points (FLOP/byte at which a kernel flips from
#: memory- to compute-bound), per platform. TPU: v5e-class bf16 peak
#: (~197 TFLOP/s) over HBM bandwidth (~819 GB/s) ≈ 240. CPU: order of a
#: server core's FMA throughput over DRAM bandwidth. Coarse by design —
#: the table labels a kernel "memory"- or "compute"-bound, not a percent.
_RIDGE_FLOPS_PER_BYTE = {"tpu": 240.0, "gpu": 80.0, "cpu": 10.0, "host": 10.0}

_ENV_FLAG = "KVTPU_INTROSPECT"

_lock = threading.RLock()
_enabled: Optional[bool] = None  # None = defer to the env var
_reports: Dict[Tuple[str, str, object], "KernelCostReport"] = {}


@dataclasses.dataclass(frozen=True)
class KernelCostReport:
    """Structured cost/memory summary of one compiled dispatch site."""

    engine: str
    fn: str
    platform: str
    source: str  # "xla" (AOT cost/memory analysis) | "host-estimate"
    flops: int
    bytes_accessed: int
    argument_bytes: int
    output_bytes: int
    temp_bytes: int
    peak_bytes: int
    generated_code_bytes: int = 0

    @property
    def arithmetic_intensity(self) -> float:
        """FLOPs per byte of memory traffic — the roofline x-axis."""
        return self.flops / self.bytes_accessed if self.bytes_accessed else 0.0

    @property
    def ridge_flops_per_byte(self) -> float:
        return roofline_ridge(self.platform)

    @property
    def roofline_bound(self) -> str:
        """Which roofline the kernel sits under on its platform."""
        ridge = self.ridge_flops_per_byte
        if not self.flops or not self.bytes_accessed:
            return "n/a"
        return "compute" if self.arithmetic_intensity >= ridge else "memory"

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["arithmetic_intensity"] = round(self.arithmetic_intensity, 4)
        d["ridge_flops_per_byte"] = self.ridge_flops_per_byte
        d["roofline_bound"] = self.roofline_bound
        return d


def roofline_ridge(platform: str) -> float:
    return _RIDGE_FLOPS_PER_BYTE.get(platform, _RIDGE_FLOPS_PER_BYTE["host"])


# ------------------------------------------------------------------ gating
def introspection_enabled() -> bool:
    if _enabled is not None:
        return _enabled
    return os.environ.get(_ENV_FLAG, "").lower() not in ("", "0", "false")


def set_introspection(on: bool) -> None:
    """Force introspection on/off for this process (overrides the
    KVTPU_INTROSPECT env var)."""
    global _enabled
    with _lock:
        _enabled = bool(on)


# ------------------------------------------------------------- publishing
def _store(key: Tuple[str, str, object], rep: KernelCostReport) -> None:
    with _lock:
        _reports[key] = rep
    KERNEL_FLOPS.labels(engine=rep.engine, fn=rep.fn).set(rep.flops)
    KERNEL_BYTES_ACCESSED.labels(engine=rep.engine, fn=rep.fn).set(
        rep.bytes_accessed
    )
    KERNEL_PEAK_BYTES.labels(engine=rep.engine, fn=rep.fn).set(rep.peak_bytes)
    COST_REPORTS_TOTAL.labels(
        engine=rep.engine, fn=rep.fn, source=rep.source
    ).inc()
    log_event(
        "kernel_cost_report",
        engine=rep.engine,
        fn=rep.fn,
        source=rep.source,
        flops=rep.flops,
        bytes_accessed=rep.bytes_accessed,
        peak_bytes=rep.peak_bytes,
        bound=rep.roofline_bound,
    )


def _first_cost_dict(cost) -> dict:
    """``compiled.cost_analysis()`` is a dict on new jax, a list of dicts on
    older versions, or None when the backend doesn't implement it."""
    if isinstance(cost, dict):
        return cost
    if isinstance(cost, (list, tuple)) and cost and isinstance(cost[0], dict):
        return cost[0]
    return {}


def publish_compiled(
    engine: str,
    fn: str,
    lower: Callable[[], object],
    signature: object = None,
) -> Optional[KernelCostReport]:
    """Evaluate a zero-arg ``lower`` closure (returning ``jitted.lower(...)``
    or an already-``.compile()``d executable), extract cost/memory analysis,
    and cache the report per (engine, fn, signature). No-op when
    introspection is disabled; never raises — an unanalysable kernel logs
    an event and returns None."""
    if not introspection_enabled():
        return None
    key = (engine, fn, signature)
    with _lock:
        if key in _reports:
            return _reports[key]
    try:
        obj = lower()
        compiled = obj.compile() if hasattr(obj, "compile") else obj
        cost = _first_cost_dict(compiled.cost_analysis())
        mem = None
        try:
            mem = compiled.memory_analysis()
        except Exception:  # some backends lower but don't expose memory
            mem = None
        platform = "cpu"
        try:
            platform = compiled.devices()[0].platform
        except Exception:
            pass
    except Exception as e:  # AOT analysis must never break the solve path
        log_event(
            "introspect_error", engine=engine, fn=fn, error=f"{type(e).__name__}: {e}"
        )
        return None
    arg_b = int(getattr(mem, "argument_size_in_bytes", 0) or 0)
    out_b = int(getattr(mem, "output_size_in_bytes", 0) or 0)
    tmp_b = int(getattr(mem, "temp_size_in_bytes", 0) or 0)
    alias_b = int(getattr(mem, "alias_size_in_bytes", 0) or 0)
    rep = KernelCostReport(
        engine=engine,
        fn=fn,
        platform=platform,
        source="xla",
        flops=int(cost.get("flops", 0) or 0),
        bytes_accessed=int(cost.get("bytes accessed", 0) or 0),
        argument_bytes=arg_b,
        output_bytes=out_b,
        temp_bytes=tmp_b,
        # live high-water mark: everything resident at once, minus buffers
        # the executable aliases input->output
        peak_bytes=max(0, arg_b + out_b + tmp_b - alias_b),
        generated_code_bytes=int(
            getattr(mem, "generated_code_size_in_bytes", 0) or 0
        ),
    )
    _store(key, rep)
    return rep


def maybe_publish(
    engine: str, fn: str, jitted, args: Tuple = (), kwargs: Optional[dict] = None
) -> Optional[KernelCostReport]:
    """Publish a cost report for ``jitted(*args, **kwargs)`` keyed by the
    operands' abstract signature. For dispatch sites without a
    ``DispatchTracker`` (the sharded ops build their shard_map jits
    per-call); cheap no-op when introspection is off."""
    if not introspection_enabled():
        return None
    from .jit import abstract_signature

    kwargs = kwargs or {}
    sig = (
        abstract_signature(args),
        tuple(sorted((k, abstract_signature(v)) for k, v in kwargs.items())),
    )
    return publish_compiled(
        engine, fn, lambda: jitted.lower(*args, **kwargs), signature=sig
    )


def _host_peak_bytes() -> int:
    """Peak RSS of this process — the host analogue of peak HBM."""
    try:
        import resource
        import sys

        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(ru) * (1 if sys.platform == "darwin" else 1024)
    except Exception:  # pragma: no cover - resource is POSIX-only
        return 0


def publish_host_estimate(
    engine: str,
    fn: str,
    *,
    flops: int,
    bytes_accessed: int,
    argument_bytes: int = 0,
    output_bytes: int = 0,
    temp_bytes: int = 0,
    signature: object = None,
) -> Optional[KernelCostReport]:
    """Analytic cost report for a pure-host kernel (no XLA program to
    lower): the caller supplies order-of-magnitude FLOP/byte counts from
    its problem shape; peak memory falls back to process peak RSS."""
    if not introspection_enabled():
        return None
    key = (engine, fn, signature)
    with _lock:
        if key in _reports:
            return _reports[key]
    rep = KernelCostReport(
        engine=engine,
        fn=fn,
        platform="host",
        source="host-estimate",
        flops=int(flops),
        bytes_accessed=int(bytes_accessed),
        argument_bytes=int(argument_bytes),
        output_bytes=int(output_bytes),
        temp_bytes=int(temp_bytes),
        peak_bytes=_host_peak_bytes(),
    )
    _store(key, rep)
    return rep


# -------------------------------------------------------------- reporting
def reports() -> List[KernelCostReport]:
    """All published reports, in publication order."""
    with _lock:
        return list(_reports.values())


def reports_dict() -> List[dict]:
    """JSON-ready report list (what bench.py attaches to its result line)."""
    return [r.to_dict() for r in reports()]


def clear_reports() -> None:
    with _lock:
        _reports.clear()


def _fmt_count(v: float) -> str:
    """Engineering-style count: 0, 999, 1.2e6."""
    v = float(v)
    if v == 0:
        return "0"
    if abs(v) < 1e4:
        return str(int(v)) if v == int(v) else f"{v:.1f}"
    return f"{v:.2e}"


def _fmt_bytes(v: float) -> str:
    v = float(v)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024 or unit == "TiB":
            return f"{v:.0f}{unit}" if unit == "B" else f"{v:.1f}{unit}"
        v /= 1024
    return f"{v:.1f}TiB"  # pragma: no cover - unreachable


def format_cost_table(reps: Optional[List[KernelCostReport]] = None) -> str:
    """Fixed-width per-kernel cost/memory table (the ``kv-tpu explain``
    body). Empty string when nothing was published."""
    reps = reports() if reps is None else list(reps)
    if not reps:
        return ""
    header = (
        "engine", "kernel", "src", "flops", "bytes", "flops/B",
        "bound", "peak", "args", "out", "temp",
    )
    rows = [header]
    for r in reps:
        rows.append(
            (
                r.engine,
                r.fn,
                r.source if r.source == "xla" else "host",
                _fmt_count(r.flops),
                _fmt_bytes(r.bytes_accessed),
                _fmt_count(round(r.arithmetic_intensity, 2)),
                r.roofline_bound,
                _fmt_bytes(r.peak_bytes),
                _fmt_bytes(r.argument_bytes),
                _fmt_bytes(r.output_bytes),
                _fmt_bytes(r.temp_bytes),
            )
        )
    widths = [max(len(row[i]) for row in rows) for i in range(len(header))]
    lines = []
    for ri, row in enumerate(rows):
        lines.append(
            "  ".join(cell.ljust(widths[i]) for i, cell in enumerate(row)).rstrip()
        )
        if ri == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)
