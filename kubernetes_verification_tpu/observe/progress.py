"""Progress plane: pass-boundary instrumentation for long-running host
loops.

The system's longest work — packed/sharded closure squaring passes,
bounded-closure BFS levels, follower bootstrap chunk shipping, WAL replay,
checkpoint saves — runs as host-side multi-pass loops that used to be
black boxes between "started" and "done". Each such loop drives a
:class:`ProgressTicker` at every pass boundary; the ticker

* emits one structured ``progress`` event line per pass (job id, pass,
  fraction, rate, smoothed ETA) on the ``kvtpu`` logger,
* keeps the ``kvtpu_progress_*`` metric families current, and
* registers the job in a process-global table that ``kv-tpu jobs`` /
  ``kv-tpu top`` and every replica's ``/healthz`` read live.

ETA smoothing is an exponential moving average of the per-pass rate, so a
single slow stripe does not whipsaw the estimate. The ``on_pass`` callback
is the generic pass-boundary hook — pass-boundary closure checkpointing
(``ops/closure.py``) hangs off it.

Time comes from the shared injectable clock (``observe.events.set_clock``)
so tests drive rates and ETAs deterministically.
"""
from __future__ import annotations

import itertools
import math
import os
import threading
from typing import Callable, Dict, List, Optional

from .events import get_clock, log_event
from .metrics import (
    PROGRESS_ACTIVE_JOBS,
    PROGRESS_ETA_SECONDS,
    PROGRESS_FRACTION,
    PROGRESS_PASSES_TOTAL,
)

__all__ = [
    "ProgressTicker",
    "RATE_ALPHA",
    "active_jobs",
    "render_jobs",
    "eta_bar",
]

#: EMA weight of the newest per-pass rate sample: heavy enough that the
#: estimate tracks a genuine slowdown within ~3 passes, light enough that
#: one GC pause does not dominate the ETA
RATE_ALPHA = 0.4

#: in-flight jobs, job_id -> snapshot dict (what /healthz and kv-tpu jobs
#: render); finished jobs are removed, their final event line remains
_JOBS: Dict[str, dict] = {}
_JOBS_LOCK = threading.Lock()
_JOB_IDS = itertools.count(1)


def active_jobs() -> List[dict]:
    """Snapshot of every in-flight job in this process, oldest first —
    JSON-safe (the ``/healthz`` overlay embeds it verbatim)."""
    with _JOBS_LOCK:
        return [dict(snap) for snap in _JOBS.values()]


class ProgressTicker:
    """One long-running job's progress: drive :meth:`tick` at every pass
    boundary, :meth:`finish` (or use as a context manager) when done.

    ``total`` is the expected pass count when one exists (an upper bound is
    fine — closure fixpoints finish early and report ``converged``); with
    ``total=None`` the job still ticks rate and pass counts but carries no
    fraction/ETA. ``unit`` names what a pass is (``pass``, ``level``,
    ``file``, ``batch``, ``phase``) for humans reading the event stream.
    """

    def __init__(
        self,
        job: str,
        total: Optional[int] = None,
        *,
        unit: str = "pass",
        initial: int = 0,
        on_pass: Optional[Callable[[int], None]] = None,
        min_interval: float = 0.0,
    ) -> None:
        self.job = job
        # a job reporting total_passes=0 (or any non-positive total) has an
        # *unknown* extent, not a zero-length one: normalise to None so the
        # fraction/ETA math never divides by it and renderers draw the
        # indeterminate bar
        self.total = int(total) if total and int(total) > 0 else None
        self.unit = unit
        self.done = int(initial)
        self.on_pass = on_pass
        self.min_interval = float(min_interval)
        self.outcome: Optional[str] = None
        clock = get_clock()
        self._clock = clock
        self._started_ts = clock.wall()
        self._start_perf = clock.perf()
        self._last_perf = self._start_perf
        self._last_emit_perf: Optional[float] = None
        self._initial = self.done
        self.rate: Optional[float] = None  # units/second, EMA-smoothed
        self.job_id = f"{job}-{os.getpid()}-{next(_JOB_IDS)}"
        self._publish()
        log_event(
            "progress_start",
            job=self.job,
            job_id=self.job_id,
            unit=self.unit,
            done=self.done,
            total=self.total,
        )

    # ------------------------------------------------------------- core
    def tick(self, done: Optional[int] = None, **fields) -> None:
        """One pass boundary: ``done`` is the absolute completed count
        (monotone — a lower value is clamped to the current one); omitted,
        it increments by one. Extra keyword fields land on the event line.
        """
        if done is None:
            done = self.done + 1
        done = max(int(done), self.done)
        delta = done - self.done
        self.done = done
        now = self._clock.perf()
        dt = now - self._last_perf
        self._last_perf = now
        if delta > 0 and dt > 0:
            inst = delta / dt
            self.rate = (
                inst
                if self.rate is None
                else RATE_ALPHA * inst + (1.0 - RATE_ALPHA) * self.rate
            )
        PROGRESS_PASSES_TOTAL.labels(job=self.job).inc(max(delta, 0))
        self._publish()
        emit = (
            self._last_emit_perf is None
            or now - self._last_emit_perf >= self.min_interval
        )
        if emit:
            self._last_emit_perf = now
            log_event(
                "progress",
                job=self.job,
                job_id=self.job_id,
                unit=self.unit,
                done=self.done,
                total=self.total,
                fraction=self.fraction,
                rate=None if self.rate is None else round(self.rate, 6),
                eta_s=None if self.eta_s is None else round(self.eta_s, 6),
                elapsed_s=round(now - self._start_perf, 6),
                **fields,
            )
        if self.on_pass is not None:
            self.on_pass(self.done)

    def finish(self, outcome: str = "done", **fields) -> None:
        """Close the job (idempotent): final event line, gauges parked at
        complete, table entry removed."""
        if self.outcome is not None:
            return
        self.outcome = outcome
        now = self._clock.perf()
        if outcome != "error":
            PROGRESS_FRACTION.labels(job=self.job).set(1.0)
            PROGRESS_ETA_SECONDS.labels(job=self.job).set(0.0)
        with _JOBS_LOCK:
            _JOBS.pop(self.job_id, None)
            PROGRESS_ACTIVE_JOBS.set(float(len(_JOBS)))
        log_event(
            "progress_end",
            job=self.job,
            job_id=self.job_id,
            unit=self.unit,
            done=self.done,
            total=self.total,
            outcome=outcome,
            elapsed_s=round(now - self._start_perf, 6),
            **fields,
        )

    # ------------------------------------------------------- derived views
    @property
    def fraction(self) -> Optional[float]:
        if not self.total:
            return None
        return min(1.0, self.done / self.total)

    @property
    def eta_s(self) -> Optional[float]:
        """Smoothed remaining seconds: remaining passes over the EMA rate;
        None until a rate exists or when the total is unknown."""
        if not self.total or self.rate is None or self.rate <= 0:
            return None
        return max(0, self.total - self.done) / self.rate

    def _publish(self) -> None:
        fraction = self.fraction
        eta = self.eta_s
        PROGRESS_FRACTION.labels(job=self.job).set(
            -1.0 if fraction is None else fraction
        )
        PROGRESS_ETA_SECONDS.labels(job=self.job).set(
            -1.0 if eta is None else eta
        )
        snap = {
            "job": self.job,
            "job_id": self.job_id,
            "pid": os.getpid(),
            "unit": self.unit,
            "done": self.done,
            "total": self.total,
            "fraction": fraction,
            "rate": None if self.rate is None else round(self.rate, 6),
            "eta_s": None if eta is None else round(eta, 6),
            "started_ts": self._started_ts,
            "updated_ts": self._clock.wall(),
        }
        with _JOBS_LOCK:
            _JOBS[self.job_id] = snap
            PROGRESS_ACTIVE_JOBS.set(float(len(_JOBS)))

    # ------------------------------------------------------ context manager
    def __enter__(self) -> "ProgressTicker":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.finish("error" if exc_type is not None else "done")


# ------------------------------------------------------------- rendering
def eta_bar(fraction: Optional[float], width: int = 20) -> str:
    """``[########------------]`` for a known fraction, an indeterminate
    ``[????]`` bar otherwise — shared by ``kv-tpu jobs`` and ``kv-tpu
    top``. Anything unrenderable (None, negative, NaN/inf from a job that
    reported a zero or garbage total) is "unknown", never a raise: this
    runs inside the operator's status loop."""
    if fraction is None:
        return "[" + "?" * width + "]"
    fraction = float(fraction)
    if not math.isfinite(fraction) or fraction < 0:
        return "[" + "?" * width + "]"
    fraction = max(0.0, min(1.0, fraction))
    fill = int(round(fraction * width))
    return "[" + "#" * fill + "-" * (width - fill) + "]"


def _fmt_eta(eta: Optional[float]) -> str:
    if eta is None:
        return "-"
    eta = max(0.0, float(eta))
    if eta >= 3600:
        return f"{eta / 3600:.1f}h"
    if eta >= 60:
        return f"{eta / 60:.1f}m"
    return f"{eta:.1f}s"


def render_jobs(jobs: List[dict], bar_width: int = 20) -> List[str]:
    """One aligned row per in-flight job: id, pass counter, ETA bar, rate,
    ETA. Jobs with unknown totals render pass counts and rate only."""
    header = ("job", "unit", "done", "progress", "rate/s", "eta")
    rows = [header]
    for j in jobs:
        total = j.get("total")
        done = j.get("done", 0)
        # non-positive totals come from jobs that reported total_passes=0:
        # unknown extent — render the bare counter + indeterminate bar
        known_total = isinstance(total, (int, float)) and total > 0
        counter = f"{done}/{total}" if known_total else str(done)
        rate = j.get("rate")
        rows.append(
            (
                str(j.get("job_id", j.get("job", "-"))),
                str(j.get("unit", "pass")),
                counter,
                eta_bar(j.get("fraction"), bar_width),
                "-" if rate is None else f"{rate:.2f}",
                _fmt_eta(j.get("eta_s")),
            )
        )
    widths = [max(len(r[i]) for r in rows) for i in range(len(header))]
    return [
        "  ".join(cell.ljust(widths[i]) for i, cell in enumerate(row)).rstrip()
        for row in rows
    ]
