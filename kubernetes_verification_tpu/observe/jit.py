"""Recompile detection + operand accounting for jitted dispatch sites.

XLA recompiles whenever a jitted function sees a new abstract signature
(shapes/dtypes of array operands plus static arguments). Those compiles are
silent multi-hundred-ms cliffs — exactly the thing an incremental engine
must not hit per update. ``DispatchTracker`` mirrors jax's cache key
cheaply on the host: hash the abstract shape of every operand at each
dispatch and count signatures never seen before as
``kvtpu_jit_recompiles_total{engine=...,fn=...}``.

This is deliberately jax-free: it walks shapes via duck typing
(``.shape``/``.dtype``), so NumPy-oracle paths can use the same tracker.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Set, Tuple

from .events import log_event
from .metrics import JIT_RECOMPILES

__all__ = ["DispatchTracker", "abstract_signature", "tree_nbytes"]


def abstract_signature(tree) -> Tuple:
    """Hashable (shape, dtype) skeleton of a pytree-ish value: arrays become
    ``("a", shape, dtype)``; containers/dataclasses recurse; scalars pass
    through (they are usually static or weakly-typed constants)."""
    if hasattr(tree, "shape") and hasattr(tree, "dtype"):
        return ("a", tuple(tree.shape), str(tree.dtype))
    if isinstance(tree, (list, tuple)):
        return tuple(abstract_signature(x) for x in tree)
    if isinstance(tree, dict):
        return tuple(
            (k, abstract_signature(tree[k])) for k in sorted(tree)
        )
    if dataclasses.is_dataclass(tree) and not isinstance(tree, type):
        return tuple(
            abstract_signature(getattr(tree, f.name))
            for f in dataclasses.fields(tree)
        )
    if tree is None or isinstance(tree, (bool, int, float, str)):
        return tree
    return type(tree).__name__


def tree_nbytes(tree) -> int:
    """Total array bytes in a pytree-ish value (same walk as above)."""
    if hasattr(tree, "nbytes") and hasattr(tree, "shape"):
        return int(tree.nbytes)
    if isinstance(tree, (list, tuple)):
        return sum(tree_nbytes(x) for x in tree)
    if isinstance(tree, dict):
        return sum(tree_nbytes(v) for v in tree.values())
    if dataclasses.is_dataclass(tree) and not isinstance(tree, type):
        return sum(
            tree_nbytes(getattr(tree, f.name))
            for f in dataclasses.fields(tree)
        )
    return 0


class DispatchTracker:
    """Per-module recompile counter. One tracker per engine/backend module
    (jit caches are per-function and process-global, so instance-level
    tracking would double-count across engine instances)."""

    def __init__(self, engine: str) -> None:
        self.engine = engine
        self._seen: Dict[str, Set[Tuple]] = {}

    def track(self, fn: str, *operands, static: Tuple = (), lower=None) -> bool:
        """Record one dispatch of ``fn``; returns True (and bumps the
        recompile counter) when this abstract signature is new.

        ``lower`` is an optional zero-arg closure returning
        ``jitted.lower(<the real dispatch args>)`` — evaluated only when the
        signature is new AND introspection is enabled, publishing a
        ``KernelCostReport`` for the fresh compile (the AOT analysis pass
        does not share jit's executable cache, so it must stay opt-in)."""
        sig = (tuple(static), abstract_signature(operands))
        seen = self._seen.setdefault(fn, set())
        if sig in seen:
            return False
        seen.add(sig)
        JIT_RECOMPILES.labels(engine=self.engine, fn=fn).inc()
        log_event(
            "jit_recompile",
            engine=self.engine,
            fn=fn,
            signatures=len(seen),
        )
        if lower is not None:
            from .introspect import publish_compiled

            publish_compiled(self.engine, fn, lower, signature=sig)
        return True

    def signatures(self, fn: str) -> int:
        return len(self._seen.get(fn, ()))
