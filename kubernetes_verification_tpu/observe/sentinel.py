"""Perf sentinel: fixed-shape calibration kernels + a dispatch-latency probe.

The bench trajectory's open wound (ROADMAP "Perf sentinel + roofline
accounting", VERDICT §3) is that a headline walking 2.76 → 2.41 G pairs/s
across rounds is indistinguishable from the tunnel's documented ±30%
dispatch noise: rounds are different sessions, and nothing in the recorded
rounds separates "the code got slower" from "the host↔device path got
slower".  This module is that missing instrument:

* **Calibration kernels** — 2–3 *fixed-shape, compute-bound* kernels
  (a chained int8 MXU matmul, a chained f32 matmul, a VPU bitwise-rotate
  loop over packed words) whose run-to-run spread is verified against a
  bound **at registration** (`SentinelSuite.register` measures the kernel
  and refuses — or records ``calibrated=False`` — when the spread exceeds
  it).  A calibrated kernel repeating within its bound means the *device
  compute* path is stable; if the headline moved anyway, the cause is
  dispatch, config, or code — not silicon.
* **Dispatch probe** — a near-empty kernel timed round-trip (dispatch +
  scalar read-back), whose median *is* the per-dispatch overhead the
  tunnel adds to every timed solve.  Headlines are re-expressed
  "dispatch-deflated" by removing it (``observe/history.py:
  deflate_record``), which is what the regression gate evaluates.

``bench.py --mode sentinel`` runs the suite standalone; every other bench
mode prepends it as a calibration block so each ``bench_history.jsonl``
record carries its own noise context (``sentinel`` field).  The measured
MACs/s of the matmul sentinels doubles as the *practical peak* reference
for roofline accounting on hosts with no published peak table
(``observe/introspect.py: device_peak_macs_per_s`` fallback).

The kernels are chained (iteration *k+1* consumes iteration *k*'s output)
so XLA can neither CSE the loop body into one matmul nor dead-code any
iteration, and each is sized per platform so compute dominates the
dispatch overhead it is calibrating against.  Shapes are fixed per
platform — cross-round comparability on the same device class is the
whole point — and recorded in the context so a config change is visible
as such.
"""
from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Dict, List, Optional

from ..resilience.errors import ConfigError
from .events import log_event
from .metrics import (
    SENTINEL_CALIBRATION_FAILURES_TOTAL,
    SENTINEL_DISPATCH_SECONDS,
    SENTINEL_KERNEL_SECONDS,
    SENTINEL_SPREAD_PCT,
)

__all__ = [
    "SentinelCalibrationError",
    "SentinelKernel",
    "SentinelSuite",
    "default_suite",
    "run_calibration",
    "slim_context",
    "DEFAULT_MAX_SPREAD_PCT",
]

#: Registration-time spread bound (max−min over median, percent), per
#: platform.  On a real chip the compute-bound kernels repeat within 1%
#: (the r05 closure evidence: 0.5% while dispatch-bound ops read 1.5–2×
#: slow); shared CI hosts juggle noisy neighbours, so the host bound is
#: loose — the *measured* spread is recorded either way, and that number,
#: not the bound, is what rides every bench record.
DEFAULT_MAX_SPREAD_PCT = {"tpu": 1.0, "gpu": 5.0}
_HOST_MAX_SPREAD_PCT = 40.0

_ENV_MAX_SPREAD = "KVTPU_SENTINEL_MAX_SPREAD_PCT"


class SentinelCalibrationError(ConfigError):
    """A sentinel kernel's measured spread exceeded the registration bound
    (strict mode): the instrument itself is too noisy to calibrate with."""


@dataclasses.dataclass(frozen=True)
class SentinelKernel:
    """One fixed-shape calibration kernel.

    ``build(device)`` returns a zero-arg runner that executes ONE chained
    iteration block and forces completion (scalar read-back — under the
    remote-TPU tunnel ``block_until_ready`` returns at dispatch).
    ``macs_per_run`` is the exact multiply-accumulate count of one run for
    the matmul sentinels (0 for non-MXU kernels); ``kind`` tags which unit
    the kernel saturates.
    """

    name: str
    build: Callable[[object, Dict[str, int]], Callable[[], float]]
    macs_per_run: int
    kind: str  # "mxu" | "vpu"
    dtype: str
    config: Dict[str, int] = dataclasses.field(default_factory=dict)


def _read_scalar(out) -> float:
    """Force one element back to the host — completion under the tunnel."""
    import numpy as np

    return float(np.asarray(out.ravel()[0]))


def _platform() -> str:
    import jax

    return jax.default_backend()


# --------------------------------------------------------------- kernels
def _matmul_sizes(platform: str) -> Dict[str, int]:
    """Fixed per-platform chain sizes: on TPU the chain must dominate the
    ~80 ms tunnel dispatch it calibrates against (~100+ ms of MXU work);
    on hosts it must stay sub-second under pytest."""
    if platform == "tpu":
        return {"n": 8192, "loops": 64}
    return {"n": 256, "loops": 4}


def _vpu_sizes(platform: str) -> Dict[str, int]:
    if platform == "tpu":
        return {"words": 1 << 24, "loops": 256}
    return {"words": 1 << 18, "loops": 16}


def _build_matmul_int8(device, cfg: Dict[str, int]) -> Callable[[], float]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    n, loops = cfg["n"], cfg["loops"]
    rng = np.random.default_rng(0)
    x0 = jax.device_put(
        rng.integers(-64, 64, (n, n), dtype=np.int8), device
    )
    w = jax.device_put(rng.integers(-64, 64, (n, n), dtype=np.int8), device)

    @jax.jit
    def chain(x, w):
        def body(_, x):
            y = jnp.dot(x, w, preferred_element_type=jnp.int32)
            # re-quantize so the chain stays int8 and no iteration folds
            return (y & 0x3F).astype(jnp.int8)

        return jax.lax.fori_loop(0, loops, body, x)

    def run() -> float:
        return _read_scalar(chain(x0, w))

    return run


def _build_matmul_f32(device, cfg: Dict[str, int]) -> Callable[[], float]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    n, loops = cfg["n"], cfg["loops"]
    rng = np.random.default_rng(1)
    x0 = jax.device_put(
        rng.standard_normal((n, n), dtype=np.float32), device
    )
    w = jax.device_put(
        (rng.standard_normal((n, n), dtype=np.float32) / np.sqrt(n)).astype(
            np.float32
        ),
        device,
    )

    @jax.jit
    def chain(x, w):
        def body(_, x):
            return jnp.dot(x, w)  # ||w|| ≈ 1 keeps the chain finite

        return jax.lax.fori_loop(0, loops, body, x)

    def run() -> float:
        return _read_scalar(chain(x0, w))

    return run


def _build_vpu_bitops(device, cfg: Dict[str, int]) -> Callable[[], float]:
    """Packed-word bitwise chain — the VPU analogue of the closure kernels'
    uint32 inner loop (rotate-xor keeps every lane live)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    words, loops = cfg["words"], cfg["loops"]
    rng = np.random.default_rng(2)
    w0 = jax.device_put(
        rng.integers(0, 2**32, words, dtype=np.uint32), device
    )

    @jax.jit
    def chain(w):
        def body(_, w):
            rot = (w << jnp.uint32(1)) | (w >> jnp.uint32(31))
            return rot ^ jnp.uint32(0x9E3779B9)

        return jax.lax.fori_loop(0, loops, body, w)

    def run() -> float:
        return _read_scalar(chain(w0))

    return run


def _default_kernels(platform: str) -> List[SentinelKernel]:
    mm = _matmul_sizes(platform)
    # the MXU runs f32 dots far below its int8 rate — a shorter chain keeps
    # the f32 sentinel's wall time in the same band as the int8 one
    f32 = dict(mm, loops=max(1, mm["loops"] // (4 if platform == "tpu" else 1)))
    vp = _vpu_sizes(platform)
    return [
        SentinelKernel(
            name="mxu_int8",
            build=_build_matmul_int8,
            macs_per_run=mm["loops"] * mm["n"] ** 3,
            kind="mxu",
            dtype="int8",
            config=dict(mm),
        ),
        SentinelKernel(
            name="mxu_f32",
            build=_build_matmul_f32,
            macs_per_run=f32["loops"] * f32["n"] ** 3,
            kind="mxu",
            dtype="f32",
            config=f32,
        ),
        SentinelKernel(
            name="vpu_bitops",
            build=_build_vpu_bitops,
            macs_per_run=0,
            kind="vpu",
            dtype="uint32",
            config=dict(vp),
        ),
    ]


# ----------------------------------------------------------------- suite
def _band(times: List[float]) -> Dict[str, float]:
    ts = sorted(float(t) for t in times)
    med = ts[len(ts) // 2]
    return {
        "n": len(ts),
        "min_s": ts[0],
        "median_s": med,
        "max_s": ts[-1],
        "spread_pct": 100.0 * (ts[-1] - ts[0]) / med if med else 0.0,
    }


def default_max_spread_pct(platform: str) -> float:
    env = os.environ.get(_ENV_MAX_SPREAD)
    if env:
        try:
            return float(env)
        except ValueError:
            pass  # a malformed override falls back to the platform bound
    return DEFAULT_MAX_SPREAD_PCT.get(platform, _HOST_MAX_SPREAD_PCT)


class SentinelSuite:
    """Registered sentinels plus the measurements taken at registration.

    ``register`` runs the kernel (warmup + ``reps`` timed runs, up to
    ``retries`` re-measurements keeping the tightest band) and verifies the
    measured spread against ``max_spread_pct``:

    * strict (default off): a persistent violation raises
      :class:`SentinelCalibrationError` — the caller refuses to calibrate
      with a noisy instrument;
    * non-strict: the kernel is registered with ``calibrated=False`` and
      ``kvtpu_sentinel_calibration_failures_total`` counts it — a bench
      must still run, carrying the honesty marker instead of a verdict.

    ``timer`` is injectable so tests exercise the verification logic with
    deterministic fake clocks.
    """

    def __init__(
        self,
        device=None,
        *,
        reps: int = 5,
        retries: int = 2,
        max_spread_pct: Optional[float] = None,
        timer: Callable[[], float] = time.perf_counter,
    ) -> None:
        import jax

        self.device = device if device is not None else jax.devices()[0]
        self.platform = _platform()
        self.reps = max(3, int(reps))
        self.retries = max(1, int(retries))
        self.max_spread_pct = (
            default_max_spread_pct(self.platform)
            if max_spread_pct is None
            else float(max_spread_pct)
        )
        self.timer = timer
        self.results: Dict[str, dict] = {}
        self._order: List[str] = []

    def _measure(self, run: Callable[[], float]) -> Dict[str, float]:
        for _ in range(2):  # compile + cache warm
            run()
        times = []
        for _ in range(self.reps):
            s = self.timer()
            run()
            times.append(self.timer() - s)
        return _band(times)

    def register(self, kernel: SentinelKernel, *, strict: bool = False) -> dict:
        """Measure ``kernel`` and admit it to the suite, verifying its
        spread against the bound (see class docstring)."""
        run = kernel.build(self.device, dict(kernel.config))
        band = self._measure(run)
        for _ in range(self.retries - 1):
            if band["spread_pct"] <= self.max_spread_pct:
                break
            again = self._measure(run)
            if again["spread_pct"] < band["spread_pct"]:
                band = again
        calibrated = band["spread_pct"] <= self.max_spread_pct
        if not calibrated:
            SENTINEL_CALIBRATION_FAILURES_TOTAL.labels(
                kernel=kernel.name
            ).inc()
            log_event(
                "sentinel_calibration_failed",
                kernel=kernel.name,
                spread_pct=round(band["spread_pct"], 3),
                bound_pct=self.max_spread_pct,
            )
            if strict:
                raise SentinelCalibrationError(
                    f"sentinel {kernel.name!r}: measured spread "
                    f"{band['spread_pct']:.2f}% exceeds the "
                    f"{self.max_spread_pct:g}% calibration bound after "
                    f"{self.retries} measurement(s)"
                )
        med = band["median_s"]
        res = {
            "kind": kernel.kind,
            "dtype": kernel.dtype,
            "config": dict(kernel.config),
            "median_s": med,
            "min_s": band["min_s"],
            "max_s": band["max_s"],
            "spread_pct": band["spread_pct"],
            "reps": band["n"],
            "calibrated": calibrated,
            "macs_per_run": kernel.macs_per_run,
            "macs_per_s": (kernel.macs_per_run / med) if med else 0.0,
        }
        self.results[kernel.name] = res
        self._order.append(kernel.name)
        SENTINEL_KERNEL_SECONDS.labels(kernel=kernel.name).set(med)
        SENTINEL_SPREAD_PCT.labels(kernel=kernel.name).set(
            res["spread_pct"]
        )
        return res

    # ------------------------------------------------------ dispatch probe
    def probe_dispatch(self, reps: int = 16) -> Dict[str, float]:
        """Median round-trip of a near-empty kernel: dispatch + scalar
        read-back.  This is the additive overhead every timed solve pays
        per dispatch — the quantity deflation removes."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        x = jax.device_put(np.arange(8, dtype=np.int32), self.device)
        tick = jax.jit(lambda v: v + jnp.int32(1))
        for _ in range(3):  # compile + warm the transfer path
            _read_scalar(tick(x))
        times = []
        for _ in range(max(4, reps)):
            s = self.timer()
            _read_scalar(tick(x))
            times.append(self.timer() - s)
        band = _band(times)
        self.results["_dispatch"] = band
        SENTINEL_DISPATCH_SECONDS.set(band["median_s"])
        return band

    # ------------------------------------------------------------ context
    def context(self) -> dict:
        """The calibration block a bench record carries: per-kernel bands,
        the worst calibrated-kernel spread (``spread_pct`` — the round's
        noise figure), the dispatch probe, and the measured practical peak
        (max MACs/s over the matmul sentinels — the roofline fallback
        reference on hosts with no published peak)."""
        kernels = {
            name: dict(self.results[name])
            for name in self._order
            if name in self.results
        }
        spreads = [k["spread_pct"] for k in kernels.values()]
        peaks = [
            k["macs_per_s"] for k in kernels.values() if k["macs_per_run"]
        ]
        dispatch = self.results.get("_dispatch") or {}
        import jax

        dev = self.device
        return {
            "platform": self.platform,
            "device": getattr(dev, "device_kind", str(dev)),
            "jax_version": jax.__version__,
            "max_spread_pct_bound": self.max_spread_pct,
            "spread_pct": max(spreads) if spreads else 0.0,
            "calibrated": all(k["calibrated"] for k in kernels.values()),
            "calibrated_peak_macs_per_s": max(peaks) if peaks else 0.0,
            "dispatch_s": dispatch.get("median_s", 0.0),
            "dispatch_min_s": dispatch.get("min_s", 0.0),
            "dispatch_band": dispatch,
            "kernels": kernels,
        }


def default_suite(
    device=None,
    *,
    reps: int = 5,
    max_spread_pct: Optional[float] = None,
    strict: bool = False,
) -> SentinelSuite:
    """Build the default 3-kernel suite, registering (and thereby
    measuring + verifying) every kernel, then run the dispatch probe."""
    suite = SentinelSuite(device, reps=reps, max_spread_pct=max_spread_pct)
    for k in _default_kernels(suite.platform):
        suite.register(k, strict=strict)
    suite.probe_dispatch()
    return suite


def run_calibration(
    device=None,
    *,
    reps: int = 5,
    max_spread_pct: Optional[float] = None,
    strict: bool = False,
) -> dict:
    """One-call calibration: build + measure the default suite and return
    its context block (what ``bench.py`` prepends to every record)."""
    return default_suite(
        device, reps=reps, max_spread_pct=max_spread_pct, strict=strict
    ).context()


def slim_context(ctx: dict) -> dict:
    """The compact calibration block stored on every bench record: enough
    to deflate (``dispatch_s``), to judge the round's noise
    (``spread_pct`` + per-kernel medians/spreads), and to anchor the
    roofline fallback (``calibrated_peak_macs_per_s``) — without the
    per-kernel config/band bulk."""
    return {
        "platform": ctx.get("platform"),
        "device": ctx.get("device"),
        "dispatch_s": round(float(ctx.get("dispatch_s", 0.0)), 6),
        "dispatch_min_s": round(float(ctx.get("dispatch_min_s", 0.0)), 6),
        "spread_pct": round(float(ctx.get("spread_pct", 0.0)), 3),
        "calibrated": bool(ctx.get("calibrated", False)),
        "calibrated_peak_macs_per_s": round(
            float(ctx.get("calibrated_peak_macs_per_s", 0.0)), 1
        ),
        "kernels": {
            name: {
                "median_s": round(float(k.get("median_s", 0.0)), 6),
                "spread_pct": round(float(k.get("spread_pct", 0.0)), 3),
                "calibrated": bool(k.get("calibrated", False)),
            }
            for name, k in (ctx.get("kernels") or {}).items()
        },
    }
