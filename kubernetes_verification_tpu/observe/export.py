"""Exporters: JSON registry dumps and Prometheus text exposition."""
from __future__ import annotations

import json
import os
from typing import Optional

from .registry import REGISTRY, MetricsRegistry, _format_le

__all__ = [
    "dump_registry",
    "write_metrics",
    "to_prometheus",
    "parse_prometheus",
    "parse_exemplars",
]


def dump_registry(
    registry: Optional[MetricsRegistry] = None, include_buckets: bool = True
) -> dict:
    """JSON-ready snapshot of a registry (the process-global one by
    default)."""
    reg = REGISTRY if registry is None else registry
    return reg.dump(include_buckets=include_buckets)


def write_metrics(
    path: str, registry: Optional[MetricsRegistry] = None
) -> None:
    """Write the registry to ``path`` — Prometheus text when the suffix is
    ``.prom``/``.txt``, a JSON dump otherwise."""
    reg = REGISTRY if registry is None else registry
    if path.endswith((".prom", ".txt")):
        body = to_prometheus(reg)
    else:
        body = json.dumps(reg.dump(), indent=2, sort_keys=True) + "\n"
    # scrape targets read this file concurrently: promote atomically so a
    # reader never sees a half-written exposition
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(body)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def _escape(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _labels_text(child_key: str, extra: str = "") -> str:
    """``backend=cpu,mode=k8s`` (registry child key) -> ``{backend="cpu",mode="k8s"}``."""
    parts = []
    if child_key:
        for pair in child_key.split(","):
            k, _, v = pair.partition("=")
            parts.append(f'{k}="{_escape(v)}"')
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _num(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def to_prometheus(
    registry: Optional[MetricsRegistry] = None, *, exemplars: bool = False
) -> str:
    """Render the registry in the Prometheus text exposition format
    (version 0.0.4): HELP/TYPE headers, one sample per line, histograms as
    cumulative ``_bucket{le=...}`` plus ``_sum``/``_count``.

    With ``exemplars=True``, bucket lines that retained a slowest-in-window
    exemplar grow an OpenMetrics annotation (`` # {trace_id="..."} value
    ts``) — opt-in, so the default output stays byte-identical for parsers
    that predate exemplar support."""
    reg = REGISTRY if registry is None else registry
    lines = []
    for m in reg.collect():
        lines.append(f"# HELP {m.name} {_escape(m.help)}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        children = m.children()
        for key in sorted(children):
            child = children[key]
            if m.kind == "histogram":
                per_bucket = child.exemplars() if exemplars else None
                for i, (ub, n) in enumerate(child.cumulative_buckets()):
                    le = f'le="{_format_le(ub)}"'
                    line = f"{m.name}_bucket{_labels_text(key, le)} {n}"
                    ex = per_bucket[i] if per_bucket else None
                    if ex is not None:
                        value, trace_id, ts = ex
                        line += (
                            f' # {{trace_id="{_escape(trace_id)}"}} '
                            f"{_num(value)} {ts:.3f}"
                        )
                    lines.append(line)
                lines.append(
                    f"{m.name}_sum{_labels_text(key)} {_num(child.sum)}"
                )
                lines.append(
                    f"{m.name}_count{_labels_text(key)} {child.count}"
                )
            else:
                lines.append(
                    f"{m.name}{_labels_text(key)} {_num(child.value)}"
                )
    return "\n".join(lines) + "\n"


def _parse_labels(text: str) -> dict:
    """``backend="cpu",mode="k8s"`` -> dict, honoring the exposition
    escapes ``\\\\``, ``\\"`` and ``\\n``."""
    labels: dict = {}
    i, n = 0, len(text)
    while i < n:
        eq = text.index("=", i)
        name = text[i:eq].strip().lstrip(",").strip()
        assert text[eq + 1] == '"', f"unquoted label value near {text[eq:]!r}"
        j = eq + 2
        out = []
        while j < n:
            c = text[j]
            if c == "\\" and j + 1 < n:
                nxt = text[j + 1]
                out.append({"n": "\n"}.get(nxt, nxt))
                j += 2
                continue
            if c == '"':
                break
            out.append(c)
            j += 1
        labels[name] = "".join(out)
        i = j + 1
    return labels


def parse_prometheus(text: str) -> dict:
    """Inverse of :func:`to_prometheus`, for the fleet scraper: parse a
    text-exposition body into ``{sample_name: [(labels, value), ...]}``.

    Histogram series keep their expanded names (``*_bucket``/``_sum``/
    ``_count``) — the fleet table reads plain gauges and counters, so no
    re-bucketing is attempted. Unparseable lines are skipped rather than
    failing the whole scrape (a replica mid-restart may truncate)."""
    samples: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        # drop any OpenMetrics exemplar annotation — the sample value is
        # everything before it, and pre-exemplar parsers must keep working
        cut = line.rfind(" # {")
        if cut != -1:
            line = line[:cut].rstrip()
        try:
            if "{" in line:
                name, rest = line.split("{", 1)
                label_text, _, value_text = rest.rpartition("}")
                labels = _parse_labels(label_text)
            else:
                name, _, value_text = line.partition(" ")
                labels = {}
            value = float(value_text.strip().replace("+Inf", "inf"))
        except Exception:
            continue
        samples.setdefault(name.strip(), []).append((labels, value))
    return samples


def parse_exemplars(text: str) -> list:
    """Extract the OpenMetrics exemplar annotations from a text exposition
    (the ``to_prometheus(..., exemplars=True)`` / ``/metrics?exemplars=1``
    form): one dict per annotated sample line with the sample ``name``, its
    ``labels``, the ``exemplar`` labels (``trace_id``), the exemplar
    ``value`` (the observation, not the cumulative bucket count) and its
    wall ``ts``. Unparseable lines are skipped — same contract as
    :func:`parse_prometheus`."""
    out: list = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        cut = line.rfind(" # {")
        if cut == -1:
            continue
        sample, annotation = line[:cut].rstrip(), line[cut + len(" # ") :]
        try:
            if "{" in sample:
                name, rest = sample.split("{", 1)
                label_text, _, _ = rest.rpartition("}")
                labels = _parse_labels(label_text)
            else:
                name, _, _ = sample.partition(" ")
                labels = {}
            ex_text, _, tail = annotation.lstrip("{").partition("}")
            ex_labels = _parse_labels(ex_text) if ex_text else {}
            parts = tail.split()
            value = float(parts[0].replace("+Inf", "inf"))
            ts = float(parts[1]) if len(parts) > 1 else None
        except Exception:
            continue
        out.append(
            {
                "name": name.strip(),
                "labels": labels,
                "exemplar": ex_labels,
                "value": value,
                "ts": ts,
            }
        )
    return out
