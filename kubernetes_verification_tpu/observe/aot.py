"""AOT kernel pack: warm start as a production SLO.

Compile+first-solve is the dominant term in failover (a promoted follower
recompiles every kernel before its first answer) and in ``--resume``
recovery. Long-running TPU systems amortize compilation by reusing
precompiled executables across runs (PAPERS.md: *Large Scale Distributed
Linear Algebra With Tensor Processing Units*); this module is that reuse,
built on the same abstract-shape signatures the recompile tracker
(``observe/jit.py``) and the cost introspector (``observe/introspect.py``)
already key on.

Three pieces:

* **Kernel manifest** — every jitted entry point registers once at module
  import (``register_kernel``; per-call jits like the sharded closure's
  shard_map use ``transient_kernel``) and is rebound to a
  :class:`WarmKernel` wrapper. Call sites are unchanged: the wrapper
  delegates to the jitted function whenever the warm path cannot apply
  (tracer operands from jit-in-jit calls, unbindable signatures, AOT
  disabled) and otherwise looks its cache key up first.

* **Content-addressed cache** — the key is the canonical repr of (engine,
  kernel, static arguments, operand pytree structure, per-leaf
  shape/dtype/weak-type, platform, device kind, device count, jax/jaxlib
  versions, XLA flags); the pack entry's filename is the key's sha256.
  A key mismatch of *any* component is a counted miss
  (``kvtpu_aot_cache_misses_total``) that falls back to a fresh compile —
  a serialized executable is never loaded under a non-matching key, so a
  stale pack can cost time but never correctness.

* **Warm executable pack** — ``save_pack`` AOT-compiles every recorded
  dispatch signature via ``jitted.lower(...).compile()``, serializes the
  executables (``jax.experimental.serialize_executable``) and writes them
  next to a checksummed ``PACK_MANIFEST.json``; ``load_pack`` verifies
  environment + per-entry payload digests and installs matching
  executables for the wrappers to serve. Corrupt or truncated entries
  degrade to a recompile with a warning — the pack path never raises into
  a solve.

``CheckpointManager`` ships the pack alongside its ``gen-N/`` snapshots
(``serve/durability.py``), so ``recover()``, follower bootstrap and
breaker-gated promotion restore *compiled* state; ``kv-tpu warmup``
pre-populates a pack for a config, and ``bench.py`` gates the warm-path
compile time so the cold-start walk can never silently return.

Everything here is fail-open: any error on the warm path is a warning, a
counted miss and a delegation to the ordinary jit dispatch.
"""
from __future__ import annotations

import hashlib
import inspect
import json
import os
import pickle
import threading
import warnings
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from .events import log_event
from .metrics import (
    AOT_CACHE_HITS_TOTAL,
    AOT_CACHE_MISSES_TOTAL,
    AOT_PACK_BYTES,
)

__all__ = [
    "PACK_DIRNAME",
    "PACK_MANIFEST_NAME",
    "WarmKernel",
    "aot_enabled",
    "set_aot",
    "register_kernel",
    "transient_kernel",
    "manifest",
    "current_env",
    "save_pack",
    "load_pack",
    "pack_status",
    "pack_dir",
    "drop_executables",
    "hit_total",
    "miss_total",
]

PACK_FORMAT = 1
PACK_DIRNAME = "aot-pack"
PACK_MANIFEST_NAME = "PACK_MANIFEST.json"

_ENV_FLAG = "KVTPU_AOT"

_lock = threading.RLock()
_enabled: Optional[bool] = None  # None = defer to the env var
#: every registered kernel, keyed by (engine, fn) — the kernel manifest
_MANIFEST: Dict[Tuple[str, str], "_KernelBase"] = {}
#: pack-loaded executables keyed by full cache key (exact-match only)
_LOADED: Dict[str, Any] = {}
#: serialized payload cache keyed by full cache key — lets repeated
#: checkpoints reship the pack without re-running ``.lower().compile()``
_PAYLOADS: Dict[str, bytes] = {}


# ---------------------------------------------------------------- gating
def aot_enabled() -> bool:
    if _enabled is not None:
        return _enabled
    return os.environ.get(_ENV_FLAG, "").lower() not in ("0", "false")


def set_aot(on: Optional[bool]) -> None:
    """Force the warm path on/off for this process (None = defer to the
    KVTPU_AOT env var again)."""
    global _enabled
    with _lock:
        _enabled = on if on is None else bool(on)


# ------------------------------------------------------- environment key
def current_env() -> Dict[str, Any]:
    """The environment fingerprint baked into every cache key: anything
    that can invalidate a serialized executable. Tests monkeypatch this to
    exercise the key-mismatch paths."""
    import jax
    import jaxlib

    try:
        dev = jax.devices()[0]
        platform, kind = dev.platform, dev.device_kind
    except Exception:  # uninitialisable backend — still key deterministically
        platform, kind = "unknown", "unknown"
    return {
        "platform": platform,
        "device_kind": kind,
        "num_devices": int(jax.device_count()),
        "jax": jax.__version__,
        "jaxlib": getattr(jaxlib, "__version__", "unknown"),
        "xla_flags": os.environ.get("XLA_FLAGS", ""),
    }


class _TracerSeen(Exception):
    """An operand is a tracer: the wrapper is being called inside another
    trace (jit-in-jit) — delegate straight to the jitted function."""


def _leaf_sig(x) -> Tuple:
    """Hashable, process-stable description of one operand leaf."""
    import jax

    if isinstance(x, jax.core.Tracer):
        raise _TracerSeen
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return (
            "a",
            tuple(int(d) for d in shape),
            str(dtype),
            bool(getattr(x, "weak_type", False)),
        )
    if isinstance(x, (bool, int, float, complex)):
        return ("s", type(x).__name__)
    return ("o", repr(x))


def _leaf_skel(x):
    """Operand leaf → lowering skeleton: arrays become ShapeDtypeStructs
    (no device buffers kept alive), scalars pass through verbatim."""
    import jax

    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return jax.ShapeDtypeStruct(
            tuple(int(d) for d in shape),
            dtype,
            weak_type=bool(getattr(x, "weak_type", False)),
        )
    return x


def _key_repr(
    engine: str, fn: str, statics: str, treedef: str, sig: Tuple
) -> str:
    env = tuple(sorted((k, str(v)) for k, v in current_env().items()))
    return repr((engine, fn, statics, treedef, sig, env))


def _key_id(key: str) -> str:
    return hashlib.sha256(key.encode()).hexdigest()


#: per-kernel table sentinel: this key was seen and must compile fresh
_FRESH = object()


class _KernelBase:
    """Shared warm-dispatch state for one manifest entry."""

    def __init__(self, engine: str, name: str) -> None:
        self.engine = engine
        self.name = name
        self._exes: Dict[str, Any] = {}  # key -> executable | _FRESH
        self._recorded: Dict[str, Tuple] = {}  # key -> lowering recipe

    # ------------------------------------------------------------ lookup
    def _serve(self, key: str) -> Any:
        """Executable for ``key`` or ``_FRESH``/None; installs (and counts
        a hit for) a pack-loaded executable on first use."""
        exe = self._exes.get(key)
        if exe is None:
            loaded = _LOADED.get(key)
            if loaded is not None:
                self._exes[key] = loaded
                AOT_CACHE_HITS_TOTAL.labels(
                    engine=self.engine, fn=self.name
                ).inc()
                return loaded
        return exe

    def _miss(self, reason: str) -> None:
        AOT_CACHE_MISSES_TOTAL.labels(
            engine=self.engine, fn=self.name, reason=reason
        ).inc()

    def _poison(self, key: str, err: Exception) -> None:
        """A served executable failed to run: warn, count, and pin the key
        to the fresh-compile path — degrade, never raise."""
        self._exes[key] = _FRESH
        self._miss("exec-error")
        warnings.warn(
            f"aot: packed executable for {self.engine}/{self.name} failed "
            f"({type(err).__name__}: {err}); recompiling fresh",
            RuntimeWarning,
            stacklevel=3,
        )
        log_event(
            "aot_exec_fallback",
            engine=self.engine,
            fn=self.name,
            error=f"{type(err).__name__}: {err}",
        )

    # ------------------------------------------------------------ packing
    def recorded_keys(self) -> List[str]:
        return list(self._recorded)

    def compile_recorded(self, key: str):
        """AOT-compile the recorded signature for ``key`` (the save_pack
        path; also caches the executable for this process)."""
        raise NotImplementedError

    def drop_executables(self) -> None:
        self._exes.clear()


class WarmKernel(_KernelBase):
    """Wrapper around one module-level jitted function.

    Canonical calling convention for the AOT artifacts: dynamic operands
    positional in signature order with statics keyword-bound whenever
    every dynamic parameter precedes every static one (the repo-wide
    kernel shape — and the form under which ``donate_argnums`` keeps its
    input/output aliasing through ``.lower()``); all-keyword otherwise.
    Statics are *stripped* when invoking a compiled executable — a
    ``Compiled`` rejects its static arguments outright.
    """

    def __init__(
        self,
        engine: str,
        name: str,
        jitted,
        static_argnames: Iterable[str] = (),
    ) -> None:
        super().__init__(engine, name)
        self.jitted = jitted
        self.static_argnames = frozenset(static_argnames)
        try:
            self._sig = inspect.signature(jitted)
        except (TypeError, ValueError):  # C-level callable, no signature
            self._sig = None
        self._bindable = self._sig is not None and not any(
            p.kind
            in (inspect.Parameter.VAR_POSITIONAL, inspect.Parameter.VAR_KEYWORD)
            for p in self._sig.parameters.values()
        )
        self._positional = False
        if self._bindable:
            params = list(self._sig.parameters.values())
            dyn_idx = [
                i for i, p in enumerate(params)
                if p.name not in self.static_argnames
            ]
            static_idx = [
                i for i, p in enumerate(params)
                if p.name in self.static_argnames
            ]
            kwonly_dyn = any(
                params[i].kind == inspect.Parameter.KEYWORD_ONLY
                for i in dyn_idx
            )
            self._positional = not kwonly_dyn and (
                not static_idx
                or not dyn_idx
                or max(dyn_idx) < min(static_idx)
            )

    def lower(self, *args, **kwargs):
        return self.jitted.lower(*args, **kwargs)

    def _plan(self, args, kwargs):
        """(key, dynamic kwargs, statics, skeleton) for this call, or None
        when the warm path cannot apply."""
        import jax

        if not self._bindable:
            return None
        try:
            bound = self._sig.bind(*args, **kwargs)
            bound.apply_defaults()
        except TypeError:
            return None
        statics: Dict[str, Any] = {}
        dyn_kw: Dict[str, Any] = {}
        for pname, val in bound.arguments.items():
            (statics if pname in self.static_argnames else dyn_kw)[pname] = val
        # bound.arguments preserves signature order, so under the
        # positional convention the values line up with the parameters
        dyn: Any = list(dyn_kw.values()) if self._positional else dyn_kw
        try:
            statics_key = repr(tuple(sorted(statics.items())))
        except Exception:
            return None
        try:
            leaves, treedef = jax.tree_util.tree_flatten(dyn)
            sig = tuple(_leaf_sig(x) for x in leaves)
        except _TracerSeen:
            return None
        except Exception:
            return None
        key = _key_repr(self.engine, self.name, statics_key, str(treedef), sig)
        return key, dyn, statics, (leaves, treedef)

    def __call__(self, *args, **kwargs):
        if not aot_enabled():
            return self.jitted(*args, **kwargs)
        plan = self._plan(args, kwargs)
        if plan is None:
            return self.jitted(*args, **kwargs)
        key, dyn, statics, (leaves, treedef) = plan
        exe = self._serve(key)
        if exe is not None and exe is not _FRESH:
            try:
                return exe(*dyn) if self._positional else exe(**dyn)
            except Exception as e:  # shape drift, corrupt program, ...
                self._poison(key, e)
                return self.jitted(*args, **kwargs)
        if exe is None:
            import jax

            self._miss("cold")
            self._exes[key] = _FRESH
            skel = jax.tree_util.tree_unflatten(
                treedef, [_leaf_skel(x) for x in leaves]
            )
            self._recorded[key] = (skel, dict(statics))
        return self.jitted(*args, **kwargs)

    def compile_recorded(self, key: str):
        skel, statics = self._recorded[key]
        if self._positional:
            lowered = self.jitted.lower(*skel, **statics)
        else:
            lowered = self.jitted.lower(**skel, **statics)
        compiled = lowered.compile()
        self._exes[key] = compiled
        return compiled


class TransientKernel(_KernelBase):
    """Manifest entry for jits constructed per call (the sharded closure
    jits a fresh ``shard_map`` closure per geometry). ``bind`` wraps one
    such jitted object; the cache key carries the construction parameters
    (``key_extras``) the closure baked in. Positional-only convention —
    these callables take operand pytrees positionally and have no static
    arguments of their own."""

    def bind(self, jitted, key_extras: Tuple = ()) -> Callable:
        import jax

        engine, name = self.engine, self.name

        def call(*args):
            if not aot_enabled():
                return jitted(*args)
            try:
                extras = repr(tuple(key_extras))
                leaves, treedef = jax.tree_util.tree_flatten(args)
                sig = tuple(_leaf_sig(x) for x in leaves)
            except Exception:
                return jitted(*args)
            key = _key_repr(engine, name, extras, str(treedef), sig)
            exe = self._serve(key)
            if exe is not None and exe is not _FRESH:
                try:
                    return exe(*args)
                except Exception as e:
                    self._poison(key, e)
                    return jitted(*args)
            if exe is None:
                self._miss("cold")
                self._exes[key] = _FRESH
                skel = jax.tree_util.tree_unflatten(
                    treedef, [_leaf_skel(x) for x in leaves]
                )
                self._recorded[key] = (jitted, skel)
            return jitted(*args)

        call.jitted = jitted
        return call

    def compile_recorded(self, key: str):
        jitted, skel = self._recorded[key]
        compiled = jitted.lower(*skel).compile()
        self._exes[key] = compiled
        return compiled


# ---------------------------------------------------------- registration
def register_kernel(
    engine: str,
    name: str,
    jitted,
    *,
    static_argnames: Iterable[str] = (),
) -> WarmKernel:
    """Register a module-level jitted entry point with the kernel manifest
    and return its :class:`WarmKernel` (rebind the module name to it:
    ``_f = register_kernel("eng", "_f", _f, ...)``). ``static_argnames``
    must mirror the jit decorator's — jax exposes no introspection for
    them on this version."""
    kernel = WarmKernel(engine, name, jitted, static_argnames)
    with _lock:
        _MANIFEST[(engine, name)] = kernel
    return kernel


def transient_kernel(
    engine: str, name: str, jitted, *, key_extras: Tuple = ()
) -> Callable:
    """Register (or reuse) a manifest entry for a per-call jit and return
    the warm-dispatch wrapper for this particular jitted object."""
    with _lock:
        entry = _MANIFEST.get((engine, name))
        if not isinstance(entry, TransientKernel):
            entry = TransientKernel(engine, name)
            _MANIFEST[(engine, name)] = entry
    return entry.bind(jitted, key_extras)


def manifest() -> Dict[Tuple[str, str], _KernelBase]:
    """The live kernel manifest (read-only view)."""
    with _lock:
        return dict(_MANIFEST)


def drop_executables() -> None:
    """Forget every in-process executable (per-kernel tables and the
    pack-loaded set). Recorded signatures and serialized payload caches
    survive — this is the bench/test hook that simulates a fresh process
    in front of an on-disk pack."""
    with _lock:
        _LOADED.clear()
        for kernel in _MANIFEST.values():
            kernel.drop_executables()


def hit_total() -> float:
    return sum(c.value for c in AOT_CACHE_HITS_TOTAL.children().values())


def miss_total() -> float:
    return sum(c.value for c in AOT_CACHE_MISSES_TOTAL.children().values())


# ------------------------------------------------------------- the pack
def pack_dir(checkpoint_dir: str) -> str:
    """Where the warm pack lives relative to a checkpoint directory."""
    return os.path.join(checkpoint_dir, PACK_DIRNAME)


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def _read_manifest(directory: str) -> Optional[dict]:
    path = os.path.join(directory, PACK_MANIFEST_NAME)
    try:
        with open(path) as fh:
            man = json.load(fh)
    except FileNotFoundError:
        return None
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        warnings.warn(
            f"aot: unreadable pack manifest {path} ({e}); ignoring pack",
            RuntimeWarning,
        )
        return None
    if not isinstance(man, dict) or not isinstance(man.get("entries"), list):
        warnings.warn(
            f"aot: malformed pack manifest {path}; ignoring pack",
            RuntimeWarning,
        )
        return None
    return man


def save_pack(directory: str) -> dict:
    """AOT-compile every recorded dispatch signature and persist the
    serialized executables into ``directory`` (incremental: entries whose
    key is already packed are reused, serialized payloads are cached
    in-process so repeated checkpoints don't recompile). Per-entry
    failures are warnings, never raises. Returns a summary dict."""
    from jax.experimental import serialize_executable

    os.makedirs(directory, exist_ok=True)
    existing = _read_manifest(directory)
    entries: Dict[str, dict] = {}
    if existing is not None:
        for ent in existing.get("entries", []):
            if isinstance(ent, dict) and "id" in ent:
                path = os.path.join(directory, f"{ent['id']}.kexe")
                if os.path.exists(path):
                    entries[ent["id"]] = ent
    env = current_env()
    compiled_n, skipped_n = 0, 0
    with _lock:
        kernels = list(_MANIFEST.values())
    for kernel in kernels:
        for key in kernel.recorded_keys():
            kid = _key_id(key)
            if kid in entries:
                continue
            blob = _PAYLOADS.get(key)
            if blob is None:
                try:
                    compiled = kernel.compile_recorded(key)
                    payload, in_tree, out_tree = serialize_executable.serialize(
                        compiled
                    )
                    blob = pickle.dumps((payload, in_tree, out_tree))
                except Exception as e:  # unserializable kernel — skip it
                    skipped_n += 1
                    log_event(
                        "aot_pack_skip",
                        engine=kernel.engine,
                        fn=kernel.name,
                        error=f"{type(e).__name__}: {e}",
                    )
                    continue
                _PAYLOADS[key] = blob
            try:
                _atomic_write(os.path.join(directory, f"{kid}.kexe"), blob)
            except OSError as e:
                skipped_n += 1
                warnings.warn(
                    f"aot: could not write pack entry for {kernel.engine}/"
                    f"{kernel.name}: {e}",
                    RuntimeWarning,
                )
                continue
            entries[kid] = {
                "id": kid,
                "engine": kernel.engine,
                "fn": kernel.name,
                "key": key,
                "payload_sha256": hashlib.sha256(blob).hexdigest(),
                "bytes": len(blob),
            }
            compiled_n += 1
    manifest_obj = {
        "format": PACK_FORMAT,
        "env": env,
        "entries": sorted(entries.values(), key=lambda e: e["id"]),
    }
    _atomic_write(
        os.path.join(directory, PACK_MANIFEST_NAME),
        (json.dumps(manifest_obj, sort_keys=True, indent=2) + "\n").encode(),
    )
    total_bytes = sum(int(e.get("bytes", 0)) for e in entries.values())
    AOT_PACK_BYTES.set(total_bytes)
    summary = {
        "directory": directory,
        "entries": len(entries),
        "new": compiled_n,
        "skipped": skipped_n,
        "bytes": total_bytes,
    }
    log_event("aot_pack_save", **summary)
    return summary


def load_pack(directory: str) -> dict:
    """Verify and install a warm pack: entries whose environment matches
    the current fingerprint *and* whose payload digest checks out are
    deserialized into the loaded-executable set (served by exact cache-key
    match only); anything else is a counted miss — environment drift under
    ``key-mismatch``, damage under ``corrupt`` — and a warning, never an
    error. Returns a summary dict."""
    from jax.experimental import serialize_executable

    summary = {
        "directory": directory,
        "present": False,
        "loaded": 0,
        "mismatched": 0,
        "corrupt": 0,
        "bytes": 0,
    }
    man = _read_manifest(directory)
    if man is None:
        return summary
    summary["present"] = True
    env = current_env()
    pack_env = man.get("env") or {}
    for ent in man.get("entries", []):
        if not isinstance(ent, dict) or "key" not in ent or "id" not in ent:
            summary["corrupt"] += 1
            continue
        engine = str(ent.get("engine", "?"))
        fn = str(ent.get("fn", "?"))
        if pack_env != env:
            # the executable was built for a different world — counted
            # miss, never loaded
            summary["mismatched"] += 1
            AOT_CACHE_MISSES_TOTAL.labels(
                engine=engine, fn=fn, reason="key-mismatch"
            ).inc()
            continue
        key = ent["key"]
        if key in _LOADED:
            summary["loaded"] += 1
            summary["bytes"] += int(ent.get("bytes", 0))
            continue
        path = os.path.join(directory, f"{ent['id']}.kexe")
        try:
            with open(path, "rb") as fh:
                blob = fh.read()
            if hashlib.sha256(blob).hexdigest() != ent.get("payload_sha256"):
                raise PersistenceDamage("payload digest mismatch")
            payload, in_tree, out_tree = pickle.loads(blob)
            exe = serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree
            )
        except Exception as e:
            summary["corrupt"] += 1
            AOT_CACHE_MISSES_TOTAL.labels(
                engine=engine, fn=fn, reason="corrupt"
            ).inc()
            warnings.warn(
                f"aot: pack entry {ent['id'][:12]}… ({engine}/{fn}) is "
                f"unusable ({type(e).__name__}: {e}); will recompile fresh",
                RuntimeWarning,
            )
            log_event(
                "aot_pack_corrupt",
                entry=ent["id"],
                engine=engine,
                fn=fn,
                error=f"{type(e).__name__}: {e}",
            )
            continue
        with _lock:
            _LOADED[key] = exe
            _PAYLOADS.setdefault(key, blob)
        summary["loaded"] += 1
        summary["bytes"] += len(blob)
    if summary["bytes"]:
        AOT_PACK_BYTES.set(summary["bytes"])
    log_event("aot_pack_load", **summary)
    return summary


class PersistenceDamage(Exception):
    """Internal marker for a pack entry that failed its digest check."""


def pack_status(directory: str) -> dict:
    """Read-only validity report for ``kv-tpu recover --json``: entry
    count, how many keys match the current environment, and per-entry
    damage — nothing is deserialized and no metrics move."""
    status: Dict[str, Any] = {
        "directory": directory,
        "present": False,
        "entries": 0,
        "env_match": False,
        "matching": 0,
        "mismatched": 0,
        "corrupt": 0,
        "bytes": 0,
    }
    man = _read_manifest(directory)
    if man is None:
        return status
    status["present"] = True
    env = current_env()
    pack_env = man.get("env") or {}
    status["env_match"] = pack_env == env
    status["pack_env"] = pack_env
    for ent in man.get("entries", []):
        if not isinstance(ent, dict) or "id" not in ent:
            status["corrupt"] += 1
            continue
        status["entries"] += 1
        path = os.path.join(directory, f"{ent['id']}.kexe")
        try:
            with open(path, "rb") as fh:
                blob = fh.read()
        except OSError:
            status["corrupt"] += 1
            continue
        if hashlib.sha256(blob).hexdigest() != ent.get("payload_sha256"):
            status["corrupt"] += 1
            continue
        status["bytes"] += len(blob)
        if status["env_match"]:
            status["matching"] += 1
        else:
            status["mismatched"] += 1
    return status
