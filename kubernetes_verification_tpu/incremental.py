"""Incremental re-verify on policy diffs (BASELINE config 5).

The reference hints at per-policy contribution tracking with
``Container.select_policies``/``allow_policies``
(``kano_py/kano/model.py:16-17,161-163``) but always rebuilds from scratch.
Here the decomposition is explicit: with any-port semantics the reachability
matrix is

    reach = ((Σ_p ing_peersₚ ⊗ sel_ingₚ > 0) ∨ ¬ing_iso)
          ∧ ((Σ_p sel_egₚ ⊗ eg_peersₚ > 0) ∨ ¬eg_iso)   ∨ diag

an OR over per-policy outer products. ``IncrementalVerifier`` keeps the *sum*
(int32 count matrices, device-resident) instead of the OR, so a policy
add/remove/update is one subtract + one add of a rank-1 outer product —
O(N²) work independent of the policy count (vs O(P·N²) for a rebuild) — and
pod label changes patch one row + one column of each count matrix. All
updates run as jitted device ops with donated buffers (no reallocation);
``reach`` re-derives from the counts on demand.

Scope: any-port semantics (the ``compute_ports=False`` mode, like the tiled
path); pod add/remove changes N and falls back to a rebuild.
"""
from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .backends.base import VerifyConfig
from .models.core import Cluster, NetworkPolicy, Pod
from .observe import DispatchTracker
from .observe.metrics import INCREMENTAL_OPS
from .resilience.retry import RetryPolicy, retry_transient

__all__ = ["IncrementalVerifier"]

_I32 = jnp.int32

#: jit caches are per-function and process-global — one tracker per module
_TRACKER = DispatchTracker("dense")


@partial(jax.jit, donate_argnums=(0,))
def _rank1_add(count, src, dst, sign):
    """count += sign · src ⊗ dst (int32, donated in place)."""
    return count + sign * (src.astype(_I32)[:, None] * dst.astype(_I32)[None, :])


@partial(jax.jit, donate_argnums=(0,))
def _row_col_patch(count, idx, d_row, d_col):
    """Add deltas to row ``idx`` and column ``idx`` of a count matrix. The
    (idx, idx) cell must be carried by ``d_row`` only (``d_col[idx] == 0``)."""
    count = count.at[idx, :].add(d_row.astype(_I32))
    count = count.at[:, idx].add(d_col.astype(_I32))
    return count


@partial(
    jax.jit,
    static_argnames=("self_traffic", "default_allow_unselected"),
)
def _derive_reach(
    ing_count,
    eg_count,
    ing_iso_count,
    eg_iso_count,
    *,
    self_traffic: bool,
    default_allow_unselected: bool,
):
    ing_ok = ing_count > 0
    eg_ok = eg_count > 0
    if default_allow_unselected:
        ing_ok |= ing_iso_count[None, :] == 0
        eg_ok |= eg_iso_count[:, None] == 0
    reach = ing_ok & eg_ok
    if self_traffic:
        n = reach.shape[0]
        reach |= jnp.eye(n, dtype=bool)
    return reach


class IncrementalVerifier:
    """Maintains a cluster's reachability under policy/pod-label diffs."""

    #: engine label on kvtpu_incremental_ops_total et al.; methods the
    #: engines share (namespace bookkeeping below) label per-class via this
    metrics_engine = "dense"
    #: transient-failure budget around the jitted reach derivation;
    #: assign a tuned RetryPolicy on the instance to change it
    retry_policy = RetryPolicy()

    def _count_op(self, op: str) -> None:
        INCREMENTAL_OPS.labels(engine=self.metrics_engine, op=op).inc()

    def __init__(
        self,
        cluster: Cluster,
        config: Optional[VerifyConfig] = None,
        device=None,
    ) -> None:
        self.config = config or VerifyConfig()
        self.device = device or jax.devices()[0]
        # deep-copy pods: update_pod_labels mutates labels in place, and the
        # verifier must not silently rewrite the caller's Cluster
        self.pods: List[Pod] = [
            dataclasses.replace(
                p, labels=dict(p.labels), container_ports=dict(p.container_ports)
            )
            for p in cluster.pods
        ]
        self.namespaces = list(cluster.namespaces)
        self.policies: Dict[str, NetworkPolicy] = {}
        n = len(self.pods)
        self._ing_count, self._eg_count = self._alloc_counts(n)
        self._ing_iso = np.zeros(n, dtype=np.int64)
        self._eg_iso = np.zeros(n, dtype=np.int64)
        #: per-policy contribution vectors (host copies, bool [N])
        self._vectors: Dict[str, Tuple[np.ndarray, ...]] = {}
        self._reach_dirty = True
        self._reach = None
        self.update_count = 0
        self._batch_init(cluster)

    def _alloc_counts(self, n: int):
        """Empty device count matrices for ``n`` pods. The one allocation
        hook subclasses with partial row ownership override — the stripe
        engine (``serve/stripes.py``) returns [S, N] row stripes here so
        no [N, N] operand ever exists in its process."""
        return (
            jnp.zeros((n, n), dtype=_I32, device=self.device),
            jnp.zeros((n, n), dtype=_I32, device=self.device),
        )

    def _batch_init(self, cluster: Cluster) -> None:
        """Initial build: one encoder pass + one batched device contraction
        (P rank-1 updates collapsed into two [P,N]×[P,N] matmuls). The frozen
        encoding also seeds the :class:`~.packed_incremental.PolicyVectorizer`
        that later policy diffs re-encode through."""
        from .encode.encoder import cluster_vocab, encode_cluster
        from .ops.tiled import _grant_peers_full
        from .packed_incremental import PolicyVectorizer

        snapshot = Cluster(
            pods=self.pods, namespaces=self.namespaces,
            policies=list(cluster.policies),
        )
        # label dicts are COPIED: an aliased caller dict mutated in place
        # would satisfy the relabel no-op guard and silently skip the
        # re-derivation (pods are deep-copied for the same reason)
        self._ns_labels = {
            ns.name: dict(ns.labels) for ns in self.namespaces
        }

        def seed_vectorizer(vocab) -> None:
            self._vectorizer = PolicyVectorizer(
                self.pods,
                self._ns_labels,
                vocab,
                {ns.name: i for i, ns in enumerate(self.namespaces)},
                self.config.direction_aware_isolation,
            )

        if not cluster.policies:
            # nothing to solve: skip the full encode (its [N, V] label
            # matrices and grant stacks feed only the batch contraction) —
            # the vectorizer needs just the vocab
            seed_vectorizer(cluster_vocab(self.pods, self.namespaces))
            return
        enc = encode_cluster(snapshot, compute_ports=False)
        seed_vectorizer(enc.vocab)
        P, n = enc.n_policies, enc.n_pods
        cfg = self.config

        @jax.jit
        def build(pod_kv, pod_key, pod_ns, ns_kv, ns_key, pol_sel, pol_ns,
                  aff_i, aff_e, ingress, egress):
            from .ops.match import match_selectors

            selected = match_selectors(pol_sel, pod_kv, pod_key)
            selected &= pol_ns[:, None] == pod_ns[None, :]
            if cfg.direction_aware_isolation:
                sel_ing = selected & aff_i[:, None]
                sel_eg = selected & aff_e[:, None]
            else:
                sel_ing = selected
                sel_eg = selected
            ip = _grant_peers_full(
                ingress, pod_kv, pod_key, ns_kv, ns_key, pod_ns, pol_ns
            )
            ep = _grant_peers_full(
                egress, pod_kv, pod_key, ns_kv, ns_key, pod_ns, pol_ns
            )
            seg = lambda v, s: jnp.clip(
                jax.ops.segment_max(v.astype(jnp.int8), s, num_segments=P + 1)[:P],
                0, 1,
            ).astype(bool)
            ing_peers = seg(ip, ingress.pol)
            eg_peers = seg(ep, egress.pol)
            if cfg.direction_aware_isolation:
                ing_peers &= aff_i[:, None]
                eg_peers &= aff_e[:, None]
            ing_c, eg_c = self._contract_counts(
                sel_ing, sel_eg, ing_peers, eg_peers
            )
            return ing_c, eg_c, sel_ing, sel_eg, ing_peers, eg_peers

        args = jax.device_put(
            (
                enc.pod_kv, enc.pod_key, enc.pod_ns, enc.ns_kv, enc.ns_key,
                enc.pol_sel, enc.pol_ns, enc.pol_affects_ingress,
                enc.pol_affects_egress, enc.ingress, enc.egress,
            ),
            self.device,
        )
        ing_c, eg_c, sel_ing, sel_eg, ing_peers, eg_peers = build(*args)
        self._ing_count = ing_c
        self._eg_count = eg_c
        sel_ing = np.asarray(sel_ing)
        sel_eg = np.asarray(sel_eg)
        ing_peers = np.asarray(ing_peers)
        eg_peers = np.asarray(eg_peers)
        self._ing_iso = sel_ing.sum(axis=0, dtype=np.int64)
        self._eg_iso = sel_eg.sum(axis=0, dtype=np.int64)
        for i, pol in enumerate(cluster.policies):
            key = self._key(pol)
            if key in self.policies:
                raise KeyError(f"duplicate policy {key}")
            self.policies[key] = pol
            self._vectors[key] = (
                sel_ing[i].copy(), sel_eg[i].copy(),
                ing_peers[i].copy(), eg_peers[i].copy(),
            )

    @staticmethod
    def _count_dot(a, b):
        """The count contraction: int8 policy-axis matmul accumulating to
        int32 (traced — called inside the init build jit)."""
        return jax.lax.dot_general(
            a.astype(jnp.int8), b.astype(jnp.int8),
            (((0,), (0,)), ((), ())), preferred_element_type=_I32,
        )

    def _contract_counts(self, sel_ing, sel_eg, ing_peers, eg_peers):
        """Collapse P rank-1 contributions into the two count matrices
        (traced, inside the build jit). The stripe engine overrides this
        to slice the source axis BEFORE the contraction, so the [N, N]
        products are never formed in a striped process."""
        return (
            self._count_dot(ing_peers, sel_ing),
            self._count_dot(sel_eg, eg_peers),
        )

    # ---------------------------------------------------------------- diffs
    def _key(self, pol: NetworkPolicy) -> str:
        return f"{pol.namespace}/{pol.name}"

    def _policy_vectors(
        self, pol: NetworkPolicy
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """(sel_ing, sel_eg, ing_peers, eg_peers) bool [N] for one policy —
        re-encoded against the frozen init-time encoding and evaluated with
        the batch match/peer kernels on device (label-drifted pods fixed up
        on host), replacing the old per-rule × per-peer × per-pod Python
        loops. Semantics are the CPU oracle's (``backends/cpu.py``)."""
        return tuple(
            np.asarray(v, dtype=bool) for v in self._vectorizer.vectors(pol)
        )

    def _apply(self, vecs, sign: int) -> None:
        sel_ing, sel_eg, ing_peers, eg_peers = (jnp.asarray(v) for v in vecs)
        _TRACKER.track(
            "_rank1_add",
            self._ing_count,
            ing_peers,
            sel_ing,
            lower=lambda: _rank1_add.lower(
                self._ing_count, ing_peers, sel_ing, sign
            ),
        )
        self._ing_count = _rank1_add(self._ing_count, ing_peers, sel_ing, sign)
        self._eg_count = _rank1_add(self._eg_count, sel_eg, eg_peers, sign)
        self._ing_iso += sign * np.asarray(vecs[0], dtype=np.int64)
        self._eg_iso += sign * np.asarray(vecs[1], dtype=np.int64)
        self._reach_dirty = True
        self.update_count += 1

    def add_policy(self, pol: NetworkPolicy) -> None:
        key = self._key(pol)
        if key in self.policies:
            raise KeyError(f"policy {key} exists; use update_policy")
        if pol.namespace not in self._ns_labels:
            self._ns_labels[pol.namespace] = {}
        vecs = self._policy_vectors(pol)
        self.policies[key] = pol
        self._vectors[key] = vecs
        self._apply(vecs, +1)
        self._count_op("policy_add")

    def remove_policy(self, namespace: str, name: str) -> None:
        key = f"{namespace}/{name}"
        pol = self.policies.pop(key)  # KeyError if absent
        vecs = self._vectors.pop(key)
        self._apply(vecs, -1)
        self._count_op("policy_remove")

    def update_policy(self, pol: NetworkPolicy) -> None:
        self.remove_policy(pol.namespace, pol.name)
        self.add_policy(pol)

    def update_pod_labels(self, idx: int, labels: Dict[str, str]) -> None:
        """Relabel pod ``idx``: every policy's contribution through this pod
        is patched — one row + one column of each count matrix, O(P + N)
        host work and O(N) device writes."""
        pod = self.pods[idx]
        n = len(self.pods)

        def row_col_sums():
            """(ing_row, ing_col, eg_row, eg_col, iso_i, iso_e): Σ_p
            contributions through pod ``idx``, from the current vectors.
            ing_count[src, dst] = Σ ing_peers[src]·sel_ing[dst] so its row
            idx is Σ ing_peers[idx]·sel_ing[:], its col idx (corner zeroed)
            Σ sel_ing[idx]·ing_peers[:]; egress is the mirror."""
            ing_row = np.zeros(n, dtype=np.int64)
            ing_col = np.zeros(n, dtype=np.int64)
            eg_row = np.zeros(n, dtype=np.int64)
            eg_col = np.zeros(n, dtype=np.int64)
            iso_i = 0
            iso_e = 0
            for vec in self._vectors.values():
                sel_ing, sel_eg, ing_peers, eg_peers = vec
                if ing_peers[idx]:
                    ing_row += sel_ing
                if sel_ing[idx]:
                    ing_col += ing_peers
                    iso_i += 1
                if sel_eg[idx]:
                    eg_row += eg_peers
                    iso_e += 1
                if eg_peers[idx]:
                    eg_col += sel_eg
            ing_col[idx] = 0  # corner lives in the row sums
            eg_col[idx] = 0
            return ing_row, ing_col, eg_row, eg_col, iso_i, iso_e

        old = row_col_sums()
        pod.labels = dict(labels)
        # re-index the pod in the vectorizer (or dirty-mark it when its new
        # labels fall outside the frozen universe) so later policy
        # re-encodes see the change
        self._vectorizer.note_pod(idx)
        from .packed_incremental import pod_policy_flags

        for key, pol in self.policies.items():
            flags = pod_policy_flags(
                pol, pod, self._ns_labels, self.config.direction_aware_isolation
            )
            for vec, f in zip(self._vectors[key], flags):
                vec[idx] = f
        new = row_col_sums()
        self._patch_row_col(
            idx,
            new[0] - old[0], new[1] - old[1],
            new[2] - old[2], new[3] - old[3],
        )
        self._ing_iso[idx] += new[4] - old[4]
        self._eg_iso[idx] += new[5] - old[5]
        self._reach_dirty = True
        self.update_count += 1
        self._count_op("pod_relabel")

    def _patch_row_col(
        self,
        idx: int,
        d_ing_row: np.ndarray,
        d_ing_col: np.ndarray,
        d_eg_row: np.ndarray,
        d_eg_col: np.ndarray,
    ) -> None:
        """Apply one relabel's count deltas on device: row ``idx`` and
        column ``idx`` of both matrices (the (idx, idx) corner rides the
        row deltas — ``d_*_col[idx] == 0`` by construction). The stripe
        engine overrides this: the row patch lands only on the owning
        stripe (at its local offset) while the column slice lands on
        every stripe."""
        d_row = jnp.asarray(d_ing_row, dtype=_I32)
        d_col = jnp.asarray(d_ing_col, dtype=_I32)
        _TRACKER.track(
            "_row_col_patch",
            self._ing_count,
            lower=lambda: _row_col_patch.lower(
                self._ing_count, idx, d_row, d_col
            ),
        )
        self._ing_count = _row_col_patch(self._ing_count, idx, d_row, d_col)
        self._eg_count = _row_col_patch(
            self._eg_count, idx,
            jnp.asarray(d_eg_row, dtype=_I32),
            jnp.asarray(d_eg_col, dtype=_I32),
        )

    # ----------------------------------------------------------- namespaces
    # registration bookkeeping (live _ns_labels dict + namespaces list +
    # vectorizer ns row) is identical across engines — share the packed
    # engine's implementations rather than keeping three copies in sync
    def _shared_ns(name):
        from .packed_incremental import PackedIncrementalVerifier

        return getattr(PackedIncrementalVerifier, name)

    add_namespace = _shared_ns("add_namespace")
    _set_ns_labels = _shared_ns("_set_ns_labels")
    del _shared_ns

    def update_namespace_labels(
        self, name: str, labels: Dict[str, str]
    ) -> None:
        """Relabel namespace ``name``: namespaceSelector peer matches can
        move for EVERY policy, so this small-N oracle engine simply
        re-derives each policy's vectors and swaps the changed ones —
        clarity over cleverness (the packed engines own the batched form)."""
        if name not in self._ns_labels:
            raise KeyError(f"namespace {name} is not registered")
        if dict(self._ns_labels[name]) == dict(labels):
            return
        self._set_ns_labels(name, labels)
        for key, pol in self.policies.items():
            old = self._vectors[key]
            new = self._policy_vectors(pol)
            if any((a != b).any() for a, b in zip(old, new)):
                self._apply(old, -1)
                self._apply(new, +1)
                self._vectors[key] = new
        self._count_op("namespace_relabel")

    def remove_namespace(self, name: str) -> None:
        """Same contract as the packed engines' (this engine has no pod
        churn, so only resident policies can block the removal)."""
        if name not in self._ns_labels:
            raise KeyError(f"namespace {name} is not registered")
        pols = [k for k in self.policies if k.split("/", 1)[0] == name]
        if pols:
            raise ValueError(
                f"namespace {name} still holds {len(pols)} polic(ies); "
                "remove them before removing the namespace"
            )
        if any(p.namespace == name for p in self.pods):
            raise ValueError(
                f"namespace {name} still holds pods; this engine cannot "
                "remove them — rebuild without the namespace"
            )
        del self._ns_labels[name]
        self.namespaces = [ns for ns in self.namespaces if ns.name != name]
        self._count_op("namespace_remove")

    # --------------------------------------------------------------- result
    @property
    def reach(self) -> np.ndarray:
        """Current reachability matrix (derived from counts on demand)."""
        if self._reach_dirty:
            t0 = time.perf_counter()
            _TRACKER.track(
                "_derive_reach",
                self._ing_count,
                static=(
                    self.config.self_traffic,
                    self.config.default_allow_unselected,
                ),
                lower=lambda: _derive_reach.lower(
                    self._ing_count,
                    self._eg_count,
                    jnp.asarray(self._ing_iso, dtype=_I32),
                    jnp.asarray(self._eg_iso, dtype=_I32),
                    self_traffic=self.config.self_traffic,
                    default_allow_unselected=self.config.default_allow_unselected,
                ),
            )
            self._reach = np.asarray(
                retry_transient(
                    lambda: _derive_reach(
                        self._ing_count,
                        self._eg_count,
                        jnp.asarray(self._ing_iso, dtype=_I32),
                        jnp.asarray(self._eg_iso, dtype=_I32),
                        self_traffic=self.config.self_traffic,
                        default_allow_unselected=self.config.default_allow_unselected,
                    ),
                    policy=self.retry_policy,
                    backend=self.metrics_engine,
                )
            )
            self._derive_time = time.perf_counter() - t0
            self._reach_dirty = False
        return self._reach

    def as_cluster(self) -> Cluster:
        """Snapshot of the current state as a plain Cluster (for full-solve
        cross-checks and checkpointing)."""
        return Cluster(
            pods=[Pod(p.name, p.namespace, dict(p.labels), p.ip, dict(p.container_ports)) for p in self.pods],
            namespaces=list(self.namespaces),
            policies=list(self.policies.values()),
        )
