"""The lint core: rules, findings, per-file contexts, and the runner.

One framework replaces the three ad-hoc AST scripts that accreted across
PRs 1–5 (``check_error_taxonomy``, ``check_metrics_names``'s name lint,
the ``serve/durability.py`` atomic-write pass): every rule walks the SAME
parse of each file, reports through the same :class:`Finding` shape, honours
the same inline suppressions, and is budgeted by the same
``LINT_BASELINE.json`` (:mod:`.baseline`).

Design points:

* **Pure AST.** Nothing under lint is imported, so the whole framework runs
  without JAX and can lint arbitrary source strings (the test fixtures do).
* **Shared parse.** Each file is parsed once into a :class:`FileContext`
  (tree + parent links + suppression table); rules never re-parse.
* **Inline suppressions.** ``# kvtpu: ignore[rule-id]`` on a line (or on
  its own line, covering the next) silences that rule there; a reason
  string after the bracket is encouraged. Stale suppressions are themselves
  findings (``unused-suppression``) so ignores rot loudly.
* **Two rule scopes.** ``check(ctx)`` sees one file; ``check_project(ctxs)``
  runs once over every context for cross-file contracts (e.g. a metric
  family registered in one module but missing from ``REQUIRED_FAMILIES``).
"""
from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

__all__ = [
    "Finding",
    "FileContext",
    "Rule",
    "RULES",
    "register",
    "rule_ids",
    "build_context",
    "iter_package_files",
    "package_root",
    "repo_root",
    "LintResult",
    "run_lint",
    "lint_source",
    "UNUSED_SUPPRESSION",
]

#: the synthetic rule id findings about stale ignores are reported under —
#: not suppressible (an ignore of the ignore-checker defeats the point)
UNUSED_SUPPRESSION = "unused-suppression"

_SUPPRESS_RE = re.compile(r"#\s*kvtpu:\s*ignore\[([^\]]+)\]")
_RULE_ID_RE = re.compile(r"^[a-z][a-z0-9-]*$")


@dataclass(frozen=True)
class Finding:
    """One lint hit: rule id, package-relative path, 1-based line, message."""

    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }


@dataclass
class FileContext:
    """One file's shared lint state: source, parse tree, parent links, and
    the suppression table (line → rule ids, with per-entry use tracking)."""

    rel: str
    source: str
    tree: Optional[ast.AST]
    #: parse failure, when ``tree`` is None
    syntax_error: Optional[str] = None
    #: line → rule ids suppressed there
    suppressions: Dict[int, List[str]] = field(default_factory=dict)
    #: (line, rule) pairs that actually silenced a finding
    used_suppressions: set = field(default_factory=set)
    #: child AST node (by id) → parent node, for context-sensitive rules
    parents: Dict[int, ast.AST] = field(default_factory=dict)
    #: the interprocedural :class:`~.summaries.Program` for the run this
    #: context belongs to (attached by :func:`run_lint`; None in isolation)
    program: Optional[object] = None

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(id(node))

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(id(node))
        while cur is not None:
            yield cur
            cur = self.parents.get(id(cur))

    def is_suppressed(self, finding: Finding) -> bool:
        rules = self.suppressions.get(finding.line, ())
        if finding.rule in rules:
            self.used_suppressions.add((finding.line, finding.rule))
            return True
        return False


class Rule:
    """Base class: subclass, set the metadata, implement ``check`` and/or
    ``check_project``, and decorate with :func:`register`. The metadata is
    load-bearing — ``LINTS.md`` is generated from it (``report.catalog``)."""

    #: stable kebab-case id — the suppression / --rules / baseline key
    id: str = ""
    #: one-paragraph why (rendered into LINTS.md)
    rationale: str = ""
    #: a minimal flagged snippet (rendered into LINTS.md)
    example: str = ""

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def check_project(self, ctxs: Sequence[FileContext]) -> Iterable[Finding]:
        return ()


#: id → rule instance, in registration order (catalog order)
RULES: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and index a :class:`Rule` by id."""
    rule = cls()
    if not _RULE_ID_RE.match(rule.id or ""):
        raise AssertionError(f"bad rule id: {rule.id!r}")
    if rule.id in RULES:
        raise AssertionError(f"duplicate rule id: {rule.id}")
    RULES[rule.id] = rule
    return cls


def rule_ids() -> List[str]:
    return list(RULES)


# --------------------------------------------------------------- contexts
def _parse_suppressions(source: str) -> Dict[int, List[str]]:
    """``# kvtpu: ignore[a, b] reason`` → {target_line: [a, b]}. A comment
    sharing a line with code covers that line; a comment-only line covers
    the next line (so a suppression can sit above a long statement).
    Tokenized, not regexed, so the pattern inside a string literal (a
    docstring showing the syntax, this very function) is never a
    suppression."""
    table: Dict[int, List[str]] = {}
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return table  # unparsable files already report parse-error
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        ids = [t.strip() for t in m.group(1).split(",") if t.strip()]
        lineno = tok.start[0]
        own_line = tok.line.lstrip().startswith("#")
        target = lineno + 1 if own_line else lineno
        table.setdefault(target, []).extend(ids)
    return table


def build_context(rel: str, source: str) -> FileContext:
    try:
        tree = ast.parse(source, filename=rel)
    except SyntaxError as e:
        return FileContext(
            rel=rel, source=source, tree=None,
            syntax_error=f"line {e.lineno}: {e.msg}",
            suppressions=_parse_suppressions(source),
        )
    ctx = FileContext(
        rel=rel, source=source, tree=tree,
        suppressions=_parse_suppressions(source),
    )
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            ctx.parents[id(child)] = parent
    return ctx


# ------------------------------------------------------------- file walks
def package_root() -> str:
    """The installed ``kubernetes_verification_tpu`` directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def repo_root() -> str:
    """One level above the package — where ``LINT_BASELINE.json`` lives."""
    return os.path.dirname(package_root())


def iter_package_files(root: Optional[str] = None) -> List[Tuple[str, str]]:
    """(relative-posix-path, absolute-path) for every ``.py`` under
    ``root`` (default: the package), sorted, skipping ``__pycache__``."""
    base = root or package_root()
    if os.path.isfile(base):
        return [(os.path.basename(base), os.path.abspath(base))]
    out: List[Tuple[str, str]] = []
    for dirpath, dirs, files in os.walk(base):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, base).replace(os.sep, "/")
            out.append((rel, path))
    return out


# ----------------------------------------------------------------- runner
@dataclass
class LintResult:
    """The runner's verdict. ``findings`` are actionable (exit 1 when
    non-empty); ``grandfathered``/``suppressed`` are kept for reporting and
    for the baseline-shrink machinery; ``counts`` is the post-suppression,
    pre-baseline tally the monotonicity test and ``--update-baseline``
    read."""

    findings: List[Finding]
    grandfathered: List[Finding]
    suppressed: List[Finding]
    #: rule → path → count (after inline suppression, before baseline)
    counts: Dict[str, Dict[str, int]]

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "findings": [f.to_dict() for f in self.findings],
            "grandfathered": len(self.grandfathered),
            "suppressed": len(self.suppressed),
            "counts": self.counts,
        }


def _select_rules(rules: Optional[Sequence[str]]) -> List[Rule]:
    # rule modules register on import; pull them in exactly once here so
    # `from analysis.core import run_lint` alone is enough
    from . import (  # noqa: F401
        rules_hygiene,
        rules_interproc,
        rules_jax,
        rules_metrics,
    )

    if rules is None:
        return list(RULES.values())
    unknown = [r for r in rules if r not in RULES]
    if unknown:
        from ..resilience.errors import ConfigError

        raise ConfigError(
            f"unknown rule id(s): {', '.join(unknown)} "
            f"(known: {', '.join(RULES)})"
        )
    return [RULES[r] for r in rules]


def run_lint(
    sources: Mapping[str, str],
    rules: Optional[Sequence[str]] = None,
    baseline: Optional[Mapping[str, Mapping[str, int]]] = None,
    cache_path: Optional[str] = None,
) -> LintResult:
    """Lint ``{relative-path: source}`` with the selected rules.

    Pipeline: parse each file once → build the interprocedural program
    (callgraph + summaries, attached to every context) → per-file rules →
    project rules → inline suppressions (marking each one used) →
    stale-suppression findings → baseline budgets (a file's per-rule count
    at or under its budget is grandfathered wholesale; over budget, every
    site reports). ``cache_path`` enables the local-summary cache."""
    selected = _select_rules(rules)
    ctxs = [build_context(rel, src) for rel, src in sources.items()]
    by_rel = {c.rel: c for c in ctxs}
    parsed = [c for c in ctxs if c.tree is not None]

    from .summaries import build_program

    program = build_program(parsed, cache_path=cache_path)
    for ctx in parsed:
        ctx.program = program

    raw: List[Finding] = []
    for ctx in ctxs:
        if ctx.tree is None:
            raw.append(
                Finding(
                    "parse-error", ctx.rel, 1,
                    f"file does not parse: {ctx.syntax_error}",
                )
            )
            continue
        for rule in selected:
            raw.extend(rule.check(ctx))
    for rule in selected:
        raw.extend(rule.check_project(parsed))

    suppressed: List[Finding] = []
    kept: List[Finding] = []
    for f in raw:
        ctx = by_rel.get(f.path)
        if ctx is not None and f.rule != UNUSED_SUPPRESSION and ctx.is_suppressed(f):
            suppressed.append(f)
        else:
            kept.append(f)

    checking_stale = rules is None or UNUSED_SUPPRESSION in rules
    if checking_stale:
        for ctx in ctxs:
            for line, ids in sorted(ctx.suppressions.items()):
                for rid in ids:
                    if (line, rid) in ctx.used_suppressions:
                        continue
                    kept.append(
                        Finding(
                            UNUSED_SUPPRESSION, ctx.rel, line,
                            f"suppression `kvtpu: ignore[{rid}]` silenced "
                            "nothing — the finding moved or was fixed; "
                            "delete the comment",
                        )
                    )

    counts: Dict[str, Dict[str, int]] = {}
    for f in kept:
        counts.setdefault(f.rule, {}).setdefault(f.path, 0)
        counts[f.rule][f.path] += 1

    findings: List[Finding] = []
    grandfathered: List[Finding] = []
    baseline = baseline or {}
    for f in sorted(kept, key=lambda x: (x.path, x.line, x.rule)):
        budget = baseline.get(f.rule, {}).get(f.path)
        n = counts[f.rule][f.path]
        if budget is not None and n <= budget:
            grandfathered.append(f)
        elif budget is not None:
            findings.append(
                Finding(
                    f.rule, f.path, f.line,
                    f.message + f" [{n} sites exceed the grandfathered "
                    f"budget of {budget}]",
                )
            )
        else:
            findings.append(f)
    return LintResult(findings, grandfathered, suppressed, counts)


def lint_source(
    source: str,
    path: str = "<string>.py",
    rules: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Lint one source string (no baseline) — the fixture-test entry point."""
    return run_lint({path: source}, rules=rules).findings


def run_package(
    rules: Optional[Sequence[str]] = None,
    baseline: Optional[Mapping[str, Mapping[str, int]]] = None,
    root: Optional[str] = None,
    cache_path: Optional[str] = None,
    only: Optional[Sequence[str]] = None,
) -> LintResult:
    """Lint every ``.py`` file in the package (or under ``root``).

    ``only`` restricts *reporting* to the given relative paths while the
    whole package is still parsed and summarised — interprocedural rules
    need the full program even when only a few files changed."""
    sources = {}
    for rel, path in iter_package_files(root):
        with open(path, "r") as fh:
            sources[rel] = fh.read()
    result = run_lint(
        sources, rules=rules, baseline=baseline, cache_path=cache_path
    )
    if only is None:
        return result
    keep = set(only)
    return LintResult(
        findings=[f for f in result.findings if f.path in keep],
        grandfathered=[f for f in result.grandfathered if f.path in keep],
        suppressed=[f for f in result.suppressed if f.path in keep],
        counts={
            rule: {p: n for p, n in by_path.items() if p in keep}
            for rule, by_path in result.counts.items()
        },
    )
