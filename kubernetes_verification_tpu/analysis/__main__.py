"""Headless entry point: ``python -m kubernetes_verification_tpu.analysis``
runs the same lint driver as ``kv-tpu lint`` (identical flags, identical
exit codes) without importing the CLI or any backend."""
from __future__ import annotations

import sys

from . import main

if __name__ == "__main__":
    sys.exit(main())
