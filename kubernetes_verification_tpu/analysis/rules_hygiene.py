"""Hygiene rules: the error-taxonomy / bare-except / atomic-write checks
ported from the ad-hoc scripts, plus the concurrency rules PR 2's watchdog
bug motivated.

``atomic-write`` is the generalisation the durability work earned: the old
script only watched ``serve/durability.py``, but a torn half-written file is
a torn half-written file wherever it happens — any function that opens a
path for writing without promoting via ``os.replace`` re-opens the window
PR 5's kill-point fuzz exists to close. Appends (WAL/JSONL logs) are
flagged too: an append CAN be the right design when the reader tolerates a
torn tail (``scan_wal`` truncates), but that is a per-site judgement call,
recorded as an inline ``# kvtpu: ignore[atomic-write]`` with the reason.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from .core import FileContext, Finding, Rule, register

__all__ = [
    "DISALLOWED_RAISES",
    "ALWAYS_ALLOWED_RAISES",
    "WRITE_MODE_CHARS",
]

#: builtins whose raise sites the KvTpuError taxonomy replaces
DISALLOWED_RAISES = frozenset({
    "ValueError",
    "RuntimeError",
    "KeyError",
    "TypeError",
    "Exception",
    "BaseException",
    "OSError",
    "IOError",
    "IndexError",
    "LookupError",
    "ArithmeticError",
})

#: idioms the taxonomy does not absorb (always fine to raise)
ALWAYS_ALLOWED_RAISES = frozenset({
    "SystemExit",
    "NotImplementedError",
    "AssertionError",
    "ImportError",
    "ModuleNotFoundError",
    "StopIteration",
    "AttributeError",
})

#: open() modes that create or mutate bytes on disk
WRITE_MODE_CHARS = frozenset("wax+")


def walk_own(fn: ast.AST) -> Iterable[ast.AST]:
    """``ast.walk`` that does not descend into nested function/class
    bodies — per-function rules (atomic-write) must not attribute a nested
    def's statements to its enclosing function as well."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _last_name(node: ast.expr) -> Optional[str]:
    """Terminal identifier of a Name/Attribute chain (``a.b.c`` → ``c``)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _dotted(node: ast.expr) -> Optional[str]:
    """``a.b.c`` → ``"a.b.c"`` when the chain is pure Name/Attribute."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@register
class ErrorTaxonomyRule(Rule):
    id = "error-taxonomy"
    rationale = (
        "Package code must raise `KvTpuError` subclasses "
        "(`resilience/errors.py`), not bare builtins: a bare `ValueError` "
        "three layers deep cannot be mapped to the CLI exit-code contract "
        "(0 ok / 1 violations / 2 input error / 3 backend failure) and "
        "never carries `transient`/`kind` for the retry/fallback driver. "
        "Engine/model layers that expose `KeyError`/`ValueError` as their "
        "documented API contract are grandfathered in `LINT_BASELINE.json` "
        "(budgets shrink, never grow)."
    )
    example = 'raise ValueError("bad tile size")'

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Raise) and node.exc is not None):
                continue
            exc = node.exc
            name = None
            if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name):
                name = exc.func.id
            elif isinstance(exc, ast.Name):
                name = exc.id
            if name in DISALLOWED_RAISES and name not in ALWAYS_ALLOWED_RAISES:
                yield Finding(
                    self.id, ctx.rel, node.lineno,
                    f"raise {name}(...) — raise a KvTpuError subclass from "
                    "resilience/errors.py instead",
                )


@register
class BareExceptRule(Rule):
    id = "bare-except"
    rationale = (
        "A bare `except:` swallows `KeyboardInterrupt`/`SystemExit` and "
        "hides taxonomy errors from the exit-code contract; catch a named "
        "type (`Exception` at the broadest) instead. Zero budget: the "
        "package has none and must stay at none."
    )
    example = "try:\n    solve()\nexcept:\n    pass"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                yield Finding(
                    self.id, ctx.rel, node.lineno,
                    "bare `except:` — catch a named type (Exception at the "
                    "broadest) so KeyboardInterrupt and taxonomy errors are "
                    "not swallowed",
                )


def _open_write_mode(call: ast.Call) -> Optional[str]:
    """The mode string of an ``open()`` call when it writes, else None."""
    if not (isinstance(call.func, ast.Name) and call.func.id == "open"):
        return None
    mode = "r"
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    if isinstance(mode, str) and set(mode) & WRITE_MODE_CHARS:
        return mode
    return None


@register
class AtomicWriteRule(Rule):
    id = "atomic-write"
    rationale = (
        "Durable state must be promoted atomically: write a tmp file, "
        "fsync, `os.replace` — a bare `open(path, 'w')` is a torn-state "
        "window, which is exactly what the recovery fuzz's kill points "
        "SIGKILL into. Any function that opens for writing without calling "
        "`os.replace` is flagged; genuinely torn-tolerant sites (WAL/JSONL "
        "appends whose reader truncates torn tails, throwaway exports) "
        "carry an inline ignore with the reason."
    )
    example = 'def save(path, body):\n    with open(path, "w") as fh:\n        fh.write(body)'

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            opens: List[Tuple[int, str]] = []
            has_replace = False
            for node in walk_own(fn):
                if not isinstance(node, ast.Call):
                    continue
                mode = _open_write_mode(node)
                if mode is not None:
                    opens.append((node.lineno, mode))
                if _dotted(node.func) in ("os.replace", "os.rename"):
                    has_replace = True
            if has_replace:
                continue
            for line, mode in opens:
                yield Finding(
                    self.id, ctx.rel, line,
                    f"open(..., {mode!r}) in a function without os.replace "
                    "— durable writes must use the tmp-file + fsync + "
                    "os.replace promotion (or justify with an inline "
                    "ignore: torn-tolerant append, throwaway export)",
                )


@register
class LeaseAtomicRule(Rule):
    id = "lease-atomic"
    rationale = (
        "The leader lease is the failover protocol's ground truth: a torn "
        "or unsynced `leader.lease` can elect two leaders (a reader sees "
        "the old epoch while the new one is only in the page cache). "
        "Stricter than `atomic-write`: any lease-scoped function that "
        "opens a file for writing must BOTH promote via `os.replace`/"
        "`os.rename` AND `os.fsync` before promoting — replace without "
        "fsync survives a process crash but not a power cut, which is "
        "precisely the window the `before-lease-renew` kill point fuzzes. "
        "A function is lease-scoped when its name, its class's name, or "
        "the opened path expression mentions `lease`."
    )
    example = (
        'def write_lease(path, body):\n'
        '    with open(path + ".tmp", "w") as fh:\n'
        '        fh.write(body)\n'
        '    os.replace(path + ".tmp", path)  # no fsync before promote'
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            scoped = "lease" in fn.name.lower() or any(
                isinstance(a, ast.ClassDef) and "lease" in a.name.lower()
                for a in ctx.ancestors(fn)
            )
            opens: List[Tuple[int, str]] = []
            has_replace = False
            has_fsync = False
            for node in walk_own(fn):
                if not isinstance(node, ast.Call):
                    continue
                mode = _open_write_mode(node)
                if mode is not None:
                    target = node.args[0] if node.args else None
                    if scoped or (
                        target is not None
                        and "lease" in ast.dump(target).lower()
                    ):
                        opens.append((node.lineno, mode))
                dotted = _dotted(node.func)
                if dotted in ("os.replace", "os.rename"):
                    has_replace = True
                if dotted == "os.fsync":
                    has_fsync = True
            if not opens or (has_replace and has_fsync):
                continue
            missing = []
            if not has_replace:
                missing.append("os.replace")
            if not has_fsync:
                missing.append("os.fsync")
            for line, mode in opens:
                yield Finding(
                    self.id, ctx.rel, line,
                    f"lease write open(..., {mode!r}) without "
                    f"{' + '.join(missing)} — leader leases must be "
                    "promoted tmp + fsync + os.replace, or a reader can "
                    "see a torn/unsynced epoch and elect two leaders",
                )


@register
class BoundedQueueRule(Rule):
    id = "bounded-queue"
    rationale = (
        "The serve path stands between unbounded client demand and a "
        "fixed-capacity device: any `queue.Queue`/`collections.deque` "
        "constructed there without an explicit positive `maxsize`/`maxlen` "
        "is an overload liability — memory grows with offered load until "
        "the process dies, which is exactly the failure the ingress tier's "
        "typed `queue-full` rejection exists to replace. `SimpleQueue` has "
        "no bound at all and is flagged unconditionally; `maxsize=0` is "
        "the unbounded spelling and counts as missing. Genuinely "
        "drain-bounded sites (a queue whose producer is itself bounded) "
        "carry an inline `# kvtpu: ignore[bounded-queue]` with the reason."
    )
    example = "self._queue = queue.Queue()  # in serve/"

    #: package-relative prefixes on the serve path (between clients and
    #: the device); queues elsewhere are tooling and may buffer freely
    SERVE_PREFIXES = ("serve/",)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.rel.startswith(self.SERVE_PREFIXES):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _last_name(node.func)
            if name == "SimpleQueue":
                yield Finding(
                    self.id, ctx.rel, node.lineno,
                    "SimpleQueue on the serve path — it cannot be bounded; "
                    "use queue.Queue(maxsize=N) so overload becomes "
                    "back-pressure instead of memory growth",
                )
            elif name in ("Queue", "LifoQueue", "PriorityQueue"):
                if not self._has_bound(node, "maxsize", positional=0):
                    yield Finding(
                        self.id, ctx.rel, node.lineno,
                        f"{name}() without a positive maxsize on the serve "
                        "path — unbounded queues turn overload into memory "
                        "growth; pass maxsize=N (or justify with an inline "
                        "ignore)",
                    )
            elif name == "deque":
                if not self._has_bound(node, "maxlen", positional=1):
                    yield Finding(
                        self.id, ctx.rel, node.lineno,
                        "deque() without maxlen on the serve path — "
                        "unbounded buffers turn overload into memory "
                        "growth; pass maxlen=N (or justify with an inline "
                        "ignore)",
                    )

    @staticmethod
    def _has_bound(call: ast.Call, kwarg: str, *, positional: int) -> bool:
        """An explicit bound argument that is not the unbounded literal
        (0/None). Computed values are trusted — the author bounded it."""
        value: Optional[ast.expr] = None
        for kw in call.keywords:
            if kw.arg == kwarg:
                value = kw.value
        if value is None and len(call.args) > positional:
            value = call.args[positional]
        if value is None:
            return False
        if isinstance(value, ast.Constant):
            return bool(value.value)
        return True


def _is_thread_class(node: ast.ClassDef) -> bool:
    return any(_last_name(b) == "Thread" for b in node.bases)


def _daemon_true(call: ast.Call) -> Optional[bool]:
    """True/False when ``daemon=`` is a literal, None when absent."""
    for kw in call.keywords:
        if kw.arg == "daemon":
            if isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
            return True  # computed value: trust the author
    return None


@register
class ConcurrencyHygieneRule(Rule):
    id = "concurrency-hygiene"
    rationale = (
        "The exact PR 2 watchdog bug, made structural: a non-daemon "
        "`threading.Thread` is joined at interpreter exit, so one hung "
        "solve blocks the process and swallows the exit-code contract — "
        "every thread here must pass `daemon=True` (subclasses: in the "
        "`super().__init__` call). Also flagged: `Lock.acquire()` outside "
        "a `with` block (an exception between acquire and release deadlocks "
        "every later caller), and module-global writes (`global X` + "
        "assignment) outside a `with <lock>:` guard — the serve worker "
        "shares the interpreter with the submitting thread."
    )
    example = "t = threading.Thread(target=run)\nt.start()"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                yield from self._check_thread_call(ctx, node)
                yield from self._check_acquire(ctx, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_global_writes(ctx, node)

    def _check_thread_call(self, ctx: FileContext, node: ast.Call):
        name = _last_name(node.func)
        if name == "Thread":
            daemon = _daemon_true(node)
            if daemon is not True:
                why = "daemon=False" if daemon is False else "no daemon="
                yield Finding(
                    self.id, ctx.rel, node.lineno,
                    f"threading.Thread with {why} — a non-daemon thread is "
                    "joined at interpreter exit and a hung target blocks "
                    "the process; pass daemon=True",
                )
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "__init__"
            and isinstance(node.func.value, ast.Call)
            and _last_name(node.func.value.func) == "super"
        ):
            cls = next(
                (a for a in ctx.ancestors(node) if isinstance(a, ast.ClassDef)),
                None,
            )
            if cls is not None and _is_thread_class(cls):
                if _daemon_true(node) is not True:
                    yield Finding(
                        self.id, ctx.rel, node.lineno,
                        f"Thread subclass {cls.name} never passes "
                        "daemon=True to super().__init__ — a hung run() "
                        "blocks interpreter exit",
                    )

    def _check_acquire(self, ctx: FileContext, node: ast.Call):
        if not (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "acquire"
        ):
            return
        owner = _last_name(node.func.value)
        if owner is None or "lock" not in owner.lower():
            return
        yield Finding(
            self.id, ctx.rel, node.lineno,
            f"{owner}.acquire() outside `with` — an exception between "
            "acquire and release deadlocks every later caller; use "
            f"`with {owner}:`",
        )

    def _check_global_writes(self, ctx: FileContext, fn):
        declared = set()
        for node in walk_own(fn):
            if isinstance(node, ast.Global):
                declared.update(node.names)
        if not declared:
            return
        for node in walk_own(fn):
            targets: List[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for tgt in targets:
                if not (isinstance(tgt, ast.Name) and tgt.id in declared):
                    continue
                if self._under_lock(ctx, node):
                    continue
                yield Finding(
                    self.id, ctx.rel, node.lineno,
                    f"module global {tgt.id!r} written outside a "
                    "`with <lock>:` guard — shared mutable state raced by "
                    "the serve worker / watchdog threads",
                )

    @staticmethod
    def _under_lock(ctx: FileContext, node: ast.AST) -> bool:
        for anc in ctx.ancestors(node):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    name = _last_name(item.context_expr)
                    if name is None and isinstance(item.context_expr, ast.Call):
                        name = _last_name(item.context_expr.func)
                    if name and "lock" in name.lower():
                        return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
        return False


@register
class BoundedJournalRule(Rule):
    id = "bounded-journal"
    rationale = (
        "The posture plane journals *witnesses* extracted from per-batch "
        "delta planes, and the extraction index sets (`np.nonzero` / "
        "`flatnonzero` / `argwhere`) scale with the delta — a pathological "
        "batch (FullResync flipping half the matrix) would otherwise "
        "balloon one journal record to O(N²) witness entries and stall the "
        "apply path serialising them. Any function on the posture modules "
        "that extracts indices must also cap what it keeps: at least one "
        "slice with an explicit upper bound (`[:TOP_K]`, `[:cap]`) in the "
        "same function body. Extractions bounded some other way (a loop "
        "over an already-small [G, G] namespace matrix) carry an inline "
        "`# kvtpu: ignore[bounded-journal]` with the reason."
    )
    example = "witnesses = np.flatnonzero(changed)  # no [:cap] in scope"

    #: the modules whose extraction feeds the posture journal; index
    #: extraction elsewhere is not a journal-size liability
    POSTURE_FILES = ("serve/posture.py", "ops/posture.py")

    #: calls that materialise an index set proportional to the delta
    #: (`where` only in its single-argument extractor form — the
    #: three-argument select returns a same-shaped array, not indices)
    EXTRACTORS = frozenset({"nonzero", "flatnonzero", "argwhere", "where"})

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.rel not in self.POSTURE_FILES:
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            extractions = []
            capped = False
            for node in walk_own(fn):
                if isinstance(node, ast.Call):
                    name = _last_name(node.func)
                    if name in self.EXTRACTORS and (
                        name != "where" or len(node.args) == 1
                    ):
                        extractions.append((node.lineno, name))
                elif isinstance(node, ast.Slice) and node.upper is not None:
                    capped = True
            if extractions and not capped:
                for lineno, name in extractions:
                    yield Finding(
                        self.id, ctx.rel, lineno,
                        f"{name}() extracts delta-proportional indices but "
                        f"{fn.name}() has no bounding slice — a "
                        "pathological batch makes the journal record "
                        "O(N^2); keep a top-k cap ([:TOP_K]) next to every "
                        "extraction (or justify with an inline ignore)",
                    )


@register
class StripeLocalityRule(Rule):
    id = "stripe-locality"
    rationale = (
        "A stripe engine's count matrices are `[S, N]` row stripes — row "
        "index 0 is GLOBAL pod `lo`, not pod 0. Any function in "
        "`serve/stripes.py` that subscripts the striped count state "
        "(`_ing_count` / `_eg_count`) with an unbounded global index "
        "silently reads or patches the WRONG pod's row: the answer is "
        "well-shaped, plausible, and incorrect for every pod outside "
        "`[lo, hi)` — the worst failure mode a sharded serving plane "
        "has. Every such function must reference the owned stripe range "
        "in the same body (the `_lo`/`_hi` bounds, `stripe_rows`, "
        "`local()`/`owns()` translation, or a `row_base` rebase) so the "
        "global→local mapping is visible at the indexing site. Helpers "
        "whose operands arrive pre-bounded by the caller carry an inline "
        "`# kvtpu: ignore[stripe-locality]` with the reason."
    )
    example = "self._ing_count.at[idx, :]  # idx is GLOBAL; no lo/hi in scope"

    #: the stripe serving plane; count-state subscripts elsewhere are a
    #: different engine's (whole-state) indexing and globally addressed
    STRIPE_FILES = ("serve/stripes.py",)

    #: terminal names of the striped count state ("count" covers the
    #: jitted patch helpers' parameter spelling)
    COUNT_NAMES = frozenset({"_ing_count", "_eg_count", "count"})

    #: in-scope references that make the stripe range visible: the owned
    #: bounds themselves, the range property, the geometry helpers, the
    #: global→local translators, and the kernel rebase scalar
    BOUND_NAMES = frozenset({
        "_lo", "_hi", "lo", "hi", "stripe_rows", "stripe_bounds",
        "local", "owns", "row_base",
    })

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.rel not in self.STRIPE_FILES:
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            subscripts = []
            bounded = False
            for node in walk_own(fn):
                if isinstance(node, ast.Subscript):
                    chain = _dotted(node.value) or ""
                    parts = set(chain.split("."))
                    if parts & self.COUNT_NAMES:
                        subscripts.append(node.lineno)
                elif isinstance(node, (ast.Name, ast.Attribute)):
                    if _last_name(node) in self.BOUND_NAMES:
                        bounded = True
                elif isinstance(node, ast.keyword):
                    if node.arg in self.BOUND_NAMES:
                        bounded = True
            if subscripts and not bounded:
                for lineno in sorted(set(subscripts)):
                    yield Finding(
                        self.id, ctx.rel, lineno,
                        "striped count state subscripted but "
                        f"{fn.name}() never references the owned stripe "
                        "range — row 0 here is global pod `lo`, so an "
                        "unbounded index answers for the wrong pod; keep "
                        "the lo/hi bound (or the local()/owns() "
                        "translation) in the same function, or justify "
                        "with an inline ignore",
                    )
