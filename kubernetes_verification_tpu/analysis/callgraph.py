"""Package-wide module resolver and call graph — the substrate every
interprocedural rule stands on.

The per-file rules of PR 6 see one :class:`~.core.FileContext` at a time;
the bug classes that matter now (a host sync two helper calls below a jit
boundary, a ``psum`` whose axis name only the enclosing ``shard_map`` knows,
a taxonomy error no CLI handler maps to an exit code) all span function and
file boundaries. This module turns a set of parsed contexts into:

* a **module table** — package-relative path → dotted module name, with the
  import graph resolved (absolute, package-absolute, and relative forms);
* a **function index** — every top-level def and every method, keyed by a
  stable qualified name ``module:Class.method`` / ``module:func``;
* **call edges** — for each function, the call sites whose callee resolves
  to another indexed function (through ``from x import y [as z]`` aliases,
  module-attribute calls ``mod.func(...)``, and ``self.method()`` /
  ``cls.method()`` within a class);
* **Tarjan SCCs** in bottom-up (callee-first) order, so summary computation
  (:mod:`.summaries`) visits every callee before its callers and iterates
  only inside genuine recursion cycles.

Everything here is pure AST (no imports of linted code) and total: an
unresolvable callee is simply absent from the edge set — interprocedural
rules degrade to their within-function behaviour instead of guessing.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import FileContext
from .rules_hygiene import _last_name

__all__ = [
    "FunctionInfo",
    "CallSite",
    "CallGraph",
    "module_name",
    "build_callgraph",
]

#: the real package prefix — absolute internal imports are normalised by
#: stripping it, so ``from kubernetes_verification_tpu.ops import closure``
#: and ``from ..ops import closure`` resolve identically
PACKAGE_NAME = "kubernetes_verification_tpu"

FunctionNode = (ast.FunctionDef, ast.AsyncFunctionDef)


def module_name(rel: str) -> str:
    """Package-relative posix path → dotted module name.

    ``ops/closure.py`` → ``ops.closure``; a package ``__init__.py`` maps to
    the package itself (``parallel/__init__.py`` → ``parallel``, the root
    ``__init__.py`` → ``""``)."""
    parts = rel[:-3].split("/") if rel.endswith(".py") else rel.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


@dataclass
class CallSite:
    """One call whose callee resolved to an indexed function."""

    callee: str  #: qualified name (``module:qualname``)
    node: ast.Call
    line: int


@dataclass
class FunctionInfo:
    """One indexed function: where it lives and what it calls."""

    qname: str  #: ``module:qualname`` (methods: ``module:Class.method``)
    rel: str  #: package-relative path of the defining file
    module: str
    node: ast.AST  #: the FunctionDef / AsyncFunctionDef
    class_name: Optional[str] = None
    calls: List[CallSite] = field(default_factory=list)


@dataclass
class CallGraph:
    """The resolved program: functions, edges, and bottom-up SCC order."""

    functions: Dict[str, FunctionInfo]
    #: id(def node) → qname, for rules that start from an AST node
    by_node: Dict[int, str]
    #: module → {local name → qname} (defs + from-imports of indexed defs)
    module_scopes: Dict[str, Dict[str, str]]
    #: module → {alias → dotted module} for module-object imports
    module_aliases: Dict[str, Dict[str, str]]
    #: module → {NAME → string value} for module-level str constants
    str_constants: Dict[str, Dict[str, str]]
    #: class name → base-class names, program-wide (exception taxonomy)
    class_bases: Dict[str, Tuple[str, ...]]

    @property
    def n_edges(self) -> int:
        return sum(len(f.calls) for f in self.functions.values())

    def qname_of(self, node: ast.AST) -> Optional[str]:
        return self.by_node.get(id(node))

    def resolve_call(self, module: str, call: ast.Call,
                     class_name: Optional[str] = None) -> Optional[str]:
        """The qname a call expression dispatches to, when statically
        resolvable inside ``module`` (optionally within ``class_name`` for
        ``self.``/``cls.`` receivers)."""
        func = call.func
        if isinstance(func, ast.Name):
            return self.module_scopes.get(module, {}).get(func.id)
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name):
                if base.id in ("self", "cls") and class_name:
                    qn = f"{module}:{class_name}.{func.attr}"
                    if qn in self.functions:
                        return qn
                    return None
                target_mod = self.module_aliases.get(module, {}).get(base.id)
                if target_mod is not None:
                    qn = f"{target_mod}:{func.attr}"
                    if qn in self.functions:
                        return qn
        return None

    def resolve_str(self, module: str, node: ast.expr) -> Optional[str]:
        """A string-valued expression → its value: literals directly, bare
        names through module-level constants (following from-imports), and
        module-attribute reads (``mesh.POD_AXIS``)."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        consts = self.str_constants.get(module, {})
        if isinstance(node, ast.Name):
            return consts.get(node.id)
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            target_mod = self.module_aliases.get(module, {}).get(node.value.id)
            if target_mod is not None:
                return self.str_constants.get(target_mod, {}).get(node.attr)
        return None

    # ------------------------------------------------------------- SCCs
    def sccs_bottom_up(self) -> List[List[str]]:
        """Tarjan's SCCs of the call graph, emitted callee-first — iterative
        (the package's call chains outrun the default recursion limit)."""
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]
        succ = {
            q: sorted({c.callee for c in f.calls if c.callee in self.functions})
            for q, f in self.functions.items()
        }

        for root in sorted(self.functions):
            if root in index:
                continue
            work: List[Tuple[str, int]] = [(root, 0)]
            while work:
                node, i = work.pop()
                if i == 0:
                    index[node] = low[node] = counter[0]
                    counter[0] += 1
                    stack.append(node)
                    on_stack.add(node)
                recurse = False
                for j in range(i, len(succ[node])):
                    w = succ[node][j]
                    if w not in index:
                        work.append((node, j + 1))
                        work.append((w, 0))
                        recurse = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index[w])
                if recurse:
                    continue
                if low[node] == index[node]:
                    comp: List[str] = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    sccs.append(sorted(comp))
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
        return sccs


def _resolve_import_from(
    module: str, node: ast.ImportFrom, known: Iterable[str] = ()
) -> Optional[str]:
    """The dotted package-relative module an ``ImportFrom`` names, or None
    for imports that leave the package. ``known`` (the linted module set)
    also resolves plain absolute names, so fixture files importing each
    other (``from helpers import g``) build edges too."""
    if node.level == 0:
        mod = node.module or ""
        if mod == PACKAGE_NAME:
            return ""
        if mod.startswith(PACKAGE_NAME + "."):
            return mod[len(PACKAGE_NAME) + 1:]
        if mod in known:
            return mod
        return None
    # relative: level=1 is the current package, each extra level climbs one
    parts = module.split(".") if module else []
    # a module's package is its parent; climbing starts there
    base = parts[:-1] if parts else []
    up = node.level - 1
    if up > len(base):
        return None
    if up:
        base = base[:-up]
    if node.module:
        base = base + node.module.split(".")
    return ".".join(base)


def build_callgraph(ctxs: Sequence[FileContext]) -> CallGraph:
    """Resolve a set of parsed files into a :class:`CallGraph`."""
    functions: Dict[str, FunctionInfo] = {}
    by_node: Dict[int, str] = {}
    module_scopes: Dict[str, Dict[str, str]] = {}
    module_aliases: Dict[str, Dict[str, str]] = {}
    str_constants: Dict[str, Dict[str, str]] = {}
    class_bases: Dict[str, Tuple[str, ...]] = {}
    modules = {module_name(ctx.rel): ctx for ctx in ctxs if ctx.tree is not None}

    # pass 1: index defs, module-level constants, class bases
    for mod, ctx in modules.items():
        scope: Dict[str, str] = {}
        consts: Dict[str, str] = {}
        for stmt in ctx.tree.body:
            if isinstance(stmt, FunctionNode):
                qn = f"{mod}:{stmt.name}"
                functions[qn] = FunctionInfo(qn, ctx.rel, mod, stmt)
                by_node[id(stmt)] = qn
                scope[stmt.name] = qn
            elif isinstance(stmt, ast.ClassDef):
                bases = tuple(
                    b for b in (_last_name(e) for e in stmt.bases) if b
                )
                class_bases.setdefault(stmt.name, bases)
                for item in stmt.body:
                    if isinstance(item, FunctionNode):
                        qn = f"{mod}:{stmt.name}.{item.name}"
                        functions[qn] = FunctionInfo(
                            qn, ctx.rel, mod, item, class_name=stmt.name
                        )
                        by_node[id(item)] = qn
            elif isinstance(stmt, ast.Assign):
                if (
                    isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)
                ):
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            consts[tgt.id] = stmt.value.value
        module_scopes[mod] = scope
        str_constants[mod] = consts
        module_aliases[mod] = {}

    # pass 2: resolve imports into scopes / aliases / constants
    for mod, ctx in modules.items():
        scope = module_scopes[mod]
        aliases = module_aliases[mod]
        consts = str_constants[mod]
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.name
                    target = None
                    if name == PACKAGE_NAME:
                        target = ""
                    elif name.startswith(PACKAGE_NAME + "."):
                        target = name[len(PACKAGE_NAME) + 1:]
                    elif name.split(".")[0] in modules or name in modules:
                        target = name
                    if target is not None and target in modules:
                        aliases[alias.asname or name.split(".")[-1]] = target
            elif isinstance(node, ast.ImportFrom):
                src = _resolve_import_from(mod, node, modules)
                if src is None:
                    continue
                for alias in node.names:
                    local = alias.asname or alias.name
                    qn = f"{src}:{alias.name}"
                    if qn in functions:
                        scope.setdefault(local, qn)
                    sub = f"{src}.{alias.name}" if src else alias.name
                    if sub in modules:
                        aliases.setdefault(local, sub)
                    value = str_constants.get(src, {}).get(alias.name)
                    if value is not None:
                        consts.setdefault(local, value)

    graph = CallGraph(
        functions=functions,
        by_node=by_node,
        module_scopes=module_scopes,
        module_aliases=module_aliases,
        str_constants=str_constants,
        class_bases=class_bases,
    )

    # pass 3: call edges (each call attributed to its innermost indexed
    # function — nested defs/lambdas charge the enclosing indexed def, so
    # trace callbacks (scan/cond bodies) count as their owner's calls)
    for mod, ctx in modules.items():
        owner_of: Dict[int, FunctionInfo] = {}

        def assign_owner(node: ast.AST, owner: Optional[FunctionInfo]):
            qn = by_node.get(id(node))
            if qn is not None:
                owner = functions[qn]
            for child in ast.iter_child_nodes(node):
                if owner is not None:
                    owner_of[id(child)] = owner
                assign_owner(child, owner)

        assign_owner(ctx.tree, None)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            owner = owner_of.get(id(node))
            if owner is None:
                continue
            callee = graph.resolve_call(mod, node, owner.class_name)
            if callee is not None:
                owner.calls.append(CallSite(callee, node, node.lineno))
    return graph
