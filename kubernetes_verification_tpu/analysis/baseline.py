"""The unified grandfather baseline: one ``LINT_BASELINE.json`` at the repo
root, shared by every rule.

Shape::

    {"rule-id": {"package/relative/path.py": budget, ...}, ...}

A budget is the finding count a file was carrying when the rule was
adopted. The contract is monotone: a budget **may shrink but never grow** —
new findings anywhere must be fixed or carry an inline
``# kvtpu: ignore[rule-id]`` with a reason, never a bigger number here.
``shrink()`` (the ``--update-baseline`` path) enforces that direction: it
lowers budgets to the current counts and drops cleaned-up entries, and it
refuses to add entries or raise numbers.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Mapping, Optional

from .core import LintResult, repo_root

__all__ = [
    "BASELINE_NAME",
    "default_baseline_path",
    "load_baseline",
    "save_baseline",
    "shrink",
    "over_budget",
]

BASELINE_NAME = "LINT_BASELINE.json"

Budgets = Dict[str, Dict[str, int]]


def default_baseline_path() -> str:
    return os.path.join(repo_root(), BASELINE_NAME)


def load_baseline(path: Optional[str] = None) -> Budgets:
    """Parse the baseline; a missing file is an empty baseline (zero budget
    everywhere), a malformed one raises — silence here would un-gate every
    grandfathered rule at once."""
    target = path or default_baseline_path()
    if not os.path.exists(target):
        return {}
    with open(target, "r") as fh:
        data = json.load(fh)
    out: Budgets = {}
    for rule, files in data.items():
        if not isinstance(files, dict):
            raise json.JSONDecodeError(
                f"baseline entry for rule {rule!r} must be an object",
                target, 0,
            )
        out[rule] = {str(rel): int(n) for rel, n in files.items()}
    return out


def save_baseline(budgets: Budgets, path: Optional[str] = None) -> str:
    """Atomic write (the lint of the linter: rule ``atomic-write`` watches
    this module too)."""
    target = path or default_baseline_path()
    body = json.dumps(
        {r: dict(sorted(files.items())) for r, files in sorted(budgets.items())},
        indent=2,
        sort_keys=True,
    ) + "\n"
    tmp = target + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(body)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, target)
    return target


def shrink(budgets: Budgets, result: LintResult) -> Budgets:
    """The only legal baseline update: clamp every existing budget down to
    the current count and drop entries that reached zero. Counts above
    budget (or findings with no entry at all) are NOT absorbed — they stay
    red until fixed or inline-suppressed."""
    out: Budgets = {}
    for rule, files in budgets.items():
        for rel, budget in files.items():
            current = result.counts.get(rule, {}).get(rel, 0)
            new = min(budget, current)
            if new > 0:
                out.setdefault(rule, {})[rel] = new
    return out


def over_budget(budgets: Budgets, result: LintResult) -> Dict[str, Dict[str, int]]:
    """{rule: {path: count}} for every grandfathered entry whose current
    count GREW past its budget — the monotonicity test's assertion body."""
    bad: Dict[str, Dict[str, int]] = {}
    for rule, files in budgets.items():
        for rel, budget in files.items():
            current = result.counts.get(rule, {}).get(rel, 0)
            if current > budget:
                bad.setdefault(rule, {})[rel] = current
    return bad
