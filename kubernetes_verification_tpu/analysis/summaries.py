"""Per-function summaries and their bottom-up interprocedural propagation.

Each indexed function (:mod:`.callgraph`) gets a **local summary** — facts
computed from its own AST with a parameter-label dataflow pass (which
parameters reach a host-sync sink / a return / a ``donate_argnums`` slot,
which collectives it calls with which axis names, which exception types its
``raise`` statements can leak) — and a **propagated summary** folding in its
callees, computed over Tarjan SCCs in callee-first order with a fixpoint
inside each SCC so mutual recursion terminates at the least solution.

The label pass generalises :class:`~.rules_jax._TaintPass` from one boolean
("tracer-origin?") to *which parameter(s)* a value derives from: the same
kill set (``.shape``/``.dtype``/``len()`` return static metadata), the same
assignment fixpoint, but an environment of parameter-index sets. A helper's
summary is therefore caller-agnostic — ``jit-host-sync`` decides at each
jitted call site whether the argument feeding a syncing parameter is a
tracer *there*.

Local summaries are pure functions of one file's bytes, so they cache:
``.kvtpu_lint_cache.json`` (repo root, gitignored) maps each file's sha256
to its serialised local summaries. A warm ``kv-tpu lint`` run re-parses
(every per-file rule needs the tree anyway) but skips the dataflow, the
dominant analysis cost; propagation is a cheap graph pass and always runs,
so cross-file facts are never stale. Cache health and graph size are
observables: ``kvtpu_lint_cache_hits_total`` and
``kvtpu_lint_callgraph_{nodes,edges}``.
"""
from __future__ import annotations

import ast
import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import CallGraph, FunctionInfo, build_callgraph
from .core import FileContext
from .rules_hygiene import _dotted, _last_name
from .rules_jax import (
    CONCRETIZING_BUILTINS,
    HOST_FETCH_CALLS,
    KILL_CALLS,
    SHAPE_KILL_ATTRS,
    SYNC_METHODS,
    collect_jit_sites,
)

__all__ = [
    "CACHE_NAME",
    "SyncSite",
    "LocalSummary",
    "Summary",
    "Program",
    "build_program",
    "default_cache_path",
]

CACHE_NAME = ".kvtpu_lint_cache.json"
_CACHE_VERSION = 1

#: collective primitives whose axis names must name a mesh axis
COLLECTIVES = {
    "psum": 1,
    "pmean": 1,
    "pmax": 1,
    "pmin": 1,
    "all_gather": 1,
    "ppermute": 1,
    "psum_scatter": 1,
    "all_to_all": 1,
    "axis_index": 0,
    "pbroadcast": 1,
}


def default_cache_path() -> str:
    from .core import repo_root

    return os.path.join(repo_root(), CACHE_NAME)


# ------------------------------------------------------------- summaries
@dataclass
class SyncSite:
    """One host-sync (or concretisation) sink, with the helper chain that
    leads to it — ``via`` is empty for a direct sink."""

    kind: str
    rel: str
    line: int
    via: Tuple[str, ...] = ()

    def to_dict(self) -> dict:
        return {"kind": self.kind, "rel": self.rel, "line": self.line,
                "via": list(self.via)}

    @classmethod
    def from_dict(cls, d: dict) -> "SyncSite":
        return cls(d["kind"], d["rel"], int(d["line"]), tuple(d["via"]))

    def described(self) -> str:
        chain = " -> ".join(self.via)
        where = f"{self.rel}:{self.line}"
        if chain:
            return f"{self.kind} at {where} (via {chain})"
        return f"{self.kind} at {where}"


@dataclass
class LocalSummary:
    """Cacheable per-function facts (see module docstring)."""

    params: List[str] = field(default_factory=list)
    #: param indices whose value can reach a ``return``
    returns_params: List[int] = field(default_factory=list)
    #: param index → direct host-sync sinks on values derived from it
    syncs: Dict[int, List[SyncSite]] = field(default_factory=dict)
    #: direct collective calls: {kind, line, axes: [axis-expr dicts]}
    collectives: List[dict] = field(default_factory=list)
    #: direct raises escaping local handlers: {name, guards: [...]}
    raises: List[dict] = field(default_factory=list)
    #: param index → line of a jit call donating that parameter's buffer
    donates: Dict[int, int] = field(default_factory=dict)
    #: resolved-shape call sites: {shape, line, args: [[labels]],
    #: kwargs: {name: [labels]}, guards: [...]}
    calls: List[dict] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "params": self.params,
            "returns_params": self.returns_params,
            "syncs": {str(i): [s.to_dict() for s in v]
                      for i, v in self.syncs.items()},
            "collectives": self.collectives,
            "raises": self.raises,
            "donates": {str(i): ln for i, ln in self.donates.items()},
            "calls": self.calls,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "LocalSummary":
        return cls(
            params=list(d.get("params", [])),
            returns_params=[int(i) for i in d.get("returns_params", [])],
            syncs={int(i): [SyncSite.from_dict(s) for s in v]
                   for i, v in d.get("syncs", {}).items()},
            collectives=list(d.get("collectives", [])),
            raises=list(d.get("raises", [])),
            donates={int(i): int(ln) for i, ln in d.get("donates", {}).items()},
            calls=list(d.get("calls", [])),
        )


@dataclass
class Summary:
    """A function's propagated (callee-folded) summary."""

    info: FunctionInfo
    local: LocalSummary
    #: param index → every sync sink reachable from it, any call depth
    param_syncs: Dict[int, List[SyncSite]] = field(default_factory=dict)
    #: exception type names that can escape this function
    raises: Set[str] = field(default_factory=set)
    #: param index → (line, via-chain) of a reachable buffer donation
    donates: Dict[int, Tuple[int, Tuple[str, ...]]] = field(default_factory=dict)


@dataclass
class Program:
    """The interprocedural view rules consume: graph + summaries."""

    graph: CallGraph
    summaries: Dict[str, Summary]
    cache_hits: int = 0
    cache_misses: int = 0

    def summary_for_node(self, node: ast.AST) -> Optional[Summary]:
        qn = self.graph.qname_of(node)
        return self.summaries.get(qn) if qn else None

    def resolve_axis(self, module: str, axis: dict) -> Optional[str]:
        """A serialised axis expression → its string value, when static."""
        if "s" in axis:
            return axis["s"]
        if "n" in axis:
            return self.graph.str_constants.get(module, {}).get(axis["n"])
        if "a" in axis:
            base, attr = axis["a"]
            target = self.graph.module_aliases.get(module, {}).get(base)
            if target is not None:
                return self.graph.str_constants.get(target, {}).get(attr)
        return None


# ------------------------------------------------------- label dataflow
class _LabelFlow:
    """Forward dataflow mapping each local name to the set of parameter
    indices its value may derive from."""

    def __init__(self, fn: ast.AST, params: List[str]):
        self.fn = fn
        self.env: Dict[str, Set[int]] = {p: {i} for i, p in enumerate(params)}

    def labels(self, node: ast.AST) -> Set[int]:
        if isinstance(node, ast.Name):
            return self.env.get(node.id, set())
        if isinstance(node, ast.Constant):
            return set()
        if isinstance(node, ast.Attribute):
            if node.attr in SHAPE_KILL_ATTRS:
                return set()
            return self.labels(node.value)
        if isinstance(node, ast.Call):
            if _last_name(node.func) in KILL_CALLS:
                return set()
            out = self.labels(node.func)
            for a in node.args:
                out |= self.labels(a)
            for kw in node.keywords:
                out |= self.labels(kw.value)
            return out
        if isinstance(node, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
            return set()
        out: Set[int] = set()
        for child in ast.iter_child_nodes(node):
            out |= self.labels(child)
        return out

    def _bind(self, target: ast.expr, labels: Set[int]) -> bool:
        changed = False
        if isinstance(target, ast.Name):
            cur = self.env.get(target.id)
            if cur != labels:
                self.env[target.id] = set(labels)
                changed = True
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                changed |= self._bind(elt, labels)
        elif isinstance(target, ast.Starred):
            changed |= self._bind(target.value, labels)
        return changed

    def run(self) -> None:
        for _ in range(10):
            changed = False
            for node in ast.walk(self.fn):
                if isinstance(node, ast.Assign):
                    lab = self.labels(node.value)
                    for tgt in node.targets:
                        changed |= self._bind(tgt, lab)
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    changed |= self._bind(node.target, self.labels(node.value))
                elif isinstance(node, ast.AugAssign):
                    if isinstance(node.target, ast.Name):
                        lab = self.labels(node.target) | self.labels(node.value)
                        changed |= self._bind(node.target, lab)
                elif isinstance(node, ast.NamedExpr):
                    changed |= self._bind(node.target, self.labels(node.value))
                elif isinstance(node, ast.For):
                    changed |= self._bind(node.target, self.labels(node.iter))
                elif isinstance(node, ast.comprehension):
                    changed |= self._bind(node.target, self.labels(node.iter))
                elif isinstance(node, ast.With):
                    for item in node.items:
                        if item.optional_vars is not None:
                            changed |= self._bind(
                                item.optional_vars,
                                self.labels(item.context_expr),
                            )
            if not changed:
                break


def _branch_labels(flow: _LabelFlow, test: ast.expr) -> Set[int]:
    """Labels of a branch condition, minus ``is``/``is not`` comparisons —
    identity tests (``if x is not None:``) inspect pytree *structure*, not
    tracer values, and are legal in traced code."""
    if isinstance(test, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
    ):
        return set()
    if isinstance(test, ast.BoolOp):
        out: Set[int] = set()
        for v in test.values:
            out |= _branch_labels(flow, v)
        return out
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _branch_labels(flow, test.operand)
    return flow.labels(test)


def _param_names(fn) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def _call_shape(call: ast.Call) -> Optional[dict]:
    """Serialise how a call names its callee, for later resolution."""
    func = call.func
    if isinstance(func, ast.Name):
        return {"name": func.id}
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        if func.value.id in ("self", "cls"):
            return {"method": func.attr}
        return {"attr": [func.value.id, func.attr]}
    return None


def _resolve_shape(
    graph: CallGraph, module: str, class_name: Optional[str], shape: dict
) -> Optional[str]:
    if "name" in shape:
        return graph.module_scopes.get(module, {}).get(shape["name"])
    if "method" in shape and class_name:
        qn = f"{module}:{class_name}.{shape['method']}"
        return qn if qn in graph.functions else None
    if "attr" in shape:
        base, attr = shape["attr"]
        target = graph.module_aliases.get(module, {}).get(base)
        if target is not None:
            qn = f"{target}:{attr}"
            if qn in graph.functions:
                return qn
    return None


def _axis_exprs(node: ast.expr) -> List[dict]:
    """Serialise an ``axis_name`` argument: literal strings, names, and
    module-attribute reads survive; anything else is dynamic."""
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[dict] = []
        for elt in node.elts:
            out.extend(_axis_exprs(elt))
        return out
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [{"s": node.value}]
    if isinstance(node, ast.Name):
        return [{"n": node.id}]
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return [{"a": [node.value.id, node.attr]}]
    return [{"dyn": True}]


def _is_collective(call: ast.Call) -> Optional[Tuple[str, int]]:
    name = _last_name(call.func)
    if name not in COLLECTIVES:
        return None
    dotted = _dotted(call.func)
    # accept `lax.psum` / `jax.lax.psum` / bare `psum` (from-import); a
    # `psum` method on some unrelated object would need a dotted receiver
    # that is neither `lax` nor `jax.lax`, which the package never has
    if dotted is not None and "." in dotted:
        head = dotted.rsplit(".", 1)[0]
        if head not in ("lax", "jax.lax"):
            return None
    return name, COLLECTIVES[name]


def _exc_name(node: Optional[ast.expr]) -> Optional[str]:
    if node is None:
        return None
    if isinstance(node, ast.Call):
        return _last_name(node.func)
    return _last_name(node)


def _handler_names(handler: ast.ExceptHandler) -> List[str]:
    if handler.type is None:
        return ["BaseException"]
    if isinstance(handler.type, ast.Tuple):
        return [n for n in (_last_name(e) for e in handler.type.elts) if n]
    n = _last_name(handler.type)
    return [n] if n else []


def _compute_local(info: FunctionInfo, donate_map: Dict[str, Set[int]]) -> LocalSummary:
    """One function's local summary: label dataflow + sink/collective/raise
    extraction. ``donate_map`` maps local jitted-callable names to the
    parameter indices they donate."""
    fn = info.node
    params = _param_names(fn)
    flow = _LabelFlow(fn, params)
    flow.run()
    out = LocalSummary(params=params)

    returns: Set[int] = set()
    syncs: Dict[int, List[SyncSite]] = {}

    def add_sync(labels: Set[int], kind: str, line: int) -> None:
        for i in labels:
            syncs.setdefault(i, []).append(SyncSite(kind, info.rel, line))

    # guards: exception type names caught by try blocks enclosing a node
    guard_of: Dict[int, Tuple[str, ...]] = {}

    def walk_guarded(node: ast.AST, guards: Tuple[str, ...]) -> None:
        if isinstance(node, ast.Try):
            inner = guards + tuple(
                n for h in node.handlers for n in _handler_names(h)
            )
            for child in node.body:
                guard_of[id(child)] = inner
                walk_guarded(child, inner)
            for part in (node.orelse, node.finalbody):
                for child in part:
                    walk_guarded(child, guards)
            for h in node.handlers:
                for child in h.body:
                    walk_guarded(child, guards)
            return
        for child in ast.iter_child_nodes(node):
            walk_guarded(child, guards)
            guard_of.setdefault(id(child), guards)

    walk_guarded(fn, ())

    for node in ast.walk(fn):
        guards = list(guard_of.get(id(node), ()))
        if isinstance(node, ast.Return) and node.value is not None:
            returns |= flow.labels(node.value)
        elif isinstance(node, ast.Raise):
            name = _exc_name(node.exc)
            if name:
                out.raises.append(
                    {"name": name, "guards": guards, "line": node.lineno}
                )
        elif isinstance(node, (ast.If, ast.While)):
            add_sync(_branch_labels(flow, node.test), "Python branch",
                     node.lineno)
        elif isinstance(node, ast.Assert):
            add_sync(_branch_labels(flow, node.test), "assert", node.lineno)
        elif isinstance(node, ast.Call):
            coll = _is_collective(node)
            if coll is not None:
                kind, axis_pos = coll
                axis_node: Optional[ast.expr] = None
                if len(node.args) > axis_pos:
                    axis_node = node.args[axis_pos]
                for kw in node.keywords:
                    if kw.arg == "axis_name":
                        axis_node = kw.value
                out.collectives.append({
                    "kind": kind,
                    "line": node.lineno,
                    "axes": _axis_exprs(axis_node) if axis_node is not None
                    else [],
                })
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in SYNC_METHODS
            ):
                add_sync(
                    flow.labels(node.func.value),
                    f".{node.func.attr}()", node.lineno,
                )
            elif (
                isinstance(node.func, ast.Name)
                and node.func.id in CONCRETIZING_BUILTINS
                and node.args
            ):
                add_sync(
                    flow.labels(node.args[0]),
                    f"{node.func.id}()", node.lineno,
                )
            elif _dotted(node.func) in HOST_FETCH_CALLS:
                lab: Set[int] = set()
                for a in node.args:
                    lab |= flow.labels(a)
                add_sync(lab, f"{_dotted(node.func)}()", node.lineno)

            shape = _call_shape(node)
            if shape is not None:
                # donation: a bare parameter fed to a donating slot of a
                # local jitted callable marks that parameter donated
                if "name" in shape and shape["name"] in donate_map:
                    for i in donate_map[shape["name"]]:
                        if i < len(node.args) and isinstance(
                            node.args[i], ast.Name
                        ):
                            for j in flow.env.get(node.args[i].id, set()):
                                out.donates.setdefault(j, node.lineno)
                out.calls.append({
                    "shape": shape,
                    "line": node.lineno,
                    "args": [sorted(flow.labels(a)) for a in node.args],
                    "kwargs": {
                        kw.arg: sorted(flow.labels(kw.value))
                        for kw in node.keywords
                        if kw.arg is not None
                    },
                    "guards": guards,
                })

    out.returns_params = sorted(returns)
    out.syncs = syncs
    return out


def _donate_map(tree: ast.AST) -> Dict[str, Set[int]]:
    """Local jitted-callable name → donated parameter indices."""
    _sites, by_name = collect_jit_sites(tree)
    return {
        name: site.donated for name, site in by_name.items() if site.donated
    }


# ----------------------------------------------------------- propagation
#: builtin exception hierarchy the guard filter understands (the package's
#: own taxonomy is read from class defs at propagation time)
_BUILTIN_BASES: Dict[str, Tuple[str, ...]] = {
    "ValueError": ("Exception",),
    "TypeError": ("Exception",),
    "KeyError": ("LookupError",),
    "IndexError": ("LookupError",),
    "LookupError": ("Exception",),
    "RuntimeError": ("Exception",),
    "NotImplementedError": ("RuntimeError",),
    "OSError": ("Exception",),
    "IOError": ("OSError",),
    "ArithmeticError": ("Exception",),
    "ZeroDivisionError": ("ArithmeticError",),
    "AttributeError": ("Exception",),
    "StopIteration": ("Exception",),
    "ImportError": ("Exception",),
    "ModuleNotFoundError": ("ImportError",),
    "AssertionError": ("Exception",),
    "Exception": ("BaseException",),
    "KeyboardInterrupt": ("BaseException",),
    "SystemExit": ("BaseException",),
}


def exception_ancestors(
    name: str, class_bases: Dict[str, Tuple[str, ...]]
) -> Set[str]:
    """All (known) ancestors of an exception type, itself included."""
    seen: Set[str] = set()
    todo = [name]
    while todo:
        cur = todo.pop()
        if cur in seen:
            continue
        seen.add(cur)
        todo.extend(class_bases.get(cur, ()))
        todo.extend(_BUILTIN_BASES.get(cur, ()))
    return seen


def _caught_by(
    name: str, guards: Sequence[str], class_bases: Dict[str, Tuple[str, ...]]
) -> bool:
    if not guards:
        return False
    ancestors = exception_ancestors(name, class_bases)
    return any(g in ancestors for g in guards)


def _map_call_labels(call: dict, callee: Summary) -> Dict[int, Set[int]]:
    """Callee param index → caller labels flowing into it at this site."""
    offset = 1 if "method" in call["shape"] and callee.info.class_name else 0
    out: Dict[int, Set[int]] = {}
    for k, labels in enumerate(call["args"]):
        if labels:
            out.setdefault(k + offset, set()).update(labels)
    if call["kwargs"]:
        index_of = {p: i for i, p in enumerate(callee.local.params)}
        for pname, labels in call["kwargs"].items():
            if labels and pname in index_of:
                out.setdefault(index_of[pname], set()).update(labels)
    return out


_MAX_SYNCS_PER_PARAM = 4  # keep summaries (and messages) bounded


def _propagate(graph: CallGraph, summaries: Dict[str, Summary]) -> None:
    for scc in graph.sccs_bottom_up():
        for _ in range(len(scc) + 1):
            changed = False
            for qn in scc:
                s = summaries[qn]
                info = s.info
                for call in s.local.calls:
                    callee_qn = _resolve_shape(
                        graph, info.module, info.class_name, call["shape"]
                    )
                    if callee_qn is None or callee_qn not in summaries:
                        continue
                    callee = summaries[callee_qn]
                    label_map = _map_call_labels(call, callee)
                    step = callee.info.node.name
                    # syncs: callee param j syncs + our labels reach j
                    for j, sites in callee.param_syncs.items():
                        for i in label_map.get(j, ()):
                            mine = s.param_syncs.setdefault(i, [])
                            for site in sites:
                                if len(site.via) >= 6:
                                    continue
                                lifted = SyncSite(
                                    site.kind, site.rel, site.line,
                                    (step,) + site.via,
                                )
                                if lifted not in mine and len(mine) < _MAX_SYNCS_PER_PARAM:
                                    mine.append(lifted)
                                    changed = True
                    # donations lift the same way
                    for j, (line, via) in callee.donates.items():
                        for i in label_map.get(j, ()):
                            if i not in s.donates and len(via) < 6:
                                s.donates[i] = (call["line"], (step,) + via)
                                changed = True
                    # raises: callee escapes filtered by this site's guards
                    for r in callee.raises:
                        if r in s.raises:
                            continue
                        if _caught_by(r, call["guards"], graph.class_bases):
                            continue
                        s.raises.add(r)
                        changed = True
            if not changed:
                break


# ------------------------------------------------------------------ cache
def _load_cache(path: str) -> dict:
    try:
        with open(path, "r") as fh:
            data = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return {}
    if data.get("version") != _CACHE_VERSION:
        return {}
    files = data.get("files")
    return files if isinstance(files, dict) else {}


def _save_cache(path: str, files: dict) -> None:
    body = json.dumps({"version": _CACHE_VERSION, "files": files})
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(body)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def build_program(
    ctxs: Sequence[FileContext],
    cache_path: Optional[str] = None,
) -> Program:
    """Callgraph + summaries for a set of parsed files. ``cache_path``
    enables the content-hash local-summary cache (propagation always runs
    fresh, so cross-file facts cannot go stale)."""
    graph = build_callgraph(ctxs)
    by_rel: Dict[str, List[FunctionInfo]] = {}
    for info in graph.functions.values():
        by_rel.setdefault(info.rel, []).append(info)

    cache = _load_cache(cache_path) if cache_path else {}
    new_cache: dict = {}
    hits = misses = 0
    locals_by_qname: Dict[str, LocalSummary] = {}

    for ctx in ctxs:
        if ctx.tree is None:
            continue
        infos = by_rel.get(ctx.rel, [])
        digest = hashlib.sha256(ctx.source.encode("utf-8")).hexdigest()
        entry = cache.get(ctx.rel)
        cached_fns = (
            entry.get("functions", {})
            if entry and entry.get("hash") == digest
            else None
        )
        if cached_fns is not None and set(cached_fns) == {
            i.qname for i in infos
        }:
            hits += 1
            for info in infos:
                locals_by_qname[info.qname] = LocalSummary.from_dict(
                    cached_fns[info.qname]
                )
            new_cache[ctx.rel] = entry
            continue
        misses += 1
        donate_map = _donate_map(ctx.tree)
        fresh: Dict[str, dict] = {}
        for info in infos:
            local = _compute_local(info, donate_map)
            locals_by_qname[info.qname] = local
            fresh[info.qname] = local.to_dict()
        new_cache[ctx.rel] = {"hash": digest, "functions": fresh}

    summaries: Dict[str, Summary] = {}
    for qn, info in graph.functions.items():
        local = locals_by_qname.get(qn, LocalSummary())
        summaries[qn] = Summary(
            info=info,
            local=local,
            param_syncs={i: list(v) for i, v in local.syncs.items()},
            raises={
                r["name"]
                for r in local.raises
                if not _caught_by(r["name"], r["guards"], graph.class_bases)
            },
            donates={i: (ln, ()) for i, ln in local.donates.items()},
        )
    _propagate(graph, summaries)

    if cache_path and misses:
        try:
            _save_cache(cache_path, new_cache)
        except OSError:
            pass  # read-only checkout: the cache is an optimisation only

    program = Program(graph, summaries, cache_hits=hits, cache_misses=misses)
    try:
        from ..observe.metrics import (
            LINT_CACHE_HITS_TOTAL,
            LINT_CALLGRAPH_EDGES,
            LINT_CALLGRAPH_NODES,
        )

        LINT_CALLGRAPH_NODES.set(len(graph.functions))
        LINT_CALLGRAPH_EDGES.set(graph.n_edges)
        if hits:
            LINT_CACHE_HITS_TOTAL.inc(hits)
    except ImportError:  # linting outside an installed package tree
        pass
    return program
