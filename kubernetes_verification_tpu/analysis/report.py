"""Reporters: text / JSON output for lint runs, plus the auto-generated
``LINTS.md`` rule catalog (same regime as ``METRICS.md``: the committed
file is generated, and a drift check fails when the two diverge)."""
from __future__ import annotations

import json
from typing import Optional

from .core import RULES, LintResult, UNUSED_SUPPRESSION

__all__ = [
    "render_text",
    "render_json",
    "render_sarif",
    "catalog_markdown",
    "CATALOG_HEADER",
]


def render_text(result: LintResult, verbose: bool = False) -> str:
    lines = [f.render() for f in result.findings]
    if verbose and result.grandfathered:
        lines.append("grandfathered (baseline budget, shrink to clear):")
        lines += [f"  {f.render()}" for f in result.grandfathered]
    lines.append(
        f"{len(result.findings)} finding(s), "
        f"{len(result.grandfathered)} grandfathered, "
        f"{len(result.suppressed)} suppressed inline"
    )
    return "\n".join(lines)


def render_json(result: LintResult) -> str:
    return json.dumps(result.to_dict(), sort_keys=True)


#: pinned schema pointer — CI annotators key on the exact 2.1.0 shape
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def render_sarif(result: LintResult) -> str:
    """SARIF 2.1.0 for CI PR annotation: one run, one `result` per
    actionable finding (grandfathered/suppressed stay out — SARIF is the
    merge gate's view), rule metadata inlined so viewers can render the
    rationale without the repo checked out."""
    from . import core  # ensure rule modules are imported

    core._select_rules(None)
    used = sorted({f.rule for f in result.findings})
    rules = []
    for rid in used:
        rule = RULES.get(rid)
        desc = (
            rule.rationale.split(". ")[0].rstrip(".") + "."
            if rule is not None and rule.rationale
            else rid
        )
        rules.append({
            "id": rid,
            "shortDescription": {"text": desc},
        })
    index = {rid: i for i, rid in enumerate(used)}
    results = [
        {
            "ruleId": f.rule,
            "ruleIndex": index[f.rule],
            "level": "error",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path,
                            "uriBaseId": "SRCROOT",
                        },
                        "region": {"startLine": f.line},
                    }
                }
            ],
        }
        for f in result.findings
    ]
    doc = {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "kv-tpu-lint",
                        "informationUri": "LINTS.md",
                        "rules": rules,
                    }
                },
                "columnKind": "utf16CodeUnits",
                "results": results,
            }
        ],
    }
    return json.dumps(doc, indent=2, sort_keys=True)


CATALOG_HEADER = """# Lint rule catalog

One section per `kv-tpu lint` rule. Auto-generated from the rule metadata
by `python -m kubernetes_verification_tpu.analysis --write-docs LINTS.md` —
edit the `rationale`/`example` strings on the rule classes under
`kubernetes_verification_tpu/analysis/`, not this file (`--check-docs`
fails CI when the two drift).

Suppress a finding inline with a trailing comment on the flagged line (or
a comment-only line directly above it), always with a reason:

```python
self._fh = open(path, "a")  # kvtpu: ignore[atomic-write] WAL appends are torn-tail tolerant
```

Stale suppressions are themselves findings (`unused-suppression`).
Grandfathered legacy counts live in `LINT_BASELINE.json` — budgets may
shrink (`kv-tpu lint --update-baseline`) but never grow.
"""


def catalog_markdown() -> str:
    """The LINTS.md body, one section per registered rule."""
    from . import core  # ensure rule modules are imported

    core._select_rules(None)
    sections = [CATALOG_HEADER]
    for rule in RULES.values():
        sections.append(f"## `{rule.id}`\n")
        sections.append(rule.rationale.strip() + "\n")
        if rule.example:
            sections.append("Flagged:\n")
            sections.append("```python\n" + rule.example.rstrip() + "\n```\n")
        sections.append(
            f"Suppress with `# kvtpu: ignore[{rule.id}] <reason>`.\n"
        )
    sections.append(f"## `{UNUSED_SUPPRESSION}`\n")
    sections.append(
        "A `# kvtpu: ignore[...]` comment that silenced nothing — the "
        "finding it covered moved or was fixed. Delete the comment; this "
        "rule is not itself suppressible, so stale ignores rot loudly.\n"
    )
    return "\n".join(sections)


def check_docs(path: str) -> Optional[str]:
    """None when ``path`` matches the generated catalog, else a one-line
    diagnosis."""
    try:
        with open(path) as fh:
            on_disk = fh.read()
    except OSError:
        on_disk = ""
    if on_disk != catalog_markdown():
        return (
            f"{path} is stale — regenerate with `python -m "
            f"kubernetes_verification_tpu.analysis --write-docs {path}`"
        )
    return None
