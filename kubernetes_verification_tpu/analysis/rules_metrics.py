"""Metric-discipline rules: the static twins of the import-time checks in
``scripts/check_metrics_names.py``.

The shim still validates the *live* registry (names that only exist after
imports, METRICS.md help-string drift); these rules catch the same bug
classes at the AST layer, which means they also run on fixture strings and
on modules the import-based lint never loads.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import FileContext, Finding, Rule, register
from .rules_hygiene import _last_name

METRIC_NAME_RE = re.compile(r"^kvtpu_[a-z0-9_]+$")

#: registry constructor names (observe/registry.py)
_FAMILY_CLASSES = frozenset({"Counter", "Gauge", "Histogram"})

#: labels per family above which the exposition cardinality explodes:
#: every label multiplies the child count, and the dashboards key on
#: stable low-dimensional families
MAX_LABELS = 3


def _registrations(ctx: FileContext) -> List[Tuple[ast.Call, str, Sequence[str]]]:
    """(call, family-name, labelnames) for every static Counter/Gauge/
    Histogram construction with a literal name."""
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if _last_name(node.func) not in _FAMILY_CLASSES:
            continue
        if not (
            node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            continue
        name = node.args[0].value
        if not name.startswith("kvtpu"):
            continue  # not ours (fixture helpers, third-party shims)
        labels: Sequence[str] = ()
        label_node: Optional[ast.expr] = (
            node.args[2] if len(node.args) >= 3 else None
        )
        for kw in node.keywords:
            if kw.arg == "labelnames":
                label_node = kw.value
        if isinstance(label_node, (ast.Tuple, ast.List)):
            labels = [
                e.value
                for e in label_node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            ]
        out.append((node, name, labels))
    return out


def _required_families(ctx: FileContext) -> Optional[Tuple[int, Set[str]]]:
    """(lineno, names) of a ``REQUIRED_FAMILIES = frozenset({...})`` /
    set-literal assignment, when this file declares one."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "REQUIRED_FAMILIES"
            for t in node.targets
        ):
            continue
        value = node.value
        if (
            isinstance(value, ast.Call)
            and _last_name(value.func) == "frozenset"
            and value.args
        ):
            value = value.args[0]
        if isinstance(value, (ast.Set, ast.Tuple, ast.List)):
            names = {
                e.value
                for e in value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            }
            return node.lineno, names
    return None


@register
class MetricsNamesRule(Rule):
    id = "metrics-names"
    rationale = (
        "Every family registered in the package must match "
        "`^kvtpu_[a-z0-9_]+$`: the Prometheus/JSON exporter output is a "
        "frozen contract (dashboards and scrape configs key on these "
        "names), and one camelCase or un-prefixed family silently forks "
        "the namespace. Static twin of the import-based lint in "
        "`scripts/check_metrics_names.py`."
    )
    example = 'BAD = Counter("kvtpuBadName", "help")'

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for call, name, _labels in _registrations(ctx):
            if not METRIC_NAME_RE.match(name):
                yield Finding(
                    self.id, ctx.rel, call.lineno,
                    f"metric family {name!r} does not match "
                    "^kvtpu_[a-z0-9_]+$ — the exporter namespace is a "
                    "frozen dashboard contract",
                )


@register
class MetricDisciplineRule(Rule):
    id = "metric-discipline"
    rationale = (
        "Two failure modes the registry cannot catch at runtime: a family "
        "emitted somewhere but never added to `REQUIRED_FAMILIES` (the "
        "dashboard contract) disappears without a failing lint when its "
        "registration site is later deleted; and a family with too many "
        "labels multiplies exposition cardinality until scrapes fall over. "
        f"Bound: at most {MAX_LABELS} labels per family."
    )
    example = (
        'WIDE = Counter("kvtpu_wide_total", "help",\n'
        '               ("a", "b", "c", "d"))  # 4 labels'
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for call, name, labels in _registrations(ctx):
            if len(labels) > MAX_LABELS:
                yield Finding(
                    self.id, ctx.rel, call.lineno,
                    f"family {name!r} declares {len(labels)} labels "
                    f"({', '.join(labels)}) — exposition cardinality is "
                    "multiplicative; bound is "
                    f"{MAX_LABELS}",
                )

    def check_project(self, ctxs: Sequence[FileContext]) -> Iterable[Finding]:
        required: Optional[Set[str]] = None
        req_ctx: Optional[FileContext] = None
        req_line = 0
        registered: Dict[str, Tuple[FileContext, int]] = {}
        for ctx in ctxs:
            found = _required_families(ctx)
            if found is not None:
                req_line, required = found
                req_ctx = ctx
            for call, name, _labels in _registrations(ctx):
                if METRIC_NAME_RE.match(name):
                    registered.setdefault(name, (ctx, call.lineno))
        if required is None:
            return  # nothing to cross-check against (fixture snippets)
        for name, (ctx, line) in sorted(registered.items()):
            if name not in required:
                yield Finding(
                    self.id, ctx.rel, line,
                    f"family {name!r} is emitted but never registered in "
                    "REQUIRED_FAMILIES — it can vanish from the dashboard "
                    "contract without a failing lint",
                )
        for name in sorted(required - set(registered)):
            yield Finding(
                self.id, req_ctx.rel, req_line,
                f"REQUIRED_FAMILIES names {name!r} but no registration "
                "site declares it — dead contract entry or a renamed "
                "family",
            )


#: names whose appearance inside a ``do_GET``/``do_POST`` body proves the
#: handler adopts the incoming trace context (observe/spans.py wire
#: contract)
_TRACE_PARSE_NAMES = frozenset({"parse_trace_header", "TRACE_HEADER"})

#: BaseHTTPRequestHandler entry points the adoption requirement covers
_HTTP_HANDLER_NAMES = frozenset({"do_GET", "do_POST"})


@register
class TraceContextRule(Rule):
    id = "trace-context"
    rationale = (
        "Distributed traces only join up when every HTTP hop carries the "
        "`X-Kvtpu-Trace` header: an outgoing `conn.request(...)` that "
        "passes no `headers` drops the caller's trace context on the "
        "floor, and a `do_GET`/`do_POST` handler that never parses the "
        "header (`parse_trace_header` / `TRACE_HEADER`) orphans every "
        "server-side span into a fresh trace. Either break silently turns "
        "`kv-tpu trace <id>` into a single-process view — the cross-"
        "process timeline still renders, it just lies by omission."
    )
    example = 'conn.request("GET", "/v1/tip")  # headers= missing'

    @staticmethod
    def _has_headers(call: ast.Call) -> bool:
        # http.client's signature is request(method, url, body, headers):
        # a 4th positional, an explicit headers=, or an opaque ** splat
        # (can't see inside statically) all count as propagating
        if len(call.args) >= 4:
            return True
        return any(kw.arg in ("headers", None) for kw in call.keywords)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "request"
                and not self._has_headers(node)
            ):
                yield Finding(
                    self.id, ctx.rel, node.lineno,
                    "outgoing HTTP request without headers= — pass "
                    "headers=trace_headers() so the X-Kvtpu-Trace context "
                    "survives the hop",
                )
            if (
                isinstance(node, ast.FunctionDef)
                and node.name in _HTTP_HANDLER_NAMES
            ):
                refs = {
                    n.id
                    for n in ast.walk(node)
                    if isinstance(n, ast.Name)
                } | {
                    n.attr
                    for n in ast.walk(node)
                    if isinstance(n, ast.Attribute)
                }
                if not (refs & _TRACE_PARSE_NAMES):
                    yield Finding(
                        self.id, ctx.rel, node.lineno,
                        f"{node.name} never parses the incoming trace "
                        "header (parse_trace_header/TRACE_HEADER) — "
                        "server-side spans orphan into fresh traces "
                        "instead of parenting under the caller's span",
                    )


#: uppercase module-level counters whose `.inc()` inside a loop marks that
#: loop as a multi-pass host iteration (squaring passes, BFS levels, delta
#: rounds — the package's pass-counter naming convention)
_PASS_COUNTER_RE = re.compile(r"^[A-Z0-9_]*(ITERATIONS|LEVELS|ROUNDS)[A-Z0-9_]*$")


@register
class LongLoopProgressRule(Rule):
    id = "long-loop-progress"
    rationale = (
        "A multi-pass host loop (one that bumps a pass counter like "
        "CLOSURE_ITERATIONS / *_LEVELS / *_ROUNDS per trip) can run for "
        "minutes at flagship scale with nothing but a frozen terminal to "
        "show for it. Every such loop must drive a ProgressTicker "
        "(`ticker.tick(...)` in the loop body) so operators get pass "
        "counts, smoothed rates and ETAs on /healthz, `kv-tpu jobs` and "
        "`kv-tpu top` — a silent long loop is indistinguishable from a "
        "hung one."
    )
    example = (
        "while True:\n"
        "    CLOSURE_ITERATIONS.inc()  # pass counter, no ticker.tick()\n"
        "    cur = step(cur)"
    )

    @staticmethod
    def _body_calls(loop: ast.AST) -> Iterable[ast.Call]:
        # the loop's own body/orelse only — a nested loop's calls belong
        # to the nested loop's finding (its ticks cannot discharge the
        # OUTER loop's obligation), and a nested def's calls to neither
        stack = list(ast.iter_child_nodes(loop))
        while stack:
            node = stack.pop()
            if isinstance(
                node,
                (ast.For, ast.While, ast.FunctionDef, ast.AsyncFunctionDef),
            ):
                continue
            if isinstance(node, ast.Call):
                yield node
            stack.extend(ast.iter_child_nodes(node))

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            counter = None
            ticked = False
            for call in self._body_calls(loop):
                if not isinstance(call.func, ast.Attribute):
                    continue
                if call.func.attr == "tick":
                    ticked = True
                elif (
                    call.func.attr == "inc"
                    and isinstance(call.func.value, ast.Name)
                    and _PASS_COUNTER_RE.match(call.func.value.id)
                ):
                    counter = call.func.value.id
            if counter and not ticked:
                yield Finding(
                    self.id, ctx.rel, loop.lineno,
                    f"multi-pass loop bumps {counter} but never calls "
                    "ticker.tick() — drive a ProgressTicker so the pass "
                    "count, rate and ETA reach /healthz and kv-tpu "
                    "jobs/top",
                )
