"""Flow-aware JAX/TPU rules: host-sync leaks inside jitted code and
recompile hazards around jit cache keys.

These are the rules the ad-hoc scripts could never express: both need to
know *which* functions are traced (decorated or wrapped with ``jax.jit`` /
``shard_map`` / the ``parallel.mesh`` compat wrapper) and *which* values in
them are tracer-origin. ``jit-host-sync`` runs a small within-function
dataflow pass: non-static parameters seed a taint set, assignments
propagate it (to a fixpoint, so loops converge), and attribute reads that
return static metadata (``.shape``/``.dtype``/``.ndim``/...) *kill* it —
``int(x.shape[0])`` inside jit is fine, ``int(x[0])`` is a trace-time
crash. A flagged ``.item()``/``float()``/``np.asarray``/... on a tainted
value is a host round-trip (or a ``ConcretizationTypeError`` /
``TracerBoolConversionError``) caught before runtime — the bug class that
silently destroys the ROADMAP's peak-FLOP/s batched-query target.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple, Union

from .core import FileContext, Finding, Rule, register
from .rules_hygiene import _dotted, _last_name

FunctionNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]

#: attribute reads that return host-static metadata, not a tracer — taint
#: stops here (`x.shape[0]` is a Python int during tracing)
SHAPE_KILL_ATTRS = frozenset(
    {"shape", "dtype", "ndim", "size", "itemsize", "nbytes", "weak_type",
     "aval", "sharding"}
)

#: builtins that return static values even for tracer operands
KILL_CALLS = frozenset({"len", "isinstance", "type", "id", "repr"})

#: method calls that force a device→host sync on a traced/deviced value
SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})

#: `bool(x)`/`float(x)`/`int(x)` on a tracer: trace-time crash
CONCRETIZING_BUILTINS = frozenset({"bool", "float", "int", "complex"})

#: host-materialising calls by dotted name
HOST_FETCH_CALLS = frozenset({
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "onp.asarray", "onp.array", "jax.device_get",
})

_JIT_NAMES = frozenset({"jit", "pjit"})
_WRAPPER_NAMES = frozenset({"shard_map", "pmap", "xmap", "vmap_of_jit"})


def _const_str_set(node: ast.expr) -> Set[str]:
    """A ``static_argnames`` value → the set of names it pins."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out: Set[str] = set()
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.add(elt.value)
        return out
    return set()


def _const_int_tuple(node: ast.expr) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            elt.value
            for elt in node.elts
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int)
        )
    return ()


def _param_names(fn: FunctionNode) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


class _JitSite:
    """One traced function plus the statically-pinned parameter names and
    the donated parameter indices (``donate_argnums``/``donate_argnames``)."""

    def __init__(self, fn: FunctionNode, static: Set[str],
                 argnums: Tuple[int, ...],
                 donate_nums: Tuple[int, ...] = (),
                 donate_names: Set[str] = frozenset()):
        self.fn = fn
        params = _param_names(fn)
        self.static = set(static)
        for i in argnums:
            if 0 <= i < len(params):
                self.static.add(params[i])
        self.donated: Set[int] = {i for i in donate_nums if 0 <= i < len(params)}
        for name in donate_names:
            if name in params:
                self.donated.add(params.index(name))


def _jit_call_info(
    call: ast.Call,
) -> Optional[Tuple[Set[str], Tuple[int, ...], Tuple[int, ...], Set[str]]]:
    """(static_argnames, static_argnums, donate_argnums, donate_argnames)
    when ``call`` is jit-ish (``jax.jit(...)`` / ``jax.pjit(...)`` or
    ``partial(jax.jit, ...)``), else None."""
    name = _last_name(call.func)
    static: Set[str] = set()
    argnums: Tuple[int, ...] = ()
    donate_nums: Tuple[int, ...] = ()
    donate_names: Set[str] = set()
    is_jit = False
    if name in _JIT_NAMES:
        is_jit = True
    elif name == "partial" and call.args:
        inner = _last_name(call.args[0])
        if inner in _JIT_NAMES:
            is_jit = True
    if not is_jit:
        return None
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            static |= _const_str_set(kw.value)
        elif kw.arg == "static_argnums":
            argnums = _const_int_tuple(kw.value)
        elif kw.arg == "donate_argnums":
            donate_nums = _const_int_tuple(kw.value)
        elif kw.arg == "donate_argnames":
            donate_names = _const_str_set(kw.value)
    return static, argnums, donate_nums, donate_names


def _unwrap_traced_target(node: ast.expr) -> Optional[ast.expr]:
    """Peel ``shard_map(f, ...)`` / ``partial(f, ...)`` wrappers off a jit
    argument until a Name / Lambda / def reference remains."""
    seen = 0
    while isinstance(node, ast.Call) and seen < 8:
        name = _last_name(node.func)
        if name in _WRAPPER_NAMES or name == "partial":
            if not node.args:
                return None
            node = node.args[0]
            seen += 1
        else:
            return None if name in _JIT_NAMES else node
    return node


def collect_jit_sites(tree: ast.AST) -> Tuple[List[_JitSite], Dict[str, _JitSite]]:
    """Every traced function in a module: decorator forms
    (``@jax.jit`` / ``@partial(jax.jit, static_argnames=...)`` /
    ``@shard_map``-style wrappers) and call forms
    (``f2 = jax.jit(shard_map(f, ...))`` / ``jax.jit(lambda x: ...)``).
    Returns the sites plus a name → site map for call-site rules."""
    defs_by_name: Dict[str, List[FunctionNode]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    sites: List[_JitSite] = []
    by_name: Dict[str, _JitSite] = {}
    covered: Set[int] = set()

    def add(fn: FunctionNode, static: Set[str], argnums: Tuple[int, ...],
            donate_nums: Tuple[int, ...] = (),
            donate_names: Set[str] = frozenset(),
            name: Optional[str] = None) -> None:
        if id(fn) in covered:
            return
        covered.add(id(fn))
        site = _JitSite(fn, static, argnums, donate_nums, donate_names)
        sites.append(site)
        if name:
            by_name.setdefault(name, site)
        elif isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(fn.name, site)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _last_name(dec) in _JIT_NAMES | _WRAPPER_NAMES:
                    add(node, set(), ())
                elif isinstance(dec, ast.Call):
                    info = _jit_call_info(dec)
                    if info is not None:
                        add(node, *info)
                    elif _last_name(dec.func) in _WRAPPER_NAMES:
                        add(node, set(), ())
        elif isinstance(node, ast.Call):
            info = _jit_call_info(node)
            if info is None or not node.args:
                continue
            static, argnums, dnums, dnames = info
            target = _unwrap_traced_target(node.args[0])
            if isinstance(target, ast.Lambda):
                add(target, static, argnums, dnums, dnames)
            elif isinstance(target, ast.Name):
                for fn in defs_by_name.get(target.id, ()):
                    add(fn, static, argnums, dnums, dnames, name=target.id)

    # bind `f2 = jax.jit(...)` assignment names so call-site rules can see
    # through the alias
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
            continue
        if _jit_call_info(node.value) is None or not node.value.args:
            continue
        target = _unwrap_traced_target(node.value.args[0])
        bound: Optional[_JitSite] = None
        if isinstance(target, ast.Name):
            for fn in defs_by_name.get(target.id, ()):
                if id(fn) in covered:
                    bound = next(s for s in sites if s.fn is fn)
                    break
        elif isinstance(target, ast.Lambda) and id(target) in covered:
            bound = next(s for s in sites if s.fn is target)
        if bound is None:
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                by_name.setdefault(tgt.id, bound)
    return sites, by_name


class _TaintPass:
    """Within-function forward dataflow over tracer-origin values."""

    def __init__(self, site: _JitSite):
        self.fn = site.fn
        self.tainted: Set[str] = {
            p for p in _param_names(site.fn) if p not in site.static
        }
        # nested defs/lambdas inside a traced function are trace callbacks
        # (scan/cond/fori bodies): their parameters carry tracers too
        for node in ast.walk(self.fn):
            if node is self.fn:
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                self.tainted.update(_param_names(node))

    # ---------------------------------------------------------- expression
    def is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in SHAPE_KILL_ATTRS:
                return False
            return self.is_tainted(node.value)
        if isinstance(node, ast.Call):
            fname = _last_name(node.func)
            if fname in KILL_CALLS:
                return False
            if self.is_tainted(node.func):
                return True
            return any(self.is_tainted(a) for a in node.args) or any(
                self.is_tainted(kw.value) for kw in node.keywords
            )
        if isinstance(node, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        return any(self.is_tainted(c) for c in ast.iter_child_nodes(node))

    # ----------------------------------------------------------- statements
    def _bind(self, target: ast.expr, value_tainted: bool) -> bool:
        changed = False
        if isinstance(target, ast.Name):
            if value_tainted and target.id not in self.tainted:
                self.tainted.add(target.id)
                changed = True
            elif not value_tainted and target.id in self.tainted:
                # a host-origin rebind (e.g. `x = np.ones(3)`) kills taint
                self.tainted.discard(target.id)
                changed = True
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                changed |= self._bind(elt, value_tainted)
        elif isinstance(target, ast.Starred):
            changed |= self._bind(target.value, value_tainted)
        return changed

    def run(self) -> None:
        for _ in range(10):  # fixpoint; loops re-taint in later passes
            changed = False
            for node in ast.walk(self.fn):
                if isinstance(node, ast.Assign):
                    t = self.is_tainted(node.value)
                    for tgt in node.targets:
                        changed |= self._bind(tgt, t)
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    changed |= self._bind(node.target, self.is_tainted(node.value))
                elif isinstance(node, ast.AugAssign):
                    t = self.is_tainted(node.target) or self.is_tainted(node.value)
                    if t and isinstance(node.target, ast.Name):
                        if node.target.id not in self.tainted:
                            self.tainted.add(node.target.id)
                            changed = True
                elif isinstance(node, ast.NamedExpr):
                    changed |= self._bind(node.target, self.is_tainted(node.value))
                elif isinstance(node, ast.For):
                    changed |= self._bind(node.target, self.is_tainted(node.iter))
                elif isinstance(node, ast.comprehension):
                    changed |= self._bind(node.target, self.is_tainted(node.iter))
                elif isinstance(node, ast.With):
                    for item in node.items:
                        if item.optional_vars is not None:
                            changed |= self._bind(
                                item.optional_vars,
                                self.is_tainted(item.context_expr),
                            )
            if not changed:
                break


@register
class JitHostSyncRule(Rule):
    id = "jit-host-sync"
    rationale = (
        "Inside a function traced by `jax.jit`/`shard_map`, a "
        "`.item()`/`.tolist()`/`bool()`/`float()`/`int()`/`np.asarray`/"
        "`jax.device_get`/`.block_until_ready()` on a tracer-origin value "
        "is at best a host round-trip serialising the hot path (the silent "
        "killer of the peak-FLOP/s batched-query target) and at worst a "
        "trace-time `ConcretizationTypeError`/`TracerBoolConversionError`. "
        "The rule runs a within-function dataflow pass: non-static "
        "parameters seed the tracer set, assignments propagate it, and "
        "static-metadata reads (`.shape`, `.dtype`, `len()`) kill it — so "
        "`int(x.shape[0])` passes while `int(x[0])` two assignments later "
        "is still caught. With interprocedural summaries (PR 9) the rule "
        "also crosses call boundaries: a helper that syncs one of its "
        "parameters is flagged at the jitted call site feeding it a "
        "tracer, even when the sink is several helpers down the chain."
    )
    example = (
        "@jax.jit\n"
        "def f(x):\n"
        "    y = x * 2\n"
        "    z = y.sum()\n"
        "    return z.item()  # host sync inside jit"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        sites, _ = collect_jit_sites(ctx.tree)
        seen: Set[Tuple[int, str]] = set()
        program = getattr(ctx, "program", None)
        for site in sites:
            taint = _TaintPass(site)
            taint.run()
            found = self._scan_sinks(ctx, site, taint)
            if program is not None:
                found = list(found) + list(
                    self._scan_helper_calls(ctx, site, taint, program)
                )
            for f in found:
                key = (f.line, f.message)
                if key not in seen:
                    seen.add(key)
                    yield f

    def _scan_helper_calls(self, ctx: FileContext, site: _JitSite,
                           taint: _TaintPass, program):
        """Cross-function sinks: a call inside traced code whose argument
        feeds a callee parameter that (transitively) hits a host sync."""
        from .callgraph import module_name

        module = module_name(ctx.rel)
        qn = program.graph.qname_of(site.fn)
        own = program.summaries.get(qn) if qn else None
        class_name = own.info.class_name if own else None
        for node in ast.walk(site.fn):
            if not isinstance(node, ast.Call):
                continue
            callee_qn = program.graph.resolve_call(module, node, class_name)
            callee = program.summaries.get(callee_qn) if callee_qn else None
            if callee is None or not callee.param_syncs:
                continue
            offset = (
                1
                if callee.info.class_name
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in ("self", "cls")
                else 0
            )
            params = callee.local.params
            for j, sinks in sorted(callee.param_syncs.items()):
                expr: Optional[ast.expr] = None
                pos = j - offset
                if 0 <= pos < len(node.args):
                    expr = node.args[pos]
                elif j < len(params):
                    for kw in node.keywords:
                        if kw.arg == params[j]:
                            expr = kw.value
                if expr is None or not taint.is_tainted(expr):
                    continue
                pname = params[j] if j < len(params) else f"#{j}"
                helper = callee.info.node.name
                yield Finding(
                    self.id, ctx.rel, node.lineno,
                    f"tracer passed to {helper}() parameter {pname!r}, "
                    f"which performs {sinks[0].described()} — host sync "
                    "reached from a jitted function through a helper "
                    "call; keep the value an array through the chain or "
                    "hoist the sync out of jit",
                )

    def _scan_sinks(self, ctx: FileContext, site: _JitSite, taint: _TaintPass):
        for node in ast.walk(site.fn):
            if isinstance(node, ast.Call):
                fname = _last_name(node.func)
                dotted = _dotted(node.func)
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in SYNC_METHODS
                    and taint.is_tainted(node.func.value)
                ):
                    yield Finding(
                        self.id, ctx.rel, node.lineno,
                        f".{node.func.attr}() on a tracer-origin value "
                        "inside a jitted function — device→host sync in "
                        "the traced hot path; return the array and convert "
                        "outside jit",
                    )
                elif (
                    isinstance(node.func, ast.Name)
                    and fname in CONCRETIZING_BUILTINS
                    and node.args
                    and taint.is_tainted(node.args[0])
                ):
                    yield Finding(
                        self.id, ctx.rel, node.lineno,
                        f"{fname}() concretises a tracer inside a jitted "
                        "function — trace-time ConcretizationTypeError; "
                        "keep it an array (jnp ops / lax.cond) or hoist "
                        "out of jit",
                    )
                elif dotted in HOST_FETCH_CALLS and (
                    any(taint.is_tainted(a) for a in node.args)
                ):
                    yield Finding(
                        self.id, ctx.rel, node.lineno,
                        f"{dotted}() materialises a tracer on the host "
                        "inside a jitted function — use jnp equivalents "
                        "or move the fetch outside jit",
                    )
            elif isinstance(node, (ast.If, ast.While)) and taint.is_tainted(
                node.test
            ):
                yield Finding(
                    self.id, ctx.rel, node.lineno,
                    "Python branch on a tracer inside a jitted function — "
                    "TracerBoolConversionError at trace time; use "
                    "jnp.where / lax.cond / lax.while_loop",
                )
            elif isinstance(node, ast.Assert) and taint.is_tainted(node.test):
                yield Finding(
                    self.id, ctx.rel, node.lineno,
                    "assert on a tracer inside a jitted function — "
                    "TracerBoolConversionError at trace time; use "
                    "checkify or assert on static metadata only",
                )


#: registrar call names from ``observe.aot`` (leading underscores of
#: import aliases like ``_register_kernel`` are stripped before matching)
_AOT_REGISTRARS = frozenset({"register_kernel", "transient_kernel"})


@register
class AotUnregisteredKernelRule(Rule):
    id = "aot-unregistered-kernel"
    rationale = (
        "Warm start is a production SLO: every module-level jitted entry "
        "point must be registered in the AOT kernel manifest "
        "(`observe.aot.register_kernel` / `transient_kernel`) so its "
        "compiled executable lands in the checkpoint-shipped warm pack "
        "and `kvtpu_aot_cache_{hits,misses}_total` can account for it. "
        "An unregistered jit silently recompiles on every cold start — "
        "the recovery/promotion paths then miss their "
        "resume_to_first_answer_s budget with nothing in the metrics to "
        "say why. Registration is one line at module end: "
        "`_kernel = register_kernel(\"engine\", \"_kernel\", _kernel, "
        "static_argnames=(...))`. Kernels jitted per call inside a "
        "function (transient shapes) use `transient_kernel` at the jit "
        "site instead. Legacy modules predating the manifest are "
        "grandfathered in `LINT_BASELINE.json`."
    )
    example = (
        "@partial(jax.jit, static_argnames=(\"tile\",))\n"
        "def _my_step(x, *, tile):  # never passed to register_kernel\n"
        "    ...\n"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        registered: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = (_last_name(node.func) or "").lstrip("_")
            if name not in _AOT_REGISTRARS:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    registered.add(arg.id)
        for stmt in ctx.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if stmt.name in registered:
                    continue
                for dec in stmt.decorator_list:
                    jitted = _last_name(dec) in _JIT_NAMES or (
                        isinstance(dec, ast.Call)
                        and _jit_call_info(dec) is not None
                    )
                    if jitted:
                        yield Finding(
                            self.id, ctx.rel, stmt.lineno,
                            f"module-level jitted entry point "
                            f"{stmt.name}() is not in the AOT kernel "
                            "manifest — register it via observe.aot."
                            "register_kernel so the warm pack covers it",
                        )
                        break
            elif isinstance(stmt, ast.Assign) and isinstance(
                stmt.value, ast.Call
            ):
                if _jit_call_info(stmt.value) is None:
                    continue
                for tgt in stmt.targets:
                    if (
                        isinstance(tgt, ast.Name)
                        and tgt.id not in registered
                    ):
                        yield Finding(
                            self.id, ctx.rel, stmt.lineno,
                            f"module-level jitted binding {tgt.id} is "
                            "not in the AOT kernel manifest — register "
                            "it via observe.aot.register_kernel so the "
                            "warm pack covers it",
                        )


_KEYISH = ("key", "sig", "cache", "memo")


def _contains_shape_attr(node: ast.AST) -> bool:
    return any(
        isinstance(n, ast.Attribute) and n.attr == "shape"
        for n in ast.walk(node)
    )


@register
class RecompileHazardRule(Rule):
    id = "recompile-hazard"
    rationale = (
        "jit recompiles are the silent latency cliff "
        "(`kvtpu_jit_recompiles_total` exists to count them at runtime; "
        "this rule catches the causes statically). Flagged: (1) f-string/"
        "`str()` of `.shape` used as a cache key — string keys collide "
        "across dtypes and miss weak_type, so the cache lies about "
        "recompiles (hash the `abstract_signature` tuple instead); "
        "(2) `static_argnames` naming a parameter the function does not "
        "have — the typo'd name is silently never static; (3) a Python "
        "`float` or an unhashable list/dict/set literal passed for a "
        "static parameter — every distinct float is a fresh compile cache "
        "entry (and NaN never hits), unhashables raise at dispatch; "
        "(4) `tuple(d.values()/items()/keys())` fed straight into a jitted "
        "call — the signature then depends on dict iteration order "
        "(`sorted(...)` first)."
    )
    example = 'key = f"{x.shape}-{backend}"\n_cache[key] = compiled'

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        sites, by_name = collect_jit_sites(ctx.tree)
        yield from self._check_shape_keys(ctx)
        yield from self._check_static_argnames(ctx, sites)
        yield from self._check_call_sites(ctx, by_name)

    # -------------------------------------------------- str(shape) keys
    def _check_shape_keys(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            is_shape_str = (
                isinstance(node, ast.JoinedStr) and _contains_shape_attr(node)
            ) or (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "str"
                and node.args
                and _contains_shape_attr(node.args[0])
            )
            if not is_shape_str:
                continue
            if self._used_as_key(ctx, node):
                yield Finding(
                    self.id, ctx.rel, node.lineno,
                    "stringified .shape used as a cache key — collides "
                    "across dtypes and misses weak_type, so the jit cache "
                    "lies about recompiles; key on the abstract-signature "
                    "tuple (observe.jit.abstract_signature) instead",
                )

    @staticmethod
    def _used_as_key(ctx: FileContext, node: ast.AST) -> bool:
        prev: ast.AST = node
        for anc in ctx.ancestors(node):
            if isinstance(anc, ast.Subscript) and prev is anc.slice:
                return True
            if isinstance(anc, ast.Assign):
                for tgt in anc.targets:
                    name = _last_name(tgt) or ""
                    if any(k in name.lower() for k in _KEYISH):
                        return True
            if isinstance(anc, ast.Call) and prev is not anc.func:
                name = _last_name(anc.func) or ""
                if any(k in name.lower() for k in _KEYISH):
                    return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
            prev = anc
        return False

    # --------------------------------------- static_argnames typo check
    def _check_static_argnames(self, ctx: FileContext, sites: Sequence[_JitSite]):
        for site in sites:
            if isinstance(site.fn, ast.Lambda):
                continue
            params = set(_param_names(site.fn))
            unknown = sorted(site.static - params)
            if unknown:
                yield Finding(
                    self.id, ctx.rel, site.fn.lineno,
                    f"static_argnames {unknown} name no parameter of "
                    f"{site.fn.name}() — the typo'd arg is silently "
                    "traced, recompiling on every new value",
                )

    # ---------------------------------------------- jitted call sites
    def _check_call_sites(self, ctx: FileContext, by_name: Dict[str, _JitSite]):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = _last_name(node.func)
            site = by_name.get(callee or "")
            if site is None:
                continue
            for kw in node.keywords:
                if kw.arg not in site.static:
                    continue
                if isinstance(kw.value, ast.Constant) and isinstance(
                    kw.value.value, float
                ):
                    yield Finding(
                        self.id, ctx.rel, node.lineno,
                        f"Python float for static arg {kw.arg!r} of "
                        f"{callee}() — every distinct value is a fresh "
                        "XLA compile (and NaN never cache-hits); pass it "
                        "as a traced operand or quantise to int",
                    )
                elif isinstance(kw.value, (ast.List, ast.Dict, ast.Set)):
                    yield Finding(
                        self.id, ctx.rel, node.lineno,
                        f"unhashable literal for static arg {kw.arg!r} of "
                        f"{callee}() — jit static args must be hashable "
                        "(use a tuple)",
                    )
            for arg in node.args:
                if (
                    isinstance(arg, ast.Call)
                    and isinstance(arg.func, ast.Name)
                    and arg.func.id == "tuple"
                    and arg.args
                    and isinstance(arg.args[0], ast.Call)
                    and isinstance(arg.args[0].func, ast.Attribute)
                    and arg.args[0].func.attr in ("values", "items", "keys")
                ):
                    yield Finding(
                        self.id, ctx.rel, node.lineno,
                        f"tuple(dict.{arg.args[0].func.attr}()) passed to "
                        f"jitted {callee}() — the jit signature then "
                        "depends on dict iteration order; sorted(...) it "
                        "first",
                    )
