"""Interprocedural rules: mesh/collective consistency, use-after-donate,
and the CLI exit-code contract.

All three stand on the :mod:`.callgraph` + :mod:`.summaries` program view
attached to every :class:`~.core.FileContext` by the runner:

* ``collective-axis`` — the static form of the distributed-kernel abort
  class PAPERS.md's TPU linear-algebra work calls out: a ``psum`` /
  ``all_gather`` whose ``axis_name`` does not name an axis of the enclosing
  ``shard_map`` mesh fails at trace time on device (and on a mesh that
  *happens* to define the name, silently reduces over the wrong axis).
  Reachability is computed over the call graph, so a collective buried two
  helpers below the ``shard_map``-wrapped body is still checked.
* ``donation-hazard`` — ``donate_argnums`` hands the buffer to XLA; any
  later read sees invalidated memory (jax raises on CPU, garbage is
  possible elsewhere). The read-after-donate scan follows donation through
  helper calls via summaries.
* ``exit-contract`` — every CLI subcommand handler registered with
  ``set_defaults(fn=...)`` must keep its reachable ``KvTpuError`` raises
  inside the documented 0/1/2/3 exit-code mapping; a taxonomy error that
  can escape a handler uncaught is a lint failure, not a field bug.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import FileContext, Finding, Rule, register
from .rules_hygiene import _last_name, walk_own
from .rules_jax import _param_names, _unwrap_traced_target, collect_jit_sites

#: wrappers that establish named mesh axes for the code they trace
_SHARD_WRAPPERS = frozenset({"shard_map", "pmap", "xmap"})

_DefNode = (ast.FunctionDef, ast.AsyncFunctionDef)


def _program(ctxs: Sequence[FileContext]):
    for ctx in ctxs:
        if ctx.program is not None:
            return ctx.program
    return None


# ------------------------------------------------------------ mesh axes
def _axis_strings(graph, module: str, node: ast.expr) -> Optional[Set[str]]:
    """The axis-name strings a Mesh axis-names argument pins, or None when
    any element is not statically resolvable."""
    elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    out: Set[str] = set()
    for elt in elts:
        s = graph.resolve_str(module, elt)
        if s is None:
            return None
        out.add(s)
    return out


def _mesh_call_axes(graph, module: str, call: ast.Call) -> Optional[Set[str]]:
    """Axes of a literal ``Mesh(devices, ("a", "b"))`` / ``make_mesh``-style
    construction, or None."""
    if _last_name(call.func) not in ("Mesh", "make_mesh", "AbstractMesh"):
        return None
    axis_arg: Optional[ast.expr] = None
    if len(call.args) >= 2:
        axis_arg = call.args[1]
    for kw in call.keywords:
        if kw.arg in ("axis_names", "axis_name"):
            axis_arg = kw.value
    if axis_arg is None:
        return None
    return _axis_strings(graph, module, axis_arg)


def _resolve_mesh_axes(
    ctx: FileContext, graph, module: str, mesh_expr: ast.expr
) -> Optional[Set[str]]:
    """Axes of the ``mesh=`` argument of a shard_map site, when statically
    known: a literal Mesh construction, or a name assigned one anywhere in
    the same file. An opaque mesh (function parameter, factory call) maps
    to None and the caller falls back to the program-wide axis universe."""
    if isinstance(mesh_expr, ast.Call):
        return _mesh_call_axes(graph, module, mesh_expr)
    if isinstance(mesh_expr, ast.Name):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Assign):
                continue
            if not any(
                isinstance(t, ast.Name) and t.id == mesh_expr.id
                for t in node.targets
            ):
                continue
            if isinstance(node.value, ast.Call):
                axes = _mesh_call_axes(graph, module, node.value)
                if axes is not None:
                    return axes
    return None


def _axis_universe(ctxs: Sequence[FileContext], graph) -> Set[str]:
    """Every axis name the program mentions anywhere: ``*_AXIS`` string
    constants, literal Mesh constructions, and ``P(...)`` partition specs.
    The fallback oracle for shard_map sites whose mesh is opaque — an axis
    name outside even this set names no mesh axis in the whole program."""
    from .callgraph import module_name

    out: Set[str] = set()
    for consts in graph.str_constants.values():
        for name, val in consts.items():
            if name.endswith("_AXIS"):
                out.add(val)
    for ctx in ctxs:
        if ctx.tree is None:
            continue
        mod = module_name(ctx.rel)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            axes = _mesh_call_axes(graph, mod, node)
            if axes:
                out |= axes
            if _last_name(node.func) in ("P", "PartitionSpec"):
                for a in node.args:
                    s = graph.resolve_str(mod, a)
                    if s is not None:
                        out.add(s)
    return out


def _partial_bindings(node: ast.expr) -> Tuple[int, Set[str], ast.expr]:
    """Peel ``partial(f, a, b, kw=...)`` → (n positionals bound, kw names
    bound, the innermost target expression)."""
    n_pos = 0
    kw_names: Set[str] = set()
    depth = 0
    while (
        isinstance(node, ast.Call)
        and _last_name(node.func) == "partial"
        and node.args
        and depth < 8
    ):
        n_pos += len(node.args) - 1
        kw_names |= {kw.arg for kw in node.keywords if kw.arg}
        node = node.args[0]
        depth += 1
    return n_pos, kw_names, node


def _literal_return_arity(fn: ast.AST) -> Optional[int]:
    """The tuple arity every ``return`` in ``fn`` (own scope) agrees on,
    or None when returns are not all literal tuples of one length."""
    arity: Optional[int] = None
    for node in walk_own(fn):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        if not isinstance(node.value, ast.Tuple):
            return None
        n = len(node.value.elts)
        if arity is None:
            arity = n
        elif arity != n:
            return None
    return arity


@register
class CollectiveAxisRule(Rule):
    id = "collective-axis"
    rationale = (
        "A `psum`/`all_gather`/`ppermute`/`psum_scatter` with an "
        "`axis_name` the enclosing `shard_map` mesh does not define aborts "
        "at trace time on device — and when an unrelated mesh *does* "
        "define the name, silently reduces over the wrong axis (the "
        "block-distributed-matmul failure mode PAPERS.md's TPU "
        "linear-algebra paper warns about). The rule resolves each "
        "shard_map site's mesh axes (literal `Mesh((...))` constructions, "
        "or the program-wide axis universe of `*_AXIS` constants and "
        "`P(...)` specs when the mesh is an opaque parameter), walks the "
        "call graph so collectives in helpers below the wrapped body are "
        "covered, checks `in_specs`/`out_specs` arity against the wrapped "
        "function's signature, and flags collectives only reachable from "
        "un-sharded entry points — a collective outside any axis-binding "
        "wrapper is a guaranteed `NameError`-style trace abort."
    )
    example = (
        "mesh = Mesh(devs, (\"pods\", \"grants\"))\n"
        "def body(x):\n"
        "    return jax.lax.psum(x, \"rows\")  # no such mesh axis\n"
        "f = jax.jit(shard_map(body, mesh=mesh, in_specs=P(\"pods\"),\n"
        "                      out_specs=P(\"pods\")))"
    )

    def check_project(self, ctxs: Sequence[FileContext]) -> Iterable[Finding]:
        from .callgraph import module_name

        program = _program(ctxs)
        if program is None:
            return
        graph = program.graph
        universe = _axis_universe(ctxs, graph)
        by_rel = {c.rel: c for c in ctxs}

        # 1. shard roots: functions wrapped by shard_map/pmap/xmap, with
        #    the mesh axes each wrap binds (None → opaque mesh)
        roots: Dict[str, Optional[Set[str]]] = {}

        def add_root(qn: str, axes: Optional[Set[str]]) -> None:
            if qn in roots:
                prev = roots[qn]
                roots[qn] = (
                    None if prev is None or axes is None else prev | axes
                )
            else:
                roots[qn] = axes

        for ctx in ctxs:
            if ctx.tree is None:
                continue
            mod = module_name(ctx.rel)
            for node in ast.walk(ctx.tree):
                if isinstance(node, _DefNode):
                    for dec in node.decorator_list:
                        dname = _last_name(
                            dec.func if isinstance(dec, ast.Call) else dec
                        )
                        if dname in _SHARD_WRAPPERS:
                            qn = graph.qname_of(node)
                            if qn:
                                axes = None
                                if isinstance(dec, ast.Call):
                                    for kw in dec.keywords:
                                        if kw.arg == "mesh":
                                            axes = _resolve_mesh_axes(
                                                ctx, graph, mod, kw.value
                                            )
                                add_root(qn, axes)
                    continue
                if not (
                    isinstance(node, ast.Call)
                    and _last_name(node.func) in _SHARD_WRAPPERS
                    and node.args
                ):
                    continue
                n_bound, kw_bound, target = _partial_bindings(node.args[0])
                # follow one level of local aliasing:
                # `body = partial(_k8s_local, ...)` → shard_map(body, ...)
                if (
                    isinstance(target, ast.Name)
                    and target.id
                    not in graph.module_scopes.get(mod, {})
                ):
                    for asn in ast.walk(ctx.tree):
                        if not (
                            isinstance(asn, ast.Assign)
                            and any(
                                isinstance(t, ast.Name)
                                and t.id == target.id
                                for t in asn.targets
                            )
                        ):
                            continue
                        n2, kw2, inner = _partial_bindings(asn.value)
                        if isinstance(inner, ast.Name) and inner is not target:
                            n_bound += n2
                            kw_bound |= kw2
                            target = inner
                            break
                axes = None
                in_specs = out_specs = None
                for kw in node.keywords:
                    if kw.arg == "mesh":
                        axes = _resolve_mesh_axes(ctx, graph, mod, kw.value)
                    elif kw.arg == "in_specs":
                        in_specs = kw.value
                    elif kw.arg == "out_specs":
                        out_specs = kw.value
                fn_node: Optional[ast.AST] = None
                qn = None
                if isinstance(target, ast.Name):
                    qn = graph.module_scopes.get(mod, {}).get(target.id)
                    if qn and qn in graph.functions:
                        fn_node = graph.functions[qn].node
                        add_root(qn, axes)
                elif isinstance(target, ast.Lambda):
                    fn_node = target
                if fn_node is not None:
                    yield from self._check_specs(
                        ctx, node, fn_node, n_bound, kw_bound,
                        in_specs, out_specs,
                    )

        # 2. allowed axes per function, propagated root → callees
        allowed: Dict[str, Set[str]] = {}
        work: List[Tuple[str, Set[str]]] = [
            (qn, axes if axes is not None else set(universe))
            for qn, axes in roots.items()
        ]
        while work:
            qn, axes = work.pop()
            cur = allowed.get(qn)
            if cur is not None and axes <= cur:
                continue
            allowed[qn] = (cur or set()) | axes
            info = graph.functions.get(qn)
            if info is None:
                continue
            for call in info.calls:
                work.append((call.callee, axes))

        # 3. judge every collective against its function's allowed axes
        for qn, summary in sorted(program.summaries.items()):
            if not summary.local.collectives:
                continue
            rel = summary.info.rel
            ctx = by_rel.get(rel)
            axes_here = allowed.get(qn)
            for coll in summary.local.collectives:
                if axes_here is None:
                    yield Finding(
                        self.id, rel, coll["line"],
                        f"{coll['kind']}() in {summary.info.node.name}() is "
                        "not reachable from any shard_map/pmap-wrapped "
                        "entry point — collectives outside an axis-binding "
                        "wrapper fail at trace time (unbound axis name)",
                    )
                    continue
                for axis in coll["axes"]:
                    name = (
                        program.resolve_axis(summary.info.module, axis)
                        if ctx is not None else None
                    )
                    if name is None and "s" in axis:
                        name = axis["s"]
                    if name is not None and name not in axes_here:
                        have = ", ".join(sorted(axes_here)) or "(none)"
                        yield Finding(
                            self.id, rel, coll["line"],
                            f"{coll['kind']}(axis_name={name!r}) — the "
                            f"enclosing shard_map mesh defines axes "
                            f"[{have}]; a collective over an undefined "
                            "axis aborts at trace time (or reduces over "
                            "the wrong axis on a mesh that happens to "
                            "define it)",
                        )

    def _check_specs(
        self,
        ctx: FileContext,
        call: ast.Call,
        fn_node: ast.AST,
        n_bound: int,
        kw_bound: Set[str],
        in_specs: Optional[ast.expr],
        out_specs: Optional[ast.expr],
    ) -> Iterable[Finding]:
        """Literal-tuple in_specs/out_specs arity vs the wrapped function's
        unbound signature. A single (non-tuple) spec legally broadcasts
        over the argument pytree, so only literal tuples are judged."""
        params = [
            p for p in _param_names(fn_node)[n_bound:] if p not in kw_bound
        ]
        name = getattr(fn_node, "name", "<lambda>")
        if isinstance(in_specs, ast.Tuple) and len(in_specs.elts) != len(params):
            yield Finding(
                self.id, ctx.rel, call.lineno,
                f"in_specs has {len(in_specs.elts)} entries but {name}() "
                f"takes {len(params)} unbound argument(s) "
                f"({', '.join(params) or 'none'}) — shard_map raises a "
                "structure mismatch at trace time",
            )
        if isinstance(out_specs, ast.Tuple):
            arity = _literal_return_arity(fn_node)
            if arity is not None and arity != len(out_specs.elts):
                yield Finding(
                    self.id, ctx.rel, call.lineno,
                    f"out_specs has {len(out_specs.elts)} entries but "
                    f"{name}() returns {arity}-tuples — shard_map raises "
                    "a structure mismatch at trace time",
                )


# ------------------------------------------------------- donation hazard
@register
class DonationHazardRule(Rule):
    id = "donation-hazard"
    rationale = (
        "`donate_argnums`/`donate_argnames` hands the buffer to XLA for "
        "in-place reuse; any read after the jitted call sees invalidated "
        "memory (jax raises `RuntimeError: Array has been deleted` on CPU "
        "— on other backends the failure can be silent). The rule finds "
        "every call to a donating jitted callable (same-file sites "
        "directly, helpers that forward a parameter into a donating call "
        "through summaries), then scans the enclosing scope for reads of "
        "the donated name after the call: a straight-line read before any "
        "rebind, or any read in an enclosing loop whose body never "
        "rebinds the name (the second iteration reads a donated buffer). "
        "`cur = step(cur)` is the sanctioned pattern — the rebind makes "
        "later reads see the fresh buffer."
    )
    example = (
        "@partial(jax.jit, donate_argnums=(0,))\n"
        "def step(buf):\n"
        "    return buf + 1\n"
        "out = step(buf)\n"
        "print(buf.sum())  # use-after-donate"
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        from .callgraph import module_name

        _sites, by_name = collect_jit_sites(ctx.tree)
        donators = {
            name: site.donated
            for name, site in by_name.items()
            if site.donated
        }
        program = ctx.program
        mod = module_name(ctx.rel)

        scopes: List[ast.AST] = [ctx.tree]
        for node in ast.walk(ctx.tree):
            if isinstance(node, _DefNode):
                scopes.append(node)
        for scope in scopes:
            yield from self._scan_scope(ctx, scope, donators, program, mod)

    def _donated_args(
        self, call: ast.Call, donators: Dict[str, Set[int]], program, mod: str,
        class_name: Optional[str],
    ) -> List[Tuple[str, str, Tuple[str, ...]]]:
        """(donated-name, callee-name, via-chain) for each bare-Name
        argument this call donates, directly or through a helper."""
        out: List[Tuple[str, str, Tuple[str, ...]]] = []
        callee_name = _last_name(call.func)
        direct = donators.get(callee_name or "")
        if direct:
            for i in direct:
                if i < len(call.args) and isinstance(call.args[i], ast.Name):
                    out.append((call.args[i].id, callee_name, ()))
            return out
        if program is None:
            return out
        qn = program.graph.resolve_call(mod, call, class_name)
        summary = program.summaries.get(qn) if qn else None
        if summary is None or not summary.donates:
            return out
        offset = (
            1
            if summary.info.class_name
            and isinstance(call.func, ast.Attribute)
            and isinstance(call.func.value, ast.Name)
            and call.func.value.id in ("self", "cls")
            else 0
        )
        for j, (_line, via) in sorted(summary.donates.items()):
            pos = j - offset
            if 0 <= pos < len(call.args) and isinstance(
                call.args[pos], ast.Name
            ):
                out.append(
                    (call.args[pos].id, summary.info.node.name, via)
                )
        return out

    def _scan_scope(
        self, ctx: FileContext, scope: ast.AST,
        donators: Dict[str, Set[int]], program, mod: str,
    ) -> Iterable[Finding]:
        class_name = None
        if isinstance(scope, _DefNode):
            parent = ctx.parent(scope)
            if isinstance(parent, ast.ClassDef):
                class_name = parent.name

        nodes = list(walk_own(scope))
        loads: Dict[str, List[int]] = {}
        stores: Dict[str, List[int]] = {}
        for node in nodes:
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    loads.setdefault(node.id, []).append(node.lineno)
                else:
                    stores.setdefault(node.id, []).append(node.lineno)

        # loop extents in this scope (own walk: nested defs excluded)
        loops: List[Tuple[int, int]] = []
        for node in nodes:
            if isinstance(node, (ast.For, ast.While)):
                end = max(
                    (n.lineno for n in ast.walk(node)
                     if hasattr(n, "lineno")),
                    default=node.lineno,
                )
                loops.append((node.lineno, end))

        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            for name, callee, via in self._donated_args(
                node, donators, program, mod, class_name
            ):
                chain = f" (via {' -> '.join(via)})" if via else ""
                line = node.lineno
                # loop case: the call re-executes; a read anywhere in the
                # loop without a rebind in the loop is a hazard
                in_loop = next(
                    ((s, e) for s, e in loops if s <= line <= e), None
                )
                if in_loop is not None:
                    s, e = in_loop
                    rebinds = [
                        ln for ln in stores.get(name, []) if s <= ln <= e
                    ]
                    if not rebinds:
                        reads = [
                            ln for ln in loads.get(name, []) if s <= ln <= e
                        ]
                        if reads:
                            yield Finding(
                                self.id, ctx.rel, line,
                                f"{name!r} is donated to {callee}(){chain} "
                                "inside a loop and never rebound there — "
                                "the next iteration reads a donated "
                                "buffer; rebind it "
                                f"(`{name} = {callee}(...)`) or drop the "
                                "donation",
                            )
                            continue
                first_rebind = min(
                    (ln for ln in stores.get(name, []) if ln >= line),
                    default=None,
                )
                late_reads = [
                    ln for ln in loads.get(name, [])
                    if ln > line
                    and (first_rebind is None or ln < first_rebind)
                ]
                if late_reads:
                    yield Finding(
                        self.id, ctx.rel, late_reads[0],
                        f"{name!r} read after being donated to "
                        f"{callee}(){chain} at line {line} — "
                        "use-after-donate (jax invalidates donated "
                        "buffers); read the call's result instead or "
                        "remove it from donate_argnums",
                    )


# --------------------------------------------------------- exit contract
@register
class ExitContractRule(Rule):
    id = "exit-contract"
    rationale = (
        "The CLI documents a 0/1/2/3 exit-code contract (ok / violations "
        "found / input error / backend failure) and `resilience.errors."
        "exit_code_for` implements it — but only for `KvTpuError`s a "
        "handler actually catches. This rule discovers every subcommand "
        "handler registered via `set_defaults(fn=...)`, takes its "
        "summary's transitive escaped-raise set (guards are "
        "hierarchy-aware: `except KvTpuError` catches every subclass), "
        "and flags any `KvTpuError`-family type that can escape — a new "
        "taxonomy subclass nobody routes through `exit_code_for` would "
        "otherwise surface as a raw traceback in the field instead of a "
        "diagnosable exit code."
    )
    example = (
        "def cmd_new(args):\n"
        "    run()  # can raise ConfigError — no except KvTpuError\n"
        "p.set_defaults(fn=cmd_new)"
    )

    def check_project(self, ctxs: Sequence[FileContext]) -> Iterable[Finding]:
        from .callgraph import module_name
        from .summaries import exception_ancestors

        program = _program(ctxs)
        if program is None:
            return
        graph = program.graph
        for ctx in ctxs:
            if ctx.tree is None:
                continue
            mod = module_name(ctx.rel)
            handlers: Dict[str, int] = {}
            for node in ast.walk(ctx.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "set_defaults"
                ):
                    continue
                for kw in node.keywords:
                    if kw.arg == "fn" and isinstance(kw.value, ast.Name):
                        handlers.setdefault(kw.value.id, node.lineno)
            for name in sorted(handlers):
                qn = graph.module_scopes.get(mod, {}).get(name)
                summary = program.summaries.get(qn) if qn else None
                if summary is None:
                    continue
                escaped = sorted(
                    r for r in summary.raises
                    if "KvTpuError" in exception_ancestors(
                        r, graph.class_bases
                    )
                )
                for exc in escaped:
                    yield Finding(
                        self.id, ctx.rel, summary.info.node.lineno,
                        f"subcommand handler {name}() can raise {exc} "
                        "uncaught — it escapes the documented 0/1/2/3 "
                        "exit-code contract as a raw traceback; wrap the "
                        "body in `except KvTpuError` and exit via "
                        "`exit_code_for`",
                    )
