"""``analysis/`` — the rule-based static-analysis framework behind
``kv-tpu lint``.

One framework, one finding shape, one baseline: every hygiene invariant the
repo used to police with ad-hoc AST scripts (error taxonomy, bare excepts,
atomic writes) plus the flow-aware JAX/TPU rules those scripts could never
express (tracer host-sync leaks inside jit, recompile hazards, concurrency
hygiene, metric discipline). Pure AST throughout — linting needs no JAX and
runs on source strings.

Entry points:

* ``kv-tpu lint [PATHS] [--rules ...] [--format json|sarif] [--changed]
  [--no-cache] [--update-baseline]``
* ``python -m kubernetes_verification_tpu.analysis`` (same flags, headless)
* :func:`lint_source` / :func:`run_package` for tests and tooling

See ``LINTS.md`` (generated via ``--write-docs``) for the rule catalog and
the suppression / baseline contract.
"""
from __future__ import annotations

import argparse
import sys
from typing import Optional, Sequence

from .baseline import (
    default_baseline_path,
    load_baseline,
    over_budget,
    save_baseline,
    shrink,
)
from .core import (
    RULES,
    Finding,
    LintResult,
    Rule,
    lint_source,
    register,
    rule_ids,
    run_lint,
    run_package,
)
from .report import (
    catalog_markdown,
    check_docs,
    render_json,
    render_sarif,
    render_text,
)

__all__ = [
    "Finding",
    "LintResult",
    "Rule",
    "RULES",
    "register",
    "rule_ids",
    "lint_source",
    "run_lint",
    "run_package",
    "load_baseline",
    "save_baseline",
    "default_baseline_path",
    "shrink",
    "over_budget",
    "catalog_markdown",
    "render_text",
    "render_json",
    "render_sarif",
    "main",
    "add_lint_arguments",
]


def add_lint_arguments(ap: argparse.ArgumentParser) -> None:
    """The shared flag surface (``kv-tpu lint`` and ``python -m ...analysis``)."""
    ap.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: the installed package)",
    )
    ap.add_argument(
        "--rules", metavar="ID[,ID...]",
        help="comma-separated rule ids to run (default: all; see --list)",
    )
    ap.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        help="finding output format (sarif: 2.1.0, for CI PR annotation)",
    )
    ap.add_argument(
        "--changed", action="store_true",
        help="report findings only for files changed vs "
        "`git merge-base HEAD origin/main` (the whole package is still "
        "parsed, so interprocedural rules stay sound); falls back to a "
        "full run outside a git repo",
    )
    ap.add_argument(
        "--no-cache", action="store_true",
        help="skip the warm-run summary cache (.kvtpu_lint_cache.json at "
        "the repo root, keyed by file content hash)",
    )
    ap.add_argument(
        "--baseline", metavar="PATH", default=None,
        help="grandfather budgets (default: LINT_BASELINE.json at the repo "
        "root; missing file = zero budgets everywhere)",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="shrink baseline budgets down to the current counts and drop "
        "cleaned-up entries (budgets may never grow — new findings must "
        "be fixed or inline-suppressed)",
    )
    ap.add_argument(
        "--list", action="store_true", dest="list_rules",
        help="print the registered rule ids and exit",
    )
    ap.add_argument(
        "--write-docs", metavar="PATH",
        help="write the auto-generated LINTS.md rule catalog to PATH",
    )
    ap.add_argument(
        "--check-docs", metavar="PATH",
        help="exit 1 when PATH drifted from the generated rule catalog",
    )
    ap.add_argument(
        "-v", "--verbose", action="store_true",
        help="also list grandfathered findings in text output",
    )


def changed_package_rels(base_ref: str = "origin/main"):
    """Package-relative paths of ``.py`` files modified vs
    ``git merge-base HEAD origin/main``. None means "cannot tell" (not a
    git checkout, no such ref, git missing) and the caller falls back to a
    full run — `--changed` must never silently lint nothing."""
    import os
    import subprocess

    from .core import package_root

    root = package_root()

    def _git(*argv):
        try:
            proc = subprocess.run(
                ["git", *argv], capture_output=True, text=True,
                cwd=root, timeout=30,
            )
        except (OSError, subprocess.SubprocessError):
            return None
        return proc.stdout.strip() if proc.returncode == 0 else None

    toplevel = _git("rev-parse", "--show-toplevel")
    merge_base = _git("merge-base", "HEAD", base_ref)
    if toplevel is None or merge_base is None:
        return None
    diff = _git("diff", "--name-only", merge_base)
    if diff is None:
        return None
    rels = []
    for line in diff.splitlines():
        if not line.endswith(".py"):
            continue
        abs_path = os.path.join(toplevel, line)
        rel = os.path.relpath(abs_path, root).replace(os.sep, "/")
        if not rel.startswith(".."):
            rels.append(rel)
    return sorted(rels)


def run_from_args(args) -> int:
    """Drive a lint run from parsed :func:`add_lint_arguments` flags."""
    if args.list_rules:
        from .core import _select_rules

        for rule in _select_rules(None):
            first = rule.rationale.split(". ")[0].rstrip(".").strip()
            print(f"{rule.id}: {first}.")
        return 0
    if args.write_docs:
        with open(args.write_docs, "w") as fh:  # kvtpu: ignore[atomic-write] regenerated doc, not durable state
            fh.write(catalog_markdown())
        print(f"wrote {args.write_docs}")
        return 0
    if args.check_docs:
        problem = check_docs(args.check_docs)
        if problem:
            print(problem, file=sys.stderr)
            return 1
        print(f"{args.check_docs} is in sync")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    baseline_path = args.baseline or default_baseline_path()
    budgets = load_baseline(baseline_path)

    if args.paths:
        import os

        sources = {}
        from .core import iter_package_files

        for p in args.paths:
            base = os.path.abspath(p)
            for rel, path in iter_package_files(base):
                with open(path, "r") as fh:
                    sources[rel] = fh.read()
        result = run_lint(sources, rules=rules, baseline=budgets)
    else:
        # the summary cache only keys package-relative paths, so it is
        # scoped to full-package runs (explicit paths rel differently)
        cache_path = (
            None
            if getattr(args, "no_cache", False)
            else _default_cache_path()
        )
        only = None
        if getattr(args, "changed", False):
            only = changed_package_rels()
            if only is None:
                print(
                    "lint --changed: not a git checkout (or origin/main "
                    "unknown) — running the full package",
                    file=sys.stderr,
                )
        result = run_package(
            rules=rules, baseline=budgets, cache_path=cache_path, only=only
        )

    # lint health is an observable: the findings surface on the same
    # dashboards as every other kvtpu_* family
    try:
        from ..observe.metrics import LINT_FINDINGS_TOTAL

        for f in result.findings:
            LINT_FINDINGS_TOTAL.labels(rule=f.rule).inc()
    except ImportError:  # linting outside an installed package tree
        pass

    if args.update_baseline:
        new_budgets = shrink(budgets, result)
        if new_budgets != budgets:
            save_baseline(new_budgets, baseline_path)
            print(f"baseline shrunk: {baseline_path}")
        else:
            print("baseline already minimal")
        grew = over_budget(budgets, result)
        if grew:
            print(
                "counts grew past budget (fix or suppress, the baseline "
                f"never grows): {grew}",
                file=sys.stderr,
            )
            return 1
        return 0

    if args.format == "json":
        print(render_json(result))
    elif args.format == "sarif":
        print(render_sarif(result))
    else:
        print(render_text(result, verbose=args.verbose))
    return 0 if result.ok else 1


def _default_cache_path():
    from .summaries import default_cache_path

    return default_cache_path()


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="kv-tpu lint",
        description="flow-aware static analysis for the package "
        "(see LINTS.md for the rule catalog)",
    )
    add_lint_arguments(ap)
    return run_from_args(ap.parse_args(argv))
