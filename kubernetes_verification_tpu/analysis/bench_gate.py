"""The bench-history regression gate, relocated from
``scripts/check_bench_regression.py`` (now a thin shim over this module)
so every repo gate lives under ``analysis/``.

Compares the newest run of every metric series against the trailing median
of the previous runs (``observe/history.py``) and exits 1 when a series
slipped more than ``--tolerance`` (relative). Reads ``bench_history.jsonl``
when present, else the committed ``BENCH_r*.json`` trajectory snapshots —
so the gate runs out of the box on a fresh checkout. Throughput series are
gated higher-is-better: names with an explicit direction
(``closure_pairs_per_second`` and ``aggregate_queries_per_second``, the
``bench.py --mode closure`` / ``--mode replicate`` headlines) plus
rate-shaped ones recognised structurally — a ``*_per_second`` metric name
or a ``.../s`` unit (the ``queries_per_second`` series ``bench.py --mode
query`` emits rides the gate with no further configuration). Latency-like
series gate lower-is-better, by unit or by explicit name
(``replica_lag_seconds``).

By default (``--deflated``) the gate expands each record into its derived
series first: a ``"<metric> compile_s"`` series (lower-is-better — the
14.3s→59.8s compile walk slipped through ungated) and, for records carrying
a perf-sentinel calibration block, the dispatch-deflated ``<metric>_deflated``
twin. Wherever a twin has ≥ 2 entries it carries the verdict and the raw
headline is reported as an ungated context row — the gate stops failing on
tunnel dispatch noise while raw numbers stay visible side by side.
``--raw`` restores the pre-sentinel behaviour (no expansion, raw gates).

``--dry-run`` exercises the full parse-and-compare path but always exits 0:
tier-1 runs it on every PR so a malformed history entry (or a gate-logic
regression) fails fast, without making perf noise a test failure.
"""
from __future__ import annotations

import argparse
import json
import os
from typing import Optional, Sequence

from .core import repo_root

__all__ = ["main"]


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "paths", nargs="*",
        help="history files: JSONL (bench_history.jsonl) and/or whole-file "
        "JSON snapshots (BENCH_r*.json); default: bench_history.jsonl when "
        "present, else BENCH_r*.json next to the repo root",
    )
    ap.add_argument(
        "--tolerance", type=float, default=0.25,
        help="relative slip vs. the trailing median before flagging "
        "(default 0.25 — the recorded trajectory's ~10%% drift passes, a "
        "2x slowdown fails)",
    )
    ap.add_argument(
        "--window", type=int, default=5,
        help="trailing runs the median is taken over (default 5)",
    )
    ap.add_argument(
        "--dry-run", action="store_true",
        help="parse and report but always exit 0 (the tier-1 CI mode)",
    )
    ap.add_argument("--json", action="store_true")
    deflation = ap.add_mutually_exclusive_group()
    deflation.add_argument(
        "--deflated", dest="deflated", action="store_true", default=True,
        help="expand derived series (compile_s, dispatch-deflated twins) "
        "and let a twin with enough history carry the verdict (default)",
    )
    deflation.add_argument(
        "--raw", dest="deflated", action="store_false",
        help="gate raw series only; no derived-series expansion",
    )
    args = ap.parse_args(argv)

    from ..observe.history import (
        check_regression,
        default_paths,
        expand_derived,
        format_findings,
        load_runs,
    )

    paths = args.paths or default_paths(repo_root())
    runs = load_runs(paths)
    if args.deflated:
        runs = expand_derived(runs)
    ok, findings = check_regression(
        runs, tolerance=args.tolerance, window=args.window,
        prefer_deflated=args.deflated,
    )
    if args.json:
        print(json.dumps({"ok": ok, "findings": findings}, sort_keys=True))
    else:
        print(
            f"{len(runs)} runs from {len(paths)} file(s), "
            f"tolerance {args.tolerance:g}, window {args.window}"
        )
        print(format_findings(findings))
    if args.dry_run:
        if not ok:
            print("(dry run: regression found but exit forced to 0)")
        return 0
    return 0 if ok else 1
