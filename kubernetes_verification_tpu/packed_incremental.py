"""Packed incremental re-verify — BASELINE config 5's diff path at scale.

The dense :class:`~.incremental.IncrementalVerifier` keeps two ``int32 [N, N]``
count matrices — exact rank-1 updates, but 40 GB at 100k pods. This module
keeps the *policy-space* decomposition instead, the same state the tiled
solver builds transiently (``ops/tiled.py``):

* four ``int8 [C, Np]`` per-policy maps (``sel_ing``/``sel_eg`` selection,
  ``ing_by_pol``/``eg_by_pol`` peer maps) — C is the slot capacity (policies
  + headroom), Np the padded pod count;
* two ``int32 [Np]`` isolation *count* vectors (how many policies select each
  pod per direction — exact add/remove, like the reference's
  ``Container.select_policies`` index lists, ``kano_py/kano/model.py:16-17``);
* the bit-packed reachability matrix ``uint32 [Np, Np/32]`` itself.

At the flagship 100k-pod / 10k-policy config this totals ~5.4 GB (4 maps
x 1.0 GB + 1.25 GB packed) — device-resident on one v5e chip, where the dense
counts could not even be allocated.

A policy diff then runs in three device steps, O(P·N·|touched|) instead of a
full O(P·N²) re-solve:

1. **re-encode one policy** against the frozen vocab/atom/namespace universe
   (``encode_policy_delta``) and evaluate its four contribution vectors with
   the same match/peer kernels the batch solve uses — no per-pod Python;
2. **slot update**: write the vectors into the policy's slot, patch the
   isolation counts, and derive the touched row/column sets — rows where the
   policy's egress side (or egress isolation) changed, columns where its
   ingress side (or ingress isolation) changed;
3. **patch**: recompute exactly the touched source *rows* ([Sb, Np] tiles)
   and touched packed dst *words* ([Np, 32·Db] tiles) from the updated maps
   — two int8 MXU contractions each — and scatter them into the packed
   matrix (rows by ``.at[rows].set``, words by an arithmetic delta-add that
   is exact because real indices are unique and padded slots carry delta 0).

Pod relabels patch one column of each map (O(P) host evaluation of that one
pod, as the dense verifier does) plus the pod's own row and word. Pods whose
labels diverge from the frozen encoding are tracked in a dirty set and fixed
up on every later policy re-encode, so label drift never silently decays the
frozen-vocab device path.

**Pod churn** uses the same slot mechanism as policies, on the pod axis: the
padded columns ``[n, Np)`` (plus an optional ``pod_headroom``) are free pod
slots, and removed pods return their slot to a free list. One ``add_pod`` /
``remove_pod`` is a single fused device dispatch (``_pod_step``): write the
pod's per-policy column into the four maps, set its isolation counts, flip
its validity bit in the packed column mask, and recompute exactly its own
row and its own bit-column of the packed matrix — the rest of the matrix is
untouched because a pod's existence only contributes its own row/column
(unlike a policy, which fans out to every pod it selects). This is the
vectorised form of the reference's per-container policy index hint
(``kano_py/kano/model.py:16-17,161-163``): the per-pod column of the policy
maps IS that index, kept device-resident. Exhausting the headroom grows the
pod axis in place (a full copy — size ``pod_headroom`` to your churn rate).

Scope matches the dense verifier: any-port semantics. Differentially tested
against the CPU oracle and the dense incremental verifier in
``tests/test_packed_incremental.py``.
"""
from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .backends.base import VerifyConfig
from .encode.encoder import (
    GrantBlock,
    SelectorEnc,
    cluster_vocab,
    encode_cluster,
    encode_policy_delta,
)
from .encode.ports import ALL_ATOM
from .models.core import Cluster, Namespace, NetworkPolicy, Pod
from .observe import DispatchTracker
from .observe.metrics import INCREMENTAL_OPS, STRIPE_WIDTH, STRIPES_SOLVED
from .resilience.errors import ConfigError
from .resilience.retry import RetryPolicy, retry_transient
from .ops.tiled import (
    PackedReach,
    _peers_by_slot,
    _select_maps,
    _sweep_packed,
    pack_bool_cols,
)
from .parallel.sharded_ops import pad_grants, pad_pods

__all__ = ["PackedIncrementalVerifier", "PolicyVectorizer", "pod_policy_flags"]

_I8 = jnp.int8
_I32 = jnp.int32
_U32 = jnp.uint32

#: max rows recomputed per patch-kernel call (bounds the [Sb, Np] transient)
_ROW_GROUP = 512
#: max dst columns recomputed per call (bounds the [Np, Dc] transients)
_COL_GROUP = 256

#: jit caches are per-function and process-global — one tracker per module
_TRACKER = DispatchTracker("packed")


def _groups(
    idx: np.ndarray, cap: int
) -> Iterable[Tuple[np.ndarray, np.ndarray]]:
    """Split an index list into fixed-size ``cap`` buckets (padding repeats
    the last real index; the bool mask marks real slots). One fixed bucket
    size ⇒ exactly one compile per patch kernel, and the padded compute is
    a few ms of MXU work — far cheaper than per-size recompiles."""
    for i in range(0, len(idx), cap):
        g = np.asarray(idx[i : i + cap], dtype=np.int32)
        pad = cap - len(g)
        yield (
            np.concatenate([g, np.repeat(g[-1:], pad)]),
            np.concatenate([np.ones(len(g), bool), np.zeros(pad, bool)]),
        )


# ---------------------------------------------------------------------------
# single-policy contribution vectors (device path + host fixup)
# ---------------------------------------------------------------------------


def pod_policy_flags(
    pol: NetworkPolicy,
    pod: Pod,
    ns_labels: Dict[str, Dict[str, str]],
    direction_aware: bool,
) -> Tuple[bool, bool, bool, bool]:
    """(sel_ing, sel_eg, ing_peer, eg_peer) for one (policy, pod) pair —
    object-level semantics (the CPU oracle's, ``backends/cpu.py``), used to
    fix up device vectors for pods whose labels diverged from the frozen
    encoding."""
    aff_i = pol.affects_ingress if direction_aware else True
    aff_e = pol.affects_egress if direction_aware else True
    selected = pod.namespace == pol.namespace and pol.pod_selector.matches(
        pod.labels
    )

    def peer_one(rules) -> bool:
        for rule in rules or ():
            if rule.matches_all_peers:
                return True
            for peer in rule.peers:
                if peer.ip_block is not None:
                    if peer.ip_block.matches_ip(pod.ip):
                        return True
                    continue
                if peer.namespace_selector is None:
                    ns_ok = pod.namespace == pol.namespace
                else:
                    ns_ok = peer.namespace_selector.matches(
                        ns_labels.get(pod.namespace, {})
                    )
                if ns_ok and (
                    peer.pod_selector is None
                    or peer.pod_selector.matches(pod.labels)
                ):
                    return True
        return False

    return (
        selected and aff_i,
        selected and aff_e,
        aff_i and peer_one(pol.ingress),
        aff_e and peer_one(pol.egress),
    )


class PolicyVectorizer:
    """Computes one policy's four contribution vectors on HOST against a
    frozen cluster encoding, via inverted label-index posting lists — the
    vectorised form of the reference's ``labelMap`` bitmap index
    (``kano_py/kano/model.py:128-133``) — with object-semantics fixups for
    label-drifted pods.

    Shared by the packed and dense incremental verifiers: this replaces the
    old per-rule × per-peer × per-pod Python loops with O(atoms) numpy mask
    ops per selector, and (unlike a device evaluation) costs zero host↔device
    round-trips per diff — the packed verifier derives its patch row/word
    sets from these vectors without ever fetching device state.
    """

    def __init__(
        self,
        pods: Sequence[Pod],
        ns_labels: Dict[str, Dict[str, str]],
        vocab,
        ns_index: Dict[str, int],
        direction_aware: bool,
    ) -> None:
        self.pods = pods  # live reference — callers mutate labels in place
        self.ns_labels = ns_labels
        self.vocab = vocab
        self.ns_index = dict(ns_index)
        self.direction_aware = direction_aware
        self.n = len(pods)
        #: pods whose labels/namespace fall outside the frozen universe —
        #: these re-evaluate object-level on every later policy (re-)encode
        self.dirty: set = set()
        #: removed pod slots — their vectors are forced to 0 so a later
        #: policy re-encode can never resurrect a tombstoned pod
        self.inactive: set = set()
        #: namespaces known at freeze time: pods churned into them can be
        #: re-indexed in place; later-created namespaces have no row in the
        #: frozen namespace matrices, so their pods stay dirty
        self._n_frozen_ns = len(self.ns_index)
        # inverted indices over the (frozen, then churn-patched) pod labels:
        # pair/key/ns → pod ids, plus the per-pod reverse entries that make
        # single-pod re-indexing O(labels)
        pair_pods: Dict[int, List[int]] = {}
        key_pods: Dict[int, List[int]] = {}
        ns_pods: Dict[int, List[int]] = {}
        self._pod_entries: Dict[int, Tuple[List[int], List[int], int]] = {}
        for i, pod in enumerate(pods):
            ns_idx = self.ns_index.get(pod.namespace, -3)
            ns_pods.setdefault(ns_idx, []).append(i)
            pairs: List[int] = []
            keyids: List[int] = []
            for k, v in pod.labels.items():
                pid = vocab.pair(k, v)
                if pid is not None:
                    pair_pods.setdefault(pid, []).append(i)
                    pairs.append(pid)
                kid = vocab.key(k)
                if kid is not None:
                    key_pods.setdefault(kid, []).append(i)
                    keyids.append(kid)
            self._pod_entries[i] = (pairs, keyids, ns_idx)
        as_arr = lambda d: {
            k: np.asarray(v, dtype=np.int64) for k, v in d.items()
        }
        self._pair_pods = as_arr(pair_pods)
        self._key_pods = as_arr(key_pods)
        self._ns_pods = as_arr(ns_pods)
        self._empty = np.asarray([], dtype=np.int64)

    def _mask_of(self, idx: np.ndarray) -> np.ndarray:
        m = np.zeros(self.n, dtype=bool)
        m[idx] = True
        return m

    def _sel_mask(self, enc: SelectorEnc, row: int) -> np.ndarray:
        """bool [n]: which (frozen-label) pods match selector ``row``."""
        if enc.impossible[row]:
            return np.zeros(self.n, dtype=bool)
        acc = np.ones(self.n, dtype=bool)
        for pid in np.nonzero(enc.req_eq[row])[0]:
            acc &= self._mask_of(self._pair_pods.get(int(pid), self._empty))
        for kid in np.nonzero(enc.req_key[row])[0]:
            acc &= self._mask_of(self._key_pods.get(int(kid), self._empty))
        forb = np.nonzero(enc.forbid_eq[row])[0]
        for pid in forb:
            acc &= ~self._mask_of(self._pair_pods.get(int(pid), self._empty))
        for kid in np.nonzero(enc.forbid_key[row])[0]:
            acc &= ~self._mask_of(self._key_pods.get(int(kid), self._empty))
        E = enc.in_mask.shape[1]
        for e in range(E):
            if not enc.in_valid[row, e]:
                continue
            hit = np.zeros(self.n, dtype=bool)
            for pid in np.nonzero(enc.in_mask[row, e])[0]:
                hit |= self._mask_of(self._pair_pods.get(int(pid), self._empty))
            acc &= hit
        return acc

    def _ns_mask(self, ns_idx: int) -> np.ndarray:
        return self._mask_of(self._ns_pods.get(ns_idx, self._empty))

    def _ns_selector_mask(self, pol: NetworkPolicy, peer) -> np.ndarray:
        """Pods whose namespace matches the peer's namespaceSelector (object
        semantics over the handful of namespaces — M is tiny)."""
        acc = np.zeros(self.n, dtype=bool)
        for ns_name, idx in self.ns_index.items():
            if peer.namespace_selector.matches(self.ns_labels.get(ns_name, {})):
                acc |= self._ns_mask(idx)
        return acc

    def _peer_union(
        self, pol: NetworkPolicy, block: GrantBlock, rules
    ) -> np.ndarray:
        """bool [n]: union of a direction's peer grants. ``block`` carries the
        compiled pod selectors + precomputed ipBlock↔pod-IP rows; the peer
        objects (same flattening order as ``_encode_grants``) supply the
        namespace scope."""
        acc = np.zeros(self.n, dtype=bool)
        peers_flat: List = []
        for rule in rules or ():
            if rule.matches_all_peers:
                peers_flat.append(None)  # match-all grant row
            else:
                peers_flat.extend(rule.peers)
        pol_ns = self.ns_index.get(pol.namespace, -2)
        for g in range(block.n):
            peer = peers_flat[g]
            if peer is None or bool(block.match_all[g]):
                return np.ones(self.n, dtype=bool)
            if bool(block.is_ipblock[g]):
                acc |= block.ip_match[g]
                continue
            m = self._sel_mask(block.pod_sel, g)
            if peer.namespace_selector is None:
                m &= self._ns_mask(pol_ns)
            else:
                m &= self._ns_selector_mask(pol, peer)
            acc |= m
        return acc

    def vectors(self, pol: NetworkPolicy) -> Tuple[np.ndarray, ...]:
        """(sel_ing, sel_eg, ing_peers, eg_peers) int8 [n], host arrays."""
        delta = encode_policy_delta(
            pol, self.vocab, [ALL_ATOM], self.ns_index, self.pods
        )
        selected = self._sel_mask(delta.pod_sel, 0) & self._ns_mask(delta.pol_ns)
        aff_i = delta.affects_ingress if self.direction_aware else True
        aff_e = delta.affects_egress if self.direction_aware else True
        sel_ing = selected if aff_i else np.zeros(self.n, dtype=bool)
        sel_eg = selected if aff_e else np.zeros(self.n, dtype=bool)
        ing_peers = (
            self._peer_union(pol, delta.ingress, pol.ingress)
            if aff_i
            else np.zeros(self.n, dtype=bool)
        )
        eg_peers = (
            self._peer_union(pol, delta.egress, pol.egress)
            if aff_e
            else np.zeros(self.n, dtype=bool)
        )
        out = [sel_ing, sel_eg, ing_peers, eg_peers]
        for i in sorted(self.dirty):
            flags = pod_policy_flags(
                pol, self.pods[i], self.ns_labels, self.direction_aware
            )
            for v, f in zip(out, flags):
                v[i] = f
        for i in self.inactive:
            for v in out:
                v[i] = False
        return tuple(v.astype(np.int8) for v in out)

    def _strip(self, idx: int) -> None:
        """Remove pod ``idx`` from every inverted index (O(labels) via the
        reverse entry)."""
        e = self._pod_entries.pop(idx, None)
        if e is None:
            return
        pairs, keyids, ns_idx = e
        for pid in pairs:
            a = self._pair_pods.get(pid)
            if a is not None:
                self._pair_pods[pid] = a[a != idx]
        for kid in keyids:
            a = self._key_pods.get(kid)
            if a is not None:
                self._key_pods[kid] = a[a != idx]
        a = self._ns_pods.get(ns_idx)
        if a is not None:
            self._ns_pods[ns_idx] = a[a != idx]

    def note_pod(self, idx: int) -> None:
        """Register pod slot ``idx`` as (re)occupied or relabeled: the live
        ``self.pods`` list already holds the new Pod. When its namespace and
        every label pair/key lie inside the frozen universe (the common
        churn), the inverted indices are patched in place and the pod costs
        NOTHING on later policy diffs; otherwise it joins the permanent
        object-semantics dirty set (a frozen-vocab evaluation would be
        unsound — e.g. a later policy selecting a pair the vocab never saw
        encodes as ``impossible`` and must be fixed up against this pod)."""
        self.n = len(self.pods)
        self.inactive.discard(idx)
        self._strip(idx)
        pod = self.pods[idx]
        ns_idx = self.ns_index.get(pod.namespace, -3)
        clean = 0 <= ns_idx < self._n_frozen_ns
        pairs: List[int] = []
        keyids: List[int] = []
        for k, v in pod.labels.items():
            pid = self.vocab.pair(k, v)
            kid = self.vocab.key(k)
            if pid is None or kid is None:
                clean = False
                break
            pairs.append(pid)
            keyids.append(kid)
        if not clean:
            self.dirty.add(idx)
            return
        self.dirty.discard(idx)
        add = lambda d, key: d.__setitem__(
            key, np.append(d.get(key, self._empty), np.int64(idx))
        )
        for pid in pairs:
            add(self._pair_pods, pid)
        for kid in keyids:
            add(self._key_pods, kid)
        add(self._ns_pods, ns_idx)
        self._pod_entries[idx] = (pairs, keyids, ns_idx)

    def note_removed(self, idx: int) -> None:
        self._strip(idx)
        self.inactive.add(idx)
        self.dirty.discard(idx)


# ---------------------------------------------------------------------------
# device state updates
# ---------------------------------------------------------------------------


@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5))
def _slot_write(
    sel_ing8,
    sel_eg8,
    ing_by_pol,
    eg_by_pol,
    ing_cnt,
    eg_cnt,
    slot,
    new4,  # int8 [4, Np]
):
    """Matrix-free diff: write one policy slot's vectors + isolation counts
    (the state update half of ``_diff_step``; used when the packed matrix is
    not materialised — dirty rows/columns are tracked host-side and
    re-verified by ``solve_stripe`` on demand)."""
    old_si = sel_ing8[slot]
    old_se = sel_eg8[slot]
    return (
        sel_ing8.at[slot].set(new4[0]),
        sel_eg8.at[slot].set(new4[1]),
        ing_by_pol.at[slot].set(new4[2]),
        eg_by_pol.at[slot].set(new4[3]),
        ing_cnt + (new4[0] - old_si).astype(_I32),
        eg_cnt + (new4[1] - old_se).astype(_I32),
    )


@partial(
    jax.jit,
    static_argnames=("width", "self_traffic", "default_allow"),
)
def _stripe_step(
    sel_ing8,
    sel_eg8,
    ing_by_pol,
    eg_by_pol,
    ing_cnt,
    eg_cnt,
    col_mask,
    row_valid,  # int8 [Np] — 0 for removed/padded pod rows
    d0,  # stripe start (multiple of 32)
    *,
    width: int,  # stripe width (multiple of 32)
    self_traffic: bool,
    default_allow: bool,
):
    """Re-solve one dst stripe of the packed matrix straight from the
    resident per-policy maps — the re-verify primitive of the matrix-free
    (config-5 scale) mode. Returns uint32 [Np, width/32]."""
    C, Np = sel_ing8.shape
    r = _reach_block(
        ing_by_pol,
        jax.lax.dynamic_slice(sel_ing8, (0, d0), (C, width)),
        sel_eg8,
        jax.lax.dynamic_slice(eg_by_pol, (0, d0), (C, width)),
        jax.lax.dynamic_slice(ing_cnt, (d0,), (width,)),
        eg_cnt,
        jnp.arange(Np, dtype=jnp.int32),
        d0 + jnp.arange(width, dtype=jnp.int32),
        self_traffic,
        default_allow,
    )
    r &= row_valid[:, None] > 0
    mask_t = jax.lax.dynamic_slice(col_mask, (d0 // 32,), (width // 32,))
    return pack_bool_cols(r) & mask_t[None, :]


@partial(jax.jit, static_argnames=("self_traffic", "default_allow"))
def _rows_step(
    sel_ing8,
    sel_eg8,
    ing_by_pol,
    eg_by_pol,
    ing_cnt,
    eg_cnt,
    col_mask,
    row_valid,
    rows,  # int32 [K] — source pod ids (pads repeat a valid id)
    *,
    self_traffic: bool,
    default_allow: bool,
):
    """Re-solve the packed reach ROWS of ``rows`` straight from the
    resident per-policy maps — the transpose of ``_stripe_step``: skinny
    [K, Np] instead of [Np, width]. This is the row oracle the bounded
    multi-source closure BFS rides at matrix-free scale (one frontier's
    out-edges per level, the N x N matrix never materialised). Returns
    uint32 [K, Np/32]."""
    C, Np = sel_ing8.shape
    r = _reach_block(
        jnp.take(ing_by_pol, rows, axis=1),
        sel_ing8,
        jnp.take(sel_eg8, rows, axis=1),
        eg_by_pol,
        ing_cnt,
        jnp.take(eg_cnt, rows),
        rows,
        jnp.arange(Np, dtype=jnp.int32),
        self_traffic,
        default_allow,
    )
    r &= jnp.take(row_valid, rows)[:, None] > 0
    return pack_bool_cols(r) & col_mask[None, :]


@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5))
def _apply_pod_col(
    sel_ing8,
    sel_eg8,
    ing_by_pol,
    eg_by_pol,
    ing_cnt,
    eg_cnt,
    idx,
    col_si,
    col_se,
    col_ip,
    col_ep,
):
    """Write one pod's column of every map + its isolation counts."""
    return (
        sel_ing8.at[:, idx].set(col_si),
        sel_eg8.at[:, idx].set(col_se),
        ing_by_pol.at[:, idx].set(col_ip),
        eg_by_pol.at[:, idx].set(col_ep),
        ing_cnt.at[idx].set(jnp.sum(col_si.astype(_I32))),
        eg_cnt.at[idx].set(jnp.sum(col_se.astype(_I32))),
    )


@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5))
def _apply_pod_cols_group(
    sel_ing8,
    sel_eg8,
    ing_by_pol,
    eg_by_pol,
    ing_cnt,
    eg_cnt,
    idxs,  # int32 [G] — pod slots (pads repeat a real slot: same values)
    cols4,  # int8 [4, C, G] — the pods' per-policy column quadruples
):
    """Write a GROUP of pod columns across every map + their isolation
    counts in one dispatch — the batched ``_apply_pod_col`` a namespace
    relabel needs (every pod in the namespace re-evaluates at once; a
    per-pod dispatch loop would pay the tunnel latency per pod)."""
    return (
        sel_ing8.at[:, idxs].set(cols4[0]),
        sel_eg8.at[:, idxs].set(cols4[1]),
        ing_by_pol.at[:, idxs].set(cols4[2]),
        eg_by_pol.at[:, idxs].set(cols4[3]),
        ing_cnt.at[idxs].set(jnp.sum(cols4[0].astype(_I32), axis=0)),
        eg_cnt.at[idxs].set(jnp.sum(cols4[1].astype(_I32), axis=0)),
    )


@partial(
    jax.jit,
    donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7, 8),
    static_argnames=("self_traffic", "default_allow"),
)
def _pod_step(
    packed,
    sel_ing8,
    sel_eg8,
    ing_by_pol,
    eg_by_pol,
    ing_cnt,
    eg_cnt,
    col_mask,
    row_valid,
    idx,  # int32 — the pod slot
    cols4,  # int8 [4, C] — the pod's per-policy column quadruple
    active,  # uint32 0/1 — 1 = add/occupy, 0 = remove/tombstone
    *,
    self_traffic: bool,
    default_allow: bool,
):
    """One fused pod add/remove: write the pod's column of all four maps,
    set its isolation counts, flip its validity bit in the column mask +
    row-valid vector, and recompute exactly its own packed row and its own
    bit-column — one dispatch, like ``_diff_step`` for policies (the remote
    tunnel's per-dispatch latency dominates the math otherwise). A pod only
    contributes its own row/column to the matrix, so nothing else changes."""
    sel_ing8 = sel_ing8.at[:, idx].set(cols4[0])
    sel_eg8 = sel_eg8.at[:, idx].set(cols4[1])
    ing_by_pol = ing_by_pol.at[:, idx].set(cols4[2])
    eg_by_pol = eg_by_pol.at[:, idx].set(cols4[3])
    ing_cnt = ing_cnt.at[idx].set(jnp.sum(cols4[0].astype(_I32)))
    eg_cnt = eg_cnt.at[idx].set(jnp.sum(cols4[1].astype(_I32)))
    w = idx // 32
    bit = jnp.uint32(1) << (idx % 32).astype(_U32)
    col_mask = col_mask.at[w].set((col_mask[w] & ~bit) | (bit * active))
    row_valid = row_valid.at[idx].set(active.astype(_I8))
    Np = sel_ing8.shape[1]
    idxv = jnp.reshape(idx, (1,))
    ar = jnp.arange(Np, dtype=jnp.int32)
    # the pod's own row, against the NEW maps and NEW column mask
    r_row = _reach_block(
        jnp.take(ing_by_pol, idxv, axis=1), sel_ing8,
        jnp.take(sel_eg8, idxv, axis=1), eg_by_pol,
        ing_cnt, jnp.take(eg_cnt, idxv),
        idxv, ar, self_traffic, default_allow,
    )  # [1, Np]
    packed = packed.at[idxv].set(pack_bool_cols(r_row) & (col_mask[None, :] * active))
    # the pod's own bit-column, for every (valid) source row
    r_col = _reach_block(
        ing_by_pol, jnp.take(sel_ing8, idxv, axis=1),
        sel_eg8, jnp.take(eg_by_pol, idxv, axis=1),
        jnp.take(ing_cnt, idxv), eg_cnt,
        ar, idxv, self_traffic, default_allow,
    )  # [Np, 1]
    r_colb = r_col[:, 0] & (row_valid > 0)
    newbit = (r_colb.astype(_U32) << (idx % 32).astype(_U32)) * active
    packed = packed.at[:, w].set((packed[:, w] & ~bit) | newbit)
    return (
        packed, sel_ing8, sel_eg8, ing_by_pol, eg_by_pol,
        ing_cnt, eg_cnt, col_mask, row_valid,
    )


@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def _pod_step_mf(
    sel_ing8,
    sel_eg8,
    ing_by_pol,
    eg_by_pol,
    ing_cnt,
    eg_cnt,
    col_mask,
    row_valid,
    idx,
    cols4,
    active,
):
    """Matrix-free pod add/remove: maps + counts + validity only (the packed
    matrix is not materialised; ``solve_stripe`` re-verifies on demand)."""
    sel_ing8 = sel_ing8.at[:, idx].set(cols4[0])
    sel_eg8 = sel_eg8.at[:, idx].set(cols4[1])
    ing_by_pol = ing_by_pol.at[:, idx].set(cols4[2])
    eg_by_pol = eg_by_pol.at[:, idx].set(cols4[3])
    ing_cnt = ing_cnt.at[idx].set(jnp.sum(cols4[0].astype(_I32)))
    eg_cnt = eg_cnt.at[idx].set(jnp.sum(cols4[1].astype(_I32)))
    w = idx // 32
    bit = jnp.uint32(1) << (idx % 32).astype(_U32)
    col_mask = col_mask.at[w].set((col_mask[w] & ~bit) | (bit * active))
    row_valid = row_valid.at[idx].set(active.astype(_I8))
    return (
        sel_ing8, sel_eg8, ing_by_pol, eg_by_pol,
        ing_cnt, eg_cnt, col_mask, row_valid,
    )


def _dot_c(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """int8 [C, X] × int8 [C, Y] → int32 [X, Y] (contract the slot axis)."""
    return jax.lax.dot_general(
        a, b, (((0,), (0,)), ((), ())), preferred_element_type=_I32
    )


def _reach_block(
    ing_by_pol_s,  # int8 [C, S] — src-side ingress peer operand
    sel_ing_d,  # int8 [C, D] — dst-side ingress selection operand
    sel_eg_s,  # int8 [C, S] — src-side egress selection operand
    eg_by_pol_d,  # int8 [C, D] — dst-side egress peer operand
    ing_cnt_d,  # int32 [D]
    eg_cnt_s,  # int32 [S]
    src_ids,  # int32 [S] — global pod ids of the block's rows
    dst_ids,  # int32 [D] — global pod ids of the block's columns
    self_traffic: bool,
    default_allow: bool,
) -> jnp.ndarray:
    """THE reach formula for an arbitrary (src rows × dst cols) block —
    the single copy shared by the row patch, the exact-column patch and the
    stripe re-solve, so a semantics change lands in all three kernels (and
    stays differentially pinned to ``_sweep_packed``) by construction."""
    ing_ok = _dot_c(ing_by_pol_s, sel_ing_d) > 0  # [S, D]
    eg_ok = _dot_c(sel_eg_s, eg_by_pol_d) > 0
    if default_allow:
        ing_ok |= ~(ing_cnt_d > 0)[None, :]
        eg_ok |= ~(eg_cnt_s > 0)[:, None]
    r = ing_ok & eg_ok
    if self_traffic:
        r |= src_ids[:, None] == dst_ids[None, :]
    return r


def _rows_body(
    packed, sel_ing8, sel_eg8, ing_by_pol, eg_by_pol, ing_cnt, eg_cnt,
    col_mask, rows, self_traffic, default_allow,
):
    """Recompute the full packed rows of the touched sources. ``rows`` may
    contain duplicates (pad repeats) — the scattered values are equal."""
    Np = sel_ing8.shape[1]
    r = _reach_block(
        jnp.take(ing_by_pol, rows, axis=1), sel_ing8,
        jnp.take(sel_eg8, rows, axis=1), eg_by_pol,
        ing_cnt, jnp.take(eg_cnt, rows),
        rows, jnp.arange(Np, dtype=jnp.int32),
        self_traffic, default_allow,
    )
    return packed.at[rows].set(pack_bool_cols(r) & col_mask[None, :])


@partial(
    jax.jit,
    donate_argnums=(0,),
    static_argnames=("self_traffic", "default_allow"),
)
def _patch_rows(
    packed, sel_ing8, sel_eg8, ing_by_pol, eg_by_pol, ing_cnt, eg_cnt,
    col_mask, rows, *, self_traffic: bool, default_allow: bool,
):
    return _rows_body(
        packed, sel_ing8, sel_eg8, ing_by_pol, eg_by_pol, ing_cnt, eg_cnt,
        col_mask, rows, self_traffic, default_allow,
    )


def _cols_body(
    packed, sel_ing8, sel_eg8, ing_by_pol, eg_by_pol, ing_cnt, eg_cnt,
    row_valid, cols, seg, words, wreal, clear, self_traffic, default_allow,
):
    """Recompute exactly the touched dst columns (not their whole 32-column
    words — a 32× saving on the dominant MXU contraction), fold the column
    bits into per-word values with a segment sum (bits within a word slot
    are distinct powers of two, so sum == OR; padded cols land in a scratch
    slot), and merge by arithmetic delta: real word indices are unique,
    padded slots contribute delta 0, so a uint32 scatter-add lands exactly
    ``new = old + (new - old)`` with wraparound.

    cols:  int32 [Dc] — real entries unique; pads repeat the last col.
    seg:   int32 [Dc] — word slot of each col; pads → scratch slot Dw.
    words: int32 [Dw] — real entries unique; pads repeat the last word.
    clear: uint32 [Dw] — per word-slot OR of the real cols' bit masks."""
    Np = sel_ing8.shape[1]
    Dw = words.shape[0]
    r = _reach_block(
        ing_by_pol, jnp.take(sel_ing8, cols, axis=1),
        sel_eg8, jnp.take(eg_by_pol, cols, axis=1),
        jnp.take(ing_cnt, cols), eg_cnt,
        jnp.arange(Np, dtype=jnp.int32), cols,
        self_traffic, default_allow,
    )
    # tombstoned/padded source rows must stay zero — without this mask a
    # later policy diff would resurrect reach bits in a removed pod's row
    # (its eg_cnt is 0, so default-allow marks it egress-open)
    r &= row_valid[:, None] > 0
    bits = r.astype(_U32) << (cols % 32).astype(_U32)[None, :]  # [Np, Dc]
    set_words = jax.ops.segment_sum(
        bits.T, seg, num_segments=Dw + 1
    )[:Dw].T  # [Np, Dw]
    old_words = jnp.take(packed, words, axis=1)
    new_words = (old_words & ~clear[None, :]) | set_words
    delta = (new_words - old_words) * wreal[None, :].astype(_U32)
    return packed.at[:, words].add(delta)


@partial(
    jax.jit,
    donate_argnums=(0,),
    static_argnames=("self_traffic", "default_allow"),
)
def _patch_cols(
    packed, sel_ing8, sel_eg8, ing_by_pol, eg_by_pol, ing_cnt, eg_cnt,
    row_valid, cols, seg, words, wreal, clear,
    *, self_traffic: bool, default_allow: bool,
):
    return _cols_body(
        packed, sel_ing8, sel_eg8, ing_by_pol, eg_by_pol, ing_cnt, eg_cnt,
        row_valid, cols, seg, words, wreal, clear, self_traffic, default_allow,
    )


@partial(
    jax.jit,
    donate_argnums=(0, 1, 2, 3, 4, 5, 6),
    static_argnames=("self_traffic", "default_allow", "has_rows", "has_cols"),
)
def _diff_step(
    packed,
    sel_ing8,
    sel_eg8,
    ing_by_pol,
    eg_by_pol,
    ing_cnt,
    eg_cnt,
    col_mask,
    row_valid,
    slot,
    new4,  # int8 [4, Np]
    rows,  # int32 [_ROW_GROUP]
    cols,  # int32 [_COL_GROUP]
    seg,
    words,
    wreal,
    clear,
    *,
    self_traffic: bool,
    default_allow: bool,
    has_rows: bool,
    has_cols: bool,
):
    """One fused policy diff: slot write + isolation counts + first row
    group + first column group, in a single dispatch — per-dispatch latency
    (tens of ms over this environment's remote-TPU tunnel) would otherwise
    dominate the patch math. Empty groups compile away entirely
    (``has_rows``/``has_cols``); larger diffs spill their remaining groups
    to ``_patch_rows``/``_patch_cols`` calls."""
    old_si = sel_ing8[slot]
    old_se = sel_eg8[slot]
    sel_ing8 = sel_ing8.at[slot].set(new4[0])
    sel_eg8 = sel_eg8.at[slot].set(new4[1])
    ing_by_pol = ing_by_pol.at[slot].set(new4[2])
    eg_by_pol = eg_by_pol.at[slot].set(new4[3])
    ing_cnt = ing_cnt + (new4[0] - old_si).astype(_I32)
    eg_cnt = eg_cnt + (new4[1] - old_se).astype(_I32)
    if has_rows:
        packed = _rows_body(
            packed, sel_ing8, sel_eg8, ing_by_pol, eg_by_pol, ing_cnt,
            eg_cnt, col_mask, rows, self_traffic, default_allow,
        )
    if has_cols:
        packed = _cols_body(
            packed, sel_ing8, sel_eg8, ing_by_pol, eg_by_pol, ing_cnt,
            eg_cnt, row_valid, cols, seg, words, wreal, clear, self_traffic,
            default_allow,
        )
    return packed, sel_ing8, sel_eg8, ing_by_pol, eg_by_pol, ing_cnt, eg_cnt


@partial(jax.jit, static_argnames=("chunk", "direction_aware"))
def _build_maps(
    pod_kv,
    pod_key,
    pod_ns,
    ns_kv,
    ns_key,
    pol_sel: SelectorEnc,
    pol_ns,
    aff_i,
    aff_e,
    ingress: GrantBlock,
    egress: GrantBlock,
    *,
    chunk: int,
    direction_aware: bool,
):
    """Batched init: the tiled solver's prologue, kept as state."""
    P = pol_ns.shape[0]
    _, sel_ing8, sel_eg8, _, _ = _select_maps(
        pod_kv, pod_key, pod_ns, pol_sel, pol_ns, aff_i, aff_e,
        direction_aware,
    )
    args = (pod_kv, pod_key, ns_kv, ns_key, pod_ns, pol_ns)
    ing_by_pol = _peers_by_slot(ingress, ingress.pol, P + 1, chunk, *args)[:P]
    eg_by_pol = _peers_by_slot(egress, egress.pol, P + 1, chunk, *args)[:P]
    if direction_aware:
        # match the per-policy vector convention (peer side gated too);
        # redundant for reach — sel gating covers it — but keeps slots
        # byte-identical with PolicyVectorizer outputs
        ing_by_pol = ing_by_pol * aff_i.astype(_I8)[:, None]
        eg_by_pol = eg_by_pol * aff_e.astype(_I8)[:, None]
    ing_cnt = jnp.sum(sel_ing8.astype(_I32), axis=0)
    eg_cnt = jnp.sum(sel_eg8.astype(_I32), axis=0)
    return sel_ing8, sel_eg8, ing_by_pol, eg_by_pol, ing_cnt, eg_cnt


_sweep_jit = jax.jit(
    _sweep_packed,
    static_argnames=("tile", "self_traffic", "default_allow_unselected"),
)


@partial(jax.jit, donate_argnums=(0,))
def _mask_rows(packed, row_valid):
    return packed & jnp.where(
        row_valid > 0, jnp.uint32(0xFFFFFFFF), jnp.uint32(0)
    )[:, None]


class PackedIncrementalVerifier:
    """Maintains a packed reachability matrix under policy / pod-label diffs.

    Same API shape as the dense :class:`~.incremental.IncrementalVerifier`
    (``add_policy``/``remove_policy``/``update_policy``/``update_pod_labels``)
    but every piece of state is device-resident and bit-packed, so it runs at
    the 100k-pod flagship scale the dense counts cannot reach.
    """

    #: engine label on kvtpu_incremental_ops_total et al.; the namespace
    #: methods the dense engine borrows from this class label per-class
    metrics_engine = "packed"
    #: transient-failure budget around jitted dispatches (stripe re-solves);
    #: assign a tuned RetryPolicy on the instance to change it
    retry_policy = RetryPolicy()

    def _count_op(self, op: str) -> None:
        INCREMENTAL_OPS.labels(engine=self.metrics_engine, op=op).inc()

    def __init__(
        self,
        cluster: Cluster,
        config: Optional[VerifyConfig] = None,
        device=None,
        slot_round: int = 256,
        chunk: int = 2048,
        mesh: Optional[jax.sharding.Mesh] = None,
        keep_matrix: Optional[bool] = None,
        pod_headroom: int = 0,
    ) -> None:
        """``pod_headroom``: extra pod slots padded into the matrix at build
        time so ``add_pod`` never has to grow (a grow is a full device-state
        copy + kernel recompile) — size it to the expected churn between
        rebuilds. ``mesh``: shard the state over a ``(pods, grants)`` mesh — the
        slot axis over ``grants``, the pod axis over ``pods`` — instead of a
        single device; every diff kernel then runs SPMD via jit sharding
        propagation. ``keep_matrix=False`` (the default on a mesh when the
        packed matrix exceeds ~1 GB/device) skips materialising the matrix:
        diffs update the per-policy maps + isolation counts only, touched
        rows/columns accumulate in ``dirty_rows``/``dirty_cols``, and
        ``solve_stripe`` re-verifies any dst range straight from the maps —
        the config-5 (1M-pod) composition, where the full packed matrix
        (125 GB) never fits."""
        self.config = config or VerifyConfig()
        self.mesh = mesh
        self.device = device or (None if mesh else jax.devices()[0])
        self.pods: List[Pod] = [
            dataclasses.replace(
                p, labels=dict(p.labels), container_ports=dict(p.container_ports)
            )
            for p in cluster.pods
        ]
        self.namespaces = list(cluster.namespaces)
        self.policies: Dict[str, NetworkPolicy] = {}
        self._slot: Dict[str, int] = {}
        self.update_count = 0
        #: cached transitive closure + nodes touched since (closure_packed)
        self._closure = None
        self._closure_base = None
        self._closure_dirty: Optional[np.ndarray] = None
        cfg = self.config

        t0 = time.perf_counter()
        snapshot = Cluster(
            pods=self.pods,
            namespaces=self.namespaces,  # __post_init__ appends missing ns
            policies=list(cluster.policies),
        )
        # label dicts are COPIED: an aliased caller dict mutated in place
        # would satisfy the relabel no-op guard and silently skip the
        # re-derivation (pods are deep-copied for the same reason)
        self._ns_labels = {
            ns.name: dict(ns.labels) for ns in self.namespaces
        }
        enc = encode_cluster(snapshot, compute_ports=False)
        n = enc.n_pods
        self.n_pods = n
        if mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as PS

            from .parallel.mesh import GRANT_AXIS, POD_AXIS

            dp = mesh.shape[POD_AXIS]
            mp = mesh.shape[GRANT_AXIS]
            if slot_round % mp:
                raise ValueError(
                    f"slot_round={slot_round} not divisible by the grant "
                    f"axis size {mp}"
                )
            self._sh = {
                "maps": NamedSharding(mesh, PS(GRANT_AXIS, POD_AXIS)),
                "vec": NamedSharding(mesh, PS(POD_AXIS)),
                "pods": NamedSharding(mesh, PS(POD_AXIS, None)),
                "new4": NamedSharding(mesh, PS(None, POD_AXIS)),
                "rep": NamedSharding(mesh, PS()),
            }
        else:
            dp = 1
            self._sh = None
        align = 128 * dp
        self._pod_align = align
        if pod_headroom < 0:
            raise ValueError("pod_headroom must be >= 0")
        Np = max(align, -(-(n + pod_headroom) // align) * align)
        self._n_padded = Np
        tile = next(
            t for t in (4096, 2048, 1024, 512, 256, 128) if Np % t == 0
        )
        n_pad = Np - n
        pod_kv, pod_key, pod_ns = pad_pods(
            enc.pod_kv, enc.pod_key, enc.pod_ns, n_pad
        )
        # pod-slot bookkeeping: [0, n_pods) is the high-water mark of ever-
        # occupied slots; [n_pods, Np) is headroom; removed slots recycle
        self.pod_active = np.ones(n, dtype=bool)
        self._pod_free: List[int] = []
        self._pod_idx: Dict[str, int] = {}
        for i, p in enumerate(self.pods):
            self._pod_idx.setdefault(self._pod_key(p), i)
        self._col_valid = np.zeros(Np, dtype=bool)
        self._col_valid[:n] = True
        self._col_mask = self._put(
            np.packbits(self._col_valid, bitorder="little").view("<u4").copy(),
            "rep",
        )
        rv = np.zeros(Np, dtype=np.int8)
        rv[:n] = 1
        self._row_valid = self._put(rv, "vec")

        P = enc.n_policies
        self._slot_round = slot_round
        g_chunk = max(1, min(chunk, max(enc.ingress.n, enc.egress.n, 1)))
        ingress = pad_grants(
            enc.ingress, (-enc.ingress.n) % g_chunk, P, n_pad
        )
        egress = pad_grants(enc.egress, (-enc.egress.n) % g_chunk, P, n_pad)
        args = (
            self._put(pod_kv, "pods"),
            self._put(pod_key, "pods"),
            self._put(pod_ns, "vec"),
            *(
                self._put(a, "rep")
                for a in (
                    enc.ns_kv, enc.ns_key, enc.pol_sel, enc.pol_ns,
                    enc.pol_affects_ingress, enc.pol_affects_egress,
                    ingress, egress,
                )
            ),
        )
        maps = _build_maps(
            *args,
            chunk=g_chunk,
            direction_aware=cfg.direction_aware_isolation,
        )
        self._capacity = max(slot_round, -(-(P + 8) // slot_round) * slot_round)
        pad_slots = self._capacity - P
        self._sel_ing8 = self._place_map(jnp.pad(maps[0], ((0, pad_slots), (0, 0))))
        self._sel_eg8 = self._place_map(jnp.pad(maps[1], ((0, pad_slots), (0, 0))))
        self._ing_by_pol = self._place_map(jnp.pad(maps[2], ((0, pad_slots), (0, 0))))
        self._eg_by_pol = self._place_map(jnp.pad(maps[3], ((0, pad_slots), (0, 0))))
        self._ing_cnt = self._put(np.asarray(maps[4]), "vec")
        self._eg_cnt = self._put(np.asarray(maps[5]), "vec")
        self._free = list(range(P, self._capacity))
        for i, pol in enumerate(cluster.policies):
            key = self._key(pol)
            if key in self.policies:
                raise KeyError(f"duplicate policy {key}")
            self.policies[key] = pol
            self._slot[key] = i

        W = Np // 32
        if keep_matrix is None:
            keep_matrix = mesh is None or Np * W * 4 // dp <= (1 << 30)
        self.keep_matrix = keep_matrix
        #: matrix-free mode: touched rows/cols since the last full re-solve
        self.dirty_rows = np.zeros(n, dtype=bool)
        self.dirty_cols = np.zeros(n, dtype=bool)
        if keep_matrix:
            self._packed = _sweep_jit(
                self._sel_ing8,
                self._sel_eg8,
                self._ing_by_pol,
                self._eg_by_pol,
                self._ing_cnt > 0,
                self._eg_cnt > 0,
                self._col_mask,
                tile=tile,
                self_traffic=cfg.self_traffic,
                default_allow_unselected=cfg.default_allow_unselected,
            )
            # zero the padded/invalid ROWS too (the sweep masks columns
            # only): their junk default-allow bits never reach queries
            # (trimmed at [:n]) but later exact column patches clear them,
            # which the delta-closure base comparison would misread as
            # removed pairs
            self._packed = _mask_rows(self._packed, self._row_valid)
        else:
            self._packed = None
        self._vectorizer = PolicyVectorizer(
            self.pods,
            self._ns_labels,
            enc.vocab,
            {ns.name: i for i, ns in enumerate(self.namespaces)},
            cfg.direction_aware_isolation,
        )
        # host mirrors of the isolation counts (real pods only) — these plus
        # the vectorizer make every diff's row/word derivation host-local
        self._h_ing_cnt = np.asarray(self._ing_cnt, dtype=np.int64)[:n]
        self._h_eg_cnt = np.asarray(self._eg_cnt, dtype=np.int64)[:n]
        self._prewarm()
        self.init_time = time.perf_counter() - t0

    def _put(self, x, kind: str):
        """Place a host array: on the mesh with the named sharding, or on
        the single device."""
        if self._sh is not None:
            return jax.device_put(x, self._sh[kind])
        if self.device is not None:
            return jax.device_put(x, self.device)
        return jnp.asarray(x)

    def _place_map(self, x):
        """Reshard a computed [C, Np] map onto the state sharding (no-op on
        a single device — the array is already there)."""
        if self._sh is not None:
            return jax.device_put(x, self._sh["maps"])
        return x

    def _place_vec(self, x):
        if self._sh is not None:
            return jax.device_put(x, self._sh["vec"])
        return x

    def _prewarm(self) -> None:
        """Compile the diff-path kernels up front — through the exact same
        call path and argument construction real diffs use, so the first
        real diff isn't charged seconds of XLA compile: a no-op fused diff
        on a free slot (zeros in, zeros out; row 0 recomputed to its current
        value; column group fully masked) plus no-op spill patches."""
        if not self._free:
            # a checkpoint can be saved with zero free slots (growth happens
            # on the NEXT allocation); writing the prewarm zeros into an
            # occupied slot would silently erase that policy's device state
            self._grow()
        slot = self._free[-1]
        zeros4 = np.zeros((4, self._n_padded), dtype=np.int8)
        if self._packed is None:
            # matrix-free mode: the only diff kernels are the slot write and
            # the pod step
            out = _slot_write(
                *self._maps, np.int32(slot), self._put(zeros4, "new4")
            )
            (
                self._sel_ing8, self._sel_eg8, self._ing_by_pol,
                self._eg_by_pol, self._ing_cnt, self._eg_cnt,
            ) = out
            self._prewarm_pod_step()
            jax.block_until_ready(self._sel_ing8)
            return
        r0 = np.zeros(_ROW_GROUP, dtype=np.int32)
        c0 = np.zeros(_COL_GROUP, dtype=np.int32)
        meta0 = self._col_meta(c0, 0)
        for has_rows, has_cols in (
            (True, True), (False, True), (True, False), (False, False),
        ):
            out = _diff_step(
                self._packed, *self._maps, self._col_mask, self._row_valid,
                np.int32(slot),
                self._put(zeros4, "new4"),
                self._put(r0, "rep"), self._put(c0, "rep"),
                *(self._put(m, "rep") for m in meta0),
                has_rows=has_rows, has_cols=has_cols, **self._flags,
            )
            (
                self._packed, self._sel_ing8, self._sel_eg8,
                self._ing_by_pol, self._eg_by_pol, self._ing_cnt,
                self._eg_cnt,
            ) = out
        self._patch_spill(
            [(r0, None)],
            [(c0, np.zeros(_COL_GROUP, dtype=bool))],
        )
        self._prewarm_pod_step()
        jax.block_until_ready(self._packed)

    def _prewarm_pod_step(self) -> None:
        """Compile the pod add/remove kernel via a no-op: an ``active=0``
        (remove-style) step on an already-invalid slot writes zeros over
        zeros and clears bits that are already clear. Skipped when every
        slot is valid — the first real ``add_pod`` then grows the pod axis,
        which recompiles anyway."""
        invalid = np.nonzero(~self._col_valid)[0]
        if not len(invalid):
            return
        zeros_c = np.zeros((4, self._capacity), dtype=np.int8)
        self._dispatch_pod(
            int(invalid[-1]), zeros_c, active=False, bookkeep=False
        )

    # ------------------------------------------------------------- plumbing
    def _key(self, pol: NetworkPolicy) -> str:
        return f"{pol.namespace}/{pol.name}"

    @staticmethod
    def _pod_key(pod: Pod) -> str:
        return f"{pod.namespace}/{pod.name}"

    @property
    def _maps(self):
        return (
            self._sel_ing8,
            self._sel_eg8,
            self._ing_by_pol,
            self._eg_by_pol,
            self._ing_cnt,
            self._eg_cnt,
        )

    def _grow(self) -> None:
        slot_round = self._slot_round
        self._free.extend(
            range(self._capacity, self._capacity + slot_round)
        )
        self._capacity += slot_round
        pad = ((0, slot_round), (0, 0))
        # _place_map: a bare jnp.pad would leave grown maps with whatever
        # sharding XLA picked, not the state's (grants, pods) layout
        self._sel_ing8 = self._place_map(jnp.pad(self._sel_ing8, pad))
        self._sel_eg8 = self._place_map(jnp.pad(self._sel_eg8, pad))
        self._ing_by_pol = self._place_map(jnp.pad(self._ing_by_pol, pad))
        self._eg_by_pol = self._place_map(jnp.pad(self._eg_by_pol, pad))

    def _grow_pods(self, min_extra: int = 1) -> None:
        """Grow the pod axis by at least ``min_extra`` slots (rounded to the
        mesh-aligned pod padding, with a generous floor — a grow copies every
        device buffer and recompiles the diff kernels at the new shapes, so
        it must be rare; prefer ``pod_headroom`` at build time)."""
        a = self._pod_align
        grow = max(-(-min_extra // a) * a, 4 * a)
        Np = self._n_padded
        Np2 = Np + grow
        pod_pad = ((0, 0), (0, grow))
        self._sel_ing8 = self._place_map(jnp.pad(self._sel_ing8, pod_pad))
        self._sel_eg8 = self._place_map(jnp.pad(self._sel_eg8, pod_pad))
        self._ing_by_pol = self._place_map(jnp.pad(self._ing_by_pol, pod_pad))
        self._eg_by_pol = self._place_map(jnp.pad(self._eg_by_pol, pod_pad))
        self._ing_cnt = self._place_vec(jnp.pad(self._ing_cnt, (0, grow)))
        self._eg_cnt = self._place_vec(jnp.pad(self._eg_cnt, (0, grow)))
        self._col_valid = np.concatenate(
            [self._col_valid, np.zeros(grow, dtype=bool)]
        )
        self._col_mask = self._put(
            np.packbits(self._col_valid, bitorder="little").view("<u4").copy(),
            "rep",
        )
        rv = np.zeros(Np2, dtype=np.int8)
        rv[: self.n_pods] = self.pod_active
        self._row_valid = self._put(rv, "vec")
        if self._packed is not None:
            grown = jnp.pad(self._packed, ((0, grow), (0, grow // 32)))
            self._packed = (
                jax.device_put(grown, self._sh["pods"])
                if self._sh is not None
                else grown
            )
        self._n_padded = Np2
        self._closure = None  # shape changed; next closure_packed is full
        self._closure_base = None
        self._prewarm()  # recompile the diff kernels at the new shapes

    @property
    def _flags(self) -> dict:
        return dict(
            self_traffic=self.config.self_traffic,
            default_allow=self.config.default_allow_unselected,
        )

    @staticmethod
    def _col_meta(idx: np.ndarray, k: int):
        """(seg, words, wreal, clear) for one column group; ``k`` real cols
        (unique, sorted) at the front of ``idx``."""
        D = len(idx)
        uw, inv = np.unique(idx[:k] // 32, return_inverse=True)
        words = np.full(D, uw[-1] if len(uw) else 0, dtype=np.int32)
        words[: len(uw)] = uw
        wreal = np.zeros(D, dtype=bool)
        wreal[: len(uw)] = True
        seg = np.full(D, D, dtype=np.int32)  # pads → scratch slot D
        seg[:k] = inv
        clear = np.zeros(D, dtype=np.uint32)
        if k:
            np.bitwise_or.at(
                clear, inv, np.uint32(1) << (idx[:k] % 32).astype(np.uint32)
            )
        return seg, words, wreal, clear

    def _mark_closure_dirty(self, rows, cols) -> None:
        """Accumulate touched nodes since the last ``closure_packed`` — the
        delta-closure's suspect-row seed (``ops/closure.py``)."""
        if self._closure is None:
            return
        self._closure_dirty[rows] = True
        self._closure_dirty[cols] = True

    def closure_packed(self, tile: int = 7168):
        """Transitive closure of the current packed matrix (uint32 [Np, W]),
        incremental across diffs: the first call runs the full
        ``packed_closure``; later calls seed from the previous closure and
        re-derive only rows whose paths could route through a node a diff
        touched (``packed_closure_delta``) — bit-for-bit equal to a full
        re-closure, at diff-local cost. The cached closure is invalidated by
        pod-axis growth (shape change)."""
        if self._packed is None:
            raise ValueError(
                "closure needs the packed matrix; this verifier runs "
                "matrix-free (keep_matrix=False)"
            )
        from .ops.closure import packed_closure, packed_closure_delta

        # _closure_base is an explicit COPY, not a reference or an
        # arithmetic identity (XLA may alias `x + 0` to x): later diff
        # kernels donate self._packed's buffer, and an alias would silently
        # corrupt the stored base. Unlocks the additions-only fast path
        # (+1 packed-matrix of device memory, ~1.25 GB at 100k pods).
        # Taken only when the closure actually recomputes — a cache-hit
        # call implies _packed is unchanged since the base was stored.
        if self._closure is None:
            self._closure = packed_closure(self._packed, tile=tile)
            self._closure_dirty = np.zeros(self._n_padded, dtype=bool)
            self._closure_base = jnp.array(self._packed, copy=True)
        elif self._closure_dirty.any():
            self._closure = packed_closure_delta(
                self._packed, self._closure, self._closure_dirty,
                prev_base=self._closure_base, tile=tile,
            )
            self._closure_dirty[:] = False
            self._closure_base = jnp.array(self._packed, copy=True)
        return self._closure

    def _dispatch_diff(
        self, slot: int, new4_padded: np.ndarray,
        rows: np.ndarray, cols: np.ndarray,
    ) -> None:
        """One fused _diff_step covering the slot write + the first row and
        column groups; remaining groups spill to the standalone patches.
        (Row group no-ops recompute row 0 to its current value; column
        group no-ops are fully masked.)"""
        self._mark_closure_dirty(rows, cols)
        if self._packed is None:
            # matrix-free: update the maps + counts; record what a later
            # solve_stripe must re-verify
            step_args = (
                *self._maps, np.int32(slot), self._put(new4_padded, "new4"),
            )
            _TRACKER.track(
                "_slot_write",
                self._maps,
                lower=lambda: _slot_write.lower(*step_args),
            )
            out = _slot_write(*step_args)
            (
                self._sel_ing8, self._sel_eg8, self._ing_by_pol,
                self._eg_by_pol, self._ing_cnt, self._eg_cnt,
            ) = out
            self.dirty_rows[rows] = True
            self.dirty_cols[cols] = True
            return
        row_groups = list(_groups(rows, _ROW_GROUP))
        col_groups = list(_groups(cols, _COL_GROUP))
        r0 = (
            row_groups[0][0]
            if row_groups
            else np.zeros(_ROW_GROUP, dtype=np.int32)
        )
        if col_groups:
            c0, creal0 = col_groups[0]
            meta0 = self._col_meta(c0, int(creal0.sum()))
        else:
            c0 = np.zeros(_COL_GROUP, dtype=np.int32)
            meta0 = self._col_meta(c0, 0)
        step_args = (
            self._packed, *self._maps, self._col_mask, self._row_valid,
            np.int32(slot),
            self._put(new4_padded, "new4"),
            self._put(r0, "rep"),
            self._put(c0, "rep"),
            *(self._put(m, "rep") for m in meta0),
        )
        step_kwargs = dict(
            has_rows=bool(row_groups),
            has_cols=bool(col_groups),
            **self._flags,
        )
        _TRACKER.track(
            "_diff_step", self._packed, self._maps,
            static=(bool(row_groups), bool(col_groups))
            + tuple(sorted(self._flags.items())),
            lower=lambda: _diff_step.lower(*step_args, **step_kwargs),
        )
        out = _diff_step(*step_args, **step_kwargs)
        (
            self._packed, self._sel_ing8, self._sel_eg8, self._ing_by_pol,
            self._eg_by_pol, self._ing_cnt, self._eg_cnt,
        ) = out
        self._patch_spill(row_groups[1:], col_groups[1:])

    def _patch_spill(self, row_groups, col_groups) -> None:
        for idx, _ in row_groups:
            self._packed = _patch_rows(
                self._packed, *self._maps, self._col_mask,
                self._put(idx, "rep"), **self._flags,
            )
        for idx, creal in col_groups:
            meta = self._col_meta(idx, int(creal.sum()))
            self._packed = _patch_cols(
                self._packed, *self._maps, self._row_valid,
                self._put(idx, "rep"), *(self._put(m, "rep") for m in meta),
                **self._flags,
            )

    def _patch(self, rows: np.ndarray, cols: np.ndarray) -> None:
        """``rows``/``cols``: unique sorted touched src rows / dst columns."""
        self._mark_closure_dirty(rows, cols)
        self._patch_spill(
            list(_groups(rows, _ROW_GROUP)), list(_groups(cols, _COL_GROUP))
        )

    def _set_slot(self, slot: int, old4, new4) -> None:
        """old4/new4: host int8 [n] vector quadruples (old may be None for a
        fresh slot). Everything here is host math + async device dispatch —
        no device→host fetch sits on the diff's critical path."""
        n = self.n_pods
        zeros = np.zeros(n, dtype=np.int8)
        if old4 is None:
            old4 = (zeros,) * 4
        old_si, old_se = old4[0] != 0, old4[1] != 0
        new_si, new_se = new4[0] != 0, new4[1] != 0
        ing2 = self._h_ing_cnt + (new4[0].astype(np.int64) - old4[0])
        eg2 = self._h_eg_cnt + (new4[1].astype(np.int64) - old4[1])
        iso_chg_i = (self._h_ing_cnt > 0) != (ing2 > 0)
        iso_chg_e = (self._h_eg_cnt > 0) != (eg2 > 0)
        # rows (sources): egress selection or egress isolation changed;
        # dst columns: ingress selection or ingress isolation changed.
        # Peer-map changes need no extra rows/columns: an ing_by_pol change
        # only matters on dst columns the policy selects (⊆ the column set)
        # and an eg_by_pol change only on src rows it selects (⊆ the rows).
        rows = np.nonzero((old_se | new_se) | iso_chg_e)[0]
        cols = np.nonzero((old_si | new_si) | iso_chg_i)[0]
        self._h_ing_cnt = ing2
        self._h_eg_cnt = eg2
        stacked = np.zeros((4, self._n_padded), dtype=np.int8)
        stacked[:, :n] = new4
        self._dispatch_diff(slot, stacked, rows, cols)
        self.update_count += 1

    # ---------------------------------------------------------------- diffs
    def add_policy(self, pol: NetworkPolicy) -> None:
        key = self._key(pol)
        if key in self.policies:
            raise KeyError(f"policy {key} exists; use update_policy")
        if pol.namespace not in self._ns_labels:
            self._ns_labels[pol.namespace] = {}
        if not self._free:
            self._grow()
        vecs = self._vectorizer.vectors(pol)
        slot = self._free.pop()
        self.policies[key] = pol
        self._slot[key] = slot
        self._set_slot(slot, None, vecs)
        self._count_op("policy_add")

    def remove_policy(self, namespace: str, name: str) -> None:
        key = f"{namespace}/{name}"
        pol = self.policies.pop(key)  # KeyError if absent
        slot = self._slot.pop(key)
        old = self._vectorizer.vectors(pol)
        zero = np.zeros(self.n_pods, dtype=np.int8)
        self._set_slot(slot, old, (zero, zero, zero, zero))
        self._free.append(slot)
        self._count_op("policy_remove")

    def update_policy(self, pol: NetworkPolicy) -> None:
        key = self._key(pol)
        slot = self._slot[key]  # KeyError if absent
        old = self._vectorizer.vectors(self.policies[key])
        vecs = self._vectorizer.vectors(pol)
        self.policies[key] = pol
        self._set_slot(slot, old, vecs)
        self._count_op("policy_update")

    def _pod_cols(self, pod: Pod) -> np.ndarray:
        """int8 [4, C]: one pod's (sel_ing, sel_eg, ing_peer, eg_peer) flag
        against every resident policy, slot-indexed — O(P) host evaluation
        with object semantics (the pod may carry pairs the frozen vocab has
        never seen)."""
        cols = np.zeros((4, self._capacity), dtype=np.int8)
        for key, pol in self.policies.items():
            cols[:, self._slot[key]] = pod_policy_flags(
                pol, pod, self._ns_labels,
                self.config.direction_aware_isolation,
            )
        return cols

    def update_pod_labels(self, idx: int, labels: Dict[str, str]) -> None:
        """Relabel pod ``idx``: one map column + the pod's own row/word are
        patched; O(P) host evaluation of this single pod (object semantics —
        the pod may now carry pairs the frozen vocab has never seen)."""
        if not 0 <= idx < self.n_pods or not self.pod_active[idx]:
            raise KeyError(f"pod slot {idx} is not an active pod")
        pod = self.pods[idx]
        pod.labels = dict(labels)
        self._vectorizer.note_pod(idx)
        cols = self._pod_cols(pod)
        out = _apply_pod_col(
            *self._maps,
            np.int32(idx),
            *(self._put(c, "rep") for c in cols),
        )
        (
            self._sel_ing8, self._sel_eg8, self._ing_by_pol, self._eg_by_pol,
            self._ing_cnt, self._eg_cnt,
        ) = out
        self._h_ing_cnt[idx] = int(cols[0].sum())
        self._h_eg_cnt[idx] = int(cols[1].sum())
        if self._packed is None:
            self.dirty_rows[idx] = True
            self.dirty_cols[idx] = True
        else:
            self._patch(np.asarray([idx]), np.asarray([idx]))
        self.update_count += 1
        self._count_op("pod_relabel")

    # ------------------------------------------------------------ pod churn
    def _dispatch_pod(
        self, idx: int, cols4: np.ndarray, active: bool, *, bookkeep: bool = True
    ) -> None:
        """One fused pod-slot dispatch (occupy or tombstone). ``bookkeep``
        is False only for the prewarm no-op (a tombstone-over-tombstone
        write whose slot may lie beyond the dirty arrays)."""
        if bookkeep:
            self._mark_closure_dirty([idx], [idx])
        if self._packed is None:
            step_args = (
                *self._maps, self._col_mask, self._row_valid,
                np.int32(idx), self._put(cols4, "rep"),
                np.uint32(1 if active else 0),
            )
            _TRACKER.track(
                "_pod_step_mf",
                self._maps,
                lower=lambda: _pod_step_mf.lower(*step_args),
            )
            out = _pod_step_mf(*step_args)
            (
                self._sel_ing8, self._sel_eg8, self._ing_by_pol,
                self._eg_by_pol, self._ing_cnt, self._eg_cnt,
                self._col_mask, self._row_valid,
            ) = out
            if bookkeep:
                self.dirty_rows[idx] = True
                self.dirty_cols[idx] = True
        else:
            step_args = (
                self._packed, *self._maps, self._col_mask, self._row_valid,
                np.int32(idx), self._put(cols4, "rep"),
                np.uint32(1 if active else 0),
            )
            _TRACKER.track(
                "_pod_step", self._packed, self._maps,
                static=tuple(sorted(self._flags.items())),
                lower=lambda: _pod_step.lower(*step_args, **self._flags),
            )
            out = _pod_step(*step_args, **self._flags)
            (
                self._packed, self._sel_ing8, self._sel_eg8,
                self._ing_by_pol, self._eg_by_pol, self._ing_cnt,
                self._eg_cnt, self._col_mask, self._row_valid,
            ) = out
        if bookkeep:
            self.update_count += 1

    def add_namespace(self, ns: Namespace) -> bool:
        """Register a namespace created after the freeze (WITH its labels)
        before adding pods into it — pods in post-freeze namespaces
        evaluate object-level, so the labels take effect immediately.
        Returns True when newly registered; a no-op for a known namespace
        with identical labels; a label CHANGE on a known namespace
        delegates to :meth:`update_namespace_labels` (the batched
        incremental relabel — pre-round-5 engines raised here)."""
        existing = self._ns_labels.get(ns.name)
        if existing is not None:
            if dict(existing) != dict(ns.labels):
                self.update_namespace_labels(ns.name, ns.labels)
            return False
        self._ns_labels[ns.name] = dict(ns.labels)
        self.namespaces.append(Namespace(ns.name, dict(ns.labels)))
        vz = self._vectorizer
        vz.ns_index.setdefault(ns.name, len(vz.ns_index))
        self._count_op("namespace_add")
        return True

    def _ns_pod_slots(self, name: str) -> np.ndarray:
        """Active pod slots living in namespace ``name``, ascending."""
        return np.asarray(
            [
                i
                for i in range(self.n_pods)
                if self.pod_active[i] and self.pods[i].namespace == name
            ],
            dtype=np.int32,
        )

    def _set_ns_labels(self, name: str, labels: Dict[str, str]) -> None:
        """Swap the namespace's label set in the live ``_ns_labels`` dict
        (shared by reference with the vectorizer, whose
        ``_ns_selector_mask`` re-reads it on every policy (re-)encode — so
        FUTURE policy diffs see the new labels with no other bookkeeping)
        and in the ``namespaces`` list (checkpoint/round-trip surface)."""
        self._ns_labels[name] = dict(labels)
        for i, ns in enumerate(self.namespaces):
            if ns.name == name:
                self.namespaces[i] = Namespace(name, dict(labels))
                return
        self.namespaces.append(Namespace(name, dict(labels)))

    def update_namespace_labels(
        self, name: str, labels: Dict[str, str]
    ) -> None:
        """Relabel namespace ``name`` incrementally: a namespace label
        change moves ``namespaceSelector`` peer matches for EVERY pod in
        the namespace (the reference compiles those matches per namespace,
        ``kubesv/kubesv/model.py:271-295``) — the batched form of a pod
        relabel. Host side, each resident policy re-evaluates against the
        namespace's pods (object semantics — same oracle as
        ``update_pod_labels``); device side, the pods' map columns land in
        ``_COL_GROUP``-sized fused dispatches instead of one per pod, then
        the packed matrix re-derives just those rows ∧ columns (or the
        dirty sets grow, matrix-free). Pod selection cannot move — a
        policy selects by namespace IDENTITY plus pod labels — but the
        full column quadruple is recomputed anyway: it falls out of the
        same host pass for free and keeps one oracle."""
        if name not in self._ns_labels:
            raise KeyError(f"namespace {name} is not registered")
        if dict(self._ns_labels[name]) == dict(labels):
            return
        self._set_ns_labels(name, labels)
        self._count_op("namespace_relabel")
        idx_arr = self._ns_pod_slots(name)
        if not len(idx_arr):
            return
        G = _COL_GROUP
        for g0 in range(0, len(idx_arr), G):
            g = idx_arr[g0 : g0 + G]
            cols = np.stack(
                [self._pod_cols(self.pods[int(i)]) for i in g], axis=-1
            )  # int8 [4, C, k]
            for i, c in zip(g, np.moveaxis(cols, -1, 0)):
                self._h_ing_cnt[i] = int(c[0].sum())
                self._h_eg_cnt[i] = int(c[1].sum())
            pad = G - len(g)
            gi = np.concatenate([g, np.repeat(g[-1:], pad)])
            colsp = np.concatenate(
                [cols, np.repeat(cols[:, :, -1:], pad, axis=2)], axis=2
            )
            out = _apply_pod_cols_group(
                *self._maps,
                self._put(gi.astype(np.int32), "rep"),
                self._put(colsp, "rep"),
            )
            (
                self._sel_ing8, self._sel_eg8, self._ing_by_pol,
                self._eg_by_pol, self._ing_cnt, self._eg_cnt,
            ) = out
        if self._packed is None:
            self._mark_closure_dirty(idx_arr, idx_arr)
            self.dirty_rows[idx_arr] = True
            self.dirty_cols[idx_arr] = True
        else:
            self._patch(idx_arr, idx_arr)
        self.update_count += 1

    def remove_namespace(self, name: str) -> None:
        """Unregister namespace ``name``. Refuses while the namespace still
        holds active pods or policies (remove those first — the CLI's diff
        orders removals that way); otherwise drops it from the label dict
        and the ``namespaces`` list. The vectorizer keeps its frozen
        namespace row — membership masks are already empty, and a
        same-named namespace created later simply re-registers over it."""
        if name not in self._ns_labels:
            raise KeyError(f"namespace {name} is not registered")
        live = self._ns_pod_slots(name)
        if len(live):
            raise ValueError(
                f"namespace {name} still holds {len(live)} active pod(s); "
                "remove them before removing the namespace"
            )
        pols = [k for k in self.policies if k.split("/", 1)[0] == name]
        if pols:
            raise ValueError(
                f"namespace {name} still holds {len(pols)} polic(ies); "
                "remove them before removing the namespace"
            )
        del self._ns_labels[name]
        self.namespaces = [ns for ns in self.namespaces if ns.name != name]
        self._count_op("namespace_remove")

    def add_pod(self, pod: Pod) -> int:
        """Add a pod in O(P + N) — one fused device dispatch. Returns the
        pod's slot index (its row/column in the reach matrix). Reuses a
        tombstoned slot when one exists, then the built-in headroom
        (``pod_headroom`` + pad-to-alignment), and only then grows the pod
        axis (expensive — full state copy + kernel recompile)."""
        key = self._pod_key(pod)
        if key in self._pod_idx:
            raise KeyError(f"pod {key} exists; remove it first")
        if pod.namespace not in self._ns_labels:
            # auto-created namespace (empty labels) — mirrors
            # Cluster.__post_init__; fresh ns index, no frozen pods carry it
            self._ns_labels[pod.namespace] = {}
            vz = self._vectorizer
            vz.ns_index.setdefault(pod.namespace, len(vz.ns_index))
        pod = dataclasses.replace(
            pod, labels=dict(pod.labels), container_ports=dict(pod.container_ports)
        )
        # the host evaluation can raise (e.g. a malformed pod IP against an
        # ipBlock peer) — run it BEFORE any bookkeeping mutation so a failed
        # add leaves no phantom half-registered pod
        cols4 = self._pod_cols(pod)
        if self._pod_free:
            idx = self._pod_free.pop()
            self.pods[idx] = pod
            self.pod_active[idx] = True
        else:
            if self.n_pods >= self._n_padded:
                self._grow_pods()
            idx = self.n_pods
            self.n_pods += 1
            self.pods.append(pod)
            self.pod_active = np.append(self.pod_active, True)
            self._h_ing_cnt = np.append(self._h_ing_cnt, 0)
            self._h_eg_cnt = np.append(self._h_eg_cnt, 0)
            self.dirty_rows = np.append(self.dirty_rows, False)
            self.dirty_cols = np.append(self.dirty_cols, False)
        self._pod_idx[key] = idx
        self._col_valid[idx] = True
        self._vectorizer.note_pod(idx)
        self._h_ing_cnt[idx] = int(cols4[0].sum())
        self._h_eg_cnt[idx] = int(cols4[1].sum())
        self._dispatch_pod(idx, cols4, active=True)
        self._count_op("pod_add")
        return idx

    def remove_pod(self, namespace: str, name: str) -> int:
        """Remove a pod: tombstone its slot (zero column in every map, zero
        isolation counts, clear validity, zero its packed row + bit-column)
        in one fused dispatch. Returns the freed slot index."""
        key = f"{namespace}/{name}"
        idx = self._pod_idx.pop(key)  # KeyError if absent
        self.pod_active[idx] = False
        self._col_valid[idx] = False
        self._pod_free.append(idx)
        self._vectorizer.note_removed(idx)
        self._h_ing_cnt[idx] = 0
        self._h_eg_cnt[idx] = 0
        zeros = np.zeros((4, self._capacity), dtype=np.int8)
        self._dispatch_pod(idx, zeros, active=False)
        self._count_op("pod_remove")
        return idx

    @property
    def n_active(self) -> int:
        return int(self.pod_active.sum())

    def active_indices(self) -> np.ndarray:
        """Slot indices of live pods, ascending — the row/col order of
        :meth:`reach_active` and of ``as_cluster()``'s pod list."""
        return np.nonzero(self.pod_active)[0]

    def reach_active(self) -> np.ndarray:
        """Dense bool reach over live pods only (host) — tombstoned slots
        dropped; aligned with ``as_cluster()`` for oracle comparison."""
        act = self.active_indices()
        return self.reach[np.ix_(act, act)]

    # --------------------------------------------------------------- result
    def dirty_stripes(self, width: int) -> List[int]:
        """Stripe starts whose values may differ from the last sweep: the
        stripes containing a dirty column — or every stripe, when a dirty
        row exists (a row change spans all columns)."""
        if width % 32 or width <= 0:
            raise ValueError("width must be a positive multiple of 32")
        if self.dirty_rows.any():
            return list(range(0, self._n_padded, width))
        cols = np.nonzero(self.dirty_cols)[0]
        return sorted({int(c) // width * width for c in cols})

    def sweep_dirty(self, width: int):
        """Yield ``(d0, packed_words)`` for every stripe needing re-verify
        (``dirty_stripes``); when the iteration COMPLETES, both dirty sets
        are cleared — an abandoned sweep leaves them marked."""
        for d0 in self.dirty_stripes(width):
            yield d0, self.solve_stripe(d0, width)
        self.dirty_rows[:] = False
        self.dirty_cols[:] = False

    def solve_stripe(self, d0: int, width: int) -> np.ndarray:
        """Re-solve dst columns ``[d0, d0+width)`` straight from the current
        per-policy maps → uint32 [n, width/32]. This is matrix-free mode's
        re-verify primitive (config-5 scale, where the full packed matrix
        never fits); the result always reflects the CURRENT maps. Drive a
        post-diff re-verify through ``sweep_dirty`` (which also retires the
        dirty bookkeeping) rather than calling this directly."""
        if d0 < 0 or d0 % 32 or width % 32 or width <= 0:
            raise ValueError(
                "d0 must be a non-negative multiple of 32 and width a "
                "positive multiple of 32"
            )
        if d0 + width > self._n_padded:
            raise ValueError(
                f"stripe [{d0}, {d0 + width}) outside the padded pod range "
                f"{self._n_padded}"
            )
        STRIPE_WIDTH.labels(engine=self.metrics_engine).set(width)
        STRIPES_SOLVED.labels(engine=self.metrics_engine).inc()
        stripe_args = (
            *self._maps, self._col_mask, self._row_valid, np.int32(d0),
        )
        stripe_kwargs = dict(width=width, **self._flags)
        _TRACKER.track(
            "_stripe_step", self._maps,
            static=(width,) + tuple(sorted(self._flags.items())),
            lower=lambda: _stripe_step.lower(*stripe_args, **stripe_kwargs),
        )
        out = retry_transient(
            lambda: _stripe_step(*stripe_args, **stripe_kwargs),
            policy=self.retry_policy,
            backend=self.metrics_engine,
        )
        return np.asarray(out[: self.n_pods])

    def solve_rows(self, rows) -> np.ndarray:
        """Re-solve the packed reach ROWS of the given source pod indices
        straight from the current maps → uint32 [K, n_padded/32] (word
        columns cover the full padded dst range; padded/tombstoned columns
        are masked off). The transpose of :meth:`solve_stripe` and the row
        oracle for :func:`~.ops.closure.bounded_closure_rows` at config-5
        scale — a path query's whole BFS touches K rows per level, never
        the N x N matrix. The batch is padded to the next power of two
        (pads repeat a valid id) so compiled signatures stay logarithmic
        in K."""
        rows = np.asarray(rows, dtype=np.int32)
        if rows.ndim != 1:
            raise ConfigError("rows must be a 1-D index array")
        if rows.size == 0:
            return np.zeros((0, self._n_padded // 32), dtype=np.uint32)
        if rows.min() < 0 or rows.max() >= self.n_pods:
            raise ConfigError(
                f"row index out of range [0, {self.n_pods})"
            )
        k = rows.size
        pad = 1 << max(0, k - 1).bit_length()
        padded = np.empty(pad, dtype=np.int32)
        padded[:k] = rows
        padded[k:] = rows[-1]
        row_args = (
            *self._maps, self._col_mask, self._row_valid,
            self._put(padded, "rep"),
        )
        _TRACKER.track(
            "_rows_step", self._maps,
            static=(pad,) + tuple(sorted(self._flags.items())),
            lower=lambda: _rows_step.lower(*row_args, **self._flags),
        )
        out = retry_transient(
            lambda: _rows_step(*row_args, **self._flags),
            policy=self.retry_policy,
            backend=self.metrics_engine,
        )
        return np.asarray(out[:k])

    def packed_reach(self) -> PackedReach:
        """Current state as a :class:`~.ops.tiled.PackedReach` (the packed
        matrix stays device-resident; queries reduce on device)."""
        if self._packed is None:
            raise ValueError(
                "keep_matrix=False: the packed matrix is not materialised at "
                "this scale — use solve_stripe(d0, width) to re-verify dst "
                "ranges from the maps"
            )
        n = self.n_pods
        return PackedReach(
            packed=self._packed[:n],
            n_pods=n,
            ingress_isolated=np.asarray(self._ing_cnt > 0)[:n],
            egress_isolated=np.asarray(self._eg_cnt > 0)[:n],
            active=None if self.pod_active.all() else self.pod_active.copy(),
        )

    @property
    def reach(self) -> np.ndarray:
        """Dense bool [N, N] view (host) — for tests and small clusters."""
        return self.packed_reach().to_bool()

    def as_cluster(self, include_inactive: bool = False) -> Cluster:
        """The live cluster (pods in slot order, tombstones dropped).
        ``include_inactive=True`` keeps tombstoned pods in place — the
        checkpoint manifest form, where list position must equal slot
        index (paired with ``state_dict()["pod_active"]``)."""
        return Cluster(
            pods=[
                Pod(p.name, p.namespace, dict(p.labels), p.ip, dict(p.container_ports))
                for i, p in enumerate(self.pods)
                if include_inactive or self.pod_active[i]
            ],
            namespaces=list(self.namespaces),
            policies=list(self.policies.values()),
        )

    # ---------------------------------------------------------- persistence
    def state_dict(self) -> Dict[str, np.ndarray]:
        """Device state as host arrays for checkpointing (``utils/persist``).
        The int8 maps are bit-packed (8×); slot assignment and the
        ``dirty_rows``/``dirty_cols`` re-verify bookkeeping travel alongside
        so a resume restores the exact layout AND its pending sweep work.
        The cluster manifest (pods with their CURRENT labels + policies) is
        saved separately — the maintained maps already reflect every
        relabel, so the resume re-freezes the encoding on the current labels
        and the VECTORIZER's label-drift set starts empty (distinct from the
        preserved dirty row/col sets)."""
        keys = list(self.policies)
        pack = lambda m: np.packbits(
            np.asarray(m, dtype=np.uint8), axis=1, bitorder="little"
        )
        state = {
            "sel_ing": pack(self._sel_ing8),
            "sel_eg": pack(self._sel_eg8),
            "ing_by_pol": pack(self._ing_by_pol),
            "eg_by_pol": pack(self._eg_by_pol),
            "ing_cnt": np.asarray(self._ing_cnt, dtype=np.int32),
            "eg_cnt": np.asarray(self._eg_cnt, dtype=np.int32),
            "slots": np.asarray([self._slot[k] for k in keys], dtype=np.int32),
            "keys": np.array(keys),
            "n_padded": np.int64(self._n_padded),
            "capacity": np.int64(self._capacity),
            "slot_round": np.int64(self._slot_round),
            "update_count": np.int64(self.update_count),
            "dirty_rows": self.dirty_rows,
            "dirty_cols": self.dirty_cols,
            "pod_active": self.pod_active,
            # authoritative namespace list: tombstoned pods still sitting in
            # a REMOVED namespace make the manifest's auto-create resurrect
            # it on load — from_state prunes back to this list
            "ns_names": np.array([ns.name for ns in self.namespaces]),
        }
        if self._packed is not None:
            state["packed"] = np.asarray(self._packed)
        if self._closure is not None:
            # the maintained closure travels with the state so a serving
            # restart resumes `kv-tpu diff`'s delta re-closure instead of
            # paying a full re-closure (closure_base unlocks the
            # additions-only fast path across the restart too)
            state["closure"] = np.asarray(self._closure)
            state["closure_dirty"] = self._closure_dirty
            if self._closure_base is not None:
                state["closure_base"] = np.asarray(self._closure_base)
        return state

    @classmethod
    def from_state(
        cls,
        cluster: Cluster,
        state: Dict[str, np.ndarray],
        config: Optional[VerifyConfig] = None,
        device=None,
        mesh: Optional[jax.sharding.Mesh] = None,
        keep_matrix: Optional[bool] = None,
    ) -> "PackedIncrementalVerifier":
        """Resume from :meth:`state_dict` output WITHOUT re-solving: the
        maps/counts/matrix upload straight to the device (or mesh), only the
        host-side vectorizer re-freezes on the manifest's labels.
        ``keep_matrix=False`` drops a checkpointed matrix and resumes
        matrix-free (e.g. onto a mesh it would not fit); ``True`` requires
        the checkpoint to contain one."""
        self = cls.__new__(cls)
        self.config = config or VerifyConfig()
        self.mesh = mesh
        self.device = device or (None if mesh else jax.devices()[0])
        self.pods = [
            dataclasses.replace(
                p, labels=dict(p.labels), container_ports=dict(p.container_ports)
            )
            for p in cluster.pods
        ]
        # the manifest (dump_cluster) already lists every auto-created
        # namespace, so no snapshot/__post_init__ pass is needed here; the
        # state's authoritative ns list prunes namespaces a tombstone pod
        # resurrected through auto-create (see state_dict)
        self.namespaces = list(cluster.namespaces)
        if "ns_names" in state:
            live_ns = {str(x) for x in state["ns_names"]}
            self.namespaces = [
                ns for ns in self.namespaces if ns.name in live_ns
            ]
        # label dicts are COPIED: an aliased caller dict mutated in place
        # would satisfy the relabel no-op guard and silently skip the
        # re-derivation (pods are deep-copied for the same reason)
        self._ns_labels = {
            ns.name: dict(ns.labels) for ns in self.namespaces
        }
        self.n_pods = len(self.pods)
        Np = int(state["n_padded"])
        self._n_padded = Np
        self._capacity = int(state["capacity"])
        self._slot_round = int(state["slot_round"])
        self.update_count = int(state["update_count"])
        self._closure = None
        self._closure_base = None
        self._closure_dirty = None
        if mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as PS

            from .parallel.mesh import GRANT_AXIS, POD_AXIS

            dp = mesh.shape[POD_AXIS]
            mp = mesh.shape[GRANT_AXIS]
            if Np % (128 * dp):
                raise ValueError(
                    f"checkpointed padding {Np} incompatible with a "
                    f"{dp}-way pod axis"
                )
            if self._slot_round % mp:
                # _grow pads the grant-sharded slot axis by slot_round; a
                # non-divisible round would fail deep inside XLA later
                raise ValueError(
                    f"checkpointed slot_round={self._slot_round} not "
                    f"divisible by the grant axis size {mp}"
                )
            self._sh = {
                "maps": NamedSharding(mesh, PS(GRANT_AXIS, POD_AXIS)),
                "vec": NamedSharding(mesh, PS(POD_AXIS)),
                "pods": NamedSharding(mesh, PS(POD_AXIS, None)),
                "new4": NamedSharding(mesh, PS(None, POD_AXIS)),
                "rep": NamedSharding(mesh, PS()),
            }
        else:
            self._sh = None
        # kinds are ignored in single-device mode (self._sh is None)
        unpack = lambda m: np.unpackbits(
            m, axis=1, count=Np, bitorder="little"
        ).astype(np.int8)
        self._sel_ing8 = self._put(unpack(state["sel_ing"]), "maps")
        self._sel_eg8 = self._put(unpack(state["sel_eg"]), "maps")
        self._ing_by_pol = self._put(unpack(state["ing_by_pol"]), "maps")
        self._eg_by_pol = self._put(unpack(state["eg_by_pol"]), "maps")
        self._ing_cnt = self._put(np.asarray(state["ing_cnt"]), "vec")
        self._eg_cnt = self._put(np.asarray(state["eg_cnt"]), "vec")
        self._pod_align = 128 * (dp if mesh is not None else 1)
        self.pod_active = np.asarray(
            state.get("pod_active", np.ones(self.n_pods, dtype=bool))
        ).copy()
        self._pod_free = [
            i for i in range(self.n_pods) if not self.pod_active[i]
        ]
        self._pod_idx = {}
        for i, p in enumerate(self.pods):
            if self.pod_active[i]:
                self._pod_idx.setdefault(self._pod_key(p), i)
        self._col_valid = np.zeros(Np, dtype=bool)
        self._col_valid[: self.n_pods] = self.pod_active
        self._col_mask = self._put(
            np.packbits(self._col_valid, bitorder="little").view("<u4").copy(),
            "rep",
        )
        rv = np.zeros(Np, dtype=np.int8)
        rv[: self.n_pods] = self.pod_active
        self._row_valid = self._put(rv, "vec")
        keys = [str(k) for k in state["keys"]]
        slots = [int(s) for s in state["slots"]]
        by_key = {f"{p.namespace}/{p.name}": p for p in cluster.policies}
        self.policies = {}
        self._slot = {}
        for key, slot in zip(keys, slots):
            self.policies[key] = by_key[key]
            self._slot[key] = slot
        used = set(slots)
        self._free = [s for s in range(self._capacity) if s not in used]
        if keep_matrix is None:
            keep_matrix = "packed" in state
        elif keep_matrix and "packed" not in state:
            raise ValueError(
                "keep_matrix=True but the checkpoint was saved matrix-free; "
                "re-solve (or resume matrix-free and use solve_stripe)"
            )
        self.keep_matrix = keep_matrix
        self._packed = (
            self._put(np.asarray(state["packed"]), "pods")
            if keep_matrix
            else None
        )
        self.dirty_rows = np.asarray(state["dirty_rows"]).copy()
        self.dirty_cols = np.asarray(state["dirty_cols"]).copy()
        if "closure" in state and self._packed is not None:
            self._closure = self._put(np.asarray(state["closure"]), "pods")
            self._closure_dirty = np.asarray(
                state["closure_dirty"], dtype=bool
            ).copy()
            if "closure_base" in state:
                self._closure_base = self._put(
                    np.asarray(state["closure_base"]), "pods"
                )
        self._vectorizer = PolicyVectorizer(
            self.pods,
            self._ns_labels,
            cluster_vocab(self.pods, self.namespaces),
            {ns.name: i for i, ns in enumerate(self.namespaces)},
            self.config.direction_aware_isolation,
        )
        self._vectorizer.inactive = {
            i for i in range(self.n_pods) if not self.pod_active[i]
        }
        self._h_ing_cnt = np.asarray(state["ing_cnt"], dtype=np.int64)[: self.n_pods]
        self._h_eg_cnt = np.asarray(state["eg_cnt"], dtype=np.int64)[: self.n_pods]
        self.init_time = 0.0
        self._prewarm()
        return self


# Kernel-manifest registration (observe/aot.py): rebind the jitted entry
# points so the warm-start pack can serve packed executables; call sites
# above are unchanged (late binding). Donation aliasing is preserved —
# the wrapper lowers/dispatches dynamics positionally for these kernels.
from .observe.aot import register_kernel as _register_kernel  # noqa: E402

_slot_write = _register_kernel("packed", "_slot_write", _slot_write)
_stripe_step = _register_kernel(
    "packed", "_stripe_step", _stripe_step,
    static_argnames=("width", "self_traffic", "default_allow"),
)
_rows_step = _register_kernel(
    "packed", "_rows_step", _rows_step,
    static_argnames=("self_traffic", "default_allow"),
)
_apply_pod_col = _register_kernel("packed", "_apply_pod_col", _apply_pod_col)
_apply_pod_cols_group = _register_kernel(
    "packed", "_apply_pod_cols_group", _apply_pod_cols_group
)
_pod_step = _register_kernel(
    "packed", "_pod_step", _pod_step,
    static_argnames=("self_traffic", "default_allow"),
)
_pod_step_mf = _register_kernel("packed", "_pod_step_mf", _pod_step_mf)
_patch_rows = _register_kernel(
    "packed", "_patch_rows", _patch_rows,
    static_argnames=("self_traffic", "default_allow"),
)
_patch_cols = _register_kernel(
    "packed", "_patch_cols", _patch_cols,
    static_argnames=("self_traffic", "default_allow"),
)
_diff_step = _register_kernel(
    "packed", "_diff_step", _diff_step,
    static_argnames=("self_traffic", "default_allow", "has_rows", "has_cols"),
)
_build_maps = _register_kernel(
    "packed", "_build_maps", _build_maps,
    static_argnames=("chunk", "direction_aware"),
)
_sweep_jit = _register_kernel(
    "packed", "_sweep_packed", _sweep_jit,
    static_argnames=("tile", "self_traffic", "default_allow_unselected"),
)
_mask_rows = _register_kernel("packed", "_mask_rows", _mask_rows)
