"""Resilience for kubernetes-verification-tpu: typed errors, retries,
fallback chains, watchdogs, OOM degradation and fault injection.

* ``errors``  — the :class:`KvTpuError` taxonomy + the CLI exit-code
  contract (dependency-free; every other layer imports it).
* ``retry``   — :class:`RetryPolicy` / :func:`retry_transient`, the
  bounded-backoff primitive the incremental engines wrap their jitted
  dispatches in.
* ``wrapper`` — :func:`resilient_verify` / :func:`resilient_verify_kano`:
  the fallback-chain / watchdog / adaptive-degradation driver.
* ``breaker`` — per-backend circuit breaker (closed/open/half-open with
  cooldown) consulted by the chain driver and the serving loop.
* ``faults``  — the deterministic ``faulty:<backend>`` injection harness
  plus the named durability kill-points for the crash-fault harness.

Only ``errors`` is imported eagerly: modules like ``backends.base`` and
``ingest.yaml_io`` import taxonomy classes from here *while they are
themselves being imported by* ``wrapper``/``faults`` — the lazy attribute
hook below keeps that edge acyclic.
"""
from __future__ import annotations

from .errors import (  # noqa: F401  (re-exported)
    EXIT_BACKEND_FAILED,
    EXIT_INPUT_ERROR,
    EXIT_OK,
    EXIT_VIOLATIONS,
    BackendChainExhausted,
    BackendError,
    BackendOOM,
    BackendTimeout,
    ConfigError,
    DeviceLost,
    EncodeError,
    FencedError,
    IngestError,
    KvTpuError,
    PersistError,
    ServeError,
    StaleReadError,
    UnknownBackendError,
    classify_exception,
    exit_code_for,
)

__all__ = [
    "KvTpuError",
    "IngestError",
    "PersistError",
    "EncodeError",
    "ConfigError",
    "ServeError",
    "StaleReadError",
    "FencedError",
    "BackendError",
    "BackendOOM",
    "BackendTimeout",
    "DeviceLost",
    "UnknownBackendError",
    "BackendChainExhausted",
    "classify_exception",
    "exit_code_for",
    "EXIT_OK",
    "EXIT_VIOLATIONS",
    "EXIT_INPUT_ERROR",
    "EXIT_BACKEND_FAILED",
    # lazy (see __getattr__):
    "RetryPolicy",
    "retry_transient",
    "ResilienceConfig",
    "resilient_verify",
    "resilient_verify_kano",
    "FaultRule",
    "FaultInjector",
    "FaultyBackend",
    "parse_fault_spec",
    "register_faulty",
    "FAULT_KINDS",
    "KILL_POINTS",
    "KillPointInjector",
    "install_kill_points",
    "clear_kill_points",
    "kill_point",
    "CircuitBreaker",
    "breaker_for",
    "reset_breakers",
    "breaker_states",
]

_LAZY = {
    "RetryPolicy": "retry",
    "retry_transient": "retry",
    "ResilienceConfig": "wrapper",
    "resilient_verify": "wrapper",
    "resilient_verify_kano": "wrapper",
    "FaultRule": "faults",
    "FaultInjector": "faults",
    "FaultyBackend": "faults",
    "parse_fault_spec": "faults",
    "register_faulty": "faults",
    "FAULT_KINDS": "faults",
    "KILL_POINTS": "faults",
    "KillPointInjector": "faults",
    "install_kill_points": "faults",
    "clear_kill_points": "faults",
    "kill_point": "faults",
    "CircuitBreaker": "breaker",
    "breaker_for": "breaker",
    "reset_breakers": "breaker",
    "breaker_states": "breaker",
}


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)
