"""Per-backend circuit breaker: closed / open / half-open with cooldown.

A flapping backend (a TPU pod slice mid-preemption, a driver wedged until
restart) fails *every* solve for a while. Without a breaker the fallback
chain pays the full retry schedule and watchdog timeout on that backend
for every request before falling through — latency the healthy tail of
the chain never sees. The breaker remembers: after ``failure_threshold``
consecutive exhausted attempts the circuit **opens** and the backend is
skipped outright; after ``cooldown`` seconds one probe is let through
(**half-open**); a probe success re-**closes** the circuit, a probe
failure re-opens it for another cooldown.

State transitions increment
``kvtpu_breaker_transitions_total{backend,to}`` so a flapping backend is
visible as open/half_open churn on the dashboard.

Two consumers:

* :func:`~.wrapper._resilient_call` consults a process-wide registry
  (:func:`breaker_for`) when ``ResilienceConfig.breaker_threshold`` > 0,
  skipping open backends in the chain;
* :class:`~..serve.service.VerificationService` owns a private instance
  guarding the incremental derivation, so a persistently failing engine
  stops paying a doomed solve before every from-scratch fallback.

``clock`` is injectable (``time.monotonic`` signature) so tests drive the
cooldown without sleeping.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Tuple

from ..observe import log_event
from ..observe.flight import trigger_dump
from ..observe.metrics import BREAKER_TRANSITIONS_TOTAL

__all__ = [
    "CLOSED",
    "OPEN",
    "HALF_OPEN",
    "CircuitBreaker",
    "breaker_for",
    "reset_breakers",
    "breaker_states",
]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """One backend's breaker. Thread-safe; all methods are O(1)."""

    def __init__(
        self,
        backend: str,
        *,
        failure_threshold: int = 3,
        cooldown: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.backend = backend
        self.failure_threshold = max(1, int(failure_threshold))
        self.cooldown = float(cooldown)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at: float = 0.0
        self._probe_inflight = False
        #: transition history (new state names, oldest first) — cheap to
        #: keep and makes test assertions direct
        self.transitions: List[str] = []

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _transition(self, to: str) -> None:
        # lock held by the caller
        if to == self._state:
            return
        self._state = to
        self.transitions.append(to)
        BREAKER_TRANSITIONS_TOTAL.labels(backend=self.backend, to=to).inc()
        log_event("breaker", backend=self.backend, state=to)
        if to == OPEN:
            # a circuit opening is exactly the moment whose prior context
            # matters for post-mortem: flush the flight-recorder ring (a
            # no-op unless one is installed; rare, so the dump cost under
            # this lock is acceptable)
            trigger_dump("breaker-open", backend=self.backend)

    def allow(self) -> bool:
        """May the caller attempt this backend now? An open circuit whose
        cooldown has elapsed admits exactly one half-open probe."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self.cooldown:
                    self._transition(HALF_OPEN)
                    self._probe_inflight = True
                    return True
                return False
            # HALF_OPEN: one outstanding probe at a time
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._probe_inflight = False
            if self._state == HALF_OPEN:
                # the probe failed: back to a full cooldown
                self._opened_at = self._clock()
                self._transition(OPEN)
                return
            self._failures += 1
            if self._state == CLOSED and (
                self._failures >= self.failure_threshold
            ):
                self._opened_at = self._clock()
                self._transition(OPEN)


_BREAKERS: Dict[str, CircuitBreaker] = {}
_REGISTRY_LOCK = threading.Lock()


def breaker_for(
    backend: str,
    *,
    failure_threshold: int = 3,
    cooldown: float = 30.0,
    clock: Callable[[], float] = time.monotonic,
) -> CircuitBreaker:
    """The process-wide breaker for ``backend`` (created on first use —
    breaker state must survive across ``resilient_verify`` calls, which is
    the whole point). The first caller's knobs win."""
    with _REGISTRY_LOCK:
        br = _BREAKERS.get(backend)
        if br is None:
            br = CircuitBreaker(
                backend,
                failure_threshold=failure_threshold,
                cooldown=cooldown,
                clock=clock,
            )
            _BREAKERS[backend] = br
        return br


def reset_breakers() -> None:
    """Drop every registered breaker (test isolation)."""
    with _REGISTRY_LOCK:
        _BREAKERS.clear()


def breaker_states() -> List[Tuple[str, str]]:
    """(backend, state) for every registered breaker, sorted by backend."""
    with _REGISTRY_LOCK:
        return sorted((name, br.state) for name, br in _BREAKERS.items())
