"""Bounded retry with exponential backoff + deterministic jitter.

Two consumers:

* the incremental engines wrap their jitted stripe/derive dispatches in
  :func:`retry_transient` so a flaky device dispatch doesn't kill a
  long-lived serving verifier mid-diff;
* ``resilience.wrapper`` reuses :class:`RetryPolicy` for the per-backend
  attempt loop of the fallback chain.

Jitter is seeded (``random.Random(seed)`` per call), so a given failure
sequence produces the same delay schedule on every run — fault-injection
tests and production post-mortems replay identically.
"""
from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, Iterator, Optional, TypeVar

from ..observe.metrics import RETRIES_TOTAL
from .errors import BackendError, classify_exception

__all__ = ["RetryPolicy", "retry_transient"]

T = TypeVar("T")


@dataclass(frozen=True)
class RetryPolicy:
    """How many times to retry a transient failure and how long to wait.

    Delay for retry ``i`` (0-based) is
    ``min(backoff_base * 2**i, backoff_max) * (1 + U[0, jitter))`` with the
    uniform draw from a ``seed``-initialised PRNG — exponential backoff,
    capped, with deterministic decorrelation jitter.
    """

    max_retries: int = 2
    backoff_base: float = 0.05
    backoff_max: float = 2.0
    jitter: float = 0.1
    seed: int = 0

    def delays(self) -> Iterator[float]:
        rng = random.Random(self.seed)
        for i in range(self.max_retries):
            base = min(self.backoff_base * (2.0 ** i), self.backoff_max)
            yield base * (1.0 + rng.random() * self.jitter)


#: a no-retry policy for hot paths that opt out (still classifies errors)
NO_RETRY = RetryPolicy(max_retries=0)


def retry_transient(
    fn: Callable[[], T],
    *,
    policy: RetryPolicy = RetryPolicy(),
    backend: str = "unknown",
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Optional[Callable[[BackendError, int], None]] = None,
) -> T:
    """Call ``fn``; on a *transient* :class:`BackendError` (after
    :func:`classify_exception`), back off and retry up to
    ``policy.max_retries`` times. Non-transient errors and exhausted
    budgets raise the classified error (original exception chained as
    ``__cause__``). Each retry increments ``kvtpu_retries_total``.
    """
    delays = policy.delays()
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — classify-and-dispatch point
            err = classify_exception(e, backend)
            try:
                delay = next(delays)
            except StopIteration:
                delay = None
            if not err.transient or delay is None:
                raise err from e
            RETRIES_TOTAL.labels(backend=backend, kind=err.kind).inc()
            if on_retry is not None:
                on_retry(err, attempt)
            sleep(delay)
            attempt += 1
