"""``resilient_verify`` — the fault-tolerant front door to the backend
registry.

Production TPU serving treats OOM, preemption and device loss as routine
(PAPERS.md: the distributed-linear-algebra pods only scale because chip
faults are tolerated; the CFD framework degrades tile sizes under memory
pressure). This wrapper gives the verifier the same posture around
``backends.base.verify``:

* **fallback chain** — an ordered backend list (``tpu → sharded → cpu``);
  when one backend fails non-transiently, the next is tried. The chain
  exhausting raises :class:`~.errors.BackendChainExhausted` (CLI exit 3).
* **bounded retry** — transient :class:`~.errors.BackendError`\\ s retry the
  *same* backend with exponential backoff + deterministic jitter
  (:class:`~.retry.RetryPolicy`).
* **watchdog** — each solve attempt runs under a wall-clock timeout; a hung
  attempt is abandoned (the worker thread is orphaned — XLA dispatches are
  not cancellable) and surfaces as a transient
  :class:`~.errors.BackendTimeout`.
* **adaptive OOM degradation** — ``RESOURCE_EXHAUSTED`` halves the ``tile``
  backend option and re-attempts, down to ``min_tile``, before the chain
  falls back. Halvings don't consume the retry budget: a smaller tile is
  progress, not repetition.
* **circuit breaker** (opt-in: ``breaker_threshold`` > 0) — a backend
  whose attempts keep exhausting their retries trips its per-backend
  breaker (:mod:`~.breaker`) and is skipped outright until the cooldown
  admits a half-open probe, so a flapping backend stops charging every
  request the full retry + watchdog toll.

Every decision is visible through the PR 1 registry:
``kvtpu_retries_total``, ``kvtpu_fallbacks_total``,
``kvtpu_degradations_total`` (and ``kvtpu_faults_injected_total`` from the
injection harness in ``resilience.faults``).
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, replace
from typing import Callable, List, Optional, Sequence, Tuple

from ..observe import log_event
from ..observe.metrics import DEGRADATIONS_TOTAL, FALLBACKS_TOTAL, RETRIES_TOTAL
from .errors import (
    BackendChainExhausted,
    BackendError,
    BackendOOM,
    BackendTimeout,
    ConfigError,
    KvTpuError,
    classify_exception,
)
from .retry import RetryPolicy

__all__ = ["ResilienceConfig", "resilient_verify", "resilient_verify_kano"]


@dataclass(frozen=True)
class ResilienceConfig:
    """The resilient wrapper's knobs (CLI: ``--fallback-chain``,
    ``--max-retries``, ``--solve-timeout``)."""

    #: ordered backends to try; () means "just the VerifyConfig's backend"
    fallback_chain: Tuple[str, ...] = ()
    max_retries: int = 2
    backoff_base: float = 0.05
    backoff_max: float = 2.0
    jitter: float = 0.1
    seed: int = 0
    #: wall-clock seconds per solve attempt; None disables the watchdog
    solve_timeout: Optional[float] = None
    #: halve the ``tile`` backend option on RESOURCE_EXHAUSTED
    degrade_on_oom: bool = True
    #: starting tile when the config carries none and an OOM asks for a halving
    initial_tile: int = 2048
    min_tile: int = 128
    #: consecutive exhausted attempts before a backend's circuit breaker
    #: opens and the chain skips it outright; 0 disables the breaker
    breaker_threshold: int = 0
    #: seconds an open circuit waits before admitting a half-open probe
    breaker_cooldown: float = 30.0

    def retry_policy(self) -> RetryPolicy:
        return RetryPolicy(
            max_retries=self.max_retries,
            backoff_base=self.backoff_base,
            backoff_max=self.backoff_max,
            jitter=self.jitter,
            seed=self.seed,
        )


def _with_opt(config, key: str, value) -> "object":
    """A copy of ``config`` with backend option ``key`` set to ``value``."""
    opts = [(k, v) for k, v in config.backend_options if k != key]
    opts.append((key, value))
    return replace(config, backend_options=tuple(opts))


def _run_with_watchdog(
    fn: Callable[[], object], timeout: Optional[float], backend: str
):
    """Run one solve attempt, bounded by ``timeout`` seconds.

    The attempt runs on a single-use **daemon** thread; on timeout it is
    abandoned (never joined — a hung XLA dispatch cannot be cancelled from
    Python) and :class:`BackendTimeout` is raised so the caller can retry
    or fall back. Daemon status is what keeps the contract honest: a
    non-daemon worker (e.g. ``ThreadPoolExecutor``'s) would be joined at
    interpreter exit, so the very hang the watchdog detected would block
    the CLI from ever delivering its exit code.
    """
    if timeout is None:
        return fn()
    outcome: List[Tuple[bool, object]] = []
    done = threading.Event()

    def _attempt() -> None:
        try:
            outcome.append((True, fn()))
        except BaseException as e:  # noqa: BLE001 — relayed to the caller
            outcome.append((False, e))
        finally:
            done.set()

    t = threading.Thread(
        target=_attempt, name=f"kvtpu-{backend}-watchdog", daemon=True
    )
    t.start()
    if not done.wait(timeout):
        raise BackendTimeout(
            f"watchdog: solve on {backend!r} exceeded {timeout}s",
            backend=backend,
        ) from None
    ok, payload = outcome[0]
    if ok:
        return payload
    raise payload  # type: ignore[misc]


def _resilient_call(
    run_one: Callable[[object], object],
    config,
    res: ResilienceConfig,
    sleep: Callable[[float], None],
):
    """The shared chain/retry/degrade driver behind both public wrappers.

    ``run_one(cfg)`` performs a single dispatch with ``cfg.backend`` /
    ``cfg.backend_options`` already set for the attempt.
    """
    chain: Tuple[str, ...] = res.fallback_chain or (config.backend,)
    if not chain:
        raise ConfigError("fallback chain is empty")
    failures: List[Tuple[str, BackendError]] = []
    for pos, backend in enumerate(chain):
        breaker = None
        if res.breaker_threshold > 0:
            from .breaker import breaker_for

            breaker = breaker_for(
                backend,
                failure_threshold=res.breaker_threshold,
                cooldown=res.breaker_cooldown,
            )
            if not breaker.allow():
                # circuit open: skip the doomed backend without burning
                # its retry schedule or watchdog budget
                err = BackendError(
                    f"circuit breaker open for {backend!r} "
                    f"(cooldown {res.breaker_cooldown}s)",
                    backend=backend, kind="breaker_open", transient=True,
                )
                failures.append((backend, err))
                if pos + 1 < len(chain):
                    FALLBACKS_TOTAL.labels(
                        from_backend=backend, to_backend=chain[pos + 1]
                    ).inc()
                    log_event(
                        "fallback", from_backend=backend,
                        to_backend=chain[pos + 1], kind="breaker_open",
                    )
                continue
        cfg = replace(config, backend=backend)
        delays = res.retry_policy().delays()
        err: Optional[BackendError] = None
        while True:
            try:
                result = _run_with_watchdog(
                    lambda: run_one(cfg), res.solve_timeout, backend
                )
                if breaker is not None:
                    breaker.record_success()
                return result
            except BackendError as e:
                err = classify_exception(e, backend)
            except KvTpuError:
                # IngestError / ConfigError / EncodeError ... are the
                # caller's input bug, not infrastructure: retrying or
                # falling back cannot fix them, and wrapping them would
                # misreport exit 2 (input error) as exit 3 (backend failed).
                raise
            except Exception as e:  # noqa: BLE001 — the classification point
                err = classify_exception(e, backend)
            # -- adaptive OOM degradation: halve the tile, try again -------
            if (
                isinstance(err, BackendOOM)
                and res.degrade_on_oom
            ):
                tile = dict(cfg.backend_options).get("tile", res.initial_tile)
                if isinstance(tile, int) and tile // 2 >= res.min_tile:
                    cfg = _with_opt(cfg, "tile", tile // 2)
                    DEGRADATIONS_TOTAL.labels(backend=backend).inc()
                    log_event(
                        "degrade", backend=backend, tile=tile // 2,
                        reason="oom",
                    )
                    continue
            # -- bounded transient retry on the same backend ---------------
            if err.transient:
                try:
                    delay = next(delays)
                except StopIteration:
                    delay = None
                if delay is not None:
                    RETRIES_TOTAL.labels(backend=backend, kind=err.kind).inc()
                    log_event(
                        "retry", backend=backend, kind=err.kind,
                        delay_seconds=round(delay, 4),
                    )
                    sleep(delay)
                    continue
            # -- give up on this backend: fall through the chain -----------
            if breaker is not None:
                breaker.record_failure()
            failures.append((backend, err))
            if pos + 1 < len(chain):
                FALLBACKS_TOTAL.labels(
                    from_backend=backend, to_backend=chain[pos + 1]
                ).inc()
                log_event(
                    "fallback", from_backend=backend,
                    to_backend=chain[pos + 1], kind=err.kind,
                )
            break
    raise BackendChainExhausted(chain, failures)


def resilient_verify(
    cluster,
    config=None,
    resilience: Optional[ResilienceConfig] = None,
    *,
    sleep: Callable[[float], None] = time.sleep,
):
    """:func:`backends.base.verify` behind the fallback chain / retry /
    watchdog / degradation driver. ``sleep`` is injectable so tests run the
    full backoff schedule in zero wall-clock time."""
    from ..backends import base

    config = config or base.VerifyConfig()
    res = resilience or ResilienceConfig()
    return _resilient_call(
        lambda cfg: base.verify(cluster, cfg), config, res, sleep
    )


def resilient_verify_kano(
    containers: Sequence,
    policies: Sequence,
    config=None,
    resilience: Optional[ResilienceConfig] = None,
    *,
    sleep: Callable[[float], None] = time.sleep,
):
    """:func:`backends.base.verify_kano` behind the same driver."""
    from ..backends import base

    config = config or base.VerifyConfig()
    res = resilience or ResilienceConfig()
    return _resilient_call(
        lambda cfg: base.verify_kano(containers, policies, cfg),
        config,
        res,
        sleep,
    )
