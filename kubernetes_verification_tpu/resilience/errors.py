"""The typed error taxonomy, rooted at :class:`KvTpuError`.

Every layer of the stack raises these instead of bare ``ValueError`` /
``RuntimeError`` (linted by ``scripts/check_error_taxonomy.py``), so callers
— the CLI's exit-code contract, the fallback chain in
``resilience.wrapper``, a serving loop's error budget — can dispatch on
*what failed* without string-matching tracebacks:

* :class:`IngestError`   — malformed manifests (parse layer);
* :class:`PersistError`  — corrupt / truncated / mismatched checkpoints;
* :class:`EncodeError`   — model objects the tensorizer cannot encode;
* :class:`ConfigError`   — invalid flag / option combinations;
* :class:`BackendError`  — a solve attempt failed. Carries ``transient``
  (retry the same backend may succeed), ``kind`` (``oom`` / ``timeout`` /
  ``device_loss`` / ``flaky`` / ``error``) and ``backend``.

Each taxonomy class also subclasses the builtin its call sites historically
raised (``ValueError`` / ``KeyError``), so pre-taxonomy ``except`` clauses
keep working — the re-parent widens the surface, it never narrows it.

``classify_exception`` maps raw XLA/JAX runtime errors onto the taxonomy by
their gRPC-style status markers (``RESOURCE_EXHAUSTED``,
``DEADLINE_EXCEEDED``, ...) — the production-TPU reality that preemption,
OOM and device loss are routine, not exceptional (PAPERS.md: the
distributed-linear-algebra and CFD TPU stacks both degrade-and-continue).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

__all__ = [
    "KvTpuError",
    "IngestError",
    "PersistError",
    "EncodeError",
    "ConfigError",
    "ServeError",
    "StaleReadError",
    "FencedError",
    "ReplicationError",
    "AdmissionRejectedError",
    "StripeRouteError",
    "StripeCoverageError",
    "BackendError",
    "BackendOOM",
    "BackendTimeout",
    "DeviceLost",
    "UnknownBackendError",
    "BackendChainExhausted",
    "classify_exception",
    "exit_code_for",
    "EXIT_OK",
    "EXIT_VIOLATIONS",
    "EXIT_INPUT_ERROR",
    "EXIT_BACKEND_FAILED",
]

#: The CLI exit-code contract (README "Resilience"): scripts and operators
#: branch on these, so they are part of the stable interface.
EXIT_OK = 0  #: verified, no requested invariant violated
EXIT_VIOLATIONS = 1  #: verified, but --check found violations
EXIT_INPUT_ERROR = 2  #: bad manifests / checkpoint / flags (IngestError, ...)
EXIT_BACKEND_FAILED = 3  #: every backend in the fallback chain failed


class KvTpuError(Exception):
    """Root of the kubernetes-verification-tpu error taxonomy."""


class IngestError(KvTpuError, ValueError):
    """Malformed manifests (the reference printed and continued,
    ``kano_py/kano/parser.py:32-33``; here the parse layer raises typed)."""


class PersistError(KvTpuError, ValueError):
    """A checkpoint/artifact failed to load or verify: truncated file,
    corrupt array, sha256 mismatch, or semantic-config mismatch. ``path``
    names the offending artifact."""

    def __init__(self, message: str, *, path: Optional[str] = None) -> None:
        super().__init__(message)
        self.path = path


class EncodeError(KvTpuError, ValueError):
    """The tensorizer cannot encode the model objects (e.g. a named-port
    restriction outside a frozen bank)."""


class ConfigError(KvTpuError, ValueError):
    """Invalid configuration: flag combinations, backend options, mesh
    shapes — errors the caller fixes by changing inputs, not by retrying."""


class ServeError(KvTpuError, ValueError):
    """The continuous-verification service rejected an input: an event that
    references an unknown pod/policy/namespace, a query naming a pod the
    engine does not hold, or misuse of the service lifecycle. Exit-code
    contract: input error (2) — the *stream*, not the solver, is wrong.
    ``event_index`` (when set) names the offending event's position in its
    stream."""

    def __init__(
        self, message: str, *, event_index: Optional[int] = None
    ) -> None:
        super().__init__(message)
        self.event_index = event_index


class StaleReadError(ServeError):
    """A follower read exceeded its staleness bound: the replica's applied
    state lags the leader's WAL by more than ``max_lag_seconds`` /
    ``max_lag_seq``, and the caller asked for a bounded read rather than a
    possibly-stale verdict. Carries the *measured* lag alongside the bound
    that was violated, so callers can retry, widen the bound, or route to
    the leader. Exit-code contract: input error (2), like every
    :class:`ServeError` — the replica is healthy, the bound is just unmet.
    """

    def __init__(
        self,
        message: str,
        *,
        lag_seconds: Optional[float] = None,
        lag_seq: Optional[int] = None,
        bound_seconds: Optional[float] = None,
        bound_seq: Optional[int] = None,
    ) -> None:
        super().__init__(message)
        self.lag_seconds = lag_seconds
        self.lag_seq = lag_seq
        self.bound_seconds = bound_seconds
        self.bound_seq = bound_seq


class FencedError(ServeError):
    """A writer holding a superseded epoch tried to append to the WAL (or
    renew the lease) after a follower promoted past it. ``epoch`` is the
    writer's stale reign, ``lease_epoch`` the current one in
    ``leader.lease``. The only correct reaction is to stop writing — the
    cluster has moved on."""

    def __init__(
        self,
        message: str,
        *,
        epoch: Optional[int] = None,
        lease_epoch: Optional[int] = None,
    ) -> None:
        super().__init__(message)
        self.epoch = epoch
        self.lease_epoch = lease_epoch


class ReplicationError(ServeError):
    """A replication-transport operation failed: the connection was refused
    or reset, a request timed out, a chunk arrived checksum-mismatched, or
    an injected network fault (``net-drop`` / ``net-partition``) fired at
    the transport seam. ``op`` names the wire operation (``tip`` / ``wal``
    / ``manifest`` / ``file``) and ``url`` the endpoint. Transient by
    construction — callers retry with capped jittered backoff and feed
    per-replica breakers; a follower that cannot reach its leader keeps
    serving (increasingly stale) reads from its local mirror."""

    def __init__(
        self,
        message: str,
        *,
        op: Optional[str] = None,
        url: Optional[str] = None,
    ) -> None:
        super().__init__(message)
        self.op = op
        self.url = url


class AdmissionRejectedError(ServeError):
    """The ingress admission controller refused a request at the front
    door. ``reason`` is one of the stable rejection classes —
    ``over-quota`` (the tenant's token bucket is empty; HTTP 429),
    ``concurrency`` (the global in-flight limit is reached; HTTP 503),
    ``queue-full`` (the bounded continuous-batching queue has no slot;
    HTTP 503), ``brownout`` (the overload ladder is shedding this
    tenant's priority class or the whole door; HTTP 503), ``deadline``
    (the request's budget cannot survive the current queue + service
    estimate, so admitting it would only manufacture a deadline
    violation; HTTP 503). ``retry_after_s`` is always finite and
    computed, never a guess: for ``over-quota`` it is the bucket's
    refill horizon, for the capacity reasons an escalating backoff hint
    — the HTTP seam renders it as a ``Retry-After`` header so clients
    back off instead of hammering. ``tenant`` names who was refused."""

    def __init__(
        self,
        message: str,
        *,
        retry_after_s: float = 1.0,
        tenant: Optional[str] = None,
        reason: str = "over-quota",
    ) -> None:
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)
        self.tenant = tenant
        self.reason = reason


class StripeRouteError(ServeError):
    """A query landed on a stripe owner that does not own the source rows
    it needs: the routing layer (or a direct caller) asked stripe ``k``
    for a row outside its ``[lo, hi)`` range. ``pod`` is the offending
    global row index, ``stripe`` the ``(index, count)`` pair that refused
    it. Always a routing bug or a direct misuse, never data loss — the
    row exists on its owning stripe."""

    def __init__(
        self,
        message: str,
        *,
        pod: Optional[int] = None,
        stripe: Optional[tuple] = None,
    ) -> None:
        super().__init__(message)
        self.pod = pod
        self.stripe = stripe


class StripeCoverageError(ServeError):
    """A scatter-gather query needed a stripe that has **no live owner**:
    every registered owner for that pod range failed or none was ever
    registered. The coordinator raises this instead of returning a
    silently-truncated answer — a coverage gap is an outage, not a
    smaller result set. ``stripe`` is the dead ``(index, count)`` pair,
    ``rows`` its ``(lo, hi)`` pod range."""

    def __init__(
        self,
        message: str,
        *,
        stripe: Optional[tuple] = None,
        rows: Optional[tuple] = None,
    ) -> None:
        super().__init__(message)
        self.stripe = stripe
        self.rows = rows


class BackendError(KvTpuError, RuntimeError):
    """A solve attempt failed on ``backend``. ``transient=True`` means the
    same backend may succeed on retry (flaky dispatch, preemption);
    ``transient=False`` sends the fallback chain to the next backend."""

    kind: str = "error"

    def __init__(
        self,
        message: str,
        *,
        backend: Optional[str] = None,
        kind: Optional[str] = None,
        transient: bool = False,
    ) -> None:
        super().__init__(message)
        self.backend = backend
        if kind is not None:
            self.kind = kind
        self.transient = transient


class BackendOOM(BackendError):
    """Device memory exhausted (XLA ``RESOURCE_EXHAUSTED``). Transient in
    the adaptive sense: the resilient wrapper halves the tile size and
    retries before giving up on the backend."""

    kind = "oom"

    def __init__(self, message: str, *, backend: Optional[str] = None) -> None:
        super().__init__(message, backend=backend, transient=True)


class BackendTimeout(BackendError):
    """The per-attempt watchdog fired (or XLA reported
    ``DEADLINE_EXCEEDED``): the solve is presumed hung, not wrong."""

    kind = "timeout"

    def __init__(self, message: str, *, backend: Optional[str] = None) -> None:
        super().__init__(message, backend=backend, transient=True)


class DeviceLost(BackendError):
    """The accelerator went away (preemption, reset, ICI failure).
    Non-transient for this backend — retrying the same dead device wastes
    the error budget; the chain falls back instead."""

    kind = "device_loss"

    def __init__(self, message: str, *, backend: Optional[str] = None) -> None:
        super().__init__(message, backend=backend, transient=False)


class UnknownBackendError(BackendError, KeyError):
    """Requested backend is not registered (also a ``KeyError`` — the
    registry's historical type)."""

    kind = "unknown_backend"
    # KeyError.__str__ reprs its argument, which would quote every CLI
    # diagnostic and BackendChainExhausted detail ('"unknown backend ..."')
    __str__ = Exception.__str__

    def __init__(self, message: str, *, backend: Optional[str] = None) -> None:
        super().__init__(message, backend=backend, transient=False)


class BackendChainExhausted(BackendError):
    """Every backend in the fallback chain failed. ``failures`` lists
    ``(backend, BackendError)`` in attempt order — the post-mortem."""

    kind = "chain_exhausted"

    def __init__(
        self, chain: Tuple[str, ...], failures: List[Tuple[str, "BackendError"]]
    ) -> None:
        detail = "; ".join(
            f"{b}: [{e.kind}] {e}" for b, e in failures
        )
        super().__init__(
            f"all backends in chain {list(chain)} failed: {detail}",
            transient=False,
        )
        self.chain = tuple(chain)
        self.failures = list(failures)


#: substring → taxonomy class, checked in order. XLA surfaces gRPC status
#: names inside RuntimeError/XlaRuntimeError messages; jax has no stable
#: exception hierarchy for them, so message markers are the only portable
#: classification key.
_MESSAGE_MARKERS = (
    ("RESOURCE_EXHAUSTED", BackendOOM),
    ("out of memory", BackendOOM),
    ("Out of memory", BackendOOM),
    ("DEADLINE_EXCEEDED", BackendTimeout),
    ("deadline exceeded", BackendTimeout),
    ("DATA_LOSS", DeviceLost),
    ("device is lost", DeviceLost),
    ("Device lost", DeviceLost),
    ("device halted", DeviceLost),
)

#: markers for generically transient conditions (retry same backend)
_TRANSIENT_MARKERS = ("UNAVAILABLE", "ABORTED", "CANCELLED", "try again")


def classify_exception(
    exc: BaseException, backend: Optional[str] = None
) -> BackendError:
    """Map an arbitrary solve-time exception onto the taxonomy.

    Already-typed :class:`BackendError`\\ s pass through (with ``backend``
    filled in when missing); raw XLA/JAX errors classify by message marker;
    anything else becomes a non-transient :class:`BackendError` so the
    fallback chain still gets a chance before the run dies.
    """
    if isinstance(exc, BackendError):
        if exc.backend is None:
            exc.backend = backend
        return exc
    msg = str(exc)
    for marker, cls in _MESSAGE_MARKERS:
        if marker in msg:
            err = cls(msg, backend=backend)
            err.__cause__ = exc
            return err
    transient = any(m in msg for m in _TRANSIENT_MARKERS)
    err = BackendError(
        f"{type(exc).__name__}: {msg}", backend=backend, transient=transient
    )
    err.__cause__ = exc
    return err


def exit_code_for(exc: BaseException) -> int:
    """The CLI exit-code contract for an exception that escaped a command."""
    if isinstance(exc, BackendError):
        return EXIT_BACKEND_FAILED
    if isinstance(exc, KvTpuError):
        return EXIT_INPUT_ERROR
    # kvtpu: ignore[error-taxonomy] API-misuse guard on the taxonomy's own entry point — a foreign exception here is a caller bug, not an input error
    raise TypeError(f"not a KvTpuError: {type(exc).__name__}")
