"""Deterministic fault injection: ``faulty:<name>`` backends.

``register_faulty("tpu", parse_fault_spec("device_loss"))`` registers a
``faulty:tpu`` backend that delegates to the real ``tpu`` backend but
raises (or hangs) on a seeded, reproducible schedule — so the whole
resilience stack (fallback chain, retries, watchdog, OOM degradation) is
exercisable in tier-1 under ``JAX_PLATFORMS=cpu``, no broken hardware
required. The injector object lives in the registry closure, so its call
counter survives across ``get_backend`` instantiations: ``flaky@0`` means
"the first verify call through this registration fails", not "every fresh
instance fails once".

Fault spec grammar (comma list; also the CLI's ``--inject-faults`` value):

* ``KIND``      — inject on every call (``device_loss`` → dead backend);
* ``KIND@N``    — inject on call index ``N`` only (``flaky@0`` → fails
  once, the retry succeeds);
* ``oom>T``     — inject OOM while the attempt's ``tile`` option (default
  2048) is above ``T`` — exercises adaptive degradation: the wrapper
  halves the tile until the injector relents;
* ``KIND%P``    — inject with probability ``P`` per call, drawn from a
  ``seed``-initialised PRNG (deterministic across runs).

Kinds: ``oom``, ``timeout`` (a simulated hang of ``hang_seconds`` — pair
with a watchdog), ``device_loss``, ``flaky`` (generic transient).

Every injection increments ``kvtpu_faults_injected_total{backend,kind}``.

Crash kill-points: the spec grammar also accepts the named points in the
durability write path (``after-tmp-write``, ``before-rename``,
``mid-log-append``, ``after-manifest``) and the replication control plane
(``before-lease-renew``, ``after-promote-epoch``). These are not backend
faults —
:func:`install_kill_points` arms them process-wide and the durability code
calls :func:`kill_point` at each site; a firing point hard-kills the
process with ``os._exit`` (no cleanup, no atexit — the closest userspace
stand-in for SIGKILL), which is what the recovery fuzz harness drives
through a subprocess.
"""
from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from ..observe.metrics import (
    FAULTS_INJECTED_TOTAL,
    INGRESS_FAULTS_INJECTED_TOTAL,
    NET_FAULTS_INJECTED_TOTAL,
)
from .errors import (
    BackendError,
    BackendOOM,
    ConfigError,
    DeviceLost,
    ReplicationError,
)

__all__ = [
    "FAULT_KINDS",
    "KILL_POINTS",
    "NET_FAULT_KINDS",
    "INGRESS_FAULT_KINDS",
    "FaultRule",
    "FaultInjector",
    "FaultyBackend",
    "KillPointInjector",
    "NetFaultInjector",
    "IngressFaultInjector",
    "parse_fault_spec",
    "register_faulty",
    "install_kill_points",
    "clear_kill_points",
    "kill_point",
    "install_net_faults",
    "clear_net_faults",
    "heal_net_partition",
    "net_fault",
    "install_ingress_faults",
    "clear_ingress_faults",
    "ingress_fault",
]

#: named crash points in the durability write path (serve/durability.py,
#: the WAL append path) and the replication control plane
#: (serve/replication.py lease renewal / promotion) — process-killing,
#: not backend faults
KILL_POINTS = (
    "after-tmp-write",
    "before-rename",
    "mid-log-append",
    "after-manifest",
    "before-lease-renew",
    "after-promote-epoch",
)

#: network fault kinds injected at the replication-transport seam
#: (serve/transport.py calls :func:`net_fault` before every wire request):
#: ``net-drop`` fails one request, ``net-delay`` adds latency to one,
#: ``net-partition`` latches — every request fails until
#: :func:`heal_net_partition` (or :func:`clear_net_faults`)
NET_FAULT_KINDS = ("net-drop", "net-delay", "net-partition")

#: client-behaviour faults injected at the ingress seam (serve/ingress.py
#: calls :func:`ingress_fault` once per client submission): ``client-burst``
#: amplifies one submission into an N-times arrival spike, ``slow-client``
#: stalls the request body before it reaches admission — both exercisable
#: under ``JAX_PLATFORMS=cpu``
INGRESS_FAULT_KINDS = ("client-burst", "slow-client")

FAULT_KINDS = (
    ("oom", "timeout", "device_loss", "flaky")
    + KILL_POINTS
    + NET_FAULT_KINDS
    + INGRESS_FAULT_KINDS
)

#: tile assumed when an ``oom>T`` rule fires against a config carrying no
#: explicit ``tile`` option — matches ResilienceConfig.initial_tile
_DEFAULT_TILE = 2048


@dataclass(frozen=True)
class FaultRule:
    """One injection rule; exactly one trigger dimension is set (or none,
    meaning "every call")."""

    kind: str
    at_call: Optional[int] = None
    while_tile_above: Optional[int] = None
    prob: Optional[float] = None

    def __post_init__(self) -> None:
        if self.kind not in FAULT_KINDS:
            raise ConfigError(
                f"unknown fault kind {self.kind!r}; have {FAULT_KINDS}"
            )
        if self.while_tile_above is not None and self.kind != "oom":
            raise ConfigError("'>' (tile relief) only applies to oom faults")


def parse_fault_spec(spec: str) -> List[FaultRule]:
    """Parse the ``KIND[@N|>T|%P]`` comma grammar (module docstring)."""
    rules = []
    for token in spec.split(","):
        token = token.strip()
        if not token:
            continue
        for sep, field in (("@", "at_call"), (">", "while_tile_above"), ("%", "prob")):
            if sep in token:
                kind, _, raw = token.partition(sep)
                try:
                    val = float(raw) if sep == "%" else int(raw)
                except ValueError:
                    raise ConfigError(
                        f"fault spec {token!r}: {raw!r} is not a number"
                    ) from None
                rules.append(FaultRule(kind=kind, **{field: val}))
                break
        else:
            rules.append(FaultRule(kind=token))
    if not rules:
        raise ConfigError(f"empty fault spec {spec!r}")
    return rules


class FaultInjector:
    """Seeded, thread-safe fault schedule shared by every instance of one
    ``faulty:*`` registration."""

    def __init__(self, rules: Sequence[FaultRule], seed: int = 0) -> None:
        self.rules = list(rules)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.calls = 0

    def next_fault(self, config) -> Optional[str]:
        """Advance the call counter and return the fault kind to inject on
        this call, or None."""
        with self._lock:
            idx = self.calls
            self.calls += 1
            for rule in self.rules:
                if rule.at_call is not None:
                    if rule.at_call == idx:
                        return rule.kind
                elif rule.while_tile_above is not None:
                    tile = dict(config.backend_options).get(
                        "tile", _DEFAULT_TILE
                    )
                    if isinstance(tile, int) and tile > rule.while_tile_above:
                        return rule.kind
                elif rule.prob is not None:
                    if self._rng.random() < rule.prob:
                        return rule.kind
                else:
                    return rule.kind
        return None


class FaultyBackend:
    """A :class:`~..backends.base.VerifierBackend` decorator that injects
    the schedule's fault before delegating to the wrapped backend."""

    def __init__(
        self,
        inner,
        injector: FaultInjector,
        *,
        hang_seconds: float = 0.25,
        sleep=time.sleep,
    ) -> None:
        self.inner = inner
        self.injector = injector
        self.hang_seconds = hang_seconds
        self._sleep = sleep
        self.name = f"faulty:{inner.name}"
        self.supports_label_relation = inner.supports_label_relation

    def _inject(self, config) -> None:
        kind = self.injector.next_fault(config)
        if kind is None:
            return
        FAULTS_INJECTED_TOTAL.labels(backend=self.name, kind=kind).inc()
        if kind == "oom":
            raise BackendOOM(
                "injected RESOURCE_EXHAUSTED: out of memory while "
                "allocating reach tiles",
                backend=self.name,
            )
        if kind == "device_loss":
            raise DeviceLost("injected device loss", backend=self.name)
        if kind == "flaky":
            raise BackendError(
                "injected flaky dispatch", backend=self.name,
                kind="flaky", transient=True,
            )
        # kind == "timeout": a simulated hang, not an exception — the
        # caller's watchdog is what should notice. Without a watchdog this
        # is just added latency.
        self._sleep(self.hang_seconds)

    def verify(self, cluster, config):
        self._inject(config)
        return self.inner.verify(cluster, config)

    def verify_kano(self, containers, policies, config):
        self._inject(config)
        return self.inner.verify_kano(containers, policies, config)


def register_faulty(
    inner_name: str,
    rules: Sequence[FaultRule],
    *,
    seed: int = 0,
    hang_seconds: float = 0.25,
) -> str:
    """Register ``faulty:<inner_name>`` wrapping the already-registered
    ``inner_name`` backend with a fresh :class:`FaultInjector`; returns the
    new backend name. Re-registering replaces the previous schedule."""
    from ..backends.base import get_backend, register_backend

    get_backend(inner_name)  # fail fast on unknown inner backends
    for rule in rules:
        if rule.kind in KILL_POINTS:
            raise ConfigError(
                f"kill-point {rule.kind!r} is a process crash, not a "
                "backend fault — arm it with install_kill_points()"
            )
        if rule.kind in NET_FAULT_KINDS:
            raise ConfigError(
                f"network fault {rule.kind!r} fires at the replication-"
                "transport seam, not in a backend — arm it with "
                "install_net_faults()"
            )
        if rule.kind in INGRESS_FAULT_KINDS:
            raise ConfigError(
                f"ingress fault {rule.kind!r} fires at the front-door "
                "ingress seam, not in a backend — arm it with "
                "install_ingress_faults()"
            )
    injector = FaultInjector(rules, seed=seed)
    name = f"faulty:{inner_name}"
    register_backend(
        name,
        lambda: FaultyBackend(
            get_backend(inner_name), injector, hang_seconds=hang_seconds
        ),
    )
    return name


# ------------------------------------------------------------ kill points
class KillPointInjector:
    """Seeded, per-point-counting crash schedule: ``should_kill(name)``
    advances that point's hit counter and answers whether this hit is the
    one that dies (``KIND@N`` = hit index N, ``KIND%P`` = probability P
    per hit, bare ``KIND`` = every hit)."""

    def __init__(
        self,
        rules: Sequence[FaultRule],
        *,
        seed: int = 0,
        exit_code: int = 137,  # what a shell reports for SIGKILL
    ) -> None:
        self.rules = [r for r in rules if r.kind in KILL_POINTS]
        if not self.rules:
            raise ConfigError(
                f"no kill-point rules in {list(rules)!r}; known points: "
                f"{KILL_POINTS}"
            )
        self.exit_code = exit_code
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.hits: Dict[str, int] = {}

    def should_kill(self, point: str) -> bool:
        with self._lock:
            idx = self.hits.get(point, 0)
            self.hits[point] = idx + 1
            for rule in self.rules:
                if rule.kind != point:
                    continue
                if rule.at_call is not None:
                    if rule.at_call == idx:
                        return True
                elif rule.prob is not None:
                    if self._rng.random() < rule.prob:
                        return True
                else:
                    return True
        return False


#: the process-wide armed schedule (None = every kill_point() is a no-op)
_KILL_INJECTOR: Optional[KillPointInjector] = None


def install_kill_points(
    rules: Sequence[FaultRule], *, seed: int = 0, exit_code: int = 137
) -> KillPointInjector:
    """Arm the durability kill-points process-wide (rules typically come
    from ``parse_fault_spec("mid-log-append@7")``); returns the injector
    so a harness can inspect hit counters before the crash."""
    global _KILL_INJECTOR
    # kvtpu: ignore[concurrency-hygiene] armed by the fuzz harness before any worker thread starts; arm/disarm is single-threaded
    _KILL_INJECTOR = KillPointInjector(rules, seed=seed, exit_code=exit_code)
    return _KILL_INJECTOR


def clear_kill_points() -> None:
    """Disarm every kill-point (tests; the child process never needs to)."""
    global _KILL_INJECTOR
    _KILL_INJECTOR = None  # kvtpu: ignore[concurrency-hygiene] disarm happens on the harness thread after workers join


def kill_point(name: str, flush=None) -> None:
    """A named crash site. No-op unless armed via
    :func:`install_kill_points`; when the armed schedule fires, ``flush``
    (a file object, if given) is flushed so partially written bytes reach
    the OS — a torn tail, not an empty one — and the process dies with
    ``os._exit`` (bypassing ``finally``/``atexit``, like SIGKILL would).
    """
    inj = _KILL_INJECTOR
    if inj is None:
        return
    if inj.should_kill(name):
        FAULTS_INJECTED_TOTAL.labels(backend="durability", kind=name).inc()
        if flush is not None:
            flush.flush()
        try:
            # last act before the un-catchable exit: flush the flight
            # recorder ring so the post-mortem survives the "SIGKILL"
            from ..observe.flight import trigger_dump

            trigger_dump("kill-point", point=name)
        except Exception:
            pass  # dying is the contract; a failed dump must not block it
        os._exit(inj.exit_code)


# ---------------------------------------------------------- network faults
class NetFaultInjector:
    """Seeded, request-counting network fault schedule for the transport
    seam. One counter spans every wire operation (``tip``/``wal``/
    ``manifest``/``file``) so ``net-drop@3`` means "the 4th request this
    process makes fails", whatever it was for. ``net-partition`` *latches*:
    once its rule fires, every subsequent request fails until
    :meth:`heal` — the two-sided silence of a real partition, not a
    one-shot error."""

    def __init__(
        self,
        rules: Sequence[FaultRule],
        *,
        seed: int = 0,
        delay_seconds: float = 0.05,
        sleep=time.sleep,
    ) -> None:
        self.rules = [r for r in rules if r.kind in NET_FAULT_KINDS]
        if not self.rules:
            raise ConfigError(
                f"no network fault rules in {list(rules)!r}; known kinds: "
                f"{NET_FAULT_KINDS}"
            )
        self.delay_seconds = delay_seconds
        self._sleep = sleep
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.calls = 0
        self.partitioned = False
        self.injected: Dict[str, int] = {}

    def next_fault(self) -> Optional[str]:
        """Advance the request counter and return the fault kind to inject
        on this request, or None."""
        with self._lock:
            idx = self.calls
            self.calls += 1
            if self.partitioned:
                self.injected["net-partition"] = (
                    self.injected.get("net-partition", 0) + 1
                )
                return "net-partition"
            for rule in self.rules:
                if rule.at_call is not None:
                    fired = rule.at_call == idx
                elif rule.prob is not None:
                    fired = self._rng.random() < rule.prob
                else:
                    fired = True
                if fired:
                    if rule.kind == "net-partition":
                        self.partitioned = True
                    self.injected[rule.kind] = (
                        self.injected.get(rule.kind, 0) + 1
                    )
                    return rule.kind
        return None

    def heal(self) -> None:
        """End a latched partition; other rules keep their schedule."""
        with self._lock:
            self.partitioned = False


#: the process-wide armed schedule (None = every net_fault() is a no-op)
_NET_INJECTOR: Optional[NetFaultInjector] = None


def install_net_faults(
    rules: Sequence[FaultRule],
    *,
    seed: int = 0,
    delay_seconds: float = 0.05,
    sleep=time.sleep,
) -> NetFaultInjector:
    """Arm the transport-seam network faults process-wide (rules typically
    come from ``parse_fault_spec("net-drop@2,net-delay%0.1")``); returns
    the injector so a harness can inspect counters and heal partitions."""
    global _NET_INJECTOR
    # kvtpu: ignore[concurrency-hygiene] armed by the chaos harness before any transport client issues requests; arm/disarm is single-threaded
    _NET_INJECTOR = NetFaultInjector(
        rules, seed=seed, delay_seconds=delay_seconds, sleep=sleep
    )
    return _NET_INJECTOR


def clear_net_faults() -> None:
    """Disarm every network fault (tests; also ends a latched partition)."""
    global _NET_INJECTOR
    _NET_INJECTOR = None  # kvtpu: ignore[concurrency-hygiene] disarm happens on the harness thread after the scenario finishes


def heal_net_partition() -> None:
    """Heal the armed injector's latched partition, keeping its other
    rules scheduled — the partition-then-heal chaos move."""
    inj = _NET_INJECTOR
    if inj is not None:
        inj.heal()


def net_fault(op: str) -> None:
    """The transport seam. :class:`~.serve.transport.ReplicationClient`
    calls this before every wire request; a firing ``net-delay`` sleeps
    ``delay_seconds`` and lets the request proceed, ``net-drop`` and
    ``net-partition`` raise :class:`ReplicationError` as if the connection
    died. No-op unless armed via :func:`install_net_faults`."""
    inj = _NET_INJECTOR
    if inj is None:
        return
    kind = inj.next_fault()
    if kind is None:
        return
    NET_FAULTS_INJECTED_TOTAL.labels(kind=kind, op=op).inc()
    if kind == "net-delay":
        inj._sleep(inj.delay_seconds)
        return
    raise ReplicationError(f"injected {kind} on {op!r} request", op=op)


# ---------------------------------------------------------- ingress faults
class IngressFaultInjector:
    """Seeded, submission-counting client-behaviour fault schedule for the
    front-door seam. One counter spans every client submission, so
    ``client-burst@3`` means "the 4th submission this process sees arrives
    as a burst". ``client-burst`` amplifies one submission into
    ``burst_factor`` arrivals (an arrival-rate spike the admission
    controller and bounded queue must absorb or shed); ``slow-client``
    stalls the submission ``stall_seconds`` before it reaches admission —
    a request body trickling in, which eats the request's own deadline
    budget, not the batcher's."""

    def __init__(
        self,
        rules: Sequence[FaultRule],
        *,
        seed: int = 0,
        burst_factor: int = 8,
        stall_seconds: float = 0.05,
        sleep=time.sleep,
    ) -> None:
        self.rules = [r for r in rules if r.kind in INGRESS_FAULT_KINDS]
        if not self.rules:
            raise ConfigError(
                f"no ingress fault rules in {list(rules)!r}; known kinds: "
                f"{INGRESS_FAULT_KINDS}"
            )
        if burst_factor < 1:
            raise ConfigError(
                f"burst_factor must be >= 1, got {burst_factor}"
            )
        self.burst_factor = int(burst_factor)
        self.stall_seconds = float(stall_seconds)
        self._sleep = sleep
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.calls = 0
        self.injected: Dict[str, int] = {}

    def next_fault(self) -> Optional[str]:
        """Advance the submission counter and return the fault kind to
        inject on this submission, or None."""
        with self._lock:
            idx = self.calls
            self.calls += 1
            for rule in self.rules:
                if rule.at_call is not None:
                    fired = rule.at_call == idx
                elif rule.prob is not None:
                    fired = self._rng.random() < rule.prob
                else:
                    fired = True
                if fired:
                    self.injected[rule.kind] = (
                        self.injected.get(rule.kind, 0) + 1
                    )
                    return rule.kind
        return None


#: the process-wide armed schedule (None = every ingress_fault() is a no-op)
_INGRESS_INJECTOR: Optional[IngressFaultInjector] = None


def install_ingress_faults(
    rules: Sequence[FaultRule],
    *,
    seed: int = 0,
    burst_factor: int = 8,
    stall_seconds: float = 0.05,
    sleep=time.sleep,
) -> IngressFaultInjector:
    """Arm the front-door client faults process-wide (rules typically come
    from ``parse_fault_spec("client-burst@2,slow-client%0.1")``); returns
    the injector so a harness can inspect counters."""
    global _INGRESS_INJECTOR
    # kvtpu: ignore[concurrency-hygiene] armed by the chaos harness before any client submits; arm/disarm is single-threaded
    _INGRESS_INJECTOR = IngressFaultInjector(
        rules, seed=seed, burst_factor=burst_factor,
        stall_seconds=stall_seconds, sleep=sleep,
    )
    return _INGRESS_INJECTOR


def clear_ingress_faults() -> None:
    """Disarm every ingress fault (tests)."""
    global _INGRESS_INJECTOR
    _INGRESS_INJECTOR = None  # kvtpu: ignore[concurrency-hygiene] disarm happens on the harness thread after the scenario finishes


def ingress_fault() -> int:
    """The front-door seam. The ingress tier calls this once per client
    submission, *before* admission; returns the arrival amplification
    factor (1 = no fault). A firing ``client-burst`` returns
    ``burst_factor`` — the submission counts as that many arrivals, so
    quota, queue slots and batch pressure all see the spike. A firing
    ``slow-client`` sleeps ``stall_seconds`` (the stalled request body)
    and returns 1 — the stall burns the request's own deadline budget
    while the batcher keeps serving everyone else. No-op unless armed via
    :func:`install_ingress_faults`."""
    inj = _INGRESS_INJECTOR
    if inj is None:
        return 1
    kind = inj.next_fault()
    if kind is None:
        return 1
    INGRESS_FAULTS_INJECTED_TOTAL.labels(kind=kind).inc()
    if kind == "slow-client":
        inj._sleep(inj.stall_seconds)
        return 1
    return inj.burst_factor
