"""``kv-tpu`` — command-line front end.

The reference has no CLI at all (both verifiers are driven by unit tests
only, SURVEY.md §1); this exposes the full pipeline:

* ``kv-tpu verify PATH``   — load manifests, verify, print queries/summary;
* ``kv-tpu snapshot PATH DIR`` — build a packed incremental verifier from
  manifests and checkpoint it (the serving loop's "cold start");
* ``kv-tpu diff DIR``      — load a checkpoint, apply pod/policy diffs from
  YAML manifests (and ``--remove`` forms), print the changed aggregates,
  save — the checkpoint → diff → patch → save serving cycle the
  incremental engines implement (BASELINE config 5's operational story);
* ``kv-tpu explain PATH``  — export the encoded tensors + the Datalog
  program text (the ``get_datalog`` facility, ``kubesv/kubesv/
  constraint.py:127-128``, for both representations);
* ``kv-tpu generate DIR``  — write a synthetic cluster as YAML manifests
  (``--events-out`` adds a churn event stream);
* ``kv-tpu serve``         — continuous verification: apply a mutation-event
  stream through the coalescing service loop, check declarative
  assertions (violations exit 1 with pod-pair witnesses);
* ``kv-tpu query``         — can-reach / who-can-reach / blast-radius /
  what-if admission checks against manifests or a serve snapshot;
* ``kv-tpu lb``            — spread query batches across follower replicas
  by staleness-weighted routing (stale reads retry on the leader,
  unreachable replicas are breaker-ejected);
* ``kv-tpu recover``       — read-only triage of a serve checkpoint
  directory (generation health, WAL valid prefix, flight-recorder dumps);
* ``kv-tpu trace ID``      — reassemble one trace's cross-process timeline
  from per-replica JSON event logs (span tree + query stage breakdown);
  ``--slowest --metrics URL`` picks the id from the worst latency exemplar;
* ``kv-tpu fleet``         — scrape every replica's ``/healthz`` +
  ``/metrics``, render the fleet table, evaluate SLO burn rates;
* ``kv-tpu jobs``          — merge every replica's in-flight long-job
  progress (pass counters, rates, ETAs) into one table;
* ``kv-tpu profile``       — trigger a bounded on-demand ``jax.profiler``
  capture on a running replica (or locally), rate-limited;
* ``kv-tpu top``           — live fleet dashboard: replica table, job ETA
  bars, qps/lag/burn sparklines, recent flight dumps;
* ``kv-tpu backends``      — list available execution backends.
"""
from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
from typing import Optional


def _add_obs_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--metrics-out", metavar="FILE",
        help="write the metrics registry dump on exit (.json; .prom/.txt "
        "for Prometheus text exposition)",
    )
    p.add_argument(
        "--profile", metavar="DIR",
        help="capture a jax.profiler device trace into DIR "
        "(view with TensorBoard's profile plugin)",
    )
    p.add_argument(
        "--log-json", action="store_true",
        help="emit one JSON event line per span/phase on stderr",
    )
    p.add_argument(
        "--flight", metavar="DIR",
        help="arm the flight recorder: keep a bounded in-memory ring of "
        "recent spans/events/metric deltas and dump it to "
        "DIR/flight-<ts>.json on error escalation, breaker-open, "
        "kill-points and SIGUSR2 (render dumps with `kv-tpu recover DIR`)",
    )


@contextlib.contextmanager
def _observed(args):
    """Honour the shared observability flags around a command body."""
    from .observe import configure_logging, profile_to, write_metrics
    from .observe import flight as _flight

    if getattr(args, "log_json", False):
        configure_logging()
    flight_dir = getattr(args, "flight", None)
    if flight_dir:
        _flight.install(flight_dir)
    else:
        _flight.install_from_env()
    profile_dir = getattr(args, "profile", None)
    ctx = profile_to(profile_dir) if profile_dir else contextlib.nullcontext()
    try:
        with ctx:
            yield
    finally:
        # written even when the command raises: a failed solve's partial
        # spans/counters are exactly what a post-mortem wants
        out = getattr(args, "metrics_out", None)
        if out:
            write_metrics(out)


def _add_verify_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--backend", default="cpu")
    p.add_argument("--closure", action="store_true")
    p.add_argument("--no-ports", dest="ports", action="store_false")
    p.add_argument("--no-self-traffic", dest="self_traffic", action="store_false")
    p.add_argument(
        "--no-default-allow", dest="default_allow", action="store_false",
        help="reproduce the reference's unselected-pods-unreachable behaviour",
    )
    p.add_argument("--kano", action="store_true", help="kano-level semantics")
    p.add_argument("--output", help="save the VerifyResult as .npz")
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument(
        "--opt", action="append", default=[], metavar="KEY=VALUE",
        help="backend option (repeatable), e.g. --opt mesh=4,2 "
        "--opt tile=512 --opt keep_matrix=true for sharded-packed",
    )
    p.add_argument(
        "--fallback-chain", metavar="B1,B2,...",
        help="ordered backends to try (e.g. tpu,sharded,cpu); supersedes "
        "--backend — exit 3 when the whole chain fails",
    )
    p.add_argument(
        "--max-retries", type=int, default=None, metavar="N",
        help="transient-failure retries per backend before falling back "
        "(default 2 when the resilient path is active)",
    )
    p.add_argument(
        "--solve-timeout", type=float, default=None, metavar="SECONDS",
        help="watchdog wall-clock bound per solve attempt",
    )
    p.add_argument(
        "--inject-faults", action="append", default=[],
        metavar="BACKEND=SPEC",
        help="register a fault-injecting wrapper backend 'faulty:BACKEND' "
        "(repeatable); SPEC e.g. oom@0, timeout, device_loss, flaky@0, "
        "oom>256 — see resilience.faults.parse_fault_spec",
    )
    p.add_argument(
        "--check", action="store_true",
        help="exit 1 when policy shadow/conflict pairs are found",
    )


#: options whose values must be integers (string fallthrough would surface
#: as a confusing type error deep in the backend, after the solve)
_INT_OPTS = frozenset(
    {"tile", "chunk", "dense_reach_limit", "max_port_masks", "closure_tile"}
)


def _parse_opt(kv_str: str):
    key, sep, raw = kv_str.partition("=")
    if not sep or not key:
        raise SystemExit(f"--opt expects KEY=VALUE, got {kv_str!r}")
    low = raw.lower()
    if low in ("true", "false"):
        return key, low == "true"
    if "," in raw:
        try:
            return key, tuple(int(x) for x in raw.split(","))
        except ValueError:
            raise SystemExit(
                f"--opt {key}: comma lists must be integers, got {raw!r}"
            )
    try:
        return key, int(raw)
    except ValueError:
        if key in _INT_OPTS:
            # numeric option but not an int (2e4, 1.5) — fail at parse time
            # instead of as a type error deep in the backend post-solve
            raise SystemExit(
                f"--opt {key}: expected an integer, got {raw!r}"
            )
        return key, raw  # string-valued options (e.g. groups_label=3tier)


def _diagnose(args, e: Exception) -> int:
    """The ``KvTpuError`` → exit-code contract: one line on stderr (the
    operator path) unless ``--log-json`` asked for the debugging traceback."""
    from .observe.flight import trigger_dump
    from .resilience.errors import exit_code_for

    # a typed error escalating out of a command is a flight-recorder
    # trigger: the ring holds the spans/events that led here
    path = trigger_dump("error", error=f"{type(e).__name__}: {e}")
    if path:
        print(f"kv-tpu: flight recorder dumped to {path}", file=sys.stderr)
    if getattr(args, "log_json", False):
        raise e
    print(f"kv-tpu: {type(e).__name__}: {e}", file=sys.stderr)
    return exit_code_for(e)


def cmd_verify(args) -> int:
    from .resilience.errors import KvTpuError

    try:
        with _observed(args):
            return _run_verify(args)
    except KvTpuError as e:
        return _diagnose(args, e)


def _resilience_from_args(args):
    """``--fallback-chain``/``--max-retries``/``--solve-timeout`` →
    :class:`~.resilience.ResilienceConfig`, or None when none were given
    (the plain dispatcher path — identical behaviour to pre-resilience)."""
    chain = tuple(
        b.strip()
        for b in (args.fallback_chain or "").split(",")
        if b.strip()
    )
    if not chain and args.solve_timeout is None and args.max_retries is None:
        return None
    from .resilience import ResilienceConfig

    return ResilienceConfig(
        fallback_chain=chain,
        max_retries=2 if args.max_retries is None else args.max_retries,
        solve_timeout=args.solve_timeout,
    )


def _register_faults(args) -> None:
    for spec in getattr(args, "inject_faults", []):
        backend, sep, fault_spec = spec.partition("=")
        if not sep or not backend or not fault_spec:
            raise SystemExit(
                f"--inject-faults expects BACKEND=SPEC, got {spec!r}"
            )
        from .resilience.faults import parse_fault_spec, register_faulty

        register_faulty(backend, parse_fault_spec(fault_spec))


def _run_verify(args) -> int:
    import kubernetes_verification_tpu as kv

    from .resilience.errors import EXIT_OK, EXIT_VIOLATIONS

    _register_faults(args)
    resilience = _resilience_from_args(args)
    cfg = kv.VerifyConfig(
        backend=args.backend,
        closure=args.closure,
        compute_ports=args.ports,
        self_traffic=args.self_traffic,
        default_allow_unselected=args.default_allow,
        backend_options=tuple(_parse_opt(o) for o in args.opt),
    )
    if args.kano:
        containers, policies = kv.load_kano(args.path)
        if resilience is not None:
            from .resilience import resilient_verify_kano

            res = resilient_verify_kano(containers, policies, cfg, resilience)
        else:
            res = kv.verify_kano(containers, policies, cfg)
        pods = containers
        skipped = []
    else:
        cluster, skipped = kv.load_cluster(args.path)
        if (
            args.output
            and cfg.backend == "sharded-packed"
            and cluster.n_pods > cfg.opt("dense_reach_limit", 20_000)
        ):
            # fail BEFORE the (potentially hours-long) solve: --output saves
            # a dense VerifyResult, which this scale never materialises
            raise SystemExit(
                f"--output saves a dense VerifyResult but {cluster.n_pods} "
                "pods exceeds dense_reach_limit "
                f"({cfg.opt('dense_reach_limit', 20_000)}); raise --opt "
                "dense_reach_limit=N or drop --output"
            )
        if resilience is not None:
            from .resilience import resilient_verify

            res = resilient_verify(cluster, cfg, resilience)
        else:
            res = kv.verify(cluster, cfg)
        pods = cluster.pods
    iso = res.all_isolated()
    hubs = res.all_reachable()
    if res.reach is not None:
        pairs = int(res.reach.sum())
    else:  # sharded-packed above the dense-reach limit: use the aggregates
        pairs = int(res.packed_result.total_pairs)
    out = {
        "pods": res.n_pods,
        "backend": res.backend,
        "mode": res.mode,
        "reachable_pairs": pairs,
        "all_isolated": iso,
        "all_reachable": hubs,
        "policy_shadow": (
            res.policy_shadow() if res.src_sets is not None else None
        ),
        "policy_conflict": (
            res.policy_conflict() if res.src_sets is not None else None
        ),
        "timings": res.timings,
        "skipped_documents": skipped,
    }
    if args.output:
        if res.reach is None:  # safety net; print the summary before exiting
            print(json.dumps(out))
            raise SystemExit(
                "--output saves a dense VerifyResult; this solve kept only "
                "the packed matrix/aggregates (raise --opt "
                "dense_reach_limit=N or use save_packed on packed_result)"
            )
        from .utils.persist import save_result

        save_result(res, args.output)
        out["saved"] = args.output
    violations = bool(out["policy_shadow"]) or bool(out["policy_conflict"])
    if args.check:
        out["check"] = "failed" if violations else "passed"
    if args.json:
        print(json.dumps(out))
    else:
        name = lambda i: getattr(pods[i], "name", str(i))
        print(f"{res.n_pods} pods verified on backend={res.backend} "
              f"({res.mode} mode): {out['reachable_pairs']} reachable pairs")
        print(f"  fully isolated pods: {[name(i) for i in iso] or 'none'}")
        print(f"  reachable-from-everywhere pods: {[name(i) for i in hubs] or 'none'}")
        if out["policy_shadow"]:
            print(f"  shadowed policy pairs: {out['policy_shadow']}")
        if out["policy_conflict"]:
            print(f"  conflicting policy pairs: {out['policy_conflict']}")
        for k, v in res.timings.items():
            print(f"  {k}: {v * 1e3:.1f} ms")
        if skipped:
            print(f"  skipped {len(skipped)} non-verifiable documents")
        if args.check and violations:
            print("  check: FAILED (shadowed/conflicting policies present)")
    if args.check and violations:
        return EXIT_VIOLATIONS
    return EXIT_OK


def _mesh_from_opts(opts: dict):
    if "mesh" not in opts:
        return None
    from .parallel.mesh import mesh_for

    return mesh_for(opts["mesh"])


def _load_incremental(directory: str, mesh=None):
    """Open either packed-engine checkpoint; the ports checkpoint is the one
    carrying a frozen-universe ``__meta__`` blob."""
    import os

    from .utils.persist import (
        _load_npz,
        load_packed_incremental,
        load_ports_incremental,
    )

    with _load_npz(os.path.join(directory, "state.npz")) as z:
        is_ports = "__meta__" in z.files
    if is_ports:
        return load_ports_incremental(directory, mesh=mesh)
    return load_packed_incremental(directory, mesh=mesh)


def _inc_aggregates(inc) -> dict:
    import numpy as np

    out = {
        "pods": int(inc.n_active),
        "policies": len(inc.policies),
        "update_count": int(inc.update_count),
    }
    try:
        pr = inc.packed_reach()
    except ValueError:  # matrix-free checkpoint: aggregates need a sweep
        out["reachable_pairs"] = None
        return out
    out["reachable_pairs"] = int(pr.out_degree().sum())
    act = inc.pod_active
    out["ingress_isolated"] = int(np.count_nonzero(pr.ingress_isolated[act]))
    out["egress_isolated"] = int(np.count_nonzero(pr.egress_isolated[act]))
    return out


def cmd_snapshot(args) -> int:
    from .resilience.errors import KvTpuError

    try:
        return _run_snapshot(args)
    except KvTpuError as e:
        return _diagnose(args, e)


def _run_snapshot(args) -> int:
    import kubernetes_verification_tpu as kv

    from .packed_incremental import PackedIncrementalVerifier
    from .packed_incremental_ports import PackedPortsIncrementalVerifier
    from .utils.persist import (
        save_packed_incremental,
        save_ports_incremental,
    )

    opts = dict(_parse_opt(o) for o in args.opt)
    mesh = _mesh_from_opts(opts)
    cluster, skipped = kv.load_cluster(args.path)
    cfg = kv.VerifyConfig(
        compute_ports=args.ports,
        self_traffic=args.self_traffic,
        default_allow_unselected=args.default_allow,
    )
    if args.ports:
        inc = PackedPortsIncrementalVerifier(
            cluster, cfg, mesh=mesh,
            headroom=args.headroom, pod_headroom=args.pod_headroom,
        )
    else:
        inc = PackedIncrementalVerifier(
            cluster, cfg, mesh=mesh, pod_headroom=args.pod_headroom,
        )
    closure_s = None
    if args.closure:
        import time as _time

        s = _time.perf_counter()
        c = inc.closure_packed(tile=int(opts.get("closure_tile", 7168)))
        import jax

        jax.block_until_ready(c)
        closure_s = round(_time.perf_counter() - s, 3)
    if args.ports:
        save_ports_incremental(inc, args.dir)
    else:
        save_packed_incremental(inc, args.dir)
    agg = _inc_aggregates(inc)
    agg["engine"] = "ports" if args.ports else "any-port"
    agg["init_s"] = round(inc.init_time, 3)
    if closure_s is not None:
        agg["closure_s"] = closure_s
    agg["saved"] = args.dir
    if skipped:
        agg["skipped_documents"] = skipped
    print(json.dumps(agg) if args.json else (
        f"{agg['pods']} pods / {agg['policies']} policies → "
        f"{agg['engine']} incremental state in {agg['init_s']}s "
        f"({agg['reachable_pairs']} reachable pairs); saved to {args.dir}"
    ))
    return 0


def cmd_diff(args) -> int:
    from .resilience.errors import KvTpuError

    try:
        with _observed(args):
            return _run_diff(args)
    except KvTpuError as e:
        return _diagnose(args, e)


def _run_diff(args) -> int:
    import time

    import kubernetes_verification_tpu as kv

    opts = dict(_parse_opt(o) for o in args.opt)
    t0 = time.perf_counter()
    inc = _load_incremental(args.dir, mesh=_mesh_from_opts(opts))
    t1 = time.perf_counter()
    from .packed_incremental_ports import PortUniverseChanged

    before = _inc_aggregates(inc)
    # closure presence is decided at LOAD time: a pod-axis grow during the
    # diffs invalidates the cached closure (shape change), and the
    # maintenance below must then recompute it in full rather than silently
    # dropping it from the checkpoint
    had_closure = getattr(inc, "_closure", None) is not None
    ops = []
    skipped_docs = []
    try:
        _apply_diffs(args, inc, ops, skipped_docs)
    except PortUniverseChanged as e:
        # engine diffs are atomic and nothing is saved on this path, so the
        # on-disk checkpoint is untouched
        raise SystemExit(
            f"diff outside the checkpoint's frozen port universe after "
            f"{len(ops)} applied ops (not saved): {e}\n"
            f"rebuild with: kv-tpu snapshot MANIFESTS {args.dir}"
        )
    except KeyError as e:
        raise SystemExit(
            f"diff references an unknown pod/policy/namespace after "
            f"{len(ops)} applied ops (not saved): {e}"
        )
    # any other ValueError is an internal invariant violation — let it
    # propagate with its traceback instead of masquerading as an operator
    # "rebuild required" message (advisor, round 4)
    closure_s = None
    if had_closure and not args.no_save:
        # the snapshot carries a maintained closure: bring it current via
        # the delta re-closure (diff-local; the engines marked the dirty
        # nodes as the diffs applied) so the saved state stays
        # query-ready for path questions across restarts. --no-save is a
        # dry run: don't pay for a closure that would be discarded.
        import jax

        s = time.perf_counter()
        jax.block_until_ready(
            inc.closure_packed(tile=int(opts.get("closure_tile", 7168)))
        )
        closure_s = round(time.perf_counter() - s, 3)
    t2 = time.perf_counter()
    after = _inc_aggregates(inc)
    out_dir = args.out or args.dir
    if not args.no_save:
        from .packed_incremental_ports import PackedPortsIncrementalVerifier
        from .utils.persist import (
            save_packed_incremental,
            save_ports_incremental,
        )

        if isinstance(inc, PackedPortsIncrementalVerifier):
            save_ports_incremental(inc, out_dir)
        else:
            save_packed_incremental(inc, out_dir)
    summary = {
        "ops": ops,
        "before": before,
        "after": after,
        "pairs_delta": (
            after["reachable_pairs"] - before["reachable_pairs"]
            if before.get("reachable_pairs") is not None
            and after.get("reachable_pairs") is not None
            else None
        ),
        "load_s": round(t1 - t0, 3),
        "diff_s": round(t2 - t1, 3),
        "saved": None if args.no_save else out_dir,
    }
    if closure_s is not None:
        summary["closure_s"] = closure_s
    if skipped_docs:
        summary["skipped_documents"] = skipped_docs
    if args.json:
        print(json.dumps(summary))
    else:
        for kind, key in ops:
            print(f"  {kind} {key}")
        print(
            f"{len(ops)} diffs in {summary['diff_s']}s: "
            f"{before['reachable_pairs']} → {after['reachable_pairs']} "
            f"reachable pairs ({summary['pairs_delta']:+d})"
            if summary["pairs_delta"] is not None
            else f"{len(ops)} diffs in {summary['diff_s']}s (matrix-free)"
        )
        if summary["saved"]:
            print(f"saved to {summary['saved']}")
    return 0


def _apply_diffs(args, inc, ops, skipped_docs) -> None:
    import kubernetes_verification_tpu as kv

    for path in args.apply:
        delta, skipped = kv.load_cluster(path)
        skipped_docs += skipped
        for ns in delta.namespaces:
            # labeled Namespace docs must register BEFORE their pods so
            # namespaceSelector peers see the labels; label-less entries are
            # indistinguishable from the loader's auto-created ones and are
            # left to add_pod's auto-create (which also means a relabel TO
            # empty labels cannot be expressed through a manifest — only a
            # LABELED row is treated as authoritative)
            if not ns.labels:
                continue
            existing = inc._ns_labels.get(ns.name)
            if existing is None:
                if inc.add_namespace(ns):
                    ops.append(["add-namespace", ns.name])
            elif dict(existing) != dict(ns.labels):
                inc.update_namespace_labels(ns.name, dict(ns.labels))
                ops.append(["relabel-namespace", ns.name])
        for pod in delta.pods:
            key = f"{pod.namespace}/{pod.name}"
            if key in inc._pod_idx:
                old = inc.pods[inc._pod_idx[key]]
                if (
                    dict(pod.container_ports) != dict(old.container_ports)
                    or pod.ip != old.ip
                ):
                    # ports/ip moved: full slot recycle (labels-only diffs
                    # patch in place)
                    inc.remove_pod(pod.namespace, pod.name)
                    inc.add_pod(pod)
                    ops.append(["replace-pod", key])
                elif dict(pod.labels) != dict(old.labels):
                    inc.update_pod_labels(
                        inc._pod_idx[key], dict(pod.labels)
                    )
                    ops.append(["relabel-pod", key])
                # unchanged manifest: no dispatch — apply-style full-manifest
                # reconciles must cost only the comparison
            else:
                inc.add_pod(pod)
                ops.append(["add-pod", key])
        for pol in delta.policies:
            key = f"{pol.namespace}/{pol.name}"
            if key in inc.policies:
                if pol != inc.policies[key]:
                    inc.update_policy(pol)
                    ops.append(["update-policy", key])
            else:
                inc.add_policy(pol)
                ops.append(["add-policy", key])
    for spec in args.remove:
        kind, _, rest = spec.partition("/")
        if kind == "namespace":
            if not rest or "/" in rest:
                raise SystemExit(
                    f"--remove expects namespace/NAME, got {spec!r}"
                )
            try:
                inc.remove_namespace(rest)
            except ValueError as e:
                # op-ordering error (pods/policies still inside) — a clean
                # operator message, not a traceback; list removals for the
                # namespace's contents FIRST
                raise SystemExit(f"cannot remove namespace {rest}: {e}")
            ops.append(["remove-namespace", rest])
            continue
        ns, sep, name = rest.partition("/")
        if kind not in ("pod", "policy") or not sep:
            raise SystemExit(
                f"--remove expects pod/NAMESPACE/NAME, "
                f"policy/NAMESPACE/NAME or namespace/NAME, got {spec!r}"
            )
        if kind == "pod":
            inc.remove_pod(ns, name)
        else:
            inc.remove_policy(ns, name)
        ops.append([f"remove-{kind}", f"{ns}/{name}"])


def cmd_explain(args) -> int:
    # three modes share the verb: the roofline report over the recorded
    # bench history (--roofline), per-kernel cost/memory introspection
    # when a cluster size or backend is given, and the legacy
    # encoding+Datalog export when only a manifest PATH is
    if getattr(args, "roofline", False):
        return _explain_roofline(args)
    if args.pods is not None or args.backend is not None:
        return _explain_cost(args)
    if not args.path:
        raise SystemExit(
            "explain: give a manifest PATH (tensor/Datalog export) or "
            "--pods N [--backend B] (per-kernel cost/memory table)"
        )
    import kubernetes_verification_tpu as kv
    from .datalog import build_k8s_program
    from .encode.encoder import encode_cluster
    from .utils.persist import export_encoding

    cluster, _ = kv.load_cluster(args.path)
    txt = export_encoding(
        encode_cluster(cluster, compute_ports=args.ports), args.out
    )
    prog, _, _atoms = build_k8s_program(cluster, kv.VerifyConfig())
    dl = args.out + ".datalog"
    with open(dl, "w") as fh:  # kvtpu: ignore[atomic-write] program-text export next to the .npz, regenerated on demand
        fh.write(prog.dump() + "\n")
    print(open(txt).read().rstrip())
    print(f"wrote {args.out}.npz, {txt}, {dl}")
    return 0


def _explain_cost(args) -> int:
    """``kv-tpu explain --pods N --backend B``: run one verification with
    introspection enabled and print the per-kernel cost/memory table plus a
    device-memory snapshot. Designed to run under ``JAX_PLATFORMS=cpu`` —
    XLA's cost analysis of the lowered program is platform-independent
    enough to answer "which kernel dominates and is it memory-bound"."""
    import kubernetes_verification_tpu as kv
    from .observe import introspect, telemetry

    backend = args.backend or "cpu"
    introspect.set_introspection(True)
    telemetry.install_span_memory_hook()
    if args.path:
        cluster, _ = kv.load_cluster(args.path)
    else:
        from .harness.generate import GeneratorConfig, random_cluster

        cluster = random_cluster(
            GeneratorConfig(
                n_pods=args.pods or 64,
                n_policies=args.policies,
                n_namespaces=args.namespaces,
                seed=args.seed,
            )
        )
    config = kv.VerifyConfig(backend=backend, compute_ports=args.ports)
    result = kv.verify(cluster, config)
    mem = telemetry.sample_once()
    reports = introspect.reports()
    if args.json:
        print(
            json.dumps(
                {
                    "backend": backend,
                    "n_pods": result.n_pods,
                    "n_policies": len(cluster.policies),
                    "timings": {
                        k: round(v, 6) for k, v in result.timings.items()
                    },
                    "reports": [r.to_dict() for r in reports],
                    "memory": mem,
                },
                sort_keys=True,
            )
        )
        return 0
    print(
        f"# {backend} backend · {result.n_pods} pods / "
        f"{len(cluster.policies)} policies"
    )
    table = introspect.format_cost_table(reports)
    print(table if table else "(no kernels published cost reports)")
    print()
    print(telemetry.format_memory_table(mem))
    print()
    print(
        "timings: "
        + "  ".join(f"{k}={v:.4f}s" for k, v in sorted(result.timings.items()))
    )
    return 0


def _explain_roofline(args) -> int:
    """``kv-tpu explain --roofline``: achieved MACs/s as %% of device peak
    per recorded bench mode — published v5e/v5p/v4/v6e table when the
    record names a known device model, the record's own
    sentinel-calibrated matmul peak otherwise, analytic host estimate as
    the last resort."""
    from .observe.history import default_paths, load_runs
    from .observe.introspect import format_roofline_table, roofline_rows

    paths = [args.path] if args.path else default_paths()
    runs = load_runs(paths)
    rows = roofline_rows(runs)
    if args.json:
        print(json.dumps({"rows": rows}, sort_keys=True))
        return 0
    if not rows:
        print(
            "no history record carries MAC accounting yet — run bench.py "
            "(modes tiled/k8s/closure/stripe stamp `macs` + `steady_s`)"
        )
        return 0
    print(format_roofline_table(rows))
    return 0


def cmd_history(args) -> int:
    """``kv-tpu history``: show the bench-history trajectory — raw and
    dispatch-deflated values side by side, with each round's sentinel
    noise figure — and the regression gate's verdict over the expanded
    (deflation-aware) series."""
    from .observe.history import (
        check_regression,
        deflate_record,
        default_paths,
        expand_derived,
        format_findings,
        load_runs,
    )

    paths = args.paths or default_paths()
    runs = load_runs(paths)
    if args.json:
        ok, findings = check_regression(
            expand_derived(runs), tolerance=args.tolerance,
            window=args.window, prefer_deflated=True,
        )
        print(
            json.dumps(
                {"ok": ok, "runs": runs, "findings": findings}, sort_keys=True
            )
        )
        return 0 if ok else 1
    if not runs:
        print(
            "no bench history found (run bench.py to append to "
            "bench_history.jsonl)"
        )
        return 0
    for r in runs:
        extras = "".join(
            f"  {k}={r[k]}"
            for k in ("compile_s", "steady_s", "round")
            if r.get(k) is not None
        )
        twin = deflate_record(r)
        deflated = f"  deflated={twin['value']:.6g}" if twin else ""
        sentinel = r.get("sentinel")
        noise = (
            f"  sentinel_spread={sentinel['spread_pct']:g}%"
            if isinstance(sentinel, dict)
            and sentinel.get("spread_pct") is not None
            else ""
        )
        print(
            f"{r['metric']}: {r['value']:.6g} {r.get('unit', '')}"
            f"{deflated}{noise}{extras}"
        )
    ok, findings = check_regression(
        expand_derived(runs), tolerance=args.tolerance, window=args.window,
        prefer_deflated=True,
    )
    print()
    print(format_findings(findings))
    return 0 if ok else 1


def cmd_generate(args) -> int:
    from .resilience.errors import KvTpuError

    try:
        return _run_generate(args)
    except KvTpuError as e:
        return _diagnose(args, e)


def _run_generate(args) -> int:
    from .harness.generate import GeneratorConfig, random_cluster
    from .ingest import dump_cluster

    cluster = random_cluster(
        GeneratorConfig(
            n_pods=args.pods,
            n_policies=args.policies,
            n_namespaces=args.namespaces,
            seed=args.seed,
        )
    )
    paths = dump_cluster(cluster, args.dir)
    print(f"wrote {len(cluster.pods)} pods / {len(cluster.policies)} policies "
          f"to {', '.join(paths)}")
    if args.events_out:
        from .harness.generate import random_event_stream
        from .serve.events import write_events

        events = random_event_stream(
            cluster,
            n_events=args.n_events,
            seed=args.seed,
            p_resync=args.resync_rate,
        )
        write_events(events, args.events_out)
        print(
            f"wrote a {len(events)}-event churn stream to {args.events_out} "
            f"(replay with: kv-tpu serve {args.dir} "
            f"--events {args.events_out})"
        )
    return 0


def cmd_serve(args) -> int:
    from .resilience.errors import KvTpuError

    try:
        with _observed(args):
            return _run_serve(args)
    except KvTpuError as e:
        return _diagnose(args, e)


def _maybe_ride_warm_pack(args) -> None:
    """Install a warm executable pack before any engine is built: an
    explicit ``--warm-pack``, else the ``aot-pack`` auto-detected next to
    ``--from-snapshot`` (a checkpoint directory ships one beside its
    ``gen-N/`` snapshots). Fail-open — a bad pack is counted misses and
    warnings, never an error."""
    import os

    from .observe import aot

    if not aot.aot_enabled():
        return
    candidates = []
    if getattr(args, "warm_pack", None):
        candidates.append(args.warm_pack)
    snap = getattr(args, "from_snapshot", None)
    if snap:
        snap = os.path.abspath(snap)
        candidates.append(aot.pack_dir(snap))
        candidates.append(aot.pack_dir(os.path.dirname(snap)))
    for cand in candidates:
        if os.path.isdir(cand):
            aot.load_pack(cand)
            return


def _load_serve_service(args, serve_config):
    """Build the service from manifests (``path``) or a warm-restart
    snapshot (``--from-snapshot``)."""
    from .serve import VerificationService

    _maybe_ride_warm_pack(args)
    if getattr(args, "from_snapshot", None):
        return VerificationService.from_snapshot(
            args.from_snapshot, serve_config=serve_config
        ), []
    if not args.path:
        raise SystemExit("serve: give a manifest PATH or --from-snapshot DIR")
    import kubernetes_verification_tpu as kv

    cluster, skipped = kv.load_cluster(args.path)
    cfg = kv.VerifyConfig(
        backend="cpu",
        compute_ports=False,
        self_traffic=args.self_traffic,
        default_allow_unselected=args.default_allow,
    )
    return VerificationService(cluster, cfg, serve_config), skipped


def _resume_serve_service(args, serve_config):
    """Crash recovery: rebuild the service from the checkpoint ladder in
    ``--checkpoint-dir`` (replaying the event log past the recorded
    offset), degrading to a from-scratch build of ``path`` when every
    generation is damaged."""
    from .serve import RecoveryManager

    initial_cluster, cfg, skipped = None, None, []
    if args.path:
        import kubernetes_verification_tpu as kv

        initial_cluster, skipped = kv.load_cluster(args.path)
        cfg = kv.VerifyConfig(
            backend="cpu",
            compute_ports=False,
            self_traffic=args.self_traffic,
            default_allow_unselected=args.default_allow,
        )
    result = RecoveryManager(args.checkpoint_dir).recover(
        log_path=args.events,
        initial_cluster=initial_cluster,
        config=cfg,
        serve_config=serve_config,
        batch_size=args.batch_size,
    )
    return result.service, skipped, result.source, result


def _maybe_enable_posture(svc, args):
    """Enable the posture plane when any --posture* flag asked for it;
    returns the tracker (or None). Malformed alert rules are input
    errors, like malformed --slo specs."""
    journal = getattr(args, "posture_journal", None)
    alerts = getattr(args, "posture_alert", None) or []
    if not (getattr(args, "posture", False) or journal or alerts):
        return None
    from .serve import parse_posture_rule

    try:
        rules = [parse_posture_rule(s) for s in alerts]
    except ValueError as e:
        raise SystemExit(f"serve: {e}")
    return svc.enable_posture(
        journal_path=journal,
        rules=rules,
        top_k=getattr(args, "posture_top_k", None),
    )


def _run_serve(args) -> int:
    from .resilience.errors import (
        EXIT_OK,
        EXIT_VIOLATIONS,
        EXIT_INPUT_ERROR,
    )
    from .serve import EventSource, ServeConfig, load_assertions

    if getattr(args, "stripe", None):
        if getattr(args, "follow", None):
            raise SystemExit("serve: --stripe and --follow are exclusive")
        return _run_stripe(args)
    if getattr(args, "follow", None):
        return _run_follow(args)
    serve_config = ServeConfig(
        staleness_bound=args.staleness,
        batch_size=args.batch_size,
        snapshot_dir=args.snapshot_out,
        snapshot_every=args.snapshot_every,
    )
    recovery = None
    source = None
    if getattr(args, "resume", False):
        if not args.checkpoint_dir:
            raise SystemExit("serve: --resume requires --checkpoint-dir")
        svc, skipped, source, recovery = _resume_serve_service(
            args, serve_config
        )
    else:
        svc, skipped = _load_serve_service(args, serve_config)
    if source is None and args.events:
        source = EventSource(args.events)
    cm = None
    if getattr(args, "checkpoint_dir", None):
        from .serve import CheckpointManager

        cm = CheckpointManager(args.checkpoint_dir)
    if getattr(args, "assert_file", None):
        svc.assertions.extend(load_assertions(args.assert_file))
    posture = _maybe_enable_posture(svc, args)
    checkpoints = 0

    def _checkpoint() -> None:
        nonlocal checkpoints
        cm.checkpoint(
            svc.engine,
            log_path=args.events,
            log_offset=source.offset if source else 0,
            last_seq=source.last_seq if source else -1,
        )
        checkpoints += 1

    if cm is not None:
        # checkpointing drives the loop synchronously: the recorded
        # log offset must describe a quiesced engine, so the worker
        # thread (which applies at its own pace) stays off
        try:
            if source is not None and args.events:
                batch_iter = (
                    source.tail(
                        poll_interval=args.tail_poll,
                        idle_timeout=args.idle_timeout,
                        batch_size=args.batch_size,
                    )
                    if args.tail
                    else source.batches(args.batch_size)
                )
                batches_since = 0
                for batch in batch_iter:
                    svc.apply(batch)
                    batches_since += 1
                    if (
                        args.checkpoint_every
                        and batches_since >= args.checkpoint_every
                    ):
                        _checkpoint()
                        batches_since = 0
            reach = svc.reach(
                trigger="query" if not svc.assertions else "assertions"
            )
            pairs = int(reach.sum())
            _checkpoint()  # the exit checkpoint: resume loses nothing
        finally:
            svc.close(snapshot=bool(args.snapshot_out))
    else:
        svc.start()
        try:
            if source is not None and args.events:
                if args.tail:
                    for batch in source.tail(
                        poll_interval=args.tail_poll,
                        idle_timeout=args.idle_timeout,
                        batch_size=args.batch_size,
                    ):
                        svc.submit(batch)
                else:
                    for batch in source.batches(args.batch_size):
                        svc.submit(batch)
            svc.flush()
            # force a final solve so assertion-free runs still verify the
            # stream end-state, and print the answer-bearing summary
            reach = svc.reach(trigger="query" if not svc.assertions else "assertions")
            pairs = int(reach.sum())
        finally:
            svc.close(snapshot=bool(args.snapshot_out))
    out = {
        "pods": svc.n_pods,
        "policies": len(svc.engine.policies),
        "reachable_pairs": pairs,
        "assertions": len(svc.assertions),
        "violations": [v.describe() for v in svc.violations],
        **svc.stats.to_dict(),
    }
    if skipped:
        out["skipped_documents"] = skipped
    if posture is not None:
        out["posture"] = posture.health()
    if args.snapshot_out:
        out["snapshot"] = args.snapshot_out
    if cm is not None:
        out["checkpoints"] = checkpoints
        out["checkpoint_dir"] = args.checkpoint_dir
    if recovery is not None:
        out["recovery"] = {
            "outcome": recovery.outcome,
            "generation": recovery.generation,
            "replayed": recovery.replayed,
            "duplicates_skipped": recovery.duplicates_skipped,
            "rejected_generations": len(recovery.errors),
        }
    if args.json:
        print(json.dumps(out, sort_keys=True))
    else:
        print(
            f"{out['pods']} pods / {out['policies']} policies after "
            f"{out['events_seen']} events ({out['events_applied']} applied, "
            f"{out['events_coalesced']} coalesced away) in "
            f"{out['batches']} batches / {out['total_solves']} solves: "
            f"{pairs} reachable pairs"
        )
        for v in svc.violations:
            print(f"  VIOLATION: {v.describe()}")
        if posture is not None:
            ph = posture.health()
            print(
                f"  posture: {ph['reachable_pairs']} reachable pairs @ "
                f"gen {ph['generation']} "
                f"(+{ph['widened_last']}/-{ph['narrowed_last']} last, "
                f"{ph['violations']} alert violations)"
            )
        if args.snapshot_out:
            print(f"  snapshot: {args.snapshot_out}")
        if recovery is not None:
            print(
                f"  recovered: {recovery.outcome} (gen "
                f"{recovery.generation}, {recovery.replayed} events "
                f"replayed, {recovery.duplicates_skipped} duplicates "
                "skipped)"
            )
        if cm is not None:
            print(
                f"  checkpoints: {checkpoints} -> {args.checkpoint_dir}"
            )
    return EXIT_VIOLATIONS if svc.violations else EXIT_OK


def _run_stripe(args) -> int:
    """Stripe owner: own pod rows ``[lo, hi)`` of the count state only
    (``--stripe K/N``, 1-based), bootstrap from manifests or — with
    ``--resume`` — a stripe-sliced checkpoint ladder, then tail
    ``--events`` applying EVERY mutation (cross-stripe effects fan out by
    design; the ``fanout`` counter in the summary is the measured tax).
    ``--checkpoint-dir`` writes stripe-sliced generations the same way
    whole-state serve writes whole ones."""
    import random as _random
    import time as _time
    import zlib as _zlib

    from .parallel.stripes import parse_stripe
    from .resilience.errors import EXIT_OK
    from .serve import CheckpointManager, RecoveryManager
    from .serve.stripes import StripeFollower

    stripe = parse_stripe(args.stripe)
    replica = (
        args.replica
        if args.replica != "follower"
        else f"stripe-{stripe[0] + 1}-of-{stripe[1]}"
    )
    cm = (
        CheckpointManager(args.checkpoint_dir)
        if getattr(args, "checkpoint_dir", None)
        else None
    )
    recovery = None
    skipped: list = []
    initial_cluster, cfg = None, None
    if args.path:
        import kubernetes_verification_tpu as kv

        initial_cluster, skipped = kv.load_cluster(args.path)
        cfg = kv.VerifyConfig(
            backend="cpu",
            compute_ports=False,
            self_traffic=args.self_traffic,
            default_allow_unselected=args.default_allow,
        )
    if getattr(args, "resume", False):
        if not args.checkpoint_dir:
            raise SystemExit("serve: --resume requires --checkpoint-dir")
        recovery = RecoveryManager(args.checkpoint_dir).recover_stripe(
            stripe,
            log_path=args.events,
            initial_cluster=initial_cluster,
            config=cfg,
            batch_size=args.batch_size,
            replica=replica,
        )
        follower = recovery.service
    else:
        if initial_cluster is None:
            raise SystemExit(
                "serve: --stripe needs a manifest PATH (or --resume "
                "with --checkpoint-dir)"
            )
        follower = StripeFollower(
            initial_cluster,
            cfg,
            stripe=stripe,
            replica=replica,
            log_path=args.events,
        )
    # tail loop: same capped exponential backoff + per-replica jitter as
    # _run_follow — a fleet of stripe owners started together must not
    # poll the shared WAL in phase
    interval = args.tail_poll
    max_interval = max(args.tail_poll, min(1.0, args.tail_poll * 32))
    rng = _random.Random(_zlib.crc32(replica.encode()))
    idle_since = _time.monotonic()
    checkpoints = 0
    batches_since = 0
    while args.events:
        applied = follower.poll(args.batch_size)
        now = _time.monotonic()
        if applied:
            batches_since += 1
            if (
                cm is not None
                and args.checkpoint_every
                and batches_since >= args.checkpoint_every
            ):
                follower.checkpoint(cm)
                checkpoints += 1
                batches_since = 0
            interval = args.tail_poll
            idle_since = now
            continue
        if not args.tail:
            break
        if now - idle_since >= args.idle_timeout:
            break
        _time.sleep(
            min(interval, args.idle_timeout) * (1.0 + rng.random() * 0.1)
        )
        interval = min(interval * 2, max_interval)
    if cm is not None:
        follower.checkpoint(cm)  # the exit checkpoint: resume loses nothing
        checkpoints += 1
    out = dict(follower.health())
    if skipped:
        out["skipped_documents"] = skipped
    if cm is not None:
        out["checkpoints"] = checkpoints
        out["checkpoint_dir"] = args.checkpoint_dir
    if recovery is not None:
        out["recovery"] = {
            "outcome": recovery.outcome,
            "generation": recovery.generation,
            "replayed": recovery.replayed,
            "duplicates_skipped": recovery.duplicates_skipped,
            "rejected_generations": len(recovery.errors),
        }
    if args.json:
        print(json.dumps(out, sort_keys=True))
    else:
        frag = out["stripe"]
        print(
            f"stripe {frag['index'] + 1}/{frag['count']} ({out['replica']}): "
            f"rows [{frag['lo']}, {frag['hi']}) of {frag['n']} pods, "
            f"{out['applied']} events applied "
            f"({out['fanout']} cross-stripe fan-out) at gen "
            f"{out['generation']}"
        )
        if recovery is not None:
            print(
                f"  recovered: {recovery.outcome} (gen "
                f"{recovery.generation}, {recovery.replayed} events "
                f"replayed, {recovery.duplicates_skipped} duplicates "
                "skipped)"
            )
        if cm is not None:
            print(f"  checkpoints: {checkpoints} -> {args.checkpoint_dir}")
    return EXIT_OK


def _run_follow(args) -> int:
    """Follower replica: bootstrap from the newest checkpoint generation
    in ``--follow DIR``, tail the leader's WAL under the ``--staleness``
    bound, and (with ``--promote-on-lease-expiry``) take over when the
    lease expires and the leader-probe breaker opens."""
    import random as _random
    import time as _time
    import zlib as _zlib

    from .resilience.errors import EXIT_OK, EXIT_VIOLATIONS
    from .serve import FollowerService, load_assertions

    follower = FollowerService(
        args.follow,
        log_path=args.events,
        replica=args.replica,
        max_lag_seconds=args.staleness,
        proxy_stale=args.proxy_stale,
        lease_ttl=args.lease_ttl,
        batch_size=args.batch_size,
        leader_url=getattr(args, "leader", None),
    )
    svc = follower.service
    if getattr(args, "assert_file", None):
        svc.assertions.extend(load_assertions(args.assert_file))
    posture = _maybe_enable_posture(svc, args)
    # tail loop: the same capped exponential backoff EventSource.tail
    # uses, with a leader heartbeat (and, opted in, a promotion check)
    # between drains
    interval = args.tail_poll
    max_interval = max(args.tail_poll, min(1.0, args.tail_poll * 32))
    # per-replica jitter stream (same law as EventSource.tail): a fleet
    # of followers started together must not probe the leader in phase
    rng = _random.Random(_zlib.crc32(args.replica.encode()))
    idle_since = _time.monotonic()
    while True:
        applied = follower.poll()
        follower.heartbeat()
        if args.promote_on_lease_expiry and follower.maybe_promote():
            break
        now = _time.monotonic()
        if applied:
            interval = args.tail_poll
            idle_since = now
            continue
        if now - idle_since >= args.idle_timeout:
            break
        _time.sleep(
            min(interval, args.idle_timeout) * (1.0 + rng.random() * 0.1)
        )
        interval = min(interval * 2, max_interval)
    # the final answer rides the same staleness gate as any client read:
    # over-bound exits 2 with the measured lag (or proxies under
    # --proxy-stale)
    follower._guard()
    reach = svc.reach(trigger="query" if not svc.assertions else "assertions")
    pairs = int(reach.sum())
    out = {
        **follower.describe(),
        "pods": svc.n_pods,
        "policies": len(svc.engine.policies),
        "reachable_pairs": pairs,
        "assertions": len(svc.assertions),
        "violations": [v.describe() for v in svc.violations],
        **svc.stats.to_dict(),
    }
    if posture is not None:
        out["posture"] = posture.health()
    if args.json:
        print(json.dumps(out, sort_keys=True))
    else:
        print(
            f"replica {out['replica']} ({out['outcome']} bootstrap): "
            f"{out['pods']} pods after {out['applied']} applied events "
            f"(last_seq {out['last_seq']}, lag {out['lag_seq']} records): "
            f"{pairs} reachable pairs"
        )
        if follower.promoted:
            print(f"  PROMOTED to leader at epoch {follower.epoch}")
        for v in svc.violations:
            print(f"  VIOLATION: {v.describe()}")
    return EXIT_VIOLATIONS if svc.violations else EXIT_OK


def cmd_recover(args) -> int:
    from .resilience.errors import KvTpuError

    try:
        with _observed(args):
            return _run_recover(args)
    except KvTpuError as e:
        return _diagnose(args, e)


def _run_recover(args) -> int:
    """Read-only durability triage: report every checkpoint generation's
    health and (with ``--events``) the WAL's valid prefix; nothing is
    loaded, repaired or truncated. Exit 2 when the directory is missing
    or every generation is damaged."""
    import os

    from .resilience.errors import EXIT_INPUT_ERROR, EXIT_OK
    from .serve import RecoveryManager

    if not os.path.isdir(args.dir):
        print(f"recover: {args.dir} is not a directory", file=sys.stderr)
        return EXIT_INPUT_ERROR
    report = RecoveryManager(args.dir).inspect(log_path=args.events)
    report["flight_dumps"] = _flight_dumps(args.dir)
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        gens = report["generations"]
        if not gens:
            print(f"{args.dir}: no checkpoint generations")
        for g in gens:
            if g["valid"]:
                kind = g.get("kind", "serve")
                if kind == "stripe":
                    st = g.get("stripe") or {}
                    tag = (
                        f"stripe {st.get('index', 0) + 1}"
                        f"/{st.get('count', '?')}  "
                    )
                elif kind != "serve":
                    tag = f"{kind}  "
                else:
                    tag = ""
                print(
                    f"gen {g['generation']:>3}  OK   {tag}"
                    f"offset={g['log_offset']} last_seq={g['last_seq']} "
                    f"log={g['event_log']}"
                )
            else:
                print(f"gen {g['generation']:>3}  BAD  {g['error']}")
        wal = report.get("wal")
        if wal:
            if "error" in wal:
                print(f"wal {wal['path']}: ERROR {wal['error']}")
            else:
                tail = (
                    f"  TORN tail: {wal['torn_bytes']} bytes after "
                    f"offset {wal['valid_bytes']} (serve --resume "
                    "truncates)"
                    if wal["torn"]
                    else ""
                )
                print(
                    f"wal {wal['path']}: {wal['records']} records "
                    f"({wal['sequenced']} sequenced, "
                    f"last_seq={wal['last_seq']}){tail}"
                )
        lease = report.get("lease")
        if lease:
            if "error" in lease:
                print(f"lease {lease['path']}: ERROR {lease['error']}")
            else:
                state = "EXPIRED" if lease["expired"] else "live"
                print(
                    f"lease {lease['path']}: epoch {lease['epoch']} held "
                    f"by {lease['holder']} ({state}, "
                    f"age {lease['age_seconds']:.1f}s / "
                    f"ttl {lease['ttl']:.1f}s)"
                )
        pack = report.get("aot_pack")
        if pack and pack.get("present"):
            env = "env-match" if pack.get("env_match") else "ENV MISMATCH"
            print(
                f"aot-pack {pack['directory']}: {pack['entries']} entries "
                f"({pack['matching']} usable, {pack['mismatched']} "
                f"mismatched, {pack['corrupt']} corrupt; {env}, "
                f"{pack['bytes']} bytes)"
            )
        elif pack is not None:
            print("aot-pack: none (cold start will recompile every kernel)")
        for f in report["flight_dumps"]:
            if "error" in f:
                print(f"flight {f['path']}: ERROR {f['error']}")
                continue
            print(
                f"flight {f['path']}: trigger={f['trigger']} "
                f"pid={f['pid']} entries={f['entries']}"
            )
            for line in f["tail"]:
                print(line)
    if report["generations"] and not report["usable"]:
        return EXIT_INPUT_ERROR
    return EXIT_OK


def _flight_dumps(directory: str, tail: int = 8) -> list:
    """Flight-recorder dumps found in a serve directory, each summarized
    for the recover report: trigger, pid, entry count, and the rendered
    tail (the newest ``tail`` ring entries — the moments before the
    trigger)."""
    import glob
    import os

    from .observe.flight import load_dump, render_dump

    out = []
    for path in sorted(glob.glob(os.path.join(directory, "flight-*.json"))):
        name = os.path.basename(path)
        try:
            payload = load_dump(path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            out.append({"path": name, "error": f"{type(e).__name__}: {e}"})
            continue
        lines = render_dump(payload)
        out.append(
            {
                "path": name,
                "trigger": payload.get("trigger"),
                "info": payload.get("info"),
                "pid": payload.get("pid"),
                "ts": payload.get("ts"),
                "entries": len(payload.get("entries", [])),
                "tail": lines[-tail:] if len(lines) > 1 else [],
            }
        )
    return out


def cmd_warmup(args) -> int:
    from .resilience.errors import KvTpuError

    try:
        with _observed(args):
            return _run_warmup(args)
    except KvTpuError as e:
        return _diagnose(args, e)


def _run_warmup(args) -> int:
    """Pre-populate a warm executable pack for a config: build the engine
    (construction prewarms the mutation/diff kernels through their real
    call paths), drive the batched query plane, then AOT-compile every
    recorded dispatch signature and persist the serialized executables
    (``observe/aot.py``). ``kv-tpu serve``/``query --from-snapshot`` and
    checkpoint recovery ride the resulting pack."""
    from .observe import aot
    from .resilience.errors import EXIT_OK
    from .serve import QueryEngine, ServeConfig

    svc, _skipped = _load_serve_service(args, ServeConfig())
    q = QueryEngine(svc)
    pods = svc.engine.pods
    if len(pods) >= 2:
        names = [f"{p.namespace}/{p.name}" for p in pods[:8]]
        probes = [
            (names[i], names[(i + 1) % len(names)], None, "TCP")
            for i in range(len(names))
        ]
        q.can_reach_batch(probes)
        q.who_can_reach(names[0])
        q.blast_radius(names[0])
    summary = aot.save_pack(args.out)
    if args.json:
        print(json.dumps(summary, sort_keys=True))
    else:
        print(
            f"warmup: {summary['entries']} executables "
            f"({summary['new']} newly compiled, {summary['skipped']} "
            f"skipped) in {summary['directory']} "
            f"[{summary['bytes']} bytes]"
        )
    return EXIT_OK


def _parse_probe_batch(path: str):
    """Parse a ``--batch`` JSONL probe file into ``(src, dst, port,
    protocol)`` tuples — shared by ``kv-tpu query`` and ``kv-tpu lb``."""
    from .resilience.errors import IngestError

    probes = []
    try:
        with open(path) as fh:
            lines = fh.read().splitlines()
    except OSError as e:
        raise IngestError(f"cannot read query batch {path}: {e}") from e
    for ln_no, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError as e:
            raise IngestError(
                f"{path}:{ln_no}: not valid JSON: {e}"
            ) from e
        if not isinstance(obj, dict) or "src" not in obj or "dst" not in obj:
            raise IngestError(
                f"{path}:{ln_no}: each probe needs 'src' and "
                "'dst' (optional: 'port', 'protocol')"
            )
        unknown = set(obj) - {"src", "dst", "port", "protocol"}
        if unknown:
            raise IngestError(
                f"{path}:{ln_no}: unknown field(s) {sorted(unknown)}"
            )
        port = obj.get("port")
        if port is not None:
            try:
                port = int(port)
            except (TypeError, ValueError):
                raise IngestError(
                    f"{path}:{ln_no}: port must be an integer, "
                    f"got {obj['port']!r}"
                ) from None
        probes.append(
            (
                str(obj["src"]),
                str(obj["dst"]),
                port,
                str(obj.get("protocol", "TCP")),
            )
        )
    return probes


def cmd_query(args) -> int:
    from .resilience.errors import KvTpuError

    try:
        with _observed(args):
            return _run_query(args)
    except KvTpuError as e:
        return _diagnose(args, e)


def _run_query(args) -> int:
    from .resilience.errors import EXIT_OK, EXIT_VIOLATIONS
    from .serve import (
        AddPolicy,
        QueryEngine,
        ServeConfig,
        load_assertions,
    )

    svc, _skipped = _load_serve_service(args, ServeConfig())
    assertions = (
        load_assertions(args.assert_file)
        if getattr(args, "assert_file", None)
        else []
    )
    q = QueryEngine(svc)
    out = {}
    exit_code = EXIT_OK
    if args.can_reach:
        src, dst = args.can_reach
        ok = q.can_reach(src, dst, port=args.port, protocol=args.protocol)
        out["can_reach"] = {
            "src": src, "dst": dst, "port": args.port,
            "protocol": args.protocol if args.port is not None else None,
            "allowed": ok,
        }
    if getattr(args, "batch", None):
        probes = _parse_probe_batch(args.batch)
        answers = q.can_reach_batch(probes)
        out["batch"] = {
            "file": args.batch,
            "n": len(probes),
            "allowed": int(answers.sum()),
            "results": [
                {
                    "src": s,
                    "dst": d,
                    "port": p,
                    "protocol": proto if p is not None else None,
                    "allowed": bool(a),
                }
                for (s, d, p, proto), a in zip(probes, answers)
            ],
        }
    if args.who_can_reach:
        out["who_can_reach"] = {
            "dst": args.who_can_reach,
            "sources": q.who_can_reach(args.who_can_reach),
        }
    if args.blast_radius:
        out["blast_radius"] = {
            "src": args.blast_radius,
            "targets": q.blast_radius(args.blast_radius),
        }
    if getattr(args, "path_exists", None):
        src, dst = args.path_exists
        out["path_exists"] = {
            "src": src, "dst": dst, "max_hops": args.max_hops,
            "exists": q.path_exists(src, dst, max_hops=args.max_hops),
        }
    if getattr(args, "hops", None):
        src, dst = args.hops
        out["hops"] = {
            "src": src, "dst": dst, "max_hops": args.max_hops,
            "hops": q.hops(src, dst, max_hops=args.max_hops),
        }
    if args.what_if:
        import kubernetes_verification_tpu as kv

        delta, _ = kv.load_cluster(args.what_if)
        if not delta.policies:
            raise SystemExit(
                f"--what-if {args.what_if}: no NetworkPolicy documents found"
            )
        res = q.what_if(
            [AddPolicy(policy=p) for p in delta.policies],
            assertions=assertions or None,
        )
        out["what_if"] = res.to_dict()
        if not res.ok:
            exit_code = EXIT_VIOLATIONS
    elif assertions:
        svc.assertions.extend(assertions)
        found = svc.check_assertions()
        out["assertions"] = {
            "checked": len(assertions),
            "violations": [v.describe() for v in found],
        }
        if found:
            exit_code = EXIT_VIOLATIONS
    if not out:
        raise SystemExit(
            "query: nothing to answer — give --can-reach SRC DST, "
            "--batch FILE.jsonl, --who-can-reach DST, --blast-radius SRC, "
            "--path-exists SRC DST, --hops SRC DST, "
            "--what-if MANIFESTS and/or --assert FILE"
        )
    if args.json:
        print(json.dumps(out, sort_keys=True))
    else:
        if "can_reach" in out:
            c = out["can_reach"]
            via = (
                f" on {c['protocol']}/{c['port']}"
                if c["port"] is not None
                else ""
            )
            print(
                f"{c['src']} -> {c['dst']}{via}: "
                f"{'ALLOWED' if c['allowed'] else 'DENIED'}"
            )
        if "batch" in out:
            b = out["batch"]
            for r in b["results"]:
                via = (
                    f" on {r['protocol']}/{r['port']}"
                    if r["port"] is not None
                    else ""
                )
                print(
                    f"{r['src']} -> {r['dst']}{via}: "
                    f"{'ALLOWED' if r['allowed'] else 'DENIED'}"
                )
            print(f"batch {b['file']}: {b['allowed']}/{b['n']} allowed")
        if "who_can_reach" in out:
            w = out["who_can_reach"]
            print(f"{len(w['sources'])} pods can reach {w['dst']}: "
                  f"{w['sources']}")
        if "blast_radius" in out:
            b = out["blast_radius"]
            print(f"{b['src']} can reach {len(b['targets'])} pods: "
                  f"{b['targets']}")
        if "path_exists" in out:
            pe = out["path_exists"]
            bound = (
                f" within {pe['max_hops']} hops"
                if pe["max_hops"] is not None
                else ""
            )
            print(
                f"path {pe['src']} ->* {pe['dst']}{bound}: "
                f"{'EXISTS' if pe['exists'] else 'NONE'}"
            )
        if "hops" in out:
            h = out["hops"]
            bound = (
                f" within {h['max_hops']} hops"
                if h["max_hops"] is not None
                else ""
            )
            print(
                f"hops {h['src']} ->* {h['dst']}{bound}: "
                + (str(h["hops"]) if h["hops"] > 0 else "UNREACHABLE")
            )
        if "what_if" in out:
            w = out["what_if"]
            print(
                f"what-if: {'OK' if w['ok'] else 'REJECTED'} "
                f"(+{w['pairs_added']} / -{w['pairs_removed']} pairs)"
            )
            for line in w["violations"]:
                print(f"  VIOLATION: {line}")
        if "assertions" in out:
            a = out["assertions"]
            print(f"{a['checked']} assertions checked, "
                  f"{len(a['violations'])} violated")
            for line in a["violations"]:
                print(f"  VIOLATION: {line}")
    return exit_code


def cmd_lb(args) -> int:
    from .resilience.errors import KvTpuError

    try:
        with _observed(args):
            return _run_lb(args)
    except KvTpuError as e:
        return _diagnose(args, e)


def _run_lb(args) -> int:
    """``kv-tpu lb``: answer ``--batch`` probe files through a
    staleness-weighted load balancer over follower replicas. Each
    ``--replica`` is a checkpoint directory (shared-fs follower) or
    ``DIR=URL`` (networked follower bootstrapped over HTTP from the
    replication server at URL into DIR). ``--leader DIR`` wires the
    stale-read retry / last-resort fallback."""
    from .resilience.errors import EXIT_OK, EXIT_VIOLATIONS
    from .serve import FollowerService, QueryLoadBalancer

    replicas = []
    for i, spec in enumerate(args.replica):
        directory, sep, url = spec.partition("=")
        replicas.append(
            FollowerService(
                directory,
                log_path=args.events,
                replica=f"replica-{i}",
                max_lag_seconds=args.staleness,
                leader_url=url if sep else None,
            )
        )
    leader = None
    if args.leader:
        # no staleness bound: the leader's directory IS the fresh state
        leader = FollowerService(
            args.leader, log_path=args.events, replica="leader"
        )
    lb = QueryLoadBalancer(replicas, leader=leader, seed=args.seed)
    batches = []
    denied = 0
    for path in args.batch:
        probes = _parse_probe_batch(path)
        answers, who = lb.can_reach_batch(probes)
        allowed = int(answers.sum())
        denied += len(probes) - allowed
        batches.append(
            {
                "file": path,
                "n": len(probes),
                "allowed": allowed,
                "replica": who,
            }
        )
    out = {"batches": batches, "lb": lb.describe()}
    if args.json:
        print(json.dumps(out, sort_keys=True))
    else:
        for b in batches:
            print(
                f"{b['file']}: {b['allowed']}/{b['n']} allowed "
                f"(answered by {b['replica']})"
            )
        routed = ", ".join(
            f"{who}={n}" for who, n in sorted(lb.routed.items())
        )
        print(
            f"routed: {routed or 'nothing'}  "
            f"stale_retries: {lb.stale_retries}  ejections: {lb.ejections}"
        )
    if args.check_denied and denied:
        return EXIT_VIOLATIONS
    return EXIT_OK


def _metrics_source_text(source: str, timeout: float = 5.0) -> str:
    """Exemplar-annotated metrics text from a replica URL or a saved file."""
    if source.startswith(("http://", "https://")):
        from .serve.transport import ReplicationClient

        return ReplicationClient(source, timeout=timeout).metrics_text(
            exemplars=True
        )
    try:
        with open(source) as fh:
            return fh.read()
    except OSError as e:
        raise SystemExit(f"trace: cannot read metrics source {source}: {e}")


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def cmd_trace(args) -> int:
    from .resilience.errors import KvTpuError

    try:
        with _observed(args):
            return _run_trace(args)
    except KvTpuError as e:
        return _diagnose(args, e)


def _run_trace(args) -> int:
    """``kv-tpu trace``: reassemble one trace's cross-process timeline.

    Every span close and event line carries ``trace_id`` (propagated over
    HTTP via the ``X-Kvtpu-Trace`` header), a wall-clock ``ts``/``start_ts``
    and span/parent ids — so scanning each replica's JSON event log for one
    trace id and sorting by wall time rebuilds the span tree across
    processes, plus the query stage breakdown (queue/dispatch/solve/d2h).

    ``--slowest`` closes the metric→trace loop: instead of a trace id,
    read ``/metrics?exemplars=1`` output (``--metrics`` URL or file),
    take the highest-valued latency exemplar (optionally pinned to one
    ``--stage``), and reassemble *that* trace — from "the histogram says
    something was slow" to the full cross-process timeline of the slow
    request, no log spelunking for the id."""
    from .resilience.errors import EXIT_OK, EXIT_VIOLATIONS

    if args.slowest:
        from .observe.export import parse_exemplars

        if not args.metrics:
            raise SystemExit(
                "trace: --slowest needs --metrics URL|FILE "
                "(an exemplar-annotated /metrics source)"
            )
        exemplars = []
        for source in args.metrics:
            exemplars.extend(
                parse_exemplars(_metrics_source_text(source))
            )
        if args.stage:
            exemplars = [
                e
                for e in exemplars
                if e["labels"].get("stage") == args.stage
            ]
        exemplars = [e for e in exemplars if e["exemplar"].get("trace_id")]
        if not exemplars:
            stage = f" for stage {args.stage!r}" if args.stage else ""
            print(f"trace: no exemplars{stage} in the metrics source(s)",
                  file=sys.stderr)
            return EXIT_VIOLATIONS
        best = max(exemplars, key=lambda e: e["value"])
        args.trace_id = best["exemplar"]["trace_id"]
        print(
            f"slowest exemplar: {best['name']}"
            f"{_fmt_labels(best['labels'])} = {best['value']:.6g}s "
            f"-> trace {args.trace_id}"
        )
    elif not args.trace_id:
        raise SystemExit("trace: give a TRACE_ID or use --slowest")

    spans: dict = {}  # span_id -> span-close line (+ source log)
    events = []  # non-span lines in the trace
    for path in args.log:
        try:
            fh = open(path)
        except OSError as e:
            raise SystemExit(f"trace: cannot read {path}: {e}")
        with fh:
            for raw in fh:
                raw = raw.strip()
                if not raw or not raw.startswith("{"):
                    continue
                try:
                    line = json.loads(raw)
                except ValueError:
                    continue
                if (
                    not isinstance(line, dict)
                    or line.get("trace_id") != args.trace_id
                ):
                    continue
                line["_log"] = os.path.basename(path)
                if (
                    line.get("event") in ("span", "phase")
                    and line.get("span_id")
                    and line.get("seconds") is not None
                ):
                    # first writer wins: the same span duplicated across
                    # logs (shared event file) renders once
                    spans.setdefault(line["span_id"], line)
                else:
                    events.append(line)
    if not spans and not events:
        print(
            f"trace {args.trace_id}: no matching lines in "
            f"{len(args.log)} log(s)",
            file=sys.stderr,
        )
        return EXIT_VIOLATIONS

    children: dict = {}
    roots = []
    for sid, sp in spans.items():
        pid = sp.get("parent_id")
        if pid in spans:
            children.setdefault(pid, []).append(sid)
        else:
            roots.append(sid)
    start_key = lambda sid: spans[sid].get("start_ts") or 0.0  # noqa: E731

    ordered = []  # (depth, span line) in timeline order

    def _walk(sid: str, depth: int) -> None:
        ordered.append((depth, spans[sid]))
        for kid in sorted(children.get(sid, []), key=start_key):
            _walk(kid, depth + 1)

    for sid in sorted(roots, key=start_key):
        _walk(sid, 0)

    # query stage breakdown: stage-attributed spans vs. the batch span
    stages: dict = {}
    e2e = 0.0
    for _, sp in ordered:
        if sp.get("stage"):
            stages[sp["stage"]] = (
                stages.get(sp["stage"], 0.0) + float(sp["seconds"])
            )
        if sp.get("name") == "query_batch":
            e2e += float(sp["seconds"])

    if args.json:
        print(
            json.dumps(
                {
                    "trace_id": args.trace_id,
                    "logs": args.log,
                    "spans": [
                        dict(sp, depth=depth) for depth, sp in ordered
                    ],
                    "events": events,
                    "stages": stages,
                    "e2e_seconds": e2e or None,
                },
                sort_keys=True,
            )
        )
        return EXIT_OK

    t0 = min(
        (sp.get("start_ts") for _, sp in ordered if sp.get("start_ts")),
        default=None,
    )
    n_logs = len({sp["_log"] for _, sp in ordered})
    print(
        f"trace {args.trace_id}: {len(ordered)} spans, "
        f"{len(events)} events across {n_logs} process log(s)"
    )
    for depth, sp in ordered:
        off = (
            f"+{(sp['start_ts'] - t0) * 1000.0:9.3f}ms"
            if t0 is not None and sp.get("start_ts")
            else " " * 11
        )
        dur = f"{float(sp['seconds']) * 1000.0:.3f}ms"
        flag = "" if sp.get("ok", True) else "  FAILED"
        print(
            f"{off}  {'  ' * depth}{sp.get('name', '?')} {dur} "
            f"[{sp['_log']}]{flag}"
        )
    if stages:
        parts = "  ".join(
            f"{k}={v * 1000.0:.3f}ms"
            for k, v in sorted(stages.items())
        )
        total = sum(stages.values())
        tail = (
            f"  (sum {total * 1000.0:.3f}ms, e2e {e2e * 1000.0:.3f}ms)"
            if e2e
            else f"  (sum {total * 1000.0:.3f}ms)"
        )
        print(f"stages: {parts}{tail}")
    return EXIT_OK


def cmd_fleet(args) -> int:
    from .resilience.errors import KvTpuError

    try:
        with _observed(args):
            return _run_fleet(args)
    except KvTpuError as e:
        return _diagnose(args, e)


def _run_fleet(args) -> int:
    """``kv-tpu fleet``: scrape every ``--replica`` URL's ``/healthz`` +
    ``/metrics``, render the fleet table, and evaluate the ``--slo``
    objectives' multi-window burn rates (exit 1 past ``--burn-threshold``)."""
    from .observe.fleet import (
        SloMonitor,
        fleet_row,
        parse_slo_spec,
        render_fleet,
        scrape_replica,
        stripe_coverage,
    )
    from .resilience.errors import EXIT_OK, EXIT_VIOLATIONS

    try:
        objectives = [
            parse_slo_spec(s) for s in (args.slo or ["availability=0.999"])
        ]
    except ValueError as e:
        raise SystemExit(f"fleet: {e}")
    monitor = SloMonitor(objectives)
    scrapes = [
        scrape_replica(url, timeout=args.timeout) for url in args.replica
    ]
    for s in scrapes:
        monitor.observe_scrape(s)
    burns = monitor.evaluate()
    worst = max(
        (b for per in burns.values() for b in per.values()), default=0.0
    )
    if args.json:
        inf = float("inf")
        print(
            json.dumps(
                {
                    # each replica object mirrors the table row
                    # (fleet_row) plus the raw health document
                    "replicas": [
                        dict(fleet_row(s), health=s.health)
                        for s in scrapes
                    ],
                    "slo": {
                        name: {
                            label: ("inf" if b == inf else b)
                            for label, b in per.items()
                        }
                        for name, per in burns.items()
                    },
                    "burn_threshold": args.burn_threshold,
                    # fleet-wide stripe coverage (None for a whole-state
                    # fleet): a stripe with no live owner is an outage,
                    # surfaced here and as the table's GAP line
                    "stripe_coverage": stripe_coverage(scrapes),
                },
                sort_keys=True,
            )
        )
    else:
        for line in render_fleet(scrapes):
            print(line)
        for name, per in sorted(burns.items()):
            txt = "  ".join(
                f"{label}={burn:.3g}"
                for label, burn in sorted(per.items())
            )
            verdict = (
                "BURNING"
                if max(per.values(), default=0.0) > args.burn_threshold
                else "ok"
            )
            print(f"slo {name}: {txt}  [{verdict}]")
    if worst > args.burn_threshold:
        return EXIT_VIOLATIONS
    return EXIT_OK


def cmd_posture(args) -> int:
    from .resilience.errors import KvTpuError

    try:
        with _observed(args):
            return _run_posture(args)
    except KvTpuError as e:
        return _diagnose(args, e)


def _posture_journal_path(arg: str) -> str:
    import os

    from .serve.posture import POSTURE_JOURNAL

    path = arg
    if os.path.isdir(path):
        path = os.path.join(path, POSTURE_JOURNAL)
    if not os.path.exists(path):
        raise SystemExit(f"posture: no journal at {path}")
    return path


def _run_posture(args) -> int:
    """``kv-tpu posture``: read a crc'd posture journal — timeline of
    per-generation reach deltas, ``--watch`` tailing, ``--diff A B``
    aggregation. Exit 1 when any rendered record carries an alert
    violation (the CI-gate contract); a torn journal tail is reported on
    stderr, everything before it is trusted."""
    import time as _time

    from .resilience.errors import EXIT_OK, EXIT_VIOLATIONS
    from .serve.posture import (
        posture_diff,
        render_posture_timeline,
        scan_posture,
    )

    path = _posture_journal_path(args.journal)
    scan = scan_posture(path)
    if not scan.ok:
        print(
            f"posture: journal torn at line {scan.torn_lineno} "
            f"({scan.torn_error}); rendering the valid prefix",
            file=sys.stderr,
        )
    records = scan.records

    if args.diff:
        gen_a, gen_b = args.diff
        diff = posture_diff(records, gen_a, gen_b)
        if args.json:
            print(json.dumps(diff, sort_keys=True))
        else:
            print(
                f"gen {diff['gen_a']} -> {diff['gen_b']} "
                f"({diff['generations']} generations): "
                f"+{diff['widened']}/-{diff['narrowed']} pairs, "
                f"reachable {diff['reachable_at_a']} -> "
                f"{diff['reachable_at_b']}"
            )
            for label, moved in (
                ("widened", diff["ns_widened"]),
                ("narrowed", diff["ns_narrowed"]),
            ):
                for pair, count in moved.items():
                    print(f"  {label} {pair}: {count}")
            if diff["alerts"]:
                print(f"  alert violations in range: {diff['alerts']}")
        return EXIT_VIOLATIONS if diff["alerts"] else EXIT_OK

    if args.watch:
        seen = 0
        idle_since = _time.monotonic()
        violations = 0
        try:
            while True:
                scan = scan_posture(path)
                fresh = scan.records[seen:]
                for r in fresh:
                    violations += len(r.alerts)
                    if args.json:
                        print(json.dumps(r.to_dict(), sort_keys=True))
                    else:
                        for line in render_posture_timeline(
                            [r], limit=1
                        )[1:]:
                            print(line)
                if fresh:
                    seen = len(scan.records)
                    idle_since = _time.monotonic()
                elif (
                    args.idle_timeout is not None
                    and _time.monotonic() - idle_since >= args.idle_timeout
                ):
                    break
                _time.sleep(args.poll)
        except KeyboardInterrupt:
            pass
        return EXIT_VIOLATIONS if violations else EXIT_OK

    shown = list(records)[-args.limit:]
    if args.json:
        print(
            json.dumps(
                {
                    "journal": path,
                    "records": [r.to_dict() for r in shown],
                    "torn_lineno": scan.torn_lineno,
                },
                sort_keys=True,
            )
        )
    else:
        for line in render_posture_timeline(records, limit=args.limit):
            print(line)
    return (
        EXIT_VIOLATIONS if any(r.alerts for r in shown) else EXIT_OK
    )


def cmd_jobs(args) -> int:
    from .resilience.errors import KvTpuError

    try:
        with _observed(args):
            return _run_jobs(args)
    except KvTpuError as e:
        return _diagnose(args, e)


def _run_jobs(args) -> int:
    """``kv-tpu jobs``: the fleet's in-flight long jobs. Every replica's
    ``/healthz`` carries its process's live progress table (pass counters,
    smoothed rates, ETAs — the :class:`~.observe.progress.ProgressTicker`
    plane); this merges them into one table. A dead replica degrades to a
    stderr note — the rest still render."""
    from .observe.fleet import scrape_replica
    from .observe.progress import render_jobs
    from .resilience.errors import EXIT_OK

    scrapes = [
        scrape_replica(url, timeout=args.timeout) for url in args.replica
    ]
    jobs, down = [], []
    for s in scrapes:
        if not s.ok:
            down.append({"url": s.url, "error": s.error})
            continue
        for j in (s.health or {}).get("jobs") or []:
            jobs.append(dict(j, replica=s.url))
    if args.json:
        print(json.dumps({"jobs": jobs, "down": down}, sort_keys=True))
        return EXIT_OK
    if jobs:
        for line in render_jobs(jobs):
            print(line)
    else:
        print("no jobs in flight")
    for d in down:
        print(f"{d['url']}: DOWN ({d['error']})", file=sys.stderr)
    return EXIT_OK


def cmd_profile(args) -> int:
    from .resilience.errors import KvTpuError

    try:
        with _observed(args):
            return _run_profile(args)
    except KvTpuError as e:
        return _diagnose(args, e)


def _run_profile(args) -> int:
    """``kv-tpu profile``: on-demand bounded deep profiling. With
    ``--replica`` it triggers a capture on a *running* replica
    (``/profile?seconds=N`` — no restart); without, it captures in this
    process into ``--dir``. Either way the capture is a bounded
    ``jax.profiler`` trace, rate-limited so a scrape loop cannot DoS the
    device, and recorded in the capture directory's manifest."""
    from .resilience.errors import EXIT_OK, EXIT_VIOLATIONS

    if args.replica:
        from .serve.transport import ReplicationClient

        client = ReplicationClient(
            args.replica, timeout=max(args.timeout, args.seconds + 10.0)
        )
        result = client.profile(args.seconds)
    else:
        from .observe.spans import capture_profile

        result = capture_profile(
            args.seconds, trigger="cli", capture_dir=args.dir
        )
    if args.json:
        print(json.dumps(result, sort_keys=True))
        return (
            EXIT_OK if result.get("outcome") == "ok" else EXIT_VIOLATIONS
        )
    outcome = result.get("outcome")
    if outcome == "ok":
        print(
            f"captured {result.get('seconds')}s -> {result.get('path')} "
            f"({result.get('files')} files)"
        )
        return EXIT_OK
    if outcome == "rate-limited":
        print(
            f"profile: rate-limited, retry in "
            f"{result.get('retry_after_s', 0.0):.1f}s",
            file=sys.stderr,
        )
    else:
        print(
            f"profile: {outcome}: {result.get('reason', '-')}",
            file=sys.stderr,
        )
    return EXIT_VIOLATIONS


_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def _spark(values, width: int = 16) -> str:
    """Unicode sparkline over the last ``width`` samples; None samples
    (scrape misses) render as gaps, a flat series as its floor block."""
    vals = list(values)[-width:]
    finite = [v for v in vals if v is not None]
    if not finite:
        return "-" * min(len(vals) or 1, width)
    lo, hi = min(finite), max(finite)
    span = hi - lo
    out = []
    for v in vals:
        if v is None:
            out.append(" ")
        elif span <= 0:
            out.append(_SPARK_BLOCKS[0])
        else:
            idx = int((v - lo) / span * (len(_SPARK_BLOCKS) - 1) + 0.5)
            out.append(_SPARK_BLOCKS[min(len(_SPARK_BLOCKS) - 1, idx)])
    return "".join(out)


def cmd_top(args) -> int:
    from .resilience.errors import KvTpuError

    try:
        with _observed(args):
            return _run_top(args)
    except KvTpuError as e:
        return _diagnose(args, e)


def _run_top(args) -> int:
    """``kv-tpu top``: a live terminal dashboard over the scrape surface —
    the fleet table, every in-flight job with its ETA bar, QPS / lag /
    burn-rate sparklines per poll, and recent crash flight dumps. A dead
    replica renders as a DOWN row and a gap in its sparklines; the rest of
    the fleet keeps updating. ``--once`` renders a single frame (no screen
    clearing) for scripts and tests."""
    import collections
    import time as _time

    from .observe.fleet import (
        SloMonitor,
        parse_slo_spec,
        render_fleet,
        scrape_replica,
    )
    from .observe.progress import render_jobs
    from .resilience.errors import EXIT_OK

    try:
        objectives = [
            parse_slo_spec(s) for s in (args.slo or ["availability=0.999"])
        ]
    except ValueError as e:
        raise SystemExit(f"top: {e}")
    monitor = SloMonitor(objectives)
    depth = 24
    hist = {
        url: {
            "qps": collections.deque(maxlen=depth),
            "lag": collections.deque(maxlen=depth),
        }
        for url in args.replica
    }
    burn_hist: collections.deque = collections.deque(maxlen=depth)
    prev: dict = {}  # url -> (queries_total, monotonic ts)
    prev_shed: dict = {}  # url -> ({tenant: rejections_total}, monotonic ts)
    shed_rates: dict = {}  # url -> {tenant: sheds/s}
    quota_util: dict = {}  # url -> {tenant: bucket utilization 0..1}
    frames = 0
    try:
        while True:
            scrapes = [
                scrape_replica(url, timeout=args.timeout)
                for url in args.replica
            ]
            now = _time.monotonic()
            for s in scrapes:
                monitor.observe_scrape(s)
                qps = None
                if s.ok and s.metrics is not None:
                    total = sum(
                        v
                        for _, v in s.metrics.get(
                            "kvtpu_serve_queries_total", []
                        )
                    )
                    p = prev.get(s.url)
                    if p is not None and now > p[1]:
                        qps = max(0.0, (total - p[0]) / (now - p[1]))
                    prev[s.url] = (total, now)
                    # per-tenant admission telemetry: shed-rate from the
                    # rejection counter deltas, quota utilisation straight
                    # off the gauge
                    shed: dict = {}
                    for labels, v in s.metrics.get(
                        "kvtpu_admission_rejections_total", []
                    ):
                        t = labels.get("tenant")
                        if t is not None:
                            shed[t] = shed.get(t, 0.0) + v
                    ps = prev_shed.get(s.url)
                    if ps is not None and now > ps[1]:
                        dt = now - ps[1]
                        shed_rates[s.url] = {
                            t: max(0.0, (v - ps[0].get(t, 0.0)) / dt)
                            for t, v in shed.items()
                        }
                    prev_shed[s.url] = (shed, now)
                    quota_util[s.url] = {
                        labels["tenant"]: v
                        for labels, v in s.metrics.get(
                            "kvtpu_admission_quota_utilization", []
                        )
                        if "tenant" in labels
                    }
                hist[s.url]["qps"].append(qps)
                hist[s.url]["lag"].append(s.lag_seconds)
            burns = monitor.evaluate()
            inf = float("inf")
            burn_hist.append(
                max(
                    (
                        b
                        for per in burns.values()
                        for b in per.values()
                        if b != inf
                    ),
                    default=0.0,
                )
            )
            lines = list(render_fleet(scrapes))
            jobs, dumps = [], []
            for s in scrapes:
                if s.ok and s.health:
                    jobs.extend(s.health.get("jobs") or [])
                    dumps.extend(s.health.get("flight_dumps") or [])
            lines.append("")
            if jobs:
                lines.append(f"jobs ({len(jobs)} in flight):")
                lines.extend("  " + row for row in render_jobs(jobs))
            else:
                lines.append("jobs: none in flight")
            lines.append("")
            for s in scrapes:
                h = hist[s.url]
                last_qps = next(
                    (v for v in reversed(h["qps"]) if v is not None), None
                )
                last_lag = next(
                    (v for v in reversed(h["lag"]) if v is not None), None
                )
                qtxt = "-" if last_qps is None else f"{last_qps:.1f}"
                ltxt = "-" if last_lag is None else f"{last_lag:.3f}"
                lines.append(
                    f"{s.url}  qps {_spark(h['qps'])} {qtxt}  "
                    f"lag_s {_spark(h['lag'])} {ltxt}"
                )
                tenants = sorted(
                    set(shed_rates.get(s.url, {}))
                    | set(quota_util.get(s.url, {}))
                )
                if tenants:
                    cells = []
                    for t in tenants:
                        rate = shed_rates.get(s.url, {}).get(t)
                        util = quota_util.get(s.url, {}).get(t)
                        rtxt = "-" if rate is None else f"{rate:.1f}"
                        utxt = "-" if util is None else f"{util:.2f}"
                        cells.append(f"{t} shed/s {rtxt} quota {utxt}")
                    lines.append("  tenants: " + "; ".join(cells))
            lines.append(
                f"burn (worst finite)  {_spark(burn_hist)} "
                f"{burn_hist[-1]:.3g}"
            )
            if dumps:
                uniq = sorted(set(dumps), reverse=True)[:5]
                lines.append("flight dumps: " + ", ".join(uniq))
            frames += 1
            if args.once:
                print("\n".join(lines))
                return EXIT_OK
            sys.stdout.write("\x1b[2J\x1b[H" + "\n".join(lines) + "\n")
            sys.stdout.flush()
            if args.frames and frames >= args.frames:
                return EXIT_OK
            _time.sleep(args.interval)
    except KeyboardInterrupt:
        return EXIT_OK


def cmd_backends(_args) -> int:
    import kubernetes_verification_tpu as kv

    for name in kv.available_backends():
        print(name)
    return 0


def cmd_metrics(args) -> int:
    from .observe import dump_registry, to_prometheus

    if args.file:
        if args.format == "prom":
            raise SystemExit(
                "--format prom renders the live registry; saved dumps are "
                "JSON — point --metrics-out at a .prom path to get "
                "Prometheus text directly"
            )
        with open(args.file) as fh:
            print(json.dumps(json.load(fh), indent=2, sort_keys=True))
        return 0
    # live registry: freshly-started process, so values are zero — this is
    # the metric-name/label schema reference (all families register at
    # import time)
    if args.format == "prom":
        print(to_prometheus(), end="")
    else:
        print(
            json.dumps(
                dump_registry(include_buckets=False), indent=2, sort_keys=True
            )
        )
    return 0


def cmd_lint(args) -> int:
    """``kv-tpu lint``: the analysis framework's driver behind the shared
    KvTpuError → exit-code contract (a bad --rules id is exit 2, like any
    other input error)."""
    from .analysis import run_from_args
    from .resilience.errors import KvTpuError

    try:
        return run_from_args(args)
    except KvTpuError as e:
        return _diagnose(args, e)


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(prog="kv-tpu", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("verify", help="verify manifests under PATH")
    p.add_argument("path")
    _add_verify_flags(p)
    _add_obs_flags(p)
    p.set_defaults(fn=cmd_verify)

    p = sub.add_parser(
        "snapshot",
        help="build a packed incremental verifier from manifests and "
        "checkpoint it",
    )
    p.add_argument("path", help="manifest file/dir")
    p.add_argument("dir", help="checkpoint directory to write")
    p.add_argument(
        "--no-ports", dest="ports", action="store_false",
        help="any-port engine (default: port-bitmap engine)",
    )
    p.add_argument("--no-self-traffic", dest="self_traffic", action="store_false")
    p.add_argument("--no-default-allow", dest="default_allow", action="store_false")
    p.add_argument(
        "--headroom", type=int, default=8,
        help="free VP rows per port segment (ports engine)",
    )
    p.add_argument(
        "--pod-headroom", type=int, default=0,
        help="extra pod slots for add_pod without a grow",
    )
    p.add_argument(
        "--closure", action="store_true",
        help="also compute the packed transitive closure and persist it; "
        "later `kv-tpu diff` runs maintain it incrementally "
        "(packed_closure_delta) instead of re-closing from scratch",
    )
    p.add_argument("--json", action="store_true")
    p.add_argument("--opt", action="append", default=[], metavar="KEY=VALUE")
    p.set_defaults(fn=cmd_snapshot)

    p = sub.add_parser(
        "diff",
        help="apply pod/policy diffs to a checkpointed verifier and save",
    )
    p.add_argument("dir", help="checkpoint directory (from kv-tpu snapshot)")
    p.add_argument(
        "--apply", action="append", default=[], metavar="PATH",
        help="YAML manifests to add/update (repeatable); existing pods "
        "relabel in place, existing policies update",
    )
    p.add_argument(
        "--remove", action="append", default=[], metavar="KIND/NS/NAME",
        help="remove a pod, policy or (emptied) namespace, e.g. --remove "
        "pod/prod/web-1 --remove policy/prod/allow-http --remove "
        "namespace/prod (repeatable, applied in order)",
    )
    p.add_argument("--out", help="save to a different directory")
    p.add_argument(
        "--no-save", action="store_true",
        help="apply + report only; leave the checkpoint untouched",
    )
    p.add_argument("--json", action="store_true")
    p.add_argument("--opt", action="append", default=[], metavar="KEY=VALUE")
    _add_obs_flags(p)
    p.set_defaults(fn=cmd_diff)

    p = sub.add_parser(
        "explain",
        help="export encoded model + Datalog program (PATH), or print a "
        "per-kernel cost/memory table (--pods/--backend)",
    )
    p.add_argument("path", nargs="?")
    p.add_argument("--out", default="model")
    p.add_argument("--no-ports", dest="ports", action="store_false")
    p.add_argument(
        "--pods", type=int, default=None,
        help="cost mode: synthesize a cluster of this many pods and report "
        "per-kernel FLOPs/bytes/peak memory (runs fine under "
        "JAX_PLATFORMS=cpu)",
    )
    p.add_argument("--policies", type=int, default=8)
    p.add_argument("--namespaces", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--backend", default=None,
        help="cost mode: backend to introspect (default cpu)",
    )
    p.add_argument(
        "--roofline", action="store_true",
        help="print achieved MACs/s as %% of device peak per recorded "
        "bench mode (published v5e/v5p/v4/v6e peak table; "
        "sentinel-calibrated or analytic fallback on hosts); reads the "
        "bench history (PATH overrides the default file)",
    )
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_explain)

    p = sub.add_parser(
        "history",
        help="show the bench-history trajectory and the regression gate "
        "verdict (exit 1 on a regression)",
    )
    p.add_argument(
        "paths", nargs="*",
        help="history files (default: bench_history.jsonl, else the "
        "committed BENCH_r*.json snapshots)",
    )
    p.add_argument(
        "--tolerance", type=float, default=0.25,
        help="relative slip vs. the trailing median before flagging "
        "(default 0.25)",
    )
    p.add_argument(
        "--window", type=int, default=5,
        help="trailing runs the median is taken over (default 5)",
    )
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_history)

    p = sub.add_parser("generate", help="write a synthetic cluster as YAML")
    p.add_argument("dir")
    p.add_argument("--pods", type=int, default=100)
    p.add_argument("--policies", type=int, default=50)
    p.add_argument("--namespaces", type=int, default=5)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--events-out", metavar="FILE",
        help="also write a churn event stream (JSONL) valid against the "
        "generated cluster, for kv-tpu serve / bench.py --mode serve",
    )
    p.add_argument(
        "--n-events", type=int, default=500,
        help="events in the churn stream (with --events-out)",
    )
    p.add_argument(
        "--resync-rate", type=float, default=0.0,
        help="per-event probability of a full_resync relist in the stream",
    )
    p.set_defaults(fn=cmd_generate)

    p = sub.add_parser(
        "serve",
        help="continuous verification: apply a mutation-event stream to an "
        "incremental engine, check assertions, answer with exit codes",
    )
    p.add_argument("path", nargs="?", help="manifest file/dir (cold start)")
    p.add_argument(
        "--from-snapshot", metavar="DIR",
        help="warm restart from a serve snapshot instead of manifests "
        "(dense or packed — detected from the snapshot contents)",
    )
    p.add_argument(
        "--warm-pack", metavar="DIR",
        help="AOT executable pack to install before the engine is built "
        "(default: the aot-pack directory auto-detected next to "
        "--from-snapshot); see kv-tpu warmup",
    )
    p.add_argument(
        "--events", metavar="FILE",
        help="JSONL mutation-event stream to apply (see kv-tpu generate "
        "--events-out for the schema)",
    )
    p.add_argument(
        "--tail", action="store_true",
        help="keep polling --events for appended lines instead of one "
        "replay pass",
    )
    p.add_argument(
        "--idle-timeout", type=float, default=1.0, metavar="SECONDS",
        help="with --tail / --follow: stop after this long with no "
        "stream growth",
    )
    p.add_argument(
        "--tail-poll", type=float, default=0.05, metavar="SECONDS",
        help="base WAL poll interval while tailing; backs off "
        "exponentially (up to ~32x, capped at 1s) while the stream is "
        "idle and snaps back on growth",
    )
    p.add_argument(
        "--follow", metavar="DIR",
        help="run as a read-only follower replica of the leader whose "
        "checkpoints live in DIR: bootstrap from the newest valid "
        "generation, tail its WAL (--events overrides the manifest's "
        "log path), answer queries under the --staleness bound",
    )
    p.add_argument(
        "--stripe", metavar="K/N",
        help="run as stripe owner K of N (1-based): own only this "
        "contiguous pod-row stripe of the count state, bootstrap from "
        "manifests or a stripe-sliced checkpoint (--resume), and tail "
        "--events applying every mutation (cross-stripe effects fan "
        "out by design and are counted, never filtered)",
    )
    p.add_argument(
        "--replica", default="follower", metavar="NAME",
        help="with --follow / --stripe: this replica's name (lag "
        "gauges, lease holder on promotion; default for --stripe: "
        "stripe-K-of-N)",
    )
    p.add_argument(
        "--leader", metavar="URL",
        help="with --follow: the leader lives on another host — "
        "bootstrap its checkpoint over HTTP from the replication "
        "server at URL into the --follow directory and tail its WAL "
        "into a local byte mirror (--events then names the mirror "
        "file; default wal-mirror.jsonl inside the directory)",
    )
    p.add_argument(
        "--proxy-stale", action="store_true",
        help="with --follow: answer over-bound reads with leader-fresh "
        "state instead of raising StaleReadError",
    )
    p.add_argument(
        "--promote-on-lease-expiry", action="store_true",
        help="with --follow: promote to leader when the leader.lease "
        "expires AND the leader-probe breaker opens (fencing the old "
        "leader via the lease epoch)",
    )
    p.add_argument(
        "--lease-ttl", type=float, default=5.0, metavar="SECONDS",
        help="with --follow: lease time-to-live used when judging "
        "leader liveness and when renewing after a promotion",
    )
    p.add_argument(
        "--assert", dest="assert_file", metavar="FILE",
        help="declarative allow/deny assertion file (JSON), re-checked "
        "after every applied batch; violations exit 1 with a pod-pair "
        "witness",
    )
    p.add_argument(
        "--staleness", type=float, default=None, metavar="SECONDS",
        help="solve when applied-but-unsolved mutations age past this "
        "bound (default: fully lazy — solve on query/assertions only)",
    )
    p.add_argument(
        "--batch-size", type=int, default=256,
        help="max events coalesced into one engine batch",
    )
    p.add_argument(
        "--snapshot-out", metavar="DIR",
        help="snapshot the warm engine state here on exit (and every "
        "--snapshot-every batches)",
    )
    p.add_argument(
        "--snapshot-every", type=int, default=0, metavar="N",
        help="with --snapshot-out: also snapshot every N applied batches",
    )
    p.add_argument(
        "--checkpoint-dir", metavar="DIR",
        help="write atomic crash-safe checkpoints (engine snapshot + "
        "manifest binding the event-log offset) here; one is always "
        "taken on exit",
    )
    p.add_argument(
        "--checkpoint-every", type=int, default=0, metavar="N",
        help="with --checkpoint-dir: also checkpoint every N applied "
        "batches (0 = exit only)",
    )
    p.add_argument(
        "--resume", action="store_true",
        help="recover from the newest valid checkpoint in "
        "--checkpoint-dir (falling back to older generations on "
        "corruption) and replay --events past the recorded offset; "
        "PATH, if given, enables a from-scratch rebuild when every "
        "generation is damaged",
    )
    p.add_argument(
        "--posture", action="store_true",
        help="enable the posture observability plane: record the exact "
        "reachability delta (widened/narrowed pairs, per-namespace "
        "movement, top-k witnesses) for every applied batch",
    )
    p.add_argument(
        "--posture-journal", metavar="FILE",
        help="append each posture record to this crc'd JSONL journal "
        "(read back with kv-tpu posture); implies --posture",
    )
    p.add_argument(
        "--posture-alert", action="append", default=[], metavar="RULE",
        help="posture drift alert rule, repeatable — 'deny ns:SRC -> "
        "ns:DST', 'max-widening N pairs/batch' or 'max-narrowing N "
        "pairs/batch'; violations exit 1, increment "
        "kvtpu_posture_alert_violations_total and flight-record the "
        "offending delta; implies --posture",
    )
    p.add_argument(
        "--posture-top-k", type=int, default=None, metavar="K",
        help="most-changed source rows decoded into witnesses per "
        "record (default 8; every extraction stays capped)",
    )
    p.add_argument("--no-self-traffic", dest="self_traffic", action="store_false")
    p.add_argument("--no-default-allow", dest="default_allow", action="store_false")
    p.add_argument("--json", action="store_true")
    _add_obs_flags(p)
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser(
        "recover",
        help="inspect a serve checkpoint directory: per-generation "
        "manifest/snapshot health and the event log's valid prefix "
        "(read-only; exit 2 when nothing is recoverable)",
    )
    p.add_argument("dir", help="a kv-tpu serve --checkpoint-dir directory")
    p.add_argument(
        "--events", metavar="FILE",
        help="also scan this event log (WAL) without repairing it",
    )
    p.add_argument("--json", action="store_true")
    _add_obs_flags(p)
    p.set_defaults(fn=cmd_recover)

    p = sub.add_parser(
        "warmup",
        help="pre-populate a warm executable pack (AOT kernel cache) for "
        "a config: build the engine, drive the representative kernels, "
        "and persist serialized executables for serve/query "
        "--from-snapshot and checkpoint recovery to ride",
    )
    p.add_argument("path", nargs="?", help="manifest file/dir")
    p.add_argument(
        "--from-snapshot", metavar="DIR",
        help="warm up against a serve snapshot instead of manifests "
        "(records the exact shapes that snapshot serves)",
    )
    p.add_argument(
        "--out", required=True, metavar="DIR",
        help="pack directory to write — point it at "
        "CHECKPOINT_DIR/aot-pack to pre-warm a checkpoint directory",
    )
    p.add_argument(
        "--warm-pack", metavar="DIR",
        help="existing pack to install first (the written pack then "
        "extends it incrementally)",
    )
    p.add_argument("--no-self-traffic", dest="self_traffic", action="store_false")
    p.add_argument("--no-default-allow", dest="default_allow", action="store_false")
    p.add_argument("--json", action="store_true")
    _add_obs_flags(p)
    p.set_defaults(fn=cmd_warmup)

    p = sub.add_parser(
        "query",
        help="one-shot queries against a cluster or serve snapshot: "
        "can-reach (scalar or --batch JSONL) / who-can-reach / "
        "blast-radius / path-exists & hops (bounded closure) / "
        "what-if admission",
    )
    p.add_argument("path", nargs="?", help="manifest file/dir")
    p.add_argument(
        "--from-snapshot", metavar="DIR",
        help="query a serve snapshot instead of manifests; the engine "
        "kind is auto-detected, and a packed (bitmap-state) snapshot "
        "answers --batch from device-resident uint32 word rows without "
        "materialising the dense reach matrix",
    )
    p.add_argument(
        "--warm-pack", metavar="DIR",
        help="AOT executable pack to install before the engine is built "
        "(default: the aot-pack directory auto-detected next to "
        "--from-snapshot); see kv-tpu warmup",
    )
    p.add_argument(
        "--can-reach", nargs=2, metavar=("SRC", "DST"),
        help="pod pair as NAMESPACE/NAME NAMESPACE/NAME",
    )
    p.add_argument(
        "--port", type=int, default=None,
        help="with --can-reach: refine to a concrete port (CPU-oracle "
        "exact answer)",
    )
    p.add_argument("--protocol", default="TCP", help="with --port")
    p.add_argument(
        "--batch", metavar="FILE.jsonl",
        help="answer a whole probe batch through one device dispatch: one "
        'JSON object per line, {"src": "NS/POD", "dst": "NS/POD"} with '
        'optional "port" (integer; omitted = any port) and "protocol" '
        "(default TCP)",
    )
    p.add_argument("--who-can-reach", metavar="DST")
    p.add_argument("--blast-radius", metavar="SRC")
    p.add_argument(
        "--path-exists", nargs=2, metavar=("SRC", "DST"),
        help="is there a multi-hop path SRC -> ... -> DST? Rides the "
        "bounded multi-source closure — per level one [1, N] frontier, "
        "never an N x N closure, so it answers at matrix-free scale",
    )
    p.add_argument(
        "--hops", nargs=2, metavar=("SRC", "DST"),
        help="shortest allowed-path hop count SRC -> DST (1 = direct "
        "edge; exit text says UNREACHABLE when there is none)",
    )
    p.add_argument(
        "--max-hops", type=int, default=None, metavar="H",
        help="with --path-exists/--hops: bound the search to paths of at "
        "most H edges (default: unbounded)",
    )
    p.add_argument(
        "--what-if", metavar="MANIFESTS",
        help="admission dry run: would adding these NetworkPolicy "
        "manifests violate the --assert file? (exit 1 if so; nothing "
        "is committed)",
    )
    p.add_argument(
        "--assert", dest="assert_file", metavar="FILE",
        help="assertion file checked against the current state (or the "
        "what-if overlay)",
    )
    p.add_argument("--no-self-traffic", dest="self_traffic", action="store_false")
    p.add_argument("--no-default-allow", dest="default_allow", action="store_false")
    p.add_argument("--json", action="store_true")
    _add_obs_flags(p)
    p.set_defaults(fn=cmd_query)

    p = sub.add_parser(
        "lb",
        help="spread --batch probe files across follower replicas by "
        "staleness-weighted routing: stale reads retry on the leader, "
        "unreachable replicas are breaker-ejected",
    )
    p.add_argument(
        "--replica", action="append", default=[], metavar="DIR[=URL]",
        help="a follower's checkpoint directory (repeatable); DIR=URL "
        "bootstraps a networked follower over HTTP from the replication "
        "server at URL into DIR",
    )
    p.add_argument(
        "--leader", metavar="DIR",
        help="the leader's checkpoint directory — stale-read retry and "
        "last-resort fallback (without it, an over-bound replica's "
        "StaleReadError propagates and a fully-ejected fleet exits 4)",
    )
    p.add_argument(
        "--batch", action="append", default=[], required=True,
        metavar="FILE.jsonl",
        help="probe batch to route (repeatable; one batch = one routing "
        "decision); same JSONL schema as kv-tpu query --batch",
    )
    p.add_argument(
        "--events", metavar="FILE",
        help="override the WAL the replicas tail (default: the path the "
        "checkpoint manifest records)",
    )
    p.add_argument(
        "--staleness", type=float, default=None, metavar="SECONDS",
        help="per-replica staleness bound (default: unbounded)",
    )
    p.add_argument("--seed", type=int, default=0, help="routing-draw seed")
    p.add_argument(
        "--check-denied", action="store_true",
        help="exit 1 when any probe is denied",
    )
    p.add_argument("--json", action="store_true")
    _add_obs_flags(p)
    p.set_defaults(fn=cmd_lb)

    p = sub.add_parser(
        "trace",
        help="reassemble one trace id's cross-process timeline from "
        "per-replica JSON event logs: span tree, per-log attribution, "
        "query stage breakdown (queue/dispatch/solve/d2h)",
    )
    p.add_argument(
        "trace_id", nargs="?", default=None,
        help="the trace id to reassemble (16-hex, from any event line or "
        "an X-Kvtpu-Trace header); omit with --slowest",
    )
    p.add_argument(
        "--log", action="append", default=[], required=True, metavar="FILE",
        help="a JSON event log to scan (repeatable — one per "
        "process/replica; duplicated spans from shared logs render once)",
    )
    p.add_argument(
        "--slowest", action="store_true",
        help="pick the trace id from the highest-valued latency exemplar "
        "in --metrics instead of naming one",
    )
    p.add_argument(
        "--stage", metavar="STAGE",
        help="with --slowest: only consider exemplars whose stage label "
        "matches (queue/dispatch/solve/d2h/total)",
    )
    p.add_argument(
        "--metrics", action="append", default=[], metavar="URL|FILE",
        help="exemplar source for --slowest: a replica base URL (fetches "
        "/metrics?exemplars=1) or a saved metrics text file (repeatable)",
    )
    p.add_argument("--json", action="store_true")
    _add_obs_flags(p)
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser(
        "fleet",
        help="scrape every replica's /healthz + /metrics, render the "
        "fleet table, and evaluate SLO error-budget burn rates "
        "(exit 1 past --burn-threshold)",
    )
    p.add_argument(
        "--replica", action="append", default=[], required=True,
        metavar="URL",
        help="a replication server base URL, e.g. http://127.0.0.1:8700 "
        "(repeatable)",
    )
    p.add_argument(
        "--slo", action="append", default=[], metavar="SPEC",
        help="objective spec: availability=0.999 or staleness=0.995@2.0 "
        "(repeatable; default availability=0.999)",
    )
    p.add_argument(
        "--burn-threshold", type=float, default=1.0,
        help="exit 1 when any objective x window burn rate exceeds this "
        "(1.0 = consuming error budget exactly at the sustainable rate)",
    )
    p.add_argument(
        "--timeout", type=float, default=5.0,
        help="per-replica scrape timeout (seconds)",
    )
    p.add_argument("--json", action="store_true")
    _add_obs_flags(p)
    p.set_defaults(fn=cmd_fleet)

    p = sub.add_parser(
        "posture",
        help="read a posture journal: reachability-drift timeline per "
        "generation, --watch tailing, --diff between two generations "
        "(exit 1 when rendered records carry alert violations)",
    )
    p.add_argument(
        "journal",
        help="posture journal file (posture.jsonl) or a directory "
        "containing one (e.g. the serve --posture-journal target)",
    )
    p.add_argument(
        "--limit", type=int, default=20, metavar="N",
        help="timeline: render the last N records (default 20)",
    )
    p.add_argument(
        "--diff", nargs=2, type=int, metavar=("GEN_A", "GEN_B"),
        help="aggregate the exact posture movement between two "
        "generations (net widened/narrowed, namespace movement, "
        "witnesses)",
    )
    p.add_argument(
        "--watch", action="store_true",
        help="tail the journal, rendering each new record as it lands",
    )
    p.add_argument(
        "--poll", type=float, default=0.5, metavar="SECONDS",
        help="with --watch: journal poll interval",
    )
    p.add_argument(
        "--idle-timeout", type=float, default=None, metavar="SECONDS",
        help="with --watch: stop after this long with no new records "
        "(default: run until interrupted)",
    )
    p.add_argument("--json", action="store_true")
    _add_obs_flags(p)
    p.set_defaults(fn=cmd_posture)

    p = sub.add_parser(
        "jobs",
        help="merge every replica's in-flight long-job progress table "
        "(pass counters, rates, ETAs) from /healthz into one view",
    )
    p.add_argument(
        "--replica", action="append", default=[], required=True,
        metavar="URL",
        help="a replication server base URL (repeatable)",
    )
    p.add_argument(
        "--timeout", type=float, default=5.0,
        help="per-replica scrape timeout (seconds)",
    )
    p.add_argument("--json", action="store_true")
    _add_obs_flags(p)
    p.set_defaults(fn=cmd_jobs)

    p = sub.add_parser(
        "profile",
        help="trigger a bounded on-demand jax.profiler capture — on a "
        "running replica (--replica, no restart) or in this process",
    )
    p.add_argument(
        "--replica", metavar="URL",
        help="capture on this replication server via /profile?seconds=N "
        "(default: capture locally)",
    )
    p.add_argument(
        "--seconds", type=float, default=2.0,
        help="capture duration (clamped to 0.01..60)",
    )
    p.add_argument(
        "--dir", metavar="DIR",
        help="local capture directory (default: $KVTPU_PROFILE_DIR or "
        "kvtpu-profiles/)",
    )
    p.add_argument(
        "--timeout", type=float, default=5.0,
        help="HTTP timeout floor for --replica (raised to cover --seconds)",
    )
    p.add_argument("--json", action="store_true")
    _add_obs_flags(p)
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser(
        "top",
        help="live fleet dashboard: replica table, in-flight jobs with "
        "ETA bars, qps/lag/burn sparklines, recent flight dumps",
    )
    p.add_argument(
        "--replica", action="append", default=[], required=True,
        metavar="URL",
        help="a replication server base URL (repeatable)",
    )
    p.add_argument(
        "--interval", type=float, default=2.0,
        help="refresh period in live mode (seconds)",
    )
    p.add_argument(
        "--once", action="store_true",
        help="render one frame to stdout (no screen clearing) and exit",
    )
    p.add_argument(
        "--frames", type=int, default=0, metavar="N",
        help="stop after N live frames (0 = run until interrupted)",
    )
    p.add_argument(
        "--slo", action="append", default=[], metavar="SPEC",
        help="objective spec for the burn sparkline (as in kv-tpu fleet; "
        "default availability=0.999)",
    )
    p.add_argument(
        "--timeout", type=float, default=5.0,
        help="per-replica scrape timeout (seconds)",
    )
    _add_obs_flags(p)
    p.set_defaults(fn=cmd_top)

    p = sub.add_parser("backends", help="list available backends")
    p.set_defaults(fn=cmd_backends)

    p = sub.add_parser(
        "metrics",
        help="print the metric schema (live registry) or a saved "
        "--metrics-out dump",
    )
    p.add_argument("file", nargs="?", help="a saved --metrics-out JSON dump")
    p.add_argument(
        "--format", choices=("json", "prom"), default="json",
        help="live-registry output format",
    )
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser(
        "lint",
        help="run the flow-aware static analysis over the package "
        "(rule catalog: LINTS.md; budgets: LINT_BASELINE.json)",
    )
    from .analysis import add_lint_arguments

    add_lint_arguments(p)
    p.set_defaults(fn=cmd_lint)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
