"""``kv-tpu`` — command-line front end.

The reference has no CLI at all (both verifiers are driven by unit tests
only, SURVEY.md §1); this exposes the full pipeline:

* ``kv-tpu verify PATH``   — load manifests, verify, print queries/summary;
* ``kv-tpu explain PATH``  — export the encoded tensors + the Datalog
  program text (the ``get_datalog`` facility, ``kubesv/kubesv/
  constraint.py:127-128``, for both representations);
* ``kv-tpu generate DIR``  — write a synthetic cluster as YAML manifests;
* ``kv-tpu backends``      — list available execution backends.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Optional


def _add_verify_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--backend", default="cpu")
    p.add_argument("--closure", action="store_true")
    p.add_argument("--no-ports", dest="ports", action="store_false")
    p.add_argument("--no-self-traffic", dest="self_traffic", action="store_false")
    p.add_argument(
        "--no-default-allow", dest="default_allow", action="store_false",
        help="reproduce the reference's unselected-pods-unreachable behaviour",
    )
    p.add_argument("--kano", action="store_true", help="kano-level semantics")
    p.add_argument("--output", help="save the VerifyResult as .npz")
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument(
        "--opt", action="append", default=[], metavar="KEY=VALUE",
        help="backend option (repeatable), e.g. --opt mesh=4,2 "
        "--opt tile=512 --opt keep_matrix=true for sharded-packed",
    )


#: options whose values must be integers (string fallthrough would surface
#: as a confusing type error deep in the backend, after the solve)
_INT_OPTS = frozenset(
    {"tile", "chunk", "dense_reach_limit", "max_port_masks", "closure_tile"}
)


def _parse_opt(kv_str: str):
    key, sep, raw = kv_str.partition("=")
    if not sep or not key:
        raise SystemExit(f"--opt expects KEY=VALUE, got {kv_str!r}")
    low = raw.lower()
    if low in ("true", "false"):
        return key, low == "true"
    if "," in raw:
        try:
            return key, tuple(int(x) for x in raw.split(","))
        except ValueError:
            raise SystemExit(
                f"--opt {key}: comma lists must be integers, got {raw!r}"
            )
    try:
        return key, int(raw)
    except ValueError:
        if key in _INT_OPTS:
            # numeric option but not an int (2e4, 1.5) — fail at parse time
            # instead of as a type error deep in the backend post-solve
            raise SystemExit(
                f"--opt {key}: expected an integer, got {raw!r}"
            )
        return key, raw  # string-valued options (e.g. groups_label=3tier)


def cmd_verify(args) -> int:
    import kubernetes_verification_tpu as kv

    cfg = kv.VerifyConfig(
        backend=args.backend,
        closure=args.closure,
        compute_ports=args.ports,
        self_traffic=args.self_traffic,
        default_allow_unselected=args.default_allow,
        backend_options=tuple(_parse_opt(o) for o in args.opt),
    )
    if args.kano:
        containers, policies = kv.load_kano(args.path)
        res = kv.verify_kano(containers, policies, cfg)
        pods = containers
        skipped = []
    else:
        cluster, skipped = kv.load_cluster(args.path)
        if (
            args.output
            and cfg.backend == "sharded-packed"
            and cluster.n_pods > cfg.opt("dense_reach_limit", 20_000)
        ):
            # fail BEFORE the (potentially hours-long) solve: --output saves
            # a dense VerifyResult, which this scale never materialises
            raise SystemExit(
                f"--output saves a dense VerifyResult but {cluster.n_pods} "
                "pods exceeds dense_reach_limit "
                f"({cfg.opt('dense_reach_limit', 20_000)}); raise --opt "
                "dense_reach_limit=N or drop --output"
            )
        res = kv.verify(cluster, cfg)
        pods = cluster.pods
    iso = res.all_isolated()
    hubs = res.all_reachable()
    if res.reach is not None:
        pairs = int(res.reach.sum())
    else:  # sharded-packed above the dense-reach limit: use the aggregates
        pairs = int(res.packed_result.total_pairs)
    out = {
        "pods": res.n_pods,
        "backend": res.backend,
        "mode": res.mode,
        "reachable_pairs": pairs,
        "all_isolated": iso,
        "all_reachable": hubs,
        "policy_shadow": (
            res.policy_shadow() if res.src_sets is not None else None
        ),
        "policy_conflict": (
            res.policy_conflict() if res.src_sets is not None else None
        ),
        "timings": res.timings,
        "skipped_documents": skipped,
    }
    if args.output:
        if res.reach is None:  # safety net; print the summary before exiting
            print(json.dumps(out))
            raise SystemExit(
                "--output saves a dense VerifyResult; this solve kept only "
                "the packed matrix/aggregates (raise --opt "
                "dense_reach_limit=N or use save_packed on packed_result)"
            )
        from .utils.persist import save_result

        save_result(res, args.output)
        out["saved"] = args.output
    if args.json:
        print(json.dumps(out))
    else:
        name = lambda i: getattr(pods[i], "name", str(i))
        print(f"{res.n_pods} pods verified on backend={res.backend} "
              f"({res.mode} mode): {out['reachable_pairs']} reachable pairs")
        print(f"  fully isolated pods: {[name(i) for i in iso] or 'none'}")
        print(f"  reachable-from-everywhere pods: {[name(i) for i in hubs] or 'none'}")
        if out["policy_shadow"]:
            print(f"  shadowed policy pairs: {out['policy_shadow']}")
        if out["policy_conflict"]:
            print(f"  conflicting policy pairs: {out['policy_conflict']}")
        for k, v in res.timings.items():
            print(f"  {k}: {v * 1e3:.1f} ms")
        if skipped:
            print(f"  skipped {len(skipped)} non-verifiable documents")
    return 0


def cmd_explain(args) -> int:
    import kubernetes_verification_tpu as kv
    from .datalog import build_k8s_program
    from .encode.encoder import encode_cluster
    from .utils.persist import export_encoding

    cluster, _ = kv.load_cluster(args.path)
    txt = export_encoding(
        encode_cluster(cluster, compute_ports=args.ports), args.out
    )
    prog, _, _atoms = build_k8s_program(cluster, kv.VerifyConfig())
    dl = args.out + ".datalog"
    with open(dl, "w") as fh:
        fh.write(prog.dump() + "\n")
    print(open(txt).read().rstrip())
    print(f"wrote {args.out}.npz, {txt}, {dl}")
    return 0


def cmd_generate(args) -> int:
    from .harness.generate import GeneratorConfig, random_cluster
    from .ingest import dump_cluster

    cluster = random_cluster(
        GeneratorConfig(
            n_pods=args.pods,
            n_policies=args.policies,
            n_namespaces=args.namespaces,
            seed=args.seed,
        )
    )
    paths = dump_cluster(cluster, args.dir)
    print(f"wrote {len(cluster.pods)} pods / {len(cluster.policies)} policies "
          f"to {', '.join(paths)}")
    return 0


def cmd_backends(_args) -> int:
    import kubernetes_verification_tpu as kv

    for name in kv.available_backends():
        print(name)
    return 0


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(prog="kv-tpu", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("verify", help="verify manifests under PATH")
    p.add_argument("path")
    _add_verify_flags(p)
    p.set_defaults(fn=cmd_verify)

    p = sub.add_parser("explain", help="export encoded model + Datalog program")
    p.add_argument("path")
    p.add_argument("--out", default="model")
    p.add_argument("--no-ports", dest="ports", action="store_false")
    p.set_defaults(fn=cmd_explain)

    p = sub.add_parser("generate", help="write a synthetic cluster as YAML")
    p.add_argument("dir")
    p.add_argument("--pods", type=int, default=100)
    p.add_argument("--policies", type=int, default=50)
    p.add_argument("--namespaces", type=int, default=5)
    p.add_argument("--seed", type=int, default=0)
    p.set_defaults(fn=cmd_generate)

    p = sub.add_parser("backends", help="list available backends")
    p.set_defaults(fn=cmd_backends)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
