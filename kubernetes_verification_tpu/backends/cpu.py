"""Object-level NumPy reference backend — the semantics oracle.

This backend interprets the model objects directly (per-pod/per-policy Python
loops + NumPy outer products), deliberately sharing no code with the tensorised
encoder/kernels so differential tests between the two are meaningful. It plays
the role of both reference verifiers:

* ``verify_kano`` reproduces the bit-vector matrix build
  (``kano_py/kano/model.py:124-165``) exactly, including the matcher quirk
  that a selector key appearing on *no* container is ignored (the interaction
  of the label-presence bitmap at ``kano_py/kano/model.py:142-147`` with the
  value refinement loop at ``:150-154``).
* ``verify`` implements full NetworkPolicy semantics, the role of the
  Datalog program (``kubesv/kubesv/constraint.py:136-298``), with the
  reference's two semantic flags plus correct policyTypes handling.

"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..encode.ports import ALL_ATOM, compute_port_atoms, rule_port_mask
from ..models.core import (
    Cluster,
    Container,
    KanoPolicy,
    NetworkPolicy,
    Peer,
    Pod,
    Rule,
)
from ..observe import Phases
from ..observe.introspect import publish_host_estimate
from ..observe.metrics import BYTES_TRANSFERRED, CLOSURE_ITERATIONS
from .base import (
    VerifierBackend,
    VerifyConfig,
    VerifyResult,
    register_backend,
)

__all__ = ["CpuBackend"]


def _kano_match(
    labels: Dict[str, str],
    rule: Dict[str, str],
    cluster_keys: Set[str],
    relation=None,
) -> bool:
    """kano select/allow semantics: every rule key that exists *somewhere* in
    the cluster must be present on the container with a matching value; rule
    keys unknown to the whole cluster are ignored
    (``kano_py/kano/model.py:142-154``). ``relation`` is the pluggable value
    matcher (``LabelRelation``, ``kano_py/kano/model.py:59-68``); None =
    string equality — the reference's key-presence bitmap semantics mean the
    container must CARRY the key either way, the relation only decides
    whether the values agree."""
    for k, v in rule.items():
        if k not in cluster_keys:
            continue
        if k not in labels:
            return False
        if relation is None:
            if labels[k] != v:
                return False
        elif not relation.match(v, labels[k]):
            return False
    return True


class CpuBackend(VerifierBackend):
    name = "cpu"
    supports_label_relation = True

    # ------------------------------------------------------------------ kano
    def verify_kano(
        self,
        containers: Sequence[Container],
        policies: Sequence[KanoPolicy],
        config: VerifyConfig,
    ) -> VerifyResult:
        n = len(containers)
        ph = Phases()
        with ph("encode"):
            cluster_keys: Set[str] = set()
            for c in containers:
                cluster_keys.update(c.labels)

            reach = np.zeros((n, n), dtype=bool)
            src_sets = np.zeros((len(policies), n), dtype=bool)
            dst_sets = np.zeros((len(policies), n), dtype=bool)

            for c in containers:  # rebuild the per-container policy indices
                c.select_policies.clear()
                c.allow_policies.clear()

        with ph("solve", backend=self.name):
            relation = config.label_relation
            for pi, pol in enumerate(policies):
                for i, c in enumerate(containers):
                    src_sets[pi, i] = _kano_match(
                        c.labels, pol.src_labels, cluster_keys, relation
                    )
                    dst_sets[pi, i] = _kano_match(
                        c.labels, pol.dst_labels, cluster_keys, relation
                    )
                # matrix[src] |= dst_set for every selected src
                # (kano_py/kano/model.py:158-163)
                reach |= np.outer(src_sets[pi], dst_sets[pi])
                for i in range(n):
                    if src_sets[pi, i]:
                        containers[i].select_policies.append(pi)
                    if dst_sets[pi, i]:
                        containers[i].allow_policies.append(pi)

        BYTES_TRANSFERRED.labels(backend=self.name).set(0)  # pure host
        # analytic host estimate (no XLA program to analyse): P selector
        # sweeps over n containers plus P rank-1 outer products into [n,n]
        publish_host_estimate(
            self.name,
            "verify_kano",
            flops=len(policies) * n * (2 + n),
            bytes_accessed=len(policies) * n * n + 2 * len(policies) * n,
            output_bytes=reach.nbytes + src_sets.nbytes + dst_sets.nbytes,
            signature=(n, len(policies)),
        )
        return VerifyResult(
            n_pods=n,
            mode="kano",
            backend=self.name,
            config=config,
            reach=reach,
            src_sets=src_sets,
            dst_sets=dst_sets,
            closure=_transitive_closure(reach) if config.closure else None,
            timings=ph.timings,
        )

    # ------------------------------------------------------------------- k8s
    def verify(self, cluster: Cluster, config: VerifyConfig) -> VerifyResult:
        pods, policies, namespaces = cluster.pods, cluster.policies, cluster.namespaces
        n, P = len(pods), len(policies)
        ns_labels = {ns.name: ns.labels for ns in namespaces}
        ph = Phases()

        with ph("encode"):
            atoms = (
                compute_port_atoms(policies, pods)
                if config.compute_ports
                else [ALL_ATOM]
            )
        Q = len(atoms)

        def rule_dst_ports(rule: Rule) -> np.ndarray:
            """bool [N, Q]: which atoms this rule's ports cover *per
            destination pod* — numeric specs cover their atoms for every
            dst; a named spec covers, for dst d, exactly the atom holding
            the number d's container spec declares under that name (real
            k8s resolution; independent of the encoder's restriction-bank
            mechanism so the differential tests stay meaningful)."""
            pmask = rule_port_mask(rule, atoms)
            out = np.broadcast_to(pmask, (n, Q)).copy()
            for spec in rule.ports or ():
                if not isinstance(spec.port, str):
                    continue
                for d, pod in enumerate(pods):
                    entry = pod.container_ports.get(spec.port)
                    if entry is None or entry[0] != spec.protocol:
                        continue
                    num = int(entry[1])
                    for q, atom in enumerate(atoms):
                        if (
                            atom.name is None
                            and atom.protocol == spec.protocol
                            and atom.lo <= num <= atom.hi
                        ):
                            out[d, q] = True
            return out

        with ph("encode"):
            selected = np.zeros((P, n), dtype=bool)
            for pi, pol in enumerate(policies):
                for i, pod in enumerate(pods):
                    selected[pi, i] = (
                        pod.namespace == pol.namespace
                        and pol.pod_selector.matches(pod.labels)
                    )

        # Direction gating: with direction_aware_isolation=False (reference
        # compat, kubesv never consults policyTypes) every selecting policy
        # isolates AND its rules apply in both directions.
        with ph("compile"):
            affects_in = np.array(
                [
                    pol.affects_ingress if config.direction_aware_isolation else True
                    for pol in policies
                ],
                dtype=bool,
            )
            affects_eg = np.array(
                [
                    pol.affects_egress if config.direction_aware_isolation else True
                    for pol in policies
                ],
                dtype=bool,
            )
            ing_iso = np.zeros(n, dtype=bool)
            eg_iso = np.zeros(n, dtype=bool)
            for pi in range(P):
                if affects_in[pi]:
                    ing_iso |= selected[pi]
                if affects_eg[pi]:
                    eg_iso |= selected[pi]

        def peer_match(peer: Peer, pol: NetworkPolicy) -> np.ndarray:
            """bool[N]: pods this peer matches (see Peer docstring)."""
            out = np.zeros(n, dtype=bool)
            for i, pod in enumerate(pods):
                if peer.ip_block is not None:
                    out[i] = peer.ip_block.matches_ip(pod.ip)
                    continue
                if peer.namespace_selector is None:
                    ns_ok = pod.namespace == pol.namespace
                else:
                    ns_ok = peer.namespace_selector.matches(
                        ns_labels.get(pod.namespace, {})
                    )
                pod_ok = peer.pod_selector is None or peer.pod_selector.matches(
                    pod.labels
                )
                out[i] = ns_ok and pod_ok
            return out

        def rule_peer_set(rule: Rule, pol: NetworkPolicy) -> np.ndarray:
            if rule.matches_all_peers:
                return np.ones(n, dtype=bool)
            acc = np.zeros(n, dtype=bool)
            for peer in rule.peers:
                acc |= peer_match(peer, pol)
            return acc

        # Single pass over rules: compute each rule's peer set once and use it
        # both for the allow tensors and the per-policy src/dst edge sets.
        with ph("solve", backend=self.name):
            ingress_allow = np.zeros((n, n, Q), dtype=bool)
            egress_allow = np.zeros((n, n, Q), dtype=bool)
            src_sets = np.zeros((P, n), dtype=bool)
            dst_sets = np.zeros((P, n), dtype=bool)
            for pi, pol in enumerate(policies):
                tgt = selected[pi]
                if affects_in[pi] and pol.ingress:
                    for rule in pol.ingress:
                        srcs = rule_peer_set(rule, pol)
                        dmask = rule_dst_ports(rule)  # [N, Q], dst = selected
                        ingress_allow |= (
                            srcs[:, None, None] & (tgt[:, None] & dmask)[None, :, :]
                        )
                        src_sets[pi] |= srcs
                    dst_sets[pi] |= tgt
                if affects_eg[pi] and pol.egress:
                    for rule in pol.egress:
                        dsts = rule_peer_set(rule, pol)
                        dmask = rule_dst_ports(rule)  # [N, Q], dst = peers
                        egress_allow |= (
                            tgt[:, None, None] & (dsts[:, None] & dmask)[None, :, :]
                        )
                        dst_sets[pi] |= dsts
                    src_sets[pi] |= tgt

            # default-allow: pods unselected in a direction allow everything in
            # it iff the flag is on (real k8s True; reference's default False,
            # kubesv/kubesv/constraint.py:202-223).
            if config.default_allow_unselected:
                ingress_ok = ingress_allow | ~ing_iso[None, :, None]
                egress_ok = egress_allow | ~eg_iso[:, None, None]
            else:
                ingress_ok = ingress_allow
                egress_ok = egress_allow

            reach_pq = ingress_ok & egress_ok
            if config.self_traffic:
                di = np.arange(n)
                reach_pq[di, di, :] = True
            reach = reach_pq.any(axis=2)

        BYTES_TRANSFERRED.labels(backend=self.name).set(0)  # pure host
        # analytic host estimates, one per phase: selector/peer matching is
        # the "encode" side, rule ORs into the [n,n,Q] allow tensors (then
        # the 3-tensor combine) dominate the "solve" side
        n_rules = sum(
            (len(pol.ingress or ()) if affects_in[pi] else 0)
            + (len(pol.egress or ()) if affects_eg[pi] else 0)
            for pi, pol in enumerate(policies)
        )
        publish_host_estimate(
            self.name,
            "encode_selectors",
            flops=(P + n_rules) * n,
            bytes_accessed=2 * (P + n_rules) * n,
            output_bytes=selected.nbytes,
            signature=(n, P, Q),
        )
        publish_host_estimate(
            self.name,
            "solve_reach",
            flops=(n_rules + 3) * n * n * Q,
            bytes_accessed=2 * (n_rules + 3) * n * n * Q,
            argument_bytes=selected.nbytes,
            output_bytes=reach.nbytes + reach_pq.nbytes,
            temp_bytes=ingress_allow.nbytes + egress_allow.nbytes,
            signature=(n, P, Q),
        )
        return VerifyResult(
            n_pods=n,
            mode="k8s",
            backend=self.name,
            config=config,
            reach=reach,
            reach_ports=reach_pq if config.compute_ports else None,
            port_atoms=list(atoms) if config.compute_ports else [],
            src_sets=src_sets,
            dst_sets=dst_sets,
            selected=selected,
            ingress_isolated=ing_iso,
            egress_isolated=eg_iso,
            closure=_transitive_closure(reach) if config.closure else None,
            timings=ph.timings,
        )


def _transitive_closure(reach: np.ndarray) -> np.ndarray:
    """Boolean transitive closure by repeated squaring — the full-path
    generalisation of the reference's ≤2-hop ``path``
    (``kubesv/kubesv/constraint.py:233-237``)."""
    import math

    from ..observe.progress import ProgressTicker

    closure = reach.copy()
    bound = max(1, math.ceil(math.log2(max(closure.shape[0], 2))))
    with ProgressTicker("cpu_closure", total=bound, unit="pass") as ticker:
        while True:
            CLOSURE_ITERATIONS.inc()
            nxt = closure | (
                (closure.astype(np.int64) @ closure.astype(np.int64)) > 0
            )
            ticker.tick()
            if np.array_equal(nxt, closure):
                return closure
            closure = nxt


register_backend("cpu", CpuBackend)
