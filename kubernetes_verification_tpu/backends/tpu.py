"""Single-device JAX/XLA backend.

Encode on host (one transfer), then run the jitted kernels from ``ops/``:
selector matching, grant contraction and closure all fuse into a handful of
MXU matmuls. Jitted callables are cached per (shape signature, semantic
flags); re-verifying a same-shaped cluster (the incremental path) reuses the
compiled executable.
"""
from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import numpy as np

from ..encode.encoder import encode_cluster, encode_kano
from ..models.core import Cluster, Container, KanoPolicy
from ..observe import DispatchTracker, Phases, tree_nbytes
from ..observe.metrics import BYTES_TRANSFERRED
from ..ops.closure import transitive_closure
from ..ops.reach import k8s_reach, kano_reach
from .base import (
    VerifierBackend,
    VerifyConfig,
    VerifyResult,
    register_backend,
)

__all__ = ["TpuBackend"]

#: jit caches are per-function and process-global, so one tracker per module
_TRACKER = DispatchTracker("tpu")


@partial(jax.jit, static_argnames=("with_closure",))
def _kano_step(pod_kv, src_req, src_imp, dst_req, dst_imp, *, with_closure: bool):
    out = kano_reach(pod_kv, src_req, src_imp, dst_req, dst_imp)
    closure = transitive_closure(out.reach) if with_closure else None
    return out, closure


@partial(jax.jit, static_argnames=("with_closure",))
def _kano_relation_step(pod_kv, pod_key, src_sel, dst_sel, *, with_closure: bool):
    """kano matrix build under a custom LabelRelation: each policy's label
    requirements were re-encoded as acceptable-pair In-masks
    (``encode_kano_relation``), so the pluggable matcher evaluates as the
    standard selector-match MXU contraction."""
    from ..ops.match import match_selectors
    from ..ops.reach import KanoOut, _bool_or_matmul

    src_sets = match_selectors(src_sel, pod_kv, pod_key)
    dst_sets = match_selectors(dst_sel, pod_kv, pod_key)
    reach = _bool_or_matmul(src_sets, dst_sets)
    out = KanoOut(reach=reach, src_sets=src_sets, dst_sets=dst_sets)
    closure = transitive_closure(reach) if with_closure else None
    return out, closure


@partial(
    jax.jit,
    static_argnames=(
        "self_traffic",
        "default_allow_unselected",
        "direction_aware_isolation",
        "with_closure",
    ),
)
def _k8s_step(
    pod_kv,
    pod_key,
    pod_ns,
    ns_kv,
    ns_key,
    pol_sel,
    pol_ns,
    aff_ing,
    aff_eg,
    ingress,
    egress,
    restrict_bank=None,
    *,
    self_traffic: bool,
    default_allow_unselected: bool,
    direction_aware_isolation: bool,
    with_closure: bool,
):
    out = k8s_reach(
        pod_kv,
        pod_key,
        pod_ns,
        ns_kv,
        ns_key,
        pol_sel,
        pol_ns,
        aff_ing,
        aff_eg,
        ingress,
        egress,
        restrict_bank,
        self_traffic=self_traffic,
        default_allow_unselected=default_allow_unselected,
        direction_aware_isolation=direction_aware_isolation,
    )
    closure = transitive_closure(out.reach) if with_closure else None
    return out, closure


class TpuBackend(VerifierBackend):
    name = "tpu"
    supports_label_relation = True

    def verify(self, cluster: Cluster, config: VerifyConfig) -> VerifyResult:
        ph = Phases()
        with ph("encode"):
            enc = encode_cluster(cluster, compute_ports=config.compute_ports)
        flags = (
            config.self_traffic,
            config.default_allow_unselected,
            config.direction_aware_isolation,
            config.closure,
        )
        step_args = (
            enc.pod_kv,
            enc.pod_key,
            enc.pod_ns,
            enc.ns_kv,
            enc.ns_key,
            enc.pol_sel,
            enc.pol_ns,
            enc.pol_affects_ingress,
            enc.pol_affects_egress,
            enc.ingress,
            enc.egress,
            enc.restrict_bank,
        )
        step_kwargs = dict(
            self_traffic=config.self_traffic,
            default_allow_unselected=config.default_allow_unselected,
            direction_aware_isolation=config.direction_aware_isolation,
            with_closure=config.closure,
        )
        _TRACKER.track(
            "_k8s_step",
            enc,
            static=flags,
            lower=lambda: _k8s_step.lower(*step_args, **step_kwargs),
        )
        # "compile" covers the jitted dispatch: trace+compile on a novel
        # signature, cache-hit dispatch otherwise (execution is async)
        with ph("compile", backend=self.name):
            out, closure = _k8s_step(*step_args, **step_kwargs)
        with ph("solve", backend=self.name):
            jax.block_until_ready(out.reach)
        BYTES_TRANSFERRED.labels(backend=self.name).set(
            tree_nbytes(enc) + tree_nbytes(out) + tree_nbytes(closure)
        )
        return VerifyResult(
            n_pods=cluster.n_pods,
            mode="k8s",
            backend=self.name,
            config=config,
            reach=np.asarray(out.reach),
            reach_ports=np.asarray(out.reach_ports) if config.compute_ports else None,
            port_atoms=list(enc.atoms) if config.compute_ports else [],
            src_sets=np.asarray(out.src_sets),
            dst_sets=np.asarray(out.dst_sets),
            selected=np.asarray(out.selected),
            ingress_isolated=np.asarray(out.ingress_isolated),
            egress_isolated=np.asarray(out.egress_isolated),
            closure=np.asarray(closure) if closure is not None else None,
            timings=ph.timings,
        )

    def verify_kano(
        self,
        containers: Sequence[Container],
        policies: Sequence[KanoPolicy],
        config: VerifyConfig,
    ) -> VerifyResult:
        ph = Phases()
        if config.label_relation is not None:
            from ..encode.encoder import encode_kano_relation

            with ph("encode"):
                enc_r = encode_kano_relation(
                    containers, policies, config.label_relation
                )
            step_args = (
                enc_r.pod_kv,
                enc_r.pod_key,
                enc_r.src_sel,
                enc_r.dst_sel,
            )
            _TRACKER.track(
                "_kano_relation_step",
                enc_r,
                static=(config.closure,),
                lower=lambda: _kano_relation_step.lower(
                    *step_args, with_closure=config.closure
                ),
            )
            with ph("compile", backend=self.name):
                out, closure = _kano_relation_step(
                    *step_args, with_closure=config.closure
                )
            enc_bytes = tree_nbytes(enc_r)
        else:
            with ph("encode"):
                enc = encode_kano(containers, policies)
            step_args = (
                enc.pod_kv,
                enc.src_req,
                enc.src_impossible,
                enc.dst_req,
                enc.dst_impossible,
            )
            _TRACKER.track(
                "_kano_step",
                enc,
                static=(config.closure,),
                lower=lambda: _kano_step.lower(
                    *step_args, with_closure=config.closure
                ),
            )
            with ph("compile", backend=self.name):
                out, closure = _kano_step(
                    *step_args, with_closure=config.closure
                )
            enc_bytes = tree_nbytes(enc)
        with ph("solve", backend=self.name):
            jax.block_until_ready(out.reach)
        BYTES_TRANSFERRED.labels(backend=self.name).set(
            enc_bytes + tree_nbytes(out) + tree_nbytes(closure)
        )
        src_sets = np.asarray(out.src_sets)
        dst_sets = np.asarray(out.dst_sets)
        # maintain the reference's per-container policy index lists
        # (kano_py/kano/model.py:158-163)
        for i, c in enumerate(containers):
            c.select_policies.clear()
            c.allow_policies.clear()
            c.select_policies.extend(np.nonzero(src_sets[:, i])[0].tolist())
            c.allow_policies.extend(np.nonzero(dst_sets[:, i])[0].tolist())
        return VerifyResult(
            n_pods=len(containers),
            mode="kano",
            backend=self.name,
            config=config,
            reach=np.asarray(out.reach),
            src_sets=src_sets,
            dst_sets=dst_sets,
            closure=np.asarray(closure) if closure is not None else None,
            timings=ph.timings,
        )


register_backend("tpu", TpuBackend)
