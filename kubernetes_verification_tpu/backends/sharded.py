"""Multi-device sharded backend (``shard_map`` over a ``(pods, grants)`` mesh).

The scale-out role the reference never had (SURVEY.md §2.4, §5.8): the pod
axis — the problem's batch dimension — shards across devices, the grant stack
across the second mesh axis, and XLA collectives (``all_gather`` over pods,
``psum`` over grants) ride ICI/DCN. Results are bit-identical to the ``cpu``
and ``tpu`` backends (differential tests, ``tests/test_sharded.py``).

Mesh selection: ``backend_options``'s ``mesh`` entry may be ``(dp, mp)``;
default is all visible devices on the pod axis.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np

from ..encode.encoder import encode_cluster, encode_kano
from ..models.core import Cluster, Container, KanoPolicy
from ..observe import Phases, tree_nbytes
from ..observe.metrics import BYTES_TRANSFERRED
from ..parallel.mesh import mesh_for
from ..parallel.sharded_ops import sharded_k8s_reach, sharded_kano_reach
from .base import (
    VerifierBackend,
    VerifyConfig,
    VerifyResult,
    register_backend,
)

__all__ = ["ShardedBackend"]


class ShardedBackend(VerifierBackend):
    name = "sharded"

    def __init__(self, mesh: Optional[jax.sharding.Mesh] = None) -> None:
        self._mesh = mesh

    def _resolve_mesh(self, config: VerifyConfig) -> jax.sharding.Mesh:
        if self._mesh is not None:
            return self._mesh
        # mesh_for normalises: None, a bare int (``--opt mesh=8``), or (dp, mp)
        return mesh_for(config.opt("mesh"))

    def verify(self, cluster: Cluster, config: VerifyConfig) -> VerifyResult:
        ph = Phases()
        with ph("compile", backend=self.name):
            mesh = self._resolve_mesh(config)
        with ph("encode"):
            enc = encode_cluster(cluster, compute_ports=config.compute_ports)
        with ph("solve", backend=self.name):
            out, closure = sharded_k8s_reach(
                mesh,
                enc,
                self_traffic=config.self_traffic,
                default_allow_unselected=config.default_allow_unselected,
                direction_aware_isolation=config.direction_aware_isolation,
                with_closure=config.closure,
            )
        BYTES_TRANSFERRED.labels(backend=self.name).set(
            tree_nbytes(enc) + tree_nbytes(out) + tree_nbytes(closure)
        )
        return VerifyResult(
            n_pods=cluster.n_pods,
            mode="k8s",
            backend=self.name,
            config=config,
            reach=out.reach,
            reach_ports=out.reach_ports if config.compute_ports else None,
            port_atoms=list(enc.atoms) if config.compute_ports else [],
            src_sets=out.src_sets,
            dst_sets=out.dst_sets,
            selected=out.selected,
            ingress_isolated=out.ingress_isolated,
            egress_isolated=out.egress_isolated,
            closure=closure,
            timings=ph.timings,
        )

    def verify_kano(
        self,
        containers: Sequence[Container],
        policies: Sequence[KanoPolicy],
        config: VerifyConfig,
    ) -> VerifyResult:
        ph = Phases()
        with ph("compile", backend=self.name):
            mesh = self._resolve_mesh(config)
        with ph("encode"):
            enc = encode_kano(containers, policies)
        with ph("solve", backend=self.name):
            out, closure = sharded_kano_reach(
                mesh, enc, with_closure=config.closure
            )
        BYTES_TRANSFERRED.labels(backend=self.name).set(
            tree_nbytes(enc) + tree_nbytes(out) + tree_nbytes(closure)
        )
        for i, c in enumerate(containers):
            c.select_policies.clear()
            c.allow_policies.clear()
            c.select_policies.extend(np.nonzero(out.src_sets[:, i])[0].tolist())
            c.allow_policies.extend(np.nonzero(out.dst_sets[:, i])[0].tolist())
        return VerifyResult(
            n_pods=len(containers),
            mode="kano",
            backend=self.name,
            config=config,
            reach=out.reach,
            src_sets=out.src_sets,
            dst_sets=out.dst_sets,
            closure=closure,
            timings=ph.timings,
        )


register_backend("sharded", ShardedBackend)
