"""Native packed-bitset backend (``backend="native"``).

The host-side production engine: same encoder as the JAX backends, but the
hot loops run in the framework's own C++ kernels (``native/bitset.cpp``)
over 64-bit packed words — the role the third-party ``bitarray`` extension
plays in the reference (``kano_py/kano/model.py:128-163``), owned and
OpenMP-threaded. Per-word bit ops replace the MXU count-matmuls:

* selector matching → packed subset / disjoint / any-intersect scans;
* the reach contraction → ``or_scatter`` (for each grant, OR the destination
  set into every source row);
* closure → packed Warshall;
* default-allow / self-traffic → row-mask ORs and diagonal sets.

Differentially identical to ``cpu``/``tpu``/``sharded``/``datalog``
(``tests/test_native.py``). Unavailable (and unregistered) when no C++
compiler exists.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..encode.encoder import (
    EncodedCluster,
    GrantBlock,
    SelectorEnc,
    encode_cluster,
    encode_kano,
)
from ..models.core import Cluster, Container, KanoPolicy
from ..native.binding import BitMatrix, pack, words
from ..observe import Phases
from ..observe.introspect import publish_host_estimate
from ..observe.metrics import BYTES_TRANSFERRED
from .base import (
    VerifierBackend,
    VerifyConfig,
    VerifyResult,
    register_backend,
)

__all__ = ["NativeBackend"]


def _match_selectors(sel: SelectorEnc, kv_bm: BitMatrix, key_bm: BitMatrix) -> np.ndarray:
    """Packed-scan evaluation of a compiled selector stack — semantics of
    ``ops/match.py:match_selectors`` word-by-word instead of by matmul."""
    ok = BitMatrix.from_bool(sel.req_eq).subset_of(kv_bm)
    ok &= BitMatrix.from_bool(sel.req_key).subset_of(key_bm)
    ok &= BitMatrix.from_bool(sel.forbid_eq).disjoint_from(kv_bm)
    ok &= BitMatrix.from_bool(sel.forbid_key).disjoint_from(key_bm)
    S, E, V = sel.in_mask.shape
    for e in range(E):
        hits = BitMatrix.from_bool(sel.in_mask[:, e, :]).intersects(kv_bm)
        ok &= hits | ~sel.in_valid[:, e][:, None]
    return ok & ~sel.impossible[:, None]


def _grant_peers(
    block: GrantBlock,
    kv_bm: BitMatrix,
    key_bm: BitMatrix,
    ns_kv_bm: BitMatrix,
    ns_key_bm: BitMatrix,
    pod_ns: np.ndarray,
    pol_ns: np.ndarray,
) -> np.ndarray:
    pod_ok = _match_selectors(block.pod_sel, kv_bm, key_bm)
    ns_sel_ok = _match_selectors(block.ns_sel, ns_kv_bm, ns_key_bm)  # [G, M]
    same_ns = pol_ns[block.pol][:, None] == pod_ns[None, :]
    if ns_sel_ok.shape[1]:
        ns_by_pod = ns_sel_ok[:, pod_ns]
    else:  # no namespaces: only same-ns scope can hold
        ns_by_pod = np.zeros_like(same_ns)
    ns_ok = np.where(block.ns_sel_null[:, None], same_ns, ns_by_pod)
    ok = pod_ok & ns_ok
    if block.ip_match is not None:
        ok = np.where(block.is_ipblock[:, None], block.ip_match, ok)
    else:
        ok &= ~block.is_ipblock[:, None]
    return ok | block.match_all[:, None]


def _segment_or_packed(rows: np.ndarray, seg: np.ndarray, n_seg: int) -> np.ndarray:
    """OR packed rows [G, W] into [n_seg, W] by segment id."""
    out = np.zeros((n_seg, rows.shape[1]), dtype=np.uint64)
    np.bitwise_or.at(out, seg, rows)
    return out


class NativeBackend(VerifierBackend):
    name = "native"

    # ------------------------------------------------------------------ kano
    def verify_kano(
        self,
        containers: Sequence[Container],
        policies: Sequence[KanoPolicy],
        config: VerifyConfig,
    ) -> VerifyResult:
        ph = Phases()
        with ph("encode"):
            enc = encode_kano(containers, policies)
        with ph("compile", backend=self.name):
            kv_bm = BitMatrix.from_bool(enc.pod_kv)
        with ph("solve", backend=self.name):
            src_sets = (
                BitMatrix.from_bool(enc.src_req).subset_of(kv_bm)
                & ~enc.src_impossible[:, None]
            )
            dst_sets = (
                BitMatrix.from_bool(enc.dst_req).subset_of(kv_bm)
                & ~enc.dst_impossible[:, None]
            )
            n = len(containers)
            reach_bm = BitMatrix.zeros(n, n)
            reach_bm.or_scatter_into(
                BitMatrix.from_bool(src_sets), BitMatrix.from_bool(dst_sets)
            )
            closure = None
            if config.closure:
                cbm = BitMatrix(reach_bm.data.copy(), n)
                cbm.closure_inplace()
                closure = cbm.to_bool()
            reach = reach_bm.to_bool()
        BYTES_TRANSFERRED.labels(backend=self.name).set(0)  # host C++ engine
        # analytic host estimate: subset-match over packed words plus the
        # rank-1 OR-scatter into the packed n x n matrix (64 pods per word)
        publish_host_estimate(
            self.name,
            "verify_kano",
            flops=2 * len(policies) * n * words(n) + n * words(n),
            bytes_accessed=8 * (2 * len(policies) + n) * words(n),
            output_bytes=reach.nbytes,
            signature=(n, len(policies)),
        )
        for i, c in enumerate(containers):
            c.select_policies.clear()
            c.allow_policies.clear()
            c.select_policies.extend(np.nonzero(src_sets[:, i])[0].tolist())
            c.allow_policies.extend(np.nonzero(dst_sets[:, i])[0].tolist())
        return VerifyResult(
            n_pods=n,
            mode="kano",
            backend=self.name,
            config=config,
            reach=reach,
            src_sets=src_sets,
            dst_sets=dst_sets,
            closure=closure,
            timings=ph.timings,
        )

    # ------------------------------------------------------------------- k8s
    def verify(self, cluster: Cluster, config: VerifyConfig) -> VerifyResult:
        ph = Phases()
        with ph("encode"):
            enc = encode_cluster(cluster, compute_ports=config.compute_ports)
        n, P = enc.n_pods, enc.n_policies
        Q = len(enc.atoms)
        W = words(n)

        with ph("compile", backend=self.name):
            kv_bm = BitMatrix.from_bool(enc.pod_kv)
            key_bm = BitMatrix.from_bool(enc.pod_key)
            ns_kv_bm = BitMatrix.from_bool(enc.ns_kv)
            ns_key_bm = BitMatrix.from_bool(enc.ns_key)

        with ph("solve", backend=self.name):
            selected = _match_selectors(enc.pol_sel, kv_bm, key_bm)
            selected &= enc.pol_ns[:, None] == enc.pod_ns[None, :]
            if config.direction_aware_isolation:
                sel_ing = selected & enc.pol_affects_ingress[:, None]
                sel_eg = selected & enc.pol_affects_egress[:, None]
            else:
                sel_ing = selected
                sel_eg = selected
            ing_iso = sel_ing.any(axis=0)
            eg_iso = sel_eg.any(axis=0)

            ing_peers = _grant_peers(
                enc.ingress, kv_bm, key_bm, ns_kv_bm, ns_key_bm, enc.pod_ns, enc.pol_ns
            )
            eg_peers = _grant_peers(
                enc.egress, kv_bm, key_bm, ns_kv_bm, ns_key_bm, enc.pod_ns, enc.pol_ns
            )
            ing_targets = sel_ing[enc.ingress.pol]  # [G, N]
            eg_targets = sel_eg[enc.egress.pol]
            # named-port resolution: AND each grant's dst-restriction bank row
            # into its dst-side operand (ingress dst = targets, egress dst =
            # peers); the unrestricted eg_peers still feed the edge sets below
            eg_peers_dst = eg_peers
            if enc.ingress.dst_restrict is not None:
                ing_targets = ing_targets & enc.restrict_bank[enc.ingress.dst_restrict]
            if enc.egress.dst_restrict is not None:
                eg_peers_dst = eg_peers & enc.restrict_bank[enc.egress.dst_restrict]

            ing_peers_p = pack(ing_peers) if ing_peers.size else np.zeros((0, W), np.uint64)
            ing_targets_p = pack(ing_targets) if ing_targets.size else np.zeros((0, W), np.uint64)
            eg_peers_p = pack(eg_peers) if eg_peers.size else np.zeros((0, W), np.uint64)
            eg_peers_dst_p = (
                pack(eg_peers_dst) if eg_peers_dst.size else np.zeros((0, W), np.uint64)
            )
            eg_targets_p = pack(eg_targets) if eg_targets.size else np.zeros((0, W), np.uint64)

            not_ing_iso_row = pack(~ing_iso[None, :])[0]
            ones_row = pack(np.ones((1, n), dtype=bool))[0]
            all_pods = np.ones(n, dtype=np.uint8)

            reach_bm = BitMatrix.zeros(n, n)
            reach_pq = (
                np.zeros((n, n, Q), dtype=bool) if config.compute_ports else None
            )
            for q in range(Q):
                gi = np.nonzero(enc.ingress.ports[:, q])[0]
                ge = np.nonzero(enc.egress.ports[:, q])[0]
                ing_q = BitMatrix.zeros(n, n)  # rows: src over dst
                ing_q.or_scatter_into(
                    BitMatrix(np.ascontiguousarray(ing_peers_p[gi]), n),
                    BitMatrix(np.ascontiguousarray(ing_targets_p[gi]), n),
                )
                eg_q = BitMatrix.zeros(n, n)
                eg_q.or_scatter_into(
                    BitMatrix(np.ascontiguousarray(eg_targets_p[ge]), n),
                    BitMatrix(np.ascontiguousarray(eg_peers_dst_p[ge]), n),
                )
                if config.default_allow_unselected:
                    # unselected dst accept from anyone; unselected src send anywhere
                    ing_q.row_or_mask(all_pods, not_ing_iso_row)
                    eg_q.row_or_mask((~eg_iso).astype(np.uint8), ones_row)
                rq = ing_q.and_with(eg_q)
                if config.self_traffic:
                    rq.set_diagonal()
                reach_bm.or_into(rq)
                if reach_pq is not None:
                    reach_pq[:, :, q] = rq.to_bool()
            reach = reach_bm.to_bool()

            closure = None
            if config.closure:
                cbm = BitMatrix(reach_bm.data.copy(), n)
                cbm.closure_inplace()
                closure = cbm.to_bool()

            # per-policy src/dst edge sets (kernel formulas, ops/reach.py:186-202)
            n_seg = P + 1
            seg_i = enc.ingress.pol.astype(np.int64)
            seg_e = enc.egress.pol.astype(np.int64)
            ing_src = _segment_or_packed(ing_peers_p, seg_i, n_seg)[:P]
            eg_dst = _segment_or_packed(eg_peers_p, seg_e, n_seg)[:P]
            ing_src = (
                BitMatrix(ing_src, n).to_bool() if P else np.zeros((0, n), bool)
            )
            eg_dst = BitMatrix(eg_dst, n).to_bool() if P else np.zeros((0, n), bool)
            has_ing = np.zeros(P, dtype=bool)
            has_eg = np.zeros(P, dtype=bool)
            np.logical_or.at(has_ing, seg_i[seg_i < P], True)
            np.logical_or.at(has_eg, seg_e[seg_e < P], True)
            if config.direction_aware_isolation:
                ing_src &= enc.pol_affects_ingress[:, None]
                eg_dst &= enc.pol_affects_egress[:, None]
            src_sets = ing_src | (sel_eg & has_eg[:, None])
            dst_sets = eg_dst | (sel_ing & has_ing[:, None])

        BYTES_TRANSFERRED.labels(backend=self.name).set(0)  # host C++ engine
        # analytic host estimate: grant evaluation + packed [n, n, Q]
        # combine, word-parallel over 64-pod lanes
        n_grants = len(enc.ingress.pol) + len(enc.egress.pol)
        n_q = len(enc.atoms) if config.compute_ports else 1
        publish_host_estimate(
            self.name,
            "verify_k8s",
            flops=(n_grants + 3 * n) * n_q * words(n),
            bytes_accessed=8 * (n_grants + 3 * n) * n_q * words(n),
            output_bytes=reach.nbytes
            + (reach_pq.nbytes if reach_pq is not None else 0),
            signature=(n, P, n_q),
        )

        return VerifyResult(
            n_pods=n,
            mode="k8s",
            backend=self.name,
            config=config,
            reach=reach,
            reach_ports=reach_pq,
            port_atoms=list(enc.atoms) if config.compute_ports else [],
            src_sets=src_sets,
            dst_sets=dst_sets,
            selected=selected,
            ingress_isolated=ing_iso,
            egress_isolated=eg_iso,
            closure=closure,
            timings=ph.timings,
        )


register_backend("native", NativeBackend)
