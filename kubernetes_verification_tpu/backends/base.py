"""The ``VerifierBackend`` plugin boundary.

Every backend consumes the same model objects and produces the same
``VerifyResult`` so backends can be differentially tested against each other
(the rebuild's first-class version of the reference's implicit two-verifier
cross-check, SURVEY.md §4). Backends register themselves on import via
``register_backend``; ``available_backends()`` lists what this build provides
(at minimum ``cpu`` — the object-level NumPy semantics oracle — and ``tpu``,
the single-device JAX/XLA kernel backend).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..models.core import Cluster, Container, KanoPolicy
from ..observe import trace
from ..observe.metrics import PAIRS_PER_SECOND, VERIFY_TOTAL
from ..resilience.errors import ConfigError, UnknownBackendError

__all__ = [
    "VerifyConfig",
    "PortAtom",
    "VerifyResult",
    "VerifierBackend",
    "register_backend",
    "get_backend",
    "available_backends",
    "verify",
    "verify_kano",
]


@dataclass(frozen=True)
class VerifyConfig:
    """Typed verification config — the single flag surface (SURVEY.md §5.6).

    Semantic flags (k8s mode):

    * ``self_traffic`` — treat every pod as reachable from itself regardless of
      policy (the reference's ``check_self_ingress_traffic``,
      ``kubesv/kubesv/constraint.py:12,193-194``; default True there and here).
    * ``default_allow_unselected`` — pods selected by no policy in a direction
      default to allow-all in that direction. This is real Kubernetes
      semantics and our default; the reference gates it behind
      ``check_select_by_no_policy`` (default False,
      ``kubesv/kubesv/constraint.py:13,202-207``) — set it False to reproduce
      the reference's "unselected pods are unreachable" behaviour.
    * ``direction_aware_isolation`` — only policies whose
      ``effective_policy_types`` include a direction isolate pods in that
      direction (real k8s). The reference never consults policyTypes
      (``kubesv/kubesv/model.py:522-545`` is dead code), so any selecting
      policy isolates both directions; set False to reproduce that.

    ``backend`` selects the execution engine; ``closure`` asks for the
    transitive closure of the reachability graph (the generalisation of the
    reference's ≤2-hop ``path``, ``kubesv/kubesv/constraint.py:233-237``).
    """

    backend: str = "cpu"
    self_traffic: bool = True
    default_allow_unselected: bool = True
    direction_aware_isolation: bool = True
    compute_ports: bool = True
    closure: bool = False
    #: kano-mode label matcher plugin (the reference's only extension point,
    #: ``kano_py/kano/model.py:59-68``): an object with
    #: ``match(rule_value, label_value) -> bool``; None = string equality.
    #: Honored by ``verify_kano`` backends; k8s-mode selectors follow the
    #: Kubernetes API spec and reject a custom relation. Keyword-only so its
    #: insertion (round 3) never silently reorders positional callers that
    #: were passing ``backend_options`` by position.
    label_relation: Optional[object] = field(default=None, kw_only=True)
    #: extra, backend-specific options (e.g. mesh shape for ``sharded``)
    backend_options: Tuple[Tuple[str, object], ...] = ()

    def opt(self, key: str, default=None):
        return dict(self.backend_options).get(key, default)


@dataclass(frozen=True)
class PortAtom:
    """One equivalence class of (protocol, port) space: all ports in
    ``[lo, hi]`` of ``protocol`` behave identically under every policy in the
    cluster, so the port dimension of the reach tensor needs one slot per atom
    instead of 65536×3. ``name`` is set for named-port atoms."""

    protocol: str
    lo: int
    hi: int
    name: Optional[str] = None

    @property
    def width(self) -> int:
        return 1 if self.name is not None else self.hi - self.lo + 1


@dataclass
class VerifyResult:
    """Backend-independent verification output.

    ``reach[src, dst]`` — src can reach dst on *some* port (row = source, the
    reference's matrix orientation, ``kano_py/kano/model.py:158-163``).
    ``reach_ports[src, dst, q]`` — per port-atom reachability (k8s mode with
    ``compute_ports``). ``src_sets``/``dst_sets`` are the per-policy
    direction-swapped select/allow bitmaps the reference caches via
    ``store_bcp`` (``kano_py/kano/model.py:119-121``) — queries and
    incremental re-verify consume them.
    """

    n_pods: int
    mode: str  # "kano" | "k8s"
    backend: str
    config: VerifyConfig
    reach: np.ndarray  # bool [N, N]
    reach_ports: Optional[np.ndarray] = None  # bool [N, N, Q]
    port_atoms: List[PortAtom] = field(default_factory=list)
    #: per policy: which pods are sources of its edges (kano working_select)
    src_sets: Optional[np.ndarray] = None  # bool [P, N]
    #: per policy: which pods are destinations of its edges (kano working_allow)
    dst_sets: Optional[np.ndarray] = None  # bool [P, N]
    #: k8s mode: pod selected by policy (podSelector ∧ namespace) [P, N]
    selected: Optional[np.ndarray] = None
    ingress_isolated: Optional[np.ndarray] = None  # bool [N]
    egress_isolated: Optional[np.ndarray] = None  # bool [N]
    closure: Optional[np.ndarray] = None  # bool [N, N] transitive closure
    timings: Dict[str, float] = field(default_factory=dict)

    # -- convenience views -------------------------------------------------
    def reachable(self, src: int, dst: int) -> bool:
        return bool(self.reach[src, dst])

    def edges(self) -> List[Tuple[int, int]]:
        """Reachable (src, dst) index pairs — the decoded form of the
        reference's only result API (``kubesv/sample/__init__.py:14-25``)."""
        s, d = np.nonzero(self.reach)
        return list(zip(s.tolist(), d.tolist()))

    # -- the six kano verification queries (kano_py/kano/algorithm.py) -----
    def all_reachable(self) -> List[int]:
        from ..ops.queries import all_reachable

        return all_reachable(self.reach)

    def all_isolated(self) -> List[int]:
        from ..ops.queries import all_isolated

        return all_isolated(self.reach)

    def user_crosscheck(self, containers_or_pods, label: str) -> List[int]:
        from ..ops.queries import user_crosscheck

        return user_crosscheck(self.reach, containers_or_pods, label)

    def system_isolation(self, idx: int) -> List[int]:
        from ..ops.queries import system_isolation

        return system_isolation(self.reach, idx)

    def policy_shadow(self) -> List[Tuple[int, int]]:
        from ..ops.queries import policy_shadow

        return policy_shadow(self.src_sets, self.dst_sets)

    def policy_conflict(self) -> List[Tuple[int, int]]:
        from ..ops.queries import policy_conflict

        return policy_conflict(self.src_sets, self.dst_sets)


class VerifierBackend:
    """Backend interface. Implementations provide one or both modes."""

    name: str = "abstract"
    #: whether verify_kano honors VerifyConfig.label_relation (the kano
    #: matcher plugin); the dispatcher rejects a custom relation otherwise
    #: rather than silently computing equality-only results
    supports_label_relation: bool = False

    def verify(self, cluster: Cluster, config: VerifyConfig) -> VerifyResult:
        raise NotImplementedError

    def verify_kano(
        self,
        containers: Sequence[Container],
        policies: Sequence[KanoPolicy],
        config: VerifyConfig,
    ) -> VerifyResult:
        raise NotImplementedError


_REGISTRY: Dict[str, Callable[[], VerifierBackend]] = {}


def register_backend(name: str, factory: Callable[[], VerifierBackend]) -> None:
    _REGISTRY[name] = factory


def available_backends() -> List[str]:
    return sorted(_REGISTRY)


def get_backend(name: str) -> VerifierBackend:
    if name not in _REGISTRY:
        raise UnknownBackendError(
            f"unknown backend {name!r}; have {available_backends()}",
            backend=name,
        )
    return _REGISTRY[name]()


def _record_run(res: VerifyResult) -> None:
    """Registry bookkeeping shared by both dispatchers: run counter plus the
    roofline-style throughput gauge (decided pod pairs per solve second)."""
    VERIFY_TOTAL.labels(backend=res.backend, mode=res.mode).inc()
    solve = res.timings.get("solve", 0.0)
    if solve > 0:
        PAIRS_PER_SECOND.labels(backend=res.backend).set(
            res.n_pods * res.n_pods / solve
        )


def verify(cluster: Cluster, config: Optional[VerifyConfig] = None) -> VerifyResult:
    """Verify a k8s-level cluster with the configured backend."""
    config = config or VerifyConfig()
    if config.label_relation is not None:
        raise ConfigError(
            "label_relation is the kano-mode matcher plugin; k8s-mode "
            "selectors follow the Kubernetes LabelSelector spec (use "
            "verify_kano)"
        )
    with trace("verify", backend=config.backend, mode="k8s"):
        res = get_backend(config.backend).verify(cluster, config)
    _record_run(res)
    return res


def verify_kano(
    containers: Sequence[Container],
    policies: Sequence[KanoPolicy],
    config: Optional[VerifyConfig] = None,
) -> VerifyResult:
    """Verify a kano-level scenario with the configured backend."""
    config = config or VerifyConfig()
    backend = get_backend(config.backend)
    if (
        config.label_relation is not None
        and not backend.supports_label_relation
    ):
        raise ConfigError(
            f"backend {config.backend!r} does not honor label_relation; "
            "use the cpu or tpu backend for a custom kano matcher"
        )
    with trace("verify", backend=config.backend, mode="kano"):
        res = backend.verify_kano(containers, policies, config)
    _record_run(res)
    return res
