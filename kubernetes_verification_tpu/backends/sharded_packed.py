"""The config-5 engine behind the plugin boundary: ``backend="sharded-packed"``.

Routes ``kv.verify()`` / the CLI through
:func:`~..parallel.packed_sharded.sharded_packed_reach` — the bit-packed,
dst-tile-streaming SPMD solver (any-port AND port-bitmap semantics via the
mask-group decomposition) — so large-N solves no longer require importing the
function API directly. All six verification queries answer here: four on the
packed/aggregate forms, the pairwise ``policy_shadow``/``policy_conflict``
through lazily-computed sharded Gram masks
(:func:`~..ops.tiled.policy_pair_masks_sharded`). The dense ``sharded``
backend remains for small/medium N where a full ``[N, N]`` bool result (plus
per-atom ``reach_ports`` and materialised per-policy src/dst sets) is
wanted.

Result shape: a :class:`ShardedPackedVerifyResult`. ``reach`` is materialised
densely only up to ``dense_reach_limit`` pods (default 20k — beyond that a
bool [N, N] is the exact thing this engine exists to avoid); the packed
matrix / aggregates stay available via ``packed_result`` and power the
whole-matrix queries either way.

Backend options (``VerifyConfig.backend_options``): ``mesh`` = (dp, mp)
factorisation, ``tile``/``chunk`` sweep geometry, ``keep_matrix``,
``groups_label`` (aggregate per-group in-degrees at solve time so
``user_crosscheck`` works matrix-free), ``dense_reach_limit``.
``VerifyConfig.closure`` runs the packed-domain closure on the kept matrix
(dense ``closure`` below the dense-reach limit; the packed words stay on
``closure_packed`` either way).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..encode.encoder import encode_cluster
from ..models.core import Cluster, Container, KanoPolicy
from ..observe import Phases, tree_nbytes
from ..observe.metrics import BYTES_TRANSFERRED
from ..parallel.mesh import mesh_for
from ..parallel.packed_sharded import PackedShardedResult, sharded_packed_reach
from .base import (
    VerifierBackend,
    VerifyConfig,
    VerifyResult,
    register_backend,
)

__all__ = ["ShardedPackedBackend", "ShardedPackedVerifyResult"]


@dataclass
class ShardedPackedVerifyResult(VerifyResult):
    """``VerifyResult`` whose queries run on the packed/aggregate forms.

    ``reach`` is a dense bool matrix only below the dense-reach limit;
    above it, ``reach`` is ``None`` and the packed-domain queries (and
    ``packed_result``) are the API — exactly the contract of
    :class:`~..ops.tiled.PackedReach` at flagship scale."""

    packed_result: Optional[PackedShardedResult] = None
    #: packed transitive closure (uint32 [N, W]) when config.closure ran —
    #: present even above the dense-reach limit where ``closure`` stays None
    closure_packed: Optional[np.ndarray] = None
    #: lazy thunk installed by the backend: () -> (shadow, conflict) bool
    #: [P, P] masks via the sharded Gram kernel (``policy_pair_masks_sharded``)
    #: — computed on first pairwise-policy query, cached thereafter
    pair_masks_fn: Optional[Callable] = None
    _pair_masks: Optional[Tuple[np.ndarray, np.ndarray]] = None
    #: lazy thunk: () -> (src_sets, dst_sets) bool [P, N] via the sharded
    #: set build (``policy_sets_sharded``) — see materialize_policy_sets
    policy_sets_fn: Optional[Callable] = None
    #: host bytes the materialised sets would occupy (2·P·N), set by the
    #: backend so the budget check runs BEFORE any device work
    policy_sets_bytes: Optional[int] = None

    def materialize_policy_sets(
        self, max_bytes: int = 2_000_000_000
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Fetch the per-policy src/dst edge sets (kano ``working_select``/
        ``working_allow``) from a sharded build into ``self.src_sets``/
        ``dst_sets`` — the one result view this engine keeps implicit by
        default (two host bool [P, N] arrays; at 100k pods × 10k policies
        that is 2 GB, hence the explicit byte budget). The pairwise policy
        queries do NOT need this — they run on device Gram masks."""
        if self.src_sets is None:
            if self.policy_sets_fn is None:
                raise ValueError("no policy-sets thunk attached to this result")
            need = self.policy_sets_bytes or 0
            if need > max_bytes:
                raise ValueError(
                    f"policy sets need {need / 1e9:.1f} GB on host, over "
                    f"the {max_bytes / 1e9:.1f} GB budget; raise max_bytes "
                    "explicitly to fetch them anyway"
                )
            self.src_sets, self.dst_sets = self.policy_sets_fn()
            self.policy_sets_fn = None  # result cached — release the thunk
        return self.src_sets, self.dst_sets

    def release_policy_queries(self) -> None:
        """Drop the lazy pairwise/policy-set thunks. Each thunk closes over
        the full host ``EncodedCluster``, pinning it for the result's
        lifetime; the thunks self-release once their result is cached, but
        a caller that will never run the pairwise policy queries can call
        this to let a large encoding be garbage-collected immediately.
        Already-materialised masks/sets survive; un-materialised ones
        raise their usual "no thunk attached" error afterwards."""
        self.pair_masks_fn = None
        self.policy_sets_fn = None

    def _pk(self) -> PackedShardedResult:
        if self.packed_result is None:
            raise ValueError("no packed result attached")
        return self.packed_result

    def reachable(self, src: int, dst: int) -> bool:
        if self.reach is not None:
            return bool(self.reach[src, dst])
        pk = self._pk()
        if pk.packed is None:
            raise ValueError(
                "solve ran matrix-free (keep_matrix=False): per-pair lookup "
                "needs the packed matrix; re-run with keep_matrix=True or "
                "query the aggregates"
            )
        w = pk.packed[src, dst // 32]
        return bool((np.uint32(w) >> np.uint32(dst % 32)) & np.uint32(1))

    def edges(self) -> List[Tuple[int, int]]:
        if self.reach is not None:
            return super().edges()
        s, d = np.nonzero(self._pk().to_bool())
        return list(zip(s.tolist(), d.tolist()))

    def all_reachable(self) -> List[int]:
        return self._pk().all_reachable()

    def all_isolated(self) -> List[int]:
        return self._pk().all_isolated()

    def user_crosscheck(self, containers_or_pods, label: str) -> List[int]:
        return self._pk().user_crosscheck(containers_or_pods, label)

    def system_isolation(self, idx: int) -> List[int]:
        return self._pk().system_isolation(idx)

    def _masks(self) -> Tuple[np.ndarray, np.ndarray]:
        if self._pair_masks is None:
            if self.pair_masks_fn is None:
                raise ValueError("no pair-mask thunk attached to this result")
            self._pair_masks = self.pair_masks_fn()
            self.pair_masks_fn = None  # result cached — release the thunk
        return self._pair_masks

    def policy_shadow(self) -> List[Tuple[int, int]]:
        """Pairwise shadow query via the device Gram masks — the [P, N]
        src/dst sets and their O(P²·N) contractions stay sharded on the
        mesh (``ops.tiled.policy_pair_masks_sharded``); only [P, P] masks
        reach the host. Lazy: the Grams run on the first call."""
        from ..ops.queries import _pairs

        return _pairs(self._masks()[0])

    def policy_conflict(self) -> List[Tuple[int, int]]:
        from ..ops.queries import _pairs

        return _pairs(self._masks()[1])


class ShardedPackedBackend(VerifierBackend):
    name = "sharded-packed"

    def __init__(self, mesh: Optional[jax.sharding.Mesh] = None) -> None:
        self._mesh = mesh

    def _resolve_mesh(self, config: VerifyConfig) -> jax.sharding.Mesh:
        if self._mesh is not None:
            return self._mesh
        # mesh_for normalises: None, a bare int (``--opt mesh=8``), or (dp, mp)
        return mesh_for(config.opt("mesh"))

    def verify(self, cluster: Cluster, config: VerifyConfig) -> VerifyResult:
        keep_matrix = config.opt("keep_matrix")
        if config.closure:
            if keep_matrix is False:
                raise ValueError(
                    "closure needs the packed matrix; drop keep_matrix=False "
                    "or use the sharded/tpu backends"
                )
            # force the matrix BEFORE the solve — the auto heuristic
            # declining it after a full sweep would discard all that work
            keep_matrix = True
        ph = Phases()
        with ph("compile", backend=self.name):
            mesh = self._resolve_mesh(config)
        with ph("encode"):
            enc = encode_cluster(cluster, compute_ports=config.compute_ports)
        groups = None
        glabel = config.opt("groups_label")
        if glabel is not None:
            from ..ops.queries import user_groups

            groups = user_groups(cluster.pods, glabel)
        with ph("solve", backend=self.name):
            pk = sharded_packed_reach(
                mesh,
                enc,
                self_traffic=config.self_traffic,
                default_allow_unselected=config.default_allow_unselected,
                direction_aware_isolation=config.direction_aware_isolation,
                tile=config.opt("tile", 512),
                chunk=config.opt("chunk", 1024),
                keep_matrix=keep_matrix,
                groups=groups,
                max_port_masks=config.opt("max_port_masks"),
            )
        BYTES_TRANSFERRED.labels(backend=self.name).set(
            tree_nbytes(enc) + tree_nbytes(pk.packed)
        )
        dense_limit = config.opt("dense_reach_limit", 20_000)
        dense_ok = pk.packed is not None and cluster.n_pods <= dense_limit
        reach = pk.to_bool() if dense_ok else None
        closure = None
        closure_packed = None
        if config.closure:
            from ..ops.tiled import unpack_cols

            # closure_tile is its own knob: the dst-sweep "tile" shapes the
            # broadcast geometry and is often tuned small; the squaring
            # kernel wants its larger default. The closure rides the SAME
            # mesh as the sweep — row stripes over the pod axis — so the
            # per-device working set scales down with the fleet, and the
            # pre-flight HBM guard (ClosureBudgetError → exit 2) refuses
            # configs that would OOM instead of letting the device die
            closure_packed = pk.closure(
                tile=config.opt("closure_tile", 7168),
                mesh=mesh,
                hbm_limit=config.opt("hbm_limit"),
            )
            if dense_ok:
                closure = unpack_cols(closure_packed, cluster.n_pods)
        from ..ops.tiled import policy_pair_masks_sharded, policy_sets_sharded

        return ShardedPackedVerifyResult(
            n_pods=cluster.n_pods,
            mode="k8s",
            backend=self.name,
            config=config,
            reach=reach,
            port_atoms=list(enc.atoms) if config.compute_ports else [],
            ingress_isolated=pk.ingress_isolated,
            egress_isolated=pk.egress_isolated,
            closure=closure,
            timings={
                # "solve" is the whole engine call (host prep + device
                # sweep); the inner sweep-only figures keep their own keys
                **ph.timings,
                **{f"sweep_{k}": v for k, v in (pk.timings or {}).items()},
            },
            packed_result=pk,
            closure_packed=closure_packed,
            # lazy: the O(P²·N) pairwise-policy Grams run sharded on first
            # policy_shadow/policy_conflict call, not on every verify
            pair_masks_fn=lambda: policy_pair_masks_sharded(
                mesh,
                enc,
                direction_aware_isolation=config.direction_aware_isolation,
                chunk=config.opt("chunk", 1024),
            ),
            policy_sets_fn=lambda: policy_sets_sharded(
                mesh,
                enc,
                direction_aware_isolation=config.direction_aware_isolation,
                chunk=config.opt("chunk", 1024),
            ),
            policy_sets_bytes=2 * enc.n_policies * cluster.n_pods,
        )

    def verify_kano(
        self,
        containers: Sequence[Container],
        policies: Sequence[KanoPolicy],
        config: VerifyConfig,
    ) -> VerifyResult:
        raise ValueError(
            "sharded-packed is a k8s-mode engine; use the sharded backend "
            "for kano-mode scale-out"
        )


register_backend("sharded-packed", ShardedPackedBackend)
