"""YAML ⇄ model objects.

Plays the role of both reference parsers, self-contained and with their bugs
fixed:

* ``load_cluster`` / ``parse_*`` — the k8s-level deserializer. The reference
  abused ``kubernetes.client.ApiClient.deserialize`` behind a fake HTTP
  response and called ``config.load_kube_config()`` at import time
  (``kubesv/kubesv/parser.py:9-22``), so offline parsing required a live kube
  config. Here the exact ``V1*`` fields the verifier consumes are parsed
  directly (labels, selectors, matchExpressions, peers, ipBlock, ports incl.
  ``endPort``, ``policyTypes``, pod IP + named container ports).
* ``load_kano`` — the kano-level walk (``kano_py/kano/parser.py:11-89``):
  file-or-directory traversal, ``kind:`` dispatch, one ``KanoPolicy`` per
  ingress/egress rule, one ``Container`` per pod-spec container. Fixed
  relative to the reference: ``ports`` are read as rule siblings where
  Kubernetes puts them, not from inside ``from``/``to`` items
  (``kano/parser.py:61-62,73-74``); protocols land in
  ``KanoPolicy.protocols`` instead of a raw dict being passed where a class
  was expected (``:63,75``); parse errors raise instead of being swallowed by
  bare ``except`` + print (``:32-33,46-47``).

Null-vs-empty is preserved everywhere it is semantic
(``kubesv/kubesv/model.py:129-170``): an *absent* mapping parses to ``None``,
an explicit ``{}`` to an empty ``Selector``; absent ``ingress:`` to ``None``,
``ingress: []`` to ``()``; absent ``from:`` to ``None`` (allow-all rule).

Multi-document YAML streams and ``kind: List`` wrappers are supported; other
kinds are skipped with a warning list returned by ``load_cluster`` (strict
mode raises).
"""
from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

import yaml

try:  # libyaml, as the reference uses (kano_py/kano/parser.py:6-9)
    from yaml import CSafeLoader as _Loader
except ImportError:  # pragma: no cover
    from yaml import SafeLoader as _Loader

from ..models.core import (
    Cluster,
    Container,
    Expr,
    IpBlock,
    KanoPolicy,
    Namespace,
    NetworkPolicy,
    Peer,
    Pod,
    PortSpec,
    Rule,
    Selector,
)
from ..resilience.errors import IngestError

__all__ = [
    "load_cluster",
    "load_kano",
    "dump_cluster",
    "parse_pod",
    "parse_namespace",
    "parse_network_policy",
    "pod_to_dict",
    "namespace_to_dict",
    "network_policy_to_dict",
    "IngestError",
    "SkipDiagnostic",
]


class SkipDiagnostic(str):
    """One lenient-mode skip, structured: ``path`` / ``doc_index`` /
    ``kind`` / ``name`` / ``reason`` attributes, with the str value kept as
    the historical ``"file: kind/name"`` note so existing consumers (JSON
    dumps, substring asserts) are untouched."""

    path: str
    doc_index: int
    kind: Optional[str]
    name: Optional[str]
    reason: str

    def __new__(
        cls,
        path: str,
        doc_index: int,
        kind: Optional[str],
        name: Optional[str],
        reason: str,
    ) -> "SkipDiagnostic":
        self = super().__new__(cls, f"{path}: {kind}/{name}")
        self.path = path
        self.doc_index = doc_index
        self.kind = kind
        self.name = name
        self.reason = reason
        return self

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "doc_index": self.doc_index,
            "kind": self.kind,
            "name": self.name,
            "reason": self.reason,
        }


def _meta(obj: dict) -> dict:
    return obj.get("metadata") or {}


def _name(obj: dict, kind: str) -> str:
    name = _meta(obj).get("name")
    if not name:
        raise IngestError(f"{kind} without metadata.name: {obj!r:.120}")
    return str(name)


def _labels(obj: dict) -> Dict[str, str]:
    labels = _meta(obj).get("labels") or {}
    return {str(k): str(v) for k, v in labels.items()}


# ---------------------------------------------------------------------------
# k8s level
# ---------------------------------------------------------------------------


def _parse_selector(raw: Optional[dict]) -> Optional[Selector]:
    """``None`` stays ``None`` (null selector); ``{}`` is the match-everything
    empty selector — the distinction the whole peer semantics hangs on."""
    if raw is None:
        return None
    exprs = []
    for e in raw.get("matchExpressions") or ():
        exprs.append(
            Expr(
                key=str(e["key"]),
                op=str(e["operator"]),
                values=tuple(str(v) for v in e.get("values") or ()),
            )
        )
    match_labels = {
        str(k): str(v) for k, v in (raw.get("matchLabels") or {}).items()
    }
    return Selector(match_labels=match_labels, match_expressions=tuple(exprs))


def _parse_peer(raw: dict) -> Peer:
    ip = None
    if raw.get("ipBlock") is not None:
        b = raw["ipBlock"]
        ip = IpBlock(
            cidr=str(b["cidr"]), excepts=tuple(str(e) for e in b.get("except") or ())
        )
    return Peer(
        pod_selector=_parse_selector(raw.get("podSelector")),
        namespace_selector=_parse_selector(raw.get("namespaceSelector")),
        ip_block=ip,
    )


def _parse_ports(raw: Optional[list]) -> Optional[Tuple[PortSpec, ...]]:
    if raw is None:
        return None
    specs = []
    for p in raw:
        port = p.get("port")
        if isinstance(port, str) and port.isdigit():
            port = int(port)
        specs.append(
            PortSpec(
                protocol=str(p.get("protocol") or "TCP"),
                port=port,
                end_port=p.get("endPort"),
            )
        )
    return tuple(specs)


def _parse_rules(raw: Optional[list], peer_key: str) -> Optional[Tuple[Rule, ...]]:
    """``None`` (absent section) → None; ``[]`` → (); rule without
    ``from``/``to`` → allow-all-peers rule (the case the reference's
    ``define_peer_rule`` returned None for and crashed on,
    ``kubesv/kubesv/model.py:350-363``)."""
    if raw is None:
        return None
    rules = []
    for r in raw:
        r = r or {}
        peers_raw = r.get(peer_key)
        peers = (
            None
            if peers_raw is None
            else tuple(_parse_peer(p) for p in peers_raw)
        )
        rules.append(Rule(peers=peers, ports=_parse_ports(r.get("ports"))))
    return tuple(rules)


def parse_network_policy(obj: dict) -> NetworkPolicy:
    spec = obj.get("spec") or {}
    pt = spec.get("policyTypes")
    return NetworkPolicy(
        name=_name(obj, "NetworkPolicy"),
        namespace=str(_meta(obj).get("namespace") or "default"),
        pod_selector=_parse_selector(spec.get("podSelector")) or Selector(),
        policy_types=tuple(str(t) for t in pt) if pt is not None else None,
        ingress=_parse_rules(spec.get("ingress"), "from"),
        egress=_parse_rules(spec.get("egress"), "to"),
    )


def parse_pod(obj: dict) -> Pod:
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    cports: Dict[str, Tuple[str, int]] = {}
    for c in spec.get("containers") or ():
        for p in c.get("ports") or ():
            if p.get("name") and p.get("containerPort"):
                cports[str(p["name"])] = (
                    str(p.get("protocol") or "TCP"),
                    int(p["containerPort"]),
                )
    return Pod(
        name=_name(obj, "Pod"),
        namespace=str(_meta(obj).get("namespace") or "default"),
        labels=_labels(obj),
        ip=status.get("podIP"),
        container_ports=cports,
    )


def parse_namespace(obj: dict) -> Namespace:
    return Namespace(name=_name(obj, "Namespace"), labels=_labels(obj))


def _iter_docs(path: str) -> Iterable[Tuple[str, int, dict]]:
    """Yield (source_file, doc_index, document) over a file or a directory
    walk — the reference's traversal shape (``kano_py/kano/parser.py:17-49``).
    ``doc_index`` counts yielded documents per file (``kind: List`` items
    each get their own index)."""
    if os.path.isdir(path):
        for root, _dirs, files in sorted(os.walk(path)):
            for fname in sorted(files):
                if fname.endswith((".yml", ".yaml", ".json")):
                    yield from _iter_docs(os.path.join(root, fname))
        return
    try:
        fh = open(path, "r")
    except OSError as e:
        raise IngestError(f"{path}: cannot read manifests: {e}") from e
    with fh:
        try:
            docs = list(yaml.load_all(fh, Loader=_Loader))
        except yaml.YAMLError as e:
            raise IngestError(f"{path}: {e}") from e
    idx = 0
    for doc in docs:
        if doc is None:
            continue
        if not isinstance(doc, dict):
            raise IngestError(f"{path}: top-level document is not a mapping")
        if doc.get("kind") == "List":
            for item in doc.get("items") or ():
                yield path, idx, item
                idx += 1
        else:
            yield path, idx, doc
            idx += 1


def load_cluster(
    path: Union[str, os.PathLike], strict: bool = False
) -> Tuple[Cluster, List[str]]:
    """Parse every manifest under ``path`` into a :class:`Cluster`.

    Returns ``(cluster, skipped)`` where ``skipped`` lists a
    :class:`SkipDiagnostic` (str-compatible ``"file: kind/name"``, plus
    structured ``path``/``doc_index``/``kind``/``name``/``reason``) per
    document of a kind the verifier doesn't consume. ``strict=True`` raises
    on them instead.
    """
    pods: List[Pod] = []
    namespaces: List[Namespace] = []
    policies: List[NetworkPolicy] = []
    skipped: List[SkipDiagnostic] = []
    for src, idx, doc in _iter_docs(os.fspath(path)):
        kind = doc.get("kind")
        if kind == "Pod":
            pods.append(parse_pod(doc))
        elif kind == "Namespace":
            namespaces.append(parse_namespace(doc))
        elif kind == "NetworkPolicy":
            policies.append(parse_network_policy(doc))
        else:
            diag = SkipDiagnostic(
                path=src,
                doc_index=idx,
                kind=None if kind is None else str(kind),
                name=_meta(doc).get("name"),
                reason=(
                    "document has no kind" if kind is None
                    else f"kind {kind} is not verifiable"
                ),
            )
            if strict:
                raise IngestError(f"unsupported kind: {diag}")
            skipped.append(diag)
    return Cluster(pods=pods, namespaces=namespaces, policies=policies), skipped


# ---------------------------------------------------------------------------
# kano level
# ---------------------------------------------------------------------------


def load_kano(
    path: Union[str, os.PathLike]
) -> Tuple[List[Container], List[KanoPolicy]]:
    """The kano-level parse: flat matchLabels only, one policy object per
    ingress/egress rule (``kano_py/kano/parser.py:51-89``)."""
    containers: List[Container] = []
    policies: List[KanoPolicy] = []
    for _src, _idx, doc in _iter_docs(os.fspath(path)):
        kind = doc.get("kind")
        if kind == "Pod":
            labels = _labels(doc)
            for c in (doc.get("spec") or {}).get("containers") or ():
                containers.append(Container(str(c.get("name")), dict(labels)))
        elif kind == "NetworkPolicy":
            spec = doc.get("spec") or {}
            name = _name(doc, "NetworkPolicy")
            select = {
                str(k): str(v)
                for k, v in ((spec.get("podSelector") or {}).get("matchLabels") or {}).items()
            }
            for direction, peer_key, is_ingress in (
                ("ingress", "from", True),
                ("egress", "to", False),
            ):
                for rule in spec.get(direction) or ():
                    rule = rule or {}
                    allow: Dict[str, str] = {}
                    for peer in rule.get(peer_key) or ():
                        sel = (peer.get("podSelector") or {}).get("matchLabels") or {}
                        allow.update({str(k): str(v) for k, v in sel.items()})
                    protocols = tuple(
                        str(p.get("protocol") or "TCP")
                        for p in rule.get("ports") or ()
                    )
                    policies.append(
                        KanoPolicy(
                            name=f"{name}/{direction}",
                            select=dict(select),
                            allow=allow,
                            ingress=is_ingress,
                            protocols=protocols,
                        )
                    )
    return containers, policies


# ---------------------------------------------------------------------------
# model → YAML (round-trip support for the harness/checkpointing)
# ---------------------------------------------------------------------------


def _selector_to_yaml(sel: Optional[Selector]) -> Optional[dict]:
    if sel is None:
        return None
    out: dict = {}
    if sel.match_labels:
        out["matchLabels"] = dict(sel.match_labels)
    if sel.match_expressions:
        out["matchExpressions"] = [
            {"key": e.key, "operator": e.op, **({"values": list(e.values)} if e.values else {})}
            for e in sel.match_expressions
        ]
    return out  # {} encodes the empty selector


def _rules_to_yaml(rules: Optional[Tuple[Rule, ...]], peer_key: str) -> Optional[list]:
    if rules is None:
        return None
    out = []
    for r in rules:
        entry: dict = {}
        if r.peers is not None:
            peers = []
            for p in r.peers:
                peer: dict = {}
                if p.ip_block is not None:
                    peer["ipBlock"] = {
                        "cidr": p.ip_block.cidr,
                        **({"except": list(p.ip_block.excepts)} if p.ip_block.excepts else {}),
                    }
                if p.pod_selector is not None:
                    peer["podSelector"] = _selector_to_yaml(p.pod_selector)
                if p.namespace_selector is not None:
                    peer["namespaceSelector"] = _selector_to_yaml(p.namespace_selector)
                peers.append(peer)
            entry[peer_key] = peers
        if r.ports is not None:
            entry["ports"] = [
                {
                    "protocol": s.protocol,
                    **({"port": s.port} if s.port is not None else {}),
                    **({"endPort": s.end_port} if s.end_port is not None else {}),
                }
                for s in r.ports
            ]
        out.append(entry)
    return out


def namespace_to_dict(ns: Namespace) -> dict:
    """Manifest-shaped doc for one namespace; ``parse_namespace`` inverts."""
    return {
        "apiVersion": "v1",
        "kind": "Namespace",
        "metadata": {"name": ns.name, **({"labels": dict(ns.labels)} if ns.labels else {})},
    }


def pod_to_dict(p: Pod) -> dict:
    """Manifest-shaped doc for one pod; ``parse_pod`` inverts."""
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": p.name,
            "namespace": p.namespace,
            **({"labels": dict(p.labels)} if p.labels else {}),
        },
        "spec": {
            "containers": [
                {
                    "name": p.name,
                    **(
                        {
                            "ports": [
                                {"name": n, "protocol": proto, "containerPort": port}
                                for n, (proto, port) in p.container_ports.items()
                            ]
                        }
                        if p.container_ports
                        else {}
                    ),
                }
            ]
        },
        **({"status": {"podIP": p.ip}} if p.ip else {}),
    }


def network_policy_to_dict(pol: NetworkPolicy) -> dict:
    """Manifest-shaped doc for one policy; ``parse_network_policy`` inverts
    (null-vs-empty preserved: absent sections stay absent)."""
    return {
        "apiVersion": "networking.k8s.io/v1",
        "kind": "NetworkPolicy",
        "metadata": {"name": pol.name, "namespace": pol.namespace},
        "spec": {
            "podSelector": _selector_to_yaml(pol.pod_selector),
            **(
                {"policyTypes": list(pol.policy_types)}
                if pol.policy_types is not None
                else {}
            ),
            **(
                {"ingress": _rules_to_yaml(pol.ingress, "from")}
                if pol.ingress is not None
                else {}
            ),
            **(
                {"egress": _rules_to_yaml(pol.egress, "to")}
                if pol.egress is not None
                else {}
            ),
        },
    }


def dump_cluster(cluster: Cluster, directory: Union[str, os.PathLike]) -> List[str]:
    """Write the cluster as one multi-doc manifest per object kind under
    ``directory``; returns the written paths. ``load_cluster`` of the
    directory round-trips to an equivalent cluster (asserted in tests)."""
    directory = os.fspath(directory)
    os.makedirs(directory, exist_ok=True)
    written = []

    def emit(fname: str, docs: Sequence[dict]) -> None:
        if not docs:
            return
        p = os.path.join(directory, fname)
        with open(p, "w") as fh:  # kvtpu: ignore[atomic-write] manifest export into a fresh directory, not durable state
            yaml.safe_dump_all(list(docs), fh, sort_keys=False)
        written.append(p)

    emit("namespaces.yaml", [namespace_to_dict(ns) for ns in cluster.namespaces])
    emit("pods.yaml", [pod_to_dict(p) for p in cluster.pods])
    emit(
        "networkpolicies.yaml",
        [network_policy_to_dict(pol) for pol in cluster.policies],
    )
    return written
