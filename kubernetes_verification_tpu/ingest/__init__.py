"""Cluster ingestion: YAML → model objects, no cluster/kube-config required.

The reference needed a loadable ``~/.kube/config`` just to *parse* YAML
(``kubesv/kubesv/parser.py:10``); here ingestion is self-contained.
"""
from .yaml_io import (
    IngestError,
    SkipDiagnostic,
    dump_cluster,
    load_cluster,
    load_kano,
    namespace_to_dict,
    network_policy_to_dict,
    parse_network_policy,
    parse_namespace,
    parse_pod,
    pod_to_dict,
)

__all__ = [
    "IngestError",
    "SkipDiagnostic",
    "dump_cluster",
    "load_cluster",
    "load_kano",
    "namespace_to_dict",
    "network_policy_to_dict",
    "parse_network_policy",
    "parse_namespace",
    "parse_pod",
    "pod_to_dict",
]
