"""Cluster ingestion: YAML → model objects, no cluster/kube-config required.

The reference needed a loadable ``~/.kube/config`` just to *parse* YAML
(``kubesv/kubesv/parser.py:10``); here ingestion is self-contained.
"""
from .yaml_io import (
    IngestError,
    SkipDiagnostic,
    dump_cluster,
    load_cluster,
    load_kano,
    parse_network_policy,
    parse_namespace,
    parse_pod,
)

__all__ = [
    "IngestError",
    "SkipDiagnostic",
    "dump_cluster",
    "load_cluster",
    "load_kano",
    "parse_network_policy",
    "parse_namespace",
    "parse_pod",
]
