"""Replicated serving: the epoch-stamped WAL codec, the atomic lease
heartbeat and its fencing, follower bootstrap + exactly-once tailing with
staleness-bounded reads, breaker-gated promotion with exactly-one-winner
claim arbitration, the ``serve --follow`` / ``recover`` CLI surface, the
bench-gate direction entries, and the SIGKILL failover chaos run (leader
killed at every named kill-point with two followers attached)."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import kubernetes_verification_tpu as kv
from kubernetes_verification_tpu.cli import main
from kubernetes_verification_tpu.harness.generate import (
    GeneratorConfig,
    random_cluster,
    random_event_stream,
)
from kubernetes_verification_tpu.observe import REGISTRY
from kubernetes_verification_tpu.observe.history import _direction
from kubernetes_verification_tpu.observe.metrics import REQUIRED_FAMILIES
from kubernetes_verification_tpu.resilience import (
    EXIT_OK,
    FencedError,
    PersistError,
    ServeError,
    StaleReadError,
)
from kubernetes_verification_tpu.resilience.breaker import CLOSED, OPEN
from kubernetes_verification_tpu.resilience.errors import exit_code_for
from kubernetes_verification_tpu.resilience.faults import (
    KILL_POINTS,
    clear_kill_points,
)
from kubernetes_verification_tpu.serve import (
    CheckpointManager,
    EventSource,
    FollowerService,
    LeaseFile,
    UpdatePodLabels,
    VerificationService,
    WalWriter,
    decode_record,
    encode_event,
    lease_path,
    scan_wal,
)
from kubernetes_verification_tpu.serve.events import decode_wal

CHILD = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "replication_child.py"
)


def _counter(name, key):
    return REGISTRY.dump()["counters"].get(name, {}).get(key, 0.0)


class Clock:
    """Injectable wall clock. Starts at the REAL time.time() — Lease
    timestamps are wall-clock, so a fake below real time never expires
    anything written with the real clock."""

    def __init__(self):
        self.t = time.time()

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(scope="module")
def churn():
    cluster = random_cluster(
        GeneratorConfig(
            n_pods=24, n_policies=10, n_namespaces=3, seed=7,
            p_ipblock_peer=0.0, min_selector_labels=1,
        )
    )
    events = random_event_stream(cluster, n_events=120, seed=3)
    cfg = kv.VerifyConfig(backend="cpu", compute_ports=False)
    return cluster, events, cfg


def _reach(svc):
    return np.asarray(svc.reach())


def _leader_dir(tmp_path, churn, *, ttl=60.0, ck_at=60, clock=time.time):
    """Write a leader's on-disk footprint: epoch-1 WAL, one mid-stream
    checkpoint, and a renewed lease. Returns (log, ckdir, leader svc)."""
    cluster, events, cfg = churn
    log = str(tmp_path / "events.jsonl")
    ckdir = str(tmp_path / "ck")
    os.makedirs(ckdir, exist_ok=True)
    lease = LeaseFile(ckdir, clock=clock)
    lease.acquire("leader-0", ttl=ttl)
    svc = VerificationService(cluster, cfg)
    cm = CheckpointManager(ckdir)
    writer = WalWriter(log, epoch=1, lease=lease)
    src = EventSource(log)
    writer.append(events[:ck_at])
    for b in src.batches(64):
        svc.apply(b)
    cm.checkpoint(
        svc.engine, log_path=log, log_offset=src.offset, last_seq=src.last_seq
    )
    writer.append(events[ck_at:])
    for b in src.batches(64):
        svc.apply(b)
    writer.close()
    lease.renew("leader-0", 1, ttl)
    return log, ckdir, svc


def _relabel(svc, k):
    """An idempotent-safe churn event: flip one label on an existing pod."""
    pods = svc.engine.pods
    p = pods[k % len(pods)]
    labels = dict(p.labels)
    labels["churn"] = str(k)
    return UpdatePodLabels(namespace=p.namespace, pod=p.name, labels=labels)


# -------------------------------------------------------------- epoch codec
def test_epoch_codec_round_trips_inside_crc(churn):
    _, events, _ = churn
    line = encode_event(events[0], seq=5, epoch=3)
    obj = json.loads(line)
    assert obj["seq"] == 5 and obj["epoch"] == 3 and "crc" in obj
    ev, seq, epoch = decode_wal(line)
    assert (seq, epoch) == (5, 3)
    assert encode_event(ev) == encode_event(events[0])
    # decode_record stays the 2-tuple compat wrapper
    assert decode_record(line)[1] == 5
    # the epoch is INSIDE the checksum: tampering it must not decode
    tampered = line.replace('"epoch": 3', '"epoch": 9')
    with pytest.raises(Exception, match="checksum"):
        decode_wal(tampered)
    # legacy (frameless) records still decode, with no seq and no epoch
    legacy = encode_event(events[0])
    assert decode_wal(legacy) == (events[0], None, None)
    # epoch is only stamped on sequenced records
    assert "epoch" not in json.loads(encode_event(events[0], epoch=3))


def test_scan_wal_tracks_epoch_and_rejects_regression(tmp_path, churn):
    _, events, _ = churn
    log = str(tmp_path / "wal.jsonl")
    with open(log, "w") as fh:
        for i, epoch in enumerate((1, 1, 2)):
            fh.write(encode_event(events[i], seq=i, epoch=epoch) + "\n")
    info = scan_wal(log)
    assert info.last_epoch == 2 and info.records == 3
    with open(log, "a") as fh:  # a fenced leader kept writing
        fh.write(encode_event(events[3], seq=3, epoch=1) + "\n")
    with pytest.raises(ServeError, match="epoch regressed"):
        scan_wal(log)


def test_event_source_min_epoch_drops_fenced_records(tmp_path, churn):
    _, events, _ = churn
    log = str(tmp_path / "wal.jsonl")
    with open(log, "w") as fh:
        for i, epoch in enumerate((1, 1, 2, 2)):
            fh.write(encode_event(events[i], seq=i, epoch=epoch) + "\n")
    src = EventSource(log, min_epoch=2)
    got = list(src.replay())
    assert got == events[2:4]
    assert src.fenced == 2 and src.last_epoch == 2


def test_event_source_drops_epoch_regression_while_tailing(tmp_path, churn):
    """A fenced leader's stray append (old epoch after a newer reign's
    records) is dropped by a live tail — the same shape scan_wal raises
    on at open — while the new reign keeps applying."""
    _, events, _ = churn
    log = str(tmp_path / "wal.jsonl")
    with open(log, "w") as fh:
        for i, epoch in enumerate((1, 1, 2)):
            fh.write(encode_event(events[i], seq=i, epoch=epoch) + "\n")
    src = EventSource(log)
    assert list(src.replay()) == events[:3]
    with open(log, "a") as fh:  # the deposed leader kept writing
        fh.write(encode_event(events[3], seq=3, epoch=1) + "\n")
    assert list(src.replay()) == []
    assert src.fenced == 1 and src.last_epoch == 2 and src.last_seq == 2
    with open(log, "a") as fh:  # the new reign is unaffected
        fh.write(encode_event(events[4], seq=3, epoch=2) + "\n")
    assert list(src.replay()) == [events[4]]


def test_wal_writer_refuses_log_with_newer_epoch(tmp_path, churn):
    _, events, _ = churn
    log = str(tmp_path / "wal.jsonl")
    w = WalWriter(log, epoch=2)
    w.append(events[:2])
    w.close()
    with pytest.raises(FencedError):
        WalWriter(log, epoch=1)


# ------------------------------------------------------------- tail backoff
def test_tail_backoff_doubles_and_caps(tmp_path, churn):
    _, events, _ = churn
    log = str(tmp_path / "wal.jsonl")
    WalWriter(log).append(events[:3])
    sleeps = []
    src = EventSource(log)
    batches = list(
        src.tail(
            poll_interval=0.01, max_poll_interval=0.05,
            idle_timeout=0.25, batch_size=64, sleep=sleeps.append,
            jitter=0.0,
        )
    )
    assert sum(len(b) for b in batches) == 3
    # jitter off: idle polls back off exponentially from the base
    # interval to the cap, exactly
    assert sleeps[:4] == [0.01, 0.02, 0.04, 0.05]
    assert all(s <= 0.05 for s in sleeps)


def test_tail_backoff_jitter_bounded_and_decorrelated(tmp_path, churn):
    """Default jitter stretches each idle sleep by U[0, 10%) — bounded
    within [base, base*1.1) at every step, still capped, and two
    followers seeded differently don't poll in phase."""
    _, events, _ = churn
    log = str(tmp_path / "wal.jsonl")
    WalWriter(log).append(events[:3])

    def _sleeps(seed):
        sleeps = []
        src = EventSource(log)
        list(
            src.tail(
                poll_interval=0.01, max_poll_interval=0.05,
                idle_timeout=0.25, batch_size=64, sleep=sleeps.append,
                seed=seed,
            )
        )
        return sleeps

    a = _sleeps(1)
    expected = [0.01, 0.02, 0.04, 0.05]
    for s, base in zip(a, expected + [0.05] * len(a)):
        assert base <= s < base * 1.1 + 1e-12
    assert a != _sleeps(2)  # different seeds, different phase


# -------------------------------------------------------------------- lease
def test_lease_acquire_renew_fence_and_describe(tmp_path):
    clock = Clock()
    lf = LeaseFile(str(tmp_path), clock=clock)
    assert lf.read() is None and lf.expired()
    lease = lf.acquire("a", ttl=5.0)
    assert lease.epoch == 1 and not lf.expired()
    assert lf.acquire("b", ttl=5.0).epoch == 2  # monotonic reigns
    with pytest.raises(FencedError):  # a deposed holder cannot renew
        lf.renew("a", 1, 5.0)
    clock.advance(6.0)
    assert lf.expired()
    d = lf.describe()
    assert d["present"] and d["epoch"] == 2 and d["holder"] == "b"
    assert d["expired"] and d["age_seconds"] >= 6.0
    # atomic promotion: no tmp file survives a completed renew
    assert not os.path.exists(lease_path(str(tmp_path)) + ".tmp")
    with open(lease_path(str(tmp_path)), "w") as fh:
        fh.write("{torn")
    with pytest.raises(PersistError):
        lf.read()


def test_renew_refuses_equal_epoch_different_holder(tmp_path):
    """The lease renewal is the promotion protocol's final arbiter: two
    claimants racing one target epoch must not both hold the reign."""
    clock = Clock()
    lf = LeaseFile(str(tmp_path), clock=clock)
    lf.acquire("a", ttl=5.0)  # epoch 1
    lf.renew("a", 1, 5.0)  # self-renewal at one's own epoch stays fine
    with pytest.raises(FencedError):  # a rival cannot share the epoch
        lf.renew("b", 1, 5.0)
    assert lf.read().holder == "a"


def test_corrupt_lease_counts_as_dead_leader(tmp_path, churn):
    """A bit-rotted lease must feed the breaker toward failover, not
    permanently block promotion with a PersistError."""
    clock = Clock()
    log, ckdir, _ = _leader_dir(tmp_path, churn, ttl=5.0, clock=clock)
    f = FollowerService(
        ckdir, log_path=log, replica="r1",
        breaker_threshold=2, lease_ttl=5.0, clock=clock,
    )
    with open(lease_path(ckdir), "w") as fh:
        fh.write("{bit rot")
    assert f.lease.expired()  # unreadable == no live leader
    assert not f.heartbeat()
    assert not f.heartbeat()
    assert f.probe.state == OPEN
    assert f.maybe_promote()  # promotes through the rot, no PersistError
    assert f.promoted and f.epoch == 2  # prior reign from the WAL's epochs
    assert f.lease.read().holder == "r1"


# ---------------------------------------------------------------- bootstrap
def test_follower_bootstraps_bit_for_bit_and_never_writes(tmp_path, churn):
    log, ckdir, leader = _leader_dir(tmp_path, churn)
    f = FollowerService(ckdir, log_path=log, replica="r1")
    assert f.recovery.outcome == "newest"
    assert f.recovery.duplicates_skipped == 0
    f.catch_up()
    assert f.lag().caught_up
    np.testing.assert_array_equal(_reach(f.service), _reach(leader))
    # read-only: the follower side can never produce durable artifacts
    assert f.service.read_only
    with pytest.raises(ServeError, match="read-only"):
        f.service.snapshot(str(tmp_path / "snap"))
    with pytest.raises(ServeError, match="read-only"):
        f.service.start()


def test_follower_queries_answer_through_guard(tmp_path, churn):
    log, ckdir, leader = _leader_dir(tmp_path, churn)
    f = FollowerService(ckdir, log_path=log, replica="r1")
    pods = leader.engine.pods
    a = f"{pods[0].namespace}/{pods[0].name}"
    b = f"{pods[1].namespace}/{pods[1].name}"
    want = bool(_reach(leader)[0, 1])
    assert f.can_reach(a, b) == want
    assert list(f.can_reach_batch([(a, b)])) == [want]


# ------------------------------------------------------------- stale reads
def test_stale_read_rejected_with_measured_lag(tmp_path, churn):
    log, ckdir, leader = _leader_dir(tmp_path, churn)
    f = FollowerService(
        ckdir, log_path=log, replica="r1",
        max_lag_seq=0, auto_catch_up=False,
    )
    f.catch_up()
    w = WalWriter(log, epoch=1)  # the leader keeps writing
    w.append([_relabel(leader, k) for k in range(5)])
    w.close()
    before = _counter("kvtpu_stale_reads_total", "outcome=rejected")
    pods = leader.engine.pods
    a = f"{pods[0].namespace}/{pods[0].name}"
    with pytest.raises(StaleReadError) as ei:
        f.can_reach(a, a)
    assert ei.value.lag_seq == 5 and ei.value.bound_seq == 0
    assert exit_code_for(ei.value) == 2  # ServeError family → input error
    assert (
        _counter("kvtpu_stale_reads_total", "outcome=rejected") == before + 1
    )


def test_stale_read_proxies_when_enabled(tmp_path, churn):
    log, ckdir, leader = _leader_dir(tmp_path, churn)
    f = FollowerService(
        ckdir, log_path=log, replica="r1",
        max_lag_seq=0, auto_catch_up=False, proxy_stale=True,
    )
    f.catch_up()
    w = WalWriter(log, epoch=1)
    w.append([_relabel(leader, k) for k in range(5)])
    w.close()
    before = _counter("kvtpu_stale_reads_total", "outcome=proxied")
    pods = leader.engine.pods
    a = f"{pods[0].namespace}/{pods[0].name}"
    assert f.can_reach(a, a) is not None  # answered, not raised
    assert (
        _counter("kvtpu_stale_reads_total", "outcome=proxied") == before + 1
    )
    assert f.lag().caught_up  # the proxy forced a full catch-up


# ----------------------------------------------------------------- failover
def test_promotion_is_breaker_gated(tmp_path, churn):
    clock = Clock()
    log, ckdir, leader = _leader_dir(
        tmp_path, churn, ttl=5.0, clock=clock
    )
    f = FollowerService(
        ckdir, log_path=log, replica="r2",
        breaker_threshold=2, lease_ttl=5.0, clock=clock,
    )
    # live lease: no promotion, breaker stays closed
    assert f.heartbeat() and f.probe.state == CLOSED
    assert not f.maybe_promote()
    # lease expires, but ONE missed heartbeat is jitter, not death
    clock.advance(6.0)
    assert not f.heartbeat()
    assert not f.maybe_promote()
    # the second consecutive failure opens the breaker → promotion
    assert not f.heartbeat()
    assert f.probe.state == OPEN
    before = _counter("kvtpu_promotions_total", "replica=r2")
    assert f.maybe_promote()
    assert f.promoted and f.epoch == 2
    assert _counter("kvtpu_promotions_total", "replica=r2") == before + 1
    assert f.lease.read().holder == "r2"
    # the promoted follower owns a fenced writer at the new epoch
    f.writer.append([_relabel(leader, 0)])
    assert scan_wal(log).last_epoch == 2
    # ... and the deposed leader is fenced on BOTH paths
    with pytest.raises(FencedError):
        old = WalWriter(log[:-6] + "other.jsonl", epoch=1, lease=f.lease)
        old.append([_relabel(leader, 1)])
    with pytest.raises(FencedError):
        f.lease.renew("leader-0", 1, 5.0)


def test_heartbeat_does_not_fence_unapplied_prior_reign(tmp_path, churn):
    """A follower that observes a new lease epoch while still BEHIND the
    promotion point must not raise its min_epoch floor yet: the previous
    reign's committed records it has not applied would be silently
    fence-dropped and its state would diverge from the leader's."""
    clock = Clock()
    log, ckdir, leader = _leader_dir(tmp_path, churn, ttl=1.0, clock=clock)
    f = FollowerService(
        ckdir, log_path=log, replica="r1",
        auto_catch_up=False, lease_ttl=1.0, clock=clock,
    )
    f.catch_up()
    # the old reign commits more records; r1 does NOT poll them
    lease = LeaseFile(ckdir, clock=clock)
    w = WalWriter(log, epoch=1, lease=lease)
    w.append([_relabel(leader, k) for k in range(4)])
    w.close()
    # the leader dies; a sibling follower (already at the tip) promotes
    clock.advance(2.0)
    sib = FollowerService(
        ckdir, log_path=log, replica="r2",
        breaker_threshold=1, lease_ttl=1.0, clock=clock,
    )
    assert not sib.heartbeat()
    assert sib.maybe_promote() and sib.epoch == 2
    sib.writer.append([_relabel(sib.service, k) for k in range(90, 93)])
    sib.catch_up()
    # r1 heartbeats while still behind: it sees epoch 2 in the lease but
    # must not fence the epoch-1 records it still owes itself
    f.heartbeat()
    assert f.source.min_epoch in (None, 1)
    f.catch_up()
    assert f.source.fenced == 0
    np.testing.assert_array_equal(_reach(f.service), _reach(sib.service))
    # once caught up past the transition, the floor may rise
    f.heartbeat()
    assert f.source.min_epoch == 2


def test_catch_up_bounded_on_undecodable_tail(tmp_path, churn):
    """An invalid newline-terminated WAL tail (a dead leader's torn
    buffered write) is left unconsumed by the source but still counts as
    a pending newline — catch_up must return, not spin forever."""
    log, ckdir, _ = _leader_dir(tmp_path, churn)
    f = FollowerService(
        ckdir, log_path=log, replica="r1", auto_catch_up=False
    )
    f.catch_up()
    with open(log, "a") as fh:
        fh.write('{"event": "add_policy", "torn\n')
    assert f.catch_up() == 0  # bounded: returns despite pending newline
    assert f.lag().seq == 1  # the junk still measures as lag


def test_claim_sweep_runs_on_the_injected_clock(tmp_path, churn):
    """Claim staleness is judged in the injected clock's time base (via
    the claimed_at stamped inside the claim), so a fake-clock harness can
    exercise the dead-claimant sweep without real sleeps."""
    clock = Clock()
    log, ckdir, _ = _leader_dir(tmp_path, churn, ttl=1.0, clock=clock)
    fa = FollowerService(
        ckdir, log_path=log, replica="ra", lease_ttl=1.0, clock=clock
    )
    fb = FollowerService(
        ckdir, log_path=log, replica="rb", lease_ttl=1.0, clock=clock
    )
    assert fa._claim(2) and not fb._claim(2)  # a fresh claim blocks
    # ra dies mid-promotion (epoch never bumped); only the FAKE clock
    # advances — the sweep must still see the claim as stale
    clock.advance(5.0)
    assert fb._claim(2)


def test_claim_arbitration_exactly_one_winner(tmp_path, churn):
    clock = Clock()
    log, ckdir, _ = _leader_dir(tmp_path, churn, ttl=1.0, clock=clock)
    fa = FollowerService(ckdir, log_path=log, replica="ra", clock=clock)
    fb = FollowerService(ckdir, log_path=log, replica="rb", clock=clock)
    wins = [fa._claim(2), fb._claim(2)]
    assert sorted(wins) == [False, True]
    assert os.path.exists(os.path.join(ckdir, "promote-00000002.claim"))


def test_loser_does_not_promote_after_winner_renews(tmp_path, churn):
    clock = Clock()
    log, ckdir, _ = _leader_dir(tmp_path, churn, ttl=1.0, clock=clock)
    fa = FollowerService(
        ckdir, log_path=log, replica="ra",
        breaker_threshold=2, lease_ttl=1.0, clock=clock,
    )
    fb = FollowerService(
        ckdir, log_path=log, replica="rb",
        breaker_threshold=2, lease_ttl=1.0, clock=clock,
    )
    clock.advance(2.0)
    for _ in range(2):
        fa.heartbeat()
        fb.heartbeat()
    promoted = [f.maybe_promote() for f in (fa, fb)]
    assert promoted == [True, False]  # winner renewed → loser sees a live lease
    assert fa.epoch == 2 and not fb.promoted


# ---------------------------------------------------------------- CLI surface
def test_cli_serve_follow_answers_and_reports(tmp_path, churn, capsys):
    log, ckdir, leader = _leader_dir(tmp_path, churn)
    rc = main([
        "serve", "--follow", ckdir, "--events", log,
        "--idle-timeout", "0.2", "--tail-poll", "0.01", "--json",
    ])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == EXIT_OK
    assert out["replica"] == "follower" and out["outcome"] == "newest"
    assert out["lag_seq"] == 0 and not out["promoted"]
    assert out["reachable_pairs"] == int(_reach(leader).sum())


def test_cli_serve_follow_promotes_on_lease_expiry(tmp_path, churn, capsys):
    log, ckdir, _ = _leader_dir(tmp_path, churn, ttl=0.2)
    time.sleep(0.3)
    rc = main([
        "serve", "--follow", ckdir, "--events", log,
        "--promote-on-lease-expiry", "--lease-ttl", "0.2",
        "--idle-timeout", "10", "--tail-poll", "0.01", "--json",
    ])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == EXIT_OK
    assert out["promoted"] and out["epoch"] == 2


def test_cli_recover_json_reports_lease_and_epoch(tmp_path, churn, capsys):
    log, ckdir, _ = _leader_dir(tmp_path, churn)
    rc = main(["recover", ckdir, "--events", log, "--json"])
    report = json.loads(capsys.readouterr().out.strip())
    assert rc == EXIT_OK
    assert report["wal"]["last_epoch"] == 1
    lease = report["lease"]
    assert lease["present"] and lease["epoch"] == 1
    assert lease["holder"] == "leader-0" and "age_seconds" in lease
    # text mode prints the lease line too
    rc = main(["recover", ckdir, "--events", log])
    text = capsys.readouterr().out
    assert rc == EXIT_OK and "lease" in text and "epoch 1" in text


# ------------------------------------------------- observability / gating
def test_new_metric_families_registered():
    for fam in (
        "kvtpu_replica_lag_seconds",
        "kvtpu_replica_lag_seq",
        "kvtpu_promotions_total",
        "kvtpu_stale_reads_total",
    ):
        assert fam in REQUIRED_FAMILIES


def test_bench_gate_directions():
    assert _direction("queries/s", "aggregate_queries_per_second") == "higher"
    assert _direction(None, "aggregate_queries_per_second") == "higher"
    assert _direction("s", "replica_lag_seconds") == "lower"
    assert _direction(None, "replica_lag_seconds") == "lower"


def test_new_kill_points_registered():
    assert "before-lease-renew" in KILL_POINTS
    assert "after-promote-epoch" in KILL_POINTS


def test_exit_contract_covers_follow_and_promotion_paths():
    """The interprocedural exit-contract rule must see straight through
    ``cmd_serve → _run_serve → _run_follow → FollowerService`` — the new
    StaleReadError/FencedError raise sites are KvTpuError subclasses
    caught by cmd_serve's handler, so the whole CLI stays finding-free."""
    from kubernetes_verification_tpu.analysis.core import run_package

    result = run_package(rules=["exit-contract"])
    assert result.findings == []


# ------------------------------------------------------------ failover chaos
def _run_child(workdir, kill, *, role="leader", promote=False, seed=3,
               n_events=60, pods=24, batch=10, checkpoint_every=2,
               lease_ttl=0.3):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [
        sys.executable, CHILD, "--workdir", str(workdir),
        "--kill", kill, "--role", role, "--seed", str(seed),
        "--n-events", str(n_events), "--pods", str(pods),
        "--batch", str(batch), "--checkpoint-every", str(checkpoint_every),
        "--lease-ttl", str(lease_ttl),
    ]
    if promote:
        cmd.append("--promote")
    return subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=300,
    )


def _attach_two_followers(ckdir, log, cluster, cfg, ttl, *, first=0):
    """The failover dance the chaos runs share: two followers attach to a
    dead leader's directory, both watch the lease die and the breaker
    open, and EXACTLY one wins the promotion claim."""
    time.sleep(ttl + 0.2)  # let the (real-clock) lease expire
    mk = lambda name: FollowerService(
        ckdir, log_path=log, replica=name,
        initial_cluster=cluster, config=cfg,
        breaker_threshold=2, lease_ttl=ttl,
    )
    followers = [mk("ra"), mk("rb")]
    for f in followers:
        assert f.recovery.duplicates_skipped == 0
    for _ in range(2):
        for f in followers:
            f.heartbeat()
    order = followers if first == 0 else followers[::-1]
    promoted = [f for f in order if f.maybe_promote()]
    assert len(promoted) == 1, "exactly one follower must win the epoch"
    return followers, promoted[0]


def _assert_failover_invariants(workdir, cluster, cfg, winner, followers,
                                prior_epoch=1):
    """Post-promotion invariants shared by every chaos run: the old epoch
    is fenced on the write path, and the promoted follower answers
    bit-for-bit with a from-scratch verification of the surviving log
    prefix (continued through the new epoch's writes)."""
    log = os.path.join(str(workdir), "events.jsonl")
    assert winner.epoch == prior_epoch + 1
    # fenced: the dead leader's epoch can no longer append to ANY log
    # governed by this lease
    stray = os.path.join(str(workdir), "stray.jsonl")
    with pytest.raises(FencedError):
        WalWriter(stray, epoch=prior_epoch, lease=winner.lease).append(
            [_relabel(winner.service, 99)]
        )
    # the new reign writes through the promoted writer...
    winner.writer.append(
        [_relabel(winner.service, k) for k in range(3)]
    )
    info = scan_wal(log)
    assert info.last_epoch == winner.epoch and not info.torn
    # ...and every replica converges on the same answer as a from-scratch
    # verification of the surviving prefix (zero duplicate applications:
    # exactly-once resume is what makes these equal)
    oracle = VerificationService(cluster, cfg)
    survived = 0
    for b in EventSource(log).batches(256):
        oracle.apply(b)
        survived += len(b)
    assert survived == info.records
    for f in followers:
        f.catch_up()
        np.testing.assert_array_equal(_reach(f.service), _reach(oracle))


def _chaos_cluster(pods):
    cluster = random_cluster(
        GeneratorConfig(
            n_pods=pods, n_policies=24, n_namespaces=6, seed=7,
            p_ipblock_peer=0.0, min_selector_labels=1,
        )
    )
    return cluster, kv.VerifyConfig(backend="cpu", compute_ports=False)


def test_failover_chaos_lease_renew_kill(tmp_path):
    """One fast end-to-end failover: SIGKILL the leader inside a lease
    renewal mid-stream, attach two followers, and check the whole
    protocol — single promotion, fencing, bit-for-bit convergence."""
    clear_kill_points()
    proc = _run_child(tmp_path, "before-lease-renew@4")
    assert proc.returncode == 137, proc.stderr
    cluster, cfg = _chaos_cluster(24)
    log = str(tmp_path / "events.jsonl")
    followers, winner = _attach_two_followers(
        str(tmp_path / "ck"), log, cluster, cfg, 0.3
    )
    _assert_failover_invariants(tmp_path, cluster, cfg, winner, followers)


@pytest.mark.slow
def test_failover_chaos_every_kill_point(tmp_path):
    """The acceptance chaos: a 500-event churn stream, the leader
    SIGKILLed at EVERY named kill-point (the promotion-side point fires
    inside a promoting follower — the new leader dying mid-handover),
    two followers attached per run; every run must elect exactly one new
    leader, fence the old epoch, and answer bit-for-bit with a
    from-scratch verification of the surviving prefix."""
    clear_kill_points()
    n_events, pods, batch, ck_every = 500, 64, 25, 3
    cluster, cfg = _chaos_cluster(pods)
    kill_at = {
        "mid-log-append": 137,   # record index
        "after-tmp-write": 2,    # checkpoint-internal hits
        "before-rename": 2,
        "after-manifest": 2,
        "before-lease-renew": 10,  # of ~21 renewals
        "after-promote-epoch": 0,  # fires in the promoting follower
    }
    kills = 0
    for i, point in enumerate(KILL_POINTS):
        workdir = tmp_path / f"run-{i}-{point}"
        workdir.mkdir()
        spec = f"{point}@{kill_at[point]}"
        log = str(workdir / "events.jsonl")
        ckdir = str(workdir / "ck")
        prior_epoch = 1
        if point == "after-promote-epoch":
            # clean leader run, then a promoting follower dies right
            # after bumping the lease epoch — the half-handover state
            proc = _run_child(
                workdir, "", n_events=n_events, pods=pods, batch=batch,
                checkpoint_every=ck_every,
            )
            assert proc.returncode == 0, proc.stderr
            time.sleep(0.5)  # lease (ttl 0.3) dies with the leader
            proc = _run_child(
                workdir, spec, role="follower", promote=True,
                n_events=n_events, pods=pods, batch=batch,
                checkpoint_every=ck_every,
            )
            assert proc.returncode == 137, (spec, proc.stderr)
            dead = LeaseFile(ckdir).read()
            assert dead.epoch == 2  # bumped before the kill...
            assert scan_wal(log).last_epoch == 1  # ...nothing written at it
            prior_epoch = 2  # the survivors take over from the dead reign
        else:
            proc = _run_child(
                workdir, spec, n_events=n_events, pods=pods, batch=batch,
                checkpoint_every=ck_every,
            )
            assert proc.returncode == 137, (spec, proc.stderr)
        kills += 1
        followers, winner = _attach_two_followers(
            ckdir, log, cluster, cfg, 0.3, first=i % 2
        )
        _assert_failover_invariants(
            workdir, cluster, cfg, winner, followers,
            prior_epoch=prior_epoch,
        )
    assert kills == len(KILL_POINTS)
