"""Differential tests: the tensorised JAX backend must agree exactly with the
object-level CPU reference backend — the rebuild's first-class version of the
reference's implicit two-verifier cross-check (SURVEY.md §4)."""
import numpy as np
import pytest

from kubernetes_verification_tpu import (
    VerifyConfig,
    verify,
    verify_kano,
)
from kubernetes_verification_tpu.harness.generate import (
    GeneratorConfig,
    random_cluster,
    random_kano,
)
from kubernetes_verification_tpu.models.fixtures import (
    kano_paper_example,
    kano_paper_example_as_cluster,
    kubesv_paper_example,
)

CPU = VerifyConfig(backend="cpu")
TPU = VerifyConfig(backend="tpu")


def _assert_same(res_cpu, res_tpu, ports=True):
    np.testing.assert_array_equal(res_cpu.reach, res_tpu.reach)
    np.testing.assert_array_equal(res_cpu.src_sets, res_tpu.src_sets)
    np.testing.assert_array_equal(res_cpu.dst_sets, res_tpu.dst_sets)
    if ports and res_cpu.reach_ports is not None:
        np.testing.assert_array_equal(res_cpu.reach_ports, res_tpu.reach_ports)
    if res_cpu.selected is not None:
        np.testing.assert_array_equal(res_cpu.selected, res_tpu.selected)
        np.testing.assert_array_equal(
            res_cpu.ingress_isolated, res_tpu.ingress_isolated
        )
        np.testing.assert_array_equal(res_cpu.egress_isolated, res_tpu.egress_isolated)
    if res_cpu.closure is not None or res_tpu.closure is not None:
        np.testing.assert_array_equal(res_cpu.closure, res_tpu.closure)


class TestKanoParity:
    def test_paper_example(self):
        c1, p1 = kano_paper_example()
        c2, p2 = kano_paper_example()
        _assert_same(verify_kano(c1, p1, CPU), verify_kano(c2, p2, TPU))

    def test_paper_example_ground_truth_on_tpu(self):
        containers, policies = kano_paper_example()
        res = verify_kano(containers, policies, TPU)
        assert res.reachable(0, 1) and res.reachable(2, 0) and res.reachable(4, 2)
        assert res.all_reachable() == []
        assert res.all_isolated() == [4]
        assert res.user_crosscheck(containers, "app") == [1, 2, 3]
        assert res.policy_shadow() == [(2, 3), (3, 2)]
        assert containers[2].select_policies == [2, 3]

    @pytest.mark.parametrize("seed", range(5))
    def test_random_kano(self, seed):
        c1, p1 = random_kano(n_containers=60, n_policies=30, seed=seed)
        c2, p2 = random_kano(n_containers=60, n_policies=30, seed=seed)
        _assert_same(verify_kano(c1, p1, CPU), verify_kano(c2, p2, TPU))

    def test_closure_parity(self):
        c1, p1 = random_kano(n_containers=40, n_policies=20, seed=9)
        cfg_c = VerifyConfig(backend="cpu", closure=True)
        cfg_t = VerifyConfig(backend="tpu", closure=True)
        _assert_same(verify_kano(c1, p1, cfg_c), verify_kano(c1, p1, cfg_t))


class TestK8sParity:
    def test_kano_cluster_fixture(self):
        _assert_same(
            verify(kano_paper_example_as_cluster(), CPU),
            verify(kano_paper_example_as_cluster(), TPU),
        )

    @pytest.mark.slow
    def test_kubesv_paper_example_all_flag_combos(self):
        cluster = kubesv_paper_example()
        for self_traffic in (True, False):
            for default_allow in (True, False):
                for dir_aware in (True, False):
                    kw = dict(
                        self_traffic=self_traffic,
                        default_allow_unselected=default_allow,
                        direction_aware_isolation=dir_aware,
                    )
                    _assert_same(
                        verify(cluster, VerifyConfig(backend="cpu", **kw)),
                        verify(cluster, VerifyConfig(backend="tpu", **kw)),
                    )

    @pytest.mark.parametrize("seed", range(8))
    def test_random_clusters(self, seed):
        cluster = random_cluster(
            GeneratorConfig(n_pods=50, n_policies=25, n_namespaces=4, seed=seed)
        )
        _assert_same(verify(cluster, CPU), verify(cluster, TPU))

    def test_random_cluster_reference_compat_flags(self):
        cluster = random_cluster(
            GeneratorConfig(n_pods=40, n_policies=20, n_namespaces=3, seed=42)
        )
        kw = dict(
            self_traffic=True,
            default_allow_unselected=False,
            direction_aware_isolation=False,
        )
        _assert_same(
            verify(cluster, VerifyConfig(backend="cpu", **kw)),
            verify(cluster, VerifyConfig(backend="tpu", **kw)),
        )

    def test_compute_ports_false_parity(self):
        # regression: compute_ports=False must mean "ignore ports", not
        # "enforce an empty port set" (the TPU encoder used to emit all-False
        # port masks for port-carrying rules in this mode).
        from kubernetes_verification_tpu import (
            Cluster,
            NetworkPolicy,
            Peer,
            Pod,
            PortSpec,
            Rule,
            Selector,
        )

        pods = [Pod("a", labels={"app": "a"}), Pod("b", labels={"app": "b"})]
        pol = NetworkPolicy(
            "p",
            pod_selector=Selector({"app": "b"}),
            ingress=(
                Rule(
                    peers=(Peer(pod_selector=Selector({"app": "a"})),),
                    ports=(PortSpec("TCP", 80),),
                ),
            ),
        )
        cluster = Cluster(pods=pods, policies=pol and [pol])
        for backend in ("cpu", "tpu"):
            res = verify(
                cluster, VerifyConfig(backend=backend, compute_ports=False)
            )
            assert res.reach[0, 1], backend
        _assert_same(
            verify(cluster, VerifyConfig(backend="cpu", compute_ports=False)),
            verify(cluster, VerifyConfig(backend="tpu", compute_ports=False)),
            ports=False,
        )

    def test_compat_mode_ignores_policy_types(self):
        # regression: with direction_aware_isolation=False BOTH backends must
        # apply rules of directions the policyTypes exclude (kubesv behaviour).
        from kubernetes_verification_tpu import (
            Cluster,
            NetworkPolicy,
            Peer,
            Pod,
            Rule,
            Selector,
        )

        pods = [Pod("a", labels={"app": "a"}), Pod("b", labels={"app": "b"})]
        pol = NetworkPolicy(
            "p",
            pod_selector=Selector({"app": "b"}),
            policy_types=("Egress",),  # ingress rule below is inert in k8s
            ingress=(Rule(peers=(Peer(pod_selector=Selector({"app": "a"})),)),),
        )
        cluster = Cluster(pods=pods, policies=[pol])
        for dir_aware in (True, False):
            cfg_c = VerifyConfig(
                backend="cpu",
                direction_aware_isolation=dir_aware,
                default_allow_unselected=False,
                self_traffic=False,
            )
            cfg_t = VerifyConfig(
                backend="tpu",
                direction_aware_isolation=dir_aware,
                default_allow_unselected=False,
                self_traffic=False,
            )
            r_cpu, r_tpu = verify(cluster, cfg_c), verify(cluster, cfg_t)
            _assert_same(r_cpu, r_tpu)
            # k8s semantics: inert ingress rule → no edge; compat: edge exists
            # but still needs the egress side, which grants nothing → no reach
            # either way; the observable difference is in src_sets.
            assert bool(r_cpu.src_sets.any()) == (not dir_aware)

    @pytest.mark.parametrize("seed", range(3))
    def test_random_clusters_ipblock_named_ports(self, seed):
        cluster = random_cluster(
            GeneratorConfig(
                n_pods=40,
                n_policies=20,
                n_namespaces=3,
                p_ipblock_peer=0.4,
                p_named_port=0.4,
                p_ports=0.7,
                seed=100 + seed,
            )
        )
        _assert_same(verify(cluster, CPU), verify(cluster, TPU))

    @pytest.mark.slow
    def test_queries_match(self):
        cluster = random_cluster(
            GeneratorConfig(n_pods=40, n_policies=20, n_namespaces=3, seed=7)
        )
        r_cpu = verify(cluster, CPU)
        r_tpu = verify(cluster, TPU)
        assert r_cpu.all_reachable() == r_tpu.all_reachable()
        assert r_cpu.all_isolated() == r_tpu.all_isolated()
        assert r_cpu.user_crosscheck(cluster.pods, "app") == r_tpu.user_crosscheck(
            cluster.pods, "app"
        )
        assert r_cpu.policy_shadow() == r_tpu.policy_shadow()
        assert r_cpu.policy_conflict() == r_tpu.policy_conflict()


class TestProperties:
    """Property tests from SURVEY.md §4's implication list."""

    def test_deny_all_zeroes_columns(self):
        from kubernetes_verification_tpu import Cluster, NetworkPolicy, Pod, Selector

        pods = [Pod(f"p{i}", "default", {"app": str(i)}) for i in range(6)]
        deny = NetworkPolicy("deny", pod_selector=Selector(), ingress=())
        res = verify(
            Cluster(pods=pods, policies=[deny]),
            VerifyConfig(backend="tpu", self_traffic=False),
        )
        assert not res.reach.any()

    def test_no_policies_full_matrix(self):
        from kubernetes_verification_tpu import Cluster, Pod

        pods = [Pod(f"p{i}", "default", {"app": str(i)}) for i in range(6)]
        res = verify(Cluster(pods=pods), TPU)
        assert res.reach.all()
