"""K3 — the pluggable kano label matcher (``LabelRelation``), the
reference's only extension point (``kano_py/kano/model.py:59-68``). A custom
relation must be honored identically by the object-level cpu oracle and the
tensor tpu backend (which re-encodes rule labels into acceptable-pair
masks), while preserving the reference's matcher quirks."""
import numpy as np
import pytest

import kubernetes_verification_tpu as kv


class PrefixRelation(kv.LabelRelation):
    """rule value accepts any label value it prefixes: 'web' ~ 'web-1'."""

    def match(self, rule_value: str, label_value: str) -> bool:
        return label_value.startswith(rule_value)


def _containers():
    return [
        kv.Container("w1", {"app": "web-1", "tier": "fe"}),
        kv.Container("w2", {"app": "web-2", "tier": "fe"}),
        kv.Container("db", {"app": "db-main", "tier": "be"}),
        kv.Container("x", {"tier": "fe"}),  # no app key
    ]


def _policies():
    # ingress: select app≈web, allow from app≈db
    return [kv.KanoPolicy("p", select={"app": "web"}, allow={"app": "db"})]


def test_default_equality_unchanged():
    res = kv.verify_kano(_containers(), _policies(), kv.VerifyConfig())
    # equality: 'web' matches no container → no edges beyond none
    assert not res.reach.any()


@pytest.mark.parametrize("backend", ["cpu", "tpu"])
def test_prefix_relation(backend):
    cfg = kv.VerifyConfig(backend=backend, label_relation=PrefixRelation())
    res = kv.verify_kano(_containers(), _policies(), cfg)
    # ingress direction swap: src = allow (db-main), dst = select (web-*)
    expect = np.zeros((4, 4), dtype=bool)
    expect[2, 0] = expect[2, 1] = True
    np.testing.assert_array_equal(res.reach, expect)
    # the per-policy sets honor the relation too
    np.testing.assert_array_equal(res.src_sets[0], [False, False, True, False])
    np.testing.assert_array_equal(res.dst_sets[0], [True, True, False, False])


def test_cpu_tpu_agree_with_relation():
    containers = _containers()
    pols = [
        kv.KanoPolicy("a", select={"tier": "f"}, allow={"app": "web"}),
        kv.KanoPolicy("b", select={"ghost": "z"}, allow={"tier": "b"}),
        kv.KanoPolicy("c", select={"app": "db"}, allow={}, ingress=False),
    ]
    rel = PrefixRelation()
    r_cpu = kv.verify_kano(
        containers, pols, kv.VerifyConfig(backend="cpu", label_relation=rel)
    )
    r_tpu = kv.verify_kano(
        containers, pols, kv.VerifyConfig(backend="tpu", label_relation=rel)
    )
    np.testing.assert_array_equal(r_cpu.reach, r_tpu.reach)
    np.testing.assert_array_equal(r_cpu.src_sets, r_tpu.src_sets)
    np.testing.assert_array_equal(r_cpu.dst_sets, r_tpu.dst_sets)


def test_unknown_key_quirk_preserved():
    """Rule keys no container carries are ignored under any relation
    (kano_py/kano/model.py:142-154); known keys still require presence."""
    containers = _containers()
    pols = [kv.KanoPolicy("q", select={"ghost": "x"}, allow={"app": "w"})]
    rel = PrefixRelation()
    for backend in ("cpu", "tpu"):
        res = kv.verify_kano(
            containers, pols,
            kv.VerifyConfig(backend=backend, label_relation=rel),
        )
        # ghost ignored → select matches everyone; allow 'w' prefixes web-*
        np.testing.assert_array_equal(
            res.dst_sets[0], [True, True, True, True], err_msg=backend
        )
        np.testing.assert_array_equal(
            res.src_sets[0], [True, True, False, False], err_msg=backend
        )


def test_k8s_mode_rejects_relation():
    cluster = kv.Cluster(pods=[kv.Pod("a", "default", {})])
    with pytest.raises(ValueError, match="kano"):
        kv.verify(cluster, kv.VerifyConfig(label_relation=PrefixRelation()))


def test_unsupported_backend_rejected():
    containers = _containers()
    pols = _policies()
    for backend in ("native", "sharded", "datalog"):
        if backend not in kv.available_backends():
            continue
        with pytest.raises(ValueError, match="label_relation"):
            kv.verify_kano(
                containers, pols,
                kv.VerifyConfig(backend=backend, label_relation=PrefixRelation()),
            )
