"""The posture observability plane: packed delta kernels against numpy
oracles, the 500-event churn fuzz holding the tracker bit-identical to a
dense recompute-and-diff at every generation, the crc'd journal's
torn-tail contract, declarative drift alerts (typed error + metric +
flight dump), the `kv-tpu posture` / fleet surface, and the
``bounded-journal`` lint rule's fixtures."""
import json
import textwrap

import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_verification_tpu.analysis import lint_source, rule_ids
from kubernetes_verification_tpu.backends.base import VerifyConfig
from kubernetes_verification_tpu.cli import main
from kubernetes_verification_tpu.harness.generate import (
    GeneratorConfig,
    random_cluster,
    random_event_stream,
)
from kubernetes_verification_tpu.observe import flight
from kubernetes_verification_tpu.observe.metrics import REQUIRED_FAMILIES
from kubernetes_verification_tpu.observe.registry import REGISTRY
from kubernetes_verification_tpu.ops.posture import (
    changed_columns,
    ns_pair_counts,
    ns_word_masks,
    packed_row_popcount,
    packed_xor_popcount,
    topk_changed_rows,
)
from kubernetes_verification_tpu.packed_incremental import (
    PackedIncrementalVerifier,
)
from kubernetes_verification_tpu.resilience import (
    EXIT_OK,
    EXIT_VIOLATIONS,
    ServeError,
)
from kubernetes_verification_tpu.serve import (
    PostureAlertError,
    VerificationService,
    parse_posture_rule,
    posture_diff,
    scan_posture,
)
from kubernetes_verification_tpu.serve.posture import (
    NS_PAIR_CAP,
    TOP_K_ROWS,
    WITNESS_CAP,
    PostureRecord,
    _encode_record,
    render_posture_timeline,
)


def _counter(name, key=""):
    return REGISTRY.dump()["counters"].get(name, {}).get(key, 0.0)


def _unpack(words: np.ndarray, n_cols: int) -> np.ndarray:
    """uint32 [R, W] -> bool [R, n_cols] little-bit-order oracle."""
    w = np.ascontiguousarray(np.asarray(words), dtype="<u4")
    bits = np.unpackbits(
        w.view(np.uint8).reshape(w.shape[0], -1), axis=1, bitorder="little"
    )
    return bits[:, :n_cols].astype(bool)


# ----------------------------------------------------------- ops kernels
def test_packed_xor_popcount_matches_unpacked_oracle():
    rng = np.random.default_rng(5)
    prev = rng.integers(0, 2**32, (13, 4), dtype=np.uint32)
    cur = rng.integers(0, 2**32, (13, 4), dtype=np.uint32)
    widened, narrowed, row_w, row_n = packed_xor_popcount(
        jnp.asarray(prev), jnp.asarray(cur)
    )
    p, c = _unpack(prev, 128), _unpack(cur, 128)
    assert np.array_equal(_unpack(widened, 128), c & ~p)
    assert np.array_equal(_unpack(narrowed, 128), p & ~c)
    assert np.array_equal(np.asarray(row_w), (c & ~p).sum(axis=1))
    assert np.array_equal(np.asarray(row_n), (p & ~c).sum(axis=1))
    assert np.array_equal(
        np.asarray(packed_row_popcount(jnp.asarray(cur))), c.sum(axis=1)
    )


def test_topk_changed_rows_is_static_k():
    counts, rows = topk_changed_rows(jnp.asarray([3, 0, 9, 1, 9], np.int32), 3)
    assert np.asarray(counts).shape == (3,)
    assert np.asarray(counts)[0] == 9
    assert set(np.asarray(rows)[:2]) == {2, 4}


def test_ns_pair_counts_matches_dense_grouping():
    rng = np.random.default_rng(9)
    n, words = 50, 2
    delta = rng.integers(0, 2**32, (n, words), dtype=np.uint32)
    # zero the padding columns beyond n so the oracle sees the same plane
    dense = _unpack(delta, words * 32)
    dense[:, n:] = False
    delta = np.packbits(
        np.pad(dense, ((0, 0), (0, words * 32 - dense.shape[1]))).reshape(
            n, words, 32
        ),
        axis=2,
        bitorder="little",
    ).reshape(n, words, 4).view("<u4")[..., 0]
    g = 3
    col_ns = rng.integers(0, g, n)
    row_ns = rng.integers(0, g, n).astype(np.int32)
    masks = ns_word_masks(col_ns, g, words)
    out = np.asarray(
        ns_pair_counts(
            jnp.asarray(delta), jnp.asarray(masks), jnp.asarray(row_ns), g
        )
    )
    want = np.zeros((g, g), dtype=np.int64)
    for s in range(g):
        for d in range(g):
            want[s, d] = dense[:, :n][np.ix_(row_ns == s, col_ns == d)].sum()
    assert np.array_equal(out, want)


def test_changed_columns_capped_and_ordered():
    row = np.zeros(3, dtype=np.uint32)
    row[0] = 0b1010110
    row[2] = 1  # column 64
    cols = changed_columns(row, cap=100)
    assert list(cols) == [1, 2, 4, 6, 64]
    assert list(changed_columns(row, cap=2)) == [1, 2]


# ----------------------------------------------- rule grammar + journal
def test_parse_posture_rule_grammar():
    deny = parse_posture_rule("deny  ns:dev ->  ns:prod")
    assert (deny.kind, deny.src_ns, deny.dst_ns) == ("deny", "dev", "prod")
    widen = parse_posture_rule("max-widening 500 pairs/batch")
    assert (widen.kind, widen.bound) == ("max-widening", 500)
    assert parse_posture_rule("max-narrowing 7").bound == 7
    for bad in ("deny dev -> prod", "max-widening", "max-widening -3", "nope"):
        with pytest.raises(ValueError):
            parse_posture_rule(bad)


def test_journal_crc_round_trip_and_torn_tail(tmp_path):
    path = str(tmp_path / "posture.jsonl")
    records = [
        PostureRecord(
            seq=i, ts=100.0 + i, n_pods=8, reachable_pairs=10 + i,
            widened=i, narrowed=0, delta_s=0.001,
            ns_widened={"a->b": i} if i else {},
            baseline=(i == 0),
        )
        for i in range(3)
    ]
    with open(path, "w") as fh:
        for r in records:
            fh.write(_encode_record(r) + "\n")
    scan = scan_posture(path)
    assert scan.ok and len(scan.records) == 3
    assert [r.seq for r in scan.records] == [0, 1, 2]
    assert scan.records[0].baseline and not scan.records[1].baseline
    assert scan.records[2].ns_widened == {"a->b": 2}

    # a torn tail (crash mid-append) keeps the valid prefix and reports
    # the tear; a bit-flipped crc is detected, not silently decoded
    with open(path, "a") as fh:
        fh.write(_encode_record(records[0])[: 40])
    scan = scan_posture(path)
    assert not scan.ok and scan.torn_lineno == 4 and len(scan.records) == 3
    lines = open(path).read().splitlines()
    flipped = json.loads(lines[1])
    flipped["reachable_pairs"] = 999_999
    with open(path, "w") as fh:
        fh.write(lines[0] + "\n" + json.dumps(flipped) + "\n")
    scan = scan_posture(path)
    assert scan.torn_lineno == 2 and len(scan.records) == 1
    assert scan_posture(str(tmp_path / "missing.jsonl")).ok


def test_posture_diff_telescopes_and_caps():
    records = [
        PostureRecord(
            seq=i, ts=float(i), n_pods=4, reachable_pairs=100 + 2 * i,
            widened=3 if i else 0, narrowed=1 if i else 0, delta_s=0.0,
            ns_widened={"a->b": 3} if i else {},
            witnesses=[{"src": f"s{i}", "dst": "d", "port": "*",
                        "dir": "widened"}] if i else [],
            baseline=(i == 0),
        )
        for i in range(5)
    ]
    d = posture_diff(records, 1, 4)
    assert d["generations"] == 3
    assert d["widened"] == 9 and d["narrowed"] == 3
    assert d["reachable_at_a"] == 102 and d["reachable_at_b"] == 108
    assert d["ns_widened"] == {"a->b": 9}
    assert len(d["witnesses"]) <= TOP_K_ROWS * WITNESS_CAP
    # argument order is normalised; empty span is a zero diff
    assert posture_diff(records, 4, 1) == d
    assert posture_diff(records, 4, 4)["generations"] == 0
    lines = render_posture_timeline(records, limit=3)
    assert lines[0].split()[0] == "gen"
    assert len(lines) == 4 and lines[1].startswith("2")


# --------------------------------------------------- the acceptance fuzz
@pytest.fixture(scope="module")
def churn64():
    cluster = random_cluster(
        GeneratorConfig(
            n_pods=64, n_policies=24, n_namespaces=6, seed=7,
            p_ipblock_peer=0.0, min_selector_labels=1,
        )
    )
    events = random_event_stream(cluster, n_events=500, seed=3)
    return cluster, events


def test_posture_bit_identical_to_dense_oracle_500_events(churn64):
    """The acceptance criterion: across a 500-event/64-pod churn stream
    the packed tracker's widened/narrowed/reachable must equal a dense
    recompute-and-compare oracle at EVERY generation, with no dense
    [N, N] live on the packed path."""
    cluster, events = churn64
    n = len(cluster.pods)
    cfg = VerifyConfig(compute_ports=False)
    eng = PackedIncrementalVerifier(cluster, cfg, keep_matrix=True)
    svc = VerificationService(engine=eng)
    tracker = svc.enable_posture()
    oracle = VerificationService(cluster, cfg)
    prev_dense = np.asarray(oracle.reach(), dtype=bool)

    state = svc._device_states.peek()
    words = state.arrays["reach_words"]
    # the packed posture path carries uint32 word planes, not an [N, N]
    # bool matrix: W words cover the slot-rounded columns bit-packed
    assert words.dtype == jnp.uint32
    assert words.shape[1] * 32 < words.shape[0] * 8
    assert tracker.records[0].baseline
    assert tracker.records[0].reachable_pairs == int(prev_dense.sum())

    checked = 0
    for i in range(0, len(events), 25):
        batch = events[i:i + 25]
        applied = svc.apply(batch)
        oracle.apply(batch)
        if not applied:
            continue
        cur_dense = np.asarray(oracle.reach(), dtype=bool)
        record = tracker.records[-1]
        assert record.seq == svc.generation
        widened = int((cur_dense & ~prev_dense).sum())
        narrowed = int((prev_dense & ~cur_dense).sum())
        assert record.widened == widened, f"gen {record.seq}"
        assert record.narrowed == narrowed, f"gen {record.seq}"
        assert record.reachable_pairs == int(cur_dense.sum()), (
            f"gen {record.seq}"
        )
        # witnesses name real flipped pairs of this very generation
        for w in record.witnesses:
            s = oracle.pod_index(*w["src"].split("/"))
            d = oracle.pod_index(*w["dst"].split("/"))
            flipped = (
                (cur_dense[s, d] and not prev_dense[s, d])
                if w["dir"] == "widened"
                else (prev_dense[s, d] and not cur_dense[s, d])
            )
            assert flipped, w
        prev_dense = cur_dense
        checked += 1
    assert checked >= 10, "stream applied too few generations to mean much"

    # the running namespace-pair totals (what deny rules read) equal a
    # dense per-namespace grouping of the final reach matrix
    ns = [p.namespace for p in cluster.pods]
    want = {}
    for s in range(n):
        for d in range(n):
            if prev_dense[s, d]:
                key = (ns[s], ns[d])
                want[key] = want.get(key, 0) + 1
    assert tracker._ns_pairs == want
    svc.close()
    oracle.close()


def test_tracker_journal_and_health_through_service(churn64, tmp_path):
    cluster, events = churn64
    path = str(tmp_path / "sub" / "posture.jsonl")
    svc = VerificationService(cluster, VerifyConfig(compute_ports=False))
    svc.enable_posture(journal_path=path)
    for i in range(0, 100, 25):
        svc.apply(events[i:i + 25])
    h = svc.health()["posture"]
    assert h["generation"] == svc.generation
    assert h["journal"] == path and h["violations"] == 0
    svc.close()
    scan = scan_posture(path)
    assert scan.ok and scan.records[0].baseline
    assert [r.seq for r in scan.records] == sorted(
        r.seq for r in scan.records
    )
    assert scan.records[-1].reachable_pairs == h["reachable_pairs"]


# ------------------------------------------------------------ alerting
def test_alert_violation_error_metric_and_flight_dump(churn64, tmp_path):
    cluster, events = churn64
    flight_dir = str(tmp_path / "flight")
    flight.install(flight_dir, with_signal=False)
    try:
        svc = VerificationService(cluster, VerifyConfig(compute_ports=False))
        before = _counter(
            "kvtpu_posture_alert_violations_total", "rule=max-widening"
        )
        svc.enable_posture(rules=[parse_posture_rule("max-widening 0")])
        applied = 0
        for i in range(0, len(events), 25):
            applied += svc.apply(events[i:i + 25])
            if svc.violations:
                break
        assert svc.violations, "500-event churn never widened a pair?"
        err = svc.violations[0]
        assert isinstance(err, PostureAlertError)
        assert err.kind == "max-widening" and err.measured > 0
        assert f"gen {err.generation}" in err.describe()
        assert _counter(
            "kvtpu_posture_alert_violations_total", "rule=max-widening"
        ) > before
        record = next(r for r in svc.posture.records if r.alerts)
        assert record.alerts[0]["kind"] == "max-widening"
        svc.close()
    finally:
        flight.uninstall()
    dumps = flight.recent_dumps(flight_dir)
    assert dumps, "violation must leave a flight dump"
    payload = flight.load_dump(dumps[0])
    assert payload["trigger"] == "posture-alert"
    assert payload["info"]["record"]["seq"] == err.generation

    # the dump is loadable by `kv-tpu recover` even with zero checkpoint
    # generations in the directory
    assert main(["recover", flight_dir]) == EXIT_OK


def test_deny_rule_reads_running_ns_pairs(churn64):
    cluster, _ = churn64
    ns = sorted({p.namespace for p in cluster.pods})
    svc = VerificationService(cluster, VerifyConfig(compute_ports=False))
    tracker = svc.enable_posture(
        rules=[parse_posture_rule(f"deny ns:{ns[0]} -> ns:{ns[1]}")]
    )
    reach = np.asarray(svc.reach(), dtype=bool)
    pods = [p.namespace for p in cluster.pods]
    crossing = sum(
        int(reach[s, d])
        for s in range(len(pods))
        for d in range(len(pods))
        if pods[s] == ns[0] and pods[d] == ns[1]
    )
    # the baseline record itself is checked against the rule
    if crossing:
        assert tracker.violations
        assert tracker.violations[0].measured == crossing
    else:
        assert not tracker.violations
    svc.close()


def test_enable_posture_refusals(churn64):
    cluster, _ = churn64
    eng = PackedIncrementalVerifier(
        cluster, VerifyConfig(compute_ports=False), keep_matrix=False
    )
    svc = VerificationService(engine=eng)
    with pytest.raises(ServeError, match="matrix-free"):
        svc.enable_posture()
    svc.close()
    svc = VerificationService(cluster, VerifyConfig(compute_ports=False))
    svc.enable_posture()
    with pytest.raises(ServeError, match="already enabled"):
        svc.enable_posture()
    svc.close()


# ------------------------------------------------------------- the CLI
@pytest.fixture()
def cli_cluster(tmp_path, capsys):
    d = str(tmp_path / "cluster")
    ev = str(tmp_path / "events.jsonl")
    assert main([
        "generate", d, "--pods", "24", "--policies", "8",
        "--namespaces", "3", "--events-out", ev, "--n-events", "60",
    ]) == EXIT_OK
    capsys.readouterr()
    return d, ev


def test_cli_serve_posture_journal_then_timeline(cli_cluster, tmp_path,
                                                 capsys):
    d, ev = cli_cluster
    journal = str(tmp_path / "posture.jsonl")
    assert main([
        "serve", d, "--events", ev, "--batch-size", "16",
        "--posture-journal", journal, "--json",
    ]) == EXIT_OK
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["posture"]["journal"] == journal
    scan = scan_posture(journal)
    assert scan.ok and len(scan.records) >= 2

    assert main(["posture", journal]) == EXIT_OK
    out = capsys.readouterr().out
    assert out.splitlines()[0].split()[0] == "gen"
    assert "0*" in out  # the baseline generation is marked

    assert main(["posture", str(tmp_path), "--json"]) == EXIT_OK
    payload = json.loads(capsys.readouterr().out.strip())
    rows = payload["records"]
    assert payload["torn_lineno"] is None
    assert rows[0]["baseline"] is True
    assert rows[-1]["seq"] == scan.records[-1].seq

    last = scan.records[-1].seq
    assert main(["posture", journal, "--diff", "0", str(last),
                 "--json"]) == EXIT_OK
    diff = json.loads(capsys.readouterr().out.strip())
    assert diff["generations"] == len(scan.records) - 1
    assert diff["reachable_at_a"] == scan.records[0].reachable_pairs
    assert diff["reachable_at_b"] == scan.records[-1].reachable_pairs

    with pytest.raises(SystemExit):
        main(["posture", str(tmp_path / "nope.jsonl")])


def test_cli_serve_posture_alert_exit_code(cli_cluster, capsys):
    d, ev = cli_cluster
    # an impossible bound: any widening across 60 churn events violates
    code = main([
        "serve", d, "--events", ev, "--batch-size", "16",
        "--posture", "--posture-alert", "max-widening 0 pairs/batch",
    ])
    out = capsys.readouterr().out
    assert code == EXIT_VIOLATIONS
    assert "posture-alert [max-widening]" in out

    with pytest.raises(SystemExit):
        main(["serve", d, "--events", ev, "--posture-alert", "garbage"])


def test_fleet_row_and_posture_column():
    from kubernetes_verification_tpu.observe.fleet import (
        ReplicaScrape,
        fleet_row,
        render_fleet,
    )

    up = ReplicaScrape(
        url="http://a", ok=True,
        health={
            "role": "leader", "epoch": 3, "last_seq": 41,
            "lag": {"seconds": 0.5, "seq": 0},
            "service": {
                "posture": {
                    "generation": 41, "reachable_pairs": 123,
                    "widened_last": 4, "narrowed_last": 5,
                    "rules": 1, "violations": 2, "journal": None,
                },
            },
        },
        metrics={},
    )
    down = ReplicaScrape(url="http://b", ok=False, error="boom")
    lines = render_fleet([up, down])
    # posture sits before the (newer) trailing stripe-ownership column
    assert lines[0].split()[-2:] == ["posture", "stripe"]
    assert "123p +4/-5 !2" in lines[1]
    assert "DOWN" in lines[2]

    row = fleet_row(up)
    assert row["url"] == "http://a" and row["ok"] is True
    assert row["role"] == "leader" and row["last_seq"] == 41
    assert row["posture"]["reachable_pairs"] == 123
    assert fleet_row(down)["error"] == "boom"
    assert fleet_row(down)["posture"] is None


# --------------------------------------------------- metrics + lint rule
def test_required_families_contains_posture_plane():
    assert {
        "kvtpu_posture_reachable_pairs",
        "kvtpu_posture_widened_total",
        "kvtpu_posture_narrowed_total",
        "kvtpu_posture_delta_seconds",
        "kvtpu_posture_alert_violations_total",
    } <= REQUIRED_FAMILIES


def test_bounded_journal_rule_fixtures():
    bad = textwrap.dedent(
        """
        import numpy as np

        def leaky(delta):
            return np.flatnonzero(delta)
        """
    )
    findings = lint_source(
        bad, path="serve/posture.py", rules=["bounded-journal"]
    )
    assert "bounded-journal" in rule_ids()  # registered by the lint run
    assert [f.rule for f in findings] == ["bounded-journal"]
    assert "bounding slice" in findings[0].message

    good = textwrap.dedent(
        """
        import numpy as np

        CAP = 4

        def capped(delta):
            return np.flatnonzero(delta)[:CAP]

        def select_form(delta):
            return np.where(delta > 0, delta, 0)  # 3-arg select, no indices

        def suppressed(mat):
            return list(zip(*np.nonzero(mat)))  # kvtpu: ignore[bounded-journal] [G, G] matrix
        """
    )
    assert lint_source(
        good, path="serve/posture.py", rules=["bounded-journal"]
    ) == []
    # the rule is scoped to the posture modules: extraction elsewhere is
    # not a journal-size liability
    assert lint_source(
        bad, path="serve/queries.py", rules=["bounded-journal"]
    ) == []
    bad_ops = lint_source(
        bad, path="ops/posture.py", rules=["bounded-journal"]
    )
    assert len(bad_ops) == 1


def test_posture_caps_are_positive_and_modest():
    # the journal-bound contract the lint enforces structurally: the
    # constants themselves must stay small enough that a record is O(1)
    assert 0 < TOP_K_ROWS <= 64
    assert 0 < WITNESS_CAP <= 16
    assert 0 < NS_PAIR_CAP <= 128
