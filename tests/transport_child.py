"""Subprocess body for the networked failover chaos test
(tests/test_transport.py).

A leader on its own simulated host: it writes an epoch-1 WAL with lease
renewals and periodic checkpoints, serves the checkpoint directory and
WAL over HTTP (:class:`ReplicationServer`), and — with ``--kill`` — dies
by SIGKILL (``os._exit(137)`` inside the armed kill-point) mid-write,
taking the HTTP endpoint down with it, exactly like a machine loss.

Handshake: after the first checkpoint generation exists the child starts
the server and publishes its URL to ``--url-file`` (tmp + ``os.replace``
so the parent never reads a half-written line). With ``--ack-file`` it
then keeps renewing the lease until the parent creates that file
(followers attached and bootstrapped) before arming the kill and
appending the second half — the parent never races the kill window.

Deliberately never solves reach: the child's job is to die while
writing, not to derive answers nobody will read.

``--serve-only`` flips the job: build the first half, then stay alive
serving the replication/scrape endpoints (lease renewed, ``--obs-log``
capturing this process's JSON event lines for ``kv-tpu trace``) until
the ack file appears — the live replica of the fleet-observability
chaos test. ``KVTPU_FLIGHT_DIR`` in the environment arms the flight
recorder either way.
"""
import argparse
import os
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--url-file", required=True)
    ap.add_argument(
        "--ack-file", default="",
        help="block after publishing the URL until this file exists "
        "(the parent's 'followers attached' signal)",
    )
    ap.add_argument(
        "--kill", default="",
        help="fault spec armed via install_kill_points AFTER the ack, "
        "e.g. 'before-lease-renew@2' (empty = run to completion)",
    )
    ap.add_argument(
        "--serve-only", action="store_true",
        help="after the first-half build, keep serving (renewing the "
        "lease) until --ack-file appears, then exit cleanly — no kill, "
        "no second half (the fleet-observability chaos leader)",
    )
    ap.add_argument(
        "--obs-log", default="",
        help="write this process's JSON event lines here (the per-replica "
        "log `kv-tpu trace` scans for cross-process timelines)",
    )
    ap.add_argument("--seed", type=int, default=3)
    ap.add_argument("--n-events", type=int, default=200)
    ap.add_argument("--pods", type=int, default=24)
    ap.add_argument("--batch", type=int, default=10)
    ap.add_argument("--checkpoint-every", type=int, default=2)
    ap.add_argument("--lease-ttl", type=float, default=0.3)
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

    import kubernetes_verification_tpu as kv
    from kubernetes_verification_tpu.harness.generate import (
        GeneratorConfig,
        random_cluster,
        random_event_stream,
    )
    from kubernetes_verification_tpu.observe.flight import install_from_env
    from kubernetes_verification_tpu.resilience.faults import (
        install_kill_points,
        parse_fault_spec,
    )

    # KVTPU_FLIGHT_DIR set by the parent arms the crash flight recorder:
    # the SIGKILL below then leaves a flight-*.json post-mortem behind
    install_from_env()
    if args.obs_log:
        from kubernetes_verification_tpu.observe import configure_logging

        # line-buffered so the parent reads complete event lines while
        # this process is still alive and serving
        configure_logging(stream=open(args.obs_log, "a", buffering=1))
    from kubernetes_verification_tpu.serve import (
        CheckpointManager,
        EventSource,
        LeaseFile,
        ReplicationServer,
        VerificationService,
        WalWriter,
    )

    # MUST mirror the parent test's generator knobs exactly: the parent
    # rebuilds this cluster for the from-scratch oracle
    cluster = random_cluster(
        GeneratorConfig(
            n_pods=args.pods, n_policies=24, n_namespaces=6, seed=7,
            p_ipblock_peer=0.0, min_selector_labels=1,
        )
    )
    cfg = kv.VerifyConfig(backend="cpu", compute_ports=False)
    log = os.path.join(args.workdir, "events.jsonl")
    ck = os.path.join(args.workdir, "ck")
    events = random_event_stream(
        cluster, n_events=args.n_events, seed=args.seed
    )

    svc = VerificationService(cluster, cfg)
    os.makedirs(ck, exist_ok=True)
    cm = CheckpointManager(ck, retain=3)
    lease = LeaseFile(ck)
    lease.acquire("net-leader", ttl=args.lease_ttl)  # epoch 1
    writer = WalWriter(log, epoch=1, lease=lease)
    source = EventSource(log)

    def _append(chunk) -> None:
        lease.renew("net-leader", 1, args.lease_ttl)
        writer.append(chunk)
        for batch in source.batches(args.batch):
            svc.apply(batch)

    def _checkpoint() -> None:
        cm.checkpoint(
            svc.engine, log_path=log,
            log_offset=source.offset, last_seq=source.last_seq,
        )

    mid = len(events) // 2
    batches_since = 0
    for i in range(0, mid, args.batch):
        _append(events[i:i + args.batch])
        batches_since += 1
        if batches_since >= args.checkpoint_every:
            _checkpoint()
            batches_since = 0
    _checkpoint()

    server = ReplicationServer(ck, log)
    url = server.start()
    tmp = args.url_file + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(url)
    os.replace(tmp, args.url_file)

    if args.serve_only:
        # fleet-observability mode: this process is a live replica whose
        # only job is to serve /v1/*, /metrics and /healthz (logging its
        # server-side spans to --obs-log) until the parent says stop
        deadline = time.time() + 120.0
        while not os.path.exists(args.ack_file):
            if time.time() > deadline:
                print("parent never acked", file=sys.stderr)
                return 1
            lease.renew("net-leader", 1, args.lease_ttl)
            time.sleep(args.lease_ttl / 4)
        writer.close()
        server.close()
        return 0

    if args.ack_file:
        deadline = time.time() + 60.0
        while not os.path.exists(args.ack_file):
            if time.time() > deadline:
                print("parent never acked", file=sys.stderr)
                return 1
            lease.renew("net-leader", 1, args.lease_ttl)
            time.sleep(args.lease_ttl / 4)

    # armed only now: the parent-visible phase-1 renewals never count
    # toward the kill index
    if args.kill:
        install_kill_points(parse_fault_spec(args.kill), seed=args.seed)
    for i in range(mid, len(events), args.batch):
        _append(events[i:i + args.batch])
    _checkpoint()
    writer.close()
    server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
