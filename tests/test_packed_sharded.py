"""Sharded *packed* path (BASELINE config 5 core) vs the CPU oracle on the
8-virtual-device mesh: packed matrix, aggregates, stripes, and every mesh
factorisation."""
import numpy as np
import pytest

import kubernetes_verification_tpu as kv
from kubernetes_verification_tpu.encode.encoder import encode_cluster
from kubernetes_verification_tpu.harness.generate import (
    GeneratorConfig,
    random_cluster,
)
from kubernetes_verification_tpu.parallel.mesh import mesh_for
from kubernetes_verification_tpu.parallel.packed_sharded import (
    sharded_packed_reach,
)

MESHES = [(8, 1), (4, 2), (2, 4), (1, 8)]


def _solve(cluster, shape, **kw):
    enc = encode_cluster(cluster, compute_ports=False)
    mesh = mesh_for(shape)
    return sharded_packed_reach(mesh, enc, tile=32, chunk=8, **kw)


@pytest.mark.parametrize("shape", MESHES)
def test_matches_cpu_oracle(shape):
    cluster = random_cluster(
        GeneratorConfig(n_pods=53, n_policies=13, n_namespaces=3, seed=3)
    )
    ref = kv.verify(cluster, kv.VerifyConfig(backend="cpu", compute_ports=False))
    got = _solve(cluster, shape, keep_matrix=True)
    np.testing.assert_array_equal(got.to_bool(), ref.reach)
    np.testing.assert_array_equal(got.out_degree, ref.reach.sum(axis=1))
    np.testing.assert_array_equal(got.in_degree, ref.reach.sum(axis=0))
    assert got.total_pairs == int(ref.reach.sum())
    np.testing.assert_array_equal(got.ingress_isolated, ref.ingress_isolated)
    np.testing.assert_array_equal(got.egress_isolated, ref.egress_isolated)
    assert got.all_isolated() == ref.all_isolated()
    assert got.all_reachable() == ref.all_reachable()


@pytest.mark.parametrize(
    "flags",
    [
        dict(self_traffic=False),
        dict(default_allow_unselected=False),
        dict(direction_aware_isolation=False),
    ],
)
def test_semantic_flags(flags):
    cluster = random_cluster(
        GeneratorConfig(n_pods=41, n_policies=9, n_namespaces=2, seed=5)
    )
    ref = kv.verify(
        cluster, kv.VerifyConfig(backend="cpu", compute_ports=False, **flags)
    )
    got = _solve(cluster, (4, 2), keep_matrix=True, **flags)
    np.testing.assert_array_equal(got.to_bool(), ref.reach)


def test_aggregates_only_mode():
    """keep_matrix=False: the matrix is never materialised; aggregates still
    exact (the 1M-pod operating mode)."""
    cluster = random_cluster(
        GeneratorConfig(n_pods=45, n_policies=11, n_namespaces=2, seed=9)
    )
    ref = kv.verify(cluster, kv.VerifyConfig(backend="cpu", compute_ports=False))
    got = _solve(cluster, (4, 2), keep_matrix=False)
    assert got.packed is None
    with pytest.raises(ValueError):
        got.to_bool()
    np.testing.assert_array_equal(got.out_degree, ref.reach.sum(axis=1))
    np.testing.assert_array_equal(got.in_degree, ref.reach.sum(axis=0))


def test_stripes_compose():
    """Sweeping tile stripes separately covers the full dst axis: the union
    of per-stripe aggregates equals the full solve (the checkpointable-sweep
    property, SURVEY.md §5.4)."""
    cluster = random_cluster(
        GeneratorConfig(n_pods=70, n_policies=9, n_namespaces=2, seed=11)
    )
    ref = kv.verify(cluster, kv.VerifyConfig(backend="cpu", compute_ports=False))
    enc = encode_cluster(cluster, compute_ports=False)
    mesh = mesh_for((4, 2))
    full = sharded_packed_reach(mesh, enc, tile=32, chunk=8, keep_matrix=False)
    n_tiles = full.timings["tiles"]
    assert n_tiles >= 4
    mid = (n_tiles // 2 // 2) * 2  # stripe widths must divide mp=2
    a = sharded_packed_reach(
        mesh, enc, tile=32, chunk=8, stripe=(0, mid), keep_matrix=False
    )
    b = sharded_packed_reach(
        mesh, enc, tile=32, chunk=8, stripe=(mid, n_tiles), keep_matrix=False
    )
    np.testing.assert_array_equal(
        a.out_degree + b.out_degree, ref.reach.sum(axis=1)
    )
    np.testing.assert_array_equal(
        a.in_degree + b.in_degree, ref.reach.sum(axis=0)
    )
    assert a.total_pairs + b.total_pairs == int(ref.reach.sum())


def test_full_aggregate_sweep_chunked():
    """``sweep_chunk_tiles``: the in-function full sweep (reused-executable
    stripes + remainder) must reproduce the one-shot solve's aggregates
    exactly — the path ``bench.py --mode stripe --full-sweep`` uses to
    measure config 5's single-chip share end-to-end."""
    cluster = random_cluster(
        GeneratorConfig(n_pods=70, n_policies=9, n_namespaces=2, seed=11)
    )
    ref = kv.verify(cluster, kv.VerifyConfig(backend="cpu", compute_ports=False))
    enc = encode_cluster(cluster, compute_ports=False)
    mesh = mesh_for((8, 1))
    # 70 pods / tile 32 / dp 8 → 8 dst tiles; chunks of 3 → two 3-tile
    # stripes + a 2-tile remainder: both executables exercised
    swept = sharded_packed_reach(
        mesh, enc, tile=32, chunk=8, sweep_chunk_tiles=3
    )
    assert swept.full_sweep and swept.packed is None
    assert swept.timings["n_chunks"] == 3
    np.testing.assert_array_equal(swept.out_degree, ref.reach.sum(axis=1))
    np.testing.assert_array_equal(swept.in_degree, ref.reach.sum(axis=0))
    assert swept.total_pairs == int(ref.reach.sum())
    with pytest.raises(ValueError, match="drop stripe"):
        sharded_packed_reach(
            mesh, enc, tile=32, chunk=8, sweep_chunk_tiles=3, stripe=(0, 2)
        )


def test_user_crosscheck_and_system_isolation():
    """Crosscheck from the packed matrix AND from the matrix-free per-group
    in-degree aggregates; system_isolation from the matrix (and a clear
    refusal without it)."""
    from kubernetes_verification_tpu.ops import queries

    cluster = random_cluster(
        GeneratorConfig(n_pods=57, n_policies=11, n_namespaces=3, seed=15)
    )
    ref = kv.verify(cluster, kv.VerifyConfig(backend="cpu", compute_ports=False))
    expect = queries.user_crosscheck(ref.reach, cluster.pods, "team")

    with_matrix = _solve(cluster, (4, 2), keep_matrix=True)
    assert with_matrix.user_crosscheck(cluster.pods, "team") == expect
    for idx in (0, 29):
        assert with_matrix.system_isolation(idx) == queries.system_isolation(
            ref.reach, idx
        )

    gid = queries.user_groups(cluster.pods, "team")
    no_matrix = _solve(cluster, (4, 2), keep_matrix=False, groups=gid)
    assert no_matrix.packed is None
    assert no_matrix.user_crosscheck(cluster.pods, "team") == expect
    with pytest.raises(ValueError, match="keep_matrix"):
        no_matrix.system_isolation(0)

    bare = _solve(cluster, (4, 2), keep_matrix=False)
    with pytest.raises(ValueError, match="groups"):
        bare.user_crosscheck(cluster.pods, "team")
    # a different grouping than the solve aggregated over must be refused
    with pytest.raises(ValueError, match="grouping"):
        no_matrix.user_crosscheck(cluster.pods, "app")


@pytest.mark.parametrize("shape", MESHES)
def test_ports_match_cpu_oracle(shape):
    """BASELINE config 4 semantics on the config 5 engine: the mask-group
    port decomposition composed with the dst-tile broadcast must equal the
    CPU oracle with port bitmaps on."""
    cluster = random_cluster(
        GeneratorConfig(
            n_pods=61, n_policies=11, n_namespaces=3, p_ports=0.8, seed=43
        )
    )
    enc = encode_cluster(cluster, compute_ports=True)
    assert len(enc.atoms) > 1, "fixture must exercise real port atoms"
    ref = kv.verify(cluster, kv.VerifyConfig(backend="cpu", compute_ports=True))
    got = sharded_packed_reach(
        mesh_for(shape), enc, tile=32, chunk=8, keep_matrix=True
    )
    np.testing.assert_array_equal(got.to_bool(), ref.reach)
    np.testing.assert_array_equal(got.out_degree, ref.reach.sum(axis=1))
    np.testing.assert_array_equal(got.in_degree, ref.reach.sum(axis=0))


def test_ports_match_tiled_packed():
    """Sharded-with-ports must agree bit-for-bit with the single-chip tiled
    port kernel on the packed form."""
    from kubernetes_verification_tpu.ops.tiled import tiled_k8s_reach

    cluster = random_cluster(
        GeneratorConfig(
            n_pods=87, n_policies=17, n_namespaces=4, p_ports=0.7, seed=7
        )
    )
    enc = encode_cluster(cluster, compute_ports=True)
    tiled = tiled_k8s_reach(enc, tile=128)
    got = sharded_packed_reach(
        mesh_for((4, 2)), enc, tile=32, chunk=8, keep_matrix=True
    )
    np.testing.assert_array_equal(got.to_bool(), tiled.to_bool())


@pytest.mark.slow
def test_ports_stripes_and_groups():
    """Striped port sweeps compose, and the per-group in-degree aggregates
    (matrix-free user_crosscheck) stay exact under the port kernel."""
    from kubernetes_verification_tpu.ops.queries import user_groups

    cluster = random_cluster(
        GeneratorConfig(
            n_pods=47, n_policies=9, n_namespaces=3, p_ports=0.9, seed=11
        )
    )
    enc = encode_cluster(cluster, compute_ports=True)
    assert len(enc.atoms) > 1
    ref = kv.verify(cluster, kv.VerifyConfig(backend="cpu", compute_ports=True))
    mesh = mesh_for((4, 2))
    gid = user_groups(cluster.pods, "team")
    full = sharded_packed_reach(
        mesh, enc, tile=32, chunk=8, keep_matrix=False, groups=gid
    )
    np.testing.assert_array_equal(full.out_degree, ref.reach.sum(axis=1))
    assert full.user_crosscheck(cluster.pods, "team") == ref.user_crosscheck(
        cluster.pods, "team"
    )
    # stripes: aggregate partials over disjoint stripes sum to the full sweep
    n_tiles = full.timings["tiles"]
    half = n_tiles // 2 - (n_tiles // 2) % 2  # multiple of mp=2
    if half:
        a = sharded_packed_reach(
            mesh, enc, tile=32, chunk=8, stripe=(0, half), keep_matrix=False
        )
        b = sharded_packed_reach(
            mesh, enc, tile=32, chunk=8, stripe=(half, n_tiles),
            keep_matrix=False,
        )
        np.testing.assert_array_equal(
            a.out_degree + b.out_degree, full.out_degree
        )
        np.testing.assert_array_equal(
            a.in_degree + b.in_degree, full.in_degree
        )


@pytest.mark.slow
def test_registered_backend_routes_through_verify():
    """The config-5 engine must be reachable through the plugin boundary:
    kv.verify(backend='sharded-packed') — with and without ports, dense
    reach below the limit, packed queries above it."""
    cluster = random_cluster(
        GeneratorConfig(
            n_pods=53, n_policies=13, n_namespaces=3, p_ports=0.7, seed=3
        )
    )
    for ports in (False, True):
        ref = kv.verify(
            cluster, kv.VerifyConfig(backend="cpu", compute_ports=ports)
        )
        res = kv.verify(
            cluster,
            kv.VerifyConfig(
                backend="sharded-packed",
                compute_ports=ports,
                backend_options=(
                    ("mesh", (4, 2)), ("tile", 32), ("chunk", 8),
                    ("keep_matrix", True),
                ),
            ),
        )
        np.testing.assert_array_equal(res.reach, ref.reach)
        assert res.all_isolated() == ref.all_isolated()
        assert res.system_isolation(3) == ref.system_isolation(3)
        assert res.user_crosscheck(cluster.pods, "team") == ref.user_crosscheck(
            cluster.pods, "team"
        )
        assert res.reachable(0, 1) == bool(ref.reach[0, 1])
    # above the dense limit: reach is None, packed queries still answer
    res2 = kv.verify(
        cluster,
        kv.VerifyConfig(
            backend="sharded-packed",
            compute_ports=False,
            backend_options=(
                ("mesh", (4, 2)), ("tile", 32), ("chunk", 8),
                ("keep_matrix", True), ("dense_reach_limit", 10),
            ),
        ),
    )
    assert res2.reach is None
    ref2 = kv.verify(cluster, kv.VerifyConfig(backend="cpu", compute_ports=False))
    assert res2.all_isolated() == ref2.all_isolated()
    np.testing.assert_array_equal(res2.packed_result.to_bool(), ref2.reach)
    # the pairwise policy queries answer through the sharded Gram masks
    # (pre-round-4 they raised here)
    assert res2.policy_shadow() == ref2.policy_shadow()
    assert res2.policy_conflict() == ref2.policy_conflict()


def test_closure_through_backend_and_result():
    """Transitive closure on the sharded-packed engine: the packed-domain
    squaring over the kept matrix must equal the dense closure."""
    cluster = random_cluster(
        GeneratorConfig(
            n_pods=53, n_policies=13, n_namespaces=3, p_ports=0.0, seed=3
        )
    )
    ref = kv.verify(
        cluster,
        kv.VerifyConfig(
            backend="cpu", compute_ports=False, closure=True,
            self_traffic=False,
        ),
    )
    res = kv.verify(
        cluster,
        kv.VerifyConfig(
            backend="sharded-packed", compute_ports=False, closure=True,
            self_traffic=False,
            backend_options=(
                ("mesh", (4, 2)), ("tile", 32), ("chunk", 8),
                ("keep_matrix", True),
            ),
        ),
    )
    np.testing.assert_array_equal(res.closure, ref.closure)
    assert res.closure_packed is not None
    # matrix-free closure is refused with guidance
    with pytest.raises(ValueError, match="keep_matrix"):
        kv.verify(
            cluster,
            kv.VerifyConfig(
                backend="sharded-packed", compute_ports=False, closure=True,
                backend_options=(
                    ("mesh", (4, 2)), ("tile", 32), ("chunk", 8),
                    ("keep_matrix", False),
                ),
            ),
        )


def test_port_mask_cap_enforced():
    cluster = random_cluster(
        GeneratorConfig(
            n_pods=21, n_policies=7, n_namespaces=2, p_ports=0.9, seed=5
        )
    )
    enc = encode_cluster(cluster, compute_ports=True)
    if len(enc.atoms) > 1:
        with pytest.raises(ValueError, match="max_port_masks"):
            sharded_packed_reach(
                mesh_for((8, 1)), enc, tile=32, chunk=8, max_port_masks=0
            )


def test_partial_stripe_refuses_whole_matrix_queries():
    """A striped result must not answer whole-matrix questions (unswept dsts
    would read as unreachable) and must never auto-keep a partial matrix."""
    cluster = random_cluster(
        GeneratorConfig(n_pods=70, n_policies=9, n_namespaces=2, seed=11)
    )
    enc = encode_cluster(cluster, compute_ports=False)
    mesh = mesh_for((4, 2))
    part = sharded_packed_reach(mesh, enc, tile=32, chunk=8, stripe=(0, 2))
    assert not part.full_sweep
    assert part.packed is None  # heuristic must not keep a partial matrix
    for q in (part.all_reachable, part.all_isolated):
        with pytest.raises(ValueError, match="full dst sweep"):
            q()


@pytest.mark.slow
def test_pairwise_policy_queries_through_backend():
    """All SIX verification queries answer through ``sharded-packed``:
    policy_shadow/policy_conflict route through the sharded Gram masks
    (``policy_pair_masks_sharded``), lazily, and equal the CPU oracle."""
    cluster = random_cluster(
        GeneratorConfig(
            n_pods=120, n_policies=25, n_namespaces=3, p_ports=0.7, seed=17
        )
    )
    res = kv.verify(
        cluster,
        kv.VerifyConfig(
            backend="sharded-packed", backend_options=(("mesh", (4, 2)),)
        ),
    )
    ref = kv.verify(cluster, kv.VerifyConfig(backend="cpu"))
    assert res.policy_shadow() == ref.policy_shadow()
    assert res.policy_conflict() == ref.policy_conflict()
    # the masks are computed once and cached
    assert res._pair_masks is not None
    # the remaining four already answered packed-side; spot-check parity
    assert res.all_reachable() == ref.all_reachable()
    assert res.all_isolated() == ref.all_isolated()
    assert res.system_isolation(3) == ref.system_isolation(3)
    assert res.user_crosscheck(cluster.pods, "app") == ref.user_crosscheck(
        cluster.pods, "app"
    )


@pytest.mark.slow
def test_pairwise_masks_respect_direction_aware_flag():
    cluster = random_cluster(
        GeneratorConfig(n_pods=60, n_policies=12, n_namespaces=2, seed=19)
    )
    cfg = kv.VerifyConfig(
        backend="sharded-packed",
        direction_aware_isolation=False,
        backend_options=(("mesh", (8, 1)),),
    )
    res = kv.verify(cluster, cfg)
    ref = kv.verify(
        cluster,
        kv.VerifyConfig(backend="cpu", direction_aware_isolation=False),
    )
    assert res.policy_shadow() == ref.policy_shadow()
    assert res.policy_conflict() == ref.policy_conflict()


@pytest.mark.slow
def test_materialize_policy_sets_matches_cpu():
    """The sharded-packed result can materialise the per-policy src/dst
    edge sets on demand (budget-guarded); they equal the CPU oracle's."""
    cluster = random_cluster(
        GeneratorConfig(n_pods=80, n_policies=14, n_namespaces=3, seed=23)
    )
    res = kv.verify(
        cluster,
        kv.VerifyConfig(
            backend="sharded-packed", backend_options=(("mesh", (4, 2)),)
        ),
    )
    ref = kv.verify(cluster, kv.VerifyConfig(backend="cpu"))
    with pytest.raises(ValueError, match="budget"):
        res.materialize_policy_sets(max_bytes=10)
    src, dst = res.materialize_policy_sets()
    np.testing.assert_array_equal(src, ref.src_sets)
    np.testing.assert_array_equal(dst, ref.dst_sets)
    # with the sets materialised, the base-class pairwise queries agree
    # with the Gram-mask path
    from kubernetes_verification_tpu.backends.base import VerifyResult

    assert VerifyResult.policy_shadow(res) == res.policy_shadow()
    assert VerifyResult.policy_conflict(res) == res.policy_conflict()
