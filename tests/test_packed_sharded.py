"""Sharded *packed* path (BASELINE config 5 core) vs the CPU oracle on the
8-virtual-device mesh: packed matrix, aggregates, stripes, and every mesh
factorisation."""
import numpy as np
import pytest

import kubernetes_verification_tpu as kv
from kubernetes_verification_tpu.encode.encoder import encode_cluster
from kubernetes_verification_tpu.harness.generate import (
    GeneratorConfig,
    random_cluster,
)
from kubernetes_verification_tpu.parallel.mesh import mesh_for
from kubernetes_verification_tpu.parallel.packed_sharded import (
    sharded_packed_reach,
)

MESHES = [(8, 1), (4, 2), (2, 4), (1, 8)]


def _solve(cluster, shape, **kw):
    enc = encode_cluster(cluster, compute_ports=False)
    mesh = mesh_for(shape)
    return sharded_packed_reach(mesh, enc, tile=32, chunk=8, **kw)


@pytest.mark.parametrize("shape", MESHES)
def test_matches_cpu_oracle(shape):
    cluster = random_cluster(
        GeneratorConfig(n_pods=53, n_policies=13, n_namespaces=3, seed=3)
    )
    ref = kv.verify(cluster, kv.VerifyConfig(backend="cpu", compute_ports=False))
    got = _solve(cluster, shape, keep_matrix=True)
    np.testing.assert_array_equal(got.to_bool(), ref.reach)
    np.testing.assert_array_equal(got.out_degree, ref.reach.sum(axis=1))
    np.testing.assert_array_equal(got.in_degree, ref.reach.sum(axis=0))
    assert got.total_pairs == int(ref.reach.sum())
    np.testing.assert_array_equal(got.ingress_isolated, ref.ingress_isolated)
    np.testing.assert_array_equal(got.egress_isolated, ref.egress_isolated)
    assert got.all_isolated() == ref.all_isolated()
    assert got.all_reachable() == ref.all_reachable()


@pytest.mark.parametrize(
    "flags",
    [
        dict(self_traffic=False),
        dict(default_allow_unselected=False),
        dict(direction_aware_isolation=False),
    ],
)
def test_semantic_flags(flags):
    cluster = random_cluster(
        GeneratorConfig(n_pods=41, n_policies=9, n_namespaces=2, seed=5)
    )
    ref = kv.verify(
        cluster, kv.VerifyConfig(backend="cpu", compute_ports=False, **flags)
    )
    got = _solve(cluster, (4, 2), keep_matrix=True, **flags)
    np.testing.assert_array_equal(got.to_bool(), ref.reach)


def test_aggregates_only_mode():
    """keep_matrix=False: the matrix is never materialised; aggregates still
    exact (the 1M-pod operating mode)."""
    cluster = random_cluster(
        GeneratorConfig(n_pods=45, n_policies=11, n_namespaces=2, seed=9)
    )
    ref = kv.verify(cluster, kv.VerifyConfig(backend="cpu", compute_ports=False))
    got = _solve(cluster, (4, 2), keep_matrix=False)
    assert got.packed is None
    with pytest.raises(ValueError):
        got.to_bool()
    np.testing.assert_array_equal(got.out_degree, ref.reach.sum(axis=1))
    np.testing.assert_array_equal(got.in_degree, ref.reach.sum(axis=0))


def test_stripes_compose():
    """Sweeping tile stripes separately covers the full dst axis: the union
    of per-stripe aggregates equals the full solve (the checkpointable-sweep
    property, SURVEY.md §5.4)."""
    cluster = random_cluster(
        GeneratorConfig(n_pods=70, n_policies=9, n_namespaces=2, seed=11)
    )
    ref = kv.verify(cluster, kv.VerifyConfig(backend="cpu", compute_ports=False))
    enc = encode_cluster(cluster, compute_ports=False)
    mesh = mesh_for((4, 2))
    full = sharded_packed_reach(mesh, enc, tile=32, chunk=8, keep_matrix=False)
    n_tiles = full.timings["tiles"]
    assert n_tiles >= 4
    mid = (n_tiles // 2 // 2) * 2  # stripe widths must divide mp=2
    a = sharded_packed_reach(
        mesh, enc, tile=32, chunk=8, stripe=(0, mid), keep_matrix=False
    )
    b = sharded_packed_reach(
        mesh, enc, tile=32, chunk=8, stripe=(mid, n_tiles), keep_matrix=False
    )
    np.testing.assert_array_equal(
        a.out_degree + b.out_degree, ref.reach.sum(axis=1)
    )
    np.testing.assert_array_equal(
        a.in_degree + b.in_degree, ref.reach.sum(axis=0)
    )
    assert a.total_pairs + b.total_pairs == int(ref.reach.sum())


def test_user_crosscheck_and_system_isolation():
    """Crosscheck from the packed matrix AND from the matrix-free per-group
    in-degree aggregates; system_isolation from the matrix (and a clear
    refusal without it)."""
    from kubernetes_verification_tpu.ops import queries

    cluster = random_cluster(
        GeneratorConfig(n_pods=57, n_policies=11, n_namespaces=3, seed=15)
    )
    ref = kv.verify(cluster, kv.VerifyConfig(backend="cpu", compute_ports=False))
    expect = queries.user_crosscheck(ref.reach, cluster.pods, "team")

    with_matrix = _solve(cluster, (4, 2), keep_matrix=True)
    assert with_matrix.user_crosscheck(cluster.pods, "team") == expect
    for idx in (0, 29):
        assert with_matrix.system_isolation(idx) == queries.system_isolation(
            ref.reach, idx
        )

    gid = queries.user_groups(cluster.pods, "team")
    no_matrix = _solve(cluster, (4, 2), keep_matrix=False, groups=gid)
    assert no_matrix.packed is None
    assert no_matrix.user_crosscheck(cluster.pods, "team") == expect
    with pytest.raises(ValueError, match="keep_matrix"):
        no_matrix.system_isolation(0)

    bare = _solve(cluster, (4, 2), keep_matrix=False)
    with pytest.raises(ValueError, match="groups"):
        bare.user_crosscheck(cluster.pods, "team")
    # a different grouping than the solve aggregated over must be refused
    with pytest.raises(ValueError, match="grouping"):
        no_matrix.user_crosscheck(cluster.pods, "app")


def test_ports_encoding_rejected():
    cluster = random_cluster(
        GeneratorConfig(n_pods=10, n_policies=4, p_ports=1.0, seed=2)
    )
    enc = encode_cluster(cluster, compute_ports=True)
    if len(enc.atoms) > 1:
        with pytest.raises(ValueError, match="any-port"):
            sharded_packed_reach(mesh_for((8, 1)), enc)


def test_partial_stripe_refuses_whole_matrix_queries():
    """A striped result must not answer whole-matrix questions (unswept dsts
    would read as unreachable) and must never auto-keep a partial matrix."""
    cluster = random_cluster(
        GeneratorConfig(n_pods=70, n_policies=9, n_namespaces=2, seed=11)
    )
    enc = encode_cluster(cluster, compute_ports=False)
    mesh = mesh_for((4, 2))
    part = sharded_packed_reach(mesh, enc, tile=32, chunk=8, stripe=(0, 2))
    assert not part.full_sweep
    assert part.packed is None  # heuristic must not keep a partial matrix
    for q in (part.all_reachable, part.all_isolated):
        with pytest.raises(ValueError, match="full dst sweep"):
            q()
