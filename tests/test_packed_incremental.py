"""Packed incremental re-verify: the device-resident, bit-packed diff path
(BASELINE config 5's 100k-scale half). Every mutation's result must equal a
from-scratch CPU-oracle solve of the mutated cluster, and the packed verifier
must agree bit-for-bit with the dense count-matrix verifier."""
import dataclasses
import random

import numpy as np
import pytest

import kubernetes_verification_tpu as kv
from kubernetes_verification_tpu.harness.generate import (
    GeneratorConfig,
    random_cluster,
)
from kubernetes_verification_tpu.incremental import IncrementalVerifier
from kubernetes_verification_tpu.packed_incremental import (
    PackedIncrementalVerifier,
)


def _full(cluster, config):
    return kv.verify(
        cluster,
        kv.VerifyConfig(
            backend="cpu",
            compute_ports=False,
            self_traffic=config.self_traffic,
            default_allow_unselected=config.default_allow_unselected,
            direction_aware_isolation=config.direction_aware_isolation,
        ),
    ).reach


@pytest.fixture()
def setup():
    cluster = random_cluster(
        GeneratorConfig(n_pods=57, n_policies=9, n_namespaces=3, seed=7)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    return cluster, cfg, PackedIncrementalVerifier(cluster, cfg)


def test_initial_build_matches_oracle(setup):
    cluster, cfg, inc = setup
    np.testing.assert_array_equal(inc.reach, _full(cluster, cfg))


def test_remove_add_update_sequence(setup):
    cluster, cfg, inc = setup
    pols = list(cluster.policies)
    inc.remove_policy(pols[0].namespace, pols[0].name)
    np.testing.assert_array_equal(inc.reach, _full(inc.as_cluster(), cfg))
    inc.add_policy(dataclasses.replace(pols[0], name="brand-new"))
    np.testing.assert_array_equal(inc.reach, _full(inc.as_cluster(), cfg))
    upd = dataclasses.replace(
        pols[1],
        ingress=list(pols[2].ingress or []),
        egress=list(pols[1].egress or []),
    )
    inc.update_policy(upd)
    np.testing.assert_array_equal(inc.reach, _full(inc.as_cluster(), cfg))


def test_relabel_then_policy_diff_uses_dirty_fixup(setup):
    """A pod relabelled to pairs the frozen vocab has never seen must still
    be matched correctly by policies (re-)encoded afterwards."""
    cluster, cfg, inc = setup
    inc.update_pod_labels(3, {"totally": "unseen", "fresh": "pair"})
    np.testing.assert_array_equal(inc.reach, _full(inc.as_cluster(), cfg))
    pol = kv.NetworkPolicy(
        name="sel-unseen",
        namespace=inc.pods[3].namespace,
        pod_selector=kv.Selector({"totally": "unseen"}),
        ingress=(
            kv.Rule(peers=(kv.Peer(pod_selector=kv.Selector({"fresh": "pair"})),)),
        ),
    )
    inc.add_policy(pol)
    ref = _full(inc.as_cluster(), cfg)
    np.testing.assert_array_equal(inc.reach, ref)
    # the new policy must actually bite: pod 3 became ingress-isolated
    assert inc.packed_reach().ingress_isolated[3]


def test_fuzzed_diffs_match_oracle_and_dense():
    cluster = random_cluster(
        GeneratorConfig(n_pods=41, n_policies=7, n_namespaces=3, seed=21)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    packed = PackedIncrementalVerifier(cluster, cfg)
    dense = IncrementalVerifier(cluster, cfg)
    donor = random_cluster(
        GeneratorConfig(n_pods=41, n_policies=24, n_namespaces=3, seed=22)
    )
    rng = random.Random(0)
    for i, p in enumerate(donor.policies[:10]):
        p2 = dataclasses.replace(p, name=f"fuzz-{i}")
        packed.add_policy(p2)
        dense.add_policy(p2)
        if i % 3 == 0:
            key = rng.choice(sorted(packed.policies))
            ns, name = key.split("/", 1)
            packed.remove_policy(ns, name)
            dense.remove_policy(ns, name)
        if i % 4 == 1:
            j = rng.randrange(41)
            lbl = {"app": f"x{i}", "env": "prod"}
            packed.update_pod_labels(j, lbl)
            dense.update_pod_labels(j, lbl)
        ref = _full(packed.as_cluster(), cfg)
        np.testing.assert_array_equal(packed.reach, ref, err_msg=f"step {i}")
        np.testing.assert_array_equal(dense.reach, ref, err_msg=f"dense {i}")


@pytest.mark.parametrize(
    "self_traffic,default_allow,direction_aware",
    [(False, True, True), (True, False, True), (True, True, False),
     (False, False, False)],
)
def test_flag_variants(self_traffic, default_allow, direction_aware):
    cluster = random_cluster(
        GeneratorConfig(n_pods=33, n_policies=7, n_namespaces=2, seed=11)
    )
    cfg = kv.VerifyConfig(
        compute_ports=False,
        self_traffic=self_traffic,
        default_allow_unselected=default_allow,
        direction_aware_isolation=direction_aware,
    )
    inc = PackedIncrementalVerifier(cluster, cfg)
    np.testing.assert_array_equal(inc.reach, _full(cluster, cfg))
    inc.update_policy(dataclasses.replace(cluster.policies[0], ingress=[]))
    inc.remove_policy(
        cluster.policies[1].namespace, cluster.policies[1].name
    )
    np.testing.assert_array_equal(inc.reach, _full(inc.as_cluster(), cfg))


@pytest.mark.slow
def test_capacity_growth():
    cluster = random_cluster(
        GeneratorConfig(n_pods=23, n_policies=3, n_namespaces=2, seed=31)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(cluster, cfg, slot_round=4)
    donor = random_cluster(
        GeneratorConfig(n_pods=23, n_policies=16, n_namespaces=2, seed=32)
    )
    for i, p in enumerate(donor.policies):
        inc.add_policy(dataclasses.replace(p, name=f"grow-{i}"))
    assert len(inc.policies) == 19
    np.testing.assert_array_equal(inc.reach, _full(inc.as_cluster(), cfg))


def test_empty_policy_cluster():
    cluster = random_cluster(
        GeneratorConfig(n_pods=19, n_policies=0, n_namespaces=2, seed=41)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(cluster, cfg)
    np.testing.assert_array_equal(inc.reach, _full(cluster, cfg))
    donor = random_cluster(
        GeneratorConfig(n_pods=19, n_policies=2, n_namespaces=2, seed=42)
    )
    for p in donor.policies:
        inc.add_policy(p)
    np.testing.assert_array_equal(inc.reach, _full(inc.as_cluster(), cfg))


@pytest.mark.slow
@pytest.mark.parametrize("shape", [(8, 1), (4, 2), (2, 4)])
def test_mesh_sharded_state_diffs(shape):
    """Config-5 composition: the same verifier with its state sharded over a
    (pods, grants) mesh — every diff kernel runs SPMD — must track the
    oracle exactly."""
    from kubernetes_verification_tpu.parallel.mesh import mesh_for

    cluster = random_cluster(
        GeneratorConfig(n_pods=61, n_policies=11, n_namespaces=3, seed=43)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(cluster, cfg, mesh=mesh_for(shape))
    assert inc.keep_matrix
    np.testing.assert_array_equal(inc.reach, _full(cluster, cfg))
    pols = list(cluster.policies)
    inc.remove_policy(pols[0].namespace, pols[0].name)
    inc.add_policy(dataclasses.replace(pols[0], name="readd"))
    inc.update_policy(dataclasses.replace(pols[1], ingress=pols[2].ingress))
    inc.update_pod_labels(5, {"zz": "qq"})
    np.testing.assert_array_equal(inc.reach, _full(inc.as_cluster(), cfg))


def test_mesh_matrix_free_stripes():
    """keep_matrix=False (the 1M-pod regime): diffs update only the sharded
    maps + dirty sets; solve_stripe re-verifies any dst range from the maps."""
    from kubernetes_verification_tpu.ops.tiled import unpack_cols
    from kubernetes_verification_tpu.parallel.mesh import mesh_for

    cluster = random_cluster(
        GeneratorConfig(n_pods=61, n_policies=11, n_namespaces=3, seed=43)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(
        cluster, cfg, mesh=mesh_for((4, 2)), keep_matrix=False
    )
    with pytest.raises(ValueError, match="keep_matrix"):
        inc.packed_reach()
    pols = list(cluster.policies)
    inc.update_policy(dataclasses.replace(pols[1], ingress=pols[2].ingress))
    inc.remove_policy(pols[3].namespace, pols[3].name)
    assert inc.dirty_cols.any() or inc.dirty_rows.any()
    ref = _full(inc.as_cluster(), cfg)
    full = unpack_cols(inc.solve_stripe(0, inc._n_padded), inc.n_pods)
    np.testing.assert_array_equal(full, ref)
    s = unpack_cols(inc.solve_stripe(32, 32), 32)  # dst cols [32, 64)
    np.testing.assert_array_equal(s[:, : 61 - 32], ref[:, 32:61])
    with pytest.raises(ValueError, match="non-negative"):
        inc.solve_stripe(-32, 32)
    # sweep_dirty covers exactly the needed stripes and retires the marks
    assert inc.dirty_stripes(32), "diffs above must have dirtied something"
    for d0, words in inc.sweep_dirty(32):
        got = unpack_cols(words, 32)
        if d0 >= inc.n_pods:  # pad-only stripe: col_mask zeroes everything
            assert not got.any()
            continue
        hi = min(d0 + 32, inc.n_pods)
        np.testing.assert_array_equal(got[:, : hi - d0], ref[:, d0:hi])
    assert not inc.dirty_rows.any() and not inc.dirty_cols.any()
    assert inc.dirty_stripes(32) == []


def test_packed_queries_available(setup):
    """The packed view serves the flagship-scale queries without unpacking."""
    cluster, cfg, inc = setup
    pr = inc.packed_reach()
    ref = _full(cluster, cfg)
    assert pr.all_isolated() == np.nonzero(~ref.any(axis=0))[0].tolist()
    assert pr.all_reachable() == np.nonzero(ref.all(axis=0))[0].tolist()
    np.testing.assert_array_equal(
        pr.out_degree(), ref.sum(axis=1, dtype=np.int64)
    )


def test_checkpoint_resume(tmp_path):
    """save → load must restore the exact state: same reach, and diffs
    applied after resume keep tracking the oracle (the resume re-freezes
    the vectorizer on the manifest's current labels)."""
    from kubernetes_verification_tpu.utils.persist import (
        load_packed_incremental,
        save_packed_incremental,
    )

    cluster = random_cluster(
        GeneratorConfig(n_pods=47, n_policies=9, n_namespaces=3, seed=71)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(cluster, cfg)
    pols = list(cluster.policies)
    inc.update_pod_labels(5, {"totally": "new"})  # dirty before the save
    inc.update_policy(dataclasses.replace(pols[1], ingress=pols[2].ingress))
    before = inc.reach.copy()

    d = str(tmp_path / "ckpt")
    save_packed_incremental(inc, d)
    res = load_packed_incremental(d)
    np.testing.assert_array_equal(res.reach, before)
    assert res.policies.keys() == inc.policies.keys()
    assert res.update_count == inc.update_count

    # diffs continue correctly after resume — including against the
    # relabelled pod (whose labels are now part of the re-frozen encoding)
    res.add_policy(
        kv.NetworkPolicy(
            "post-resume", namespace=res.pods[5].namespace,
            pod_selector=kv.Selector({"totally": "new"}),
            ingress=(),
        )
    )
    res.remove_policy(pols[0].namespace, pols[0].name)
    np.testing.assert_array_equal(res.reach, _full(res.as_cluster(), cfg))

    # a matrix-full checkpoint may resume matrix-free (e.g. onto a mesh the
    # matrix would not fit) and still re-verify via stripes
    from kubernetes_verification_tpu.ops.tiled import unpack_cols

    res2 = load_packed_incremental(d, keep_matrix=False)
    assert res2._packed is None
    got = unpack_cols(res2.solve_stripe(0, res2._n_padded), res2.n_pods)
    np.testing.assert_array_equal(got, before)


def test_checkpoint_resume_matrix_free_on_mesh(tmp_path):
    from kubernetes_verification_tpu.ops.tiled import unpack_cols
    from kubernetes_verification_tpu.parallel.mesh import mesh_for
    from kubernetes_verification_tpu.utils.persist import (
        load_packed_incremental,
        save_packed_incremental,
    )

    cluster = random_cluster(
        GeneratorConfig(n_pods=61, n_policies=11, n_namespaces=3, seed=72)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(
        cluster, cfg, mesh=mesh_for((4, 2)), keep_matrix=False
    )
    pols = list(cluster.policies)
    inc.update_policy(dataclasses.replace(pols[1], ingress=pols[2].ingress))
    d = str(tmp_path / "ckpt")
    save_packed_incremental(inc, d)
    res = load_packed_incremental(d, mesh=mesh_for((2, 4)))  # new factorisation
    assert not res.keep_matrix
    assert res.dirty_cols.any() == inc.dirty_cols.any()
    ref = _full(res.as_cluster(), cfg)
    got = unpack_cols(res.solve_stripe(0, res._n_padded), res.n_pods)
    np.testing.assert_array_equal(got, ref)


def test_checkpoint_flag_mismatch_rejected(tmp_path):
    from kubernetes_verification_tpu.utils.persist import (
        load_packed_incremental,
        save_packed_incremental,
    )

    cluster = random_cluster(
        GeneratorConfig(n_pods=23, n_policies=3, n_namespaces=2, seed=73)
    )
    inc = PackedIncrementalVerifier(cluster, kv.VerifyConfig(compute_ports=False))
    d = str(tmp_path / "ckpt")
    save_packed_incremental(inc, d)
    with pytest.raises(ValueError, match="semantic"):
        load_packed_incremental(
            d, kv.VerifyConfig(compute_ports=False, self_traffic=False)
        )


@pytest.mark.slow
def test_checkpoint_resume_with_zero_free_slots(tmp_path):
    """Regression: a checkpoint saved when every capacity slot is occupied
    (growth happens on the NEXT allocation) must resume without the prewarm
    writing its no-op zeros into an occupied slot — which would silently
    erase that policy's device state."""
    from kubernetes_verification_tpu.utils.persist import (
        load_packed_incremental,
        save_packed_incremental,
    )

    cluster = random_cluster(
        GeneratorConfig(n_pods=23, n_policies=2, n_namespaces=2, seed=81)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(cluster, cfg, slot_round=4)
    donor = random_cluster(
        GeneratorConfig(n_pods=23, n_policies=12, n_namespaces=2, seed=82)
    )
    # fill capacity exactly: initial capacity rounds (P+8)=10 up to 12
    for i, p in enumerate(donor.policies[:10]):
        inc.add_policy(dataclasses.replace(p, name=f"fill-{i}"))
    assert not inc._free, "fixture must exercise the zero-free-slot case"
    before = inc.reach.copy()

    d = str(tmp_path / "ckpt")
    save_packed_incremental(inc, d)
    res = load_packed_incremental(d)
    np.testing.assert_array_equal(res.reach, before)
    np.testing.assert_array_equal(res.reach, _full(res.as_cluster(), cfg))
    # and the grown capacity still allocates correctly
    res.add_policy(dataclasses.replace(donor.policies[0], name="after"))
    np.testing.assert_array_equal(res.reach, _full(res.as_cluster(), cfg))


# ---------------------------------------------------------------- pod churn


def _oracle_active(inc, cfg):
    """Oracle reach over the live pods, in slot order (== reach_active)."""
    return _full(inc.as_cluster(), cfg)


def test_pod_add_matches_oracle(setup):
    cluster, cfg, inc = setup
    ns = inc.pods[0].namespace
    idx = inc.add_pod(
        kv.Pod("churn-a", ns, dict(inc.pods[0].labels), ip="10.9.9.9")
    )
    assert idx == len(cluster.pods)  # took the first headroom slot
    np.testing.assert_array_equal(inc.reach_active(), _oracle_active(inc, cfg))
    # and with labels the frozen vocab has never seen
    inc.add_pod(kv.Pod("churn-b", ns, {"never": "seen-pair"}))
    np.testing.assert_array_equal(inc.reach_active(), _oracle_active(inc, cfg))


def test_pod_remove_matches_oracle(setup):
    cluster, cfg, inc = setup
    victim = inc.pods[3]
    idx = inc.remove_pod(victim.namespace, victim.name)
    assert idx == 3 and not inc.pod_active[3]
    assert inc.n_active == len(cluster.pods) - 1
    np.testing.assert_array_equal(inc.reach_active(), _oracle_active(inc, cfg))
    # the tombstoned row/column must be fully zero in the raw matrix
    raw = inc.reach
    assert not raw[3].any() and not raw[:, 3].any()
    # removing again raises; relabelling a tombstone raises
    with pytest.raises(KeyError):
        inc.remove_pod(victim.namespace, victim.name)
    with pytest.raises(KeyError):
        inc.update_pod_labels(3, {"a": "b"})


def test_pod_slot_reuse_and_policy_interaction(setup):
    """A removed slot is recycled by the next add; policies added AFTER the
    churn must see the new pod (and never the tombstone)."""
    cluster, cfg, inc = setup
    victim = inc.pods[5]
    inc.remove_pod(victim.namespace, victim.name)
    idx = inc.add_pod(kv.Pod("recycled", victim.namespace, {"role": "fresh"}))
    assert idx == 5  # recycled the tombstoned slot
    pol = kv.NetworkPolicy(
        name="sel-fresh",
        namespace=victim.namespace,
        pod_selector=kv.Selector({"role": "fresh"}),
        ingress=(),
    )
    inc.add_policy(pol)
    np.testing.assert_array_equal(inc.reach_active(), _oracle_active(inc, cfg))
    assert inc.packed_reach().ingress_isolated[5]


@pytest.mark.slow
def test_pod_headroom_growth():
    """Exhausting the pod headroom grows the pod axis in place."""
    cluster = random_cluster(
        GeneratorConfig(n_pods=120, n_policies=5, n_namespaces=2, seed=55)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(cluster, cfg)
    assert inc._n_padded == 128  # 8 headroom slots before a grow
    before = inc._n_padded
    for i in range(12):
        inc.add_pod(kv.Pod(f"grow-{i}", "ns-0", {"app": f"g{i}"}))
    assert inc._n_padded > before
    assert inc.n_active == 132
    np.testing.assert_array_equal(inc.reach_active(), _oracle_active(inc, cfg))


@pytest.mark.slow
def test_pod_headroom_param():
    cluster = random_cluster(
        GeneratorConfig(n_pods=100, n_policies=4, n_namespaces=2, seed=56)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(cluster, cfg, pod_headroom=300)
    assert inc._n_padded >= 400
    for i in range(250):
        inc.add_pod(kv.Pod(f"hr-{i}", "ns-0", {"app": "hr"}))
    assert inc._n_padded == 512  # no growth happened
    np.testing.assert_array_equal(inc.reach_active(), _oracle_active(inc, cfg))


@pytest.mark.slow
def test_fuzzed_pod_and_policy_churn():
    """Interleaved pod add/remove/relabel + policy add/remove/update must
    track the CPU oracle at every step.

    Self-validation: the fuzz is only meaningful if the churn actually
    moves reachability bits — a seed whose ops all no-op would "pass"
    while exercising nothing, so a floor on changed steps guards the
    test against silently going vacuous (seed 4 currently changes the
    matrix on 10 of 16 steps)."""
    cluster = random_cluster(
        GeneratorConfig(n_pods=37, n_policies=6, n_namespaces=3, seed=60)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(cluster, cfg)
    donor = random_cluster(
        GeneratorConfig(n_pods=30, n_policies=18, n_namespaces=3, seed=61)
    )
    rng = random.Random(4)
    added = 0
    changed_steps = 0
    prev = np.asarray(inc.reach_active()).copy()
    for step in range(16):
        op = rng.choice(
            ["add_pod", "rm_pod", "relabel", "add_pol", "rm_pol", "relabel_ns"]
        )
        if op == "add_pod":
            src = donor.pods[added % len(donor.pods)]
            inc.add_pod(
                kv.Pod(f"fz-{added}", src.namespace, dict(src.labels), ip=src.ip)
            )
            added += 1
        elif op == "rm_pod" and inc.n_active > 5:
            idx = rng.choice(list(inc.active_indices()))
            p = inc.pods[idx]
            inc.remove_pod(p.namespace, p.name)
        elif op == "relabel":
            idx = rng.choice(list(inc.active_indices()))
            inc.update_pod_labels(idx, {"fz": f"v{step}", "env": "x"})
        elif op == "add_pol":
            p = donor.policies[step % len(donor.policies)]
            key = f"{p.namespace}/fzp-{step}"
            inc.add_policy(dataclasses.replace(p, name=f"fzp-{step}"))
        elif op == "rm_pol" and inc.policies:
            key = rng.choice(sorted(inc.policies))
            ns, name = key.split("/", 1)
            inc.remove_policy(ns, name)
        elif op == "relabel_ns":
            tgt = rng.choice(inc.namespaces)
            donor_ns = rng.choice(cluster.namespaces)
            inc.update_namespace_labels(
                tgt.name, {**dict(donor_ns.labels), "fzns": f"s{step}"}
            )
        cur = np.asarray(inc.reach_active())
        np.testing.assert_array_equal(
            cur, _oracle_active(inc, cfg), err_msg=f"step {step}"
        )
        if cur.shape != prev.shape or not np.array_equal(cur, prev):
            changed_steps += 1
        prev = cur.copy()
    assert changed_steps >= 8, (
        f"fuzz went vacuous: only {changed_steps}/16 steps changed the "
        "reach matrix — the op mix or seed no longer exercises the "
        "incremental paths"
    )


@pytest.mark.slow
@pytest.mark.parametrize("shape", [(4, 2), (2, 4)])
def test_mesh_sharded_pod_churn(shape):
    from kubernetes_verification_tpu.parallel.mesh import mesh_for

    cluster = random_cluster(
        GeneratorConfig(n_pods=61, n_policies=9, n_namespaces=3, seed=62)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(cluster, cfg, mesh=mesh_for(shape))
    inc.add_pod(kv.Pod("mesh-new", inc.pods[0].namespace, {"m": "1"}))
    victim = inc.pods[7]
    inc.remove_pod(victim.namespace, victim.name)
    np.testing.assert_array_equal(inc.reach_active(), _oracle_active(inc, cfg))
    # growth on a mesh keeps the sharded layout working
    for i in range(80):
        inc.add_pod(kv.Pod(f"mesh-g{i}", "ns-0", {"app": "mg"}))
    np.testing.assert_array_equal(inc.reach_active(), _oracle_active(inc, cfg))


def test_matrix_free_pod_churn():
    from kubernetes_verification_tpu.ops.tiled import unpack_cols
    from kubernetes_verification_tpu.parallel.mesh import mesh_for

    cluster = random_cluster(
        GeneratorConfig(n_pods=61, n_policies=9, n_namespaces=3, seed=63)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(
        cluster, cfg, mesh=mesh_for((4, 2)), keep_matrix=False
    )
    inc.add_pod(kv.Pod("mf-new", inc.pods[0].namespace, {"m": "1"}))
    victim = inc.pods[9]
    inc.remove_pod(victim.namespace, victim.name)
    assert inc.dirty_rows.any() and inc.dirty_cols.any()
    ref = _oracle_active(inc, cfg)
    act = inc.active_indices()
    full = unpack_cols(inc.solve_stripe(0, inc._n_padded), inc.n_pods)
    np.testing.assert_array_equal(full[np.ix_(act, act)], ref)
    # tombstoned row/column is zero even in a fresh stripe solve
    assert not full[9].any() and not full[:, 9].any()


def test_namespace_relabel_matches_oracle(setup):
    """A namespace label change moves namespaceSelector matches for every
    pod in it — round 5's incremental op (pre-round-5 engines raised)."""
    cluster, cfg, inc = setup
    ns = cluster.namespaces[0]
    # another namespace's labels, fresh labels, then empty — each step
    # must track the oracle exactly
    for new in (
        dict(cluster.namespaces[1].labels),
        {"completely": "fresh", "tier": "x"},
        {},
    ):
        inc.update_namespace_labels(ns.name, new)
        np.testing.assert_array_equal(
            inc.reach_active(), _oracle_active(inc, cfg), err_msg=str(new)
        )
    # add_namespace with changed labels delegates to the relabel
    assert inc.add_namespace(kv.Namespace(ns.name, {"via": "add"})) is False
    assert inc._ns_labels[ns.name] == {"via": "add"}
    np.testing.assert_array_equal(inc.reach_active(), _oracle_active(inc, cfg))
    # relabeling an unknown namespace raises
    with pytest.raises(KeyError):
        inc.update_namespace_labels("no-such-ns", {"a": "b"})


def test_namespace_relabel_then_policy_diff(setup):
    """Policies (re-)encoded AFTER a namespace relabel must see the new
    labels (the vectorizer reads the live ns-label dict)."""
    cluster, cfg, inc = setup
    ns = cluster.namespaces[0]
    inc.update_namespace_labels(ns.name, {"team": "fresh-after-freeze"})
    pol = kv.NetworkPolicy(
        name="ns-sel-new",
        namespace=cluster.namespaces[1].name,
        pod_selector=kv.Selector({}),
        ingress=(
            kv.Rule(
                peers=(
                    kv.Peer(
                        namespace_selector=kv.Selector(
                            {"team": "fresh-after-freeze"}
                        )
                    ),
                )
            ),
        ),
    )
    inc.add_policy(pol)
    np.testing.assert_array_equal(inc.reach_active(), _oracle_active(inc, cfg))
    # and the relabel moves matches for a policy added before it, too
    inc.update_namespace_labels(ns.name, {"team": "other"})
    np.testing.assert_array_equal(inc.reach_active(), _oracle_active(inc, cfg))


def test_namespace_remove(setup):
    cluster, cfg, inc = setup
    ns = cluster.namespaces[2]
    # refuses while pods remain
    with pytest.raises(ValueError, match="active pod"):
        inc.remove_namespace(ns.name)
    for i in list(inc.active_indices()):
        if inc.pods[i].namespace == ns.name:
            inc.remove_pod(ns.name, inc.pods[i].name)
    # refuses while policies remain
    if any(k.split("/", 1)[0] == ns.name for k in inc.policies):
        with pytest.raises(ValueError, match="polic"):
            inc.remove_namespace(ns.name)
        for key in [
            k for k in list(inc.policies) if k.split("/", 1)[0] == ns.name
        ]:
            inc.remove_policy(*key.split("/", 1))
    inc.remove_namespace(ns.name)
    assert ns.name not in inc._ns_labels
    assert all(n.name != ns.name for n in inc.namespaces)
    np.testing.assert_array_equal(inc.reach_active(), _oracle_active(inc, cfg))
    with pytest.raises(KeyError):
        inc.remove_namespace(ns.name)
    # a same-named namespace can be re-created with different labels
    assert inc.add_namespace(kv.Namespace(ns.name, {"re": "born"})) is True
    inc.add_pod(kv.Pod("reborn", ns.name, {"app": "rb"}))
    np.testing.assert_array_equal(inc.reach_active(), _oracle_active(inc, cfg))


@pytest.mark.slow
def test_mesh_sharded_namespace_relabel():
    from kubernetes_verification_tpu.parallel.mesh import mesh_for

    cluster = random_cluster(
        GeneratorConfig(n_pods=61, n_policies=9, n_namespaces=3, seed=66)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(cluster, cfg, mesh=mesh_for((4, 2)))
    inc.update_namespace_labels(
        cluster.namespaces[0].name, dict(cluster.namespaces[2].labels)
    )
    np.testing.assert_array_equal(inc.reach_active(), _oracle_active(inc, cfg))


def test_matrix_free_namespace_relabel():
    from kubernetes_verification_tpu.ops.tiled import unpack_cols

    cluster = random_cluster(
        GeneratorConfig(n_pods=61, n_policies=9, n_namespaces=3, seed=67)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(cluster, cfg, keep_matrix=False)
    inc.update_namespace_labels(
        cluster.namespaces[0].name, {"mf": "relabel"}
    )
    assert inc.dirty_rows.any() and inc.dirty_cols.any()
    ref = _oracle_active(inc, cfg)
    act = inc.active_indices()
    full = unpack_cols(inc.solve_stripe(0, inc._n_padded), inc.n_pods)
    np.testing.assert_array_equal(full[np.ix_(act, act)], ref)


@pytest.mark.slow
def test_checkpoint_resume_with_pod_churn(tmp_path):
    from kubernetes_verification_tpu.utils.persist import (
        load_packed_incremental,
        save_packed_incremental,
    )

    cluster = random_cluster(
        GeneratorConfig(n_pods=43, n_policies=7, n_namespaces=3, seed=64)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(cluster, cfg)
    inc.add_pod(kv.Pod("ck-new", inc.pods[0].namespace, {"ck": "v"}))
    victim = inc.pods[11]
    inc.remove_pod(victim.namespace, victim.name)
    before = inc.reach_active().copy()

    d = str(tmp_path / "ckpt")
    save_packed_incremental(inc, d)
    res = load_packed_incremental(d)
    assert res.n_active == inc.n_active
    assert not res.pod_active[11]
    np.testing.assert_array_equal(res.reach_active(), before)
    # churn continues after resume: the tombstone slot is recycled
    idx = res.add_pod(kv.Pod("post-ck", "ns-0", {"p": "c"}))
    assert idx == 11
    res.remove_policy(*sorted(res.policies)[0].split("/", 1))
    np.testing.assert_array_equal(res.reach_active(), _oracle_active(res, cfg))


def test_tombstone_row_stays_zero_after_policy_diff(setup):
    """Regression (review): a policy diff's column patch recomputes every
    source row for the touched dst columns — tombstoned rows must stay zero
    (default-allow would otherwise mark the dead pod egress-open)."""
    cluster, cfg, inc = setup
    victim = inc.pods[4]
    inc.remove_pod(victim.namespace, victim.name)
    # broad policy: selects every pod in its ns, allows ingress from all
    inc.add_policy(
        kv.NetworkPolicy(
            name="broad",
            namespace=victim.namespace,
            pod_selector=kv.Selector({}),
            ingress=(kv.Rule(peers=()),),
        )
    )
    raw = inc.reach
    assert not raw[4].any() and not raw[:, 4].any()
    np.testing.assert_array_equal(inc.reach_active(), _oracle_active(inc, cfg))
    # relabel another pod (row+col patch path) — tombstone still zero
    inc.update_pod_labels(6, {"re": "label"})
    raw = inc.reach
    assert not raw[4].any() and not raw[:, 4].any()
    np.testing.assert_array_equal(inc.reach_active(), _oracle_active(inc, cfg))


def test_packed_queries_tombstone_aware():
    """all_reachable/all_isolated must neutralise tombstoned slots rather
    than letting their all-zero rows poison the word reductions."""
    cluster = random_cluster(
        GeneratorConfig(n_pods=12, n_policies=0, n_namespaces=1, seed=90)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(cluster, cfg)
    pr = inc.packed_reach()
    assert pr.all_reachable() == list(range(12))  # no policies: full mesh
    assert pr.all_isolated() == []
    p = inc.pods[5]
    inc.remove_pod(p.namespace, p.name)
    pr = inc.packed_reach()
    live = [i for i in range(12) if i != 5]
    assert pr.all_reachable() == live
    assert pr.all_isolated() == []


def test_churned_queries_tombstone_aware():
    # review r4: system_isolation must drop tombstoned dsts / reject a
    # tombstoned src; user_crosscheck must accept the live-pod list.
    cluster = random_cluster(
        GeneratorConfig(n_pods=24, n_policies=5, n_namespaces=2, seed=71)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(cluster, cfg)
    inc.remove_pod(inc.pods[5].namespace, inc.pods[5].name)
    pr = inc.packed_reach()
    assert 5 not in pr.system_isolation(0)
    with pytest.raises(ValueError, match="tombstoned"):
        pr.system_isolation(5)
    # live-pod list (what as_cluster() yields) maps onto slots
    live_pods = inc.as_cluster().pods
    assert len(live_pods) == 23
    got = pr.user_crosscheck(live_pods, "app")
    # slot-ordered full list answers identically
    slot_pods = [
        p if a else dataclasses.replace(p, labels={})
        for p, a in zip(inc.pods, inc.pod_active)
    ]
    assert pr.user_crosscheck(slot_pods, "app") == got
    # oracle: dense matrix over active pods only
    from kubernetes_verification_tpu.ops.queries import user_groups

    act = inc.active_indices()
    dense = inc.reach_active()
    gid = user_groups(live_pods, "app")
    expect = []
    for j in range(len(act)):
        other = (gid != gid[j]) & dense[:, j]
        if other.any():
            expect.append(int(act[j]))
    assert got == expect
    assert 5 not in got
    with pytest.raises(ValueError, match="pods"):
        pr.user_crosscheck(live_pods[:-1], "app")


def test_in_vocab_churn_reindexes_instead_of_dirtying(setup):
    """Review r4: churn whose labels/namespace stay inside the frozen
    universe patches the inverted indices in place — the dirty set (which
    costs object-level loops on every later policy diff) stays empty."""
    cluster, cfg, inc = setup
    donor_labels = dict(inc.pods[9].labels)
    inc.update_pod_labels(2, donor_labels)
    assert 2 not in inc._vectorizer.dirty
    np.testing.assert_array_equal(inc.reach, _full(inc.as_cluster(), cfg))
    # add with frozen-vocab labels into a frozen namespace: also clean
    idx = inc.add_pod(kv.Pod("clean", inc.pods[0].namespace, donor_labels))
    assert idx not in inc._vectorizer.dirty
    np.testing.assert_array_equal(inc.reach_active(), _oracle_active(inc, cfg))
    # a policy diff relying on the patched posting lists
    inc.update_policy(
        dataclasses.replace(
            cluster.policies[0],
            pod_selector=kv.Selector(dict(list(donor_labels.items())[:1]))
            if donor_labels else kv.Selector(),
        )
    )
    np.testing.assert_array_equal(inc.reach_active(), _oracle_active(inc, cfg))
    # out-of-vocab labels still dirty-mark
    inc.update_pod_labels(2, {"never": "seen"})
    assert 2 in inc._vectorizer.dirty
    np.testing.assert_array_equal(inc.reach_active(), _oracle_active(inc, cfg))


def test_failed_add_pod_leaves_no_state(setup):
    """Review r4: a pod whose evaluation raises (malformed IP against an
    ipBlock peer) must not leave a phantom half-registered pod."""
    cluster, cfg, inc = setup
    ns = inc.pods[0].namespace
    inc.add_policy(
        kv.NetworkPolicy(
            "ip-pol", namespace=ns, pod_selector=kv.Selector(),
            ingress=(kv.Rule(peers=(kv.Peer(ip_block=kv.IpBlock("10.0.0.0/8")),)),),
        )
    )
    before_n = inc.n_pods
    with pytest.raises(ValueError):
        inc.add_pod(kv.Pod("badip", ns, {"a": "b"}, ip="not-an-ip"))
    assert inc.n_pods == before_n
    assert f"{ns}/badip" not in inc._pod_idx
    inc.add_pod(kv.Pod("goodip", ns, {"a": "b"}, ip="10.1.2.3"))
    np.testing.assert_array_equal(inc.reach_active(), _oracle_active(inc, cfg))
