"""Packed incremental re-verify: the device-resident, bit-packed diff path
(BASELINE config 5's 100k-scale half). Every mutation's result must equal a
from-scratch CPU-oracle solve of the mutated cluster, and the packed verifier
must agree bit-for-bit with the dense count-matrix verifier."""
import dataclasses
import random

import numpy as np
import pytest

import kubernetes_verification_tpu as kv
from kubernetes_verification_tpu.harness.generate import (
    GeneratorConfig,
    random_cluster,
)
from kubernetes_verification_tpu.incremental import IncrementalVerifier
from kubernetes_verification_tpu.packed_incremental import (
    PackedIncrementalVerifier,
)


def _full(cluster, config):
    return kv.verify(
        cluster,
        kv.VerifyConfig(
            backend="cpu",
            compute_ports=False,
            self_traffic=config.self_traffic,
            default_allow_unselected=config.default_allow_unselected,
            direction_aware_isolation=config.direction_aware_isolation,
        ),
    ).reach


@pytest.fixture()
def setup():
    cluster = random_cluster(
        GeneratorConfig(n_pods=57, n_policies=9, n_namespaces=3, seed=7)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    return cluster, cfg, PackedIncrementalVerifier(cluster, cfg)


def test_initial_build_matches_oracle(setup):
    cluster, cfg, inc = setup
    np.testing.assert_array_equal(inc.reach, _full(cluster, cfg))


def test_remove_add_update_sequence(setup):
    cluster, cfg, inc = setup
    pols = list(cluster.policies)
    inc.remove_policy(pols[0].namespace, pols[0].name)
    np.testing.assert_array_equal(inc.reach, _full(inc.as_cluster(), cfg))
    inc.add_policy(dataclasses.replace(pols[0], name="brand-new"))
    np.testing.assert_array_equal(inc.reach, _full(inc.as_cluster(), cfg))
    upd = dataclasses.replace(
        pols[1],
        ingress=list(pols[2].ingress or []),
        egress=list(pols[1].egress or []),
    )
    inc.update_policy(upd)
    np.testing.assert_array_equal(inc.reach, _full(inc.as_cluster(), cfg))


def test_relabel_then_policy_diff_uses_dirty_fixup(setup):
    """A pod relabelled to pairs the frozen vocab has never seen must still
    be matched correctly by policies (re-)encoded afterwards."""
    cluster, cfg, inc = setup
    inc.update_pod_labels(3, {"totally": "unseen", "fresh": "pair"})
    np.testing.assert_array_equal(inc.reach, _full(inc.as_cluster(), cfg))
    pol = kv.NetworkPolicy(
        name="sel-unseen",
        namespace=inc.pods[3].namespace,
        pod_selector=kv.Selector({"totally": "unseen"}),
        ingress=(
            kv.Rule(peers=(kv.Peer(pod_selector=kv.Selector({"fresh": "pair"})),)),
        ),
    )
    inc.add_policy(pol)
    ref = _full(inc.as_cluster(), cfg)
    np.testing.assert_array_equal(inc.reach, ref)
    # the new policy must actually bite: pod 3 became ingress-isolated
    assert inc.packed_reach().ingress_isolated[3]


def test_fuzzed_diffs_match_oracle_and_dense():
    cluster = random_cluster(
        GeneratorConfig(n_pods=41, n_policies=7, n_namespaces=3, seed=21)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    packed = PackedIncrementalVerifier(cluster, cfg)
    dense = IncrementalVerifier(cluster, cfg)
    donor = random_cluster(
        GeneratorConfig(n_pods=41, n_policies=24, n_namespaces=3, seed=22)
    )
    rng = random.Random(0)
    for i, p in enumerate(donor.policies[:10]):
        p2 = dataclasses.replace(p, name=f"fuzz-{i}")
        packed.add_policy(p2)
        dense.add_policy(p2)
        if i % 3 == 0:
            key = rng.choice(sorted(packed.policies))
            ns, name = key.split("/", 1)
            packed.remove_policy(ns, name)
            dense.remove_policy(ns, name)
        if i % 4 == 1:
            j = rng.randrange(41)
            lbl = {"app": f"x{i}", "env": "prod"}
            packed.update_pod_labels(j, lbl)
            dense.update_pod_labels(j, lbl)
        ref = _full(packed.as_cluster(), cfg)
        np.testing.assert_array_equal(packed.reach, ref, err_msg=f"step {i}")
        np.testing.assert_array_equal(dense.reach, ref, err_msg=f"dense {i}")


@pytest.mark.parametrize(
    "self_traffic,default_allow,direction_aware",
    [(False, True, True), (True, False, True), (True, True, False),
     (False, False, False)],
)
def test_flag_variants(self_traffic, default_allow, direction_aware):
    cluster = random_cluster(
        GeneratorConfig(n_pods=33, n_policies=7, n_namespaces=2, seed=11)
    )
    cfg = kv.VerifyConfig(
        compute_ports=False,
        self_traffic=self_traffic,
        default_allow_unselected=default_allow,
        direction_aware_isolation=direction_aware,
    )
    inc = PackedIncrementalVerifier(cluster, cfg)
    np.testing.assert_array_equal(inc.reach, _full(cluster, cfg))
    inc.update_policy(dataclasses.replace(cluster.policies[0], ingress=[]))
    inc.remove_policy(
        cluster.policies[1].namespace, cluster.policies[1].name
    )
    np.testing.assert_array_equal(inc.reach, _full(inc.as_cluster(), cfg))


def test_capacity_growth():
    cluster = random_cluster(
        GeneratorConfig(n_pods=23, n_policies=3, n_namespaces=2, seed=31)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(cluster, cfg, slot_round=4)
    donor = random_cluster(
        GeneratorConfig(n_pods=23, n_policies=16, n_namespaces=2, seed=32)
    )
    for i, p in enumerate(donor.policies):
        inc.add_policy(dataclasses.replace(p, name=f"grow-{i}"))
    assert len(inc.policies) == 19
    np.testing.assert_array_equal(inc.reach, _full(inc.as_cluster(), cfg))


def test_empty_policy_cluster():
    cluster = random_cluster(
        GeneratorConfig(n_pods=19, n_policies=0, n_namespaces=2, seed=41)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(cluster, cfg)
    np.testing.assert_array_equal(inc.reach, _full(cluster, cfg))
    donor = random_cluster(
        GeneratorConfig(n_pods=19, n_policies=2, n_namespaces=2, seed=42)
    )
    for p in donor.policies:
        inc.add_policy(p)
    np.testing.assert_array_equal(inc.reach, _full(inc.as_cluster(), cfg))


@pytest.mark.parametrize("shape", [(8, 1), (4, 2), (2, 4)])
def test_mesh_sharded_state_diffs(shape):
    """Config-5 composition: the same verifier with its state sharded over a
    (pods, grants) mesh — every diff kernel runs SPMD — must track the
    oracle exactly."""
    from kubernetes_verification_tpu.parallel.mesh import mesh_for

    cluster = random_cluster(
        GeneratorConfig(n_pods=61, n_policies=11, n_namespaces=3, seed=43)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(cluster, cfg, mesh=mesh_for(shape))
    assert inc.keep_matrix
    np.testing.assert_array_equal(inc.reach, _full(cluster, cfg))
    pols = list(cluster.policies)
    inc.remove_policy(pols[0].namespace, pols[0].name)
    inc.add_policy(dataclasses.replace(pols[0], name="readd"))
    inc.update_policy(dataclasses.replace(pols[1], ingress=pols[2].ingress))
    inc.update_pod_labels(5, {"zz": "qq"})
    np.testing.assert_array_equal(inc.reach, _full(inc.as_cluster(), cfg))


def test_mesh_matrix_free_stripes():
    """keep_matrix=False (the 1M-pod regime): diffs update only the sharded
    maps + dirty sets; solve_stripe re-verifies any dst range from the maps."""
    from kubernetes_verification_tpu.ops.tiled import unpack_cols
    from kubernetes_verification_tpu.parallel.mesh import mesh_for

    cluster = random_cluster(
        GeneratorConfig(n_pods=61, n_policies=11, n_namespaces=3, seed=43)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(
        cluster, cfg, mesh=mesh_for((4, 2)), keep_matrix=False
    )
    with pytest.raises(ValueError, match="keep_matrix"):
        inc.packed_reach()
    pols = list(cluster.policies)
    inc.update_policy(dataclasses.replace(pols[1], ingress=pols[2].ingress))
    inc.remove_policy(pols[3].namespace, pols[3].name)
    assert inc.dirty_cols.any() or inc.dirty_rows.any()
    ref = _full(inc.as_cluster(), cfg)
    full = unpack_cols(inc.solve_stripe(0, inc._n_padded), inc.n_pods)
    np.testing.assert_array_equal(full, ref)
    s = unpack_cols(inc.solve_stripe(32, 32), 32)  # dst cols [32, 64)
    np.testing.assert_array_equal(s[:, : 61 - 32], ref[:, 32:61])
    with pytest.raises(ValueError, match="non-negative"):
        inc.solve_stripe(-32, 32)
    # sweep_dirty covers exactly the needed stripes and retires the marks
    assert inc.dirty_stripes(32), "diffs above must have dirtied something"
    for d0, words in inc.sweep_dirty(32):
        got = unpack_cols(words, 32)
        if d0 >= inc.n_pods:  # pad-only stripe: col_mask zeroes everything
            assert not got.any()
            continue
        hi = min(d0 + 32, inc.n_pods)
        np.testing.assert_array_equal(got[:, : hi - d0], ref[:, d0:hi])
    assert not inc.dirty_rows.any() and not inc.dirty_cols.any()
    assert inc.dirty_stripes(32) == []


def test_packed_queries_available(setup):
    """The packed view serves the flagship-scale queries without unpacking."""
    cluster, cfg, inc = setup
    pr = inc.packed_reach()
    ref = _full(cluster, cfg)
    assert pr.all_isolated() == np.nonzero(~ref.any(axis=0))[0].tolist()
    assert pr.all_reachable() == np.nonzero(ref.all(axis=0))[0].tolist()
    np.testing.assert_array_equal(
        pr.out_degree(), ref.sum(axis=1, dtype=np.int64)
    )


def test_checkpoint_resume(tmp_path):
    """save → load must restore the exact state: same reach, and diffs
    applied after resume keep tracking the oracle (the resume re-freezes
    the vectorizer on the manifest's current labels)."""
    from kubernetes_verification_tpu.utils.persist import (
        load_packed_incremental,
        save_packed_incremental,
    )

    cluster = random_cluster(
        GeneratorConfig(n_pods=47, n_policies=9, n_namespaces=3, seed=71)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(cluster, cfg)
    pols = list(cluster.policies)
    inc.update_pod_labels(5, {"totally": "new"})  # dirty before the save
    inc.update_policy(dataclasses.replace(pols[1], ingress=pols[2].ingress))
    before = inc.reach.copy()

    d = str(tmp_path / "ckpt")
    save_packed_incremental(inc, d)
    res = load_packed_incremental(d)
    np.testing.assert_array_equal(res.reach, before)
    assert res.policies.keys() == inc.policies.keys()
    assert res.update_count == inc.update_count

    # diffs continue correctly after resume — including against the
    # relabelled pod (whose labels are now part of the re-frozen encoding)
    res.add_policy(
        kv.NetworkPolicy(
            "post-resume", namespace=res.pods[5].namespace,
            pod_selector=kv.Selector({"totally": "new"}),
            ingress=(),
        )
    )
    res.remove_policy(pols[0].namespace, pols[0].name)
    np.testing.assert_array_equal(res.reach, _full(res.as_cluster(), cfg))

    # a matrix-full checkpoint may resume matrix-free (e.g. onto a mesh the
    # matrix would not fit) and still re-verify via stripes
    from kubernetes_verification_tpu.ops.tiled import unpack_cols

    res2 = load_packed_incremental(d, keep_matrix=False)
    assert res2._packed is None
    got = unpack_cols(res2.solve_stripe(0, res2._n_padded), res2.n_pods)
    np.testing.assert_array_equal(got, before)


def test_checkpoint_resume_matrix_free_on_mesh(tmp_path):
    from kubernetes_verification_tpu.ops.tiled import unpack_cols
    from kubernetes_verification_tpu.parallel.mesh import mesh_for
    from kubernetes_verification_tpu.utils.persist import (
        load_packed_incremental,
        save_packed_incremental,
    )

    cluster = random_cluster(
        GeneratorConfig(n_pods=61, n_policies=11, n_namespaces=3, seed=72)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(
        cluster, cfg, mesh=mesh_for((4, 2)), keep_matrix=False
    )
    pols = list(cluster.policies)
    inc.update_policy(dataclasses.replace(pols[1], ingress=pols[2].ingress))
    d = str(tmp_path / "ckpt")
    save_packed_incremental(inc, d)
    res = load_packed_incremental(d, mesh=mesh_for((2, 4)))  # new factorisation
    assert not res.keep_matrix
    assert res.dirty_cols.any() == inc.dirty_cols.any()
    ref = _full(res.as_cluster(), cfg)
    got = unpack_cols(res.solve_stripe(0, res._n_padded), res.n_pods)
    np.testing.assert_array_equal(got, ref)


def test_checkpoint_flag_mismatch_rejected(tmp_path):
    from kubernetes_verification_tpu.utils.persist import (
        load_packed_incremental,
        save_packed_incremental,
    )

    cluster = random_cluster(
        GeneratorConfig(n_pods=23, n_policies=3, n_namespaces=2, seed=73)
    )
    inc = PackedIncrementalVerifier(cluster, kv.VerifyConfig(compute_ports=False))
    d = str(tmp_path / "ckpt")
    save_packed_incremental(inc, d)
    with pytest.raises(ValueError, match="semantic"):
        load_packed_incremental(
            d, kv.VerifyConfig(compute_ports=False, self_traffic=False)
        )


def test_checkpoint_resume_with_zero_free_slots(tmp_path):
    """Regression: a checkpoint saved when every capacity slot is occupied
    (growth happens on the NEXT allocation) must resume without the prewarm
    writing its no-op zeros into an occupied slot — which would silently
    erase that policy's device state."""
    from kubernetes_verification_tpu.utils.persist import (
        load_packed_incremental,
        save_packed_incremental,
    )

    cluster = random_cluster(
        GeneratorConfig(n_pods=23, n_policies=2, n_namespaces=2, seed=81)
    )
    cfg = kv.VerifyConfig(compute_ports=False)
    inc = PackedIncrementalVerifier(cluster, cfg, slot_round=4)
    donor = random_cluster(
        GeneratorConfig(n_pods=23, n_policies=12, n_namespaces=2, seed=82)
    )
    # fill capacity exactly: initial capacity rounds (P+8)=10 up to 12
    for i, p in enumerate(donor.policies[:10]):
        inc.add_policy(dataclasses.replace(p, name=f"fill-{i}"))
    assert not inc._free, "fixture must exercise the zero-free-slot case"
    before = inc.reach.copy()

    d = str(tmp_path / "ckpt")
    save_packed_incremental(inc, d)
    res = load_packed_incremental(d)
    np.testing.assert_array_equal(res.reach, before)
    np.testing.assert_array_equal(res.reach, _full(res.as_cluster(), cfg))
    # and the grown capacity still allocates correctly
    res.add_policy(dataclasses.replace(donor.policies[0], name="after"))
    np.testing.assert_array_equal(res.reach, _full(res.as_cluster(), cfg))
