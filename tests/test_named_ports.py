"""Named-port resolution against destination pods (real-k8s semantics).

The reference lost ports entirely (``kubesv/kubesv/model.py:365-385``); our
round-2 build matched named specs by (protocol, name) alone. These tests pin
the real behaviour: a named port resolves, per destination pod, to the number
that pod's container spec declares under the name — two pods exposing the
same name on different numbers are matched on different concrete ports, and
a named grant on one side interoperates with a *numeric* grant covering the
resolved number on the other side.
"""
import numpy as np
import pytest

import kubernetes_verification_tpu as kv
from kubernetes_verification_tpu.encode.encoder import encode_cluster
from kubernetes_verification_tpu.harness.generate import (
    GeneratorConfig,
    random_cluster,
)
from kubernetes_verification_tpu.ops.tiled import tiled_k8s_reach


def _cluster():
    """web-a exposes http on 8080, web-b on 9090; client talks to both."""
    pods = [
        kv.Pod("web-a", "prod", {"app": "web"},
               container_ports={"http": ("TCP", 8080)}),
        kv.Pod("web-b", "prod", {"app": "web"},
               container_ports={"http": ("TCP", 9090)}),
        kv.Pod("client", "prod", {"app": "client"}),
    ]
    ingress = kv.NetworkPolicy(
        "allow-http", namespace="prod",
        pod_selector=kv.Selector({"app": "web"}),
        ingress=(
            kv.Rule(
                peers=(kv.Peer(pod_selector=kv.Selector({"app": "client"})),),
                ports=(kv.PortSpec("TCP", "http"),),
            ),
        ),
    )
    return pods, ingress


def _reach(cluster, backend, **opts):
    return kv.verify(
        cluster,
        kv.VerifyConfig(backend=backend, compute_ports=True, **opts),
    )


BACKENDS = ["cpu", "tpu", "native", "datalog"]


@pytest.mark.parametrize("backend", BACKENDS)
def test_same_name_different_numbers(backend):
    pods, ingress = _cluster()
    cluster = kv.Cluster(pods=pods, policies=[ingress])
    res = _reach(cluster, backend)
    a, b, c = 0, 1, 2
    # client reaches both webs (on their own resolved ports)
    assert res.reachable(c, a) and res.reachable(c, b)
    # webs are ingress-isolated against each other (not client-labelled)
    assert not res.reachable(a, b) and not res.reachable(b, a)
    # the allowed atom for web-a holds 8080 (not 9090) and vice versa
    atoms = res.port_atoms
    qa = [q for q, at in enumerate(atoms) if at.lo <= 8080 <= at.hi and at.protocol == "TCP"]
    qb = [q for q, at in enumerate(atoms) if at.lo <= 9090 <= at.hi and at.protocol == "TCP"]
    assert res.reach_ports[c, a, qa[0]] and not res.reach_ports[c, a, qb[0]]
    assert res.reach_ports[c, b, qb[0]] and not res.reach_ports[c, b, qa[0]]


@pytest.mark.parametrize("backend", BACKENDS)
def test_named_crosses_numeric(backend):
    """A named ingress grant must interoperate with a numeric egress grant on
    the RESOLVED number — impossible under by-name matching, which kept named
    coverage in a separate by-name slot."""
    pods, ingress = _cluster()
    egress = kv.NetworkPolicy(
        "client-egress-8080", namespace="prod",
        pod_selector=kv.Selector({"app": "client"}),
        egress=(
            kv.Rule(
                peers=(kv.Peer(pod_selector=kv.Selector({"app": "web"})),),
                ports=(kv.PortSpec("TCP", 8080),),
            ),
        ),
    )
    cluster = kv.Cluster(pods=pods, policies=[ingress, egress])
    res = _reach(cluster, backend)
    a, b, c = 0, 1, 2
    # client may egress on 8080 only: reaches web-a (http→8080) but NOT
    # web-b (http→9090 — the conjunction is empty on every atom)
    assert res.reachable(c, a)
    assert not res.reachable(c, b)


@pytest.mark.parametrize("backend", BACKENDS)
def test_egress_named_resolves_against_peer(backend):
    """Egress named ports resolve against the traffic DESTINATION (the
    peer), not the sending pod — regression for a datalog emission that
    gated the sender instead."""
    pods = [
        kv.Pod("sender", "prod", {"app": "client"}),  # declares no ports
        kv.Pod("web-a", "prod", {"app": "web"},
               container_ports={"http": ("TCP", 8080)}),
        kv.Pod("web-b", "prod", {"app": "web"},
               container_ports={"http": ("TCP", 9090)}),
    ]
    egress = kv.NetworkPolicy(
        "egress-http", namespace="prod",
        pod_selector=kv.Selector({"app": "client"}),
        egress=(
            kv.Rule(
                peers=(kv.Peer(pod_selector=kv.Selector({"app": "web"})),),
                ports=(kv.PortSpec("TCP", "http"),),
            ),
        ),
    )
    cluster = kv.Cluster(pods=pods, policies=[egress])
    res = _reach(cluster, backend)
    s, a, b = 0, 1, 2
    # sender may reach both webs, each on its own resolved port
    assert res.reachable(s, a) and res.reachable(s, b)
    atoms = res.port_atoms
    qa = next(q for q, at in enumerate(atoms)
              if at.lo <= 8080 <= at.hi and at.protocol == "TCP")
    qb = next(q for q, at in enumerate(atoms)
              if at.lo <= 9090 <= at.hi and at.protocol == "TCP")
    assert res.reach_ports[s, a, qa] and not res.reach_ports[s, a, qb]
    assert res.reach_ports[s, b, qb] and not res.reach_ports[s, b, qa]


def test_undeclared_name_matches_nothing():
    pods, ingress = _cluster()
    pods[0] = kv.Pod("web-a", "prod", {"app": "web"})  # drops the name
    cluster = kv.Cluster(pods=pods, policies=[ingress])
    res = _reach(cluster, "cpu")
    a, b, c = 0, 1, 2
    assert not res.reachable(c, a)  # nothing resolves on web-a
    assert res.reachable(c, b)


def test_protocol_must_match():
    pods, ingress = _cluster()
    pods[1] = kv.Pod(
        "web-b", "prod", {"app": "web"},
        container_ports={"http": ("UDP", 9090)},  # wrong protocol
    )
    cluster = kv.Cluster(pods=pods, policies=[ingress])
    res = _reach(cluster, "cpu")
    a, b, c = 0, 1, 2
    assert res.reachable(c, a)
    assert not res.reachable(c, b)


def test_tiled_and_sharded_packed_match_oracle():
    pods, ingress = _cluster()
    egress = kv.NetworkPolicy(
        "client-egress-8080", namespace="prod",
        pod_selector=kv.Selector({"app": "client"}),
        egress=(
            kv.Rule(
                peers=(kv.Peer(pod_selector=kv.Selector({"app": "web"})),),
                ports=(kv.PortSpec("TCP", 8080),),
            ),
        ),
    )
    cluster = kv.Cluster(pods=pods, policies=[ingress, egress])
    ref = _reach(cluster, "cpu")
    enc = encode_cluster(cluster, compute_ports=True)
    assert enc.restrict_bank is not None
    tiled = tiled_k8s_reach(enc, tile=32)
    np.testing.assert_array_equal(tiled.to_bool(), ref.reach)
    res_sp = kv.verify(
        cluster,
        kv.VerifyConfig(
            backend="sharded-packed",
            compute_ports=True,
            backend_options=(
                ("mesh", (4, 2)), ("tile", 32), ("chunk", 8),
                ("keep_matrix", True),
            ),
        ),
    )
    np.testing.assert_array_equal(res_sp.reach, ref.reach)


def test_random_clusters_with_heavy_named_ports():
    """Randomised differential sweep with a high named-port rate: every
    port-aware backend must agree with the oracle bit-for-bit."""
    cluster = random_cluster(
        GeneratorConfig(
            n_pods=41, n_policies=13, n_namespaces=3,
            p_ports=0.9, p_named_port=0.6, seed=5,
        )
    )
    enc = encode_cluster(cluster, compute_ports=True)
    ref = _reach(cluster, "cpu")
    for backend in ("tpu", "native", "datalog"):
        got = _reach(cluster, backend)
        np.testing.assert_array_equal(got.reach, ref.reach, err_msg=backend)
        np.testing.assert_array_equal(
            got.reach_ports, ref.reach_ports, err_msg=backend
        )
    tiled = tiled_k8s_reach(enc, tile=32)
    np.testing.assert_array_equal(tiled.to_bool(), ref.reach)
