"""Subprocess body for the stripe-owner SIGKILL chaos test
(tests/test_stripes.py).

One stripe owner on its own simulated host: it rebuilds the
deterministic chaos cluster, replays the deterministic event stream
into its :class:`StripeEngine`, and serves ``POST /v1/stripe`` +
``/healthz`` over HTTP (:class:`StripeFollower.serve_http`). The parent
kills it with a raw SIGKILL — no graceful shutdown, exactly like a
machine loss — and asserts the coordinator either retries a surviving
owner of the same stripe or fails typed, never truncating an answer.

Handshake: the URL is published to ``--url-file`` via tmp +
``os.replace`` so the parent never reads a half-written line; the child
then idles until ``--ack-file`` appears (clean-exit path — the chaos
paths never create it).

MUST mirror the parent test's generator knobs exactly
(``_chaos_cluster`` in tests/test_stripes.py): the parent's whole-state
oracle replays the same stream against the same cluster.
"""
import argparse
import os
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--url-file", required=True)
    ap.add_argument("--ack-file", required=True)
    ap.add_argument("--stripe-index", type=int, required=True)
    ap.add_argument("--stripe-count", type=int, required=True)
    ap.add_argument("--pods", type=int, default=36)
    ap.add_argument("--n-events", type=int, default=48)
    ap.add_argument("--replica", default="")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

    import kubernetes_verification_tpu as kv
    from kubernetes_verification_tpu.harness.generate import (
        GeneratorConfig,
        random_cluster,
        random_event_stream,
    )
    from kubernetes_verification_tpu.serve.stripes import StripeFollower

    cluster = random_cluster(
        GeneratorConfig(
            n_pods=args.pods, n_policies=16, n_namespaces=5, seed=11,
            p_ipblock_peer=0.0, min_selector_labels=1,
        )
    )
    events = random_event_stream(cluster, n_events=args.n_events, seed=13)
    cfg = kv.VerifyConfig(backend="cpu", compute_ports=False)
    replica = args.replica or (
        f"chaos-{args.stripe_index + 1}-of-{args.stripe_count}"
    )
    follower = StripeFollower(
        cluster, cfg,
        stripe=(args.stripe_index, args.stripe_count),
        replica=replica,
    )
    follower.apply(events)

    server = follower.serve_http(args.workdir)
    tmp = args.url_file + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(server.url)
    os.replace(tmp, args.url_file)

    deadline = time.time() + 120.0
    while not os.path.exists(args.ack_file):
        if time.time() > deadline:
            print("parent never acked", file=sys.stderr)
            return 1
        time.sleep(0.05)
    server.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
