"""Regression tests for review findings: port-aware Datalog reach, config
persistence in incremental checkpoints, and the zero-policy tiled path."""
import numpy as np
import pytest

import kubernetes_verification_tpu as kv
from kubernetes_verification_tpu.encode.encoder import encode_cluster
from kubernetes_verification_tpu.incremental import IncrementalVerifier
from kubernetes_verification_tpu.ops.tiled import tiled_k8s_reach
from kubernetes_verification_tpu.utils.persist import (
    load_incremental,
    save_incremental,
)


def _port_conjunction_cluster():
    """Two pods whose only grants are on disjoint ports: reachable on *no*
    port atom even though each direction allows on *some* port."""
    a = kv.Pod("a", "ns1", {"r": "a"})
    b = kv.Pod("b", "ns1", {"r": "b"})
    p1 = kv.NetworkPolicy(
        "p1", namespace="ns1", pod_selector=kv.Selector({"r": "b"}),
        ingress=(kv.Rule(peers=(kv.Peer(pod_selector=kv.Selector({"r": "a"})),),
                         ports=(kv.PortSpec("TCP", 80),)),),
    )
    p2 = kv.NetworkPolicy(
        "p2", namespace="ns1", pod_selector=kv.Selector({"r": "a"}),
        policy_types=("Egress",),
        egress=(kv.Rule(peers=(kv.Peer(pod_selector=kv.Selector({"r": "b"})),),
                        ports=(kv.PortSpec("TCP", 443),)),),
    )
    return kv.Cluster(pods=[a, b], policies=[p1, p2])


def test_datalog_enforces_port_conjunction():
    cluster = _port_conjunction_cluster()
    for backend in ("cpu", "datalog", "tpu", "native"):
        if backend not in kv.available_backends():
            continue
        res = kv.verify(cluster, kv.VerifyConfig(backend=backend))
        assert not res.reachable(0, 1), backend  # disjoint ports → no path
    # any-port mode (ports ignored) must say reachable — on every backend
    res = kv.verify(
        cluster, kv.VerifyConfig(backend="datalog", compute_ports=False)
    )
    assert res.reachable(0, 1)


def test_incremental_checkpoint_preserves_config(tmp_path):
    cluster = kv.Cluster(pods=[kv.Pod("a", "x"), kv.Pod("b", "x")])
    cfg = kv.VerifyConfig(
        compute_ports=False, default_allow_unselected=False, self_traffic=False
    )
    inc = IncrementalVerifier(cluster, cfg)
    assert not inc.reach.any()
    save_incremental(inc, str(tmp_path / "c"))
    resumed = load_incremental(str(tmp_path / "c"))  # no config passed
    assert resumed.config.default_allow_unselected is False
    assert not resumed.reach.any()


def test_tiled_zero_policies():
    cluster = kv.Cluster(pods=[kv.Pod(f"p{i}", "x", {"k": str(i)}) for i in range(5)])
    enc = encode_cluster(cluster, compute_ports=False)
    got = tiled_k8s_reach(enc, tile=32, chunk=8)
    # no policies + default allow → everything reachable
    assert got.to_bool().all()
    ref = kv.verify(cluster, kv.VerifyConfig(backend="cpu", compute_ports=False))
    np.testing.assert_array_equal(got.to_bool(), ref.reach)


def test_incremental_does_not_mutate_caller_cluster():
    # ADVICE r1: IncrementalVerifier must deep-copy pods; update_pod_labels
    # previously mutated the caller's Pod objects in place.
    pod = kv.Pod("a", "x", {"team": "blue"})
    cluster = kv.Cluster(pods=[pod, kv.Pod("b", "x")])
    inc = IncrementalVerifier(cluster, kv.VerifyConfig(compute_ports=False))
    inc.update_pod_labels(0, {"team": "red"})
    assert pod.labels == {"team": "blue"}


def test_load_incremental_rejects_flag_flip(tmp_path):
    # ADVICE r1: a resume with different semantic flags must raise instead of
    # silently reinterpreting the checkpointed counts.
    cluster = kv.Cluster(pods=[kv.Pod("a", "x"), kv.Pod("b", "x")])
    cfg = kv.VerifyConfig(compute_ports=False, self_traffic=False)
    inc = IncrementalVerifier(cluster, cfg)
    save_incremental(inc, str(tmp_path / "c"))
    with pytest.raises(ValueError, match="semantic flags"):
        load_incremental(
            str(tmp_path / "c"),
            config=kv.VerifyConfig(compute_ports=False, self_traffic=True),
        )
    # identical flags (different backend) still resumes fine
    resumed = load_incremental(
        str(tmp_path / "c"),
        config=kv.VerifyConfig(
            backend="tpu", compute_ports=False, self_traffic=False
        ),
    )
    assert resumed.config.backend == "tpu"


def test_mesh_opt_accepts_bare_int():
    # ADVICE r3: ``--opt mesh=8`` parses to a bare int; mesh_for must wrap it
    # as (n, 1) instead of crashing with "'int' object is not iterable".
    from kubernetes_verification_tpu.parallel.mesh import mesh_for

    m = mesh_for(1, devices=[__import__("jax").devices()[0]])
    assert dict(m.shape) == {"pods": 1, "grants": 1}


def test_matrix_free_to_bool_guided_error():
    # ADVICE r3: edges()/to_bool() on a matrix-free result must raise the
    # same guided keep_matrix ValueError as reachable(), not a TypeError.
    from kubernetes_verification_tpu.parallel.mesh import mesh_for
    from kubernetes_verification_tpu.parallel.packed_sharded import (
        sharded_packed_reach,
    )

    cluster = kv.Cluster(
        pods=[kv.Pod(f"p{i}", "x", {"k": str(i)}) for i in range(9)]
    )
    enc = encode_cluster(cluster, compute_ports=False)
    pk = sharded_packed_reach(mesh_for(), enc, keep_matrix=False)
    with pytest.raises(ValueError, match="keep_matrix"):
        pk.to_bool()


def test_verify_config_positional_tail_is_backend_options():
    # ADVICE r3: label_relation (round 3) is keyword-only so callers passing
    # backend_options positionally keep their pre-round-3 meaning.
    c = kv.VerifyConfig("cpu", True, True, True, True, False, (("mesh", 2),))
    assert c.backend_options == (("mesh", 2),)
    assert c.label_relation is None
